"""The explicit jit-root registry: every device program the scheduler can
dispatch, with a builder that reproduces its REAL input structures at each
rung of the pow2 bucket ladder.

The worlds are built through the same tensorization path serving uses
(hollow nodes/pods -> NodeInfo -> SnapshotBuilder -> PodBatchBuilder ->
ProgramConfig), so the abstract avals the census traces are byte-for-byte
the avals a serving cycle of that shape would compile — not a hand-kept
approximation that silently drifts from the builders.  Worlds are
deterministic (seeded generators, insertion-ordered vocabs), which is what
makes the committed manifest idempotent.

Every entry carries the qualname kubelint's call graph reports for the
root, so the census can prove the registry covers the whole discovered
compile surface (census/unregistered-root).  Rule exemptions require an
audited reason, mirroring the kubelint suppression convention.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, NamedTuple, Optional, Tuple


class Rung(NamedTuple):
    """One ladder rung: the logical world size a variant is traced at.
    Axis CAPACITIES are derived by the real builders (pow2_bucket), so a
    rung names a workload shape, not raw tensor dims."""
    name: str
    n_nodes: int
    n_pods: int


# The committed ladder: the small rung pins the minimum-bucket programs
# (every axis at its pow2 floor); the mid rung exercises genuinely distinct
# buckets on every axis (nodes, batch, labels, terms, selectors).  Tracing
# cost is shape-independent, but each rung is a manifest row per program —
# keep the ladder intentional, not exhaustive.
DEFAULT_LADDER: Tuple[Rung, ...] = (
    Rung("n8_b8", 8, 8),
    Rung("n64_b64", 64, 64),
)


class CensusWorld:
    """One deterministic world at a rung, tensorized for tracing."""

    def __init__(self, rung: Rung):
        import jax
        import numpy as np

        from kubetpu.api import types as api
        from kubetpu.framework.types import NodeInfo, PodInfo
        from kubetpu.harness import hollow
        from kubetpu.models import programs
        from kubetpu.models.batch import PodBatchBuilder
        from kubetpu.scheduler import Scheduler
        from kubetpu.state.tensors import SnapshotBuilder

        self.rung = rung
        nodes = hollow.make_nodes(rung.n_nodes, zones=4)
        # existing pods: one per node with app-group labels, every fourth
        # carrying hostname anti-affinity so the cluster-side term axes
        # (filter_terms/score_terms) are non-degenerate like real worlds
        existing = hollow.make_pods(rung.n_nodes, prefix="ex-",
                                    group_labels=8)
        for i, p in enumerate(existing):
            if i % 4 == 0:
                hollow.with_anti_affinity(p, api.LABEL_HOSTNAME)
        infos = []
        for i, n in enumerate(nodes):
            ni = NodeInfo(n)
            p = existing[i]
            p.spec.node_name = n.name
            ni.add_pod(p)
            infos.append(ni)
        pending = hollow.make_pods(rung.n_pods, prefix="pend-",
                                   group_labels=8)
        for i, p in enumerate(pending):
            # bench.py's blended topology mix: 1/3 soft zone spread, 1/5
            # hostname anti-affinity, 1/7 zone affinity
            if i % 3 == 0:
                hollow.with_spread(p, api.LABEL_ZONE, when="ScheduleAnyway")
            if i % 5 == 0:
                hollow.with_anti_affinity(p, api.LABEL_HOSTNAME)
            if i % 7 == 1:
                hollow.with_affinity(p, api.LABEL_ZONE)
        self.node_infos = infos
        self.pinfos = [PodInfo(p) for p in pending]
        sb = SnapshotBuilder()
        sb.intern_pending(self.pinfos)
        self.builder = sb
        self.host = sb.build(infos)
        self.cluster = self.host.to_device()
        pb = PodBatchBuilder(sb.table)
        self.batch = jax.tree.map(np.asarray, pb.build(self.pinfos))
        self.table = sb.table
        self.cfg = programs.ProgramConfig(
            filters=programs.DEFAULT_FILTER_PLUGINS,
            scores=programs.DEFAULT_SCORE_PLUGINS,
            hostname_topokey=max(sb.table.topokey.get(api.LABEL_HOSTNAME),
                                 0),
            # the serving loop restricts the same-pair matmuls to the
            # batch's term keys; reproduce that static exactly
            active_topo_keys=Scheduler._batch_topo_keys(sb.table,
                                                        self.pinfos))
        self.rng = jax.random.PRNGKey(0)
        self.B = int(self.batch.valid.shape[0])
        self.N = int(self.cluster.allocatable.shape[0])
        self.P = int(self.cluster.pod_valid.shape[0])
        self.R = int(self.cluster.allocatable.shape[1])

    # shared derived inputs ------------------------------------------------

    def host_ok(self):
        import numpy as np
        return np.ones((self.B, self.N), bool)

    def score_bias(self):
        import numpy as np
        return np.zeros((self.B, self.N), np.float32)

    def nominated(self):
        """(nom overlay, nom PodBatch, rows, prio) mirroring the
        scheduler's addNominatedPods two-pass overlay build."""
        import jax
        import numpy as np

        from kubetpu.models.batch import PodBatchBuilder, build_nominated

        entries = [(self.pinfos[0], 0, 0), (self.pinfos[1], 1, -1)]
        nom = build_nominated(entries, self.table)
        pb = PodBatchBuilder(self.table)
        nom_pb = jax.tree.map(np.asarray,
                              pb.build([e[0] for e in entries]))
        # rows/prio are sized to the PADDED nominated bucket, exactly like
        # Scheduler._nominated_overlay_mask
        M = int(np.asarray(nom_pb.valid).shape[0])
        rows = np.full((M,), -1, np.int32)
        prio = np.zeros((M,), np.int32)
        for i, e in enumerate(entries):
            rows[i] = e[1]
            prio[i] = e[0].pod.priority()
        return nom, nom_pb, rows, prio


_WORLDS: Dict[Rung, CensusWorld] = {}


def build_world(rung: Rung) -> CensusWorld:
    w = _WORLDS.get(rung)
    if w is None:
        w = _WORLDS[rung] = CensusWorld(rung)
    return w


@dataclasses.dataclass(frozen=True)
class Entry:
    """One registered jit root.

    ``build(world)`` returns ``(fn, args, kwargs)`` — the jit object plus
    the concrete call the serving path makes.  kwargs may mix static
    values (hashable non-arrays, consumed by static_argnames) and optional
    dynamic arrays (e.g. host_ok); the tracer tells them apart by type.
    ``tag`` distinguishes registry variants that compile under the same
    program name (e.g. donated vs shared scatter).  ``exempt`` maps census
    rule ids to audited reasons (the kubelint suppression convention:
    reasonless exemptions are themselves findings)."""
    program: str
    qualname: str
    build: Callable[[CensusWorld], tuple]
    tag: str = ""
    meshable: bool = False
    # the builder's inputs are ALREADY committed to a mesh and the
    # lowering must keep their NamedShardings (the shard_map family:
    # serving dispatches these with committed-sharded residents, and the
    # AOT capture's sha must equal the manifest's) — the per-entry twin
    # of the meshable variants' keep_sharding flow
    keep_sharding: bool = False
    donate_argnums: Tuple[int, ...] = ()
    # kwarg names / positional indices the jit treats as STATIC (mirrors
    # the decorator's static_argnames); every other arg is a traced input
    # — including Python scalars, which jit sees as weak rank-0 avals.
    # Builders mirror the SERVING call form (positional vs keyword), so
    # the manifest's flattened aval order equals the compile log's.
    static_argnames: Tuple[str, ...] = ()
    static_argnums: Tuple[int, ...] = ()
    ladder: Tuple[Rung, ...] = DEFAULT_LADDER
    exempt: Tuple[Tuple[str, str], ...] = ()
    # ---- exactness prover metadata (tools/kubeexact) -------------------
    # exact=True opts the entry into the jaxpr-level exact-reduction
    # proof: every cross-shard/cross-tile float reduction must be proved
    # max/min or an integer-valued sum bounded below 2**24 at the
    # north-star shapes.  The shard_map/Pallas family (the roots with
    # collectives or grid-accumulator folds) must all be exact=True.
    exact: bool = False
    # (input-path substring, fact name): seeds the abstract interpreter
    # with invariants the builders guarantee but tracing cannot see —
    # e.g. cluster.zone_hot rows are one-hot ("onehot_rows").  Facts are
    # part of the audited trust base and are committed in the manifest.
    exact_facts: Tuple[Tuple[str, str], ...] = ()
    # (rule, reason) exemptions for exactness findings, mirroring
    # ``exempt``: reasonless or stale entries are themselves findings.
    exact_exempt: Tuple[Tuple[str, str], ...] = ()
    # symbol name per pallas grid axis ("" = literal grid size): lets the
    # prover generalize a grid-axis fold count from the probe rung to the
    # north-star environment (e.g. ("", "WB", "NT")).
    exact_grid_syms: Tuple[str, ...] = ()
    # ---- closure prover metadata (tools/kubeclose) ---------------------
    # The (axis, value) assignment this entry covers in the program's
    # enumerated reachable-signature set: one pair per MULTI-VALUED
    # closure axis (enumerated statics as canonical reprs — "'lax'",
    # "True" — and optional-dynamic presence axes as "absent"/"present").
    # kubeclose joins CLOSURE_MANIFEST combos against these, so a combo
    # no entry matches is close/uncaptured-signature and an entry whose
    # assignment matches no reachable combo is close/unreachable-
    # manifest-row.  Single-valued and symbolic axes (cfg, mesh_key, the
    # pad ladders) are carried by the manifest itself, not repeated here.
    closure_statics: Tuple[Tuple[str, str], ...] = ()

    @property
    def key(self) -> str:
        return self.program + (":" + self.tag if self.tag else "")


def _filter_and_score(w):
    from kubetpu.models import programs
    return programs.filter_and_score, (w.cluster, w.batch, w.cfg), {}


def _filter_and_score_hostok(w):
    from kubetpu.models import programs
    return (programs.filter_and_score, (w.cluster, w.batch, w.cfg),
            {"host_ok": w.host_ok()})


def _schedule_batch(w):
    from kubetpu.models import programs
    return (programs.schedule_batch, (w.cluster, w.batch, w.cfg, w.rng),
            {})


def _explain_filters(w):
    from kubetpu.models import programs
    return programs.explain_filters, (w.cluster, w.batch, w.cfg), {}


def _explain_verdicts(w):
    from kubetpu.models import programs
    return programs._explain_verdicts, (w.cluster, w.batch, w.cfg), {}


def _explain_verdicts_hostok(w):
    from kubetpu.models import programs
    # host_ok as KEYWORD, the serving seam's call form (scheduler prewarm
    # and the audit path pass host_ok=...) — jit binds either spelling to
    # the same avals, but the AOT signature keys on the call treedef, so
    # a positional capture could never be hit by serving dispatch
    return (programs._explain_verdicts,
            (w.cluster, w.batch, w.cfg), {"host_ok": w.host_ok()})


def _filter_verdicts(w):
    from kubetpu.models import programs
    return programs.filter_verdicts, (w.cluster, w.batch, w.cfg), {}


def _wave_cfg(cfg):
    return cfg._replace(filters=tuple(
        f for f in cfg.filters
        if f not in ("PodTopologySpread", "InterPodAffinity")))


def _whatif_static_ok(w):
    from kubetpu.models import programs
    return (programs.whatif_static_ok,
            (w.cluster, w.batch, _wave_cfg(w.cfg)), {})


def _whatif_wave(w):
    import numpy as np

    from kubetpu.models import programs
    B, C, K, S, R = 8, 8, 8, 8, w.R
    static_ok = np.ones((B, w.N), bool)
    return (programs.whatif_wave,
            (w.cluster, static_ok,
             np.zeros((B, R), np.float32),          # wave_req
             np.zeros((B, C), np.int32),            # cand_rows
             np.zeros((B, C), bool),                # cand_valid
             np.zeros((B, C, R), np.float32),       # nom_add
             np.zeros((S, K, R), np.float32),       # tab_req
             np.zeros((S, K), bool),                # tab_valid
             np.zeros((B, C), np.int32)),           # cand_idx
            {})


def _whatif_reprieve(w):
    import numpy as np

    from kubetpu import preemption
    from kubetpu.models.batch import PodBatchBuilder
    import jax
    C, K, R, P = 8, 8, w.R, w.P
    pb = PodBatchBuilder(w.table)
    batch1 = jax.tree.map(np.asarray, pb.build(w.pinfos[:1]))
    return (preemption._whatif_reprieve,
            (w.cluster, batch1, _wave_cfg(w.cfg),
             np.zeros((C,), np.int32),            # cand_rows
             np.ones((C, P), bool),               # rm_valid
             np.zeros((C, R), np.float32),        # rm_req
             np.zeros((C, 2), np.float32),        # rm_nz
             np.full((C, K), -1, np.int32),       # vic_row
             np.zeros((C, K, R), np.float32),     # vic_req
             np.zeros((C, K, 2), np.float32)),    # vic_nz
            {})


def _nominated_fit_mask(w):
    from kubetpu.models import programs
    nom, _, _, _ = w.nominated()
    return programs.nominated_fit_mask, (w.cluster, w.batch, nom), {}


def _nominated_topology_mask(w):
    from kubetpu.models import programs
    _, nom_pb, rows, prio = w.nominated()
    cfg = w.cfg._replace(scores=())
    return (programs.nominated_topology_mask,
            (w.cluster, nom_pb, rows, prio, w.batch, cfg), {})


def _schedule_gang(w):
    from kubetpu.models import gang
    return (gang._schedule_gang, (w.cluster, w.batch, w.cfg, w.rng), {})


def _schedule_gang_hostok(w):
    from kubetpu.models import gang
    return (gang._schedule_gang, (w.cluster, w.batch, w.cfg, w.rng),
            {"host_ok": w.host_ok()})


def _schedule_gang_bias(w):
    from kubetpu.models import gang
    return (gang._schedule_gang, (w.cluster, w.batch, w.cfg, w.rng),
            {"host_ok": w.host_ok(), "score_bias": w.score_bias()})


def _schedule_gang_notopo(w):
    from kubetpu.models import gang
    # the term-free DEFAULT-BACKEND serving form: a batch with no
    # topology terms routes intra_batch_topology=False (scheduler's
    # needs_topo gate) while kernel_backend stays "lax" — a DISTINCT
    # static combination from the plain entry (intra=True) that the
    # closure prover found reachable-but-uncovered: the first term-free
    # cycle of a default-config deployment compiled cold on the serving
    # path
    return (gang._schedule_gang, (w.cluster, w.batch, w.cfg, w.rng),
            {"intra_batch_topology": False, "kernel_backend": "lax"})


def _schedule_gang_notopo_hostok(w):
    from kubetpu.models import gang
    # host-filter cycles over a term-free batch on the lax backend
    return (gang._schedule_gang, (w.cluster, w.batch, w.cfg, w.rng),
            {"host_ok": w.host_ok(), "intra_batch_topology": False,
             "kernel_backend": "lax"})


def _schedule_gang_pallas(w):
    from kubetpu.models import gang
    # the fused-megakernel serving call form: a TERM-FREE batch routes
    # intra_batch_topology=False + kernel_backend="pallas" (scheduler's
    # needs_topo gate); on CPU the pallas_call lowers under interpret=True
    # — a DIFFERENT program (and AOT key) from a Mosaic lowering, which is
    # exactly why the backend is a static arg
    return (gang._schedule_gang, (w.cluster, w.batch, w.cfg, w.rng),
            {"intra_batch_topology": False, "kernel_backend": "pallas"})


def _schedule_gang_pallas_hostok(w):
    from kubetpu.models import gang
    # host-filter cycles (volume pods are term-free, so they still route
    # to the megakernel) pass host_ok as KEYWORD like the serving seam
    return (gang._schedule_gang, (w.cluster, w.batch, w.cfg, w.rng),
            {"host_ok": w.host_ok(), "intra_batch_topology": False,
             "kernel_backend": "pallas"})


def _shardmap_mesh(w):
    """A (1, 1) mesh + registered key: the shard_map twins trace on a
    single-device mesh exactly like the meshable @mesh variants — the
    census environment has one CPU device, and the program STRUCTURE
    (explicit collectives, replicated vs tiled surface) is what the
    manifest rows pin, not the device count."""
    from kubetpu.parallel import mesh as pmesh
    from kubetpu.parallel import shardmap
    m = pmesh.make_mesh((1, 1))
    return m, shardmap.register_mesh(m)


def _shardmap_place(w, m):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec
    from kubetpu.parallel import mesh as pmesh
    cluster = pmesh.shard_cluster(w.cluster, m)
    batch = pmesh.shard_batch(w.batch, m)
    rng = pmesh._put(w.rng, NamedSharding(m, PartitionSpec()))
    return cluster, batch, rng


def _shardmap_gang_replicated(w):
    from kubetpu.parallel import shardmap
    m, key = _shardmap_mesh(w)
    cluster, batch, rng = _shardmap_place(w, m)
    # the serving call form for topology batches (scheduler needs_topo
    # routes intra_batch_topology=True -> surface "replicated")
    return (shardmap._shardmap_gang, (cluster, batch, w.cfg, rng),
            {"mesh_key": key, "intra_batch_topology": True,
             "residual_window": 512, "surface": "replicated"})


def _shardmap_gang_tiled(w):
    from kubetpu.parallel import shardmap
    m, key = _shardmap_mesh(w)
    cluster, batch, rng = _shardmap_place(w, m)
    # the term-free scale surface: gather-free tiled auction
    return (shardmap._shardmap_gang, (cluster, batch, w.cfg, rng),
            {"mesh_key": key, "intra_batch_topology": False,
             "residual_window": 512, "surface": "tiled"})


def _shardmap_sequential(w):
    from kubetpu.parallel import shardmap
    m, key = _shardmap_mesh(w)
    cluster, batch, rng = _shardmap_place(w, m)
    return (shardmap._shardmap_sequential,
            (cluster, batch, _seq_cfg(w), rng),
            {"mesh_key": key, "hard_pod_affinity_weight": 1.0,
             "start_index": 0})


def _shardmap_delta(w, donate):
    import jax
    from kubetpu.parallel import mesh as pmesh
    from kubetpu.parallel import shardmap
    m, key = _shardmap_mesh(w)
    cluster = pmesh.shard_cluster(w.cluster, m)
    delta = pmesh.replicate(
        jax.tree.map(jax.numpy.asarray, _cluster_delta(w)), m)
    fn = (shardmap._shardmap_apply_delta_donated if donate
          else shardmap._shardmap_apply_delta_shared)
    return fn, (cluster, delta), {"mesh_key": key}


def _shardmap_delta_donated(w):
    return _shardmap_delta(w, True)


def _shardmap_delta_shared(w):
    return _shardmap_delta(w, False)


def _seq_cfg(w):
    # the serving loop passes 0 (= the reference's ADAPTIVE default,
    # types.go:251) unless a profile pins a percentage; the adaptive
    # branch reads start_index, so the static changes the pruned arg set
    return w.cfg._replace(percentage_of_nodes_to_score=0)


def _schedule_sequential(w):
    from kubetpu.models import sequential
    return (sequential._schedule_sequential,
            (w.cluster, w.batch, _seq_cfg(w), w.rng),
            {"hard_pod_affinity_weight": 1.0, "start_index": 0})


def _schedule_sequential_hostok(w):
    from kubetpu.models import sequential
    return (sequential._schedule_sequential,
            (w.cluster, w.batch, _seq_cfg(w), w.rng),
            {"hard_pod_affinity_weight": 1.0, "start_index": 0,
             "host_ok": w.host_ok()})


def _materialize_assigned(w):
    import numpy as np

    from kubetpu.models import gang
    from kubetpu.utils.intern import pow2_bucket
    ta = int(w.batch.raa.valid.shape[1])
    p_next = pow2_bucket(w.P + w.B)
    e_next = pow2_bucket(int(w.cluster.filter_terms.valid.shape[0])
                         + w.B * ta)
    Np = int(w.cluster.ports.shape[1])
    return (gang._materialize_assigned,
            (w.cluster, w.batch,
             np.zeros((w.B,), np.int32),                 # chosen
             np.asarray(w.cluster.requested),            # requested
             np.asarray(w.cluster.nonzero_requested),    # nz
             np.zeros((w.N, Np), np.float32)),           # ports_used
            {"pad_pods_to": p_next, "pad_terms_to": e_next,
             "extend_score_terms": True,
             "hard_pod_affinity_weight": 1.0})


def _cluster_delta(w):
    from kubetpu.state.tensors import gather_delta
    return gather_delta(w.host, [0], [0])


def _apply_delta_donated(w):
    import jax

    from kubetpu.models import programs
    delta = jax.tree.map(jax.numpy.asarray, _cluster_delta(w))
    return (programs._apply_cluster_delta_donated, (w.cluster, delta), {})


def _apply_delta_shared(w):
    import jax

    from kubetpu.models import programs
    delta = jax.tree.map(jax.numpy.asarray, _cluster_delta(w))
    return (programs._apply_cluster_delta_shared, (w.cluster, delta), {})


def _densify_kv(w):
    import jax.numpy as jnp

    from kubetpu.state.tensors import _densify_ids
    a = w.host.arrays
    return (_densify_ids, (jnp.asarray(a["_kv_ids"]),),
            {"L": a["_kv_cap"]})


def _densify_pod_kv(w):
    import jax.numpy as jnp

    from kubetpu.state.tensors import _densify_ids
    a = w.host.arrays
    return (_densify_ids, (jnp.asarray(a["_pod_kv_ids"]),),
            {"L": a["_kv_cap"]})


def _volume_mask(w):
    """The device volume-family mask, built from a PVC-carrying twin of
    the rung world (mirrors bench.pv_heavy_case at rung scale)."""
    import jax
    import random

    from kubetpu.api import types as api
    from kubetpu.client.store import ClusterStore
    from kubetpu.state import volumes as svol

    rng = random.Random(0)
    zones = [f"zone-{i}" for i in range(4)]
    store = ClusterStore()
    pods = [pi.pod for pi in w.pinfos]
    for i, p in enumerate(pods):
        zone = rng.choice(zones)
        store.add(api.PersistentVolume(
            metadata=api.ObjectMeta(name=f"census-pv-{i}",
                                    labels={api.LABEL_ZONE: zone})))
        store.add(api.PersistentVolumeClaim(
            metadata=api.ObjectMeta(name=f"census-claim-{i}",
                                    namespace=p.namespace),
            volume_name=f"census-pv-{i}"))
        p.spec.volumes = [
            api.Volume(name="data",
                       persistent_volume_claim=f"census-claim-{i}"),
            api.Volume(name="scratch",
                       aws_elastic_block_store=f"ebs-{i % 4}"),
        ]
    overlay = svol.build_volume_overlay(
        store, w.node_infos, pods, w.table, svol.DEVICE_COVERED_PLUGINS)
    assert overlay is not None
    overlay = jax.tree.map(jax.numpy.asarray, overlay)
    for p in pods:
        p.spec.volumes = []          # leave the shared world untouched
    return (svol._volume_mask,
            (w.cluster.kv, w.cluster.keymask, w.cluster.num, overlay), {})


ENTRIES: List[Entry] = [
    Entry("filter_and_score", "kubetpu.models.programs:filter_and_score",
          _filter_and_score, meshable=True, static_argnums=(2,)),
    Entry("filter_and_score", "kubetpu.models.programs:filter_and_score",
          _filter_and_score_hostok, tag="hostok", static_argnums=(2,)),
    Entry("schedule_batch", "kubetpu.models.programs:schedule_batch",
          _schedule_batch, meshable=True, static_argnums=(2,)),
    Entry("explain_filters", "kubetpu.models.programs:explain_filters",
          _explain_filters, static_argnums=(2,)),
    Entry("_explain_verdicts", "kubetpu.models.programs:_explain_verdicts",
          _explain_verdicts, static_argnums=(2,),
          closure_statics=(("host_ok", "absent"),)),
    Entry("_explain_verdicts", "kubetpu.models.programs:_explain_verdicts",
          _explain_verdicts_hostok, tag="hostok", static_argnums=(2,),
          closure_statics=(("host_ok", "present"),)),
    Entry("filter_verdicts", "kubetpu.models.programs:filter_verdicts",
          _filter_verdicts, static_argnums=(2,)),
    Entry("whatif_static_ok", "kubetpu.models.programs:whatif_static_ok",
          _whatif_static_ok, static_argnums=(2,)),
    Entry("whatif_wave", "kubetpu.models.programs:whatif_wave",
          _whatif_wave, static_argnames=()),
    Entry("_whatif_reprieve", "kubetpu.preemption:_whatif_reprieve",
          _whatif_reprieve, static_argnums=(2,)),
    Entry("nominated_fit_mask",
          "kubetpu.models.programs:nominated_fit_mask",
          _nominated_fit_mask, static_argnames=()),
    Entry("nominated_topology_mask",
          "kubetpu.models.programs:nominated_topology_mask",
          _nominated_topology_mask, static_argnums=(5,)),
    Entry("_schedule_gang", "kubetpu.models.gang:_schedule_gang",
          _schedule_gang, meshable=True, static_argnums=(2,),
          closure_statics=(("host_ok", "absent"),
                           ("intra_batch_topology", "True"),
                           ("kernel_backend", "'lax'"),
                           ("score_bias", "absent"))),
    Entry("_schedule_gang", "kubetpu.models.gang:_schedule_gang",
          _schedule_gang_hostok, tag="hostok", static_argnums=(2,),
          closure_statics=(("host_ok", "present"),
                           ("intra_batch_topology", "True"),
                           ("kernel_backend", "'lax'"),
                           ("score_bias", "absent"))),
    Entry("_schedule_gang", "kubetpu.models.gang:_schedule_gang",
          _schedule_gang_bias, tag="bias", static_argnums=(2,),
          closure_statics=(("host_ok", "present"),
                           ("intra_batch_topology", "True"),
                           ("kernel_backend", "'lax'"),
                           ("score_bias", "present"))),
    Entry("_schedule_gang", "kubetpu.models.gang:_schedule_gang",
          _schedule_gang_notopo, tag="notopo", static_argnums=(2,),
          static_argnames=("intra_batch_topology", "kernel_backend"),
          closure_statics=(("host_ok", "absent"),
                           ("intra_batch_topology", "False"),
                           ("kernel_backend", "'lax'"),
                           ("score_bias", "absent"))),
    Entry("_schedule_gang", "kubetpu.models.gang:_schedule_gang",
          _schedule_gang_notopo_hostok, tag="notopo_hostok",
          static_argnums=(2,),
          static_argnames=("intra_batch_topology", "kernel_backend"),
          closure_statics=(("host_ok", "present"),
                           ("intra_batch_topology", "False"),
                           ("kernel_backend", "'lax'"),
                           ("score_bias", "absent"))),
    Entry("_schedule_gang", "kubetpu.models.gang:_schedule_gang",
          _schedule_gang_pallas, tag="pallas", static_argnums=(2,),
          static_argnames=("intra_batch_topology", "kernel_backend"),
          exact=True, exact_facts=(("zone_hot", "onehot_rows"),),
          exact_grid_syms=("", "WB", "NT"),
          closure_statics=(("host_ok", "absent"),
                           ("intra_batch_topology", "False"),
                           ("kernel_backend", "'pallas'"),
                           ("score_bias", "absent"))),
    Entry("_schedule_gang", "kubetpu.models.gang:_schedule_gang",
          _schedule_gang_pallas_hostok, tag="pallas_hostok",
          static_argnums=(2,),
          static_argnames=("intra_batch_topology", "kernel_backend"),
          exact=True, exact_facts=(("zone_hot", "onehot_rows"),),
          exact_grid_syms=("", "WB", "NT"),
          closure_statics=(("host_ok", "present"),
                           ("intra_batch_topology", "False"),
                           ("kernel_backend", "'pallas'"),
                           ("score_bias", "absent"))),
    Entry("_schedule_sequential",
          "kubetpu.models.sequential:_schedule_sequential",
          _schedule_sequential, meshable=True, static_argnums=(2,),
          closure_statics=(("host_ok", "absent"),
                           ("score_bias", "absent"))),
    Entry("_schedule_sequential",
          "kubetpu.models.sequential:_schedule_sequential",
          _schedule_sequential_hostok, tag="hostok", static_argnums=(2,),
          closure_statics=(("host_ok", "present"),
                           ("score_bias", "absent"))),
    Entry("_materialize_assigned",
          "kubetpu.models.gang:_materialize_assigned",
          _materialize_assigned,
          static_argnames=("pad_pods_to", "pad_terms_to",
                           "extend_score_terms")),
    Entry("_apply_cluster_delta",
          "kubetpu.models.programs:_apply_cluster_delta",
          _apply_delta_donated, tag="donated", donate_argnums=(0,),
          static_argnames=(),
          closure_statics=(("donate", "True"),),
          exempt=(("census/donation-unconsumed",
                   "by design: the four vocab-side tables (image_size/"
                   "image_spread/taint_is_hard/taint_is_prefer) are "
                   "REPLACED wholesale from the delta args, so their "
                   "donated twins have no output to alias into — tiny "
                   "[I]/[T] buffers, the [N,.]/[P,.] residents all "
                   "alias (50/54)"),)),
    Entry("_apply_cluster_delta",
          "kubetpu.models.programs:_apply_cluster_delta",
          _apply_delta_shared, tag="shared", static_argnames=(),
          closure_statics=(("donate", "False"),)),
    Entry("_densify_ids", "kubetpu.state.tensors:_densify_ids",
          _densify_kv, tag="kv", static_argnames=("L",)),
    Entry("_densify_ids", "kubetpu.state.tensors:_densify_ids",
          _densify_pod_kv, tag="pod_kv", static_argnames=("L",)),
    Entry("_volume_mask", "kubetpu.state.volumes:_volume_mask",
          _volume_mask, static_argnames=()),
    # ---- pod-axis mesh scale-out (parallel/shardmap.py): the explicit
    # shard_map programs the mesh serving path dispatches — the legacy
    # gspmd twins above (meshable @mesh variants) cover the OLD lowering
    Entry("_shardmap_gang", "kubetpu.parallel.shardmap:_shardmap_gang",
          _shardmap_gang_replicated, tag="replicated",
          keep_sharding=True, static_argnums=(2,),
          static_argnames=("mesh_key", "intra_batch_topology",
                           "residual_window", "surface"),
          exact=True,
          closure_statics=(("host_ok", "absent"),
                           ("intra_batch_topology", "True"),
                           ("score_bias", "absent"),
                           ("surface", "'replicated'"))),
    Entry("_shardmap_gang", "kubetpu.parallel.shardmap:_shardmap_gang",
          _shardmap_gang_tiled, tag="tiled", keep_sharding=True,
          static_argnums=(2,),
          static_argnames=("mesh_key", "intra_batch_topology",
                           "residual_window", "surface"),
          exact=True,
          # SnapshotBuilder writes zone_hot as a one-hot zone-membership
          # row per node (state/tensors.py); the zone-count psum's 2**24
          # proof rests on this row-sum-==-1 invariant
          exact_facts=(("zone_hot", "onehot_rows"),),
          closure_statics=(("host_ok", "absent"),
                           ("intra_batch_topology", "False"),
                           ("score_bias", "absent"),
                           ("surface", "'tiled'"))),
    Entry("_shardmap_sequential",
          "kubetpu.parallel.shardmap:_shardmap_sequential",
          _shardmap_sequential, keep_sharding=True, static_argnums=(2,),
          static_argnames=("mesh_key",), exact=True,
          closure_statics=(("host_ok", "absent"),
                           ("score_bias", "absent"))),
    Entry("_apply_delta_body",
          "kubetpu.parallel.shardmap:_apply_delta_body",
          _shardmap_delta_donated, tag="donated", donate_argnums=(0,),
          keep_sharding=True, static_argnames=("mesh_key",),
          closure_statics=(("donate", "True"),),
          exempt=(("census/donation-unconsumed",
                   "by design, the shard_map twin of the gspmd scatter's "
                   "audited case: the four vocab-side tables are REPLACED "
                   "wholesale from the replicated delta args, so their "
                   "donated twins have no output to alias into; shard_map "
                   "boundary resharding can further reduce the aliased "
                   "count — the [N,.]/[P,.] residents are the bytes that "
                   "matter and the scatter is correct either way"),),
          exact=True),
    Entry("_apply_delta_body",
          "kubetpu.parallel.shardmap:_apply_delta_body",
          _shardmap_delta_shared, tag="shared", keep_sharding=True,
          static_argnames=("mesh_key",), exact=True,
          closure_statics=(("donate", "False"),)),
]


def registered_qualnames() -> set:
    return {e.qualname for e in ENTRIES}
