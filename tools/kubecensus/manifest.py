"""COMPILE_MANIFEST.json: serialization, drift diffing, and the runtime
compile-event cross-check.

The committed manifest is the version-controlled compile surface.  Two
consumers:

* CI (``python -m tools.kubecensus --check``): regenerates the rows in
  memory and fails on drift in either direction — a traced variant
  absent from the committed file (surface grew silently) or a committed
  row no trace reproduces (dead ladder bucket).
* bench.py under ``BENCH_GATE=1``: every compile event the sanitize
  watchdog observes for a REGISTERED kernel program must match a
  manifest row.  Exact-shape matches pin census rungs.  At non-census
  rungs, programs inside the committed compile-surface closure
  (CLOSURE_MANIFEST.json, tools/kubeclose) classify by CLOSURE
  MEMBERSHIP: every leaf's (dtype, rank) must appear among the
  program's committed leaves and every dim must be licensed — a dim
  some committed row of the program carries, or a pow2 ladder rung at
  or below the north-star caps (tools/kubeexact/northstar.py).
  Programs outside the closure (non-seamed kernel roots) fall back to
  the legacy structural heuristic (ordered (dtype, rank) subsequence of
  a committed signature), as does everything when no closure is
  committed.  Events for unregistered names (jax-internal eager ops,
  test helpers) are counted but exempt; unregistered KERNEL roots
  cannot hide there because the static census fails on them first
  (census/unregistered-root).
"""

from __future__ import annotations

import json
import os
import re
from typing import Dict, List, Optional, Tuple

MANIFEST_PATH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "COMPILE_MANIFEST.json")
CLOSURE_PATH = os.path.join(os.path.dirname(MANIFEST_PATH),
                            "CLOSURE_MANIFEST.json")

_AVAL_RE = re.compile(r"([A-Za-z_][A-Za-z_0-9]*)\[([\d,\s]*)\]")


def row_id(row: dict) -> str:
    tag = ":" + row["tag"] if row.get("tag") else ""
    return "%s%s@%s" % (row["program"], tag, row["variant"])


def write_manifest(rows: List[dict], path: str = None) -> str:
    """Deterministic serialization: sorted rows, sorted keys, fixed
    indent, trailing newline — regeneration over an unchanged tree is
    byte-identical."""
    path = path or MANIFEST_PATH
    doc = {
        "_comment": "Compile-surface census (tools/kubecensus). "
                    "Regenerate: make census (python -m tools.kubecensus "
                    "--write). CI fails on drift in either direction.",
        "rows": rows,
    }
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    return path


def load_manifest(path: str = None) -> Optional[List[dict]]:
    path = path or MANIFEST_PATH
    try:
        with open(path) as f:
            return json.load(f)["rows"]
    except (OSError, ValueError, KeyError):
        return None


def load_closure(path: str = None) -> Optional[dict]:
    """The committed compile-surface closure (tools/kubeclose), or None
    when no CLOSURE_MANIFEST.json is committed — the event matcher then
    falls back to the structural heuristic everywhere."""
    path = path or CLOSURE_PATH
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def diff_manifest(current: List[dict],
                  committed: Optional[List[dict]]) -> Dict[str, list]:
    """Three-way drift: added (traced, not committed), removed (committed,
    not reproduced — a dead ladder bucket), changed (same id, different
    trace: avals, jaxpr hash, donation or statics moved)."""
    if committed is None:
        return {"added": [row_id(r) for r in current], "removed": [],
                "changed": [], "missing_manifest": True}
    cur = {row_id(r): r for r in current}
    com = {row_id(r): r for r in committed}
    added = sorted(set(cur) - set(com))
    removed = sorted(set(com) - set(cur))
    changed = []
    watched = ("qualname", "in_avals", "compiled_in_avals", "out_avals",
               "lowering_sha256", "donation", "static_sig", "sharding")
    for rid in sorted(set(cur) & set(com)):
        for k in watched:
            if cur[rid].get(k) != com[rid].get(k):
                changed.append("%s (%s)" % (rid, k))
                break
    return {"added": added, "removed": removed, "changed": changed}


# ------------------------------------------------- runtime event matching


def _parse_sig(sig: str) -> List[Tuple[str, int]]:
    """'[ShapedArray(float32[8,16]), ...]' -> [(dtype, rank), ...]."""
    out = []
    for dt, dims in _AVAL_RE.findall(sig):
        rank = 0 if not dims.strip() else len(dims.split(","))
        out.append((dt, rank))
    return out


def match_compile_events(events: Dict[Tuple[str, str], int],
                         rows: List[dict],
                         closure: Optional[dict] = None
                         ) -> Dict[str, object]:
    """Classify watchdog compile events against the manifest.

    events: CompileWatchdog.counts — {(program, shapes-sig): count}.
    closure: the committed CLOSURE_MANIFEST.json doc (``load_closure``);
    when given, events for programs the closure proves replace the
    structural-subsequence heuristic with closure-membership
    classification (``_closure_match``).  Returns {kernel_events,
    matched_exact, matched_closure, matched_structural, outside: [...],
    auxiliary} — ``outside`` non-empty means a registered kernel program
    compiled a variant neither the manifest nor the closure licenses."""
    by_program: Dict[str, List[dict]] = {}
    for r in rows:
        by_program.setdefault(r["program"], []).append(r)
    exact = {}
    for r in rows:
        exact.setdefault(
            (r["program"], tuple(r.get("compiled_in_avals")
                                 or r["in_avals"])), r)
    closed = set((closure or {}).get("programs") or {})

    kernel = matched_exact = matched_closure = matched_structural = 0
    auxiliary = 0
    outside: List[str] = []
    for (program, sig), _count in sorted(events.items()):
        cands = by_program.get(program)
        if cands is None:
            auxiliary += 1
            continue
        kernel += 1
        parsed = _parse_sig(sig)
        sig_key = tuple("%s[%s]" % (dt, dims.replace(" ", ""))
                        for dt, dims in _AVAL_RE.findall(sig))
        if (program, sig_key) in exact:
            matched_exact += 1
            continue
        if program in closed:
            # proved program: the closure enumerates its reachable
            # signatures, so membership — committed leaf structure +
            # licensed dims — replaces the subsequence heuristic
            if _closure_match(sig, cands):
                matched_closure += 1
                continue
        elif any(_structural_match(parsed, r) for r in cands):
            matched_structural += 1
            continue
        outside.append("%s %s" % (program, sig))
    return {"kernel_events": kernel, "matched_exact": matched_exact,
            "matched_closure": matched_closure,
            "matched_structural": matched_structural,
            "auxiliary": auxiliary, "outside": outside}


def _closure_match(sig: str, cands: List[dict]) -> bool:
    """Closure-membership at non-census rungs for a program the
    committed compile-surface closure proves.

    The closure's static axes are finite by proof, so a legitimate
    serving compile of a closed program can only differ from the census
    rungs in its ARRAY shapes — and those walk the pow2 ladders the
    serving path buckets every dim onto.  Membership therefore demands:
    every event leaf's (dtype, rank) appears among the program's
    committed leaves (no new dtypes, no new array structure), and every
    dim is licensed — equal to a dim some committed row of the program
    carries, or a BUCKET SUM at or below the north-star caps
    (tools/kubeexact/northstar.py N/P, the largest buckets the roadmap
    commits to serving).  A bucket sum is a sum of at most three powers
    of two (popcount <= 3): every padded axis in the serving path is
    either one ``pow2_bucket`` or a ``concat_selector_sets`` /
    ExistingTerms join of at most three independently bucketed sets
    (models/gang.py splices batch pref + required-affinity terms into
    the snapshot's score terms), so e.g. U=3 (1+2), U=5 (1+4) selector
    planes and S=4097 (4096+1) slot axes are reachable, while an
    unbucketed raw count (popcount climbs with entropy) is not.
    Anything else — an off-ladder dim, a dim past the committed
    deployment target, a novel dtype — stays ``outside``: with the
    statics proved finite there is no benign explanation left."""
    from tools.kubeexact.northstar import NORTHSTAR_ENV

    pairs = set()
    licensed = set()
    for r in cands:
        for s in list(r.get("compiled_in_avals") or ()) + list(
                r.get("in_avals") or ()):
            m = _AVAL_RE.match(s)
            if not m:
                continue
            dt, dims = m.groups()
            dvals = [int(d) for d in dims.replace(" ", "").split(",")
                     if d]
            pairs.add((dt, len(dvals)))
            licensed.update(dvals)
    cap = int(max(NORTHSTAR_ENV.get("N", 0.0),
                  NORTHSTAR_ENV.get("P", 0.0)))
    for dt, dims in _AVAL_RE.findall(sig):
        dvals = [int(d) for d in dims.replace(" ", "").split(",") if d]
        if (dt, len(dvals)) not in pairs:
            return False
        for d in dvals:
            if d in licensed:
                continue
            if 0 < d <= cap and bin(d).count("1") <= 3:
                continue
            return False
    return True


def _structural_match(parsed: List[Tuple[str, int]], row: dict) -> bool:
    """The event's (dtype, rank) sequence must be an ORDERED SUBSEQUENCE
    of the row's full (unpruned) call signature.

    Why subsequence, not equality: jit prunes arguments the traced
    program never reads, and the pruned set depends on batch CONTENT
    (e.g. a wave with no preferred-affinity terms drops those weight
    leaves) — so two legitimate compiles of one variant differ in which
    leaves survive, but both are order-preserving subsets of the full
    flatten.  A genuinely NEW argument structure (extra arrays, dtype
    drift, reordered layout) cannot embed into the recorded signature
    and stays ``outside``.  Exact-shape matching at the census rungs is
    handled separately (compiled_in_avals equality)."""
    want = []
    for s in row["in_avals"]:
        m = _AVAL_RE.match(s)
        if not m:
            return False     # non-array leaf recorded; never runtime-match
        dt, dims = m.groups()
        want.append((dt, 0 if not dims.strip() else len(dims.split(","))))
    if len(parsed) > len(want):
        return False
    i = 0
    for p in parsed:
        while i < len(want) and want[i] != p:
            i += 1
        if i == len(want):
            return False
        i += 1
    return True
