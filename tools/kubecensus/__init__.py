"""kubecensus: whole-program compile-surface census.

kubelint (tools/kubelint) reasons over Python ASTs; kubecensus reasons
over the TRACED programs themselves.  It discovers every jit root in
``kubetpu/`` (kubelint's call-graph closure cross-checked against an
explicit registry), abstractly traces each root with ``jax.eval_shape`` /
``jit(...).lower()`` across the pow2 bucket ladder, and emits
``COMPILE_MANIFEST.json``: one row per (program x bucket x dtype x
donation x sharding) variant with abstract in/out avals, a stable jaxpr
hash, the donation signature XLA actually honored at lowering, and XLA
cost-analysis FLOPs/bytes.

The manifest is version-controlled.  CI regenerates it in memory and
fails on drift in either direction: a traced variant missing from the
committed manifest (the surface grew — a recompile hazard and an AOT
gap) or a committed row no trace reproduces (a dead ladder bucket —
exactly what AOT prewarm should prune).  At runtime, bench.py
cross-checks that every compile event the sanitize watchdog observes
for a registered kernel root matches a manifest row, closing the loop
between static census and observed reality.  The manifest is verbatim
the compile list a future AOT pass feeds to ``lower().compile()``.

On top of the traced jaxprs a semantic rule family runs checks AST lint
cannot express — see tools/kubecensus/README.md for the rule catalog.
"""

from .census import (Finding, audit_entry, audit_callable, run_census,
                     CensusResult)
from .manifest import (MANIFEST_PATH, load_manifest, write_manifest,
                       diff_manifest, match_compile_events)
from .registry import ENTRIES, DEFAULT_LADDER, Rung, build_world

__all__ = [
    "Finding", "audit_entry", "audit_callable", "run_census",
    "CensusResult", "MANIFEST_PATH", "load_manifest", "write_manifest",
    "diff_manifest", "match_compile_events", "ENTRIES", "DEFAULT_LADDER",
    "Rung", "build_world",
]
