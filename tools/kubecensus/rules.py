"""jaxpr-level semantic rules: the checks AST lint cannot express.

Every rule runs on the TRACED program (closed jaxpr / lowered module), not
on source text, so it sees through call indirection, Python-level
branching on statics, and closure capture:

  census/donation-unconsumed   a donate_argnums buffer the lowering could
                               not alias into any output (shape/dtype
                               mismatch or unused input) — today only the
                               runtime warnings hook sees this, and only
                               when KUBETPU_SANITIZE=1 is armed
  census/f64-promotion         a float64 value appears in the traced
                               graph when the declared inputs are 32-bit
                               — detected by re-tracing under x64 so
                               latent np.float64 promotions that the
                               default config silently truncates surface
                               statically
  census/host-callback         io_callback / pure_callback /
                               debug_callback reachable from a kernel
                               root: a host round-trip inside the device
                               program
  census/rank-promotion        the trace fails under
                               jax_numpy_rank_promotion="raise" — an
                               implicit broadcast in the traced graph
  census/constant-capture      a closed-over array above the size
                               threshold baked into the program as a
                               literal (shipped with EVERY executable and
                               re-hashed on every compile-cache probe)
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

# closed-over constants at or above this many bytes are findings
CONST_CAPTURE_THRESHOLD = 256 * 1024

_CALLBACK_PRIMITIVES = frozenset({
    "io_callback", "pure_callback", "debug_callback", "host_callback_call",
    "outside_call",
})


@dataclasses.dataclass
class Finding:
    rule: str
    program: str
    message: str
    suppressed: bool = False
    reason: str = ""

    def to_json(self) -> dict:
        return {"rule": self.rule, "program": self.program,
                "message": self.message, "suppressed": self.suppressed,
                "reason": self.reason}

    def __str__(self) -> str:
        tag = " (suppressed: %s)" % self.reason if self.suppressed else ""
        return "%s: [%s] %s%s" % (self.program, self.rule, self.message, tag)


def _walk_jaxprs(jaxpr):
    """Yield ``jaxpr`` and every sub-jaxpr reachable through eqn params
    (pjit bodies, scan/while/cond branches, custom calls)."""
    seen = set()
    stack = [jaxpr]
    while stack:
        j = stack.pop()
        if id(j) in seen:
            continue
        seen.add(id(j))
        yield j
        for eqn in j.eqns:
            for v in eqn.params.values():
                for sub in _sub_jaxprs(v):
                    stack.append(sub)


def _sub_jaxprs(v):
    from jax import core
    if isinstance(v, core.Jaxpr):
        yield v
    elif isinstance(v, core.ClosedJaxpr):
        yield v.jaxpr
    elif isinstance(v, (tuple, list)):
        for x in v:
            yield from _sub_jaxprs(x)


def _iter_avals(jaxpr):
    for j in _walk_jaxprs(jaxpr):
        for v in j.invars + j.outvars + j.constvars:
            aval = getattr(v, "aval", None)
            if aval is not None:
                yield aval
        for eqn in j.eqns:
            for v in eqn.outvars:
                aval = getattr(v, "aval", None)
                if aval is not None:
                    yield aval


def check_host_callbacks(program: str, closed_jaxpr) -> List[Finding]:
    out = []
    for j in _walk_jaxprs(closed_jaxpr.jaxpr):
        for eqn in j.eqns:
            if eqn.primitive.name in _CALLBACK_PRIMITIVES:
                out.append(Finding(
                    "census/host-callback", program,
                    "primitive %r reachable from the kernel root — a host "
                    "round-trip inside the device program"
                    % eqn.primitive.name))
    return out


def check_constant_capture(program: str, closed_jaxpr,
                           threshold: int = CONST_CAPTURE_THRESHOLD
                           ) -> List[Finding]:
    import numpy as np
    out = []
    consts = list(closed_jaxpr.consts)
    for j in _walk_jaxprs(closed_jaxpr.jaxpr):
        for eqn in j.eqns:
            for v in eqn.params.values():
                if hasattr(v, "consts"):
                    consts.extend(v.consts)
    for c in consts:
        nbytes = getattr(c, "nbytes", None)
        if nbytes is None:
            try:
                nbytes = np.asarray(c).nbytes
            except Exception:
                continue
        if nbytes >= threshold:
            out.append(Finding(
                "census/constant-capture", program,
                "closed-over array of %d bytes (shape %s) baked into the "
                "program as a literal — pass it as an argument instead"
                % (nbytes, getattr(c, "shape", "?"))))
    return out


def check_f64(program: str, jaxpr_fn, args) -> List[Finding]:
    """Re-trace under x64 with the SAME declared (32-bit) input avals;
    any float64 aval in the graph is a latent promotion the default
    config silently truncates."""
    import numpy as np
    import jax
    from jax.experimental import enable_x64
    out = []
    try:
        with enable_x64():
            closed = jax.make_jaxpr(jaxpr_fn)(*args)
    except Exception as e:  # a trace that only works in x32 is itself news
        return [Finding("census/f64-promotion", program,
                        "trace failed under x64: %r" % (e,))]
    hits = set()
    for aval in _iter_avals(closed.jaxpr):
        dt = getattr(aval, "dtype", None)
        if (dt is not None and dt == np.float64
                and not getattr(aval, "weak_type", False)):
            # weak f64 = a Python float literal, canonicalized to f32
            # under the serving config with identical value — only
            # COMMITTED (non-weak) f64 marks a real promotion
            hits.add(str(aval.str_short()) if hasattr(aval, "str_short")
                     else str(aval))
    for h in sorted(hits)[:4]:
        out.append(Finding(
            "census/f64-promotion", program,
            "float64 value %s appears in the traced graph under x64 with "
            "32-bit inputs — a latent promotion (np.float64 operand or "
            "f64 literal) the x64-disabled default silently truncates"
            % h))
    return out


def check_rank_promotion(program: str, jaxpr_fn, args) -> List[Finding]:
    """Trace with jax_numpy_rank_promotion='raise'; a failing trace means
    an implicit broadcast inside the program."""
    import jax
    prev = jax.config.jax_numpy_rank_promotion
    try:
        jax.config.update("jax_numpy_rank_promotion", "raise")
        jax.eval_shape(jaxpr_fn, *args)
    except Exception as e:
        msg = str(e).splitlines()[0][:200]
        return [Finding(
            "census/rank-promotion", program,
            "trace fails under rank_promotion=raise: %s" % msg)]
    finally:
        jax.config.update("jax_numpy_rank_promotion", prev)
    return []


def check_donation(program: str, lowered, donate_argnums,
                   n_donated_leaves: Optional[int] = None) -> List[Finding]:
    """The lowering-level half of donation verification: jax annotates
    every HONORED donation as an input/output alias
    (``tf.aliasing_output``) in the lowered module; donated buffers that
    carry no alias could not be consumed (shape/dtype mismatch or unused
    input) and will be silently copied at runtime.  ``n_donated_leaves``:
    flattened leaf count of the donated args, for the partial case."""
    if not donate_argnums:
        return []
    text = lowered.as_text()
    aliased = text.count("tf.aliasing_output")
    if aliased == 0:
        return [Finding(
            "census/donation-unconsumed", program,
            "donate_argnums=%s but the lowered module aliases no input "
            "into any output — XLA cannot reuse the donated buffers"
            % (tuple(donate_argnums),))]
    if n_donated_leaves is not None and aliased < n_donated_leaves:
        return [Finding(
            "census/donation-unconsumed", program,
            "only %d of %d donated buffers alias an output — the rest "
            "are silently copied (shape/dtype mismatch between donated "
            "input and every output)" % (aliased, n_donated_leaves))]
    return []
