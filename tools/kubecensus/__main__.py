"""CLI: ``python -m tools.kubecensus [--write | --check] [--json]``.

--write      regenerate COMPILE_MANIFEST.json from a fresh census
--check      (default) regenerate in memory, diff against the committed
             manifest, run the jaxpr rule family; nonzero exit on any
             drift or unsuppressed finding — the CI drift gate
--json       machine-readable report on stdout
--no-mesh    skip the mesh twin rows (debugging aid; the committed
             manifest includes them)
--no-rules   trace only (manifest work without the semantic pass)
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="kubecensus")
    mode = ap.add_mutually_exclusive_group()
    mode.add_argument("--write", action="store_true",
                      help="regenerate COMPILE_MANIFEST.json")
    mode.add_argument("--check", action="store_true",
                      help="drift gate against the committed manifest "
                           "(default)")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--no-mesh", action="store_true")
    ap.add_argument("--no-rules", action="store_true")
    ap.add_argument("--manifest", default=None,
                    help="manifest path override (tests)")
    args = ap.parse_args(argv)

    from .census import run_census
    from .manifest import (MANIFEST_PATH, diff_manifest, load_manifest,
                           write_manifest)

    res = run_census(with_mesh=not args.no_mesh,
                     with_rules=not args.no_rules)
    path = args.manifest or MANIFEST_PATH

    if args.write:
        out = write_manifest(res.rows, path)
        report = {"written": out, "rows": len(res.rows),
                  "findings": [f.to_json() for f in res.findings],
                  "suppressed": [f.to_json() for f in res.suppressed]}
        ok = not res.findings
    else:
        drift = diff_manifest(res.rows, load_manifest(path))
        report = {"manifest": path, "rows": len(res.rows), "drift": drift,
                  "findings": [f.to_json() for f in res.findings],
                  "suppressed": [f.to_json() for f in res.suppressed]}
        ok = (not res.findings and not drift["added"]
              and not drift["removed"] and not drift["changed"]
              and not drift.get("missing_manifest"))
        report["clean"] = ok

    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        if args.write:
            print("wrote %s (%d rows)" % (report["written"], len(res.rows)))
        else:
            d = report["drift"]
            if d.get("missing_manifest"):
                print("no committed manifest at %s — run --write" % path)
            for kind in ("added", "removed", "changed"):
                for rid in d.get(kind, []):
                    print("drift(%s): %s" % (kind, rid))
        for f in res.findings:
            print(str(f))
        for f in res.suppressed:
            print(str(f))
        if not args.write:
            print("census: %s (%d rows)"
                  % ("clean" if ok else "FINDINGS/DRIFT", len(res.rows)))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
