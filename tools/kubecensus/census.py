"""Census tracer: abstract interpretation of every registered jit root.

For each (entry x ladder rung [x mesh]) variant this module abstractifies
the registry-built inputs to ShapeDtypeStructs, runs ``jit(...).lower()``
(tracing + StableHLO lowering, no device execution, no compile), and
derives the manifest row: flattened in/out avals, the donation aliasing
XLA honored, a stable sha256 of the closed jaxpr, and XLA cost-analysis
FLOPs/bytes.  The jaxpr-level rule family (rules.py) runs once per entry
on the smallest rung — the rules are shape-independent, the ladder rows
are not.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Dict, List, Optional, Tuple

from . import rules
from .registry import ENTRIES, Entry, Rung, build_world
from .rules import Finding

__all__ = ["Finding", "CensusResult", "run_census", "audit_entry",
           "audit_callable", "trace_variant"]


def _is_array(x) -> bool:
    import numpy as np
    import jax
    return isinstance(x, (np.ndarray, jax.Array))


def _abstract(tree, keep_sharding: bool = False):
    """Arrays -> ShapeDtypeStruct (optionally keeping committed
    NamedShardings); everything else passes through untouched."""
    import jax

    def leaf(x):
        if _is_array(x):
            sh = None
            if keep_sharding and isinstance(x, jax.Array):
                s = x.sharding
                if type(s).__name__ == "NamedSharding":
                    sh = s
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sh)
        return x
    return jax.tree.map(leaf, tree)


def _split_kwargs(kwargs: dict,
                  static_names: Tuple[str, ...]) -> Tuple[dict, dict]:
    """(dynamic traced kwargs, static kwargs).  Statics are exactly the
    names the jit's static_argnames declares (registry Entry mirrors the
    decorator); everything else — arrays AND Python scalars — is traced
    and contributes an aval to the compiled signature."""
    dyn, static = {}, {}
    for k, v in kwargs.items():
        (static if k in static_names else dyn)[k] = v
    return dyn, static


def aval_strs(tree) -> List[str]:
    """Flattened 'dtype[d0,d1]' signatures, matching the spelling of
    jax's own compile-log ShapedArray repr so the runtime cross-check
    (manifest.match_compile_events) compares like with like.  Python
    scalars are traced as weak-typed rank-0 avals of the default dtype —
    record them the way the log will report them."""
    import jax
    out = []
    for leaf in jax.tree_util.tree_leaves(tree):
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            dims = ",".join(str(d) for d in leaf.shape)
            out.append("%s[%s]" % (leaf.dtype.name, dims))
        elif isinstance(leaf, bool):
            out.append("bool[]")
        elif isinstance(leaf, int):
            out.append("int32[]")
        elif isinstance(leaf, float):
            out.append("float32[]")
        else:
            out.append(repr(leaf))
    return out


def _lowering_hash(text: str) -> str:
    """sha256 of the lowered StableHLO module text — the traced jaxpr's
    canonical serialization.  NOT the pretty-printed jaxpr: jax's jaxpr
    printer shares repeated sub-jaxprs through a process-wide name
    counter (_where17 vs _where18), so str(jaxpr) depends on what else
    the process traced first; the MLIR module is self-contained and —
    together with the cold-cache lowering in trace_variant — stable
    across processes for a fixed jax version."""
    return hashlib.sha256(text.encode()).hexdigest()


def _static_sig(static_kw: dict) -> str:
    """Short stable digest of the static argument values (ProgramConfig
    etc.) so manifest rows distinguish static variants without embedding
    pages of repr."""
    r = repr(sorted((k, repr(v)) for k, v in static_kw.items()))
    return hashlib.sha256(r.encode()).hexdigest()[:16]


def _closure(fn, args, static_argnums: Tuple[int, ...],
             dyn_names: List[str], static_kw: dict):
    """A positional-only callable over (dynamic pos args + dynamic
    kwargs), with every static (positional or keyword) closed over —
    what make_jaxpr / eval_shape can trace.  ``args`` supplies the
    static positions' values; dynamic positions are replaced from the
    call's flat inputs."""
    stat = set(static_argnums)
    dyn_idx = [i for i in range(len(args)) if i not in stat]

    def call(*flat):
        full = list(args)
        for j, i in enumerate(dyn_idx):
            full[i] = flat[j]
        dkw = dict(zip(dyn_names, flat[len(dyn_idx):]))
        return fn(*full, **dkw, **static_kw)
    return call


@dataclasses.dataclass
class Variant:
    """One traced (entry, rung[, mesh]) combination."""
    row: dict
    lowered: object
    entry: Entry


def trace_variant(entry: Entry, rung: Rung, mesh: bool = False) -> Variant:
    import jax

    world = build_world(rung)
    fn, args, kwargs = entry.build(world)
    dyn_kw, static_kw = _split_kwargs(kwargs, entry.static_argnames)
    if mesh:
        args, dyn_kw = _mesh_place(entry, args, dyn_kw)
    # keep committed NamedShardings either for the @mesh twin (inputs
    # placed above) or for entries whose builders already commit them
    # (the shard_map family) — stripping them would lower a module the
    # serving path never dispatches
    keep = mesh or entry.keep_sharding
    stat_idx = set(entry.static_argnums)
    abs_args = tuple(a if i in stat_idx
                     else _abstract(a, keep_sharding=keep)
                     for i, a in enumerate(args))
    abs_dyn = _abstract(dyn_kw, keep_sharding=keep)
    dyn_pos = [a for i, a in enumerate(abs_args) if i not in stat_idx]
    # Cold-cache lowering: jax dedups repeated sub-jaxprs (_where/_take/
    # clip helpers) into shared private funcs through trace caches that
    # outlive a single lower() — a warm cache from UNRELATED earlier work
    # changes which helpers dedup, adding/removing a private func and
    # renumbering every symbol after it, so the module text (and its
    # sha256) would depend on process history.  Clearing right before
    # the lower pins every variant to the one canonical cold-cache
    # module; the manifest is regenerated under the same discipline.
    jax.clear_caches()
    lowered = _lower(entry, fn, abs_args, abs_dyn, static_kw, mesh)
    out_avals = _out_avals(lowered, fn, abs_args, entry.static_argnums,
                           abs_dyn, static_kw)
    cost = _cost(lowered)
    xb = _collective_bytes(entry, rung)
    if xb is not None:
        cost = dict(cost or {})
        cost["collective_bytes"] = xb
    n_donated = 0
    if entry.donate_argnums:
        n_donated = sum(
            len(jax.tree_util.tree_leaves(_abstract(args[i])))
            for i in entry.donate_argnums if i < len(args))
    text = lowered.as_text()   # multi-MB for the big programs: once
    aliased = text.count("tf.aliasing_output")
    variant_name = rung.name + ("@mesh" if mesh else "")
    in_avals = aval_strs((dyn_pos, abs_dyn))
    statics = dict(static_kw)
    statics.update({"arg%d" % i: args[i] for i in stat_idx})
    row = {
        "program": entry.program,
        "tag": entry.tag,
        "qualname": entry.qualname,
        "variant": variant_name,
        "in_avals": in_avals,
        "compiled_in_avals": _compiled_in_avals(lowered, in_avals),
        "out_avals": aval_strs(out_avals),
        "lowering_sha256": _lowering_hash(text),
        "static_sig": _static_sig(statics),
        "donation": {"argnums": list(entry.donate_argnums),
                     "donated_leaves": n_donated,
                     "aliased_outputs": aliased},
        "sharding": "pods=1,nodes=1" if mesh else None,
        "cost": cost,
    }
    return Variant(row=row, lowered=lowered, entry=entry)


def _compiled_in_avals(lowered, fallback: List[str]) -> List[str]:
    """The POST-PRUNING input avals — what XLA actually compiles and
    what jax's compile log reports (jit drops args the program never
    reads, e.g. batch term tables a cfg without those filters ignores).
    Read from the lowering's compile args; fall back to the full call
    signature on jax versions that don't expose them."""
    try:
        avals = lowered._lowering.compile_args["global_in_avals"]
    except Exception:
        return list(fallback)
    return ["%s[%s]" % (a.dtype.name, ",".join(str(d) for d in a.shape))
            for a in avals]


def _lower(entry, fn, abs_args, abs_dyn, static_kw, mesh):
    if mesh:
        from kubetpu.parallel import mesh as pmesh
        m = pmesh.make_mesh((1, 1))
        with pmesh.ambient_mesh(m):
            return fn.lower(*abs_args, **abs_dyn, **static_kw)
    return fn.lower(*abs_args, **abs_dyn, **static_kw)


def _mesh_place(entry, args, dyn_kw):
    """Commit the variant's inputs to a (1, 1) mesh the way the serving
    path does (mesh.shard_cluster/shard_batch semantics), so the lowered
    module carries the NamedShardings of the sharded program family."""
    import jax

    from kubetpu.parallel import mesh as pmesh
    from kubetpu.state.tensors import ClusterTensors
    m = pmesh.make_mesh((1, 1))

    def place(x):
        if isinstance(x, ClusterTensors):
            return pmesh.shard_cluster(x, m)
        if _is_array(x):
            return pmesh.replicate(x, m)
        if hasattr(x, "_fields"):     # PodBatch / overlay NamedTuples
            return pmesh.shard_batch(x, m)
        return x
    stat = set(entry.static_argnums)
    return (tuple(a if i in stat else place(a)
                  for i, a in enumerate(args)),
            {k: place(v) for k, v in dyn_kw.items()})


def _out_avals(lowered, fn, abs_args, static_argnums, abs_dyn, static_kw):
    import jax
    out = getattr(lowered, "out_info", None)
    if out is not None:
        return out
    stat = set(static_argnums)
    dyn_pos = [a for i, a in enumerate(abs_args) if i not in stat]
    return jax.eval_shape(
        _closure(fn, abs_args, static_argnums, list(abs_dyn), static_kw),
        *(tuple(dyn_pos) + tuple(abs_dyn.values())))


_exact_surface_cache: Optional[dict] = None


def _collective_bytes(entry: Entry, rung: Rung) -> Optional[dict]:
    """Per-collective DCN byte attribution for this variant, joined from
    the committed exactness surface (EXACT_MANIFEST.json, written by
    ``python -m tools.kubeexact --write``).  Lets devstats/benchtrend
    split a program's roofline into arithmetic vs cross-device transfer.
    Programs outside the exactness registry (or a missing manifest)
    contribute nothing — never an error."""
    global _exact_surface_cache
    if _exact_surface_cache is None:
        try:
            from tools.kubeexact.manifest import load_manifest
            _exact_surface_cache = load_manifest() or {}
        except Exception:
            _exact_surface_cache = {}
    key = entry.program + (":" + entry.tag if entry.tag else "")
    prog = (_exact_surface_cache.get("programs") or {}).get(key)
    if prog is None:
        return None
    rows = (prog.get("surface") or {}).get(rung.name)
    if rows is None:
        return None
    by_op: Dict[str, int] = {}
    for r in rows:
        by_op[r["op"]] = by_op.get(r["op"], 0) + int(r.get("bytes", 0))
    return {"total_bytes": sum(by_op.values()), "ops": len(rows),
            "by_op": by_op}


def _cost(lowered) -> Optional[dict]:
    try:
        ca = lowered.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        return None
    out = {}
    if "flops" in ca:
        out["flops"] = float(ca["flops"])
    if "bytes accessed" in ca:
        out["bytes_accessed"] = float(ca["bytes accessed"])
    return out or None


# ---------------------------------------------------------------- rules


def audit_callable(program: str, fn, args: tuple, kwargs: dict = None,
                   donate_argnums: Tuple[int, ...] = (),
                   static_argnames: Tuple[str, ...] = (),
                   static_argnums: Tuple[int, ...] = (),
                   const_threshold: int = rules.CONST_CAPTURE_THRESHOLD,
                   ) -> List[Finding]:
    """Run every jaxpr-level rule on one callable at one input signature.
    ``fn`` may be a jit object or a plain traceable; statics ride in
    kwargs (static_argnames) or positionally (static_argnums).  This is
    the public seam the bad-snippet tests drive."""
    import jax

    kwargs = kwargs or {}
    dyn_kw, static_kw = _split_kwargs(kwargs, static_argnames)
    stat_idx = set(static_argnums)
    abs_args = tuple(a if i in stat_idx else _abstract(a)
                     for i, a in enumerate(args))
    abs_dyn = _abstract(dyn_kw)
    dyn_pos = [a for i, a in enumerate(abs_args) if i not in stat_idx]
    call = _closure(fn, abs_args, static_argnums, list(abs_dyn), static_kw)
    flat = tuple(dyn_pos) + tuple(abs_dyn.values())
    findings: List[Finding] = []
    closed = jax.make_jaxpr(call)(*flat)
    findings += rules.check_host_callbacks(program, closed)
    findings += rules.check_constant_capture(program, closed,
                                             threshold=const_threshold)
    findings += rules.check_f64(program, call, flat)
    findings += rules.check_rank_promotion(program, call, flat)
    if donate_argnums and hasattr(fn, "lower"):
        lowered = fn.lower(*abs_args, **abs_dyn, **static_kw)
        n_donated = sum(len(jax.tree_util.tree_leaves(abs_args[i]))
                        for i in donate_argnums if i < len(abs_args))
        findings += rules.check_donation(program, lowered, donate_argnums,
                                         n_donated)
    return findings


def audit_entry(entry: Entry, rung: Optional[Rung] = None) -> List[Finding]:
    """Rules for one registry entry (smallest ladder rung by default),
    with the entry's audited exemptions applied."""
    rung = rung or entry.ladder[0]
    world = build_world(rung)
    fn, args, kwargs = entry.build(world)
    raw = audit_callable(entry.key, fn, args, kwargs,
                         donate_argnums=entry.donate_argnums,
                         static_argnames=entry.static_argnames,
                         static_argnums=entry.static_argnums)
    exempt = dict(entry.exempt)
    used = set()
    out: List[Finding] = []
    for f in raw:
        reason = exempt.get(f.rule, "")
        if reason:
            f.suppressed, f.reason = True, reason
            used.add(f.rule)
        out.append(f)
    for rule, reason in exempt.items():
        if rule not in used:
            out.append(Finding(
                "census/unused-exemption", entry.key,
                "exemption for %s matches no finding — remove the stale "
                "entry (reason was: %s)" % (rule, reason)))
    return out


# ----------------------------------------------------------- whole census


@dataclasses.dataclass
class CensusResult:
    rows: List[dict]
    findings: List[Finding]          # unsuppressed
    suppressed: List[Finding]

    @property
    def clean(self) -> bool:
        return not self.findings


def run_census(entries: Optional[List[Entry]] = None,
               with_mesh: bool = True,
               with_rules: bool = True) -> CensusResult:
    """Trace every registered variant across its ladder (plus the mesh
    twin for meshable entries) and run the rule family once per entry.
    Rows come back sorted by (program, tag, variant) so the manifest
    serialization is order-independent of the registry."""
    from .discover import unregistered_roots

    entries = ENTRIES if entries is None else entries
    rows: List[dict] = []
    findings: List[Finding] = []
    suppressed: List[Finding] = []
    for e in entries:
        for rung in e.ladder:
            rows.append(trace_variant(e, rung).row)
        if e.meshable:
            rows.append(trace_variant(e, e.ladder[0], mesh=True).row)
        if with_rules:
            for f in audit_entry(e):
                (suppressed if f.suppressed else findings).append(f)
    if with_rules:
        findings.extend(unregistered_roots({e.qualname for e in entries}))
    rows.sort(key=lambda r: (r["program"], r["tag"], r["variant"]))
    return CensusResult(rows=rows, findings=findings, suppressed=suppressed)
