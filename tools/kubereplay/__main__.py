"""CLI for the journal replay rig.

  python -m tools.kubereplay <journal-dir>                  bit-match oracle
  python -m tools.kubereplay <dir> --window 10:60           seq window
  python -m tools.kubereplay <dir> --counterfactual scoreWeight:NodeResourcesBalancedAllocation=5
  python -m tools.kubereplay <dir> --counterfactual kernelBackend=pallas
  python -m tools.kubereplay <dir> --counterfactual pipelineDepth=4
  ... --json                                                machine-readable

Exit codes: 0 = replay ok (bit-match held, or counterfactual measured),
2 = bit-match divergence (a correctness failure), 1 = nothing replayable.
"""

from __future__ import annotations

import argparse
import json
import sys

from . import replay_journal


def parse_counterfactual(clauses):
    """scoreWeight:<Plugin>=<int> | kernelBackend=<lax|pallas> |
    pipelineDepth=<int> -> the replay_journal counterfactual dict."""
    if not clauses:
        return None
    out = {"score_weights": {}}
    for raw in clauses:
        key, sep, val = raw.partition("=")
        if not sep:
            raise SystemExit(f"--counterfactual {raw!r}: want key=value")
        if key.startswith("scoreWeight:"):
            out["score_weights"][key[len("scoreWeight:"):]] = int(val)
        elif key == "kernelBackend":
            if val not in ("lax", "pallas"):
                raise SystemExit("--counterfactual kernelBackend must be "
                                 "lax or pallas")
            out["kernel_backend"] = val
        elif key == "pipelineDepth":
            out["pipeline_depth"] = int(val)
        else:
            raise SystemExit(f"--counterfactual {raw!r}: unknown key "
                             f"{key!r} (scoreWeight:<Plugin>, "
                             "kernelBackend, pipelineDepth)")
    if not out["score_weights"]:
        out.pop("score_weights")
    return out


def parse_window(raw):
    if raw is None:
        return None
    lo, sep, hi = raw.partition(":")
    if not sep:
        raise SystemExit("--window wants START:END (journal seqs)")
    return int(lo), int(hi)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="kubereplay",
        description="bit-exact offline replay of kubetpu cycle journals")
    ap.add_argument("journal", help="journal directory (KUBETPU_JOURNAL)")
    ap.add_argument("--window", default=None,
                    help="replay only journal seqs START:END (lineage "
                         "warm-up from the nearest resync anchor)")
    ap.add_argument("--counterfactual", action="append", default=[],
                    metavar="K=V",
                    help="re-run under a modified profile; repeatable "
                         "(scoreWeight:<Plugin>=N, kernelBackend=lax|"
                         "pallas, pipelineDepth=N)")
    ap.add_argument("--keep-going", action="store_true",
                    help="keep replaying past a bit-match divergence "
                         "(bounded; default stops at the first)")
    ap.add_argument("--json", action="store_true", dest="as_json")
    args = ap.parse_args(argv)

    try:
        report = replay_journal(
            args.journal, window=parse_window(args.window),
            counterfactual=parse_counterfactual(args.counterfactual),
            keep_going=args.keep_going)
    except FileNotFoundError as e:
        print(str(e), file=sys.stderr)
        return 1

    if args.as_json:
        print(json.dumps(report, indent=1, default=str))
    else:
        print(f"journal {report['dir']}: {report['records']} records, "
              f"{report['considered']} considered, "
              f"{report['replayed']} replayed, "
              f"{report['matched']} bit-matched, "
              f"{len(report['skipped'])} skipped")
        for s in report["skipped"]:
            print(f"  skip seq {s['seq']}: {s['reason']}")
        cf = report.get("counterfactual")
        if cf:
            print(f"counterfactual {cf['overrides']}: "
                  f"{cf['divergent_cycles']}/{cf['cycles']} cycles "
                  f"diverged ({cf['diverged_pods']} pods moved)")
            u = cf["utilization"]
            print(f"  utilization recorded={u['recorded']}")
            print(f"  utilization counterfactual={u['counterfactual']}")
            print(f"  delta={u['delta']}")
        elif report["first_divergence"] is not None:
            d = report["first_divergence"]
            print(f"FIRST DIVERGENCE at seq {d['seq']} (cycle "
                  f"{d['cycle']}, flight_seq "
                  f"{d['links'].get('flight_seq')}): "
                  f"rounds {d['recorded_rounds']} -> "
                  f"{d['replayed_rounds']}")
            for p in d["pod_diff"][:16]:
                print(f"  {p['pod']}: {p['recorded_node'] or '-'} -> "
                      f"{p['replayed_node'] or '-'} (n_feasible "
                      f"{p['recorded_n_feasible']} -> "
                      f"{p['replayed_n_feasible']})")
        elif report["bit_match"]:
            print("bit-match oracle HELD")
    if report.get("counterfactual") is not None:
        return 0
    if report["first_divergence"] is not None:
        return 2
    return 0 if report["replayed"] else 1


if __name__ == "__main__":
    sys.exit(main())
