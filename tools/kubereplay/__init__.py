"""kubereplay: offline bit-exact re-execution of journaled cycle windows.

The durable cycle journal (kubetpu/utils/journal.py) records every
committed scheduling cycle's exact device-program inputs and outputs.
This tool re-executes any journaled window through the SAME device
programs (models/gang.run_auction / models/sequential
.schedule_sequential) and **bit-matches** the replayed packed placement
vector against the recorded one — the same oracle discipline as the
Pallas and AOT gates: a divergence is a correctness failure, attributed
to the FIRST divergent cycle with a per-pod decision diff.

Replay reconstructs the scheduler's two device lineages exactly as the
serving loop maintained them:

  * the RESIDENT lineage — ``resync`` records re-upload the journaled
    host mirror (``HostClusterArrays.to_device``), ``delta`` records
    scatter the journaled ``ClusterDelta`` (and wholesale term
    replacement) onto it via ``programs.apply_cluster_delta``, ``noop``
    records leave it untouched;
  * the CHAIN lineage — a ``chain`` record's cluster is the PREVIOUS
    record's replayed auction materialized at the journaled pad buckets
    (``models/gang.materialize_assigned``, ``extend_score_terms=True``).

A corrupt/truncated record (crash, chaos ``journal`` point) or a seq gap
(a dropped write) is skipped with a per-record reason and breaks the
lineage: every subsequent non-anchor record skips with
``broken-lineage`` until the next ``resync`` anchor restores it — the
window degrades, it never aborts.

``--counterfactual`` re-runs the window under a modified profile (score
weights, ``kernelBackend``, ``pipelineDepth``) and reports per-cycle
placement divergence plus utilization/spread deltas — every recorded
production trace becomes an eval set (ROADMAP item 3's learned-scorer
substrate).  Counterfactual placements PROPAGATE through the chain
lineage (a changed placement changes the chained cluster downstream),
while delta records replay the FACTUAL environment churn as recorded —
and host plugin / extender verdicts replay from the recorded masks, not
re-executed (documented deviations; see README "Cycle journal &
replay").  ``pipelineDepth`` never enters a device program, so changing
it must report ZERO divergence — the acceptance check that the depth-k
executor's bit-identity contract survives into the replay rig.

Supported surface: single-device cycles (mesh profiles are journaled but
skip with ``unsupported-mesh``); extender-profile cycles are not
journaled at all (host-side selection has no packed device output).
"""

from __future__ import annotations

import pickle
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from kubetpu.utils.journal import INPUT_KINDS, read_records


class ReplayError(RuntimeError):
    pass


def _load_payload(rec: Dict[str, Any]):
    payload = rec.get("input_payload")
    if isinstance(payload, (bytes, bytearray)):
        return pickle.loads(payload)
    return payload


def _apply_counterfactual(rec: Dict[str, Any],
                          counterfactual: Optional[Dict[str, Any]]):
    """(cfg, kernel_backend) for this record's dispatch, with any
    counterfactual profile overrides applied.  ``pipeline_depth`` is
    accepted and deliberately ignored at dispatch — the executor depth
    never reaches a device program (the zero-divergence contract)."""
    cfg = rec["cfg"]
    backend = rec["kernel_backend"]
    if not counterfactual:
        return cfg, backend
    weights = counterfactual.get("score_weights")
    if weights:
        unknown = set(weights) - {name for name, _w in cfg.scores}
        if unknown:
            raise ReplayError(
                "counterfactual score plugin(s) not in the recorded "
                "profile: %s (recorded: %s)"
                % (sorted(unknown), [n for n, _ in cfg.scores]))
        cfg = cfg._replace(scores=tuple(
            (name, int(weights.get(name, w))) for name, w in cfg.scores))
    if counterfactual.get("kernel_backend"):
        backend = counterfactual["kernel_backend"]
    return cfg, backend


def _dispatch(rec: Dict[str, Any], cluster, cfg, kernel_backend):
    """Re-execute one journaled cycle's device program; returns the
    result object (``.packed`` is the oracle surface)."""
    import jax
    import jax.numpy as jnp

    batch = rec["batch"]
    rng = jax.random.PRNGKey(int(rec["rng_counter"]))
    host_ok = rec.get("host_ok")
    host_ok = jnp.asarray(host_ok) if host_ok is not None else None
    bias = rec.get("score_bias")
    bias = jnp.asarray(bias) if bias is not None else None
    if rec["mode"] == "gang":
        from kubetpu.models.gang import run_auction
        return run_auction(cluster, batch, cfg, rng, host_ok=host_ok,
                           intra_batch_topology=bool(rec["needs_topo"]),
                           score_bias=bias, kernel_backend=kernel_backend)
    from kubetpu.models.sequential import schedule_sequential
    return schedule_sequential(
        cluster, batch, cfg, rng,
        hard_pod_affinity_weight=float(rec["hard_pod_affinity_weight"]),
        host_ok=host_ok, start_index=int(rec["start_index"]),
        score_bias=bias)


def _materialize_chain(rec: Dict[str, Any], prev_cluster, prev_batch,
                       prev_res):
    from kubetpu.models.gang import materialize_assigned
    pads = _load_payload(rec)
    if not pads or len(pads) != 2:
        raise ReplayError(f"chain record {rec['seq']} carries no pad "
                          "buckets")
    return materialize_assigned(
        prev_cluster, prev_batch, prev_res.chosen, prev_res.requested,
        prev_res.nz, prev_res.ports_used,
        pad_pods_to=int(pads[0]), pad_terms_to=int(pads[1]),
        extend_score_terms=True,
        hard_pod_affinity_weight=float(rec["hard_pod_affinity_weight"]))


def _apply_delta(rec: Dict[str, Any], resident):
    """Replay one ``delta`` record onto the resident lineage — the exact
    twin of DeltaTensorizer._apply (terms replaced wholesale BEFORE the
    scatter; donation irrelevant to values, so replay never donates)."""
    import jax
    import jax.numpy as jnp

    from kubetpu.models import programs
    delta, terms = _load_payload(rec)
    if terms is not None:
        ft = jax.tree.map(jnp.array, terms[0])
        st = jax.tree.map(jnp.array, terms[1])
        resident = resident._replace(filter_terms=ft, score_terms=st)
    return programs.apply_cluster_delta(resident, delta, donate=False)


def _placements_of(rec: Dict[str, Any], packed: np.ndarray,
                   node_names: List[str]) -> Dict[str, str]:
    """pod name -> node name ('' unscheduled) from a packed vector — the
    recorded twin lives in rec['placements'] (note: the journal records
    the COMMIT outcome, so a device-chosen pod whose commit failed shows
    '' there; the device-level oracle is the packed vector itself)."""
    B = rec["batch"].valid.shape[0]
    chosen = packed[:B]
    out = {}
    for i, (name, _ns, _uid) in enumerate(rec["pods"]):
        c = int(chosen[i])
        out[name] = (node_names[c]
                     if 0 <= c < len(node_names) else "")
    return out


def _pod_diff(rec: Dict[str, Any], recorded: np.ndarray,
              replayed: np.ndarray,
              node_names: List[str]) -> List[Dict[str, Any]]:
    """Per-pod decision diff between a recorded and a replayed packed
    vector: which pods moved, their feasible-node counts and terminal
    unresolvable flags on each side."""
    B = rec["batch"].valid.shape[0]
    diffs = []
    for i, (name, ns, _uid) in enumerate(rec["pods"]):
        rc, pc = int(recorded[i]), int(replayed[i])
        rn = node_names[rc] if 0 <= rc < len(node_names) else ""
        pn = node_names[pc] if 0 <= pc < len(node_names) else ""
        if (rc, int(recorded[B + i]), int(recorded[2 * B + i])) == \
           (pc, int(replayed[B + i]), int(replayed[2 * B + i])):
            continue
        diffs.append({
            "pod": f"{ns}/{name}",
            "recorded_node": rn, "replayed_node": pn,
            "recorded_n_feasible": int(recorded[B + i]),
            "replayed_n_feasible": int(replayed[B + i]),
            "recorded_unresolvable": bool(recorded[2 * B + i]),
            "replayed_unresolvable": bool(replayed[2 * B + i]),
        })
    return diffs


def _utilization(placements: Dict[str, str]) -> Dict[str, Any]:
    """Placement-distribution summary over a window: how many pods
    landed, across how many nodes, how peaked/spread the per-node load
    is (the counterfactual report's utilization/spread axis)."""
    counts: Dict[str, int] = {}
    for node in placements.values():
        if node:
            counts[node] = counts.get(node, 0) + 1
    vals = list(counts.values())
    if not vals:
        return {"placed": 0, "nodes_used": 0, "max_per_node": 0,
                "mean_per_node": 0.0, "spread_std": 0.0}
    arr = np.asarray(vals, np.float64)
    return {"placed": int(arr.sum()),
            "nodes_used": len(vals),
            "max_per_node": int(arr.max()),
            "mean_per_node": round(float(arr.mean()), 3),
            "spread_std": round(float(arr.std()), 3)}


def replay_journal(directory: str,
                   window: Optional[Tuple[int, int]] = None,
                   counterfactual: Optional[Dict[str, Any]] = None,
                   keep_going: bool = False,
                   max_divergences: int = 16) -> Dict[str, Any]:
    """Replay a journal directory (optionally a ``(start, end)`` seq
    window) and return the report dict the CLI prints.

    Bit-match mode (no counterfactual): every replayed cycle's packed
    vector must equal the recorded one byte-for-byte; the first
    divergence is reported with its per-pod decision diff and — unless
    ``keep_going`` — stops the replay (the oracle has already failed).

    Counterfactual mode: divergence is the MEASUREMENT, not a failure —
    every cycle replays, per-cycle divergence counts and
    utilization/spread deltas are reported, and chains propagate the
    counterfactual placements downstream.

    Lineage warm-up: when a window is requested, replay still begins at
    the nearest ``resync`` anchor at-or-before the window start (the
    preceding records are replayed for state only, not reported)."""
    entries = list(read_records(directory))
    if not entries:
        raise FileNotFoundError(f"no journal records under {directory!r}")

    lo, hi = window if window else (None, None)
    start_at = None
    if lo is not None:
        # the nearest anchor at-or-before the window start
        for seq, rec, skip in entries:
            if seq > lo:
                break
            if rec is not None and rec.get("input") == "resync":
                start_at = seq
        if start_at is None:
            start_at = lo

    report: Dict[str, Any] = {
        "dir": directory,
        "records": len(entries),
        "window": list(window) if window else None,
        "considered": 0, "replayed": 0, "matched": 0,
        "skipped": [], "divergences": [],
        "first_divergence": None,
        "counterfactual": None,
        # the profile/config digests seen in the window: a window that
        # spans more than one digest mixes program configurations (a
        # rollout landed mid-window) — flagged so eval-set consumers can
        # partition by configuration
        "config_digests": [],
    }
    cf_requested = bool(counterfactual)
    cf_overrides: Dict[str, Any] = dict(counterfactual or {})
    cf_divergent_cycles = 0
    cf_diverged_pods = 0
    recorded_plc: Dict[str, str] = {}
    replayed_plc: Dict[str, str] = {}
    digests: List[str] = []

    # Lineage state is PER PROFILE: the scheduler keeps one resident
    # DeltaTensorizer (and one speculative chain) per profile, so a
    # multi-profile journal interleaves independent lineages.  Each
    # entry: {resident, node_names, prev: (seq, cluster, batch, res),
    # need_anchor} — prev additionally requires GLOBAL seq adjacency for
    # chain records (any interleaved cycle of another profile destroys
    # the scheduler's single chain slot, so a non-adjacent parent means
    # the record could not have chained off it).
    class _Lineage:
        __slots__ = ("resident", "node_names", "prev", "need_anchor")

        def __init__(self):
            self.resident = None
            self.node_names: List[str] = []
            self.prev: Optional[Tuple[int, Any, Any, Any]] = None
            self.need_anchor = True

    lineages: Dict[str, _Lineage] = {}
    last_seq: Optional[int] = None
    stop = False

    def skip(seq: int, reason: str, reported: bool) -> None:
        if reported:
            report["skipped"].append({"seq": seq, "reason": reason})

    def break_all() -> None:
        for ln in lineages.values():
            ln.need_anchor = True
            ln.prev = None

    for seq, rec, why in entries:
        if stop:
            break
        if start_at is not None and seq < start_at:
            continue
        if hi is not None and seq > hi:
            break
        reported = lo is None or seq >= lo
        if reported:
            report["considered"] += 1
        if rec is None:
            # the lost record's profile is unknowable: every lineage is
            # suspect until its next anchor
            skip(seq, f"corrupt record: {why}", reported)
            break_all()
            last_seq = seq
            continue
        kind = rec.get("input")
        line = lineages.setdefault(rec.get("profile") or "", _Lineage())
        if last_seq is not None and seq != last_seq + 1:
            # a seq gap (dropped write / evicted file) may hide a delta
            # cycle of ANY profile: no resident lineage is trustworthy
            # (a resync record right after the gap simply re-anchors its
            # own profile's lineage below)
            break_all()
        last_seq = seq
        if rec.get("mesh"):
            skip(seq, "unsupported-mesh", reported)
            line.need_anchor = True
            line.prev = None
            continue
        if kind not in INPUT_KINDS:
            skip(seq, f"unknown input kind {kind!r}", reported)
            line.need_anchor = True
            line.prev = None
            continue
        try:
            if kind == "resync":
                host = _load_payload(rec)
                line.resident = host.to_device()
                line.node_names = list(rec.get("node_names")
                                       or line.node_names)
                line.need_anchor = False
                cluster = line.resident
            elif line.need_anchor:
                skip(seq, "broken-lineage (no resync anchor since the "
                          "last skip/gap)", reported)
                continue
            elif kind == "delta":
                line.resident = _apply_delta(rec, line.resident)
                cluster = line.resident
            elif kind == "noop":
                cluster = line.resident
            else:   # chain
                if line.prev is None or line.prev[0] != seq - 1:
                    skip(seq, "broken-lineage (chain parent not the "
                              "adjacent replayed cycle of this "
                              "profile)", reported)
                    line.need_anchor = True
                    continue
                cluster = _materialize_chain(rec, line.prev[1],
                                             line.prev[2], line.prev[3])
            cfg, backend = _apply_counterfactual(rec, cf_overrides)
            res = _dispatch(rec, cluster, cfg, backend)
            packed = np.asarray(res.packed)
        except ReplayError as e:
            skip(seq, str(e), reported)
            line.need_anchor = True
            line.prev = None
            continue
        line.prev = (seq, cluster, rec["batch"], res)
        node_names = line.node_names
        if not reported:
            continue   # lineage warm-up before the window
        if rec.get("config_digest") and rec["config_digest"] not in digests:
            digests.append(rec["config_digest"])
        report["replayed"] += 1
        recorded = np.asarray(rec["packed"])
        match = (recorded.shape == packed.shape
                 and bool(np.array_equal(recorded, packed)))
        if cf_requested:
            diffs = _pod_diff(rec, recorded, packed, node_names)
            moved = [d for d in diffs
                     if d["recorded_node"] != d["replayed_node"]]
            if moved:
                cf_divergent_cycles += 1
                cf_diverged_pods += len(moved)
            recorded_plc.update(
                _placements_of(rec, recorded, node_names))
            replayed_plc.update(
                _placements_of(rec, packed, node_names))
            if match:
                report["matched"] += 1
            continue
        if match:
            report["matched"] += 1
            continue
        div = {
            "seq": seq,
            "cycle": rec.get("cycle"),
            "links": dict(rec.get("links") or {}),
            "verdicts": dict(rec.get("verdicts") or {}),
            "recorded_rounds": int(recorded[-1]) if recorded.size else 0,
            "replayed_rounds": int(packed[-1]) if packed.size else 0,
            "pod_diff": _pod_diff(rec, recorded, packed, node_names),
        }
        report["divergences"].append(div)
        if report["first_divergence"] is None:
            report["first_divergence"] = div
        if not keep_going or len(report["divergences"]) >= max_divergences:
            stop = True

    report["config_digests"] = digests
    report["bit_match"] = (report["first_divergence"] is None
                          and report["replayed"] > 0)
    if cf_requested:
        rec_util = _utilization(recorded_plc)
        rep_util = _utilization(replayed_plc)
        report["counterfactual"] = {
            "overrides": {k: v for k, v in cf_overrides.items() if v},
            "cycles": report["replayed"],
            "divergent_cycles": cf_divergent_cycles,
            "diverged_pods": cf_diverged_pods,
            "utilization": {
                "recorded": rec_util,
                "counterfactual": rep_util,
                "delta": {k: round(rep_util[k] - rec_util[k], 3)
                          for k in rec_util},
            },
        }
        # counterfactual mode measures divergence, it doesn't gate on it
        report["bit_match"] = None
    return report
