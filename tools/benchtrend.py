"""Bench-trend tooling: the committed BENCH_r*.json trajectory as a
per-case trend table, with per-stage regression ATTRIBUTION.

The repo commits one bench artifact per PR round (``BENCH_r01.json`` ..,
plus the ``MULTICHIP_r*.json`` mesh runs); each carries the bench.py
``detail`` document — and, since the SLO layer (kubetpu/utils/slo.py),
a per-case ``latency`` block (``pod_e2e_p50/p90/p99_s`` +
``stage_shares``).  This tool reads that trajectory, optionally appends
a fresh run (``--run`` pointing at a BENCH_OUT-format file), and prints:

  * a per-case trend table (pods/s per round, with the round-over-round
    delta), and
  * for every case whose throughput regressed beyond the threshold,
    WHICH STAGE's latency share grew — the stage_shares diff when both
    rounds carry the latency block, the host_share/device_wait split
    otherwise — and, when both rounds carry a devstats ``device`` block
    (kubetpu/utils/devstats.py), WHICH PROGRAM regressed: the one whose
    achieved roofline fraction fell, or whose resident HBM grew.

``--check`` is the CI mode (tools/ci_lint.sh): nonzero exit when a
committed artifact is schema-INCOMPATIBLE (a case present but
non-numeric where the trend table needs numbers) or when the newest
parseable round regresses beyond the NORTHSTAR.json gate (bench.py's
northstar_gate — the same floors/ceilings BENCH_GATE=1 enforces).
Artifacts whose detail cannot be recovered (e.g. a tail-truncated
capture) are reported and skipped, never a hard failure — the committed
history is immutable.

Usage:
  python -m tools.benchtrend [--glob 'BENCH_r*.json'] [--run FRESH.json]
                             [--check] [--threshold 0.1]
"""
from __future__ import annotations

import argparse
import glob as globmod
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# dotted case -> the numeric field the trend table tracks (first match
# wins; cases carrying neither are skipped).  steady_p99_s is the
# sustained_load case's windowed steady-state pod e2e p99
# (kubetpu/utils/telemetry.py) — a seconds row like the restart SLOs.
THROUGHPUT_KEYS = ("pods_per_sec",)
SECONDS_KEYS = ("e2e_best_s", "e2e_s", "restart_s", "cold_restart_s",
                "steady_p99_s")


def _find_detail(doc) -> Optional[Dict[str, Any]]:
    """Recover the bench ``detail`` document from any committed artifact
    shape: a BENCH_OUT file ({"headline", "detail"}), a raw
    {"detail": ...} stderr line, or the round-capture wrapper
    ({"parsed": {"detail": ...}, "tail": "..."}).  Falls back to
    scanning the captured tail for a parseable {"detail": ...} line
    (r05's tail was cut mid-line — that one stays unrecoverable and the
    caller reports it)."""
    if not isinstance(doc, dict):
        return None
    if isinstance(doc.get("detail"), dict):
        return doc["detail"]
    parsed = doc.get("parsed")
    if isinstance(parsed, dict) and isinstance(parsed.get("detail"), dict):
        return parsed["detail"]
    tail = doc.get("tail")
    if isinstance(tail, str):
        for line in tail.splitlines():
            idx = line.find('{"detail"')
            if idx < 0:
                continue
            try:
                cand = json.loads(line[idx:])
            except ValueError:
                continue
            if isinstance(cand.get("detail"), dict):
                return cand["detail"]
    return None


def load_round(path: str) -> Dict[str, Any]:
    name = os.path.basename(path)
    for suffix in (".json",):
        if name.endswith(suffix):
            name = name[: -len(suffix)]
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return {"round": name, "detail": None,
                "note": f"unreadable ({e.__class__.__name__})"}
    detail = _find_detail(doc)
    if detail is None:
        if isinstance(doc, dict) and "n_devices" in doc and "rc" in doc:
            # a MULTICHIP dryrun capture ({n_devices, rc, ok, tail}) —
            # a pass/fail record, not a bench round; a MULTICHIP-only
            # trajectory is a state, never an error
            return {"round": name, "detail": None,
                    "note": "multichip dryrun capture (ok=%s, %s "
                            "devices) — no bench detail to trend"
                            % (doc.get("ok"), doc.get("n_devices"))}
        return {"round": name, "detail": None,
                "note": "no parseable detail document "
                        "(truncated capture or non-bench artifact)"}
    return {"round": name, "detail": detail, "note": ""}


def flatten_cases(detail: Dict[str, Any]) -> Dict[str, Dict[str, Any]]:
    """Dotted case name -> case dict for every bench case that carries a
    trendable number (top level, chain_drain.* and northstar.*)."""
    out: Dict[str, Dict[str, Any]] = {}

    def visit(prefix: str, node, depth: int) -> None:
        if not isinstance(node, dict):
            return
        has_metric = any(isinstance(node.get(k), (int, float))
                         for k in THROUGHPUT_KEYS + SECONDS_KEYS)
        if has_metric:
            out[prefix] = node
            return
        if depth >= 2:
            return
        for k, v in node.items():
            if isinstance(v, dict):
                visit(f"{prefix}.{k}" if prefix else k, v, depth + 1)

    visit("", detail, 0)
    return out


def case_value(case: Dict[str, Any],
               unit: str = "") -> Tuple[Optional[float], str]:
    """(value, unit) — throughput preferred, seconds as fallback.  Pass
    ``unit`` to pin the extraction to one unit (rows must not mix
    pods/s from one round with seconds from another)."""
    if unit in ("", "pods/s"):
        for k in THROUGHPUT_KEYS:
            v = case.get(k)
            if isinstance(v, (int, float)):
                return float(v), "pods/s"
    if unit in ("", "s"):
        for k in SECONDS_KEYS:
            v = case.get(k)
            if isinstance(v, (int, float)):
                return float(v), "s"
    return None, ""


def row_unit(cases: List[Dict[str, Any]]) -> str:
    """One unit per trend row: pods/s when any round carries it."""
    for case in cases:
        if any(isinstance(case.get(k), (int, float))
               for k in THROUGHPUT_KEYS):
            return "pods/s"
    return "s"


def device_attribution(prev: Dict[str, Any],
                       cur: Dict[str, Any]) -> str:
    """Device-side half of the attribution (the devstats ``device``
    block, kubetpu/utils/devstats.py): name the PROGRAM whose achieved
    roofline fraction fell the most — or slowed the most when neither
    round carries a roofline join — and whether resident HBM grew, so a
    regression reads "run_auction's achieved fraction fell" instead of
    just "the device stage grew"."""
    dp = prev.get("device") or {}
    dc = cur.get("device") or {}
    pp, pc = dp.get("programs") or {}, dc.get("programs") or {}
    notes = []
    worst = None
    for name in sorted(set(pp) & set(pc)):
        f0 = pp[name].get("roofline_fraction")
        f1 = pc[name].get("roofline_fraction")
        if isinstance(f0, (int, float)) and isinstance(f1, (int, float)) \
                and f0 > 0:
            drop = (f0 - f1) / f0
        else:
            m0 = pp[name].get("mean_s")
            m1 = pc[name].get("mean_s")
            if not (isinstance(m0, (int, float))
                    and isinstance(m1, (int, float)) and m0 > 0):
                continue
            drop = (m1 - m0) / m0      # slower mean ~ fallen fraction
            f0 = f1 = None
        if drop > 0.1 and (worst is None or drop > worst[1]):
            worst = (name, drop, f0, f1,
                     pp[name].get("mean_s"), pc[name].get("mean_s"))
    if worst is not None:
        name, _drop, f0, f1, m0, m1 = worst
        if f0 is not None:
            notes.append(f"program '{name}' achieved fraction fell "
                         f"{f0:.4f} -> {f1:.4f}")
        else:
            notes.append(f"program '{name}' device time grew "
                         f"{1000 * m0:.1f} -> {1000 * m1:.1f} ms")
    b0, b1 = dp.get("ledger_bytes"), dc.get("ledger_bytes")
    if isinstance(b0, (int, float)) and isinstance(b1, (int, float)) \
            and b0 > 0 and b1 > b0 * 1.1:
        notes.append(f"resident HBM grew {int(b0)} -> {int(b1)} bytes "
                     f"(+{100 * (b1 - b0) / b0:.0f}%)")
    return "; ".join(notes)


def attribute_regression(prev: Dict[str, Any],
                         cur: Dict[str, Any]) -> str:
    """Name the stage whose share of per-pod latency grew most between
    two rounds of one case — the SLO layer's stage_shares when both
    carry it, the host/device split otherwise — plus the device-side
    attribution (device_attribution) when both rounds carry a devstats
    ``device`` block.  Config deltas are named FIRST — a mesh_shape or
    pipeline-depth change between the rounds is a config delta, not a
    stage regression — so "mesh_shape changed" leads the line before
    any stage-share diff."""
    note = ""
    ms0, ms1 = prev.get("mesh_shape"), cur.get("mesh_shape")
    if ms0 != ms1 and (ms0 is not None or ms1 is not None):
        def _ms(v):
            return "x".join(str(x) for x in v) if isinstance(
                v, (list, tuple)) else ("none" if v is None else str(v))
        note = f"mesh_shape changed {_ms(ms0)} -> {_ms(ms1)}; "
    pd0, pd1 = prev.get("pipeline_depth"), cur.get("pipeline_depth")
    if (isinstance(pd0, (int, float)) and isinstance(pd1, (int, float))
            and pd0 != pd1):
        note += f"pipeline_depth changed {int(pd0)} -> {int(pd1)}; "
    # recovery-path growth is named BEFORE stage shares: on the
    # sustained_load case (and node_flap) a steady-state p99 regression
    # that coincides with the recovery ladder firing more often is a
    # resilience-path regression, not a hot-path one
    for key in ("demotions", "recoveries"):
        r0, r1 = prev.get(key), cur.get(key)
        if (isinstance(r0, (int, float)) and isinstance(r1, (int, float))
                and r1 > r0):
            note += f"{key} grew {int(r0)} -> {int(r1)}; "
    dev = device_attribution(prev, cur)
    dev = ("; " + dev) if dev else ""
    ps = (prev.get("latency") or {}).get("stage_shares") or {}
    cs = (cur.get("latency") or {}).get("stage_shares") or {}
    if ps and cs:
        deltas = {k: cs.get(k, 0.0) - ps.get(k, 0.0)
                  for k in set(ps) | set(cs)}
        stage = max(deltas, key=lambda k: deltas[k])
        if deltas[stage] > 0:
            return note + (f"stage '{stage}' share grew "
                           f"{ps.get(stage, 0.0):.2f} -> "
                           f"{cs.get(stage, 0.0):.2f}"
                           f" (+{deltas[stage]:.2f})") + dev
        return note + "no stage share grew (uniform slowdown)" + dev
    hp, hc = prev.get("host_share"), cur.get("host_share")
    if isinstance(hp, (int, float)) and isinstance(hc, (int, float)):
        side = "host" if hc > hp else "device"
        return note + (f"no latency block on both sides; host_share "
                       f"{hp:.2f} -> {hc:.2f} ({side} side grew)") + dev
    return note + "no latency/host_share data to attribute" + dev


def build_trend(rounds: List[Dict[str, Any]],
                threshold: float) -> Tuple[List[str], List[str], List[str]]:
    """(table lines, attribution lines, schema errors)."""
    usable = [r for r in rounds if r["detail"] is not None]
    per_round = [(r["round"], flatten_cases(r["detail"])) for r in usable]
    names: List[str] = []
    for _, cases in per_round:
        for c in cases:
            if c not in names:
                names.append(c)
    errors: List[str] = []
    width = max([len(n) for n in names] + [4])
    header = f"{'case':<{width}}  " + "  ".join(
        f"{rn[-12:]:>12}" for rn, _ in per_round) + "  unit"
    lines = [header, "-" * len(header)]
    attributions: List[str] = []
    for name in names:
        present = [cases[name] for _, cases in per_round if name in cases]
        unit = row_unit(present)
        vals: List[Optional[float]] = []
        series: List[Tuple[str, Dict[str, Any], float]] = []
        for rn, cases in per_round:
            case = cases.get(name)
            if case is None:
                vals.append(None)
                continue
            v, _ = case_value(case, unit)
            if v is None:
                if case_value(case)[0] is None:
                    errors.append(
                        f"{rn}: case {name!r} present but carries no "
                        f"numeric "
                        f"{'/'.join(THROUGHPUT_KEYS + SECONDS_KEYS)} field")
                vals.append(None)
                continue
            vals.append(v)
            series.append((rn, case, v))
        cells = "  ".join("            " if v is None else f"{v:>12.1f}"
                          for v in vals)
        lines.append(f"{name:<{width}}  {cells}  {unit}")
        # round-over-round regression attribution on adjacent PRESENT
        # rounds (throughput: lower is worse; seconds: higher is worse)
        for (rn0, c0, v0), (rn1, c1, v1) in zip(series, series[1:]):
            if not v0:
                continue
            worse = (v1 < v0 * (1 - threshold) if unit == "pods/s"
                     else v1 > v0 * (1 + threshold))
            if worse:
                attributions.append(
                    f"{name}: {rn0} -> {rn1}: {v0:.1f} -> {v1:.1f} {unit}; "
                    + attribute_regression(c0, c1))
    return lines, attributions, errors


def validate_northstar(path: str) -> List[str]:
    """Schema check of NORTHSTAR.json's gate section that needs NO
    committed round: every entry must carry a numeric pods_per_sec floor
    or seconds ceiling, and its fraction knobs must be numeric.  This is
    what ``--check`` degrades to on an empty trajectory (a fresh repo,
    or a re-anchor that dropped the BENCH_r* history) — the gate file
    itself stays validated instead of the check erroring out."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except OSError:
        return []          # no NORTHSTAR.json yet: nothing to validate
    except ValueError as e:
        return [f"NORTHSTAR.json unparseable: {e}"]
    gate = doc.get("gate")
    if gate is None:
        return []
    if not isinstance(gate, dict):
        return ["NORTHSTAR.json: 'gate' must be a mapping"]
    errs: List[str] = []
    for key, ref in sorted(gate.items()):
        if not isinstance(ref, dict):
            errs.append(f"gate entry {key!r} must be a mapping")
            continue
        if not any(isinstance(ref.get(f), (int, float))
                   for f in ("pods_per_sec", "seconds")):
            errs.append(f"gate entry {key!r} carries neither a numeric "
                        "pods_per_sec floor nor a seconds ceiling")
        for f in ("min_frac", "max_frac"):
            if f in ref and not isinstance(ref[f], (int, float)):
                errs.append(f"gate entry {key!r}: {f} must be numeric")
        if "path" in ref and not isinstance(ref["path"], str):
            errs.append(f"gate entry {key!r}: path must be a string")
    return errs


def northstar_check(rounds: List[Dict[str, Any]]
                    ) -> Tuple[List[str], str]:
    """Run bench.py's NORTHSTAR gate against the newest parseable
    round's detail — the same floors/ceilings BENCH_GATE=1 enforces,
    minus the live-run-only bit-identity checks.  Returns (failures,
    coverage line): the coverage line says HOW MANY gate entries the
    round actually carried metrics for, so a PASS where every entry was
    skipped reads as 'gate not evaluated', never as a clean bill."""
    latest = next((r for r in reversed(rounds) if r["detail"] is not None),
                  None)
    if latest is None:
        return [], ""
    if REPO_ROOT not in sys.path:
        sys.path.insert(0, REPO_ROOT)
    try:
        from bench import _gate_path, northstar_gate
    except ImportError:
        return [], ""
    path = os.path.join(REPO_ROOT, "NORTHSTAR.json")
    # the trend check gates committed HISTORY, where placements_match
    # booleans may predate the oracle cases — only gate numeric drift
    detail = {k: v for k, v in latest["detail"].items()
              if k not in ("warm_restart", "backend_compare")}
    detail["warm_restart"] = {
        k: v for k, v in (latest["detail"].get("warm_restart") or {}).items()
        if k != "placements_match"}
    # same discipline for the sustained-load contract: the live-run
    # quartet (parity, steady span, demotions, completed_frac) gates
    # BENCH_GATE=1 runs; committed history only trends the steady-p99
    # ceiling
    detail["sustained_load"] = {
        k: v
        for k, v in (latest["detail"].get("sustained_load") or {}).items()
        if k not in ("placements_match", "steady_windows", "demotions",
                     "completed_frac")}
    failures = northstar_gate(detail, path=path)
    try:
        with open(path) as f:
            gate = json.load(f).get("gate") or {}
    except (OSError, ValueError):
        gate = {}
    evaluated = [k for k, ref in gate.items()
                 if _gate_path(detail, ref.get("path", k)) is not None]
    coverage = (f"NORTHSTAR gate on {latest['round']}: "
                f"{len(evaluated)}/{len(gate)} entries evaluated"
                + ("" if evaluated or not gate else
                   " — gate NOT exercised (round carries no gated "
                   "metrics; floors/ceilings bite on BENCH_GATE=1 "
                   "live runs)"))
    return [f"{latest['round']}: {f}" for f in failures], coverage


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="benchtrend",
        description="per-case trend table + regression attribution over "
                    "the committed bench JSON trajectory")
    ap.add_argument("--glob", default="BENCH_r*.json,MULTICHIP_r*.json",
                    help="comma-separated globs, resolved in the repo "
                         "root (default: the committed round captures)")
    ap.add_argument("--run", default=None,
                    help="a fresh BENCH_OUT-format JSON appended as the "
                         "newest round")
    ap.add_argument("--threshold", type=float, default=0.1,
                    help="relative regression that triggers attribution "
                         "(default 0.1)")
    ap.add_argument("--check", action="store_true",
                    help="CI mode: nonzero exit on schema-incompatible "
                         "artifacts or NORTHSTAR-gate regressions")
    args = ap.parse_args(argv)

    paths: List[str] = []
    for pat in args.glob.split(","):
        pat = pat.strip()
        if not pat:
            continue
        hits = globmod.glob(os.path.join(REPO_ROOT, pat)) or \
            globmod.glob(pat)
        paths.extend(sorted(hits))
    rounds = [load_round(p) for p in paths]
    if args.run:
        rounds.append(load_round(args.run))

    skipped = [r for r in rounds if r["detail"] is None]
    for r in skipped:
        print(f"note: {r['round']}: {r['note']}")
    if not any(r["detail"] is not None for r in rounds):
        # empty (or fully unparseable) trajectory: degrade gracefully —
        # an empty repo history is a state, not an error.  --check still
        # validates the NORTHSTAR gate schema so the floors/ceilings
        # file can't rot while there are no rounds to trend.
        print("no trajectory (no parseable BENCH_r*/MULTICHIP_r* rounds"
              " committed yet)")
        if args.check:
            errs = validate_northstar(os.path.join(REPO_ROOT,
                                                   "NORTHSTAR.json"))
            for e in errs:
                print("schema error: " + e)
            if errs:
                return 1
            print("benchtrend --check: PASS (no trajectory; NORTHSTAR "
                  "gate schema ok)")
        return 0

    lines, attributions, errors = build_trend(rounds, args.threshold)
    print("\n".join(lines))
    if attributions:
        print()
        print("regressions (beyond %.0f%%):" % (100 * args.threshold))
        for a in attributions:
            print("  " + a)
    gate_failures, gate_coverage = northstar_check(rounds)
    if gate_coverage:
        print()
        print(gate_coverage)
    for f in gate_failures:
        print("  " + f)
    if args.check:
        # the gate file's own schema is part of the contract even when
        # every round parsed (same check the empty-trajectory path runs)
        errors = errors + validate_northstar(
            os.path.join(REPO_ROOT, "NORTHSTAR.json"))
        for e in errors:
            print("schema error: " + e)
        if errors or gate_failures:
            return 1
        print("benchtrend --check: PASS "
              f"({sum(1 for r in rounds if r['detail'] is not None)} "
              f"rounds, {len(skipped)} unparseable skipped)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
