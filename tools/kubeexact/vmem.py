"""Static VMEM budget for the Pallas megakernel.

The kernel's whole working set per grid step is knowable statically: the
BlockSpec'd input/output blocks (double-buffered by the Mosaic pipeline —
the next step's blocks stream in while the current step computes) plus
the VMEM scratch accumulators (resident across the whole grid, counted
once).  ops/pallas_kernels.kernel_buffers() is the single source of truth
for both the traced pallas_call and this budget, so the gate cannot
drift from the program.

The budget is evaluated at the north-star layout — pod tile TB, node
tile TN at their 128-lane caps, R/Z at their committed ceilings, the
scratch rows spanning the full padded auction window — and gated against
the v5e per-core VMEM capacity.  No jax imports: the committed numbers
re-validate under ``--check`` without jax.
"""

from __future__ import annotations

from typing import List

from .northstar import VMEM_CAPACITY_BYTES

_ITEMSIZE = {"bool": 1, "int8": 1, "bfloat16": 2, "float16": 2,
             "float32": 4, "int32": 4, "uint32": 4}

# in/out blocks are double-buffered by the pipeline; scratch is resident
_PIPELINE_COPIES = {"in": 2, "out": 2, "scratch": 1}


def budget(buffers: List[dict], capacity: int = VMEM_CAPACITY_BYTES) -> dict:
    """``buffers``: rows with name/kind/shape/dtype (kernel_buffers() Bufs
    or their manifest dicts).  Returns the per-buffer and total byte
    ledger plus the fits-in-VMEM verdict."""
    per = []
    total = 0
    for b in buffers:
        name = b["name"] if isinstance(b, dict) else b.name
        kind = b["kind"] if isinstance(b, dict) else b.kind
        shape = b["shape"] if isinstance(b, dict) else b.shape
        dtype = b["dtype"] if isinstance(b, dict) else b.dtype
        n = 1
        for d in shape:
            n *= int(d)
        copies = _PIPELINE_COPIES.get(kind, 1)
        nbytes = n * _ITEMSIZE.get(dtype, 4) * copies
        per.append({"name": name, "kind": kind,
                    "shape": [int(d) for d in shape], "dtype": dtype,
                    "copies": copies, "bytes": nbytes})
        total += nbytes
    return {
        "buffers": per,
        "total_bytes": total,
        "capacity_bytes": int(capacity),
        "utilization": round(total / float(capacity), 4),
        "fits": total <= capacity,
    }
