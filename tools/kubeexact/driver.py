"""kubeexact driver: per-entry proving, judging, and exemption audit.

For every registry entry with ``exact=True`` this module

  1. traces the program at its largest ladder rung (the probe rung) and
     runs the exactness lattice (absint.Interp) over the jaxpr, seeding
     input facts the builders guarantee (entry.exact_facts);
  2. judges every recorded cross-shard/cross-tile reduction against the
     committed north-star environment: float max/min and integer-dtype
     sums are exact by construction; float sums must be integer-valued
     with a finite symbolic bound that evaluates below 2**24;
  3. walks the collective surface at every ladder rung (operand bytes
     per rung — the DCN cost attribution kubecensus joins);
  4. computes the static VMEM budget for Pallas entries from the
     kernel's own buffer table evaluated at the north-star layout;
  5. applies the entry's audited (rule, reason) exemptions, flagging
     stale ones exactly like kubecensus.

``prove_callable`` is the public seam the bad-snippet tests drive,
mirroring kubecensus.audit_callable.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from tools.kubecensus.registry import ENTRIES, Entry, Rung, build_world
from tools.kubecensus.rules import Finding

from . import northstar, surface, vmem
from .absint import AbsVal, Interp, Reduction
from .bounds import INT_EXACT_LIMIT, ONE, ZERO, Expr, sym_table


# ---------------------------------------------------------------- facts

def _fact_onehot_rows(aval) -> AbsVal:
    """Rows along the last axis are one-hot: values in {0, 1} and each
    row sums to exactly 1 — a GLOBAL bound (it holds for the full array,
    not just a shard's tile)."""
    from .absint import _dtype_kind
    return AbsVal(tuple(aval.shape), _dtype_kind(aval.dtype), True,
                  ZERO, ONE, lastsum=ONE, lastsum_global=True)


_FACTS = {"onehot_rows": _fact_onehot_rows}


# ---------------------------------------------------------------- tracing

def _flat_call(fn, args, kwargs, static_argnames, static_argnums):
    """(positional-only callable, flat concrete args) via the census
    closure — the SAME seam kubecensus traces through, so the jaxpr the
    prover sees is the jaxpr the compile census commits."""
    from tools.kubecensus import census

    kwargs = kwargs or {}
    dyn_kw, static_kw = census._split_kwargs(kwargs, static_argnames)
    call = census._closure(fn, args, static_argnums, list(dyn_kw),
                           static_kw)
    stat = set(static_argnums)
    flat = [a for i, a in enumerate(args) if i not in stat]
    flat += [dyn_kw[k] for k in dyn_kw]
    return call, tuple(flat)


def _input_absvals(flat_args, jaxpr_invars,
                   facts: Tuple[Tuple[str, str], ...]) -> List[Optional[AbsVal]]:
    """Default every input to TOP; seed fact-matched leaves.  Facts match
    by substring against the leaf's pytree path (keystr), so a fact names
    a builder field (\"zone_hot\"), not a flatten position."""
    import jax

    leaves, _ = jax.tree_util.tree_flatten_with_path(tuple(flat_args))
    invals: List[Optional[AbsVal]] = []
    for (path, leaf), var in zip(leaves, jaxpr_invars):
        v = None
        ps = jax.tree_util.keystr(path)
        for substr, factname in facts:
            if substr in ps and factname in _FACTS:
                v = _FACTS[factname](var.aval)
        invals.append(v)
    return invals


# ---------------------------------------------------------------- judging

def _judge_reduction(red: Reduction, env: Dict[str, float]) -> dict:
    """One manifest proof row for a recorded reduction."""
    row = {
        "op": red.op, "kind": red.kind, "axes": list(red.axes),
        "dtype": red.dtype, "shape": list(red.shape),
        "int_valued": bool(red.int_valued), "note": red.note,
    }
    if red.kind in ("max", "min", "gather", "permute", "all_to_all"):
        row.update(status="exact", why="order-free reduction")
        return row
    if red.int_dtype:
        row.update(status="exact", why="integer dtype (modular, exact in "
                                        "any association order)")
        return row
    # a float sum: needs integer-valuedness + a bound below 2**24
    if not red.int_valued:
        row.update(status="violation", rule="exact/nonexact-psum",
                   why="float sum of values not proven integer-valued — "
                       "association order changes the bits")
        return row
    bound_expr = red.lo.neg().emax(red.hi)
    row["bound"] = bound_expr.render()
    try:
        bound = bound_expr.eval(env)
    except KeyError as e:
        row.update(status="violation", rule="exact/sum-overflow",
                   why="bound references a symbol outside the committed "
                       "north-star environment: %s" % e)
        return row
    row["bound_northstar"] = bound
    if bound >= INT_EXACT_LIMIT:
        row.update(status="violation", rule="exact/sum-overflow",
                   why="integer-valued sum bound %.6g >= 2**24 at the "
                       "north-star shapes — partial sums leave the exact "
                       "f32 integer range" % bound)
        return row
    margin = INT_EXACT_LIMIT / bound if bound > 0 else float("inf")
    row.update(status="exact", margin=round(margin, 4),
               why="integer-valued sum, bound %.6g < 2**24" % bound)
    return row


# ---------------------------------------------------------------- proving

@dataclasses.dataclass
class ProofResult:
    program: str
    proofs: List[dict]
    findings: List[Finding]          # unsuppressed
    suppressed: List[Finding]
    surface: Dict[str, List[dict]]   # rung name -> collective rows
    vmem: Optional[dict] = None
    facts: Tuple[Tuple[str, str], ...] = ()

    @property
    def clean(self) -> bool:
        return not self.findings


def prove_callable(program: str, fn, args: tuple, kwargs: dict = None,
                   static_argnames: Tuple[str, ...] = (),
                   static_argnums: Tuple[int, ...] = (),
                   facts: Tuple[Tuple[str, str], ...] = (),
                   grid_syms: Tuple[str, ...] = (),
                   sizes: Optional[Dict[str, int]] = None,
                   env: Optional[Dict[str, float]] = None,
                   ) -> Tuple[List[dict], List[Finding]]:
    """Prove one callable at one concrete input signature.  Returns
    (proof rows, findings) with NO exemptions applied — the public seam
    the bad-snippet tests drive."""
    import jax

    closed = None
    call, flat = _flat_call(fn, args, kwargs, static_argnames,
                            static_argnums)
    closed = jax.make_jaxpr(call)(*flat)
    invals = _input_absvals(flat, closed.jaxpr.invars, tuple(facts))
    gs = {i: Expr.sym(name)
          for i, name in enumerate(grid_syms) if name}
    interp = Interp(sym_table({k: int(v) for k, v in (sizes or {}).items()}),
                    grid_syms=gs, program=program)
    interp.run(closed, invals)
    env = dict(northstar.NORTHSTAR_ENV if env is None else env)
    proofs: List[dict] = []
    findings: List[Finding] = list(interp.findings)
    for red in interp.reductions:
        row = _judge_reduction(red, env)
        proofs.append(row)
        if row["status"] == "violation":
            findings.append(Finding(
                rule=row["rule"], program=program,
                message="%s %s %s %s: %s" % (
                    red.op, red.kind, "x".join(map(str, red.shape)),
                    red.dtype, row["why"])))
    return proofs, findings


def _entry_sizes(w) -> Dict[str, int]:
    return {"B": w.B, "N": w.N, "P": w.P, "R": w.R,
            "Z": int(w.cluster.zone_hot.shape[-1])}


def _entry_vmem(entry: Entry, w) -> Optional[dict]:
    """North-star VMEM budget for a Pallas entry, from the kernel's own
    buffer table evaluated at the committed deployment layout."""
    if not entry.exact_grid_syms:
        return None
    from kubetpu.ops.pallas_kernels import _layout, kernel_buffers

    ns = northstar.NORTHSTAR_ENV
    W, N = int(ns["B"]), int(ns["N"])
    # has_bias=True is the worst case (one more score plane resident);
    # the ports vocabulary is workload- not scale-bound, so the probe
    # world's bucket is the committed parameter (recorded in the row)
    ports = int(w.cluster.ports.shape[1])
    L = _layout(w.cfg, True, W=W, N=N, R=int(ns["R"]),
                P=ports, Z=int(ns["Z"]))
    WB = -(-W // L.TB)
    bufs = kernel_buffers(L, WB)
    out = vmem.budget(list(bufs))
    out["params"] = {"W": W, "N": N, "R": int(ns["R"]),
                     "Z": int(ns["Z"]), "ports": ports,
                     "TB": L.TB, "TN": L.TN, "WB": WB, "NT": L.NT,
                     "n_stats": L.n_stats, "planes": len(L.planes)}
    return out


def prove_entry(entry: Entry) -> ProofResult:
    """Prove one registry entry at its largest ladder rung, census the
    collective surface at every rung, and apply its audited exemptions."""
    import jax

    rung = entry.ladder[-1]
    w = build_world(rung)
    fn, args, kwargs = entry.build(w)
    proofs, raw = prove_callable(
        entry.key, fn, args, kwargs,
        static_argnames=entry.static_argnames,
        static_argnums=entry.static_argnums,
        facts=entry.exact_facts,
        grid_syms=entry.exact_grid_syms,
        sizes=_entry_sizes(w))

    surf: Dict[str, List[dict]] = {}
    for r in entry.ladder:
        wr = build_world(r)
        fr, ar, kr = entry.build(wr)
        call, flat = _flat_call(fr, ar, kr, entry.static_argnames,
                                entry.static_argnums)
        surf[r.name] = surface.collect_collectives(
            jax.make_jaxpr(call)(*flat))

    exempt = dict(entry.exact_exempt)
    used = set()
    findings: List[Finding] = []
    suppressed: List[Finding] = []
    for f in raw:
        reason = exempt.get(f.rule, "")
        if reason:
            f.suppressed, f.reason = True, reason
            used.add(f.rule)
            suppressed.append(f)
        else:
            findings.append(f)
    for p in proofs:
        if p["status"] == "violation" and p.get("rule") in exempt:
            p["status"] = "exempt"
            p["reason"] = exempt[p["rule"]]
    for rule, reason in exempt.items():
        if rule not in used:
            findings.append(Finding(
                "exact/unused-exemption", entry.key,
                "exemption for %s matches no finding — remove the stale "
                "entry (reason was: %s)" % (rule, reason)))
    return ProofResult(program=entry.key, proofs=proofs,
                       findings=findings, suppressed=suppressed,
                       surface=surf, vmem=_entry_vmem(entry, w),
                       facts=entry.exact_facts)


# ---------------------------------------------------------------- headroom

def headroom(results: List[ProofResult]) -> Tuple[dict, List[Finding]]:
    """The committed 2**24 margin: the minimum across every proved float
    sum, with the dominating term named.  Margin below the floor is a
    finding — the gate that keeps \"grow the deployment target\" an
    explicit reviewed change."""
    min_margin = float("inf")
    dominating = ""
    for r in results:
        for p in r.proofs:
            m = p.get("margin")
            if m is not None and m < min_margin:
                min_margin = m
                dominating = "%s: %s %s bound %s = %.6g" % (
                    r.program, p["op"], p["kind"], p.get("bound", "?"),
                    p.get("bound_northstar", float("nan")))
    row = {
        "floor": northstar.MARGIN_FLOOR,
        "min_margin": (None if min_margin == float("inf")
                       else round(min_margin, 4)),
        "dominating": dominating,
        "int_exact_limit": INT_EXACT_LIMIT,
    }
    findings: List[Finding] = []
    if min_margin != float("inf") and min_margin < northstar.MARGIN_FLOOR:
        findings.append(Finding(
            "exact/headroom", "<northstar>",
            "minimum 2**24 margin %.4gx is below the %gx floor — "
            "dominating term: %s" % (min_margin, northstar.MARGIN_FLOOR,
                                     dominating)))
    return row, findings


# ---------------------------------------------------------------- running

@dataclasses.dataclass
class ExactResult:
    results: List[ProofResult]
    headroom: dict
    findings: List[Finding]          # global, unsuppressed (incl. headroom)
    suppressed: List[Finding]

    @property
    def clean(self) -> bool:
        return not self.findings


def exact_entries(entries: Optional[List[Entry]] = None) -> List[Entry]:
    return [e for e in (ENTRIES if entries is None else entries)
            if e.exact]


def run_exact(entries: Optional[List[Entry]] = None) -> ExactResult:
    results = [prove_entry(e) for e in exact_entries(entries)]
    hr, hr_findings = headroom(results)
    findings: List[Finding] = list(hr_findings)
    suppressed: List[Finding] = []
    for r in results:
        findings.extend(r.findings)
        suppressed.extend(r.suppressed)
        if r.vmem is not None and not r.vmem["fits"]:
            findings.append(Finding(
                "exact/vmem-over-budget", r.program,
                "static VMEM budget %d bytes exceeds the %d-byte v5e "
                "capacity at the north-star layout" % (
                    r.vmem["total_bytes"], r.vmem["capacity_bytes"])))
    return ExactResult(results=results, headroom=hr, findings=findings,
                       suppressed=suppressed)
