"""EXACT_MANIFEST.json: serialization, drift diffing, and the pure-JSON
re-validation the no-jax CI gate runs.

The committed manifest is the version-controlled exactness surface —
every proved reduction with its symbolic bound and north-star margin,
the collective surface (operand bytes per ladder rung), the static VMEM
budget, and the committed environment the bounds were evaluated under.
Two consumers:

* CI (``python -m tools.kubeexact``): re-proves the registry and fails
  on drift in either direction — a program or reduction absent from the
  committed file (exactness surface grew silently) or a committed row no
  trace reproduces (dead entry).  Mirrors COMPILE_MANIFEST.json.
* CI without jax (``python -m tools.kubeexact --check``): re-validates
  the committed file alone — margins above the floor, every proof
  exact/exempt, VMEM totals re-derived from the committed buffer rows,
  the environment byte-equal to tools/kubeexact/northstar.py, and every
  program key present in COMPILE_MANIFEST.json (the exactness surface
  cannot name a program the compile census does not license).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

from . import northstar, vmem

MANIFEST_PATH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "EXACT_MANIFEST.json")

_COMMENT = ("Exactness census (tools/kubeexact). Regenerate: make exact "
            "(python -m tools.kubeexact --write). CI fails on drift in "
            "either direction; --check re-validates this file without jax.")


def build_manifest(res) -> dict:
    """ExactResult -> the committed document (plain JSON types only)."""
    programs: Dict[str, dict] = {}
    for r in res.results:
        programs[r.program] = {
            "facts": [list(f) for f in r.facts],
            "exemptions": [list(t) for t in sorted(
                {(f.rule, f.reason or "") for f in r.suppressed})],
            "proofs": r.proofs,
            "surface": r.surface,
            "vmem": r.vmem,
        }
    return {
        "_comment": _COMMENT,
        "int_exact_limit": northstar.INT_EXACT_LIMIT,
        "margin_floor": northstar.MARGIN_FLOOR,
        "vmem_capacity_bytes": northstar.VMEM_CAPACITY_BYTES,
        "northstar_env": dict(northstar.NORTHSTAR_ENV),
        "headroom": res.headroom,
        "programs": programs,
    }


def write_manifest(doc: dict, path: str = None) -> str:
    """Deterministic serialization: sorted keys, fixed indent, trailing
    newline — regeneration over an unchanged tree is byte-identical."""
    path = path or MANIFEST_PATH
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    return path


def load_manifest(path: str = None) -> Optional[dict]:
    path = path or MANIFEST_PATH
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def diff_manifest(current: dict,
                  committed: Optional[dict]) -> Dict[str, list]:
    """Two-directional drift over program keys plus watched-content
    changes: added (proved, not committed), removed (committed, not
    reproduced), changed (same program, different proofs/surface/vmem/
    facts/exemptions — or the committed environment itself moved)."""
    if committed is None:
        return {"added": sorted(current.get("programs", {})),
                "removed": [], "changed": [], "missing_manifest": True}
    cur = current.get("programs", {})
    com = committed.get("programs", {})
    added = sorted(set(cur) - set(com))
    removed = sorted(set(com) - set(cur))
    changed = []
    for key in ("int_exact_limit", "margin_floor", "vmem_capacity_bytes",
                "northstar_env", "headroom"):
        if current.get(key) != committed.get(key):
            changed.append("<%s>" % key)
    watched = ("facts", "exemptions", "proofs", "surface", "vmem")
    for k in sorted(set(cur) & set(com)):
        for w in watched:
            if cur[k].get(w) != com[k].get(w):
                changed.append("%s (%s)" % (k, w))
                break
    return {"added": added, "removed": removed, "changed": changed}


# ---------------------------------------------------------------- --check

_OK_STATUS = ("exact", "exempt")


def check_manifest(doc: Optional[dict],
                   census_path: str = None) -> List[str]:
    """Pure-JSON re-validation of the committed manifest (no jax).
    Returns failure strings; empty means the gate is green."""
    fails: List[str] = []
    if doc is None:
        return ["no committed EXACT_MANIFEST.json — run --write"]
    if doc.get("int_exact_limit") != northstar.INT_EXACT_LIMIT:
        fails.append("int_exact_limit %r != committed constant %r"
                     % (doc.get("int_exact_limit"),
                        northstar.INT_EXACT_LIMIT))
    if doc.get("margin_floor") != northstar.MARGIN_FLOOR:
        fails.append("margin_floor %r != northstar.MARGIN_FLOOR %r"
                     % (doc.get("margin_floor"), northstar.MARGIN_FLOOR))
    if doc.get("vmem_capacity_bytes") != northstar.VMEM_CAPACITY_BYTES:
        fails.append("vmem_capacity_bytes %r != northstar constant %r"
                     % (doc.get("vmem_capacity_bytes"),
                        northstar.VMEM_CAPACITY_BYTES))
    if doc.get("northstar_env") != northstar.NORTHSTAR_ENV:
        fails.append("northstar_env drifted from tools/kubeexact/"
                     "northstar.py — regenerate with --write")
    hr = doc.get("headroom") or {}
    mm = hr.get("min_margin")
    if mm is not None and mm < northstar.MARGIN_FLOOR:
        fails.append("headroom min_margin %.4g below the %gx floor (%s)"
                     % (mm, northstar.MARGIN_FLOOR,
                        hr.get("dominating", "?")))
    for key, prog in sorted((doc.get("programs") or {}).items()):
        for p in prog.get("proofs", []):
            if p.get("status") not in _OK_STATUS:
                fails.append("%s: proof %s %s is %r, not exact/exempt"
                             % (key, p.get("op"), p.get("kind"),
                                p.get("status")))
            m = p.get("margin")
            if m is not None and m < northstar.MARGIN_FLOOR:
                fails.append("%s: margin %.4gx below the %gx floor"
                             % (key, m, northstar.MARGIN_FLOOR))
        vm = prog.get("vmem")
        if vm is not None:
            re_vm = vmem.budget(vm.get("buffers", []),
                                doc.get("vmem_capacity_bytes",
                                        northstar.VMEM_CAPACITY_BYTES))
            if re_vm["total_bytes"] != vm.get("total_bytes"):
                fails.append("%s: committed VMEM total %r != %d re-derived "
                             "from the committed buffer rows"
                             % (key, vm.get("total_bytes"),
                                re_vm["total_bytes"]))
            if not vm.get("fits"):
                fails.append("%s: committed VMEM budget does not fit "
                             "capacity" % key)
    fails.extend(_check_census_join(doc, census_path))
    return fails


def _check_census_join(doc: dict, census_path: str = None) -> List[str]:
    """Every exactness program must be a program the compile census
    licenses (same key space COMPILE_MANIFEST.json rows use)."""
    from tools.kubecensus.manifest import MANIFEST_PATH as CENSUS_PATH
    path = census_path or CENSUS_PATH
    try:
        with open(path) as f:
            rows = json.load(f)["rows"]
    except (OSError, ValueError, KeyError):
        return ["cannot read COMPILE_MANIFEST.json at %s" % path]
    census_keys = {r["program"] + (":" + r["tag"] if r.get("tag") else "")
                   for r in rows}
    return ["%s: not a COMPILE_MANIFEST program — exactness surface "
            "names an unlicensed root" % k
            for k in sorted(set(doc.get("programs") or {}) - census_keys)]
