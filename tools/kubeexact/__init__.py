"""kubeexact: a jaxpr-level exactness prover + collective/VMEM surface
census for the mesh/Pallas roots in the kubecensus registry.

The bit-match contract of the hottest reductions in the system — "gumbel
decomposition, integer-exact cross-tile sums, first-index argmax" — is
enforced at runtime only by bit-match oracles that need a drained world to
fire.  kubeexact proves the discipline statically, per traced jaxpr:

  * every cross-shard (``psum``/``pmax``/``pmin``) and cross-tile (Pallas
    grid-accumulator fold) float reduction is either a float max/min
    (exactly associative) or an integer-valued sum whose value-range bound
    stays below 2**24 — proven by an integer-valuedness + interval lattice
    (absint.py) propagated from input avals and registry-declared input
    facts, with symbolic bounds evaluated at north-star shapes
    (northstar.py);
  * the collective surface (op, axis names, dtype, reduce kind, operand
    bytes per pow2-ladder rung) is a committed, drift-gated artifact
    (EXACT_MANIFEST.json) exactly like COMPILE_MANIFEST.json;
  * cross-shard row-gathers inside shard_map bodies and raw tie-broken
    argmax (no gumbel decomposition) are findings, with audited
    ``(rule, reason)`` exemptions on registry entries and stale exemptions
    flagged like kubecensus;
  * the Pallas kernel's static VMEM budget (BlockSpecs + scratch, as a
    function of pod_tile/node_tile/R/P/Z) is gated against v5e VMEM
    capacity (vmem.py) — the pre-flight check in-kernel residency work
    must pass before it is ever traced.
"""

from .bounds import Expr, INT_EXACT_LIMIT  # noqa: F401
