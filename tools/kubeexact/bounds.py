"""Symbolic bound expressions for the exactness prover.

The prover traces each program once, at a small probe rung, but the
invariant it certifies ("this f32 psum stays integer-exact") must hold at
the north-star deployment shape.  So interval endpoints are not numbers:
they are tiny closed-form expressions over named dimension symbols
(``P`` = padded existing-pod capacity, ``N`` = padded node slots, ...)
plus mesh/grid fan-in symbols, built structurally during abstract
interpretation and evaluated twice — once at the probe rung (sanity) and
once at the committed north-star environment (the headroom audit).

Design constraints:

  * expressions are immutable and *structurally deterministic*: the
    rendered string is committed into EXACT_MANIFEST.json and must be
    byte-identical across regenerations;
  * a probe rung can alias two logical dims to the same size (existing
    pods are one-per-node, so P and N pad to the same bucket at small
    rungs).  A symbol therefore carries a *tuple* of candidate dim names
    and evaluates to the max over them — a sound upper bound whichever
    dim the size actually was;
  * only the operations the lattice needs exist: +, *, max, min, consts
    and infinity.  Constant folding keeps the committed strings short.
"""

from __future__ import annotations

import math
from typing import Dict, Tuple

# Float sums of integer-valued terms are exact (any association order) as
# long as every partial sum is representable: |sum| < 2**24 for f32.
INT_EXACT_LIMIT = float(2 ** 24)

INF = math.inf


class Expr:
    """An immutable bound expression: ("const", v) | ("sym", names) |
    ("add"|"mul"|"max"|"min", a, b)."""

    __slots__ = ("node",)

    def __init__(self, node: tuple):
        self.node = node

    # ---- constructors -------------------------------------------------
    @staticmethod
    def const(v: float) -> "Expr":
        return Expr(("const", float(v)))

    @staticmethod
    def sym(names) -> "Expr":
        if isinstance(names, str):
            names = (names,)
        return Expr(("sym", tuple(names)))

    # ---- algebra (constant-folding, identity-pruning) -----------------
    def _const(self):
        return self.node[1] if self.node[0] == "const" else None

    def __add__(self, o: "Expr") -> "Expr":
        a, b = self._const(), o._const()
        if a is not None and b is not None:
            return Expr.const(a + b)
        if a == 0.0:
            return o
        if b == 0.0:
            return self
        if a == INF or b == INF:
            return Expr.const(INF)
        return Expr(("add", self.node, o.node))

    def __mul__(self, o: "Expr") -> "Expr":
        a, b = self._const(), o._const()
        # 0 * x == 0 even against infinity: bounds multiply counts of
        # nonnegative terms, never indeterminate forms
        if a == 0.0 or b == 0.0:
            return Expr.const(0.0)
        if a is not None and b is not None:
            return Expr.const(a * b)
        if a == 1.0:
            return o
        if b == 1.0:
            return self
        if a == INF or b == INF:
            return Expr.const(INF)
        return Expr(("mul", self.node, o.node))

    def emax(self, o: "Expr") -> "Expr":
        a, b = self._const(), o._const()
        if a is not None and b is not None:
            return Expr.const(max(a, b))
        if a == INF or b == INF:
            return Expr.const(INF)
        if a == -INF:
            return o
        if b == -INF:
            return self
        if self.node == o.node:
            return self
        return Expr(("max", self.node, o.node))

    def emin(self, o: "Expr") -> "Expr":
        a, b = self._const(), o._const()
        if a is not None and b is not None:
            return Expr.const(min(a, b))
        if a == INF:
            return o
        if b == INF:
            return self
        if self.node == o.node:
            return self
        return Expr(("min", self.node, o.node))

    def neg(self) -> "Expr":
        return Expr.const(-1.0) * self

    # ---- evaluation ---------------------------------------------------
    def eval(self, env: Dict[str, float]) -> float:
        return _eval(self.node, env)

    @property
    def is_finite(self) -> bool:
        """Finite under an all-finite environment (no INF constants)."""
        return _finite(self.node)

    # ---- rendering (committed; must be deterministic) -----------------
    def render(self) -> str:
        return _render(self.node)

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return "Expr(%s)" % self.render()

    def __eq__(self, o) -> bool:
        return isinstance(o, Expr) and self.node == o.node

    def __hash__(self) -> int:
        return hash(self.node)


ZERO = Expr.const(0.0)
ONE = Expr.const(1.0)
TOP = Expr.const(INF)
BOT = Expr.const(-INF)


def _eval(node: tuple, env: Dict[str, float]) -> float:
    kind = node[0]
    if kind == "const":
        return node[1]
    if kind == "sym":
        missing = [n for n in node[1] if n not in env]
        if missing:
            raise KeyError("bound symbol(s) %s not in environment %s"
                           % (missing, sorted(env)))
        return max(env[n] for n in node[1])
    a, b = _eval(node[1], env), _eval(node[2], env)
    if kind == "add":
        return a + b
    if kind == "mul":
        if a == 0.0 or b == 0.0:
            return 0.0
        return a * b
    if kind == "max":
        return max(a, b)
    if kind == "min":
        return min(a, b)
    raise ValueError("unknown Expr node %r" % (node,))


def _finite(node: tuple) -> bool:
    kind = node[0]
    if kind == "const":
        return math.isfinite(node[1])
    if kind == "sym":
        return True
    return _finite(node[1]) and _finite(node[2])


def _fmt_const(v: float) -> str:
    if v == INF:
        return "inf"
    if v == -INF:
        return "-inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def _render(node: tuple) -> str:
    kind = node[0]
    if kind == "const":
        return _fmt_const(node[1])
    if kind == "sym":
        names = node[1]
        return names[0] if len(names) == 1 else "max(%s)" % "|".join(names)
    a, b = _render(node[1]), _render(node[2])
    if kind == "add":
        return "(%s + %s)" % (a, b)
    if kind == "mul":
        return "%s*%s" % (a, b)
    return "%s(%s, %s)" % (kind, a, b)


def sym_table(sizes: Dict[str, int]) -> Dict[int, Tuple[str, ...]]:
    """size -> tuple of candidate dim names.  Small probe rungs alias
    dims (P == N when existing pods are one-per-node); the aliased symbol
    evaluates to the max over its candidates, which upper-bounds whichever
    dim the size really was."""
    table: Dict[int, list] = {}
    for name in sorted(sizes):
        table.setdefault(int(sizes[name]), []).append(name)
    return {k: tuple(v) for k, v in table.items()}
