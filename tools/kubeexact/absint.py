"""The exactness lattice: an abstract interpreter over jaxprs.

Proves the exact-reduction invariant for every cross-shard collective and
cross-tile Pallas accumulator in a traced program: a float reduction is
exact iff it is a max/min (exactly associative in IEEE754) or a sum of
integer-valued terms whose value-range bound stays below 2**24 (f32
integers are exact up to that magnitude, so any association order yields
the same bits).

Each jaxpr variable carries an ``AbsVal``:

  int_valued   the value is mathematically an integer (bools and int
               dtypes trivially; float values via the transfer rules —
               comparisons, floor, products/sums of integer-valued terms)
  lo/hi        symbolic interval endpoints (bounds.Expr) over named dim
               symbols, so one probe-rung trace yields bounds evaluable
               at the north-star shape
  lastsum      per-row bound on the sum over the LAST axis — the load-
               bearing component: a plain interval bounds the DPS zone
               count by N*P (hopeless), while "each pod lands on exactly
               one node" gives row sums <= P via the one-hot dot rule
  lastsum_global  True when the bound was derived OUTSIDE the shard_map /
               Pallas body, i.e. it bounds the GLOBAL row sum; summing a
               value across disjoint shards/tiles is then bounded by the
               single global bound instead of shards x local
  random       PRNG taint (threefry/random_bits and everything computed
               from them) — the gumbel-decomposition witness for the
               tie-broken argmax rule
  iota/varies  enough structure to recognize ``x[:, None] == iota`` as a
               one-hot row pattern (lastsum == hi) without special-casing
               the helper that builds it
  parts        per-slice components of a ``concatenate`` (jnp.stack of
               score planes), so a static plane index recovers the
               plane's own facts — the gumbel plane stays distinguishable
               from the integer count planes it is stacked with
  sharded      dim -> mesh-axis (from shard_map in_names) or grid-axis
               (from Pallas BlockSpec index maps) tiling marks
  tile_total   "summing this value over all tiles of axis k is <= Expr":
               produced when a dot contracts a tiled dim using a global
               lastsum, consumed by psum / grid-fold bounds

Unknown primitives default to TOP (sound; precision recovers at the next
comparison, which is bool-valued regardless of its inputs).  While-loop
carries are widened to a field-wise post-fixpoint (see _stabilize): each
fact survives only if the body re-establishes it every round, so the
score-plane bundle keeps its PRNG taint and per-plane facts across the
auction round loop.  Scan carries are widened to TOP in one shot.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Tuple

from tools.kubecensus.rules import Finding

from .bounds import BOT, INF, ONE, TOP, ZERO, Expr

__all__ = ["AbsVal", "Interp", "Reduction", "Finding", "COLLECTIVES"]

COLLECTIVES = ("psum", "pmax", "pmin", "all_gather", "ppermute",
               "all_to_all", "reduce_scatter")

_REDUCE_KIND = {"psum": "sum", "pmax": "max", "pmin": "min",
                "all_gather": "gather", "ppermute": "permute",
                "all_to_all": "all_to_all", "reduce_scatter": "sum"}


def _dtype_kind(dtype) -> str:
    name = getattr(dtype, "name", str(dtype))
    if name.startswith("bool"):
        return "bool"
    if name.startswith(("int", "uint")):
        return "int"
    if name.startswith(("float", "bfloat")):
        return "float"
    return "other"


@dataclasses.dataclass
class AbsVal:
    shape: Tuple[int, ...]
    kind: str                  # "bool" | "int" | "float" | "other"
    int_valued: bool
    lo: Expr
    hi: Expr
    lastsum: Optional[Expr] = None
    lastsum_global: bool = False
    random: bool = False
    iota_dim: Optional[int] = None
    varies: Optional[frozenset] = None      # None = may vary everywhere
    parts: Optional[Tuple[Tuple[int, int, "AbsVal"], ...]] = None
    parts_axis: int = 0
    sharded: Optional[Dict[object, int]] = None   # key -> dim
    tile_total: Optional[Dict[object, Tuple[Expr, bool]]] = None
    pid_deps: frozenset = frozenset()       # grid axes (linear deps only)
    pin: Optional[Tuple[int, int]] = None   # value==1 <=> program_id(g)==c
    origin: Optional[tuple] = None          # ("get", ref_id)

    # ---- helpers ------------------------------------------------------
    @property
    def nonneg(self) -> bool:
        c = self.lo._const()
        return c is not None and c >= 0.0

    def varies_on(self, dim: int) -> bool:
        return self.varies is None or dim in self.varies

    def replace(self, **kw) -> "AbsVal":
        return dataclasses.replace(self, **kw)

    def drop_structure(self, **kw) -> "AbsVal":
        """Interval/int/random survive; positional structure does not."""
        base = dataclasses.replace(
            self, lastsum=None, lastsum_global=False, iota_dim=None,
            varies=None, parts=None, sharded=None, tile_total=None,
            pid_deps=frozenset(), pin=None, origin=None)
        return dataclasses.replace(base, **kw) if kw else base


def _top(aval) -> AbsVal:
    kind = _dtype_kind(aval.dtype)
    if kind == "bool":
        return AbsVal(tuple(aval.shape), kind, True, ZERO, ONE)
    return AbsVal(tuple(aval.shape), kind, kind == "int", BOT, TOP)


def _is_zero(v: AbsVal) -> bool:
    return (v.varies == frozenset() and v.lo._const() == 0.0
            and v.hi._const() == 0.0)


def _join(a: AbsVal, b: AbsVal, shape=None) -> AbsVal:
    # joining with a constant zero (the ubiquitous where(mask, x, 0))
    # only relaxes lo toward 0 — every structural fact of x survives,
    # including the load-bearing global row-sum bound
    for p, q in ((a, b), (b, a)):
        if _is_zero(q) and not _is_zero(p):
            out = p.replace(
                shape=tuple(shape) if shape is not None else p.shape,
                lo=p.lo.emin(ZERO), parts=None, origin=None)
            if not p.nonneg:
                out.lastsum, out.lastsum_global = None, False
            return out
    nonneg = a.nonneg and b.nonneg
    lastsum = None
    if nonneg and a.lastsum is not None and b.lastsum is not None:
        lastsum = a.lastsum.emax(b.lastsum)
    tt = None
    if a.tile_total and b.tile_total:
        tt = {}
        for k in a.tile_total:
            if k in b.tile_total:
                (ea, ga), (eb, gb) = a.tile_total[k], b.tile_total[k]
                tt[k] = (ea.emax(eb), ga and gb)
        tt = tt or None
    sharded = None
    if a.sharded and b.sharded:
        sharded = {k: d for k, d in a.sharded.items()
                   if b.sharded.get(k) == d} or None
    return AbsVal(
        shape=tuple(shape) if shape is not None else a.shape,
        kind=a.kind if a.kind == b.kind else "other",
        int_valued=a.int_valued and b.int_valued,
        lo=a.lo.emin(b.lo), hi=a.hi.emax(b.hi),
        lastsum=lastsum,
        lastsum_global=(lastsum is not None and a.lastsum_global
                        and b.lastsum_global),
        random=a.random or b.random,
        iota_dim=a.iota_dim if a.iota_dim == b.iota_dim else None,
        varies=(a.varies | b.varies
                if a.varies is not None and b.varies is not None else None),
        sharded=sharded, tile_total=tt,
        pin=a.pin if a.pin == b.pin else None)


def _bool01(shape) -> AbsVal:
    return AbsVal(tuple(shape), "bool", True, ZERO, ONE)


@dataclasses.dataclass
class Reduction:
    """One cross-shard collective or cross-tile accumulator fold."""
    op: str                    # psum | pmax | ... | grid_fold
    kind: str                  # sum | max | min | gather | store | ...
    axes: Tuple[str, ...]      # mesh axis names ("grid" for Pallas folds)
    dtype: str
    shape: Tuple[int, ...]     # operand shape at the probe rung
    int_dtype: bool
    int_valued: bool
    lo: Expr
    hi: Expr
    note: str = ""


@dataclasses.dataclass
class _RefCell:
    val: Optional[AbsVal] = None
    acc_int: bool = True


class Interp:
    """One abstract interpretation of a closed jaxpr.

    ``sizes``: dim-size -> tuple of candidate symbol names (bounds.
    sym_table).  ``grid_syms``: Pallas grid axis -> Expr for its step
    count (the caller knows the kernel's grid layout).  Findings that
    need the north-star environment (sum bounds) are NOT emitted here —
    reductions are recorded with symbolic bounds and judged by the
    driver, where entry exemptions apply."""

    def __init__(self, sizes: Dict[int, Tuple[str, ...]],
                 grid_syms: Optional[Dict[int, Expr]] = None,
                 program: str = ""):
        self.sizes = dict(sizes or {})
        self.grid_syms = dict(grid_syms or {})
        self.program = program
        self.reductions: List[Reduction] = []
        self.findings: List[Finding] = []
        self.in_shardmap = 0
        self.in_kernel = 0
        self.grid: Tuple[int, ...] = ()
        self._pinned: List[frozenset] = []
        self._defs: Dict[object, object] = {}   # Var -> eqn
        self._env_all: Dict[object, AbsVal] = {}  # Var -> last written
        self._refs: Dict[object, _RefCell] = {}  # Var(ref) -> cell

    # ---- symbols ------------------------------------------------------
    def size_expr(self, n: int) -> Expr:
        names = self.sizes.get(int(n))
        return Expr.sym(names) if names else Expr.const(n)

    def mesh_sym(self, axis: str) -> Expr:
        return Expr.sym("MESH:%s" % axis)

    def grid_expr(self, g: int, size: int) -> Expr:
        return self.grid_syms.get(g, Expr.const(size))

    def _outside_body(self) -> bool:
        return self.in_shardmap == 0 and self.in_kernel == 0

    def _finding(self, rule: str, message: str) -> None:
        self.findings.append(Finding(rule=rule, program=self.program,
                                     message=message))

    # ---- entry point --------------------------------------------------
    def run(self, closed_jaxpr, invals: List[AbsVal]) -> List[AbsVal]:
        jaxpr = closed_jaxpr.jaxpr
        consts = [self._literal_val_abs(c) for c in closed_jaxpr.consts]
        return self._frame(jaxpr, consts, invals)

    # ---- frame interpretation -----------------------------------------
    def _frame(self, jaxpr, consts: List[AbsVal],
               invals: List[AbsVal]) -> List[AbsVal]:
        env: Dict[object, AbsVal] = {}

        def write(var, val):
            env[var] = val
            self._env_all[var] = val

        for var, v in zip(jaxpr.constvars, consts):
            write(var, v)
        for var, v in zip(jaxpr.invars, invals):
            write(var, v if v is not None else _top(var.aval))

        def read(atom) -> AbsVal:
            if hasattr(atom, "val"):          # core.Literal
                return self._literal(atom)
            got = env.get(atom)
            return got if got is not None else _top(atom.aval)

        for eqn in jaxpr.eqns:
            ins = [read(a) for a in eqn.invars]
            fn = _TRANSFER.get(eqn.primitive.name)
            if fn is not None:
                outs = fn(self, eqn, ins)
            else:
                outs = self._default(eqn, ins)
            for var, v in zip(eqn.outvars, outs):
                if type(var).__name__ == "DropVar":
                    continue
                write(var, v)
                self._defs[var] = eqn
        return [read(v) for v in jaxpr.outvars]

    # ---- literals / defaults ------------------------------------------
    def _literal(self, lit) -> AbsVal:
        return self._literal_val_abs(lit.val)

    def _literal_val_abs(self, val) -> AbsVal:
        import numpy as np
        try:
            arr = np.asarray(val)
        except Exception:
            return AbsVal((), "other", False, BOT, TOP)
        kind = _dtype_kind(arr.dtype)
        if arr.size == 0 or kind == "other":
            return AbsVal(tuple(arr.shape), kind, kind in ("bool", "int"),
                          BOT, TOP)
        if kind == "bool":
            return _bool01(arr.shape)
        lo, hi = float(arr.min()), float(arr.max())
        if not (np.isfinite(lo) and np.isfinite(hi)):
            return AbsVal(tuple(arr.shape), kind, kind == "int", BOT, TOP)
        int_valued = (kind == "int"
                      or bool(np.all(arr == np.floor(arr))))
        v = AbsVal(tuple(arr.shape), kind, int_valued,
                   Expr.const(lo), Expr.const(hi))
        if arr.ndim == 0 or (lo == hi):
            v.varies = frozenset()
        return v

    def _default(self, eqn, ins: List[AbsVal]) -> List[AbsVal]:
        """Sound fallback: TOP values, union PRNG taint; descend into any
        sub-jaxprs so collectives inside unmodeled primitives are still
        seen (with TOP operands)."""
        rnd = any(v.random for v in ins)
        if eqn.primitive.name.startswith("random_") or \
                eqn.primitive.name.startswith("threefry"):
            rnd = True
        for sub in _sub_jaxprs(eqn.params):
            n = len(sub.jaxpr.invars)
            self.run(sub, [None] * n)
        return [_top(v.aval).replace(random=rnd) for v in eqn.outvars]


# ======================================================================
# transfer functions
# ======================================================================

_TRANSFER: Dict[str, Callable] = {}


def _reg(*names):
    def deco(fn):
        for n in names:
            _TRANSFER[n] = fn
        return fn
    return deco


def _sub_jaxprs(params: dict):
    """Every ClosedJaxpr reachable from an eqn's params (generic)."""
    out = []
    for v in params.values():
        vs = v if isinstance(v, (list, tuple)) else [v]
        for u in vs:
            if hasattr(u, "jaxpr") and hasattr(u, "consts"):
                out.append(u)
    return out


def _mag(v: AbsVal) -> Expr:
    return v.lo.neg().emax(v.hi)


def _taint(ins: List[AbsVal]) -> bool:
    return any(v.random for v in ins)


def _shape(eqn, i=0):
    return tuple(eqn.outvars[i].aval.shape)


def _kind(eqn, i=0):
    return _dtype_kind(eqn.outvars[i].aval.dtype)


# ---- comparisons / logicals: bool01 regardless of inputs --------------

@_reg("lt", "le", "gt", "ge", "ne", "and", "or", "xor", "not",
      "is_finite", "reduce_and", "reduce_or")
def _t_bool(interp, eqn, ins):
    if _kind(eqn) != "bool":
        # and/or/xor/not are bitwise on int dtypes — not 0/1 valued
        v = _top(eqn.outvars[0].aval)
    else:
        v = _bool01(_shape(eqn))
    v.random = _taint(ins)
    return [v]


@_reg("eq")
def _t_eq(interp, eqn, ins):
    v = _bool01(_shape(eqn))
    v.random = _taint(ins)
    a, b = ins
    shape = v.shape
    if shape:
        last = len(shape) - 1
        # x[:, None] == iota  (either side): rows along the last axis hold
        # at most one True -> one-hot row, lastsum == 1.  Global iff
        # derived outside a shard_map/Pallas body (a local iota only
        # enumerates the local tile).
        for p, q in ((a, b), (b, a)):
            if p.iota_dim == last and not q.varies_on(last):
                v.lastsum = ONE
                v.lastsum_global = interp._outside_body()
    # program_id pin: eq(program_id(g), const c) -> value 1 <=> pid==c
    for p, q in ((a, b), (b, a)):
        if len(p.pid_deps) == 1 and q.varies == frozenset() \
                and q.lo == q.hi and q.lo._const() is not None \
                and p.origin == ("pid",):
            v.pin = (next(iter(p.pid_deps)), int(q.lo._const()))
    return [v]


# ---- structure --------------------------------------------------------

@_reg("iota")
def _t_iota(interp, eqn, ins):
    d = eqn.params["dimension"]
    shape = _shape(eqn)
    v = AbsVal(shape, _kind(eqn), True, ZERO,
               Expr.const(max(shape[d] - 1, 0)))
    v.iota_dim = d
    v.varies = frozenset((d,))
    return [v]


@_reg("broadcast_in_dim")
def _t_broadcast(interp, eqn, ins):
    (a,) = ins
    shape = _shape(eqn)
    bdims = tuple(eqn.params["broadcast_dimensions"])
    out = a.replace(shape=shape, parts=None, sharded=None,
                    tile_total=None, origin=None)
    # varies: only images of (possibly-varying) operand dims vary
    src_varies = (a.varies if a.varies is not None
                  else frozenset(range(len(a.shape))))
    out.varies = frozenset(bdims[d] for d in src_varies
                           if d < len(bdims) and a.shape[d] == shape[bdims[d]])
    out.iota_dim = (bdims[a.iota_dim]
                    if a.iota_dim is not None and a.iota_dim < len(bdims)
                    else None)
    last = len(shape) - 1
    if last >= 0:
        if bdims and bdims[-1] == last and len(a.shape) >= 1 \
                and a.shape[-1] == shape[last]:
            pass                                   # last axis preserved
        else:
            # last axis is new/broadcast: row sum = size * value
            out.lastsum = None
            out.lastsum_global = False
    if a.parts is not None and a.parts_axis < len(bdims) \
            and bdims[a.parts_axis] is not None:
        out.parts = a.parts
        out.parts_axis = bdims[a.parts_axis]
    return [out]


@_reg("convert_element_type")
def _t_convert(interp, eqn, ins):
    (a,) = ins
    kind = _kind(eqn)
    out = a.replace(shape=_shape(eqn), kind=kind, origin=None)
    name = eqn.outvars[0].aval.dtype.name
    if kind == "int":
        out.int_valued = True
    elif kind == "float":
        if name == "bfloat16":
            # bf16 has an 8-bit mantissa: integer values stay exact only
            # below 2**8 (the one-hot/mask casts the MXU path feeds)
            hi_c = _mag(a)._const()
            out.int_valued = (a.int_valued and hi_c is not None
                              and hi_c <= 256.0)
        else:
            out.int_valued = a.int_valued
    out.pin = a.pin            # bool -> int32 branch selector keeps pin
    return [out]


@_reg("reshape")
def _t_reshape(interp, eqn, ins):
    (a,) = ins
    shape = _shape(eqn)
    out = a.drop_structure().replace(shape=shape)
    # a row-major reshape that keeps the last-dim size keeps the rows
    # themselves (jnp.stack's expand_dims included) — the row-sum bound
    # survives
    if a.shape and shape and a.shape[-1] == shape[-1] and a.nonneg:
        out.lastsum, out.lastsum_global = a.lastsum, a.lastsum_global
    return [out]


@_reg("transpose")
def _t_transpose(interp, eqn, ins):
    (a,) = ins
    perm = tuple(eqn.params["permutation"])
    shape = _shape(eqn)
    out = a.replace(shape=shape, parts=None, origin=None)
    inv = {old: new for new, old in enumerate(perm)}
    out.iota_dim = inv.get(a.iota_dim) if a.iota_dim is not None else None
    out.varies = (frozenset(inv[d] for d in a.varies)
                  if a.varies is not None else None)
    out.sharded = ({k: inv[d] for k, d in a.sharded.items()}
                   if a.sharded else None)
    if perm and perm[-1] != len(perm) - 1:
        out.lastsum, out.lastsum_global = None, False
    if a.parts is not None:
        out.parts, out.parts_axis = a.parts, inv[a.parts_axis]
    return [out]


@_reg("squeeze")
def _t_squeeze(interp, eqn, ins):
    (a,) = ins
    dims = set(eqn.params["dimensions"])
    shape = _shape(eqn)
    keep = [d for d in range(len(a.shape)) if d not in dims]
    remap = {old: new for new, old in enumerate(keep)}
    out = a.replace(shape=shape, parts=None, origin=None)
    out.iota_dim = remap.get(a.iota_dim) if a.iota_dim is not None else None
    out.varies = (frozenset(remap[d] for d in a.varies if d in remap)
                  if a.varies is not None else None)
    out.sharded = ({k: remap[d] for k, d in a.sharded.items() if d in remap}
                   if a.sharded else None)
    if keep and keep[-1] != len(a.shape) - 1:
        out.lastsum, out.lastsum_global = None, False
    if a.parts is not None and a.parts_axis in remap:
        out.parts, out.parts_axis = a.parts, remap[a.parts_axis]
    return [out]


@_reg("concatenate")
def _t_concat(interp, eqn, ins):
    d = eqn.params["dimension"]
    shape = _shape(eqn)
    out = ins[0]
    for v in ins[1:]:
        out = _join(out, v, shape=shape)
    parts, off = [], 0
    for a, v in zip(eqn.invars, ins):
        n = a.aval.shape[d]
        parts.append((off, off + n, v))
        off += n
    out.parts, out.parts_axis = tuple(parts), d
    if d == len(shape) - 1:
        # concatenating along the last axis adds row sums
        ls = None
        if all(v.nonneg for v in ins):
            ls = ZERO
            for a, v in zip(eqn.invars, ins):
                term = (v.lastsum if v.lastsum is not None
                        else Expr.const(a.aval.shape[d]) * v.hi)
                ls = ls + term
        out.lastsum = ls
        out.lastsum_global = (ls is not None
                              and all(v.lastsum_global or v.lastsum is None
                                      for v in ins)
                              and interp._outside_body())
    return [out]


def _part_lookup(a: AbsVal, axis: int, start: int, stop: int):
    if a.parts is None or a.parts_axis != axis:
        return None
    for p0, p1, v in a.parts:
        if start >= p0 and stop <= p1:
            return v
    return None


@_reg("slice")
def _t_slice(interp, eqn, ins):
    (a,) = ins
    shape = _shape(eqn)
    starts = tuple(eqn.params["start_indices"])
    limits = tuple(eqn.params["limit_indices"])
    hit = None
    if a.parts is not None:
        ax = a.parts_axis
        full_elsewhere = all(
            starts[d] == 0 and limits[d] == a.shape[d]
            for d in range(len(a.shape)) if d != ax)
        if full_elsewhere:
            hit = _part_lookup(a, ax, starts[ax], limits[ax])
    base = hit if hit is not None else a
    out = base.replace(shape=shape, parts=None, origin=None)
    out.iota_dim = None        # offsets shift iota values
    out.varies = None
    if not base.nonneg and len(shape) > 0 \
            and (starts[-1] != 0 or limits[-1] != a.shape[-1]):
        # last-axis subset sums only shrink for nonnegative values
        out.lastsum, out.lastsum_global = None, False
    return [out]


@_reg("dynamic_slice")
def _t_dynslice(interp, eqn, ins):
    a = ins[0]
    shape = _shape(eqn)
    out = a.replace(shape=shape, parts=None, iota_dim=None, varies=None,
                    origin=None)
    if not a.nonneg and len(shape) > 0 and shape[-1] != a.shape[-1]:
        out.lastsum, out.lastsum_global = None, False
    return [out]


@_reg("rev", "sort")
def _t_perm(interp, eqn, ins):
    # permutations along an axis: per-element bounds and (for sort) the
    # axis sum are preserved; positional structure is not
    return [v.drop_structure().replace(
        shape=tuple(o.aval.shape),
        lastsum=v.lastsum if v.nonneg else None,
        lastsum_global=v.lastsum_global if v.nonneg else False,
        random=_taint(ins))
        for v, o in zip(ins[:len(eqn.outvars)], eqn.outvars)]


@_reg("gather")
def _t_gather(interp, eqn, ins):
    a = ins[0]
    shape = _shape(eqn)
    out = a.drop_structure().replace(shape=shape, random=_taint(ins))
    dnums = eqn.params.get("dimension_numbers")
    slice_sizes = eqn.params.get("slice_sizes")
    if dnums is None or slice_sizes is None:
        return [out]
    # operand dims passed through WHOLE (full slice, not collapsed) map
    # to output dims via offset_dims in order.  A row selection on the
    # OTHER dims (jnp.take of live pods out of the plane stack) keeps
    # the plane decomposition and the per-row sums on the full dims —
    # selecting (possibly duplicated) rows never grows a row's own sum.
    collapsed = set(getattr(dnums, "collapsed_slice_dims", ()))
    kept = [d for d in range(len(a.shape)) if d not in collapsed]
    full = {}
    for od, ad in zip(tuple(getattr(dnums, "offset_dims", ())), kept):
        if int(slice_sizes[ad]) == int(a.shape[ad]):
            full[ad] = od
    if a.parts is not None and a.parts_axis in full:
        out.parts, out.parts_axis = a.parts, full[a.parts_axis]
    last_a, last_o = len(a.shape) - 1, len(shape) - 1
    if a.nonneg and a.lastsum is not None and full.get(last_a) == last_o:
        out.lastsum, out.lastsum_global = a.lastsum, a.lastsum_global
    if a.sharded:
        sh = {k: full[d] for k, d in a.sharded.items() if d in full}
        out.sharded = sh or None
    return [out]


@_reg("pad")
def _t_pad(interp, eqn, ins):
    a, pval = ins
    return [_join(a, pval, shape=_shape(eqn)).drop_structure()]


# ---- arithmetic -------------------------------------------------------

def _const_like(v: AbsVal) -> bool:
    return v.varies == frozenset() or v.shape == ()


@_reg("add", "sub")
def _t_addsub(interp, eqn, ins):
    a, b = ins
    sub = eqn.primitive.name == "sub"
    lo = a.lo + (b.hi.neg() if sub else b.lo)
    hi = a.hi + (b.lo.neg() if sub else b.hi)
    out = AbsVal(_shape(eqn), _kind(eqn),
                 a.int_valued and b.int_valued, lo, hi,
                 random=_taint(ins))
    if not sub and a.nonneg and b.nonneg and out.shape:
        la = a.lastsum if a.lastsum is not None else \
            Expr.const(out.shape[-1]) * a.hi
        lb = b.lastsum if b.lastsum is not None else \
            Expr.const(out.shape[-1]) * b.hi
        if a.lastsum is not None or b.lastsum is not None:
            out.lastsum = la + lb
            out.lastsum_global = a.lastsum_global and b.lastsum_global
    # linear-in-program_id tracking for disjoint-slice detection
    if _const_like(b) and a.pid_deps:
        out.pid_deps = a.pid_deps
    elif _const_like(a) and b.pid_deps:
        out.pid_deps = b.pid_deps
    out.sharded = a.sharded if a.sharded else b.sharded
    return [out]


@_reg("mul")
def _t_mul(interp, eqn, ins):
    a, b = ins
    if a.nonneg and b.nonneg:
        lo, hi = ZERO, a.hi * b.hi
    else:
        m = _mag(a) * _mag(b)
        lo, hi = m.neg(), m
    out = AbsVal(_shape(eqn), _kind(eqn),
                 a.int_valued and b.int_valued, lo, hi,
                 random=_taint(ins))
    if a.nonneg and b.nonneg:
        for p, q in ((a, b), (b, a)):
            if p.lastsum is not None and _const_like(q):
                out.lastsum = p.lastsum * q.hi
                out.lastsum_global = p.lastsum_global
                break
    if _const_like(b) and a.pid_deps:
        out.pid_deps = a.pid_deps
    elif _const_like(a) and b.pid_deps:
        out.pid_deps = b.pid_deps
    out.sharded = a.sharded if a.sharded else b.sharded
    return [out]


@_reg("div")
def _t_div(interp, eqn, ins):
    a, b = ins
    out = AbsVal(_shape(eqn), _kind(eqn), _kind(eqn) == "int",
                 BOT, TOP, random=_taint(ins))
    if a.nonneg and b.lo._const() is not None and b.lo._const() >= 1.0:
        out.lo, out.hi = ZERO, a.hi
    return [out]


@_reg("floor", "round", "ceil")
def _t_floor(interp, eqn, ins):
    (a,) = ins
    return [a.drop_structure(random=a.random).replace(
        shape=_shape(eqn), int_valued=True,
        lo=a.lo + Expr.const(-1.0), hi=a.hi + Expr.const(1.0))]


@_reg("neg")
def _t_neg(interp, eqn, ins):
    (a,) = ins
    return [AbsVal(_shape(eqn), _kind(eqn), a.int_valued,
                   a.hi.neg(), a.lo.neg(), random=a.random)]


@_reg("abs")
def _t_abs(interp, eqn, ins):
    (a,) = ins
    return [AbsVal(_shape(eqn), _kind(eqn), a.int_valued, ZERO, _mag(a),
                   random=a.random)]


@_reg("max", "min")
def _t_maxmin(interp, eqn, ins):
    a, b = ins
    mx = eqn.primitive.name == "max"
    lo = a.lo.emax(b.lo) if mx else a.lo.emin(b.lo)
    hi = a.hi.emax(b.hi) if mx else a.hi.emin(b.hi)
    out = AbsVal(_shape(eqn), _kind(eqn),
                 a.int_valued and b.int_valued, lo, hi,
                 random=_taint(ins))
    out.sharded = a.sharded if a.sharded else b.sharded
    return [out]


@_reg("clamp")
def _t_clamp(interp, eqn, ins):
    lo_v, x, hi_v = ins
    return [AbsVal(_shape(eqn), _kind(eqn),
                   x.int_valued and lo_v.int_valued and hi_v.int_valued,
                   x.lo.emax(lo_v.lo), x.hi.emin(hi_v.hi),
                   random=_taint(ins))]


@_reg("select_n")
def _t_select(interp, eqn, ins):
    pred, cases = ins[0], ins[1:]
    out = cases[0]
    for c in cases[1:]:
        out = _join(out, c, shape=_shape(eqn))
    # value taint comes from the selected branches; a random predicate
    # choosing between non-random values does not make them gumbel
    out = out.replace(shape=_shape(eqn), origin=None)
    return [out]


@_reg("sign")
def _t_sign(interp, eqn, ins):
    (a,) = ins
    return [AbsVal(_shape(eqn), _kind(eqn), True, Expr.const(-1.0), ONE,
                   random=a.random)]


@_reg("integer_pow")
def _t_ipow(interp, eqn, ins):
    (a,) = ins
    y = eqn.params["y"]
    int_valued = a.int_valued and y >= 0
    if a.nonneg and y >= 0:
        hi = ONE
        for _ in range(min(int(y), 8)):
            hi = hi * a.hi
        if y > 8:
            hi = TOP
        return [AbsVal(_shape(eqn), _kind(eqn), int_valued, ZERO, hi,
                       random=a.random)]
    return [AbsVal(_shape(eqn), _kind(eqn), int_valued, BOT, TOP,
                   random=a.random)]


@_reg("copy", "stop_gradient", "reduce_precision", "real", "imag",
      "device_put")
def _t_copy(interp, eqn, ins):
    a = ins[0]
    return [a.replace(shape=_shape(eqn), origin=None)]


@_reg("exp", "log", "log1p", "expm1", "tanh", "logistic", "rsqrt",
      "sqrt", "sin", "cos", "erf", "erf_inv", "pow",
      "nextafter", "rem", "shift_right_logical",
      "shift_left", "bitcast_convert_type", "population_count")
def _t_float_misc(interp, eqn, ins):
    outs = []
    for o in eqn.outvars:
        v = _top(o.aval)
        v.random = _taint(ins)
        outs.append(v)
    return outs


# ---- reductions (local) ----------------------------------------------

@_reg("reduce_sum")
def _t_reduce_sum(interp, eqn, ins):
    (a,) = ins
    axes = tuple(eqn.params["axes"])
    count = 1
    for d in axes:
        count *= a.shape[d]
    cexpr = interp.size_expr(count) if len(axes) == 1 else Expr.const(count)
    if a.nonneg:
        hi = cexpr * a.hi
        if axes == (len(a.shape) - 1,) and a.lastsum is not None:
            hi = hi.emin(a.lastsum)
        lo = ZERO
    else:
        hi = cexpr * _mag(a)
        lo = hi.neg()
    out = AbsVal(_shape(eqn), _kind(eqn), a.int_valued, lo, hi,
                 random=a.random)
    # summing over a device-sharded dim: the total across shards is the
    # global sum -> bound for a following psum over that mesh axis
    if a.sharded:
        tt = {}
        for key, dim in a.sharded.items():
            if dim in axes:
                if a.lastsum is not None and a.lastsum_global \
                        and axes == (len(a.shape) - 1,):
                    tt[key] = (a.lastsum, True)
                else:
                    tt[key] = (hi * _axis_fan(interp, key), False)
        if tt:
            out.tile_total = tt
    return [out]


def _axis_fan(interp, key) -> Expr:
    if isinstance(key, tuple) and key and key[0] == "grid":
        return interp.grid_expr(key[1], 0)
    return interp.mesh_sym(key)


@_reg("reduce_max", "reduce_min", "cummax", "cummin", "argsort")
def _t_reduce_minmax(interp, eqn, ins):
    a = ins[0]
    return [a.drop_structure(random=_taint(ins)).replace(
        shape=_shape(eqn),
        sharded=None if eqn.primitive.name.startswith("cum") else None)]


@_reg("cumsum")
def _t_cumsum(interp, eqn, ins):
    (a,) = ins
    d = eqn.params.get("axis", 0)
    n = a.shape[d] if a.shape else 1
    if a.nonneg:
        lo, hi = ZERO, Expr.const(n) * a.hi
    else:
        hi = Expr.const(n) * _mag(a)
        lo = hi.neg()
    return [AbsVal(_shape(eqn), _kind(eqn), a.int_valued, lo, hi,
                   random=a.random)]


@_reg("argmax", "argmin")
def _t_argmax(interp, eqn, ins):
    (a,) = ins
    # the tie-break discipline: a float argmax is deterministic only
    # through the gumbel decomposition (argmax over where(tie, gumbel,
    # -2**62) == categorical); bool/int operands are the blessed
    # first-true-index / counting idioms
    if a.kind == "float" and not a.random:
        interp._finding(
            "exact/raw-tie-argmax",
            "argmax over a float operand with no PRNG taint: tie-broken "
            "selections must route through the gumbel decomposition "
            "(ops/kernels.py gumbel_tiebreak_argmax) so ties replay "
            "selectHost bit-for-bit")
    axes = tuple(eqn.params["axes"])
    hi = max((a.shape[d] for d in axes), default=1)
    return [AbsVal(_shape(eqn), _kind(eqn), True, ZERO,
                   Expr.const(max(hi - 1, 0)))]


@_reg("scatter", "scatter-add", "scatter-max", "scatter-min", "scatter-mul")
def _t_scatter(interp, eqn, ins):
    op, _, upd = ins[0], ins[1], ins[2]
    name = eqn.primitive.name
    int_valued = op.int_valued and upd.int_valued
    if name == "scatter-add":
        n = 1
        for d in upd.shape:
            n *= d
        hi = op.hi + Expr.const(n) * upd.hi.emax(ZERO)
        lo = op.lo + Expr.const(n) * upd.lo.emin(ZERO)
    else:
        j = _join(op, upd, shape=_shape(eqn))
        lo, hi, int_valued = j.lo, j.hi, j.int_valued
    return [AbsVal(_shape(eqn), _kind(eqn), int_valued, lo, hi,
                   random=_taint(ins))]


@_reg("dynamic_update_slice")
def _t_dus(interp, eqn, ins):
    a, b = ins[0], ins[1]
    return [_join(a, b, shape=_shape(eqn)).drop_structure()]


# ---- dot_general: the load-bearing rule -------------------------------

@_reg("dot_general")
def _t_dot(interp, eqn, ins):
    a, b = ins
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    K = 1
    for d in lc:
        K *= a.shape[d]
    kexpr = interp.size_expr(K) if len(lc) == 1 else Expr.const(K)
    int_valued = a.int_valued and b.int_valued
    nonneg = a.nonneg and b.nonneg
    if nonneg:
        hi, lo = kexpr * a.hi * b.hi, ZERO
    else:
        hi = kexpr * _mag(a) * _mag(b)
        lo = hi.neg()
    out = AbsVal(_shape(eqn), _kind(eqn), int_valued, lo, hi,
                 random=_taint(ins))
    # the exact-count refinement (2D matmul, contract A-last/B-first):
    # out[s, z] = sum_p A[s, p] * B[p, z]
    #   per-element   <= rowsum(A) * max(B)        (one-hot dot rule)
    #   per-row sum   <= rowsum(A) * rowsum(B)     (counts stay counts)
    #   over tiles of a sharded p-dim: global rowsum(A) bounds the TOTAL
    if (nonneg and len(a.shape) == 2 and len(b.shape) == 2
            and lc == (1,) and rc == (0,) and not lb and not rb):
        # effective row-sum bounds: explicit if derived (one-hot rows),
        # else the implicit size*max bound of the current (local) shape
        la = a.lastsum if a.lastsum is not None else kexpr * a.hi
        ga = a.lastsum_global if a.lastsum is not None \
            else interp._outside_body()
        lbnd = (b.lastsum if b.lastsum is not None
                else interp.size_expr(b.shape[-1]) * b.hi)
        gb = b.lastsum_global if b.lastsum is not None \
            else interp._outside_body()
        if la.is_finite:
            out.hi = out.hi.emin(la * b.hi)
        if la.is_finite and lbnd.is_finite:
            out.lastsum = la * lbnd
            out.lastsum_global = ga and gb
        if a.sharded and ga and la.is_finite:
            tt = {}
            for key, dim in a.sharded.items():
                if dim == 1:
                    tt[key] = (la * b.hi, True)
            if tt:
                out.tile_total = tt
    return [out]


# ---- PRNG -------------------------------------------------------------

@_reg("random_bits", "random_fold_in", "random_wrap", "random_unwrap",
      "random_seed", "random_split", "random_gamma", "threefry2x32")
def _t_random(interp, eqn, ins):
    outs = []
    for o in eqn.outvars:
        v = _top(o.aval)
        v.random = True
        outs.append(v)
    return outs


# ---- control flow -----------------------------------------------------

@_reg("pjit", "closed_call", "core_call", "remat", "checkpoint",
      "custom_jvp_call", "custom_vjp_call", "custom_vjp_call_jaxpr")
def _t_call(interp, eqn, ins):
    for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
        sub = eqn.params.get(key)
        if sub is not None and hasattr(sub, "jaxpr"):
            outs = interp.run(sub, list(ins))
            return outs[:len(eqn.outvars)] + [
                _top(o.aval) for o in eqn.outvars[len(outs):]]
    return interp._default(eqn, ins)


def _stabilize(prev: AbsVal, out: AbsVal) -> AbsVal:
    """Field-wise widening for loop carries: keep a fact only while the
    body's output still supports it.  Every field can only degrade (to
    its own TOP) and never recover, so iterating ``w = stabilize(w,
    body(w))`` reaches a post-fixpoint in a handful of rounds; at the
    fixpoint ``body(w) <= w`` holds field-wise, making ``w`` a sound
    invariant for every loop iteration."""
    if prev == out:
        return prev
    ls_ok = (prev.lastsum == out.lastsum
             and prev.lastsum_global == out.lastsum_global)
    return AbsVal(
        shape=prev.shape,
        kind=prev.kind if prev.kind == out.kind else "other",
        int_valued=prev.int_valued and out.int_valued,
        lo=prev.lo if prev.lo == out.lo else BOT,
        hi=prev.hi if prev.hi == out.hi else TOP,
        lastsum=prev.lastsum if ls_ok else None,
        lastsum_global=prev.lastsum_global if ls_ok else False,
        # taint is a must-property (PRNG-derived on EVERY path), so it
        # survives only if the body re-derives it each round
        random=prev.random and out.random,
        iota_dim=prev.iota_dim if prev.iota_dim == out.iota_dim else None,
        varies=prev.varies if prev.varies == out.varies else None,
        parts=prev.parts if (prev.parts == out.parts
                             and prev.parts_axis == out.parts_axis) else None,
        parts_axis=prev.parts_axis,
        sharded=prev.sharded if prev.sharded == out.sharded else None,
        tile_total=(prev.tile_total
                    if prev.tile_total == out.tile_total else None),
        pid_deps=prev.pid_deps & out.pid_deps,
        pin=prev.pin if prev.pin == out.pin else None,
    )


@_reg("while")
def _t_while(interp, eqn, ins):
    cn = eqn.params["cond_nconsts"]
    bn = eqn.params["body_nconsts"]
    cond_consts = ins[:cn]
    body_consts = ins[cn:cn + bn]
    carry = ins[cn + bn:]
    body = eqn.params["body_jaxpr"]
    cond = eqn.params["cond_jaxpr"]
    # fixpoint widening: seed the carries with their initial facts and
    # stabilize against the body until nothing degrades further.  This
    # is what lets the round loop carry the score-plane bundle (gumbel
    # taint, per-plane decomposition, one-hot row sums) into the Pallas
    # call inside the body without collapsing it to TOP.
    carry_vars = body.jaxpr.invars[bn:]
    w = [v.replace(shape=tuple(var.aval.shape), origin=None)
         for v, var in zip(carry, carry_vars)]
    w += [_top(var.aval) for var in carry_vars[len(w):]]
    # fixpoint-search passes are muted: reductions/findings are recorded
    # only on the final pass under the converged invariant
    saved = interp.reductions, interp.findings
    interp.reductions, interp.findings = [], []
    try:
        for _ in range(4):
            outs = interp.run(body, body_consts + w)
            new_w = [_stabilize(p, o) for p, o in zip(w, outs)]
            if new_w == w:
                break
            w = new_w
        else:
            # no convergence (should not happen: fields only degrade) —
            # fall back to the sound TOP widening
            w = [_top(var.aval) for var in carry_vars]
    finally:
        interp.reductions, interp.findings = saved
    interp.run(cond, cond_consts + list(w[:len(cond.jaxpr.invars) - cn]))
    outs = interp.run(body, body_consts + w)
    return [_stabilize(p, o).replace(shape=tuple(o_var.aval.shape))
            for p, o, o_var in zip(w, outs, eqn.outvars)]


@_reg("scan")
def _t_scan(interp, eqn, ins):
    num_consts = eqn.params["num_consts"]
    num_carry = eqn.params["num_carry"]
    body = eqn.params["jaxpr"]
    consts = ins[:num_consts]
    xs = ins[num_consts + num_carry:]
    carry = [_top(v.aval)
             for v in body.jaxpr.invars[num_consts:num_consts + num_carry]]
    sliced = []
    for v, var in zip(xs, body.jaxpr.invars[num_consts + num_carry:]):
        sliced.append(v.drop_structure().replace(
            shape=tuple(var.aval.shape),
            lastsum=v.lastsum if v.nonneg else None,
            lastsum_global=v.lastsum_global if v.nonneg else False))
    interp.run(body, consts + carry + sliced)
    return [_top(o.aval) for o in eqn.outvars]


@_reg("cond")
def _t_cond(interp, eqn, ins):
    index, ops = ins[0], ins[1:]
    branches = eqn.params["branches"]
    outs_per = []
    for bi, br in enumerate(branches):
        pinned = frozenset()
        if index.pin is not None and len(branches) == 2 and bi == 1:
            pinned = frozenset((index.pin[0],))
        # refs crossing into the branch (pl.when bodies) are the SAME
        # cells: alias them so block-operand facts survive the boundary
        # and branch writes land in the outer accumulator state
        for atom, bvar in zip(eqn.invars[1:], br.jaxpr.invars):
            if not hasattr(atom, "val") and atom in interp._refs:
                interp._refs[bvar] = interp._refs[atom]
        interp._pinned.append(pinned)
        try:
            outs_per.append(interp.run(br, list(ops)))
        finally:
            interp._pinned.pop()
    joined = []
    for i, o in enumerate(eqn.outvars):
        vals = [outs[i] for outs in outs_per if i < len(outs)]
        if not vals:
            joined.append(_top(o.aval))
            continue
        j = vals[0]
        for v in vals[1:]:
            j = _join(j, v, shape=tuple(o.aval.shape))
        joined.append(j.replace(shape=tuple(o.aval.shape)))
    return joined


# ---- shard_map + collectives ------------------------------------------

@_reg("shard_map")
def _t_shard_map(interp, eqn, ins):
    body = eqn.params["jaxpr"]          # plain Jaxpr
    in_names = eqn.params["in_names"]
    body_ins = []
    for v, names in zip(ins, in_names):
        sharded = dict(v.sharded or {})
        for dim, axes in names.items():
            for ax in axes:
                sharded[ax] = dim
        body_ins.append(v.replace(sharded=sharded or None, origin=None))
    interp.in_shardmap += 1
    try:
        outs = interp._frame(body, [], body_ins)
    finally:
        interp.in_shardmap -= 1
    result = []
    for o, v in zip(eqn.outvars, outs):
        result.append(v.drop_structure().replace(shape=tuple(o.aval.shape)))
    return result


def _record_collective(interp, eqn, v: AbsVal, axes, lo, hi, note=""):
    aval = eqn.invars[0].aval
    interp.reductions.append(Reduction(
        op=eqn.primitive.name,
        kind=_REDUCE_KIND.get(eqn.primitive.name, eqn.primitive.name),
        axes=tuple(str(a) for a in axes),
        dtype=aval.dtype.name,
        shape=tuple(aval.shape),
        int_dtype=_dtype_kind(aval.dtype) in ("int", "bool"),
        int_valued=v.int_valued,
        lo=lo, hi=hi, note=note))


@_reg("psum")
def _t_psum(interp, eqn, ins):
    axes = tuple(eqn.params["axes"])
    outs = []
    for v, o in zip(ins, eqn.outvars):
        lo, hi = v.lo, v.hi
        notes = []
        for ax in axes:
            tt = (v.tile_total or {}).get(ax)
            if tt is not None:
                hi = tt[0]
                lo = ZERO if v.nonneg else hi.neg()
                notes.append("disjoint-tile total over '%s'" % ax)
            else:
                fan = interp.mesh_sym(ax)
                hi = fan * hi
                lo = fan * lo if v.nonneg else (fan * _mag(v)).neg()
        _record_collective(interp, eqn, v, axes, lo, hi,
                           note="; ".join(notes))
        outs.append(AbsVal(tuple(o.aval.shape), v.kind, v.int_valued,
                           lo, hi, random=v.random))
    return outs


@_reg("pmax", "pmin")
def _t_pminmax(interp, eqn, ins):
    axes = tuple(eqn.params["axes"])
    outs = []
    for v, o in zip(ins, eqn.outvars):
        _record_collective(interp, eqn, v, axes, v.lo, v.hi)
        outs.append(v.drop_structure().replace(shape=tuple(o.aval.shape)))
    return outs


@_reg("all_gather")
def _t_all_gather(interp, eqn, ins):
    (v,) = ins
    axes = eqn.params["axis_name"]
    axes = axes if isinstance(axes, tuple) else (axes,)
    _record_collective(interp, eqn, v, axes, v.lo, v.hi)
    if len(v.shape) >= 2:
        interp._finding(
            "exact/shardmap-row-gather",
            "all_gather of a rank-%d operand %s inside a shard_map body: "
            "the gather-free discipline moves per-shard REDUCED vectors "
            "(winner indices, scalars), never tiles/rows — reduce before "
            "you gather" % (len(v.shape), "x".join(map(str, v.shape))))
    out = v.drop_structure().replace(shape=_shape(eqn))
    if v.nonneg and v.lastsum is not None \
            and eqn.params.get("all_gather_dimension", 0) != len(v.shape) - 1:
        out.lastsum, out.lastsum_global = v.lastsum, v.lastsum_global
    return [out]


@_reg("axis_index")
def _t_axis_index(interp, eqn, ins):
    return [AbsVal((), "int", True, ZERO, TOP)]


# ---- Pallas -----------------------------------------------------------

@_reg("program_id")
def _t_program_id(interp, eqn, ins):
    g = eqn.params["axis"]
    size = interp.grid[g] if g < len(interp.grid) else 0
    v = AbsVal((), "int", True, ZERO, Expr.const(max(size - 1, 0)))
    v.pid_deps = frozenset((g,))
    v.origin = ("pid",)
    return [v]


def _index_tree_vars(eqn, skip: int):
    """Dynamic index operands of a get/swap (after ref [+ value])."""
    return list(eqn.invars[skip:])


def _static_scalar_starts(eqn, skip: int, interp=None):
    """Best-effort NDIndexer decode: returns (axis0_static_index or None).
    Static ints are baked into the tree; a scalar index lowered as a
    dynamic leaf resolves through its atom when it is a Literal or a var
    the interpreter knows to be a constant (lo == hi).  Used only to
    recover a stacked plane by index — failure degrades to the joined
    value, never to unsoundness."""
    try:
        import jax
        idx = jax.tree_util.tree_unflatten(
            eqn.params["tree"], _index_tree_vars(eqn, skip))
        indexer = idx[0] if isinstance(idx, (list, tuple)) else idx
        indices = getattr(indexer, "indices", None)
        if not indices:
            return None
        first = indices[0]
        if isinstance(first, int):
            return first
        start = getattr(first, "start", None)
        size = getattr(first, "size", None)
        if isinstance(start, int) and size == 1:
            return start
        if hasattr(first, "val"):            # jaxpr Literal leaf
            return int(first.val)
        if interp is not None and hasattr(first, "aval") \
                and not getattr(first.aval, "shape", (1,)):
            av = interp._abs_of_atom(first)
            if av is not None:
                lo, hi = av.lo._const(), av.hi._const()
                if lo is not None and lo == hi and float(lo).is_integer():
                    return int(lo)
        return None
    except Exception:
        return None


@_reg("get")
def _t_get(interp, eqn, ins):
    ref = eqn.invars[0]
    cell = interp._refs.get(ref)
    stored = cell.val if cell is not None and cell.val is not None \
        else _top(eqn.outvars[0].aval)
    shape = _shape(eqn)
    axis0 = _static_scalar_starts(eqn, skip=1, interp=interp)
    if axis0 is not None and stored.parts is not None \
            and stored.parts_axis == 0:
        part = _part_lookup(stored, 0, axis0, axis0 + 1)
        if part is not None:
            stored = part.replace(sharded=stored.sharded)
    out = stored.replace(shape=shape, parts=None, origin=("get", ref))
    if len(shape) != len(stored.shape):
        # rank change via scalar indexing: remap trailing-dim facts by
        # keeping them only when the last axis is untouched
        drop = len(stored.shape) - len(shape)
        if stored.sharded:
            out.sharded = {k: d - drop for k, d in stored.sharded.items()
                           if d - drop >= 0} or None
    return [out]


def _grid_multiplier(interp, g: int, size: int, pinned: frozenset,
                     idx_deps: frozenset, covered: frozenset):
    if g in covered or g in pinned or g in idx_deps:
        return ONE
    return interp.grid_expr(g, size)


@_reg("swap")
def _t_swap(interp, eqn, ins):
    ref = eqn.invars[0]
    value = ins[1]
    cell = interp._refs.setdefault(ref, _RefCell())
    old = cell.val
    # classify the stored value against the cell: the three accumulator
    # shapes the kernels use are  ref <- ref + v  (sum fold),
    # ref <- max/min(ref, v)  (exact fold)  and  ref <- where(upd, v, ref)
    # (conditional store); anything else is a plain store
    deqn = interp._defs.get(eqn.invars[1])
    acc, inc = None, None
    if deqn is not None and deqn.primitive.name in ("add", "max", "min"):
        srcs = [interp._defs.get(a) for a in deqn.invars]
        del srcs
        get_side = None
        for i, a in enumerate(deqn.invars):
            d = interp._defs.get(a)
            if d is not None and d.primitive.name == "get" \
                    and d.invars[0] is ref:
                get_side = i
        if get_side is not None:
            acc = "sum" if deqn.primitive.name == "add" else "max"
            other = deqn.invars[1 - get_side]
            inc = ins[1]  # fallback
            # re-read the increment's absval from the defining frame
            # by construction it is one of the swap value's inputs —
            # conservative fallback keeps the full value's bounds
            inc = interp._abs_of_atom(other, fallback=ins[1])
    if acc == "sum" and value.kind == "float":
        pinned = frozenset().union(*interp._pinned) if interp._pinned \
            else frozenset()
        idx_deps = frozenset()
        for a in _index_tree_vars(eqn, skip=2):
            av = interp._abs_of_atom(a, fallback=None)
            if av is not None:
                idx_deps = idx_deps | av.pid_deps
        covered = frozenset()
        base_hi = inc.hi
        note = []
        for key in (inc.tile_total or {}):
            if isinstance(key, tuple) and key and key[0] == "grid":
                base_hi = inc.tile_total[key][0]
                covered = covered | frozenset((key[1],))
                note.append("disjoint-tile total over grid axis %d"
                            % key[1])
        total = base_hi
        for g, size in enumerate(interp.grid):
            total = total * _grid_multiplier(interp, g, size, pinned,
                                             idx_deps, covered)
        lo = ZERO if inc.nonneg else total.neg()
        interp.reductions.append(Reduction(
            op="grid_fold", kind="sum", axes=("grid",),
            dtype=eqn.invars[1].aval.dtype.name,
            shape=tuple(eqn.invars[1].aval.shape),
            int_dtype=False, int_valued=inc.int_valued,
            lo=lo, hi=total, note="; ".join(note)))
        stored = AbsVal(value.shape, value.kind,
                        inc.int_valued and (old is None or old.int_valued),
                        lo, total)
    elif acc == "sum":
        stored = value.drop_structure()
        interp.reductions.append(Reduction(
            op="grid_fold", kind="sum", axes=("grid",),
            dtype=eqn.invars[1].aval.dtype.name,
            shape=tuple(eqn.invars[1].aval.shape),
            int_dtype=True, int_valued=True, lo=BOT, hi=TOP))
    elif acc == "max":
        interp.reductions.append(Reduction(
            op="grid_fold", kind="max", axes=("grid",),
            dtype=eqn.invars[1].aval.dtype.name,
            shape=tuple(eqn.invars[1].aval.shape),
            int_dtype=_dtype_kind(eqn.invars[1].aval.dtype) != "float",
            int_valued=value.int_valued, lo=value.lo, hi=value.hi))
        stored = value.drop_structure()
    else:
        stored = value.replace(origin=None)
    cell.val = stored if old is None else _join(old, stored,
                                                shape=old.shape)
    # swap returns the OLD value
    prev = old if old is not None else _top(eqn.outvars[0].aval)
    return [prev.replace(shape=_shape(eqn), origin=None)]


@_reg("addupdate")
def _t_addupdate(interp, eqn, ins):
    ref = eqn.invars[0]
    value = ins[1]
    cell = interp._refs.setdefault(ref, _RefCell())
    interp.reductions.append(Reduction(
        op="grid_fold", kind="sum", axes=("grid",),
        dtype=eqn.invars[1].aval.dtype.name,
        shape=tuple(eqn.invars[1].aval.shape),
        int_dtype=_dtype_kind(eqn.invars[1].aval.dtype) != "float",
        int_valued=value.int_valued, lo=BOT, hi=TOP,
        note="addupdate accumulator (unmodeled fold bound)"))
    cell.val = (value.drop_structure() if cell.val is None
                else _join(cell.val, value, shape=cell.val.shape))
    return []


@_reg("pallas_call")
def _t_pallas_call(interp, eqn, ins):
    gm = eqn.params["grid_mapping"]
    body = eqn.params["jaxpr"]           # kernel jaxpr (refs as invars)
    if not hasattr(body, "consts"):      # plain Jaxpr in some versions
        import jax
        body = jax.core.ClosedJaxpr(body, ())
    grid = tuple(int(g) for g in gm.grid)
    block_ins: List[Optional[AbsVal]] = []
    mappings = list(gm.block_mappings)
    n_in = gm.num_inputs
    for i, bm in enumerate(mappings[:n_in]):
        v = ins[i] if i < len(ins) else None
        if v is None:
            block_ins.append(None)
            continue
        sharded = dict(v.sharded or {})
        idx_j = bm.index_map_jaxpr.jaxpr
        if not idx_j.eqns:     # identity tiling: outvars are grid invars
            for dim, ov in enumerate(idx_j.outvars):
                for g, iv in enumerate(idx_j.invars):
                    if ov is iv:
                        sharded[("grid", g)] = dim
        block_ins.append(v.replace(
            shape=tuple(bm.block_shape), sharded=sharded or None,
            origin=None))
    prev_grid, prev_refs = interp.grid, interp._refs
    interp.grid, interp._refs = grid, {}
    interp.in_kernel += 1
    try:
        invars = body.jaxpr.invars
        frame_ins = []
        for i, var in enumerate(invars):
            if i < len(block_ins) and block_ins[i] is not None:
                v = block_ins[i]
                # the ref's cell starts as the block operand's facts
                interp._refs[var] = _RefCell(val=v)
                frame_ins.append(v)
            else:
                interp._refs[var] = _RefCell()
                frame_ins.append(_top(var.aval) if hasattr(var, "aval")
                                 else None)
        interp._frame(body.jaxpr,
                      [interp._literal_val_abs(c) for c in body.consts],
                      frame_ins)
    finally:
        interp.in_kernel -= 1
        interp.grid, interp._refs = prev_grid, prev_refs
    return [_top(o.aval) for o in eqn.outvars]


# absval lookup for an atom from the most recent frame write
def _abs_of_atom(self, atom, fallback=None):
    if hasattr(atom, "val"):
        return self._literal(atom)
    got = self._env_all.get(atom)
    return got if got is not None else fallback


Interp._abs_of_atom = _abs_of_atom
