"""CLI: ``python -m tools.kubeexact [--write | --check] [--json]``.

--write      re-prove the registry and regenerate EXACT_MANIFEST.json
--check      pure-JSON CI gate: re-validate the committed manifest
             without jax (margins, proof statuses, VMEM re-derivation,
             environment pin, COMPILE_MANIFEST key join) — safe in
             ci_lint.sh before any jax import
(default)    full gate: re-prove everything, fail on any unsuppressed
             finding or on drift against the committed manifest in
             either direction
--json       machine-readable report on stdout
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="kubeexact")
    mode = ap.add_mutually_exclusive_group()
    mode.add_argument("--write", action="store_true",
                      help="re-prove and regenerate EXACT_MANIFEST.json")
    mode.add_argument("--check", action="store_true",
                      help="pure-JSON validation of the committed "
                           "manifest (no jax)")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--manifest", default=None,
                    help="manifest path override (tests)")
    args = ap.parse_args(argv)

    from .manifest import (MANIFEST_PATH, build_manifest, check_manifest,
                           diff_manifest, load_manifest, write_manifest)
    path = args.manifest or MANIFEST_PATH

    if args.check:
        fails = check_manifest(load_manifest(path))
        ok = not fails
        report = {"op": "check", "manifest": path, "failures": fails,
                  "clean": ok}
        if args.json:
            print(json.dumps(report, indent=2, sort_keys=True))
        else:
            for f in fails:
                print("exact-check: " + f)
            print("kubeexact check: %s" % ("clean" if ok else "FAILED"))
        return 0 if ok else 1

    from .driver import run_exact
    res = run_exact()
    doc = build_manifest(res)

    if args.write:
        out = write_manifest(doc, path)
        ok = res.clean
        report = {"op": "write", "written": out,
                  "programs": len(doc["programs"]),
                  "findings": [f.to_json() for f in res.findings],
                  "suppressed": [f.to_json() for f in res.suppressed]}
    else:
        drift = diff_manifest(doc, load_manifest(path))
        ok = (res.clean and not drift["added"] and not drift["removed"]
              and not drift["changed"]
              and not drift.get("missing_manifest"))
        report = {"op": "gate", "manifest": path,
                  "programs": len(doc["programs"]),
                  "headroom": res.headroom, "drift": drift,
                  "findings": [f.to_json() for f in res.findings],
                  "suppressed": [f.to_json() for f in res.suppressed],
                  "clean": ok}

    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        if args.write:
            print("wrote %s (%d programs)"
                  % (report["written"], report["programs"]))
        else:
            d = report["drift"]
            if d.get("missing_manifest"):
                print("no committed manifest at %s — run --write" % path)
            for kind in ("added", "removed", "changed"):
                for rid in d.get(kind, []):
                    print("drift(%s): %s" % (kind, rid))
            hr = res.headroom
            print("headroom: min margin %sx (floor %gx) — %s"
                  % (hr.get("min_margin"), hr.get("floor"),
                     hr.get("dominating") or "no float sums"))
        for f in res.findings:
            print(str(f))
        for f in res.suppressed:
            print(str(f))
        if not args.write:
            print("kubeexact: %s (%d programs)"
                  % ("clean" if ok else "FINDINGS/DRIFT",
                     report["programs"]))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
