"""The committed north-star environment: the deployment shape every
exactness bound is evaluated at.

The prover traces each program once at a small probe rung; every symbolic
bound it derives is then evaluated under THIS environment — the largest
shape the roadmap commits to serving (100k committed pods x 10k nodes,
rescore chunks of 4096 pending pods, max zone/resource vocabularies, the
largest mesh any deployment profile uses).  The environment is committed
into EXACT_MANIFEST.json, so growing the deployment target is an explicit,
reviewed change that re-runs the headroom audit.

No jax imports here: ``--check`` (the committed-manifest gate) must run in
environments without jax, exactly like tools/kubeaot.
"""

from __future__ import annotations

# f32 integer-exactness ceiling (see bounds.INT_EXACT_LIMIT; duplicated
# here as a plain literal so --check needs no other imports)
INT_EXACT_LIMIT = float(2 ** 24)

# Every proved float sum must clear its north-star bound by at least this
# factor — room for one more doubling of the dominating axis plus slack
# for per-shard padding before the invariant is threatened.
MARGIN_FLOOR = 4.0

# v5e per-core VMEM (see /opt/skills/guides; ~16 MiB usable)
VMEM_CAPACITY_BYTES = 16 * 1024 * 1024

# dimension symbols: probe-rung dim sizes are mapped to these names by
# the driver (bounds.sym_table) and bounds re-evaluate here.
#   B  pending-pod batch bucket      (rescore chunk 4096)
#   N  node-slot bucket              (10240 nodes -> pow2 16384)
#   P  committed-pod bucket          (100k existing pods -> pow2 131072)
#   R  resource-channel ceiling
#   Z  zone-vocabulary ceiling
#   MESH:pods / MESH:nodes           largest per-axis mesh fan any
#                                    profile uses (v5e-8 pod-axis 8x;
#                                    (2,4)/(4,2) node-axis up to 4)
#   WB / NT                          Pallas grid steps at north-star:
#                                    ceil(B/128) and ceil(N/128)
NORTHSTAR_ENV = {
    "B": 4096.0,
    "N": 16384.0,
    "P": 131072.0,
    "R": 16.0,
    "Z": 64.0,
    "MESH:pods": 8.0,
    "MESH:nodes": 4.0,
    "WB": 32.0,
    "NT": 128.0,
}
