"""Collective-surface census: every cross-device communication op in a
traced program, with operand bytes per ladder rung.

Unlike the prover (absint.py), this pass needs no value analysis — it is
a plain recursive walk over the jaxpr collecting (op, axis names, dtype,
reduce kind, operand shape, operand bytes) rows.  The rows are committed
into EXACT_MANIFEST.json per rung of the pow2 ladder, giving CI a
two-directional drift gate over the collective surface (a new psum or a
vanished all_gather is a diff, not a silent lowering change) and giving
kubecensus cost rows the per-collective DCN byte attribution.
"""

from __future__ import annotations

from typing import List

from .absint import COLLECTIVES, _REDUCE_KIND

_ITEMSIZE = {"bool": 1, "int8": 1, "uint8": 1, "bfloat16": 2,
             "float16": 2, "int16": 2, "uint16": 2,
             "float32": 4, "int32": 4, "uint32": 4,
             "float64": 8, "int64": 8, "uint64": 8}


def _sub_jaxprs(params: dict):
    """Every jaxpr reachable from an eqn's params — ClosedJaxpr (pjit,
    scan, cond branches) AND plain Jaxpr (shard_map bodies, pallas
    kernels store their body unclosed)."""
    for v in params.values():
        items = v if isinstance(v, (list, tuple)) else [v]
        for u in items:
            if hasattr(u, "eqns"):
                yield u
            elif hasattr(u, "jaxpr") and hasattr(u.jaxpr, "eqns"):
                yield u.jaxpr


def _axes_of(eqn) -> tuple:
    axes = eqn.params.get("axes")
    if axes is None:
        axes = eqn.params.get("axis_name")
    if axes is None:
        return ()
    if not isinstance(axes, (tuple, list)):
        axes = (axes,)
    return tuple(str(a) for a in axes)


def collect_collectives(closed_jaxpr) -> List[dict]:
    """All collective eqns in the program, in deterministic eqn order."""
    rows: List[dict] = []

    def visit(jaxpr):
        for eqn in jaxpr.eqns:
            if eqn.primitive.name in COLLECTIVES:
                aval = eqn.invars[0].aval
                dtype = aval.dtype.name
                n = 1
                for d in aval.shape:
                    n *= int(d)
                rows.append({
                    "op": eqn.primitive.name,
                    "kind": _REDUCE_KIND.get(eqn.primitive.name,
                                             eqn.primitive.name),
                    "axes": list(_axes_of(eqn)),
                    "dtype": dtype,
                    "shape": [int(d) for d in aval.shape],
                    "bytes": n * _ITEMSIZE.get(dtype, 4),
                })
            for sub in _sub_jaxprs(eqn.params):
                visit(sub)

    visit(closed_jaxpr.jaxpr)
    return rows
