"""The closure's audited trust base (the twin of kubeexact's
``exact_facts``): finite-domain declarations the AST prover cannot derive
on its own, plus the structured exemptions that carry
reachable-but-deliberately-uncovered signatures.

Everything here is reviewed, committed state: the prover TRUSTS these
tables, so growing one is an explicit diff, and a table row no finding
consumes ages out as ``close/stale-exemption`` (exemptions) or is simply
dead text under review (domains).  No jax imports.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

# ---------------------------------------------------------------- domains

# Config classes whose instances are per-deployment constants: a value of
# one of these types is label config-constant (finite: profiles are
# loaded once at scheduler construction and never mutated mid-serve; the
# ProgramConfig NamedTuple is hashable and IS the jit static key).
CONFIG_CLASSES = ("ProgramConfig", "KubeSchedulerConfiguration",
                  "KubeSchedulerProfile")

# Audited value domains of the config FIELDS that reach dispatch seams in
# static positions.  A field read without a row here stays a symbolic
# config-constant (finite per deployment, not enumerated), so every
# multi-valued axis the closure crosses exists because a row here
# declared it — declaring the domain is the reviewed act that makes the
# enumeration sound.  Value: a tuple of canonical reprs
# (registry-enumerated), or None to pin the field symbolic explicitly.
CONFIG_FIELD_DOMAINS: Dict[Tuple[str, str], Optional[Tuple[str, ...]]] = {
    # the kernel backend knob: apis/config.py restricts it to the lax
    # oracle and the fused Pallas megakernel
    ("KubeSchedulerConfiguration", "kernel_backend"): ("'lax'", "'pallas'"),
    ("KubeSchedulerConfiguration", "mode"): ("'gang'", "'sequential'"),
    # read on the seam path only to normalize the static out of the
    # program key (gang) or via the _seq_cfg replica (sequential)
    ("ProgramConfig", "percentage_of_nodes_to_score"): None,
}

# Host-state dict keys that hold pow2-bucketed CAPACITIES by construction
# (state/tensors.py: every ``*_cap`` slot is written from pow2_bucket of
# a vocab/world size).  A Subscript read of one of these keys is label
# pow2-bucketed; anything else stays unbounded.
STATE_CAPACITY_KEYS = ("_kv_cap",)

# Helper callables (resolved dotted suffix) whose RESULT class the prover
# pins without reading the body: register_mesh tokens are one per mesh
# shape (bounded by the deployment's mesh profiles).
MESH_KEY_FUNCS = ("register_mesh",)

# --------------------------------------------------------- extra roots

# Seamed serving programs dispatched as a Python-level jit-object PAIR
# instead of through aot.dispatch: the host entry picks one of two jit
# twins on a boolean.  The closure enumerates them from the host entry's
# parameter provenance; ``axes`` maps the closure axis name to the host
# parameter carrying it.
EXTRA_ROOTS = (
    {
        "program": "_apply_cluster_delta",
        "entry": "kubetpu.models.programs:apply_cluster_delta",
        "axes": {"donate": "donate"},
    },
    {
        "program": "_apply_delta_body",
        "entry": "kubetpu.parallel.shardmap:apply_cluster_delta_mesh",
        "axes": {"donate": "donate"},
        # the shard_map twins additionally key on the mesh token
        "symbolic": {"mesh_key": "mesh-key"},
    },
)

# ------------------------------------------------------------ exemptions

# Structured (rule, key, reason) exemptions.  ``key`` is the finding's
# stable key (program + sorted axis assignment for uncaptured-signature;
# program:tag for unreachable-manifest-row).  Every exemption must name
# the FALLBACK PATH that serves the exempted signature; one that matches
# no finding is itself a close/stale-exemption finding.
EXEMPTIONS: Tuple[Tuple[str, str, str], ...] = (
    # ---- branch correlations the flow-insensitive join cannot see ----
    # schedule_gang forces backend="lax" BEFORE the seam whenever
    # unsupported_reason(cfg, intra_batch_topology, batch) is non-None,
    # and intra_batch_topology=True is unconditionally unsupported
    # (utils/pallas_backend.py) — so the pallas x topology cross never
    # reaches the jit; topology batches serve on the lax auction.
    ("close/uncaptured-signature",
     "_schedule_gang host_ok=absent intra_batch_topology=True "
     "kernel_backend='pallas' score_bias=absent",
     "statically excluded before the seam: unsupported_reason returns "
     "'intra-batch-topology' and run_auction falls back to the lax "
     "auction (the covered intra=True rows)"),
    ("close/uncaptured-signature",
     "_schedule_gang host_ok=present intra_batch_topology=True "
     "kernel_backend='pallas' score_bias=absent",
     "statically excluded before the seam: unsupported_reason returns "
     "'intra-batch-topology' and run_auction falls back to the lax "
     "auction (the covered intra=True rows)"),
    ("close/uncaptured-signature",
     "_schedule_gang host_ok=absent intra_batch_topology=True "
     "kernel_backend='pallas' score_bias=present",
     "statically excluded before the seam: unsupported_reason returns "
     "'intra-batch-topology' and run_auction falls back to the lax "
     "auction (the covered intra=True rows)"),
    ("close/uncaptured-signature",
     "_schedule_gang host_ok=present intra_batch_topology=True "
     "kernel_backend='pallas' score_bias=present",
     "statically excluded before the seam: unsupported_reason returns "
     "'intra-batch-topology' and run_auction falls back to the lax "
     "auction (the covered intra=True rows)"),
    # _shardmap_gang: gang_surface returns "replicated" whenever
    # intra_batch_topology=True, so the topology x tiled cross is
    # unreachable (parallel/shardmap.py gang_surface).
    ("close/uncaptured-signature",
     "_shardmap_gang host_ok=absent intra_batch_topology=True "
     "score_bias=absent surface='tiled'",
     "statically excluded before the seam: gang_surface routes every "
     "intra_batch_topology=True dispatch to surface='replicated'"),
    ("close/uncaptured-signature",
     "_shardmap_gang host_ok=present intra_batch_topology=True "
     "score_bias=absent surface='tiled'",
     "statically excluded before the seam: gang_surface routes every "
     "intra_batch_topology=True dispatch to surface='replicated'"),
    ("close/uncaptured-signature",
     "_shardmap_gang host_ok=absent intra_batch_topology=True "
     "score_bias=present surface='tiled'",
     "statically excluded before the seam: gang_surface routes every "
     "intra_batch_topology=True dispatch to surface='replicated'"),
    ("close/uncaptured-signature",
     "_shardmap_gang host_ok=present intra_batch_topology=True "
     "score_bias=present surface='tiled'",
     "statically excluded before the seam: gang_surface routes every "
     "intra_batch_topology=True dispatch to surface='replicated'"),
    # ---- host-score-bias crosses: served by the traced fallback ----
    # The bias-variant census row covers the common host-score profile
    # (host_ok AND score_bias from the same framework runner).  The rarer
    # crosses (a Score plugin without a Filter plugin, bias on the
    # term-free/megakernel routes) fall back at the seam to the traced
    # jit dispatch: ONE bounded compile per (program, bucket), warmed by
    # Scheduler.prewarm's score_bias=warm_bias pass when the profile
    # declares host score plugins, and fenced by the BENCH_GATE watchdog
    # + the per-(program, shape) recompile watchdog.
    ("close/uncaptured-signature",
     "_schedule_gang host_ok=absent intra_batch_topology=True "
     "kernel_backend='lax' score_bias=present",
     "score-plugin-without-filter-plugin profile: traced-jit fallback at "
     "the seam, prewarmed by the score_bias=warm_bias prewarm variant"),
    ("close/uncaptured-signature",
     "_schedule_gang host_ok=absent intra_batch_topology=False "
     "kernel_backend='lax' score_bias=present",
     "score-plugin-without-filter-plugin profile on a term-free batch: "
     "traced-jit fallback at the seam, prewarmed by the "
     "score_bias=warm_bias prewarm variant"),
    ("close/uncaptured-signature",
     "_schedule_gang host_ok=present intra_batch_topology=False "
     "kernel_backend='lax' score_bias=present",
     "host filter+score profile on a term-free lax batch: traced-jit "
     "fallback at the seam, prewarmed by the score_bias=warm_bias "
     "prewarm variant"),
    ("close/uncaptured-signature",
     "_schedule_gang host_ok=absent intra_batch_topology=False "
     "kernel_backend='pallas' score_bias=present",
     "host score bias on the megakernel route: traced-jit fallback at "
     "the seam (the megakernel's lax oracle serves the bias variant); "
     "BENCH_GATE watchdog fences the compile"),
    ("close/uncaptured-signature",
     "_schedule_gang host_ok=present intra_batch_topology=False "
     "kernel_backend='pallas' score_bias=present",
     "host filter+score bias on the megakernel route: traced-jit "
     "fallback at the seam; BENCH_GATE watchdog fences the compile"),
    ("close/uncaptured-signature",
     "_schedule_sequential host_ok=absent score_bias=present",
     "score-plugin-without-filter-plugin profile: traced-jit fallback at "
     "the seam, prewarmed by the score_bias=warm_bias prewarm variant"),
    ("close/uncaptured-signature",
     "_schedule_sequential host_ok=present score_bias=present",
     "host filter+score profile: traced-jit fallback at the seam, "
     "prewarmed by the score_bias=warm_bias prewarm variant"),
    # ---- mesh twins: the kubeaot HONEST COVERAGE NOTE ----
    # Census rows for the shard_map family capture at the (1, 1)-mesh
    # rung and the mesh key is part of the signature, so a fleet mesh's
    # dispatches fall back per key to the trace path regardless — the
    # rows pin the build-time sha oracle, not a production warm start
    # (tools/kubeaot/build.py AOT_PROGRAMS note; deploy-shaped mesh
    # capture is the ROADMAP item 1 residual).  The host_ok/score_bias
    # crosses and the degraded-surface route ride that same fallback.
    ("close/uncaptured-signature",
     "_shardmap_gang host_ok=absent intra_batch_topology=False "
     "score_bias=absent surface='replicated'",
     "term-free batch degraded to the replicated surface (unsupported "
     "score plugin / soft-spread / non-dividing axis): traced-jit "
     "fallback per mesh key — the kubeaot honest-coverage note's "
     "fallback path"),
    ("close/uncaptured-signature",
     "_shardmap_gang host_ok=present intra_batch_topology=True "
     "score_bias=absent surface='replicated'",
     "mesh profile with host filter plugins: traced-jit fallback per "
     "mesh key (kubeaot honest-coverage note)"),
    ("close/uncaptured-signature",
     "_shardmap_gang host_ok=present intra_batch_topology=False "
     "score_bias=absent surface='replicated'",
     "mesh host-filter cross on the degraded surface: traced-jit "
     "fallback per mesh key (kubeaot honest-coverage note)"),
    ("close/uncaptured-signature",
     "_shardmap_gang host_ok=present intra_batch_topology=False "
     "score_bias=absent surface='tiled'",
     "mesh host-filter cross on the tiled surface: traced-jit fallback "
     "per mesh key (kubeaot honest-coverage note)"),
    ("close/uncaptured-signature",
     "_shardmap_gang host_ok=absent intra_batch_topology=True "
     "score_bias=present surface='replicated'",
     "mesh host-score cross: traced-jit fallback per mesh key (kubeaot "
     "honest-coverage note)"),
    ("close/uncaptured-signature",
     "_shardmap_gang host_ok=present intra_batch_topology=True "
     "score_bias=present surface='replicated'",
     "mesh host filter+score cross: traced-jit fallback per mesh key "
     "(kubeaot honest-coverage note)"),
    ("close/uncaptured-signature",
     "_shardmap_gang host_ok=absent intra_batch_topology=False "
     "score_bias=present surface='replicated'",
     "mesh host-score cross on the degraded surface: traced-jit "
     "fallback per mesh key (kubeaot honest-coverage note)"),
    ("close/uncaptured-signature",
     "_shardmap_gang host_ok=present intra_batch_topology=False "
     "score_bias=present surface='replicated'",
     "mesh host filter+score cross on the degraded surface: traced-jit "
     "fallback per mesh key (kubeaot honest-coverage note)"),
    ("close/uncaptured-signature",
     "_shardmap_gang host_ok=absent intra_batch_topology=False "
     "score_bias=present surface='tiled'",
     "mesh host-score cross on the tiled surface: traced-jit fallback "
     "per mesh key (kubeaot honest-coverage note)"),
    ("close/uncaptured-signature",
     "_shardmap_gang host_ok=present intra_batch_topology=False "
     "score_bias=present surface='tiled'",
     "mesh host filter+score cross on the tiled surface: traced-jit "
     "fallback per mesh key (kubeaot honest-coverage note)"),
    ("close/uncaptured-signature",
     "_shardmap_sequential host_ok=present score_bias=absent",
     "mesh host-filter cross: traced-jit fallback per mesh key (kubeaot "
     "honest-coverage note)"),
    ("close/uncaptured-signature",
     "_shardmap_sequential host_ok=absent score_bias=present",
     "mesh host-score cross: traced-jit fallback per mesh key (kubeaot "
     "honest-coverage note)"),
    ("close/uncaptured-signature",
     "_shardmap_sequential host_ok=present score_bias=present",
     "mesh host filter+score cross: traced-jit fallback per mesh key "
     "(kubeaot honest-coverage note)"),
)
