"""CLOSURE_MANIFEST.json: serialization, drift diffing, and the
pure-JSON re-validation the no-jax CI gate runs first.

The committed manifest is the version-controlled compile-surface
closure — per seamed program, the proved axis table (fixed / symbolic /
crossed), the enumerated reachable signature combos with their coverage
(a kubecensus registry row, or a structured exemption naming the
fallback path), and the committed environment.  Two consumers:

* CI (``python -m tools.kubeclose``): re-proves the closure over the
  tree and fails on drift in either direction — an enumerated combo
  absent from the committed file (the reachable surface grew silently)
  or a committed combo the prover no longer reaches (dead closure row).
* CI without jax (``python -m tools.kubeclose --check``): re-validates
  the committed file alone — every combo covered, every registry
  coverage pointer resolving to a COMPILE_MANIFEST.json row, every
  AOT-seamed program's covering rows present in AOT_INDEX.json, and the
  environment byte-equal to tools/kubeexact/northstar.py.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

from tools.kubeexact import northstar

from .closure import REPO_ROOT, ClosureResult, entry_key

MANIFEST_PATH = os.path.join(REPO_ROOT, "CLOSURE_MANIFEST.json")
CENSUS_PATH = os.path.join(REPO_ROOT, "COMPILE_MANIFEST.json")
AOT_INDEX_PATH = os.path.join(REPO_ROOT, "tools", "kubeaot",
                              "AOT_INDEX.json")

_COMMENT = ("Compile-surface closure (tools/kubeclose). Regenerate: make "
            "close (python -m tools.kubeclose --write). CI fails on drift "
            "in either direction; --check re-validates this file without "
            "jax.")


def build_manifest(res: ClosureResult) -> dict:
    programs: Dict[str, dict] = {}
    for pc in res.programs:
        programs[pc.seam.program] = {
            "target": pc.seam.target,
            "site": _relsite(pc.seam.site),
            "axes": {n: ax.to_json() for n, ax in pc.seam.axes.items()},
            "fixed": dict(pc.fixed),
            "symbolic": dict(pc.symbolic),
            "combos": {c.key: c.to_json() for c in pc.combos},
        }
    return {
        "_comment": _COMMENT,
        "northstar_env": dict(northstar.NORTHSTAR_ENV),
        "programs": programs,
        "findings": [f.to_json() for f in res.findings],
        "exemptions": [f.to_json() for f in res.exempted],
        "counts": {
            "programs": len(programs),
            "combos": sum(len(p["combos"]) for p in programs.values()),
            "covered": sum(
                1 for p in programs.values()
                for c in p["combos"].values()
                if c["coverage"].startswith("registry:")),
            "exempt": sum(1 for p in programs.values()
                          for c in p["combos"].values()
                          if c["coverage"] == "exempt"),
            "findings": len(res.findings),
        },
    }


def _relsite(site: str) -> str:
    path, _, line = site.rpartition(":")
    if os.path.isabs(path):
        path = os.path.relpath(path, REPO_ROOT)
    return "%s:%s" % (path, line)


def write_manifest(doc: dict, path: str = None) -> str:
    """Deterministic serialization: sorted keys, fixed indent, trailing
    newline — regeneration over an unchanged tree is byte-identical."""
    path = path or MANIFEST_PATH
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    return path


def load_manifest(path: str = None) -> Optional[dict]:
    path = path or MANIFEST_PATH
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def diff_manifest(current: dict,
                  committed: Optional[dict]) -> Dict[str, list]:
    """Two-directional drift over (program, combo) keys plus
    watched-content changes."""
    if committed is None:
        return {"added": sorted(current.get("programs", {})),
                "removed": [], "changed": [], "missing_manifest": True}
    cur = current.get("programs", {})
    com = committed.get("programs", {})
    added = sorted(set(cur) - set(com))
    removed = sorted(set(com) - set(cur))
    changed = []
    if current.get("northstar_env") != committed.get("northstar_env"):
        changed.append("<northstar_env>")
    if current.get("findings") != committed.get("findings"):
        changed.append("<findings>")
    if current.get("exemptions") != committed.get("exemptions"):
        changed.append("<exemptions>")
    watched = ("axes", "fixed", "symbolic", "combos", "target")
    for k in sorted(set(cur) & set(com)):
        for w in watched:
            if cur[k].get(w) != com[k].get(w):
                changed.append("%s (%s)" % (k, w))
                break
    return {"added": added, "removed": removed, "changed": changed}


# ---------------------------------------------------------------- --check

def _census_keys(census_path: str = None) -> Optional[set]:
    path = census_path or CENSUS_PATH
    try:
        with open(path) as f:
            rows = json.load(f)["rows"]
    except (OSError, ValueError, KeyError):
        return None
    return {entry_key(r["program"], r.get("tag") or "") for r in rows}


def _aot_programs(aot_path: str = None) -> Optional[set]:
    path = aot_path or AOT_INDEX_PATH
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    return {r.get("program") for r in doc.get("rows", [])}


def check_manifest(doc: Optional[dict], census_path: str = None,
                   aot_path: str = None) -> List[str]:
    """Pure-JSON re-validation of the committed closure (no jax, no AST
    walk of kubetpu).  Returns failure strings; empty means green."""
    fails: List[str] = []
    if doc is None:
        return ["no committed CLOSURE_MANIFEST.json — run --write"]
    if doc.get("northstar_env") != northstar.NORTHSTAR_ENV:
        fails.append("northstar_env drifted from tools/kubeexact/"
                     "northstar.py — regenerate with --write")
    if doc.get("findings"):
        fails.append("committed manifest carries %d open finding(s) — "
                     "the closure is not proved"
                     % len(doc.get("findings")))
    census = _census_keys(census_path)
    if census is None:
        fails.append("cannot read COMPILE_MANIFEST.json")
    aot = _aot_programs(aot_path)
    if aot is None:
        fails.append("cannot read tools/kubeaot/AOT_INDEX.json")
    for program, prog in sorted((doc.get("programs") or {}).items()):
        combos = prog.get("combos") or {}
        for key, combo in sorted(combos.items()):
            cov = combo.get("coverage", "")
            if cov.startswith("registry:"):
                rk = cov.split(":", 1)[1]
                if census is not None and rk not in census:
                    fails.append("%s: coverage row %r has no "
                                 "COMPILE_MANIFEST.json row" % (key, rk))
            elif cov == "exempt":
                if not combo.get("reason"):
                    fails.append("%s: exempt combo without a reason "
                                 "naming its fallback path" % key)
            else:
                fails.append("%s: combo is neither registry-covered nor "
                             "exempt" % key)
        for axis, ax in sorted((prog.get("axes") or {}).items()):
            if ax.get("label") == "unbounded":
                fails.append("%s: axis %r committed as unbounded — the "
                             "closure is not proved" % (program, axis))
        if aot is not None and program in aot and not combos:
            fails.append("%s: AOT-indexed program with an empty combo "
                         "set" % program)
    if aot is not None:
        progs = set(doc.get("programs") or {})
        for p in sorted(aot - progs):
            fails.append("AOT_INDEX program %r is outside the closure — "
                         "an artifact for a seam the prover cannot see"
                         % p)
    return fails
