"""The interprocedural provenance engine.

Built on kubelint's CallGraph (module scan, import resolution, jit-root
static params) and deepened four ways the one-level local-name dataflow
in kubelint's recompile family never had:

  * interprocedural parameter joins — a parameter's provenance is the
    join of the matching argument at every call site in the analyzed
    set (plus its literal default when some site omits it), memoized
    with an in-progress guard so recursion bottoms out at ⊥;
  * ``self`` resolution — ``self.method(...)`` edges and ``self.attr``
    reads join over every ``self.attr = ...`` assignment in the class;
  * constructor field tracking — reads of a dataclass field
    (``prep.host_ok_dev``) join the matching constructor argument over
    every construction site (the PreparedCycle plumbing between
    ``_prepare_group`` and ``_dispatch_group``);
  * ``aot.dispatch`` seam edges — the seam's args-tuple / kwargs-dict
    are mapped onto the jitted callee's parameters, so provenance flows
    THROUGH the seam like a direct call.

Everything is flow-insensitive: a name's provenance is the join over
all its assignments, which is sound (an over-approximation of any
execution order) and exactly why branch-correlated exclusions live in
domains.EXEMPTIONS instead of the lattice.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Tuple

from tools.kubelint.callgraph import CallGraph, FunctionInfo, ModuleInfo
from tools.kubelint.core import SourceModule

from . import domains
from .lattice import (BOOL, Prov, canon, const, drop_falsy, join, unbounded)

_IN_PROGRESS = object()

_BUILTIN_BOOL = ("bool", "isinstance", "issubclass", "any", "all",
                 "callable", "hasattr")
_BUILTIN_PASS = ("int", "float", "abs", "round")
_BUILTIN_JOINARGS = ("min", "max")


def _last_attr(expr: ast.AST) -> Optional[str]:
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        v = expr.value.split("[")[-1].rstrip("]")
        return v.split(".")[-1]
    if isinstance(expr, ast.Subscript):
        # Optional[X] is X-with-a-None-default for provenance purposes
        if _last_attr(expr.value) == "Optional":
            return _last_attr(expr.slice)
        return None
    return None


def _contains_arith(expr: ast.AST) -> bool:
    return any(isinstance(n, ast.BinOp)
               and isinstance(n.op, (ast.Add, ast.Sub))
               for n in ast.walk(expr))


class _CallSite:
    """One resolved call of ``callee``: the argument expressions bound to
    its parameter names, evaluated in the CALLER's context."""

    __slots__ = ("mi", "caller", "bound", "splat")

    def __init__(self, mi: ModuleInfo, caller: Optional[FunctionInfo],
                 bound: Dict[str, ast.AST], splat: bool):
        self.mi = mi
        self.caller = caller
        self.bound = bound       # param name -> caller-context expression
        self.splat = splat       # *args/**kwargs present: unmatched params
                                 # are unbounded, not defaulted


def _params_of(fn_node) -> List[str]:
    a = getattr(fn_node, "args", None)
    if a is None:
        return []
    return [p.arg for p in a.posonlyargs + a.args]


def _default_expr(fn_node, pname: str) -> Optional[ast.AST]:
    a = getattr(fn_node, "args", None)
    if a is None:
        return None
    pos = a.posonlyargs + a.args
    firstdef = len(pos) - len(a.defaults)
    for i, p in enumerate(pos):
        if p.arg == pname:
            return a.defaults[i - firstdef] if i >= firstdef else None
    for i, p in enumerate(a.kwonlyargs):
        if p.arg == pname:
            return a.kw_defaults[i]
    return None


def _annotation_of(fn_node, pname: str) -> Optional[ast.AST]:
    a = getattr(fn_node, "args", None)
    if a is None:
        return None
    for p in a.posonlyargs + a.args + a.kwonlyargs:
        if p.arg == pname:
            return p.annotation
    return None


def _bind_call(callee: FunctionInfo, call: ast.Call,
               bound_recv: bool) -> Tuple[Dict[str, ast.AST], bool]:
    params = _params_of(callee.node)
    if bound_recv and params and params[0] in ("self", "cls"):
        params = params[1:]
    mapping: Dict[str, ast.AST] = {}
    splat = False
    for i, arg in enumerate(call.args):
        if isinstance(arg, ast.Starred):
            splat = True
            break
        if i < len(params):
            mapping[params[i]] = arg
    for kw in call.keywords:
        if kw.arg is None:
            splat = True
        else:
            mapping[kw.arg] = kw.value
    return mapping, splat


def _is_dispatch(dotted: Optional[str]) -> bool:
    return bool(dotted) and (dotted == "aot.dispatch"
                             or dotted.endswith(".aot.dispatch"))


def seam_kwarg_exprs(call: ast.Call) -> Dict[str, ast.AST]:
    """The kwargs-dict expressions of an ``aot.dispatch`` call: accepts
    both the house ``dict(k=v, ...)`` form and a literal ``{...}``."""
    if len(call.args) < 4:
        return {}
    kw = call.args[3]
    out: Dict[str, ast.AST] = {}
    if (isinstance(kw, ast.Call) and isinstance(kw.func, ast.Name)
            and kw.func.id == "dict"):
        for k in kw.keywords:
            if k.arg is not None:
                out[k.arg] = k.value
    elif isinstance(kw, ast.Dict):
        for k, v in zip(kw.keys, kw.values):
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                out[k.value] = v
    return out


class ProvenanceEngine:
    def __init__(self, modules: Sequence[SourceModule],
                 callgraph: Optional[CallGraph] = None):
        self.modules = list(modules)
        self.cg = callgraph if callgraph is not None else CallGraph(modules)
        self._qualname: Dict[str, FunctionInfo] = {}
        self._callsites: Dict[int, List[_CallSite]] = {}
        self._self_attrs: Dict[Tuple[str, str, str],
                               List[Tuple[ModuleInfo, FunctionInfo,
                                          ast.AST]]] = {}
        # class name -> ordered dataclass field names
        self._class_fields: Dict[str, List[str]] = {}
        # class name -> field -> construction-site expressions
        self._ctor_args: Dict[str, Dict[str, List[
            Tuple[ModuleInfo, Optional[FunctionInfo], ast.AST]]]] = {}
        # field name -> owning classes (for unique-field attribute reads)
        self._field_owner: Dict[str, List[str]] = {}
        self._dispatch_calls: List[Tuple[ModuleInfo,
                                         Optional[FunctionInfo],
                                         ast.Call]] = []
        self._param_memo: Dict[Tuple[int, str], object] = {}
        self._name_memo: Dict[Tuple[int, str], object] = {}
        self._ret_memo: Dict[int, object] = {}
        self._build_index()

    # ------------------------------------------------------------- indexing

    def _build_index(self) -> None:
        for mi in self.cg.mods.values():
            for fi in mi.by_node.values():
                self._qualname[fi.qualname] = fi
            for stmt in mi.module.tree.body:
                if isinstance(stmt, ast.ClassDef):
                    fields = [t.target.id for t in stmt.body
                              if isinstance(t, ast.AnnAssign)
                              and isinstance(t.target, ast.Name)]
                    if fields:
                        self._class_fields[stmt.name] = fields
                        for f in fields:
                            self._field_owner.setdefault(f, []).append(
                                stmt.name)
        for mi in self.cg.mods.values():
            self._index_module(mi)

    def _index_module(self, mi: ModuleInfo) -> None:
        for node in ast.walk(mi.module.tree):
            if isinstance(node, ast.Assign):
                enc = mi.module.enclosing_function(node)
                fi = mi.by_node.get(id(enc)) if enc is not None else None
                for t in node.targets:
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self" and fi is not None):
                        cls = self._class_of(fi)
                        if cls:
                            self._self_attrs.setdefault(
                                (mi.module.name, cls, t.attr), []).append(
                                    (mi, fi, node.value))
            elif isinstance(node, ast.Call):
                enc = mi.module.enclosing_function(node)
                fi = mi.by_node.get(id(enc)) if enc is not None else None
                dotted = self.cg.resolve_dotted(mi, node.func)
                if _is_dispatch(dotted):
                    self._dispatch_calls.append((mi, fi, node))
                    self._index_dispatch(mi, fi, node)
                    continue
                cls = self._ctor_class(mi, node.func)
                if cls is not None:
                    self._index_ctor(mi, fi, node, cls)
                    continue
                callee, bound = self._resolve_callee(mi, fi, node)
                if callee is not None:
                    mapping, splat = _bind_call(callee, node, bound)
                    self._callsites.setdefault(id(callee), []).append(
                        _CallSite(mi, fi, mapping, splat))

    def _index_dispatch(self, mi: ModuleInfo, fi: Optional[FunctionInfo],
                        call: ast.Call) -> None:
        """Map an ``aot.dispatch(prog, jitfn, (args...), dict(...))``
        seam onto the jitted callee's parameters."""
        target = self.dispatch_target(mi, fi, call)
        if target is None:
            return
        params = _params_of(target.node)
        mapping: Dict[str, ast.AST] = {}
        if len(call.args) >= 3 and isinstance(call.args[2], ast.Tuple):
            for i, el in enumerate(call.args[2].elts):
                if i < len(params):
                    mapping[params[i]] = el
        mapping.update(seam_kwarg_exprs(call))
        self._callsites.setdefault(id(target), []).append(
            _CallSite(mi, fi, mapping, False))

    def dispatch_target(self, mi: ModuleInfo, fi: Optional[FunctionInfo],
                        call: ast.Call) -> Optional[FunctionInfo]:
        if len(call.args) < 2:
            return None
        return self._lookup(mi, fi, call.args[1])

    def dispatch_calls(self):
        return list(self._dispatch_calls)

    def _class_of(self, fi: FunctionInfo) -> Optional[str]:
        qual = fi.qualname.split(":", 1)[-1]
        return qual.rsplit(".", 1)[0] if "." in qual else None

    def _ctor_class(self, mi: ModuleInfo, func: ast.AST) -> Optional[str]:
        name = None
        if isinstance(func, ast.Name):
            name = func.id
            if name in mi.from_imports:
                name = mi.from_imports[name][1]
        elif isinstance(func, ast.Attribute):
            name = func.attr
        return name if name in self._class_fields else None

    def _index_ctor(self, mi: ModuleInfo, fi: Optional[FunctionInfo],
                    call: ast.Call, cls: str) -> None:
        fields = self._class_fields[cls]
        slots = self._ctor_args.setdefault(cls, {})
        for i, arg in enumerate(call.args):
            if isinstance(arg, ast.Starred):
                break
            if i < len(fields):
                slots.setdefault(fields[i], []).append((mi, fi, arg))
        for kw in call.keywords:
            if kw.arg is not None and kw.arg in fields:
                slots.setdefault(kw.arg, []).append((mi, fi, kw.value))

    # ------------------------------------------------------ call resolution

    def _lookup(self, mi: ModuleInfo, fi: Optional[FunctionInfo],
                func: ast.AST) -> Optional[FunctionInfo]:
        if fi is not None:
            hit = self.cg._lookup_callee(mi, fi, func)
            if hit is not None:
                return hit
        elif isinstance(func, ast.Name):
            if func.id in mi.functions:
                return mi.functions[func.id]
            if func.id in mi.from_imports:
                base, orig = mi.from_imports[func.id]
                other = self.cg.mods.get(base)
                if other is not None:
                    return other.functions.get(orig)
        elif isinstance(func, ast.Attribute) and isinstance(func.value,
                                                            ast.Name):
            alias = func.value.id
            target = None
            if alias in mi.import_aliases:
                target = self.cg.mods.get(mi.import_aliases[alias])
            elif alias in mi.from_imports:
                base, orig = mi.from_imports[alias]
                target = self.cg.mods.get((base + "." + orig) if base
                                          else orig)
            if target is not None:
                return target.functions.get(func.attr)
        return None

    def _resolve_callee(self, mi: ModuleInfo, fi: Optional[FunctionInfo],
                        call: ast.Call
                        ) -> Tuple[Optional[FunctionInfo], bool]:
        func = call.func
        if (isinstance(func, ast.Attribute)
                and isinstance(func.value, ast.Name)
                and func.value.id in ("self", "cls") and fi is not None):
            cls = self._class_of(fi)
            if cls:
                hit = self._qualname.get(
                    "%s:%s.%s" % (mi.module.name, cls, func.attr))
                if hit is not None:
                    return hit, True
            return None, False
        return self._lookup(mi, fi, func), False

    # ---------------------------------------------------------- provenance

    def prov_expr(self, mi: ModuleInfo, fi: Optional[FunctionInfo],
                  e: ast.AST) -> Optional[Prov]:
        """Provenance of an expression in (module, function) context.
        ``None`` is ⊥: an in-progress recursion, joined as identity."""
        if isinstance(e, ast.Constant):
            return const((canon(e.value),))
        if isinstance(e, ast.Name):
            return self.name_prov(mi, fi, e.id)
        if isinstance(e, ast.Attribute):
            return self._prov_attribute(mi, fi, e)
        if isinstance(e, ast.Call):
            return self._prov_call(mi, fi, e)
        if isinstance(e, ast.BoolOp):
            if isinstance(e.op, ast.Or):
                acc: Optional[Prov] = None
                for v in e.values[:-1]:
                    p = self.prov_expr(mi, fi, v)
                    acc = join(acc, drop_falsy(p) if p is not None else None)
                return join(acc, self.prov_expr(mi, fi, e.values[-1]))
            ps = [self.prov_expr(mi, fi, v) for v in e.values]
            if all(p is not None and p.label in ("bool", "const")
                   for p in ps):
                return BOOL
            acc = None
            for p in ps:
                acc = join(acc, p)
            return acc
        if isinstance(e, ast.UnaryOp) and isinstance(e.op, ast.Not):
            return BOOL
        if isinstance(e, ast.Compare):
            return BOOL
        if isinstance(e, ast.IfExp):
            return join(self.prov_expr(mi, fi, e.body),
                        self.prov_expr(mi, fi, e.orelse))
        if isinstance(e, ast.Subscript):
            sl = e.slice
            if (isinstance(sl, ast.Constant) and isinstance(sl.value, str)
                    and sl.value in domains.STATE_CAPACITY_KEYS):
                return Prov("pow2-bucketed", None,
                            "audited capacity key %r "
                            "(domains.STATE_CAPACITY_KEYS)" % sl.value)
            return unbounded("subscript of a runtime container")
        if isinstance(e, ast.NamedExpr):
            return self.prov_expr(mi, fi, e.value)
        return unbounded("unmodeled expression %s" % type(e).__name__)

    def _prov_attribute(self, mi: ModuleInfo, fi: Optional[FunctionInfo],
                        e: ast.Attribute) -> Optional[Prov]:
        if isinstance(e.value, ast.Name) and e.value.id == "self":
            if fi is None:
                return unbounded("self outside a method")
            cls = self._class_of(fi)
            sites = self._self_attrs.get(
                (mi.module.name, cls, e.attr)) if cls else None
            if not sites:
                return unbounded("unindexed attribute self.%s" % e.attr)
            acc: Optional[Prov] = None
            for smi, sfi, expr in sites:
                acc = join(acc, self.prov_expr(smi, sfi, expr))
            return acc
        base = self.prov_expr(mi, fi, e.value)
        if base is not None and base.label == "config-constant":
            owner = base.of.split(".")[0] if base.of else ""
            classes = ([owner] if owner in domains.CONFIG_CLASSES
                       else list(domains.CONFIG_CLASSES))
            for c in classes:
                dom = domains.CONFIG_FIELD_DOMAINS.get((c, e.attr))
                if dom is not None:
                    return Prov("registry-enumerated", frozenset(dom),
                                "audited domain of %s.%s" % (c, e.attr))
            # an undeclared field of a per-deployment constant is still a
            # per-deployment constant — just symbolic, never enumerated
            return Prov("config-constant", None,
                        "field of a config constant",
                        of="%s.%s" % (owner, e.attr) if owner else e.attr)
        owners = self._field_owner.get(e.attr, [])
        if owners and (base is None or not base.finite
                       or base.label == "const"):
            # joined across EVERY owning class's construction sites — a
            # sound over-approximation when a field name is shared (the
            # PreparedCycle/CycleContext `cfg` both carry the same value)
            acc: Optional[Prov] = None
            found = False
            for owner in owners:
                slots = self._ctor_args.get(owner, {}).get(e.attr)
                if slots:
                    for smi, sfi, expr in slots:
                        acc = join(acc, self.prov_expr(smi, sfi, expr))
                        found = True
                else:
                    dflt = self._field_default(owner, e.attr)
                    if isinstance(dflt, ast.Constant):
                        acc = join(acc, const((canon(dflt.value),)))
                        found = True
            if found:
                return acc
        if base is None:
            return None
        return unbounded("attribute .%s of %s value" % (e.attr, base.label))

    def _field_default(self, cls: str, field: str) -> Optional[ast.AST]:
        for mi in self.cg.mods.values():
            for stmt in mi.module.tree.body:
                if isinstance(stmt, ast.ClassDef) and stmt.name == cls:
                    for t in stmt.body:
                        if (isinstance(t, ast.AnnAssign)
                                and isinstance(t.target, ast.Name)
                                and t.target.id == field):
                            return t.value
        return None

    def _prov_call(self, mi: ModuleInfo, fi: Optional[FunctionInfo],
                   call: ast.Call) -> Optional[Prov]:
        dotted = self.cg.resolve_dotted(mi, call.func) or ""
        tail = dotted.rsplit(".", 1)[-1]
        if tail == "pow2_bucket":
            if call.args and _contains_arith(call.args[0]):
                return Prov("pad-capacity", None,
                            "pow2_bucket of a grown capacity")
            return Prov("pow2-bucketed", None, "pow2_bucket")
        if tail in domains.MESH_KEY_FUNCS:
            return Prov("mesh-key", None, "register_mesh token")
        if dotted in _BUILTIN_BOOL:
            return BOOL
        if dotted in _BUILTIN_PASS and call.args:
            return self.prov_expr(mi, fi, call.args[0])
        if dotted in _BUILTIN_JOINARGS:
            acc: Optional[Prov] = None
            for a in call.args:
                acc = join(acc, self.prov_expr(mi, fi, a))
            return acc
        if dotted == "len":
            return unbounded("len() of a runtime container")
        if (isinstance(call.func, ast.Attribute)
                and call.func.attr == "_replace"):
            base = self.prov_expr(mi, fi, call.func.value)
            if base is None or base.label == "config-constant":
                return base
        ctor = self._ctor_class(mi, call.func)
        if ctor is not None:
            if ctor in domains.CONFIG_CLASSES:
                return Prov("config-constant", None,
                            "constructed %s instance" % ctor, of=ctor)
            return unbounded("constructed %s instance" % ctor)
        callee, _bound = self._resolve_callee(mi, fi, call)
        if callee is not None:
            return self.return_prov(callee)
        return unbounded("unresolved call %s" % (dotted or "<expr>"))

    # ------------------------------------------------- names / params / ret

    def name_prov(self, mi: ModuleInfo, fi: Optional[FunctionInfo],
                  name: str) -> Optional[Prov]:
        key = (id(fi) if fi is not None else id(mi), name)
        hit = self._name_memo.get(key)
        if hit is _IN_PROGRESS:
            return None
        if hit is not None or key in self._name_memo:
            return hit
        self._name_memo[key] = _IN_PROGRESS
        try:
            out = self._name_prov_uncached(mi, fi, name)
        finally:
            self._name_memo[key] = None
        self._name_memo[key] = out
        return out

    def _name_prov_uncached(self, mi: ModuleInfo,
                            fi: Optional[FunctionInfo],
                            name: str) -> Optional[Prov]:
        acc: Optional[Prov] = None
        found = False
        scopes: List[Optional[FunctionInfo]] = [fi]
        if fi is not None:
            scopes += self.cg._function_scope_chain(mi, fi)
        for scope in scopes:
            if scope is None:
                continue
            if name in _params_of(scope.node):
                acc = join(acc, self.param_prov(scope, name))
                found = True
            for node in ast.walk(scope.node):
                if mi.module.enclosing_function(node) is not scope.node:
                    continue
                hit = self._assigned_expr(node, name)
                if hit is _IN_PROGRESS:     # widened target (loop, aug, …)
                    acc = join(acc, unbounded(
                        "widening assignment to %r" % name))
                    found = True
                elif hit is not None:
                    acc = join(acc, self.prov_expr(mi, scope, hit))
                    found = True
            if found:
                return acc
        if name in mi.module_consts:
            for stmt in mi.module.tree.body:
                if isinstance(stmt, ast.Assign):
                    for t in stmt.targets:
                        if isinstance(t, ast.Name) and t.id == name:
                            acc = join(acc, self.prov_expr(mi, None,
                                                           stmt.value))
                            found = True
                elif (isinstance(stmt, ast.AnnAssign)
                        and isinstance(stmt.target, ast.Name)
                        and stmt.target.id == name
                        and stmt.value is not None):
                    acc = join(acc, self.prov_expr(mi, None, stmt.value))
                    found = True
            if found:
                return acc
        return unbounded("unresolved name %r" % name)

    @staticmethod
    def _assigned_expr(node: ast.AST, name: str):
        """The assigned expression when ``node`` binds ``name`` exactly,
        ``_IN_PROGRESS`` when it binds it opaquely, else None."""
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == name:
                    return node.value
                if isinstance(t, (ast.Tuple, ast.List)) and any(
                        isinstance(e, ast.Name) and e.id == name
                        for e in t.elts):
                    # element-wise unpack when the RHS is a literal tuple
                    # of matching arity (the `a, b = (x, y)` idiom)
                    if (isinstance(node.value, (ast.Tuple, ast.List))
                            and len(node.value.elts) == len(t.elts)
                            and not any(isinstance(e, ast.Starred)
                                        for e in t.elts)):
                        for tgt, val in zip(t.elts, node.value.elts):
                            if isinstance(tgt, ast.Name) and tgt.id == name:
                                return val
                    return _IN_PROGRESS
        elif isinstance(node, ast.AnnAssign):
            if (isinstance(node.target, ast.Name)
                    and node.target.id == name):
                return node.value if node.value is not None else None
        elif isinstance(node, ast.AugAssign):
            if isinstance(node.target, ast.Name) and node.target.id == name:
                return _IN_PROGRESS
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            for t in ast.walk(node.target):
                if isinstance(t, ast.Name) and t.id == name:
                    return _IN_PROGRESS
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if item.optional_vars is not None:
                    for t in ast.walk(item.optional_vars):
                        if isinstance(t, ast.Name) and t.id == name:
                            return _IN_PROGRESS
        elif isinstance(node, ast.NamedExpr):
            if isinstance(node.target, ast.Name) and node.target.id == name:
                return node.value
        return None

    def name_defs(self, mi: ModuleInfo, fi: Optional[FunctionInfo],
                  name: str) -> List[Tuple[ModuleInfo,
                                           Optional[FunctionInfo],
                                           ast.AST]]:
        """The defining EXPRESSIONS of a name (assignments in the scope
        chain, call-site arguments and defaults when it is a parameter,
        module constants) — the expression-level mirror of name_prov,
        consumed by kubelint's recompile family for interprocedural
        shape/len tracing."""
        defs: List[Tuple[ModuleInfo, Optional[FunctionInfo], ast.AST]] = []
        scopes: List[Optional[FunctionInfo]] = [fi]
        if fi is not None:
            scopes += self.cg._function_scope_chain(mi, fi)
        for scope in scopes:
            if scope is None:
                continue
            found = False
            if name in _params_of(scope.node) + [
                    a.arg for a in scope.node.args.kwonlyargs]:
                found = True
                dflt = _default_expr(scope.node, name)
                for site in self._callsites.get(id(scope), []):
                    if name in site.bound:
                        defs.append((site.mi, site.caller,
                                     site.bound[name]))
                    elif not site.splat and dflt is not None:
                        defs.append((site.mi, None, dflt))
            for node in ast.walk(scope.node):
                if mi.module.enclosing_function(node) is not scope.node:
                    continue
                hit = self._assigned_expr(node, name)
                if hit is _IN_PROGRESS:
                    found = True             # opaque binding: no expr
                elif hit is not None:
                    defs.append((mi, scope, hit))
                    found = True
            if found:
                return defs
        for stmt in mi.module.tree.body:
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    if isinstance(t, ast.Name) and t.id == name:
                        defs.append((mi, None, stmt.value))
            elif (isinstance(stmt, ast.AnnAssign)
                    and isinstance(stmt.target, ast.Name)
                    and stmt.target.id == name and stmt.value is not None):
                defs.append((mi, None, stmt.value))
        return defs

    def resolve_name_exprs(self, mi: ModuleInfo,
                           fi: Optional[FunctionInfo], name: str,
                           limit: int = 64
                           ) -> List[Tuple[ModuleInfo,
                                           Optional[FunctionInfo],
                                           ast.AST]]:
        """Transitively resolve a name to its non-Name defining
        expressions across call boundaries (bounded, cycle-safe)."""
        out: List[Tuple[ModuleInfo, Optional[FunctionInfo], ast.AST]] = []
        seen = set()
        work = [(mi, fi, ast.Name(id=name))]
        while work and len(out) < limit:
            wmi, wfi, e = work.pop()
            if isinstance(e, ast.Name):
                key = (id(wfi) if wfi is not None else id(wmi), e.id)
                if key in seen:
                    continue
                seen.add(key)
                work.extend(self.name_defs(wmi, wfi, e.id))
            else:
                out.append((wmi, wfi, e))
        return out

    def param_prov(self, fi: FunctionInfo, pname: str) -> Optional[Prov]:
        key = (id(fi), pname)
        hit = self._param_memo.get(key)
        if hit is _IN_PROGRESS:
            return None
        if hit is not None or key in self._param_memo:
            return hit
        self._param_memo[key] = _IN_PROGRESS
        try:
            out = self._param_prov_uncached(fi, pname)
        finally:
            self._param_memo[key] = None
        self._param_memo[key] = out
        return out

    def _param_prov_uncached(self, fi: FunctionInfo,
                             pname: str) -> Optional[Prov]:
        if pname in ("self", "cls"):
            return unbounded("method receiver")
        ann = _annotation_of(fi.node, pname)
        ann_name = _last_attr(ann) if ann is not None else None
        if ann_name in domains.CONFIG_CLASSES:
            return Prov("config-constant", None,
                        "parameter annotated %s" % ann_name, of=ann_name)
        sites = self._callsites.get(id(fi), [])
        acc: Optional[Prov] = None
        if not sites:
            acc = unbounded("no analyzed call sites for %s(%s)"
                            % (fi.name, pname))
        dflt = _default_expr(fi.node, pname)
        for site in sites:
            if pname in site.bound:
                acc = join(acc, self.prov_expr(site.mi, site.caller,
                                               site.bound[pname]))
            elif site.splat:
                acc = join(acc, unbounded(
                    "splatted call site of %s" % fi.name))
            elif dflt is not None:
                acc = join(acc, self.prov_expr(site.mi, None, dflt))
            else:
                acc = join(acc, unbounded(
                    "unbound required parameter %s at a call site"
                    % pname))
        # a bool annotation is the declared contract: when the call-site
        # join widens (an unresolved caller, a method boundary), {True,
        # False} is still the sound finite domain — but a PRECISE join
        # (both serving sites pass True) is kept, not widened to BOOL
        if ann_name == "bool" and (acc is None or not acc.finite):
            return BOOL
        return acc

    def return_prov(self, fi: FunctionInfo) -> Optional[Prov]:
        key = id(fi)
        hit = self._ret_memo.get(key)
        if hit is _IN_PROGRESS:
            return None
        if hit is not None or key in self._ret_memo:
            return hit
        self._ret_memo[key] = _IN_PROGRESS
        try:
            out = self._return_prov_uncached(fi)
        finally:
            self._ret_memo[key] = None
        self._ret_memo[key] = out
        return out

    def _return_prov_uncached(self, fi: FunctionInfo) -> Optional[Prov]:
        mi = self.cg.mods[fi.module.name]
        acc: Optional[Prov] = None
        found = False
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Return):
                continue
            if mi.module.enclosing_function(node) is not fi.node:
                continue
            found = True
            if node.value is None:
                acc = join(acc, const(("None",), "bare return"))
            else:
                acc = join(acc, self.prov_expr(mi, fi, node.value))
        if not found:
            return const(("None",), "function never returns a value")
        return acc
