"""Dispatch-seam extraction: from ``aot.dispatch`` call sites (and the
declarative ``domains.EXTRA_ROOTS`` jit-pair entries) to per-program axis
tables the closure enumerates.

A seam's AXES are the degrees of freedom of its compiled-signature key:

* every STATIC position (``static_argnums`` indices into the seam's
  args-tuple, ``static_argnames`` keys into its kwargs-dict), carrying
  the interprocedural provenance join of the expression the seam passes;
* every optional dynamic kwarg whose jitted default is ``None`` — its
  PRESENCE flips the call treedef (utils/aot.py call_signature drops a
  None-for-None kwarg from the call), so {absent, present} is a closure
  axis even though the value itself is traced.

Axis classification:

* ``enumerated`` — the provenance carries an explicit value set (const /
  bool / registry-enumerated): crossed by the closure when multi-valued,
  recorded as ``fixed`` when single-valued;
* ``symbolic``   — finite without explicit values (config-constant,
  mesh-key, pow2-bucketed, pad-capacity): recorded, never crossed — the
  ladder/profile bound is the finiteness argument;
* anything else  — a ``close/unbounded-static`` problem, and an
  int-annotated static position whose finite class is neither a literal
  int set nor the pow2/pad ladder is ``close/unbucketed-shape``.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Tuple

from .engine import (ProvenanceEngine, _annotation_of, _default_expr,
                     _last_attr, _params_of, seam_kwarg_exprs)
from .lattice import FINITE_SYMBOLIC, Prov, presence


@dataclasses.dataclass
class SeamAxis:
    name: str
    kind: str                          # "static" | "presence"
    label: str                         # lattice label ("presence" axes: -)
    values: Optional[Tuple[str, ...]]  # sorted canonical reprs, or None
    why: str

    @property
    def enumerated(self) -> bool:
        return self.values is not None

    def to_json(self) -> dict:
        return {"kind": self.kind, "label": self.label,
                "values": list(self.values) if self.values is not None
                else None,
                "why": self.why}


@dataclasses.dataclass
class SeamProblem:
    rule: str                          # close/unbounded-static | ...
    program: str
    axis: str
    detail: str

    @property
    def key(self) -> str:
        return "%s %s" % (self.program, self.axis)


@dataclasses.dataclass
class Seam:
    program: str
    target: str                        # jitted callee qualname mod:fn
    site: str                          # path:lineno of the dispatch call
    axes: Dict[str, SeamAxis]
    problems: List[SeamProblem]


def _int_values(values) -> bool:
    for v in values:
        try:
            int(v)
        except ValueError:
            return False
    return True


def _static_names(call: ast.Call, params: List[str]) -> List[str]:
    names: List[str] = []
    for kw in call.keywords:
        if kw.arg == "static_argnums" and isinstance(kw.value,
                                                     (ast.Tuple, ast.List)):
            for el in kw.value.elts:
                if (isinstance(el, ast.Constant)
                        and isinstance(el.value, int)
                        and el.value < len(params)):
                    names.append(params[el.value])
        elif kw.arg == "static_argnames" and isinstance(
                kw.value, (ast.Tuple, ast.List)):
            for el in kw.value.elts:
                if isinstance(el, ast.Constant) and isinstance(el.value,
                                                               str):
                    names.append(el.value)
    return names


def _classify_static(program: str, name: str, p: Optional[Prov],
                     target_node, problems: List[SeamProblem]) -> SeamAxis:
    if p is None or p.label == "unbounded":
        problems.append(SeamProblem(
            "close/unbounded-static", program, name,
            "static position %r joins to unbounded provenance: %s"
            % (name, p.why if p is not None else "bottom (unreached)")))
        return SeamAxis(name, "static", "unbounded", None,
                        p.why if p is not None else "bottom")
    ann = _annotation_of(target_node, name)
    if _last_attr(ann) == "int" if ann is not None else False:
        ok = (p.label in ("pow2-bucketed", "pad-capacity")
              or (p.values is not None
                  and _int_values(p.values - frozenset(("None",)))))
        if not ok:
            problems.append(SeamProblem(
                "close/unbucketed-shape", program, name,
                "int static %r is %s — a shape-determining static must "
                "flow through pow2_bucket or be a literal ladder rung"
                % (name, p.label)))
    if p.enumerable:
        return SeamAxis(name, "static", p.label, tuple(sorted(p.values)),
                        p.why)
    if p.label in FINITE_SYMBOLIC:
        return SeamAxis(name, "static", p.label, None,
                        (p.of + ": " if p.of else "") + p.why)
    # finite label without values outside the symbolic classes (an
    # enumerable label that lost its set): treat as unbounded
    problems.append(SeamProblem(
        "close/unbounded-static", program, name,
        "static %r has finite label %r but no value set (%s)"
        % (name, p.label, p.why)))
    return SeamAxis(name, "static", "unbounded", None, p.why)


def collect(engine: ProvenanceEngine) -> Tuple[List[Seam],
                                               List[SeamProblem]]:
    """All dispatch seams plus EXTRA_ROOTS, with their axis tables."""
    from . import domains
    seams: List[Seam] = []
    orphan: List[SeamProblem] = []
    for mi, fi, call in engine.dispatch_calls():
        if not (call.args and isinstance(call.args[0], ast.Constant)
                and isinstance(call.args[0].value, str)):
            orphan.append(SeamProblem(
                "close/unbounded-static", "<unknown>", "program",
                "aot.dispatch with a non-literal program name at %s:%d"
                % (mi.module.path, call.lineno)))
            continue
        program = call.args[0].value
        target = engine.dispatch_target(mi, fi, call)
        if target is None:
            orphan.append(SeamProblem(
                "close/unbounded-static", program, "<target>",
                "cannot resolve the jitted callee of the %s seam" % program))
            continue
        params = _params_of(target.node)
        statics = _static_names(call, params)
        kwargs = seam_kwarg_exprs(call)
        pos: Dict[str, ast.AST] = {}
        if len(call.args) >= 3 and isinstance(call.args[2], ast.Tuple):
            for i, el in enumerate(call.args[2].elts):
                if i < len(params):
                    pos[params[i]] = el
        axes: Dict[str, SeamAxis] = {}
        problems: List[SeamProblem] = []
        for name in statics:
            expr = kwargs.get(name, pos.get(name))
            if expr is not None:
                p = engine.prov_expr(mi, fi, expr)
            else:
                dflt = _default_expr(target.node, name)
                p = (engine.prov_expr(mi, None, dflt)
                     if dflt is not None else None)
            axes[name] = _classify_static(program, name, p, target.node,
                                          problems)
        for name, expr in kwargs.items():
            if name in statics:
                continue
            dflt = _default_expr(target.node, name)
            if not (isinstance(dflt, ast.Constant) and dflt.value is None):
                continue   # always-materialized dynamic arg: no treedef axis
            pres = presence(engine.prov_expr(mi, fi, expr))
            axes[name] = SeamAxis(name, "presence", "presence", pres,
                                  "optional traced kwarg (None default "
                                  "drops from the call treedef)")
        seams.append(Seam(program, target.qualname,
                          "%s:%d" % (mi.module.path, call.lineno),
                          axes, problems))
    for root in domains.EXTRA_ROOTS:
        seams.append(_extra_root_seam(engine, root, orphan))
    return [s for s in seams if s is not None], orphan


def _extra_root_seam(engine: ProvenanceEngine, root: dict,
                     orphan: List[SeamProblem]) -> Optional[Seam]:
    program = root["program"]
    entry = engine._qualname.get(root["entry"])
    if entry is None:
        orphan.append(SeamProblem(
            "close/unbounded-static", program, "<entry>",
            "EXTRA_ROOTS entry %s not found in the analyzed set"
            % root["entry"]))
        return None
    axes: Dict[str, SeamAxis] = {}
    problems: List[SeamProblem] = []
    for axis, pname in root.get("axes", {}).items():
        p = engine.param_prov(entry, pname)
        axes[axis] = _classify_static(program, axis, p, entry.node,
                                      problems)
    for axis, label in root.get("symbolic", {}).items():
        axes[axis] = SeamAxis(axis, "static", label, None,
                              "declared symbolic axis (domains.EXTRA_ROOTS)")
    mi = engine.cg.mods[entry.module.name]
    return Seam(program, entry.qualname,
                "%s:%d" % (mi.module.path, entry.node.lineno),
                axes, problems)
