"""The provenance lattice.

Every value that can reach a dispatch seam in a static or
shape-determining position gets a ``Prov``: a label naming its finiteness
class, an optional explicit value set when the class is enumerable, and a
``why`` trail for findings.  Labels, least to greatest:

    const                literal constant(s); ``values`` enumerates them
    bool                 a boolean expression: {True, False}
    registry-enumerated  drawn from a finite in-package vocabulary (a
                         helper whose every return is a literal, an
                         audited config-field domain)
    config-constant      a field/instance of an audited config class
                         (ProgramConfig / KubeSchedulerConfiguration):
                         finite per deployment, symbolic to the prover;
                         ``of`` carries the class name
    mesh-key             a ``register_mesh`` token: one per mesh shape,
                         bounded by the deployment's mesh profiles
    pow2-bucketed        flows through ``utils.intern.pow2_bucket``:
                         member of the pow2 ladder, bounded at north-star
    pad-capacity         ``pow2_bucket`` of a grown capacity (the
                         ``P + B`` pad idiom): the pad ladder, a
                         pow2-bucketed subclass kept distinct because its
                         rungs RUN AHEAD of the current world size
    unbounded            everything else — not provably finite

The join is label-max with value-set union; ``unbounded`` absorbs.  A
join of two enumerable labels stays enumerable (const ⊔ bool and
const ⊔ registry-enumerated are registry-enumerated), which is what lets
``kernel_backend or "lax"`` or a helper returning one of two literals
enumerate instead of widening.

No jax imports anywhere in this package: the full prover runs in the
no-jax CI gate.
"""

from __future__ import annotations

import dataclasses
from typing import FrozenSet, Optional, Tuple

LABELS: Tuple[str, ...] = (
    "const", "bool", "registry-enumerated", "config-constant", "mesh-key",
    "pow2-bucketed", "pad-capacity", "unbounded",
)
_ORDER = {lbl: i for i, lbl in enumerate(LABELS)}

# labels whose value set is explicitly enumerable
_ENUMERABLE = ("const", "bool", "registry-enumerated")

# labels that are finite (closure-safe) without explicit values
FINITE_SYMBOLIC = ("config-constant", "mesh-key", "pow2-bucketed",
                   "pad-capacity")

# canonical reprs jit/Python treat as falsy — dropped by `x or default`
FALSY = frozenset(("None", "False", "0", "0.0", "''", '""'))


@dataclasses.dataclass(frozen=True)
class Prov:
    label: str
    values: Optional[FrozenSet[str]] = None   # canonical reprs, or None
    why: str = ""
    of: str = ""                              # config class for c-c labels

    @property
    def finite(self) -> bool:
        return self.label != "unbounded"

    @property
    def enumerable(self) -> bool:
        return self.label in _ENUMERABLE and self.values is not None

    def to_json(self) -> dict:
        d = {"label": self.label,
             "values": sorted(self.values) if self.values is not None
             else None,
             "why": self.why}
        if self.of:
            d["of"] = self.of
        return d


BOOL = Prov("bool", frozenset(("True", "False")), "boolean expression")
UNBOUNDED = Prov("unbounded", None, "unknown")


def const(values, why: str = "literal") -> Prov:
    return Prov("const", frozenset(values), why)


def unbounded(why: str) -> Prov:
    return Prov("unbounded", None, why)


def canon(v) -> str:
    """Canonical repr used for value sets, closure axes, and the
    registry's ``closure_statics`` metadata — plain ``repr`` so True /
    512 / 'lax' / None all round-trip through JSON as strings."""
    return repr(v)


def join(a: Optional[Prov], b: Optional[Prov]) -> Optional[Prov]:
    """Least upper bound.  ``None`` is bottom (an unanalyzed branch)."""
    if a is None:
        return b
    if b is None:
        return a
    if a.label == "unbounded":
        return a
    if b.label == "unbounded":
        return b
    lo, hi = (a, b) if _ORDER[a.label] <= _ORDER[b.label] else (b, a)
    if hi.label in _ENUMERABLE:
        # both enumerable: keep the values if both carry them
        values = (a.values | b.values
                  if a.values is not None and b.values is not None
                  else None)
        label = a.label if a.label == b.label else "registry-enumerated"
        if values is None:
            return Prov("unbounded", None,
                        "enumerable label without a value set (%s | %s)"
                        % (a.why, b.why))
        return Prov(label, values, _merge_why(a.why, b.why))
    if hi.label == "config-constant" and lo.label in _ENUMERABLE:
        # a config field joined with a literal default stays the field
        return hi
    if hi.label in ("pow2-bucketed", "pad-capacity", "mesh-key"):
        # a literal default (0, None) joined into a ladder class stays
        # the ladder class — the default is one more rung, not a widening
        if lo.label in _ENUMERABLE or lo.label == hi.label:
            return Prov(hi.label, None, _merge_why(a.why, b.why), hi.of)
        if lo.label in ("pow2-bucketed", "pad-capacity"):
            return Prov("pad-capacity", None, _merge_why(a.why, b.why))
        return Prov("unbounded", None,
                    "incomparable finite classes: %s | %s"
                    % (a.label, b.label))
    if a.label == b.label:
        return Prov(a.label, None, _merge_why(a.why, b.why), a.of)
    return Prov("unbounded", None,
                "incomparable finite classes: %s | %s" % (a.label, b.label))


def _merge_why(a: str, b: str) -> str:
    if not a or a == b:
        return b
    if not b:
        return a
    return "%s | %s" % (a, b)


def drop_falsy(p: Prov) -> Prov:
    """The left side of ``x or default``: its falsy members never reach
    the result."""
    if p.values is None:
        return p
    kept = frozenset(v for v in p.values if v not in FALSY)
    return dataclasses.replace(p, values=kept)


def presence(p: Optional[Prov]) -> Tuple[str, ...]:
    """The {present, absent} axis of an optional dynamic argument
    (host_ok / score_bias / tie_index): a literal None is absent, a
    maybe-None join is both, anything else is present.  Presence changes
    the dispatched program (the call treedef), so it is a closure axis
    even though the argument itself is traced, not static."""
    if p is None:
        return ("absent",)
    if p.values is not None:
        has_none = "None" in p.values
        has_val = bool(p.values - frozenset(("None",)))
        if has_none and has_val:
            return ("absent", "present")
        if has_none:
            return ("absent",)
        return ("present",)
    # non-enumerable (an array, a config product, an unbounded join):
    # conservatively both — the seam's default None keeps absent live
    return ("absent", "present")
