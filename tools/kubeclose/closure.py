"""The closure proof: enumerate every seam's reachable signature set at
the committed north-star environment, join it against the kubecensus
registry's ``closure_statics`` coverage metadata, and emit ``close/*``
findings for whatever falls outside.

The registry is read by AST (tools/kubecensus/registry.py imports jax
transitively; this package never does): ``Entry(...)`` rows of the
``ENTRIES`` list yield (program, tag, closure_statics).  Matching is
exact equality on the combo's CROSS axes — an entry must pin every
multi-valued axis of its program; single-valued axes are fixed by the
proof itself and symbolic axes (cfg, mesh keys, pad ladders) are finite
by construction, so neither splits the combo space.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from itertools import product
from typing import Dict, List, Optional, Sequence, Tuple

from . import domains
from .seams import Seam, SeamProblem

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
REGISTRY_PATH = os.path.join(REPO_ROOT, "tools", "kubecensus",
                             "registry.py")


@dataclasses.dataclass
class Finding:
    rule: str
    key: str
    message: str

    def to_json(self) -> dict:
        return {"rule": self.rule, "key": self.key,
                "message": self.message}


@dataclasses.dataclass
class Combo:
    key: str
    assignment: Dict[str, str]          # cross axis -> value
    coverage: str                       # "registry:<key>" | "exempt" | ""
    reason: str = ""

    def to_json(self) -> dict:
        return {"assignment": self.assignment, "coverage": self.coverage,
                "reason": self.reason}


@dataclasses.dataclass
class ProgramClosure:
    seam: Seam
    fixed: Dict[str, str]
    symbolic: Dict[str, str]
    combos: List[Combo]


@dataclasses.dataclass
class ClosureResult:
    programs: List[ProgramClosure]
    findings: List[Finding]             # unexempted
    exempted: List[Finding]             # carried by domains.EXEMPTIONS
    orphans: List[SeamProblem]


# -------------------------------------------------- registry (AST, no jax)

def registry_entries(path: str = REGISTRY_PATH
                     ) -> List[Tuple[str, str, Dict[str, str]]]:
    """(program, tag, closure_statics dict) for every ENTRIES row."""
    with open(path, "r", encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    rows: List[Tuple[str, str, Dict[str, str]]] = []
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign):
            targets = stmt.targets
        elif isinstance(stmt, ast.AnnAssign):
            targets = [stmt.target]
        else:
            continue
        if not (any(isinstance(t, ast.Name) and t.id == "ENTRIES"
                    for t in targets)
                and isinstance(stmt.value, ast.List)):
            continue
        for el in stmt.value.elts:
            if not (isinstance(el, ast.Call)
                    and isinstance(el.func, ast.Name)
                    and el.func.id == "Entry" and el.args
                    and isinstance(el.args[0], ast.Constant)):
                continue
            program = el.args[0].value
            tag = ""
            statics: Dict[str, str] = {}
            for kw in el.keywords:
                if kw.arg == "tag" and isinstance(kw.value, ast.Constant):
                    tag = kw.value.value
                elif kw.arg == "closure_statics" and isinstance(
                        kw.value, (ast.Tuple, ast.List)):
                    for pair in kw.value.elts:
                        if (isinstance(pair, (ast.Tuple, ast.List))
                                and len(pair.elts) == 2
                                and all(isinstance(p, ast.Constant)
                                        for p in pair.elts)):
                            statics[pair.elts[0].value] = pair.elts[1].value
            rows.append((program, tag, statics))
    return rows


def entry_key(program: str, tag: str) -> str:
    return program + (":" + tag if tag else "")


# ------------------------------------------------------------ enumeration

def combo_key(program: str, assignment: Dict[str, str]) -> str:
    parts = ["%s=%s" % (a, assignment[a]) for a in sorted(assignment)]
    return " ".join([program] + parts)


def enumerate_program(seam: Seam) -> ProgramClosure:
    fixed: Dict[str, str] = {}
    symbolic: Dict[str, str] = {}
    cross: List[Tuple[str, Tuple[str, ...]]] = []
    for name in sorted(seam.axes):
        ax = seam.axes[name]
        if ax.values is None:
            symbolic[name] = ax.label
        elif len(ax.values) <= 1:
            fixed[name] = ax.values[0] if ax.values else "<none>"
        else:
            cross.append((name, ax.values))
    combos: List[Combo] = []
    names = [n for n, _ in cross]
    for values in product(*(v for _, v in cross)):
        assignment = dict(zip(names, values))
        combos.append(Combo(combo_key(seam.program, assignment),
                            assignment, ""))
    return ProgramClosure(seam, fixed, symbolic, combos)


# --------------------------------------------------------------- coverage

def prove(seams: Sequence[Seam], orphans: Sequence[SeamProblem],
          registry_path: str = REGISTRY_PATH) -> ClosureResult:
    entries = registry_entries(registry_path)
    programs = [enumerate_program(s) for s in seams]
    raw: List[Finding] = []
    for s in seams:
        for pr in s.problems:
            raw.append(Finding(pr.rule, pr.key, pr.detail))
    for pr in orphans:
        raw.append(Finding(pr.rule, pr.key, pr.detail))

    closure_programs = {p.seam.program for p in programs}
    matched_entries = set()
    for pc in programs:
        own = [(prog, tag, st) for prog, tag, st in entries
               if prog == pc.seam.program]
        for combo in pc.combos:
            # an entry covers a combo iff it pins every CROSS axis with
            # the combo's value AND every axis the entry names agrees
            # with the combo's full (fixed + crossed) assignment — a rung
            # pinning a value the proof fixed differently is not coverage
            full = dict(pc.fixed)
            full.update(combo.assignment)
            hit = None
            for prog, tag, st in own:
                if (all(a in st and st[a] == v
                        for a, v in combo.assignment.items())
                        and all(full.get(a) == v
                                for a, v in st.items())):
                    hit = (prog, tag)
                    break
            if hit is not None:
                combo.coverage = "registry:" + entry_key(*hit)
                matched_entries.add(hit)
            else:
                raw.append(Finding(
                    "close/uncaptured-signature", combo.key,
                    "reachable signature of %s has no registry row: a "
                    "cold-start compile stall unless a fallback path is "
                    "exempted" % pc.seam.program))
    # after EVERY seam of every program has matched (a program can have
    # several seams): a registry rung of a proved program that no
    # enumerated combo selected is dead
    for prog, tag, st in entries:
        if (prog in closure_programs and st
                and (prog, tag) not in matched_entries):
            raw.append(Finding(
                "close/unreachable-manifest-row",
                entry_key(prog, tag),
                "registry entry %s matches no enumerated reachable "
                "signature of %s — a dead ladder rung"
                % (entry_key(prog, tag), prog)))

    exmap = {(rule, key): reason
             for rule, key, reason in domains.EXEMPTIONS}
    consumed = set()
    findings: List[Finding] = []
    exempted: List[Finding] = []
    for f in raw:
        reason = exmap.get((f.rule, f.key))
        if reason is not None:
            consumed.add((f.rule, f.key))
            exempted.append(Finding(f.rule, f.key, reason))
        else:
            findings.append(f)
    for (rule, key), reason in sorted(exmap.items()):
        if (rule, key) not in consumed:
            findings.append(Finding(
                "close/stale-exemption", "%s %s" % (rule, key),
                "exemption matches no finding — remove it from "
                "tools/kubeclose/domains.py (was: %s)" % reason))
    # exempted combos get their coverage stamped for the manifest
    exkeys = {key for (rule, key) in exmap
              if rule == "close/uncaptured-signature"
              and (rule, key) in consumed}
    for pc in programs:
        for combo in pc.combos:
            if not combo.coverage and combo.key in exkeys:
                combo.coverage = "exempt"
                combo.reason = exmap[("close/uncaptured-signature",
                                      combo.key)]
    findings.sort(key=lambda f: (f.rule, f.key))
    exempted.sort(key=lambda f: (f.rule, f.key))
    return ClosureResult(programs, findings, exempted, list(orphans))


def run(root: str = REPO_ROOT) -> ClosureResult:
    """Load kubetpu, build the engine, extract seams, prove closure."""
    from tools.kubelint.core import load_modules
    from . import seams as seams_mod
    from .engine import ProvenanceEngine
    modules = load_modules([os.path.join(root, "kubetpu")], root=root)
    engine = ProvenanceEngine(modules)
    seam_list, orphans = seams_mod.collect(engine)
    seam_list.sort(key=lambda s: s.program)
    return prove(seam_list, orphans)
