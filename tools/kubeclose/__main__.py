"""CLI: ``python -m tools.kubeclose``.

Default: re-prove the closure over the tree (pure AST — still no jax),
print findings, and fail on drift against the committed
CLOSURE_MANIFEST.json in either direction.  ``--write`` regenerates the
committed file (byte-identical over an unchanged tree); ``--check``
re-validates the committed JSON alone without parsing kubetpu.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="kubeclose",
        description="interprocedural compile-surface closure prover")
    ap.add_argument("--root", default=None,
                    help="repository root (default: auto-detected)")
    ap.add_argument("--write", action="store_true",
                    help="regenerate CLOSURE_MANIFEST.json")
    ap.add_argument("--check", action="store_true",
                    help="pure-JSON validation of the committed manifest "
                         "(no kubetpu parse)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output for CI")
    args = ap.parse_args(argv)

    from . import closure, manifest

    if args.check:
        fails = manifest.check_manifest(manifest.load_manifest())
        if args.json:
            print(json.dumps({"failures": fails}, indent=1,
                             sort_keys=True))
        else:
            for f in fails:
                print("close: FAIL %s" % f)
            if not fails:
                print("kubeclose --check: committed closure OK")
        return 1 if fails else 0

    res = closure.run(args.root or closure.REPO_ROOT)
    doc = manifest.build_manifest(res)

    if args.write:
        path = manifest.write_manifest(doc)
        print("wrote %s (%d programs, %d combos, %d covered, %d exempt, "
              "%d findings)"
              % (path, doc["counts"]["programs"], doc["counts"]["combos"],
                 doc["counts"]["covered"], doc["counts"]["exempt"],
                 doc["counts"]["findings"]))
        return 1 if res.findings else 0

    drift = manifest.diff_manifest(doc, manifest.load_manifest())
    drifted = bool(drift.get("added") or drift.get("removed")
                   or drift.get("changed")
                   or drift.get("missing_manifest"))
    if args.json:
        print(json.dumps({
            "findings": [f.to_json() for f in res.findings],
            "exemptions": [f.to_json() for f in res.exempted],
            "counts": doc["counts"],
            "drift": drift,
        }, indent=1, sort_keys=True))
        return 1 if (res.findings or drifted) else 0

    for f in res.findings:
        print("%s: %s\n    %s" % (f.rule, f.key, f.message))
    print("kubeclose: %d program(s), %d combo(s) (%d registry-covered, "
          "%d exempt), %d finding(s), %d exemption(s) consumed"
          % (doc["counts"]["programs"], doc["counts"]["combos"],
             doc["counts"]["covered"], doc["counts"]["exempt"],
             len(res.findings), len(res.exempted)))
    if drift.get("missing_manifest"):
        print("close: DRIFT no committed CLOSURE_MANIFEST.json — run "
              "make close")
    for k in drift.get("added", []):
        print("close: DRIFT program %s proved but not committed — run "
              "make close" % k)
    for k in drift.get("removed", []):
        print("close: DRIFT committed program %s no longer proved — run "
              "make close" % k)
    for k in drift.get("changed", []):
        print("close: DRIFT %s changed vs committed manifest — run "
              "make close" % k)
    return 1 if (res.findings or drifted) else 0


if __name__ == "__main__":
    sys.exit(main())
