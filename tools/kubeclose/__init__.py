"""kubeclose: the interprocedural compile-surface closure prover.

The fourth static-analysis layer (after kubelint, kubecensus and
kubeexact): an abstract interpretation over the HOST Python that tracks
the provenance of every value reaching a dispatch seam (the
``aot.dispatch``-seamed serving programs, raw ``jit`` roots,
``pallas_call`` grids) in a shape-determining or static-arg position,
with a lattice over {const, bool, config-constant, registry-enumerated,
mesh-key, pad-capacity, pow2-bucketed, unbounded} propagated through
calls, returns, dataclass fields, and the scheduler's
``_prepare_group``/``_dispatch_group``/pipeline-ring plumbing.

From the proved-finite provenance it ENUMERATES the reachable signature
set of each seamed program at the committed north-star environment and
commits it as ``CLOSURE_MANIFEST.json``: every enumerated signature is
either covered by a kubecensus registry entry (and hence a
COMPILE_MANIFEST row and, for the seamed programs, an AOT_INDEX
artifact) or carried by a structured exemption naming its fallback
path.  An uncaptured-but-reachable signature is a cold-start compile
stall on the v5e run; a captured-but-unreachable row is a dead ladder
rung — both are findings.

The whole prover is pure AST + JSON: it never imports jax, so the full
proof (not just the committed-file ``--check``) runs in the no-jax CI
gate.  Rule family ``close/*``:

    close/unbounded-static          a static position whose provenance
                                    join is unbounded (not provably
                                    finite at north-star shapes)
    close/unbucketed-shape          a shape-derived static position that
                                    does not flow through pow2_bucket
                                    anywhere along its interprocedural
                                    dataflow
    close/uncaptured-signature      an enumerated reachable signature no
                                    registry entry covers and no
                                    exemption carries
    close/unreachable-manifest-row  a registry entry of a seamed program
                                    that no enumerated signature matches
    close/stale-exemption           a domains.py exemption that matches
                                    no finding (ages out, like
                                    kubeexact's)
"""
