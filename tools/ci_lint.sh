#!/bin/sh
# CI lint gate: kubelint in JSON mode, nonzero exit on any unsuppressed
# finding.  Covers all seven rule families — host-sync, recompile,
# numeric, purity, exact (raw lax collectives / raw tie-argmax must
# route through the blessed ops/kernels.py helpers so tools/kubeexact
# can prove the reduction surface),
# concurrency (lock discipline for the threaded host path,
# including the flight-recorder classes: utils/trace.py FlightRecorder /
# CycleRecord and utils/decisions.py DecisionLog are guarded-by annotated
# and must stay tree-clean), and delta (incremental-tensorization
# discipline: no full re-tensorize/device_put reachable from the cycle
# loop outside the blessed DeltaTensorizer resync path).  Builders run
# this by default via `make lint`; the same check gates tier-1 through
# tests/test_kubelint.py::test_kubetpu_tree_is_clean.
set -e
cd "$(dirname "$0")/.."
python -m tools.kubelint kubetpu/ --json
# explicit concurrency-family pass over the observability layer: the new
# lock-guarded recorder/audit classes must be clean on their own, so a
# future refactor can't hide a violation behind an unrelated suppression.
# The chaos registry rides the same pass: its fire counters are
# guarded-by annotated and its decide/act split must never sleep or
# raise under the lock (blocking-under-lock).  The SLO tracker
# (utils/slo.py) joins it: its sketch/exemplar state is guarded-by
# annotated and observed from both the serving thread and binder pool.
# The depth-k pipelined executor (kubetpu/pipeline.py) joins it too: its
# in-flight ring is guarded-by annotated, and no device dispatch,
# readback or sleep may ever run under the ring lock.  The durable cycle
# journal (utils/journal.py) joins it: its file-index/counter state is
# guarded-by annotated and record I/O runs outside the lock
# devstats (utils/devstats.py) joins it: per-program timing + ledger
# state is guarded-by annotated, and every record seam does its shape
# walks / byte sums OUTSIDE the lock
# The shard_map mesh module (kubetpu/parallel/shardmap.py) joins it:
# its trace-time Mesh registry is guarded-by annotated and read only at
# trace time (never under a traced computation)
# The telemetry ring (utils/telemetry.py) joins it: its window deque is
# guarded-by annotated, the roll gathers run under a separate roll lock
# (never the ring lock), and the disarmed hot path takes zero locks
python -m tools.kubelint kubetpu/utils/trace.py kubetpu/utils/decisions.py \
	kubetpu/utils/chaos.py kubetpu/utils/slo.py kubetpu/pipeline.py \
	kubetpu/utils/journal.py kubetpu/utils/devstats.py \
	kubetpu/parallel/shardmap.py kubetpu/utils/telemetry.py \
	--rules concurrency --json
# explicit delta-family pass over the serving loop: the cycle path must
# stay scatter-only (full-retensorize-in-loop), independent of any
# unrelated suppression elsewhere in the tree.  The pipelined executor
# rides along — its drain is the cycle loop now.  journal.py rides too:
# it reads the resident mirror at commit and must never re-tensorize
# parallel/shardmap.py rides the delta pass too: the mesh dispatch
# wrappers sit on the cycle path and must never re-tensorize or
# re-device_put the resident cluster outside the blessed seams
python -m tools.kubelint kubetpu/scheduler.py kubetpu/pipeline.py \
	kubetpu/utils/journal.py kubetpu/parallel/shardmap.py \
	--rules delta --json
# compile-surface census (tools/kubecensus): jaxpr-level abstract
# interpretation of every jit root.  Fails on (a) any unsuppressed
# census finding — donation-unconsumed, f64-promotion, host-callback,
# rank-promotion, constant-capture, unregistered-root — and (b) DRIFT
# against the committed COMPILE_MANIFEST.json in either direction: a
# traced variant the manifest lacks, or a committed row no trace
# reproduces (a dead ladder bucket).  Regenerate after an intentional
# surface change: make census (python -m tools.kubecensus --write).
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m tools.kubecensus --check --json
# AOT artifact index gate (tools/kubeaot --check, pure JSON, no jax):
# the committed AOT_INDEX.json and COMPILE_MANIFEST.json must share the
# same census-family row keys in BOTH directions — an artifact with no
# manifest row, or a manifest row with no artifact at census rungs,
# fails.  Regenerate after an intentional surface change: make aot.
python -m tools.kubeaot --check --json
# Compile-surface closure gate, pure-JSON half (tools/kubeclose --check,
# no jax): the committed CLOSURE_MANIFEST.json must carry zero findings
# and zero unbounded axes, pin the northstar environment byte-equal to
# tools/kubeexact/northstar.py, resolve every registry coverage pointer
# to a COMPILE_MANIFEST.json row, give every exempt combo a reason
# naming its fallback path, and cover every AOT_INDEX.json program.
python -m tools.kubeclose --check --json
# Compile-surface closure, full prover (still no jax — pure AST over
# kubetpu/): re-proves the closure interprocedurally, enumerates every
# reachable dispatch signature at the committed north-star environment,
# and fails on any close/* finding (unbounded-static, unbucketed-shape,
# uncaptured-signature, unreachable-manifest-row, stale-exemption) or
# DRIFT against the committed CLOSURE_MANIFEST.json in either direction.
# Regenerate after an intentional seam change: make close.
python -m tools.kubeclose --json
# Exactness manifest gate, pure-JSON half (tools/kubeexact --check, no
# jax): the committed EXACT_MANIFEST.json must pin the northstar
# environment and constants, keep every proof exact/exempt with margin
# above the 4x floor, re-derive its VMEM totals from the committed
# buffer rows, and name only programs COMPILE_MANIFEST.json licenses.
python -m tools.kubeexact --check --json
# Pallas megakernel bit-match oracle (ops/pallas_kernels.py): the
# interpret-mode differential suite on CPU — lax vs pallas GangResults
# must be bit-identical on randomized churned clusters, the committed
# golden worlds, and the fallback routings.  Also covers the two new
# kubelint pallas checks (recompile/pallas-dynamic-grid,
# purity/pallas-host-callback) via tests/test_kubelint.py above.
# Environments without jax.experimental.pallas degrade to a REASONED
# pytest skip (the suite's module-level skipif), never a failure.
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m pytest \
	tests/test_pallas_gang.py -q -m 'not slow' -p no:cacheprovider
# Pod-axis mesh scale-out (kubetpu/parallel/shardmap.py): the explicit
# shard_map auction/scan vs the single-device oracle on the 8-virtual-CPU
# mesh — sharded-vs-unsharded bit-identity at the previously env-gated
# (2,4)/(4,2) shapes (tiled + replicated surfaces, windowed rounds, the
# serving path with the double-buffered batch upload and the pre-sharded
# delta scatter).  The legacy gspmd lowering keeps its documented
# env-gated skip inside the suite.
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m pytest \
	tests/test_mesh.py -q -m 'not slow' -p no:cacheprovider
# Chaos harness + self-healing runtime (utils/chaos.py): every named
# injection point's seeded recovery scenario — serving thread alive, no
# lost pods, no double binds, mirror/device fingerprint match after
# induced faults — and the disarmed-no-op poison test (a disarmed run
# adds zero locks and zero readbacks to the hot path).
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m pytest \
	tests/test_chaos.py -q -m 'not slow' -p no:cacheprovider
# Per-pod latency SLO layer (utils/slo.py): quantile-sketch property vs
# numpy.percentile, bounded memory, the disarmed zero-lock poison test,
# /debug/slo round trip, exemplar->flight-record linkage, and the
# armed-vs-disarmed placement-parity golden.
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m pytest \
	tests/test_slo.py -q -m 'not slow' -p no:cacheprovider
# Sustained-load telemetry plane (utils/telemetry.py + the open-loop
# harness streams in kubetpu/harness/hollow.py + perf.py's
# SustainedLoadRunner): window-delta merge exactness vs the numpy order
# statistic, ring wrap + drop counting, the disarmed zero-cost poison
# test, the armed-vs-disarmed placement-parity golden, seeded
# chaos-storm attribution to the firing window, /debug/loadz round
# trip, and the /metrics scheduler_load_* window series.
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m pytest \
	tests/test_telemetry.py -q -m 'not slow' -p no:cacheprovider
# Depth-k pipelined executor (kubetpu/pipeline.py): depth-parity
# placement goldens (depth 1 == 2 == 4 bit-identical), the
# gather-window/free-slot gate, per-slot exemption accounting, ring-slot
# flight tags, and the flush semantics.
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m pytest \
	tests/test_pipeline.py -q -m 'not slow' -p no:cacheprovider
# Durable cycle journal (kubetpu/utils/journal.py): record framing +
# size-cap eviction counting, the chaos journal point's degrade-to-drop
# write contract, the disarmed zero-lock poison test, and the
# armed-vs-disarmed placement parity golden.
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m pytest \
	tests/test_journal.py -q -m 'not slow' -p no:cacheprovider
# Bit-exact replay rig (tools/kubereplay): the journaled-drain replay
# oracle (byte-identical packed placements incl. delta cycles, resyncs
# and a depth-4 pipelined segment), per-record corrupt-skip reasons, and
# the counterfactual contracts (score-weight nonzero / pipelineDepth
# zero divergence).
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m pytest \
	tests/test_replay.py -q -m 'not slow' -p no:cacheprovider
# Device-side observability (kubetpu/utils/devstats.py): sampled
# deep-timing fences measure per-program device time, the residency
# ledger feeds the capacity planner (projection vs measured bytes must
# agree within 10% at bench shapes), the roofline join resolves against
# COMPILE_MANIFEST.json, and the house contract holds (disarmed zero-
# lock poison test, armed-vs-disarmed placement parity golden).
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m pytest \
	tests/test_devstats.py -q -m 'not slow' -p no:cacheprovider
# Exactness prover gate, full half (tools/kubeexact): re-traces every
# exact-marked mesh/Pallas root, re-proves each cross-shard/cross-tile
# reduction exact (float max/min or int-valued sum < 2**24 via the
# integer-valuedness + interval lattice), re-enumerates the collective
# surface and the Pallas VMEM budget, and fails on any unsuppressed
# exact/* finding, a stale exemption, or DRIFT against the committed
# EXACT_MANIFEST.json in either direction.  Regenerate after an
# intentional change: make exact (python -m tools.kubeexact --write).
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m tools.kubeexact --json
# Exactness prover suite: every prover rule fires on a seeded bad
# snippet (non-integer f32 psum, out-of-range sum, shard_map row-
# gather, raw tie-argmax, VMEM over budget), clean snippets stay empty,
# manifest regeneration is byte-identical, the drift gate sees both
# directions, and exemption staleness is audited.
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m pytest \
	tests/test_kubeexact.py -q -m 'not slow' -p no:cacheprovider
# Closure prover suite: every close/* rule fires on a seeded bad snippet
# and stays quiet on the good twin, the committed CLOSURE_MANIFEST.json
# regenerates byte-identically, drift is seen in both directions, the
# --check gate runs under a jax import blocker, stale exemptions fire,
# and a churned pipelined drain's dispatched seam signatures are all
# members of the committed closure.
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m pytest \
	tests/test_kubeclose.py -q -m 'not slow' -p no:cacheprovider
# Bench-trend CI check (tools/benchtrend.py, pure JSON, no jax): the
# committed BENCH_r*/MULTICHIP_r* trajectory must stay schema-compatible
# with the trend tooling, and the newest parseable round must not
# regress beyond the NORTHSTAR.json gate floors/ceilings.
python -m tools.benchtrend --check
