#!/bin/sh
# CI lint gate: kubelint in JSON mode, nonzero exit on any unsuppressed
# finding.  Covers all five rule families — host-sync, recompile, numeric,
# purity, and concurrency (lock discipline for the threaded host path).
# Builders run this by default via `make lint`; the same check gates
# tier-1 through tests/test_kubelint.py::test_kubetpu_tree_is_clean.
set -e
cd "$(dirname "$0")/.."
python -m tools.kubelint kubetpu/ --json
