"""Profile the gang auction device program across node scales.

Measures, for the IPA-heavy north-star workload at fixed B=4096 pending:
  - steady-state device time per cycle (readback-observed; block_until_ready
    is a no-op through the axon tunnel)
  - auction round count (the while_loop trip count)
  - per-round device time (device_s / rounds)

Usage: python tools/profile_gang.py [nodes ...]
"""
from __future__ import annotations

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kubetpu.utils.compilation import enable_persistent_cache

enable_persistent_cache()

import jax  # noqa: E402

from bench import build_world  # noqa: E402
from kubetpu.api import types as api  # noqa: E402
from kubetpu.framework.types import PodInfo  # noqa: E402
from kubetpu.models import programs  # noqa: E402
from kubetpu.models.batch import PodBatchBuilder  # noqa: E402
from kubetpu.models.gang import schedule_gang  # noqa: E402
from kubetpu.scheduler import Scheduler  # noqa: E402
from kubetpu.state.tensors import SnapshotBuilder  # noqa: E402
from kubetpu.apis.config import (KubeSchedulerConfiguration,  # noqa: E402
                                 KubeSchedulerProfile)


def profile_shape(n_nodes: int, n_pods: int = 4096, ipa_heavy: bool = True):
    store, pending = build_world(n_nodes, n_pods, existing_per_node=1,
                                 ipa_heavy=ipa_heavy)
    cfg_k = KubeSchedulerConfiguration(profiles=[KubeSchedulerProfile()],
                                       batch_size=n_pods, mode="gang")
    sched = Scheduler(store, config=cfg_k, async_binding=False)
    sched.cache.update_snapshot(sched.snapshot)
    node_infos = sched.snapshot.node_info_list
    fwk = next(iter(sched.profiles.values()))
    pinfos = [PodInfo(p) for p in pending]
    sb = SnapshotBuilder(hard_pod_affinity_weight=fwk.hard_pod_affinity_weight)
    sb.intern_pending(pinfos)
    cluster = sb.build(node_infos).to_device()
    batch = jax.tree.map(np.asarray, PodBatchBuilder(sb.table).build(pinfos))
    keys = Scheduler._batch_topo_keys(sb.table, pinfos)
    cfg = programs.ProgramConfig(
        filters=fwk.tensor_filters, scores=fwk.tensor_scores,
        hostname_topokey=max(sb.table.topokey.get(api.LABEL_HOSTNAME), 0),
        plugin_args=fwk.tensor_plugin_args(sb.table),
        active_topo_keys=keys)
    rng = jax.random.PRNGKey(1)

    P = int(cluster.pod_valid.shape[0])
    N = int(cluster.allocatable.shape[0])
    print(f"nodes={N} pod_axis={P} batch={batch.valid.shape[0]} "
          f"active_keys={keys}", flush=True)

    t0 = time.time()
    res = schedule_gang(cluster, batch, cfg, rng)
    rounds = int(np.asarray(res.rounds))
    first = time.time() - t0
    # steady state: 3 reps, readback-timed
    times = []
    for i in range(3):
        t0 = time.time()
        res = schedule_gang(cluster, batch, cfg,
                            jax.random.fold_in(rng, i))
        np.asarray(res.packed)
        times.append(time.time() - t0)
    chosen = np.asarray(res.chosen)
    dev = min(times)
    print(f"  first={first:.2f}s steady={dev:.3f}s rounds={rounds} "
          f"per_round={dev / max(rounds, 1) * 1e3:.1f}ms "
          f"scheduled={(chosen >= 0).sum()}", flush=True)

    def variant(label, **kw):
        t0 = time.time()
        r = schedule_gang(cluster, batch, cfg, rng, **kw)
        rr = int(np.asarray(r.rounds))
        f = time.time() - t0
        ts = []
        for i in range(2):
            t0 = time.time()
            r = schedule_gang(cluster, batch, cfg,
                              jax.random.fold_in(rng, 10 + i), **kw)
            np.asarray(r.packed)
            ts.append(time.time() - t0)
        print(f"  {label}: first={f:.2f}s steady={min(ts):.3f}s rounds={rr}",
              flush=True)

    if "--variants" in sys.argv:
        variant("max_rounds=1", max_rounds=1)
        variant("max_rounds=2", max_rounds=2)
        variant("no_topo", intra_batch_topology=False)

    if "--plugins" in sys.argv:
        # marginal cost of each score plugin: drop one at a time, 2 rounds
        def run_cfg(label, c):
            t0 = time.time()
            r = schedule_gang(cluster, batch, c, rng, max_rounds=2)
            np.asarray(r.packed)   # drain the device before steady timing
            f = time.time() - t0
            ts = []
            for i in range(2):
                t0 = time.time()
                r = schedule_gang(cluster, batch, c,
                                  jax.random.fold_in(rng, 99 + i),
                                  max_rounds=2)
                np.asarray(r.packed)
                ts.append(time.time() - t0)
            s = min(ts)
            print(f"  {label}: first={f:.1f}s steady={s:.3f}s", flush=True)
            return s

        base_s = run_cfg("all_scores", cfg)
        for name, _ in cfg.scores:
            c = cfg._replace(scores=tuple((n, w) for n, w in cfg.scores
                                          if n != name))
            s = run_cfg(f"-{name}", c)
            print(f"    marginal {name}: {(base_s - s) * 1e3:.0f}ms/2rounds",
                  flush=True)
        run_cfg("no_scores", cfg._replace(scores=()))
        run_cfg("no_filters_no_scores",
                cfg._replace(scores=(), filters=("NodeResourcesFit",)))
    sched.close()
    return dict(nodes=N, pod_axis=P, device_s=dev, rounds=rounds)


if __name__ == "__main__":
    shapes = [int(x) for x in sys.argv[1:]
              if not x.startswith("--")] or [1024, 2048, 5120]
    for n in shapes:
        profile_shape(n)
