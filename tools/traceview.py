"""Text flame summary for flight-recorder traces (`make trace`).

Reads either export of kubetpu's flight recorder:

  * the flat span-list document (PIPELINE_TRACE.json — bench.py /
    tools/trace_pipeline.py / /debug/flightz?format=json cycles), or
  * Chrome traceEvents JSON (PIPELINE_TRACE.perfetto.json /
    /debug/flightz?format=chrome)

and prints (1) a per-stage aggregate table — count, total/mean wall
time, share of the trace window, attributed device wait — and (2) the
span tree of the slowest cycles, indented by parent linkage with per-span
durations and thread tags.

Usage:
  python tools/traceview.py [TRACE.json] [--cycles N] [--threshold-ms M]
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List


def _load_spans(doc) -> List[dict]:
    """Normalize either export to span dicts: stage/cycle/thread/
    span_id/parent_id/start_s/end_s/args."""
    if "spans" in doc:        # pipeline doc (tolerates the pre-recorder
        out = []              # ad-hoc span list: ids/threads optional)
        for i, s in enumerate(doc["spans"]):
            out.append({"stage": s.get("stage", s.get("name", "?")),
                        "cycle": s.get("cycle", 0),
                        "thread": s.get("thread", ""),
                        "span_id": s.get("span_id", i + 1),
                        "parent_id": s.get("parent_id", 0),
                        "start_s": s.get("start_s", 0.0),
                        "end_s": s.get("end_s", s.get("start_s", 0.0)),
                        "args": s.get("args", {})})
        return out
    if "cycles" in doc and isinstance(doc.get("cycles"), list):
        # /debug/flightz dump: nested per-cycle span trees
        out = []
        t_base = min((c["t0"] for c in doc["cycles"]), default=0.0)
        for c in doc["cycles"]:
            for s in c.get("spans", []):
                out.append({"stage": s["name"], "cycle": c["seq"],
                            "thread": s.get("thread", ""),
                            "span_id": s["id"], "parent_id": s["parent"],
                            "start_s": s["t0"] - t_base,
                            "end_s": s["t1"] - t_base,
                            "args": s.get("args", {})})
        return out
    if "traceEvents" in doc:  # Chrome export
        xs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
        t_base = min((e["ts"] for e in xs), default=0)
        return [{"stage": e["name"],
                 "cycle": e.get("args", {}).get("cycle", 0),
                 "thread": str(e.get("tid", "")),
                 "span_id": e.get("args", {}).get("span_id", 0),
                 "parent_id": e.get("args", {}).get("parent_id", 0),
                 "start_s": (e["ts"] - t_base) / 1e6,
                 "end_s": (e["ts"] - t_base + e.get("dur", 0)) / 1e6,
                 "args": e.get("args", {})} for e in xs]
    raise SystemExit("unrecognized trace document (expected a flight-"
                     "recorder pipeline doc, flightz dump, or Chrome "
                     "traceEvents JSON)")


def _bar(frac: float, width: int = 24) -> str:
    n = max(0, min(width, int(round(frac * width))))
    return "#" * n + "." * (width - n)


def flame_summary(spans: List[dict]) -> str:
    if not spans:
        return "no spans recorded"
    window = (max(s["end_s"] for s in spans)
              - min(s["start_s"] for s in spans)) or 1e-9
    by_stage: Dict[str, List[dict]] = {}
    for s in spans:
        by_stage.setdefault(s["stage"], []).append(s)
    lines = [f"{len(spans)} spans over {window:.3f}s "
             f"({len(set(s['cycle'] for s in spans))} cycles)", "",
             f"{'stage':<44} {'n':>5} {'total_s':>8} {'mean_ms':>8} "
             f"{'dev_wait_s':>10}  share"]
    rows = []
    for stage, ss in by_stage.items():
        total = sum(s["end_s"] - s["start_s"] for s in ss)
        dev = sum(s.get("args", {}).get("device_wait_s", 0.0) for s in ss)
        rows.append((total, stage, ss, dev))
    for total, stage, ss, dev in sorted(rows, reverse=True):
        lines.append(
            f"{stage[:44]:<44} {len(ss):>5} {total:>8.3f} "
            f"{1000 * total / len(ss):>8.1f} {dev:>10.3f}  "
            f"{_bar(total / window)} {100 * total / window:5.1f}%")
    delta = delta_summary(spans)
    if delta:
        lines += ["", delta]
    return "\n".join(lines)


def delta_summary(spans: List[dict]) -> str:
    """One-line incremental-tensorization digest under the stage table:
    how many cycles rode the scatter path (and their p50 updated-row
    count) vs how many fell back to the blessed full resync.  Counted
    from the delta-apply / resync spans so the split matches the
    scheduler's own counters (a pod-axis-growth cycle emits a
    delta-build AND a resync span but applies no scatter — it counts as
    a resync here, exactly like Scheduler.resync_count)."""
    counts = sorted(s["args"]["delta_rows"] for s in spans
                    if s["stage"] == "delta-apply"
                    and "delta_rows" in s.get("args", {}))
    resyncs = sum(1 for s in spans if s["stage"] == "resync")
    if not counts and not resyncs:
        return ""
    p50 = counts[len(counts) // 2] if counts else 0
    return (f"delta-tensorize: {len(counts)} delta cycles "
            f"(rows p50 {p50}), {resyncs} resyncs")


def slo_summary(doc) -> str:
    """One-line per-pod latency digest under the stage table: per-stage
    p50/p99 from the SLO block the pipeline doc (or a /debug/slo-merged
    flightz dump) carries when the KUBETPU_SLO tracker was armed for the
    run (kubetpu/utils/slo.py)."""
    slo = doc.get("slo")
    if not isinstance(slo, dict):
        return ""
    stages = slo.get("stages") or {}

    def ms(v):
        return f"{1000 * v:.1f}ms" if v < 1.0 else f"{v:.2f}s"

    parts = []
    order = ["e2e", "queue_wait", "backoff", "cycle_wait", "dispatch",
             "device", "commit", "bind"]
    for name in order + sorted(set(stages) - set(order)):
        st = stages.get(name)
        if not st or not st.get("count"):
            continue
        parts.append(f"{name} p50 {ms(st.get('p50_s', 0.0))} "
                     f"p99 {ms(st.get('p99_s', 0.0))}")
    if not parts:
        return ""
    return "SLO: " + " | ".join(parts)


def auction_summary(doc) -> str:
    """One-line auction digest under the stage table: the per-cycle round
    HISTOGRAM (rounds -> cycles) plus the kernel-backend split, read from
    cycle meta (Scheduler records auction_rounds/kernel_backend on every
    gang cycle).  Makes the round-count reduction ROADMAP item 3 claims
    directly visible in `make trace` output."""
    metas = []
    if isinstance(doc.get("cycle_meta"), list):        # pipeline doc
        metas = [c.get("meta", {}) for c in doc["cycle_meta"]]
    elif isinstance(doc.get("cycles"), list):          # flightz dump
        metas = [c.get("meta", {}) for c in doc["cycles"]]
    rounds = [m["auction_rounds"] for m in metas
              if isinstance(m.get("auction_rounds"), int)]
    if not rounds:
        return ""
    hist: Dict[int, int] = {}
    for r in rounds:
        hist[r] = hist.get(r, 0) + 1
    backends: Dict[str, int] = {}
    for m in metas:
        kb = m.get("kernel_backend")
        if kb:
            backends[kb] = backends.get(kb, 0) + 1
    h = " ".join(f"{r}r:{n}" for r, n in sorted(hist.items()))
    b = " ".join(f"{k}:{n}" for k, n in sorted(backends.items()))
    return (f"auction rounds: {h} (max {max(rounds)}"
            + (f"; backend {b}" if b else "") + ")")


def journal_summary(doc) -> str:
    """One-line durable-journal digest under the stage table: record and
    byte counts, drops, the recorded cycle window, and the linkage
    hit-rates into the flight-recorder/decision rings — read from the
    "journal" block the pipeline doc (or a /debug/journal dump) carries
    when KUBETPU_JOURNAL was armed for the run (kubetpu/utils/
    journal.py; replay with python -m tools.kubereplay <dir>)."""
    j = doc.get("journal")
    if not isinstance(j, dict) or not j.get("armed"):
        return ""
    kb = j.get("bytes", 0) / 1024.0
    parts = [f"{j.get('records', 0)} records ({kb:.1f} KiB"
             + (f", {j['dropped_total']} dropped"
                if j.get("dropped_total") else "") + ")"]
    span = j.get("cycle_span")
    if span:
        parts.append(f"cycles {span[0]}-{span[1]}")
    if "flight_live_rate" in j:
        parts.append(f"flight-link {100 * j['flight_live_rate']:.0f}%")
    elif "flight_link_rate" in j:
        parts.append(f"flight-link {100 * j['flight_link_rate']:.0f}%")
    if "decision_live_rate" in j:
        parts.append(f"decision-link {100 * j['decision_live_rate']:.0f}%")
    return "journal: " + ", ".join(parts)


def device_summary(doc) -> str:
    """One-line device observability digest under the stage table:
    measured per-program device time (mean per fenced dispatch, sample
    count) with the roofline fraction where the join resolved, plus the
    residency-ledger total — read from the "device" block the pipeline
    doc carries when KUBETPU_DEVSTATS was armed for the run
    (kubetpu/utils/devstats.py; live twin at /debug/devicez)."""
    d = doc.get("device")
    if not isinstance(d, dict):
        return ""
    parts = []
    for name, p in sorted((d.get("programs") or {}).items()):
        if not p.get("count"):
            continue
        seg = (f"{name} {1000 * p.get('mean_s', 0.0):.1f}ms "
               f"x{p['count']}")
        frac = p.get("roofline_fraction")
        if isinstance(frac, (int, float)):
            seg += f" ({100 * frac:.1f}% of roofline)"
        parts.append(seg)
    lb = d.get("ledger_bytes")
    if isinstance(lb, (int, float)) and lb > 0:
        parts.append(f"HBM resident {lb / 1048576.0:.1f} MiB")
    if not parts:
        return ""
    return "device: " + " | ".join(parts)


def load_summary(doc) -> str:
    """One-line sustained-load digest under the stage table: window
    count and cadence, the steady-state span with its EXACT windowed
    p50/p99 (warmup cut by the slope test), total recovery demotions,
    and the worst window's p99 with its flight-recorder seq cross-link —
    read from the "load" block the pipeline doc carries when the
    KUBETPU_TELEMETRY ring was armed for the run
    (kubetpu/utils/telemetry.py; live twin at /debug/loadz)."""
    ld = doc.get("load")
    if not isinstance(ld, dict) or not ld.get("windows"):
        return ""

    def ms(v):
        return f"{1000 * v:.1f}ms" if v < 1.0 else f"{v:.2f}s"

    parts = [f"{ld['windows']} windows x {ld.get('window_s', 0.0):g}s"
             + (f" ({ld['dropped']} dropped)" if ld.get("dropped")
                else "")]
    steady = ld.get("steady")
    if isinstance(steady, dict):
        parts.append(f"steady [{steady.get('start', 0)}+"
                     f"{steady.get('windows', 0)}] "
                     f"p50 {ms(steady.get('p50_s', 0.0))} "
                     f"p99 {ms(steady.get('p99_s', 0.0))}")
    else:
        parts.append("no steady state reached")
    if ld.get("demotions"):
        parts.append(f"{ld['demotions']} demotions")
    worst = ld.get("worst_window")
    if isinstance(worst, dict) and worst.get("p99_s"):
        parts.append(f"worst w{worst.get('seq', 0)} "
                     f"p99 {ms(worst['p99_s'])} "
                     f"(flight seq {worst.get('flight_seq', 0)})")
    return "load: " + ", ".join(parts)


def pipeline_summary(doc) -> str:
    """One-line depth-k pipeline digest under the stage table: the
    configured depth plus the ring-slot occupancy histogram (slot ->
    cycles) read from cycle meta — slot 0 is a cycle dispatched straight
    behind a commit, higher slots are cycles parked deeper in the
    in-flight ring, so a spread across slots IS the overlap the depth-k
    executor (kubetpu/pipeline.py) recovers."""
    metas = []
    if isinstance(doc.get("cycle_meta"), list):        # pipeline doc
        metas = [c.get("meta", {}) for c in doc["cycle_meta"]]
    elif isinstance(doc.get("cycles"), list):          # flightz dump
        metas = [c.get("meta", {}) for c in doc["cycles"]]
    slots = [m["ring_slot"] for m in metas
             if isinstance(m.get("ring_slot"), int)]
    if not slots:
        return ""
    depth = max((m.get("pipeline_depth") for m in metas
                 if isinstance(m.get("pipeline_depth"), int)), default=0)
    hist: Dict[int, int] = {}
    for s in slots:
        hist[s] = hist.get(s, 0) + 1
    occ = " ".join(f"slot{k}:{n}" for k, n in sorted(hist.items()))
    return f"pipeline: depth {depth}, ring occupancy {occ}"


def cycle_tree(spans: List[dict], cycle: int,
               threshold_ms: float = 0.0) -> str:
    cs = [s for s in spans if s["cycle"] == cycle]
    by_parent: Dict[int, List[dict]] = {}
    for s in cs:
        by_parent.setdefault(s["parent_id"], []).append(s)
    for kids in by_parent.values():
        kids.sort(key=lambda s: s["start_s"])
    known = {s["span_id"] for s in cs}
    lines = [f"cycle {cycle}:"]

    def walk(parent: int, depth: int) -> None:
        for s in by_parent.get(parent, []):
            dur_ms = 1000 * (s["end_s"] - s["start_s"])
            if dur_ms < threshold_ms and depth > 1:
                continue
            extra = ""
            dev = s.get("args", {}).get("device_wait_s")
            if dev:
                extra = f"  [device_wait {1000 * dev:.1f}ms]"
            thread = s.get("thread", "")
            lines.append(f"  {'  ' * depth}{s['stage']:<40} "
                         f"{dur_ms:>9.1f}ms  ({thread}){extra}")
            walk(s["span_id"], depth + 1)

    # roots: parent 0 or parent outside this cycle's recorded set
    roots = sorted({s["parent_id"] for s in cs
                    if s["parent_id"] == 0 or s["parent_id"] not in known})
    for r in roots:
        walk(r, 0)
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="traceview",
        description="text flame summary for kubetpu flight-recorder "
                    "traces")
    ap.add_argument("trace", nargs="?", default="PIPELINE_TRACE.json")
    ap.add_argument("--cycles", type=int, default=2,
                    help="show the span tree of the N slowest cycles")
    ap.add_argument("--threshold-ms", type=float, default=0.5,
                    help="hide sub-spans shorter than this in the trees")
    args = ap.parse_args(argv)
    with open(args.trace) as f:
        doc = json.load(f)
    spans = _load_spans(doc)
    print(flame_summary(spans))
    auction = auction_summary(doc)
    if auction:
        print(auction)
    pipe = pipeline_summary(doc)
    if pipe:
        print(pipe)
    dev = device_summary(doc)
    if dev:
        print(dev)
    slo = slo_summary(doc)
    if slo:
        print(slo)
    jnl = journal_summary(doc)
    if jnl:
        print(jnl)
    ld = load_summary(doc)
    if ld:
        print(ld)
    if not spans:
        return 0
    wall: Dict[int, float] = {}
    for s in spans:
        wall[s["cycle"]] = max(wall.get(s["cycle"], 0.0),
                               s["end_s"]) - 0.0
    span_of = {c: min(s["start_s"] for s in spans if s["cycle"] == c)
               for c in wall}
    slowest = sorted(wall, key=lambda c: wall[c] - span_of[c],
                     reverse=True)[:max(args.cycles, 0)]
    for c in slowest:
        print()
        print(cycle_tree(spans, c, threshold_ms=args.threshold_ms))
    return 0


if __name__ == "__main__":
    sys.exit(main())
