"""Record the pipelined drain's stage timeline (VERDICT r4 #2 evidence:
the overlap must be visible in a committed trace).

Wraps the scheduler's prepare/readback/dispatch/commit stages with
wall-clock spans and writes PIPELINE_TRACE.json: for each serving call,
the spans show cycle k's PREPARE and DISPATCH starting before cycle
k-1's COMMIT has run, and the packed readback as the only device sync.

Usage: python tools/trace_pipeline.py
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402
from kubetpu.apis.config import (KubeSchedulerConfiguration,  # noqa: E402
                                 KubeSchedulerProfile)
from kubetpu.scheduler import Scheduler  # noqa: E402

SPANS = []
T0 = [0.0]


def wrap(cls, name, label, cycle_of):
    orig = getattr(cls, name)

    def wrapped(self, *a, **kw):
        t = time.time() - T0[0]
        out = orig(self, *a, **kw)
        SPANS.append({"stage": label, "cycle": cycle_of(a),
                      "start_s": round(t, 4),
                      "end_s": round(time.time() - T0[0], 4)})
        return out
    setattr(cls, name, wrapped)


def main():
    counter = {"prep": 0, "dispatch": 0, "finish": 0}

    def count(key):
        def f(_a):
            counter[key] += 1
            return counter[key]
        return f

    wrap(Scheduler, "_prepare_group", "prepare+tensorize", count("prep"))
    wrap(Scheduler, "_dispatch_group", "dispatch(auction+materialize)",
         count("dispatch"))
    wrap(Scheduler, "_readback_group", "packed-readback(sync)",
         lambda a: counter["finish"] + 1)
    wrap(Scheduler, "_commit_group", "commit(Reserve/assume/bind)",
         count("finish"))

    for warm in (False, True):
        SPANS.clear()
        store, pending = bench.build_world(1000, 4096, 2)
        sched = Scheduler(store, config=KubeSchedulerConfiguration(
            profiles=[KubeSchedulerProfile()], batch_size=1024,
            mode="gang", chain_cycles=True, pipeline_cycles=True,
            prewarm=False), async_binding=False)
        for p in pending:
            store.add(p)
        for k in counter:
            counter[k] = 0
        T0[0] = time.time()
        sched.device_wait_s = 0.0
        while True:
            if not sched.schedule_pending(timeout=0.0):
                break
        total = time.time() - T0[0]
        sched.close()
    doc = {
        "workload": "4096 pods x 1000 nodes, 1024-pod pipelined cycles",
        "total_s": round(total, 3),
        "device_wait_s": round(sched.device_wait_s, 3),
        "note": "cycle k's prepare/dispatch precede cycle k-1's commit: "
                "the device executes cycle k while the host commits k-1 "
                "(the packed readback is the only sync point)",
        "spans": SPANS,
    }
    with open("PIPELINE_TRACE.json", "w") as f:
        json.dump(doc, f, indent=1)
    print(json.dumps({"total_s": doc["total_s"],
                      "device_wait_s": doc["device_wait_s"],
                      "spans": len(SPANS)}))


if __name__ == "__main__":
    main()
