"""Record the pipelined drain's stage timeline from the FLIGHT RECORDER
(VERDICT r4 #2 evidence: the overlap must be visible in a committed
trace).

The recorder (kubetpu/utils/trace.py) captures every cycle's span tree —
prepare/tensorize steps, dispatch, packed-readback (with device-wait
attribution), commit, preemption wave, binds — so this tool no longer
monkeypatches the scheduler: it arms the recorder, drives the pipelined
drain, and exports the ring as

  * PIPELINE_TRACE.json          flat stage/cycle span list + span_total
  * PIPELINE_TRACE.perfetto.json Chrome traceEvents (load in
                                 ui.perfetto.dev; ph:"X" count ==
                                 span_total)

The overlap shows as cycle k's "dispatch" span starting before cycle
k-1's "commit" span has run, with "packed-readback" as the only device
sync.  `python tools/traceview.py PIPELINE_TRACE.json` prints the text
flame summary.

Usage: python tools/trace_pipeline.py
"""
from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402
from kubetpu.apis.config import (KubeSchedulerConfiguration,  # noqa: E402
                                 KubeSchedulerProfile)
from kubetpu.scheduler import Scheduler  # noqa: E402
from kubetpu.utils import slo as uslo  # noqa: E402
from kubetpu.utils import trace as utrace  # noqa: E402


def main():
    flight = utrace.arm_flight_recorder()
    # the SLO tracker rides the captured drain so the committed pipeline
    # doc carries the per-stage latency meta traceview digests ("SLO:")
    slo = uslo.arm_slo_tracker()
    sched = None
    for warm in (False, True):
        if sched is not None:
            sched.close()
        flight.clear()
        slo.clear()
        store, pending = bench.build_world(1000, 4096, 2)
        sched = Scheduler(store, config=KubeSchedulerConfiguration(
            profiles=[KubeSchedulerProfile()], batch_size=1024,
            mode="gang", chain_cycles=True, pipeline_cycles=True,
            prewarm=False), async_binding=False)
        for p in pending:
            store.add(p)
        sched.device_wait_s = 0.0
        while True:
            if not sched.schedule_pending(timeout=0.0):
                break
    doc = flight.to_pipeline_doc(
        workload="4096 pods x 1000 nodes, 1024-pod pipelined cycles "
                 "(warm pass)")
    doc["note"] = ("cycle k's dispatch precedes cycle k-1's commit: the "
                   "device executes cycle k while the host commits k-1 "
                   "(the packed readback is the only sync point)")
    doc["scheduler_device_wait_s"] = round(sched.device_wait_s, 3)
    sched.close()
    bench.atomic_write_json("PIPELINE_TRACE.json", doc)
    bench.atomic_write_json("PIPELINE_TRACE.perfetto.json",
                            flight.to_chrome_trace())
    print(json.dumps({"total_s": doc.get("total_s"),
                      "device_wait_s": doc["device_wait_s"],
                      "cycles": doc["cycles"],
                      "spans": doc["span_total"]}))


if __name__ == "__main__":
    main()
