# Developer entrypoints.  `make lint` is the static-analysis gate builders
# run by default; `make test` is the tier-1 suite (which embeds the same
# lint gate via tests/test_kubelint.py).  `make help` lists everything.

.PHONY: help lint lock-graph test sanitize-test race-test flight-test \
	delta-test census census-test aot aot-test pallas-test chaos-test \
	slo-test pipeline-test journal-test replay-test devstats-test \
	mesh-test exact exact-test close close-test load-test load-soak \
	trend trace bench

help:
	@echo "kubetpu targets:"
	@echo "  make lint           kubelint over kubetpu/ (all 7 rule families:"
	@echo "                      host-sync, recompile, numeric, purity,"
	@echo "                      concurrency, delta, exact), JSON CI mode,"
	@echo "                      nonzero on findings"
	@echo "  make lock-graph     print the lock-ownership map + acquisition-"
	@echo "                      order table (README 'Concurrency model')"
	@echo "  make test           tier-1 suite (JAX on CPU, slow tests skipped)"
	@echo "  make sanitize-test  full cycles under KUBETPU_SANITIZE=1"
	@echo "                      (debug_nans, rank-promotion, compile watchdog)"
	@echo "  make race-test      8-thread stress + seeded-violation tests under"
	@echo "                      KUBETPU_RACE=1 (instrumented locks, lock-order"
	@echo "                      + hold-time enforcement, guarded-attr checks)"
	@echo "  make flight-test    flight recorder + decision audit suite (ring"
	@echo "                      wrap/drops, Chrome-trace schema, /debug"
	@echo "                      endpoints, disarmed no-op)"
	@echo "  make delta-test     incremental tensorization suite (delta-vs-"
	@echo "                      rebuild golden equivalence, resync fallbacks,"
	@echo "                      scatter compile-once watchdog, bench gate)"
	@echo "  make census         regenerate COMPILE_MANIFEST.json from the"
	@echo "                      compile-surface census (tools/kubecensus);"
	@echo "                      run after an INTENTIONAL surface change"
	@echo "  make census-test    census suite: every jaxpr rule fires on a"
	@echo "                      bad snippet, manifest idempotence, drift"
	@echo "                      gate, runtime compile-event matching"
	@echo "  make aot            compile + serialize every COMPILE_MANIFEST"
	@echo "                      variant of the seamed serving programs into"
	@echo "                      artifacts/aot (tools/kubeaot --build) and"
	@echo "                      rewrite the committed AOT_INDEX.json"
	@echo "  make aot-test       AOT suite: serialize/deserialize round trip"
	@echo "                      with bit-identical placements, capture->serve"
	@echo "                      signature hits, env-drift fallback, index"
	@echo "                      gate, persistent-cache config coverage"
	@echo "  make pallas-test    Pallas megakernel differential suite:"
	@echo "                      lax-vs-pallas-interpret bit-match oracle"
	@echo "                      (randomized churned clusters + goldens +"
	@echo "                      compile-once watchdog); reasoned skip when"
	@echo "                      pallas is unavailable"
	@echo "  make chaos-test     chaos harness + self-healing runtime suite:"
	@echo "                      seeded fault injection (dispatch, delta"
	@echo "                      scatter, aot load, bind/extender/watch"
	@echo "                      transport), deadline demotion, anti-entropy"
	@echo "                      verifier, disarmed-no-op poison test"
	@echo "  make slo-test       per-pod latency SLO suite (utils/slo.py):"
	@echo "                      sketch-vs-numpy quantile property, bounded"
	@echo "                      memory, disarmed zero-lock poison, /debug/slo"
	@echo "                      round trip, exemplar links, armed-vs-disarmed"
	@echo "                      placement parity"
	@echo "  make pipeline-test  depth-k pipelined executor suite"
	@echo "                      (kubetpu/pipeline.py): depth-parity"
	@echo "                      placement goldens, gather-window gating on"
	@echo "                      free ring slots, per-slot exemption"
	@echo "                      accounting, chaos-at-depth scatter recovery"
	@echo "  make journal-test   durable cycle journal suite"
	@echo "                      (kubetpu/utils/journal.py): record schema,"
	@echo "                      size-cap eviction counting, chaos write"
	@echo "                      degradation, disarmed zero-lock poison,"
	@echo "                      armed-vs-disarmed placement parity,"
	@echo "                      /debug/journal round trip"
	@echo "  make replay-test    bit-exact replay rig suite (tools/"
	@echo "                      kubereplay): 50+-cycle depth-4 journaled"
	@echo "                      drain replays byte-identical, corrupt-"
	@echo "                      record skip with reason, counterfactual"
	@echo "                      score-weight/pipelineDepth divergence"
	@echo "  make devstats-test  device-side observability suite"
	@echo "                      (kubetpu/utils/devstats.py): sampled"
	@echo "                      per-program device-time fences, roofline"
	@echo "                      join vs COMPILE_MANIFEST.json, residency"
	@echo "                      ledger + capacity-planner 10% sanity gate,"
	@echo "                      /debug/devicez round trip, disarmed poison,"
	@echo "                      armed-vs-disarmed placement parity"
	@echo "  make mesh-test      pod-axis mesh scale-out suite (parallel/"
	@echo "                      shardmap.py): (2,4)/(4,2)/(1,8) sharded-vs-"
	@echo "                      unsharded bit-identity through the shard_map"
	@echo "                      auction/scan (tiled + replicated surfaces,"
	@echo "                      windowed rounds, serving path incl. the"
	@echo "                      double-buffered batch upload)"
	@echo "  make exact          re-prove the exact-reduction invariant over"
	@echo "                      every mesh/Pallas root and rewrite the"
	@echo "                      committed EXACT_MANIFEST.json (tools/"
	@echo "                      kubeexact --write); run after an INTENTIONAL"
	@echo "                      collective/VMEM surface change"
	@echo "  make exact-test     exactness prover suite: every prover rule"
	@echo "                      fires on a bad snippet, clean snippet empty,"
	@echo "                      manifest byte-idempotence + drift gate,"
	@echo "                      stale-exemption audit, committed manifest"
	@echo "                      passes the pure-JSON --check"
	@echo "  make close          re-prove the compile-surface closure (tools/"
	@echo "                      kubeclose --write): interprocedural provenance"
	@echo "                      of every dispatch-seam static, enumerated"
	@echo "                      reachable signature set, coverage join against"
	@echo "                      the kubecensus registry; rewrites the committed"
	@echo "                      CLOSURE_MANIFEST.json (byte-identical over an"
	@echo "                      unchanged tree); run after an INTENTIONAL seam"
	@echo "                      or config-domain change"
	@echo "  make close-test     closure prover suite: every close/* rule fires"
	@echo "                      on a bad snippet + quiet good twin, manifest"
	@echo "                      byte-idempotence + two-directional drift gate,"
	@echo "                      --check under a jax import blocker, stale-"
	@echo "                      exemption audit, serving-path dispatch-"
	@echo "                      signature membership e2e"
	@echo "  make load-test      sustained-load telemetry plane suite"
	@echo "                      (utils/telemetry.py + harness streams +"
	@echo "                      SustainedLoadRunner): window-delta-vs-numpy"
	@echo "                      exactness, ring wrap/drop bounds, disarmed"
	@echo "                      poison, parity golden, chaos-window"
	@echo "                      attribution, /debug/loadz + /metrics"
	@echo "  make load-soak      minutes-scale open-loop soak (slow-marked):"
	@echo "                      steady-state span found, zero demotions"
	@echo "  make trend          per-case bench trend table over the committed"
	@echo "                      BENCH_r*.json trajectory with per-stage"
	@echo "                      regression attribution (tools/benchtrend.py)"
	@echo "  make trace          run the pipelined drain with the flight"
	@echo "                      recorder armed, write PIPELINE_TRACE.json +"
	@echo "                      .perfetto.json, print the text flame summary"
	@echo "  make bench          end-to-end throughput benchmark (bench.py;"
	@echo "                      BENCH_OUT=<path> writes the JSON atomically)"

lint:
	./tools/ci_lint.sh

lock-graph:
	python -m tools.kubelint kubetpu/ --lock-graph

test:
	JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
		--continue-on-collection-errors -p no:cacheprovider

# full scheduling cycles under the runtime sanitizer (debug_nans,
# rank_promotion=raise, compile-count watchdog)
sanitize-test:
	JAX_PLATFORMS=cpu KUBETPU_SANITIZE=1 python -m pytest \
		tests/test_sanitize.py -q -p no:cacheprovider

# the race harness: stress tests with instrumented locks + guarded-attr
# enforcement (utils/racecheck.py); KUBETPU_RACE=1 arms it process-wide
race-test:
	JAX_PLATFORMS=cpu KUBETPU_RACE=1 python -m pytest \
		tests/test_racecheck.py -q -p no:cacheprovider

# flight recorder + per-pod decision audit (utils/trace.py,
# utils/decisions.py, /debug/flightz + /debug/explain)
flight-test:
	JAX_PLATFORMS=cpu python -m pytest \
		tests/test_flightrecorder.py -q -p no:cacheprovider

# incremental tensorization (state/delta.py): golden equivalence vs full
# rebuild, fallback triggers, scatter-program compile-once contract
delta-test:
	JAX_PLATFORMS=cpu python -m pytest \
		tests/test_delta.py -q -p no:cacheprovider

# compile-surface census: trace every registered jit root across the
# pow2 ladder and rewrite COMPILE_MANIFEST.json (byte-identical when the
# surface is unchanged); `make lint` / ci_lint.sh fail on drift
census:
	JAX_PLATFORMS=cpu python -m tools.kubecensus --write

census-test:
	JAX_PLATFORMS=cpu python -m pytest \
		tests/test_kubecensus.py -q -p no:cacheprovider

# AOT executable artifacts (tools/kubeaot + kubetpu/utils/aot.py):
# deploy-time jit(...).lower().compile() of every manifest variant of the
# seamed serving programs, serialized via jax.experimental
# .serialize_executable; nonzero exit on a capture failure or a
# lowering-sha mismatch vs COMPILE_MANIFEST.json (the bit-identity oracle)
aot:
	JAX_PLATFORMS=cpu python -m tools.kubeaot --build

aot-test:
	JAX_PLATFORMS=cpu python -m pytest \
		tests/test_aot.py tests/test_compilation.py -q -p no:cacheprovider

# Pallas megakernel (kubetpu/ops/pallas_kernels.py): the fused
# filter->score->propose auction round vs the lax oracle, interpret=True
# on CPU; `make bench` adds the backend_compare case with the round
# histogram.  Environments without jax.experimental.pallas skip with a
# reason, never fail.
pallas-test:
	JAX_PLATFORMS=cpu python -m pytest \
		tests/test_pallas_gang.py -q -m 'not slow' -p no:cacheprovider

# pod-axis mesh scale-out (kubetpu/parallel/shardmap.py): the explicit
# shard_map auction/scan vs the single-device oracle on the 8-virtual-CPU
# mesh — the previously env-gated (2,4)/(4,2) shapes, ungated
mesh-test:
	JAX_PLATFORMS=cpu python -m pytest \
		tests/test_mesh.py -q -m 'not slow' -p no:cacheprovider

# chaos harness (kubetpu/utils/chaos.py): every named injection point's
# seeded recovery-invariant scenario — no lost pods, no double binds,
# mirror/device bit-consistency after induced faults — plus the
# disarmed-hot-path poison test
chaos-test:
	JAX_PLATFORMS=cpu python -m pytest \
		tests/test_chaos.py -q -m 'not slow' -p no:cacheprovider

# per-pod latency SLO layer (kubetpu/utils/slo.py): streaming quantile
# sketch correctness, the disarmed-hot-path zero-lock contract, the
# /debug/slo endpoint, exemplar->flight-recorder linkage, and the
# golden parity proof that arming changes zero placements
slo-test:
	JAX_PLATFORMS=cpu python -m pytest \
		tests/test_slo.py -q -p no:cacheprovider

# depth-k pipelined executor (kubetpu/pipeline.py): depth-parity
# placement goldens, the gather-window/free-slot gate, ring exemption
# accounting, ring-slot flight tags, and the chaos-at-depth scatter
# recovery regressions that live next to the delta suite's chain-break
# test
pipeline-test:
	JAX_PLATFORMS=cpu python -m pytest \
		tests/test_pipeline.py tests/test_chain.py -q -p no:cacheprovider
	JAX_PLATFORMS=cpu python -m pytest \
		tests/test_delta.py -q -k 'depth4 or pipelined' -p no:cacheprovider

# durable cycle journal (kubetpu/utils/journal.py): on-disk record
# store bounds + eviction counting, the chaos journal point's
# degrade-to-drop contract, the disarmed-hot-path poison test, and the
# armed-vs-disarmed placement-parity golden
journal-test:
	JAX_PLATFORMS=cpu python -m pytest \
		tests/test_journal.py -q -p no:cacheprovider

# bit-exact replay rig (tools/kubereplay): the journaled-drain replay
# oracle (byte-identical packed placements incl. delta cycles, resyncs
# and a depth-4 pipelined segment), per-record corrupt-skip reasons, and
# the counterfactual divergence contracts (score weight nonzero,
# pipelineDepth zero)
replay-test:
	JAX_PLATFORMS=cpu python -m pytest \
		tests/test_replay.py -q -m 'not slow' -p no:cacheprovider

# device-side observability (kubetpu/utils/devstats.py): measured
# per-program device time via sampled deep-timing fences, the roofline
# join against the committed manifest cost rows, the HBM residency
# ledger + the capacity planner's projection-vs-measured 10% gate, and
# the house arming contract (disarmed poison, placement parity)
devstats-test:
	JAX_PLATFORMS=cpu python -m pytest \
		tests/test_devstats.py -q -p no:cacheprovider

# jaxpr-level exactness prover + collective/VMEM census (tools/
# kubeexact): abstract interpretation of every exact-marked mesh/Pallas
# root proves each cross-shard/cross-tile reduction is float max/min or
# an integer-valued sum bounded below 2**24, enumerates the collective
# surface, and budgets the Pallas kernel's VMEM; --write rewrites the
# committed EXACT_MANIFEST.json (byte-identical when the surface is
# unchanged).  `make lint` / ci_lint.sh fail on drift.
exact:
	JAX_PLATFORMS=cpu python -m tools.kubeexact --write

exact-test:
	JAX_PLATFORMS=cpu python -m pytest \
		tests/test_kubeexact.py -q -p no:cacheprovider

# compile-surface closure prover (tools/kubeclose, pure AST — no jax):
# interprocedural provenance of every value reaching a dispatch-seam
# static, enumerated at the committed north-star environment and joined
# against the kubecensus registry's closure_statics; --write rewrites
# the committed CLOSURE_MANIFEST.json (byte-identical when the seam
# surface is unchanged).  `make lint` / ci_lint.sh fail on drift.
close:
	python -m tools.kubeclose --write

close-test:
	JAX_PLATFORMS=cpu python -m pytest \
		tests/test_kubeclose.py -q -p no:cacheprovider

# sustained-load telemetry plane (kubetpu/utils/telemetry.py + the
# open-loop harness streams in kubetpu/harness/hollow.py + perf.py
# SustainedLoadRunner): window-delta merge exactness vs numpy, ring
# wrap + drop counting, the disarmed zero-cost poison test, the
# armed-vs-disarmed placement-parity golden, seeded chaos-storm
# window attribution, /debug/loadz and the /metrics window series
load-test:
	JAX_PLATFORMS=cpu python -m pytest \
		tests/test_telemetry.py -q -m 'not slow' -p no:cacheprovider

# the minutes-scale sustained soak (excluded from tier-1 via the slow
# marker): a live open-loop Poisson stream must reach a steady-state
# span with zero recovery-ladder demotions and a bounded ring
load-soak:
	JAX_PLATFORMS=cpu python -m pytest \
		tests/test_telemetry.py -q -m slow -p no:cacheprovider

# bench trend table + regression attribution over the committed rounds
trend:
	python -m tools.benchtrend

# pipelined-drain trace via the flight recorder + text flame summary
# (PIPELINE_TRACE.json + PIPELINE_TRACE.perfetto.json for ui.perfetto.dev)
trace:
	python tools/trace_pipeline.py
	python tools/traceview.py PIPELINE_TRACE.json

bench:
	python bench.py
