# Developer entrypoints.  `make lint` is the static-analysis gate builders
# run by default; `make test` is the tier-1 suite (which embeds the same
# lint gate via tests/test_kubelint.py).

.PHONY: lint test sanitize-test bench

lint:
	./tools/ci_lint.sh

test:
	JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
		--continue-on-collection-errors -p no:cacheprovider

# full scheduling cycles under the runtime sanitizer (debug_nans,
# rank_promotion=raise, compile-count watchdog)
sanitize-test:
	JAX_PLATFORMS=cpu KUBETPU_SANITIZE=1 python -m pytest \
		tests/test_sanitize.py -q -p no:cacheprovider

bench:
	python bench.py
