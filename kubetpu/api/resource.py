"""Resource quantity parsing and arithmetic.

TPU-native re-design of Kubernetes resource quantities
(reference: staging/src/k8s.io/apimachinery/pkg/api/resource/quantity.go).

Instead of the reference's arbitrary-precision ``inf.Dec`` quantities we
normalize every resource to an integer *milli-unit* (int), which is exact for
every value the scheduler ever compares (CPU in millicores, memory in bytes,
etc.).  Device-side, each resource channel is scaled to fit exactly in f32
(see kubetpu/state/tensors.py) so the fit comparison ``allocatable >=
requested + used`` is bit-exact on TPU.
"""

from __future__ import annotations

import functools
import re
from typing import Dict, Union

# Binary (Ki/Mi/Gi...) and decimal (k/M/G...) suffix multipliers.
_BIN = {"Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40, "Pi": 2**50, "Ei": 2**60}
_DEC = {"n": 10**-9, "u": 10**-6, "m": 10**-3, "": 1,
        "k": 10**3, "M": 10**6, "G": 10**9, "T": 10**12, "P": 10**15, "E": 10**18}

_QTY_RE = re.compile(r"^([+-]?[0-9.]+)([numkMGTPEi]{0,2})$")


def parse_quantity(s: Union[str, int, float]) -> float:
    """Parse a Kubernetes quantity string ("100m", "32Gi", "4") to a float
    in base units (cores, bytes, counts).  String parses are memoized: a
    cluster's quantity vocabulary is tiny, and hot host paths (the PVC
    matchable-PV scan probes every (PV, requirement-signature) pair per
    overlay build) re-parse the same strings every cycle."""
    if isinstance(s, (int, float)):
        return float(s)
    return _parse_quantity_str(s)


@functools.lru_cache(maxsize=4096)
def _parse_quantity_str(s: str) -> float:
    s = s.strip()
    m = _QTY_RE.match(s)
    if not m:
        raise ValueError(f"invalid quantity: {s!r}")
    num, suffix = m.groups()
    value = float(num)
    if suffix in _BIN:
        return value * _BIN[suffix]
    if suffix in _DEC:
        return value * _DEC[suffix]
    raise ValueError(f"invalid quantity suffix: {s!r}")


def to_milli(s: Union[str, int, float]) -> int:
    """Quantity -> integer milli-units (reference: Quantity.MilliValue)."""
    return int(round(parse_quantity(s) * 1000))


def to_int(s: Union[str, int, float]) -> int:
    """Quantity -> integer base units, rounding up (reference: Quantity.Value)."""
    import math
    return int(math.ceil(parse_quantity(s)))


# Well-known resource names (reference: staging/src/k8s.io/api/core/v1/types.go:5267).
CPU = "cpu"
MEMORY = "memory"
EPHEMERAL_STORAGE = "ephemeral-storage"
PODS = "pods"

DEFAULT_MILLI_CPU_REQUEST = 100            # 0.1 core
DEFAULT_MEMORY_REQUEST = 200 * 1024 * 1024  # 200 MB
# reference: pkg/scheduler/util/non_zero.go:30-48 (GetNonzeroRequestForResource)


def is_extended(name: str) -> bool:
    """Extended (scalar) resources: anything not in the native set and not a
    hugepages-style prefix handled natively.
    reference: pkg/apis/core/v1/helper/helpers.go (IsScalarResourceName)."""
    return name not in (CPU, MEMORY, EPHEMERAL_STORAGE, PODS)


class Resource:
    """Aggregated resource vector in integer units.

    cpu is millicores; memory/ephemeral-storage are bytes; scalar resources
    are in their native integer unit.
    reference: pkg/scheduler/framework/v1alpha1/types.go:262 (Resource).
    """

    __slots__ = ("milli_cpu", "memory", "ephemeral_storage", "allowed_pod_number",
                 "scalar_resources")

    def __init__(self, milli_cpu: int = 0, memory: int = 0, ephemeral_storage: int = 0,
                 allowed_pod_number: int = 0, scalar_resources: Dict[str, int] | None = None):
        self.milli_cpu = milli_cpu
        self.memory = memory
        self.ephemeral_storage = ephemeral_storage
        self.allowed_pod_number = allowed_pod_number
        self.scalar_resources: Dict[str, int] = dict(scalar_resources or {})

    @classmethod
    def from_resource_list(cls, rl: Dict[str, Union[str, int, float]]) -> "Resource":
        r = cls()
        r.add_resource_list(rl)
        return r

    def add_resource_list(self, rl: Dict[str, Union[str, int, float]]) -> None:
        # reference: types.go:286 (Resource.Add)
        for name, q in (rl or {}).items():
            if name == CPU:
                self.milli_cpu += to_milli(q)
            elif name == MEMORY:
                self.memory += to_int(q)
            elif name == EPHEMERAL_STORAGE:
                self.ephemeral_storage += to_int(q)
            elif name == PODS:
                self.allowed_pod_number += to_int(q)
            else:
                self.scalar_resources[name] = self.scalar_resources.get(name, 0) + to_int(q)

    def set_max(self, rl: Dict[str, Union[str, int, float]]) -> None:
        # reference: types.go:331 (Resource.SetMaxResource)
        for name, q in (rl or {}).items():
            if name == CPU:
                self.milli_cpu = max(self.milli_cpu, to_milli(q))
            elif name == MEMORY:
                self.memory = max(self.memory, to_int(q))
            elif name == EPHEMERAL_STORAGE:
                self.ephemeral_storage = max(self.ephemeral_storage, to_int(q))
            elif name == PODS:
                self.allowed_pod_number = max(self.allowed_pod_number, to_int(q))
            else:
                self.scalar_resources[name] = max(self.scalar_resources.get(name, 0), to_int(q))

    def add(self, other: "Resource") -> None:
        self.milli_cpu += other.milli_cpu
        self.memory += other.memory
        self.ephemeral_storage += other.ephemeral_storage
        self.allowed_pod_number += other.allowed_pod_number
        for k, v in other.scalar_resources.items():
            self.scalar_resources[k] = self.scalar_resources.get(k, 0) + v

    def sub(self, other: "Resource") -> None:
        self.milli_cpu -= other.milli_cpu
        self.memory -= other.memory
        self.ephemeral_storage -= other.ephemeral_storage
        self.allowed_pod_number -= other.allowed_pod_number
        for k, v in other.scalar_resources.items():
            self.scalar_resources[k] = self.scalar_resources.get(k, 0) - v

    def clone(self) -> "Resource":
        return Resource(self.milli_cpu, self.memory, self.ephemeral_storage,
                        self.allowed_pod_number, dict(self.scalar_resources))

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, Resource):
            return NotImplemented
        return (self.milli_cpu == other.milli_cpu and self.memory == other.memory
                and self.ephemeral_storage == other.ephemeral_storage
                and self.allowed_pod_number == other.allowed_pod_number
                and self.scalar_resources == other.scalar_resources)

    def __repr__(self) -> str:
        return (f"Resource(cpu={self.milli_cpu}m, mem={self.memory}, "
                f"eph={self.ephemeral_storage}, pods={self.allowed_pod_number}, "
                f"scalar={self.scalar_resources})")
