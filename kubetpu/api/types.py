"""Core object model: the subset of the Kubernetes API surface the scheduler
consumes, re-designed as plain Python dataclasses.

reference: staging/src/k8s.io/api/core/v1/types.go (Pod, Node, Affinity,
Toleration, TopologySpreadConstraint, ...).  Only scheduler-relevant fields
are modeled; everything is immutable-by-convention once handed to the
scheduler (snapshots never mutate objects — the TPU analog of the reference's
informer-cache read-only discipline).
"""

from __future__ import annotations

import itertools
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

# ---------------------------------------------------------------------------
# meta


_uid_counter = itertools.count(1)


def new_uid() -> str:
    return f"uid-{next(_uid_counter)}"


@dataclass
class OwnerReference:
    # reference: apimachinery/pkg/apis/meta/v1/types.go (OwnerReference)
    api_version: str = "v1"
    kind: str = ""
    name: str = ""
    uid: str = ""
    controller: bool = False


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    uid: str = field(default_factory=new_uid)
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    resource_version: int = 0
    creation_timestamp: float = field(default_factory=time.time)
    deletion_timestamp: Optional[float] = None
    owner_references: List[OwnerReference] = field(default_factory=list)


# ---------------------------------------------------------------------------
# selectors / affinity


@dataclass
class LabelSelectorRequirement:
    # reference: apimachinery/pkg/apis/meta/v1/types.go (LabelSelectorRequirement)
    key: str = ""
    operator: str = "In"  # In | NotIn | Exists | DoesNotExist
    values: List[str] = field(default_factory=list)


@dataclass
class LabelSelector:
    match_labels: Dict[str, str] = field(default_factory=dict)
    match_expressions: List[LabelSelectorRequirement] = field(default_factory=list)

    def requirements(self) -> List[LabelSelectorRequirement]:
        reqs = [LabelSelectorRequirement(k, "In", [v])
                for k, v in sorted(self.match_labels.items())]
        reqs.extend(self.match_expressions)
        return reqs

    def matches(self, labels: Dict[str, str]) -> bool:
        # reference: apimachinery/pkg/labels/selector.go (internalSelector.Matches)
        for r in self.requirements():
            if not _req_matches(r, labels):
                return False
        return True

    def is_empty(self) -> bool:
        return not self.match_labels and not self.match_expressions


def _req_matches(r: LabelSelectorRequirement, labels: Dict[str, str]) -> bool:
    has = r.key in labels
    if r.operator == "In":
        return has and labels[r.key] in r.values
    if r.operator == "NotIn":
        return not has or labels[r.key] not in r.values
    if r.operator == "Exists":
        return has
    if r.operator == "DoesNotExist":
        return not has
    if r.operator in ("Gt", "Lt"):
        if not has:
            return False
        try:
            lv = int(labels[r.key]); rv = int(r.values[0])
        except (ValueError, IndexError):
            return False
        return lv > rv if r.operator == "Gt" else lv < rv
    raise ValueError(f"unknown operator {r.operator}")


@dataclass
class NodeSelectorRequirement:
    key: str = ""
    operator: str = "In"  # In | NotIn | Exists | DoesNotExist | Gt | Lt
    values: List[str] = field(default_factory=list)


@dataclass
class NodeSelectorTerm:
    # Terms are ORed; requirements within a term are ANDed.
    match_expressions: List[NodeSelectorRequirement] = field(default_factory=list)
    match_fields: List[NodeSelectorRequirement] = field(default_factory=list)


@dataclass
class NodeSelector:
    node_selector_terms: List[NodeSelectorTerm] = field(default_factory=list)


@dataclass
class PreferredSchedulingTerm:
    weight: int = 1  # 1..100
    preference: NodeSelectorTerm = field(default_factory=NodeSelectorTerm)


@dataclass
class NodeAffinity:
    required_during_scheduling_ignored_during_execution: Optional[NodeSelector] = None
    preferred_during_scheduling_ignored_during_execution: List[PreferredSchedulingTerm] = \
        field(default_factory=list)


@dataclass
class PodAffinityTerm:
    # reference: api/core/v1/types.go (PodAffinityTerm)
    label_selector: Optional[LabelSelector] = None
    namespaces: List[str] = field(default_factory=list)  # empty => pod's own namespace
    topology_key: str = ""


@dataclass
class WeightedPodAffinityTerm:
    weight: int = 1  # 1..100
    pod_affinity_term: PodAffinityTerm = field(default_factory=PodAffinityTerm)


@dataclass
class PodAffinity:
    required_during_scheduling_ignored_during_execution: List[PodAffinityTerm] = \
        field(default_factory=list)
    preferred_during_scheduling_ignored_during_execution: List[WeightedPodAffinityTerm] = \
        field(default_factory=list)


@dataclass
class PodAntiAffinity:
    required_during_scheduling_ignored_during_execution: List[PodAffinityTerm] = \
        field(default_factory=list)
    preferred_during_scheduling_ignored_during_execution: List[WeightedPodAffinityTerm] = \
        field(default_factory=list)


@dataclass
class Affinity:
    node_affinity: Optional[NodeAffinity] = None
    pod_affinity: Optional[PodAffinity] = None
    pod_anti_affinity: Optional[PodAntiAffinity] = None


# ---------------------------------------------------------------------------
# taints / tolerations


TAINT_EFFECT_NO_SCHEDULE = "NoSchedule"
TAINT_EFFECT_PREFER_NO_SCHEDULE = "PreferNoSchedule"
TAINT_EFFECT_NO_EXECUTE = "NoExecute"


@dataclass
class Taint:
    key: str = ""
    value: str = ""
    effect: str = TAINT_EFFECT_NO_SCHEDULE


@dataclass
class Toleration:
    key: str = ""  # empty + Exists => tolerates everything
    operator: str = "Equal"  # Equal | Exists
    value: str = ""
    effect: str = ""  # empty => all effects
    toleration_seconds: Optional[int] = None

    def tolerates(self, taint: Taint) -> bool:
        # reference: api/core/v1/toleration.go:28 (ToleratesTaint)
        if self.effect and self.effect != taint.effect:
            return False
        if self.key and self.key != taint.key:
            return False
        if self.operator in ("", "Equal"):
            return self.value == taint.value
        if self.operator == "Exists":
            return True
        return False


def tolerations_tolerate_taint(tolerations: List[Toleration], taint: Taint) -> bool:
    # reference: pkg/apis/core/v1/helper/helpers.go (TolerationsTolerateTaint)
    return any(t.tolerates(taint) for t in tolerations)


# ---------------------------------------------------------------------------
# pods


@dataclass
class ContainerPort:
    host_ip: str = ""
    host_port: int = 0
    container_port: int = 0
    protocol: str = "TCP"


@dataclass
class ResourceRequirements:
    requests: Dict[str, Any] = field(default_factory=dict)
    limits: Dict[str, Any] = field(default_factory=dict)


@dataclass
class Container:
    name: str = ""
    image: str = ""
    resources: ResourceRequirements = field(default_factory=ResourceRequirements)
    ports: List[ContainerPort] = field(default_factory=list)


@dataclass
class Volume:
    name: str = ""
    # Exactly one of these sources is set (scheduler-relevant subset).
    persistent_volume_claim: Optional[str] = None  # claim name
    gce_persistent_disk: Optional[str] = None      # pd name
    aws_elastic_block_store: Optional[str] = None  # volume id
    azure_disk: Optional[str] = None               # disk name
    cinder: Optional[str] = None                   # volume id
    iscsi: Optional[Tuple[str, int, str]] = None   # (target portal, lun, iqn)
    rbd: Optional[Tuple[str, str, str]] = None     # (monitors-key, pool, image)
    read_only: bool = False
    host_path: Optional[str] = None
    empty_dir: bool = False


@dataclass
class TopologySpreadConstraint:
    max_skew: int = 1
    topology_key: str = ""
    when_unsatisfiable: str = "DoNotSchedule"  # DoNotSchedule | ScheduleAnyway
    label_selector: Optional[LabelSelector] = None


@dataclass
class PodSpec:
    node_name: str = ""
    scheduler_name: str = "default-scheduler"
    priority: Optional[int] = None
    priority_class_name: str = ""
    containers: List[Container] = field(default_factory=list)
    init_containers: List[Container] = field(default_factory=list)
    overhead: Dict[str, Any] = field(default_factory=dict)
    node_selector: Dict[str, str] = field(default_factory=dict)
    affinity: Optional[Affinity] = None
    tolerations: List[Toleration] = field(default_factory=list)
    topology_spread_constraints: List[TopologySpreadConstraint] = field(default_factory=list)
    volumes: List[Volume] = field(default_factory=list)
    host_network: bool = False
    service_account_name: str = ""


@dataclass
class PodCondition:
    type: str = ""
    status: str = ""  # True | False | Unknown
    reason: str = ""
    message: str = ""
    last_transition_time: float = 0.0


POD_PENDING = "Pending"
POD_RUNNING = "Running"
POD_SUCCEEDED = "Succeeded"
POD_FAILED = "Failed"

POD_SCHEDULED = "PodScheduled"  # condition type
REASON_UNSCHEDULABLE = "Unschedulable"


@dataclass
class PodStatus:
    phase: str = POD_PENDING
    nominated_node_name: str = ""
    conditions: List[PodCondition] = field(default_factory=list)


@dataclass
class Pod:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)
    kind: str = "Pod"

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

    @property
    def uid(self) -> str:
        return self.metadata.uid

    def full_name(self) -> str:
        # reference: pkg/scheduler/util/utils.go (GetPodFullName)
        return f"{self.metadata.name}_{self.metadata.namespace}"

    def priority(self) -> int:
        # reference: pkg/api/v1/pod/util.go (PodPriority)
        return self.spec.priority if self.spec.priority is not None else 0


# ---------------------------------------------------------------------------
# nodes


@dataclass
class ContainerImage:
    names: List[str] = field(default_factory=list)
    size_bytes: int = 0


@dataclass
class NodeSpec:
    unschedulable: bool = False
    taints: List[Taint] = field(default_factory=list)
    provider_id: str = ""


@dataclass
class NodeCondition:
    type: str = ""
    status: str = ""


@dataclass
class NodeStatus:
    capacity: Dict[str, Any] = field(default_factory=dict)
    allocatable: Dict[str, Any] = field(default_factory=dict)
    images: List[ContainerImage] = field(default_factory=list)
    conditions: List[NodeCondition] = field(default_factory=list)


@dataclass
class Node:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: NodeSpec = field(default_factory=NodeSpec)
    status: NodeStatus = field(default_factory=NodeStatus)
    kind: str = "Node"

    @property
    def name(self) -> str:
        return self.metadata.name


# Well-known labels (reference: pkg/apis/core/v1/well_known_labels.go).
LABEL_HOSTNAME = "kubernetes.io/hostname"
LABEL_ZONE = "topology.kubernetes.io/zone"
LABEL_REGION = "topology.kubernetes.io/region"
LABEL_ZONE_LEGACY = "failure-domain.beta.kubernetes.io/zone"
LABEL_REGION_LEGACY = "failure-domain.beta.kubernetes.io/region"

# Annotation consumed by NodePreferAvoidPods
# (reference: pkg/apis/core/v1/helper/helpers.go:239 GetAvoidPodsFromNodeAnnotations).
PREFER_AVOID_PODS_ANNOTATION_KEY = "scheduler.alpha.kubernetes.io/preferAvoidPods"


# ---------------------------------------------------------------------------
# misc cluster objects the plugins consume


@dataclass
class PersistentVolumeClaim:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    volume_name: str = ""          # bound PV name ("" => unbound)
    storage_class_name: str = ""
    phase: str = "Pending"
    # matching requirements an unbound claim imposes on candidate PVs
    # (reference: pv_controller findMatchingVolume): requested storage
    # under resources.requests["storage"], and the claim's access modes —
    # a PV must offer a SUPERSET.  Empty = unconstrained (back-compat).
    access_modes: List[str] = field(default_factory=list)
    resources: ResourceRequirements = field(
        default_factory=ResourceRequirements)
    kind: str = "PersistentVolumeClaim"


@dataclass
class PersistentVolume:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    capacity: Dict[str, Any] = field(default_factory=dict)
    access_modes: List[str] = field(default_factory=list)
    node_affinity: Optional[NodeSelector] = None
    storage_class_name: str = ""
    # volume source (scheduler-relevant subset, for NodeVolumeLimits)
    aws_elastic_block_store: Optional[str] = None   # volume id
    gce_persistent_disk: Optional[str] = None       # pd name
    azure_disk: Optional[str] = None                # disk name
    cinder: Optional[str] = None                    # volume id
    csi_driver: Optional[str] = None                # driver name
    csi_volume_handle: Optional[str] = None
    kind: str = "PersistentVolume"


@dataclass
class StorageClass:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    volume_binding_mode: str = "Immediate"  # Immediate | WaitForFirstConsumer
    provisioner: str = ""  # e.g. kubernetes.io/aws-ebs
    kind: str = "StorageClass"


@dataclass
class Service:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    selector: Dict[str, str] = field(default_factory=dict)
    kind: str = "Service"


@dataclass
class ReplicaSet:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    selector: LabelSelector = field(default_factory=LabelSelector)
    kind: str = "ReplicaSet"


@dataclass
class ReplicationController:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    selector: Dict[str, str] = field(default_factory=dict)
    kind: str = "ReplicationController"


@dataclass
class StatefulSet:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    selector: LabelSelector = field(default_factory=LabelSelector)
    kind: str = "StatefulSet"


@dataclass
class PodDisruptionBudget:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    selector: LabelSelector = field(default_factory=LabelSelector)
    disruptions_allowed: int = 0
    kind: str = "PodDisruptionBudget"


@dataclass
class CSINode:
    """Per-node CSI driver allocatable counts
    (reference: staging/src/k8s.io/api/storage/v1/types.go CSINode)."""
    metadata: ObjectMeta = field(default_factory=ObjectMeta)  # name == node name
    driver_allocatable: Dict[str, int] = field(default_factory=dict)  # driver -> count
    kind: str = "CSINode"
