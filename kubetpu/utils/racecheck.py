"""Instrumented race harness: the dynamic half of the concurrency contract.

kubelint's concurrency family (tools/kubelint/rules_concurrency.py) proves
lock discipline statically; this module enforces it on a LIVE schedule,
behind one opt-in switch (``KUBETPU_RACE=1``), in the spirit of the Go
race detector the reference tree runs in CI:

  * lock instrumentation — ``threading.Lock/RLock/Condition`` constructed
    from kubetpu modules return proxies that record per-thread acquisition
    stacks and hold times;
  * runtime lock-order enforcement — the first-seen acquisition order
    between any two lock roles becomes the declared order; acquiring them
    inverted later is reported (the dynamic mirror of the static
    ``concurrency/lock-order`` rule);
  * held-too-long — a lock held longer than ``KUBETPU_RACE_HOLD_MS``
    (default 200) is reported with the holder's stack: device work or I/O
    under a lock is exactly the convoy the verdict's chain/pipeline
    regression smells of;
  * guarded-attribute enforcement — the classes in ``GUARDED`` (the same
    ownership map the static family infers) get their ``__setattr__``
    wrapped and their container attributes replaced with checking
    subclasses, so every rebind / dict / list mutation asserts the owning
    lock is held by the mutating thread; a sampling ``sys.setprofile``
    hook additionally catches C-level mutator calls (``dict.pop``,
    ``OrderedDict.move_to_end``…) on guarded containers the subclassing
    cannot reach.  Violations are collected, and ``racechecked()`` asserts
    none happened on teardown.

Coverage envelope (documented, not bugs): reads are not checked (no write
barrier in CPython), subscript stores on non-wrapped container types are
only caught by the profile hook's c_call events, and locks created before
arming stay uninstrumented.  ``sys.setprofile`` is per-thread: threads
spawned while armed keep the (disarmed, short-circuiting) hook after
``disable_racecheck`` — only a process that was never armed pays exactly
nothing.  Off (the default) this module changes nothing and costs
nothing.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

ENV_FLAG = "KUBETPU_RACE"

# the runtime ownership map: mirrors what `python -m tools.kubelint
# kubetpu/ --lock-graph` derives statically.  (module, class) -> (lock
# attr, guarded attrs)
GUARDED: Dict[Tuple[str, str], Tuple[str, Tuple[str, ...]]] = {
    ("kubetpu.state.cache", "SchedulerCache"):
        ("_lock", ("nodes", "head", "node_tree", "assumed_pods",
                   "pod_states")),
    ("kubetpu.schedqueue.queue", "PodNominator"):
        ("_lock", ("_nominated", "_nominated_pod_to_node")),
    ("kubetpu.schedqueue.queue", "SchedulingQueue"):
        ("_cond", ("active_q", "backoff_q", "unschedulable_q",
                   "scheduling_cycle", "move_request_cycle", "_closed")),
    ("kubetpu.client.store", "ClusterStore"):
        ("_lock", ("_objs", "_subs", "_assumed_pv")),
    ("kubetpu.utils.events", "EventBroadcaster"):
        ("_lock", ("_cache", "_seq", "_watchers")),
    ("kubetpu.utils.features", "FeatureGate"):
        ("_lock", ("_known", "_enabled")),
    ("kubetpu.scheduler", "Scheduler"):
        ("_chain_lock", ("_chain", "_chain_seq")),
}

_MUTATOR_NAMES = frozenset(
    {"append", "extend", "add", "update", "insert", "setdefault", "pop",
     "popitem", "remove", "discard", "clear", "move_to_end", "appendleft",
     "__setitem__", "__delitem__"})


def _stack(skip: int = 2, limit: int = 8) -> str:
    frames = traceback.format_stack()[:-skip]
    return "".join(frames[-limit:])


class Violation:
    __slots__ = ("kind", "message", "stack", "thread")

    def __init__(self, kind: str, message: str, stack: str = ""):
        self.kind = kind
        self.message = message
        self.stack = stack
        self.thread = threading.current_thread().name

    def __str__(self) -> str:
        s = "[%s] (%s) %s" % (self.kind, self.thread, self.message)
        if self.stack:
            s += "\n" + self.stack
        return s


class _Registry:
    """Process-wide harness state: violations, the lock-order graph, and
    the per-thread held-lock stacks."""

    def __init__(self):
        self.armed = False
        self.hold_ms = 200.0
        self.sample = 1
        self._mu = threading.Lock()
        self.violations: List[Violation] = []  # kubelint: guarded-by(_mu)
        # lock-order edges: (a, b) means a was held while b was acquired
        self.edges: Dict[Tuple[str, str], str] = {}  # kubelint: guarded-by(_mu)
        self._tls = threading.local()
        # id(container) -> (attr description, weakref to owner, lock attr);
        # a finalizer on the container prunes the entry, so a freed
        # container's recycled id can never match a stale record
        self.tracked: Dict[int, Tuple[str, object, str]] = {}  # kubelint: guarded-by(_mu)

    # -- per-thread held stack ---------------------------------------------

    def held(self) -> List["_LockProxy"]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    # -- violations ---------------------------------------------------------

    def report(self, kind: str, message: str, stack: str = "") -> None:
        v = Violation(kind, message, stack)
        with self._mu:
            self.violations.append(v)

    def snapshot(self) -> List[Violation]:
        with self._mu:
            return list(self.violations)

    def reset(self) -> None:
        with self._mu:
            self.violations = []
            self.edges = {}

    # -- lock order ---------------------------------------------------------

    def note_acquire(self, proxy: "_LockProxy") -> None:
        held = self.held()
        if held:
            b = proxy.name
            inversions = []
            with self._mu:
                # inversion: a path b -> ... -> a already exists for some
                # held a, so acquiring b after a contradicts declared order
                for h in held:
                    a = h.name
                    if a == b:
                        continue
                    if self._path(b, a):
                        inversions.append(a)
                for h in held:
                    a = h.name
                    if a != b:
                        self.edges.setdefault((a, b),
                                              "%s then %s" % (a, b))
            for a in inversions:  # outside _mu: report() re-acquires it
                self.report(
                    "lock-order",
                    "acquired %s while holding %s, but the declared order "
                    "(first seen) is %s before %s" % (b, a, b, a), _stack())
        held.append(proxy)

    def _path(self, src: str, dst: str) -> bool:
        seen = {src}
        stack = [src]
        while stack:
            n = stack.pop()
            for (a, b) in self.edges:
                if a == n and b not in seen:
                    if b == dst:
                        return True
                    seen.add(b)
                    stack.append(b)
        return False

    def note_release(self, proxy: "_LockProxy", held_s: float) -> None:
        held = self.held()
        if proxy in held:
            held.remove(proxy)
        if held_s * 1000.0 > self.hold_ms:
            self.report(
                "held-too-long",
                "%s held for %.1f ms (threshold %.0f ms) — blocking work "
                "under a lock convoys every contending thread"
                % (proxy.name, held_s * 1000.0, self.hold_ms), _stack())

    # -- guarded containers --------------------------------------------------

    def track_container(self, obj, desc: str, owner, lock_attr: str) -> None:
        import weakref
        try:
            # plain set (and other non-weakrefable containers) can't carry
            # a finalizer: skip rather than risk id-reuse false positives
            weakref.finalize(obj, self._untrack, id(obj))
            owner_ref = weakref.ref(owner)
        except TypeError:
            return
        with self._mu:
            self.tracked[id(obj)] = (desc, owner_ref, lock_attr)

    def _untrack(self, obj_id: int) -> None:
        with self._mu:
            self.tracked.pop(obj_id, None)

    def check_owned(self, desc: str, owner, lock_attr: str) -> None:
        lock = getattr(owner, lock_attr, None)
        if isinstance(lock, _ConditionProxy):
            lock = lock._lockp
        if isinstance(lock, _LockProxy) and not lock.held_by_current():
            self.report(
                "unguarded-mutation",
                "%s mutated without holding %s" % (desc, lock_attr),
                _stack(skip=3))


_REG = _Registry()


def registry() -> _Registry:
    return _REG


# ---------------------------------------------------------------------------
# lock proxies


class _LockProxy:
    """Wraps a real Lock/RLock with ownership + order + hold-time
    bookkeeping.  Named after the owning ``Class.attr`` once assigned to a
    guarded class; anonymous locks keep their creation site, which groups
    instances of the same role."""

    _reentrant = False

    def __init__(self, real, name: str):
        self._real = real
        self.name = name
        self._owner: Optional[int] = None
        self._count = 0
        self._t0 = 0.0

    def acquire(self, blocking: bool = True, timeout: float = -1):
        me = threading.get_ident()
        if self._owner == me:
            if not self._reentrant:
                _REG.report(
                    "lock-order",
                    "re-acquiring non-reentrant %s already held by this "
                    "thread — deadlock" % self.name, _stack())
            else:
                self._count += 1
                return self._real.acquire(blocking, timeout)
        ok = self._real.acquire(blocking, timeout)
        if ok:
            self._owner = me
            self._count = 1
            self._t0 = time.monotonic()
            _REG.note_acquire(self)
        return ok

    def release(self):
        me = threading.get_ident()
        if self._owner == me:
            self._count -= 1
            if self._count <= 0:
                held_s = time.monotonic() - self._t0
                self._owner = None
                _REG.note_release(self, held_s)
        return self._real.release()

    def held_by_current(self) -> bool:
        return self._owner == threading.get_ident()

    def locked(self):
        return self._real.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class _RLockProxy(_LockProxy):
    _reentrant = True


class _ConditionProxy:
    """Condition over an instrumented lock: wait() hands the lock back
    (bookkeeping included) and re-registers it on wake."""

    def __init__(self, lock_proxy: _LockProxy):
        self._lockp = lock_proxy
        self._real = threading.Condition(lock_proxy._real)

    @property
    def name(self) -> str:
        return self._lockp.name

    @name.setter
    def name(self, v: str) -> None:
        self._lockp.name = v

    def acquire(self, *a, **k):
        return self._lockp.acquire(*a, **k)

    def release(self):
        return self._lockp.release()

    def held_by_current(self) -> bool:
        return self._lockp.held_by_current()

    def __enter__(self):
        self._lockp.acquire()
        return self

    def __exit__(self, *exc):
        self._lockp.release()
        return False

    def _pre_wait(self) -> None:
        lp = self._lockp
        held_s = time.monotonic() - lp._t0
        lp._owner = None
        lp._count = 0
        _REG.note_release(lp, held_s)

    def _post_wait(self) -> None:
        lp = self._lockp
        lp._owner = threading.get_ident()
        lp._count = 1
        lp._t0 = time.monotonic()
        _REG.note_acquire(lp)

    def wait(self, timeout: Optional[float] = None):
        self._pre_wait()
        try:
            return self._real.wait(timeout)
        finally:
            self._post_wait()

    def wait_for(self, predicate, timeout: Optional[float] = None):
        self._pre_wait()
        try:
            return self._real.wait_for(predicate, timeout)
        finally:
            self._post_wait()

    def notify(self, n: int = 1):
        return self._real.notify(n)

    def notify_all(self):
        return self._real.notify_all()


# ---------------------------------------------------------------------------
# guarded containers


def _check(desc_owner) -> None:
    desc, owner, lock_attr = desc_owner
    if _REG.armed:
        _REG.check_owned(desc, owner, lock_attr)


class _GuardedDict(dict):
    __slots__ = ("_rc",)

    def __setitem__(self, k, v):
        _check(self._rc)
        return dict.__setitem__(self, k, v)

    def __delitem__(self, k):
        _check(self._rc)
        return dict.__delitem__(self, k)

    def pop(self, *a):
        _check(self._rc)
        return dict.pop(self, *a)

    def popitem(self):
        _check(self._rc)
        return dict.popitem(self)

    def update(self, *a, **k):
        _check(self._rc)
        return dict.update(self, *a, **k)

    def setdefault(self, *a):
        _check(self._rc)
        return dict.setdefault(self, *a)

    def clear(self):
        _check(self._rc)
        return dict.clear(self)


class _GuardedList(list):
    __slots__ = ("_rc",)

    def append(self, x):
        _check(self._rc)
        return list.append(self, x)

    def extend(self, it):
        _check(self._rc)
        return list.extend(self, it)

    def insert(self, i, x):
        _check(self._rc)
        return list.insert(self, i, x)

    def pop(self, *a):
        _check(self._rc)
        return list.pop(self, *a)

    def remove(self, x):
        _check(self._rc)
        return list.remove(self, x)

    def clear(self):
        _check(self._rc)
        return list.clear(self)

    def __setitem__(self, i, v):
        _check(self._rc)
        return list.__setitem__(self, i, v)

    def __delitem__(self, i):
        _check(self._rc)
        return list.__delitem__(self, i)


# ---------------------------------------------------------------------------
# arming / disarming


class _PatchState:
    def __init__(self):
        self.active = False
        self.orig_lock = None
        self.orig_rlock = None
        self.orig_condition = None
        self.wrapped_setattrs: List[Tuple[type, object, bool]] = []
        self.prev_profile = None


_patch = _PatchState()
_patch_mu = threading.Lock()


def race_enabled() -> bool:
    return os.environ.get(ENV_FLAG, "0") not in ("", "0", "false", "False")


def _kubetpu_caller() -> bool:
    try:
        mod = sys._getframe(2).f_globals.get("__name__", "")
    except ValueError:
        return False
    return mod == "kubetpu" or mod.startswith("kubetpu.")


def _site() -> str:
    try:
        f = sys._getframe(2)
        return "%s:%d" % (os.path.basename(f.f_code.co_filename), f.f_lineno)
    except ValueError:
        return "<unknown>"


def _make_lock_factory(real_cls, proxy_cls):
    def factory(*a, **k):
        if not _REG.armed or not _kubetpu_caller():
            return real_cls(*a, **k)
        return proxy_cls(real_cls(*a, **k), name="lock@" + _site())
    return factory


def _condition_factory(real_condition):
    def factory(lock=None, *a, **k):
        if not _REG.armed or not _kubetpu_caller():
            return real_condition(lock, *a, **k)
        if isinstance(lock, _LockProxy):
            proxy = _ConditionProxy(lock)
        elif lock is not None:
            return real_condition(lock, *a, **k)
        else:
            proxy = _ConditionProxy(
                _RLockProxy(_patch.orig_rlock(), name="cond@" + _site()))
        return proxy
    return factory


def _wrap_setattr(cls, lock_attr: str, attrs: Tuple[str, ...]):
    orig = cls.__setattr__
    had_own = "__setattr__" in cls.__dict__

    def guarded_setattr(self, name, value, _orig=orig, _lock=lock_attr,
                        _attrs=frozenset(attrs), _cname=cls.__name__):
        if _REG.armed:
            # name the lock proxy after its owning class+attr so order
            # edges and reports read as roles, not object ids
            if name == _lock and isinstance(value,
                                            (_LockProxy, _ConditionProxy)):
                value.name = "%s.%s" % (_cname, _lock)
            if name in _attrs:
                first = name not in self.__dict__
                if not first:
                    # rebind of a guarded attr on a live (shared) object
                    _REG.check_owned("%s.%s" % (_cname, name), self, _lock)
                desc = "%s.%s" % (_cname, name)
                if type(value) is dict:
                    value = _GuardedDict(value)
                    value._rc = (desc, self, _lock)
                elif type(value) is list:
                    value = _GuardedList(value)
                    value._rc = (desc, self, _lock)
                elif isinstance(value, (dict, list, set)):
                    # subclassed containers (OrderedDict…): the profile
                    # hook covers their C-level mutators
                    _REG.track_container(value, desc, self, _lock)
        return _orig(self, name, value)

    cls.__setattr__ = guarded_setattr
    _patch.wrapped_setattrs.append((cls, orig, had_own))


def _profile_hook(frame, event, arg):
    """Sampling c_call hook: catches C-level mutators on guarded
    containers the subclass wrapping cannot reach."""
    if event != "c_call" or not _REG.armed:
        return
    tls = _REG._tls
    n = getattr(tls, "n", 0) + 1
    tls.n = n
    if n % _REG.sample:
        return
    try:
        name = getattr(arg, "__name__", "")
        if name not in _MUTATOR_NAMES:
            return
        target = getattr(arg, "__self__", None)
        if target is None:
            return
        rec = _REG.tracked.get(id(target))
        if rec is not None:
            desc, owner_ref, lock_attr = rec
            owner = owner_ref()
            if owner is not None:
                _check((desc, owner, lock_attr))
    except Exception:
        pass


def _import_guarded_classes():
    out = []
    import importlib
    for (mod_name, cls_name), (lock_attr, attrs) in GUARDED.items():
        try:
            mod = importlib.import_module(mod_name)
            cls = getattr(mod, cls_name)
        except Exception:
            # never let a silent import failure shrink the harness's
            # coverage unnoticed — the race gate would report a false clean
            import logging
            logging.getLogger("kubetpu.racecheck").warning(
                "racecheck: cannot instrument %s.%s (import failed); "
                "guarded-attr checks for it are OFF", mod_name, cls_name,
                exc_info=True)
            continue
        out.append((cls, lock_attr, attrs))
    return out


def enable_racecheck(hold_ms: Optional[float] = None,
                     sample: Optional[int] = None) -> _Registry:
    """Idempotently arm the harness.  Locks/objects created AFTER this
    call are instrumented; pre-existing ones are not (document in tests:
    build the system inside the armed scope)."""
    with _patch_mu:
        if _patch.active:
            return _REG
        _REG.hold_ms = (hold_ms if hold_ms is not None else
                        float(os.environ.get("KUBETPU_RACE_HOLD_MS", "200")))
        _REG.sample = max(1, int(sample if sample is not None else
                                 os.environ.get("KUBETPU_RACE_SAMPLE", "1")))
        _patch.orig_lock = threading.Lock
        _patch.orig_rlock = threading.RLock
        _patch.orig_condition = threading.Condition
        threading.Lock = _make_lock_factory(_patch.orig_lock, _LockProxy)
        threading.RLock = _make_lock_factory(_patch.orig_rlock, _RLockProxy)
        threading.Condition = _condition_factory(_patch.orig_condition)
        for cls, lock_attr, attrs in _import_guarded_classes():
            _wrap_setattr(cls, lock_attr, attrs)
        _patch.prev_profile = sys.getprofile()
        threading.setprofile(_profile_hook)
        sys.setprofile(_profile_hook)
        _REG.armed = True
        _patch.active = True
        return _REG


def disable_racecheck() -> None:
    """Restore everything enable touched.  Already-created proxies keep
    working as plain locks; checks stop (armed=False)."""
    with _patch_mu:
        if not _patch.active:
            return
        _REG.armed = False
        threading.Lock = _patch.orig_lock
        threading.RLock = _patch.orig_rlock
        threading.Condition = _patch.orig_condition
        for cls, orig, had_own in _patch.wrapped_setattrs:
            if had_own:
                cls.__setattr__ = orig
            else:
                # the class inherited __setattr__; deleting our wrapper
                # restores inheritance instead of pinning a stale copy
                try:
                    del cls.__setattr__
                except AttributeError:
                    pass
        _patch.wrapped_setattrs = []
        threading.setprofile(None)
        sys.setprofile(_patch.prev_profile)
        _patch.prev_profile = None
        _patch.active = False


def assert_clean() -> None:
    vs = _REG.snapshot()
    if vs:
        raise AssertionError(
            "racecheck: %d violation%s —\n%s"
            % (len(vs), "" if len(vs) == 1 else "s",
               "\n".join(str(v) for v in vs)))


@contextmanager
def racechecked(strict: bool = True, hold_ms: Optional[float] = None,
                sample: Optional[int] = None):
    """Scoped harness for tests::

        with racechecked() as rc:
            sched = Scheduler(store)     # built INSIDE the armed scope
            ...hammer it from threads...
        # strict=True asserts zero violations on exit

    Joining an already-armed harness (KUBETPU_RACE=1 at import) resets the
    violation list so the block judges only its own work, and leaves the
    harness running on exit."""
    owned = not _patch.active
    reg = enable_racecheck(hold_ms=hold_ms, sample=sample)
    prev_hold, prev_sample = reg.hold_ms, reg.sample
    if not owned:
        # joining an env-armed harness: scope the violation list AND any
        # threshold overrides to this block — leaking a stress test's
        # relaxed hold_ms into later tests would silently weaken the gate
        reg.reset()
        if hold_ms is not None:
            reg.hold_ms = hold_ms
        if sample is not None:
            reg.sample = max(1, int(sample))
    try:
        yield reg
        if strict:
            assert_clean()
    finally:
        if owned:
            disable_racecheck()
        else:
            reg.hold_ms, reg.sample = prev_hold, prev_sample
        reg.reset()


def maybe_enable_from_env() -> Optional[_Registry]:
    """Serving-path hook mirroring utils/sanitize.py: arms the harness iff
    KUBETPU_RACE=1, called from kubetpu/__init__ so every entry point gets
    it without its own wiring."""
    if race_enabled():
        return enable_racecheck()
    return None
