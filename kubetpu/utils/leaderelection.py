"""Leader election over a lease object.

reference: staging/src/k8s.io/client-go/tools/leaderelection/
leaderelection.go:111 (LeaderElector: acquire/renew loop over a
resourcelock) and cmd/kube-scheduler/app/server.go:203-218 (scheduler
exits when it loses the lease).  The TPU mesh is a single logical
scheduler; leader election provides HA of the *host process* exactly as in
the reference (SURVEY.md §2.3 multi-process scale-out).

The lock backend is pluggable; LeaseLock works against any object with
get/update/create semantics — in-process it uses the ClusterStore so
integration tests can run two contending schedulers.
"""

from __future__ import annotations

import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Callable, Optional

DEFAULT_LEASE_DURATION = 15.0   # reference: leaderelection defaults
DEFAULT_RENEW_DEADLINE = 10.0
DEFAULT_RETRY_PERIOD = 2.0


@dataclass
class LeaseRecord:
    holder: str = ""
    acquire_time: float = 0.0
    renew_time: float = 0.0
    lease_duration: float = DEFAULT_LEASE_DURATION


def _acquire_or_renew(rec: LeaseRecord, identity: str, lease_duration: float,
                      now: float) -> bool:
    """The lease decision shared by every lock backend (reference:
    leaderelection.go:326 tryAcquireOrRenew).  Mutates rec on success."""
    expired = now > rec.renew_time + rec.lease_duration
    if rec.holder and rec.holder != identity and not expired:
        return False
    if rec.holder != identity:
        rec.holder = identity
        rec.acquire_time = now
    rec.renew_time = now
    rec.lease_duration = lease_duration
    return True


class InMemoryLock:
    """Shared lock object (the coordination/v1 Lease analog)."""

    def __init__(self):
        self._rec = LeaseRecord()
        self._mu = threading.Lock()

    def get(self) -> LeaseRecord:
        with self._mu:
            return LeaseRecord(**vars(self._rec))

    def try_acquire_or_renew(self, identity: str, lease_duration: float,
                             now: float) -> bool:
        with self._mu:
            return _acquire_or_renew(self._rec, identity, lease_duration, now)

    def release(self, identity: str) -> None:
        with self._mu:
            if self._rec.holder == identity:
                self._rec = LeaseRecord()


class FileLock:
    """Lease record persisted as a JSON file — the cross-PROCESS lock
    backend for `python -m kubetpu` (the coordination/v1 Lease analog for
    standalone runs; reference resourcelock interface:
    client-go/tools/leaderelection/resourcelock/interface.go).  The whole
    read-modify-write runs under an fcntl.flock on a sidecar .lock file, so
    contending PROCESSES serialize exactly like the reference's CAS against
    the apiserver's resourceVersion; record writes are atomic (tmp+rename)
    so readers never see a torn file."""

    def __init__(self, path: str):
        self.path = path
        self._mu = threading.Lock()

    def _read(self) -> LeaseRecord:
        import json
        import os
        if not os.path.exists(self.path):
            return LeaseRecord()
        try:
            with open(self.path) as f:
                return LeaseRecord(**json.load(f))
        except Exception:
            return LeaseRecord()

    def _write(self, rec: LeaseRecord) -> None:
        import json
        import os
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(vars(rec), f)
        os.replace(tmp, self.path)

    def _flocked(self, fn):
        import fcntl
        with self._mu:
            with open(f"{self.path}.lock", "w") as lf:
                # kubelint: ignore[concurrency/blocking-under-lock] holding _mu across flock IS the design: in-process threads serialize behind the same cross-process critical section, mirroring the apiserver CAS
                fcntl.flock(lf, fcntl.LOCK_EX)
                try:
                    return fn()
                finally:
                    # kubelint: ignore[concurrency/blocking-under-lock] LOCK_UN never blocks; same audited critical section as above
                    fcntl.flock(lf, fcntl.LOCK_UN)

    def get(self) -> LeaseRecord:
        return self._flocked(self._read)

    def try_acquire_or_renew(self, identity: str, lease_duration: float,
                             now: float) -> bool:
        def attempt():
            rec = self._read()
            if not _acquire_or_renew(rec, identity, lease_duration, now):
                return False
            self._write(rec)
            return True
        return self._flocked(attempt)

    def release(self, identity: str) -> None:
        def rel():
            if self._read().holder == identity:
                self._write(LeaseRecord())
        self._flocked(rel)


class LeaderElector:
    """reference: leaderelection.go:111 LeaderElector.Run — OnStartedLeading
    / OnStoppedLeading callbacks; stopping leadership is fatal for the
    scheduler process (server.go:217 klog.Fatalf)."""

    def __init__(self, lock: InMemoryLock,
                 on_started_leading: Callable[[], None],
                 on_stopped_leading: Callable[[], None],
                 identity: Optional[str] = None,
                 lease_duration: float = DEFAULT_LEASE_DURATION,
                 retry_period: float = DEFAULT_RETRY_PERIOD,
                 clock: Callable[[], float] = time.time):
        self.lock = lock
        self.identity = identity or f"sched-{uuid.uuid4().hex[:8]}"
        self.on_started = on_started_leading
        self.on_stopped = on_stopped_leading
        self.lease_duration = lease_duration
        self.retry_period = retry_period
        self._clock = clock
        self._stop = threading.Event()
        self.is_leader = False
        self._thread: Optional[threading.Thread] = None

    def run(self, block: bool = False) -> None:
        def loop():
            while not self._stop.is_set():
                ok = self.lock.try_acquire_or_renew(
                    self.identity, self.lease_duration, self._clock())
                if ok and not self.is_leader:
                    self.is_leader = True
                    self.on_started()
                elif not ok and self.is_leader:
                    # lost the lease — fatal for the real process
                    self.is_leader = False
                    self.on_stopped()
                    return
                self._stop.wait(self.retry_period)
        if block:
            loop()
        else:
            self._thread = threading.Thread(target=loop, daemon=True)
            self._thread.start()

    def step(self) -> bool:
        """Single non-blocking acquire/renew attempt (for tests)."""
        ok = self.lock.try_acquire_or_renew(
            self.identity, self.lease_duration, self._clock())
        if ok and not self.is_leader:
            self.is_leader = True
            self.on_started()
        elif not ok and self.is_leader:
            self.is_leader = False
            self.on_stopped()
        return self.is_leader

    def release(self) -> None:
        """Idempotent: stops the renew loop, joins it (it sleeps on the
        stop event between attempts), then gives up the lease so another
        elector can acquire immediately."""
        self._stop.set()
        t = self._thread
        if (t is not None and t is not threading.current_thread()
                and t.is_alive()):
            t.join(timeout=2.0)
        self._thread = None
        if self.is_leader:
            self.lock.release(self.identity)
            self.is_leader = False
