"""Analytic FLOP accounting for the device scheduling programs.

The gang auction's device time is dominated by MXU contractions: the
same-pair matmuls that re-evaluate topology filters/scores per round
([S, P] x [P, N] per active topology key, plus [S, N] x [N, N] pair
registration), the existing-term contractions ([Et, W] x [Et, N]), and the
per-node count matmul.  This module prices those per round, with the round
width following the windowed-residual schedule (round 1 at B, residual
rounds at the window width), so benchmarks can report achieved TFLOP/s and
MFU against the chip's peak.

The model counts the IN-ROUND matmul FLOPs only (2*m*n*k per contraction);
the once-per-cycle precomputation (selector matches, static filters/scores)
and all elementwise work are excluded, so reported MFU is a LOWER bound.

Reference anchor: these matmuls replace the O(pods x nodes) hot loops of
pkg/scheduler/framework/plugins/interpodaffinity/scoring.go:128-199 and
podtopologyspread/scoring.go:108-169.
"""

from __future__ import annotations

import os


def peak_flops_per_s() -> float:
    """Chip peak for the dtype the kernels contract in (bf16 inputs, f32
    accumulate).  Default: TPU v5e, 197 TFLOP/s bf16.  Override with
    KUBETPU_PEAK_TFLOPS for other parts."""
    return float(os.environ.get("KUBETPU_PEAK_TFLOPS", "197")) * 1e12


def gang_cycle_flops(cluster, batch, cfg, rounds: int,
                     residual_window: int = 512,
                     intra_batch_topology: bool = True,
                     kernel_backend: str = "lax") -> float:
    """Matmul FLOPs of one gang-auction cycle (schedule_gang) given the
    executed round count (GangResult.rounds / packed[3B]).

    kernel_backend="pallas": rounds 1+ run the fused megakernel
    (ops/pallas_kernels.py), whose per-round matmul work collapses to the
    fit/resource elementwise sweep plus the small zone contraction — the
    interpod/default-spread raw matrices are precomputed ONCE (inside
    round 0's accounting) instead of recontracted per round, which is
    exactly the HBM/FLOP reduction the backend exists for."""
    N = int(cluster.allocatable.shape[0])
    B = int(batch.valid.shape[0])
    R = int(cluster.allocatable.shape[1])
    TK = int(cluster.topo_pair.shape[1])
    n_keys = len(cfg.active_topo_keys) if cfg.active_topo_keys else TK
    Tr = int(batch.ra.valid.shape[1])
    Ta = int(batch.raa.valid.shape[1])
    Tp = int(batch.pref.valid.shape[1])
    C = int(batch.spread.valid.shape[1])
    C2 = int(batch.spread_soft.valid.shape[1])
    filters = set(cfg.filters)
    scores = {n for n, _ in cfg.scores}
    # mirror schedule_gang's gating exactly: topology filters move into the
    # loop (and the pod axis/filter terms extend by the batch) only when a
    # topology FILTER is configured AND intra_batch_topology is on
    use_sph = "PodTopologySpread" in filters and intra_batch_topology
    use_ipa = "InterPodAffinity" in filters and intra_batch_topology
    intra = use_sph or use_ipa
    P = int(cluster.pod_valid.shape[0]) + (B if intra else 0)
    Et = int(cluster.filter_terms.valid.shape[0]) + (B * Ta if intra else 0)
    Es = int(cluster.score_terms.valid.shape[0])

    def round_flops(W: int) -> float:
        f = 0.0
        if use_sph:
            f += n_keys * (2.0 * W * C * P * N + 2.0 * W * C * N * N)
        if use_ipa:
            f += n_keys * 2.0 * W * (Tr + Ta) * P * N
            f += 2.0 * Et * W * N
        if "InterPodAffinity" in scores:
            f += n_keys * 2.0 * W * Tp * P * N + 2.0 * Es * W * N
        if "PodTopologySpread" in scores:
            f += n_keys * (2.0 * W * C2 * P * N + 2.0 * W * C2 * N * N)
        if "DefaultPodTopologySpread" in scores:
            f += 2.0 * W * P * N
        # fit + resource scorers + normalizes: [W, N, R]-ish elementwise;
        # count one multiply-add sweep as a floor
        f += 2.0 * W * N * R
        return f

    def pallas_round_flops(W: int) -> float:
        # fused megakernel round: fit + resource scorers sweep, zone
        # contraction, ports conflict dot; score raws are plane READS
        Z = int(getattr(cluster, "zone_hot").shape[1] or 1)
        Pp = int(batch.ports_hot.shape[1])
        f = 2.0 * W * N * R + 2.0 * W * N * Z
        if "NodePorts" in filters:
            f += 2.0 * W * Pp * N
        return f

    W_resid = min(residual_window or B, B)
    r = max(int(rounds), 0)
    if r == 0:
        return 0.0
    if kernel_backend == "pallas":
        # round 0 stays on the lax path (feas0 capture) and carries the
        # once-per-auction raw precompute in its own accounting
        return round_flops(B) + (r - 1) * pallas_round_flops(W_resid)
    return round_flops(B) + (r - 1) * round_flops(W_resid)
