"""Metrics: Prometheus-style registry + the scheduler metric set.

reference: staging/src/k8s.io/component-base/metrics (stability framework
over Prometheus; legacyregistry) and pkg/scheduler/metrics/metrics.go —
schedule_attempts_total :54, e2e_scheduling_duration_seconds :83,
scheduling_algorithm_duration_seconds :92, binding_duration_seconds :130,
pending_pods :155, pod_scheduling_duration_seconds :170,
pod_scheduling_attempts :180, framework_extension_point_duration_seconds
:189, plugin_execution_duration_seconds :200 (10% sampled),
queue_incoming_pods_total :212, scheduler_cache_size :230; queue-depth
gauges via the async MetricRecorder (metric_recorder.go) plumbed into the
heaps (scheduling_queue.go:230-235).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

# default duration buckets (prometheus.DefBuckets)
DEF_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


class Counter:
    def __init__(self, name: str, help_: str, label_names=()):
        self.name, self.help = name, help_
        self.label_names = tuple(label_names)
        self._vals: Dict[Tuple, float] = {}
        self._lock = threading.Lock()

    def inc(self, *labels, amount: float = 1.0):
        with self._lock:
            self._vals[labels] = self._vals.get(labels, 0.0) + amount

    def value(self, *labels) -> float:
        with self._lock:
            return self._vals.get(labels, 0.0)

    def expose(self) -> List[str]:
        out = [f"# HELP {self.name} {_escape_help(self.help)}",
               f"# TYPE {self.name} counter"]
        with self._lock:
            items = sorted(self._vals.items())
        for labels, v in items:
            out.append(f"{self.name}{_fmt(self.label_names, labels)} {v}")
        return out


class Gauge(Counter):
    def set(self, value: float, *labels):
        with self._lock:
            self._vals[labels] = value

    def inc(self, *labels, amount: float = 1.0):
        super().inc(*labels, amount=amount)

    def dec(self, *labels):
        super().inc(*labels, amount=-1.0)

    def expose(self) -> List[str]:
        out = [f"# HELP {self.name} {_escape_help(self.help)}",
               f"# TYPE {self.name} gauge"]
        with self._lock:
            items = sorted(self._vals.items())
        for labels, v in items:
            out.append(f"{self.name}{_fmt(self.label_names, labels)} {v}")
        return out


class Histogram:
    def __init__(self, name: str, help_: str, label_names=(),
                 buckets=DEF_BUCKETS):
        self.name, self.help = name, help_
        self.label_names = tuple(label_names)
        self.buckets = tuple(buckets)
        self._counts: Dict[Tuple, List[int]] = {}
        self._sums: Dict[Tuple, float] = {}
        self._lock = threading.Lock()

    def observe(self, value: float, *labels):
        with self._lock:
            counts = self._counts.setdefault(labels,
                                             [0] * (len(self.buckets) + 1))
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
            counts[-1] += 1  # +Inf
            self._sums[labels] = self._sums.get(labels, 0.0) + value

    def count(self, *labels) -> int:
        with self._lock:
            c = self._counts.get(labels)
            return c[-1] if c else 0

    def sum(self, *labels) -> float:
        with self._lock:
            return self._sums.get(labels, 0.0)

    def percentile(self, q: float, *labels) -> float:
        """Approximate quantile from bucket counts (upper bound)."""
        with self._lock:
            c = self._counts.get(labels)
            c = list(c) if c else None
        if not c or c[-1] == 0:
            return 0.0
        target = q * c[-1]
        for i, b in enumerate(self.buckets):
            if c[i] >= target:
                return b
        # above the largest finite bucket: clamp (keeps JSON outputs finite)
        return self.buckets[-1]

    def expose(self) -> List[str]:
        out = [f"# HELP {self.name} {_escape_help(self.help)}",
               f"# TYPE {self.name} histogram"]
        with self._lock:
            snapshot = sorted((k, list(v), self._sums[k])
                              for k, v in self._counts.items())
        for labels, counts, total in snapshot:
            for i, b in enumerate(self.buckets):
                lb = _fmt(self.label_names + ("le",), labels + (str(b),))
                out.append(f"{self.name}_bucket{lb} {counts[i]}")
            lb = _fmt(self.label_names + ("le",), labels + ("+Inf",))
            out.append(f"{self.name}_bucket{lb} {counts[-1]}")
            out.append(f"{self.name}_sum{_fmt(self.label_names, labels)} "
                       f"{total}")
            out.append(f"{self.name}_count{_fmt(self.label_names, labels)} "
                       f"{counts[-1]}")
        return out


def _escape_label(value) -> str:
    """Prometheus text-format label-value escaping (exposition format
    spec): backslash, double-quote and newline must be escaped or a
    label value containing any of them corrupts the whole scrape."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(text: str) -> str:
    """# HELP escaping per the exposition format: backslash and newline
    (quotes are legal in help text)."""
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt(names, values) -> str:
    if not names:
        return ""
    pairs = ",".join(f'{n}="{_escape_label(v)}"'
                     for n, v in zip(names, values))
    return "{" + pairs + "}"


class SloStageHistograms:
    """LIVE exporter: renders the armed SLO tracker's per-stage
    log-ladder sketches (utils/slo.py) as real Prometheus histograms on
    /metrics — previously reachable only via /debug/slo.  The slo.py
    ladder maps directly onto histogram ``le`` edges: slo counts are
    PER-BUCKET (searchsorted-left, ``v <= edges[i]`` lands in slot i),
    so the cumulative count at ``le=edges[i]`` is ``cumsum(counts[:i+1])``
    and the overflow slot folds into ``+Inf`` only.  Disarmed (the
    default) the exporter contributes zero lines — /metrics output is
    byte-identical to the pre-SLO exposition, the same degrade-to-
    nothing contract every armed layer keeps."""

    name = "scheduler_pod_stage_duration_seconds"

    def expose(self) -> List[str]:
        from . import slo as _slo
        trk = _slo.tracker()
        if trk is None:
            return []
        from .slo import BUCKET_EDGES
        snap = trk.counts_snapshot()
        out = [f"# HELP {self.name} Per-pod stage latency from the armed "
               "SLO tracker's log-ladder sketches (KUBETPU_SLO).",
               f"# TYPE {self.name} histogram"]
        for stage in sorted(snap["stages"]):
            counts = snap["stages"][stage]["counts"]
            cum = counts.cumsum()
            total = int(cum[-1])
            for i, edge in enumerate(BUCKET_EDGES):
                lb = _fmt(("stage", "le"), (stage, repr(float(edge))))
                out.append(f"{self.name}_bucket{lb} {int(cum[i])}")
            lb = _fmt(("stage", "le"), (stage, "+Inf"))
            out.append(f"{self.name}_bucket{lb} {total}")
            lab = _fmt(("stage",), (stage,))
            out.append(f"{self.name}_sum{lab} "
                       f"{snap['stages'][stage]['sum_s']}")
            out.append(f"{self.name}_count{lab} {total}")
        return out


class TelemetryWindowMetrics:
    """LIVE exporter for the sustained-load telemetry ring
    (utils/telemetry.py): windows-rolled/dropped counters plus the
    last CLOSED window's headline numbers as gauges — the per-window
    series Prometheus actually wants (scrape-to-scrape deltas of a
    counter, point-in-time gauges), while the full per-window history
    stays on /debug/loadz.  Disarmed: zero lines, like every armed
    layer."""

    prefix = "scheduler_load"

    def expose(self) -> List[str]:
        from . import telemetry as _telemetry
        tel = _telemetry.ring()
        if tel is None:
            return []
        wins = tel.windows()
        p = self.prefix
        out = [f"# HELP {p}_windows_total Telemetry windows rolled since "
               "arming (KUBETPU_TELEMETRY).",
               f"# TYPE {p}_windows_total counter",
               f"{p}_windows_total {wins[-1]['seq'] if wins else 0}",
               f"# HELP {p}_windows_dropped_total Telemetry windows "
               "evicted from the bounded ring.",
               f"# TYPE {p}_windows_dropped_total counter",
               f"{p}_windows_dropped_total {tel.dropped()}"]
        if not wins:
            return out
        last = wins[-1]
        gauges = [
            ("window_pods", "Terminal pods in the last closed window.",
             last.get("pods", 0)),
            ("window_e2e_p99_seconds",
             "Windowed e2e p99 of the last closed window.",
             last.get("stages", {}).get("e2e", {}).get("p99_s", 0.0)),
            ("window_cycles", "Scheduling cycles in the last closed "
             "window.", last.get("cycles", 0)),
            ("window_demotions", "Recovery-ladder demotions in the last "
             "closed window.", last.get("demotions", 0)),
            ("window_recoveries", "Recovery-ladder events in the last "
             "closed window.", last.get("recoveries", 0)),
        ]
        for suffix, help_, v in gauges:
            out.append(f"# HELP {p}_{suffix} {_escape_help(help_)}")
            out.append(f"# TYPE {p}_{suffix} gauge")
            out.append(f"{p}_{suffix} {v}")
        depths = last.get("queue_depths") or {}
        if depths:
            out.append(f"# HELP {p}_window_queue_depth Queue depths at "
                       "the last window roll, by queue.")
            out.append(f"# TYPE {p}_window_queue_depth gauge")
            for q in sorted(depths):
                lb = _fmt(("queue",), (q,))
                out.append(f"{p}_window_queue_depth{lb} {depths[q]}")
        return out


class Registry:
    def __init__(self):
        self._metrics: List = []
        self._lock = threading.Lock()

    def register(self, m):
        with self._lock:
            self._metrics.append(m)
        return m

    def expose_text(self) -> str:
        with self._lock:
            lines: List[str] = []
            for m in self._metrics:
                lines.extend(m.expose())
        return "\n".join(lines) + "\n"


class _QueueRecorder:
    """Per-queue depth recorder handed to the heaps
    (reference: metrics/metric_recorder.go PendingPodsRecorder)."""

    def __init__(self, gauge: Gauge, label: str):
        self._g, self._label = gauge, label

    def inc(self):
        self._g.inc(self._label)

    def dec(self):
        self._g.dec(self._label)


SCHEDULER_SUBSYSTEM = "scheduler"


class SchedulerMetrics:
    """The §2.1 metric set (reference: pkg/scheduler/metrics/metrics.go)."""

    def __init__(self, registry: Optional[Registry] = None):
        self.registry = registry or Registry()
        r = self.registry.register
        p = SCHEDULER_SUBSYSTEM
        self.schedule_attempts = r(Counter(
            f"{p}_schedule_attempts_total",
            "Number of attempts to schedule pods, by result.", ("result",)))
        self.e2e_scheduling_duration = r(Histogram(
            f"{p}_e2e_scheduling_duration_seconds",
            "E2e scheduling latency (scheduling algorithm + binding)."))
        self.scheduling_algorithm_duration = r(Histogram(
            f"{p}_scheduling_algorithm_duration_seconds",
            "Scheduling algorithm latency."))
        self.binding_duration = r(Histogram(
            f"{p}_binding_duration_seconds", "Binding latency."))
        self.pod_scheduling_duration = r(Histogram(
            f"{p}_pod_scheduling_duration_seconds",
            "E2e latency for a pod being scheduled, from first attempt.",
            buckets=tuple(0.01 * 2 ** i for i in range(16))))  # :170 (to ~512s)
        self.pod_scheduling_attempts = r(Histogram(
            f"{p}_pod_scheduling_attempts",
            "Number of attempts to successfully schedule a pod.",
            buckets=(1, 2, 4, 8, 16)))
        # observed by framework/runtime.py at the HOST extension points
        # that run once per pod per cycle (PreFilter, PostFilter, Reserve,
        # Permit, PreBind, Bind, PostBind) — NOT the per-(pod, node)
        # Filter loop, whose per-call observe would poison the hot path.
        # The reference's plugin_execution_duration_seconds (per-plugin,
        # 10% sampled) is deliberately NOT ported: host plugins here are
        # the thin residue of a batched device design, per-plugin wall
        # time is meaningless for the jitted families (one fused program
        # serves every plugin), and per-plugin ATTRIBUTION is already
        # served losslessly by the decision audit +
        # scheduler_framework_rejections_total{plugin}.
        self.framework_extension_point_duration = r(Histogram(
            f"{p}_framework_extension_point_duration_seconds",
            "Latency for running all plugins of a specific extension point.",
            ("extension_point", "status")))
        self.queue_incoming_pods = r(Counter(
            f"{p}_queue_incoming_pods_total",
            "Number of pods added to scheduling queues by event and queue type.",
            ("queue", "event")))
        self.pending_pods = r(Gauge(
            f"{p}_pending_pods",
            "Number of pending pods, by the queue type.", ("queue",)))
        # observed by preemption.py: victims per committed preemption
        # (at _commit_victims) and eligible pods served per wave
        self.preemption_victims = r(Histogram(
            f"{p}_preemption_victims", "Number of selected preemption victims",
            buckets=(1, 2, 4, 8, 16, 32, 64)))
        self.preemption_attempts = r(Counter(
            f"{p}_preemption_attempts_total",
            "Total preemption attempts in the cluster till now"))
        self.cache_size = r(Gauge(
            f"{p}_scheduler_cache_size",
            "Number of nodes, pods, and assumed pods in the cache.", ("type",)))
        # observed by framework/runtime.py wait_on_permit, only for pods
        # that actually entered a Wait (result: allowed/rejected/timeout)
        self.permit_wait_duration = r(Histogram(
            f"{p}_permit_wait_duration_seconds",
            "Duration of waiting on permit.", ("result",)))
        # TPU-specific: device program time per batch
        self.device_batch_duration = r(Histogram(
            f"{p}_device_batch_duration_seconds",
            "Jitted schedule program wall time per pod batch."))
        self.device_batch_size = r(Histogram(
            f"{p}_device_batch_size", "Pods per device batch.",
            buckets=(1, 8, 32, 128, 512, 2048, 8192)))
        # observability layer (utils/trace.py flight recorder +
        # utils/decisions.py audit): per-plugin rejection attribution and
        # the recorder ring's drop count
        self.framework_rejections = r(Counter(
            f"{p}_framework_rejections_total",
            "Unschedulable pods attributed to the decisive filter plugin "
            "by the per-pod decision audit.", ("plugin",)))
        self.flight_recorder_dropped = r(Counter(
            f"{p}_flight_recorder_dropped_total",
            "Cycle records dropped by the flight recorder's ring buffer."))
        # self-healing runtime (utils/chaos.py + the recovery machinery):
        # faults the armed chaos registry injected, by point, and the
        # recoveries the runtime performed — dispatch-error /
        # dispatch-deadline demotions, bind retries, anti-entropy
        # verify resyncs, aot artifact fallbacks
        self.faults_injected = r(Counter(
            f"{p}_faults_injected_total",
            "Faults injected by the armed chaos registry, by point.",
            ("point",)))
        self.recoveries = r(Counter(
            f"{p}_recoveries_total",
            "Self-healing recoveries performed by the runtime, by kind.",
            ("kind",)))
        # durable cycle journal (utils/journal.py): records appended,
        # bytes currently retained on disk, and records dropped — write
        # failures AND size-cap evictions both count (never silent).
        # Synced on the serving thread like the chaos counters.
        self.journal_records = r(Counter(
            f"{p}_journal_records_total",
            "Cycle records appended to the durable journal."))
        self.journal_bytes = r(Gauge(
            f"{p}_journal_bytes",
            "Bytes of cycle records currently retained by the journal."))
        self.journal_dropped = r(Counter(
            f"{p}_journal_dropped_total",
            "Journal records dropped: write failures plus size-cap "
            "evictions."))
        # live exporters: the armed SLO sketches as real histograms and
        # the telemetry ring's last-window series — both render at
        # scrape time from the armed layer and contribute ZERO lines
        # disarmed (the /metrics exposition is byte-identical to the
        # pre-arming output, the house degrade-to-nothing contract)
        self.slo_histograms = r(SloStageHistograms())
        self.telemetry_windows = r(TelemetryWindowMetrics())

    # hooks consumed by queue/scheduler ------------------------------------

    def active_recorder(self):
        return _QueueRecorder(self.pending_pods, "active")

    def backoff_recorder(self):
        return _QueueRecorder(self.pending_pods, "backoff")

    def unschedulable_recorder(self):
        return _QueueRecorder(self.pending_pods, "unschedulable")

    def incoming(self, event: str, queue: str):
        self.queue_incoming_pods.inc(queue, event)

    def observe_cycle(self, n_pods: int, seconds: float):
        if n_pods > 0:
            self.device_batch_size.observe(n_pods)
            self.device_batch_duration.observe(seconds)
            self.scheduling_algorithm_duration.observe(seconds / n_pods)

    def pod_scheduled(self, attempts: int, since_first_attempt: float,
                      e2e: float):
        self.schedule_attempts.inc("scheduled")
        self.pod_scheduling_attempts.observe(attempts)
        self.pod_scheduling_duration.observe(since_first_attempt)
        self.e2e_scheduling_duration.observe(e2e)

    def pod_unschedulable(self):
        self.schedule_attempts.inc("unschedulable")

    def expose_text(self) -> str:
        return self.registry.expose_text()
