"""Event recording: the client-go tools/events analog.

reference: staging/src/k8s.io/client-go/tools/events/event_broadcaster.go
(EventBroadcaster: recorders fan events into a correlator that aggregates
repeats into an EventSeries before sinking) and tools/record/events_cache.go
(EventAggregator: same (source, object, reason, ...) key within a window
increments a count instead of emitting a new object), wired into the
scheduler via profile/profile.go:33 (NewRecorderFactory) and consumed at
scheduler.go "Scheduled"/"FailedScheduling" emission sites.

The TPU build's store plays the apiserver, so the sink writes api.Event
objects into it; aggregation semantics match the reference's defaults
(10-minute window, count bump on repeats)."""

from __future__ import annotations

import copy
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from ..api import types as api

AGGREGATION_WINDOW = 600.0  # reference: events_cache.go defaultAggregateIntervalInSeconds
MAX_CACHE_ENTRIES = 4096    # reference: events_cache.go maxLruCacheEntries


@dataclass
class Event:
    """Scheduler-relevant Event subset
    (reference: api/core/v1/types.go Event + EventSeries)."""
    metadata: api.ObjectMeta = field(default_factory=api.ObjectMeta)
    involved_kind: str = ""
    involved_namespace: str = ""
    involved_name: str = ""
    involved_uid: str = ""
    type: str = ""        # Normal | Warning
    reason: str = ""
    message: str = ""
    count: int = 1
    first_timestamp: float = 0.0
    last_timestamp: float = 0.0
    kind: str = "Event"

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

    @property
    def uid(self) -> str:
        return self.metadata.uid


class EventRecorder:
    """One named recorder (reference: events/event_recorder.go
    recorderImpl.Eventf); shares its broadcaster's correlator."""

    def __init__(self, broadcaster: "EventBroadcaster", component: str):
        self._b = broadcaster
        self.component = component

    def event(self, obj, type_: str, reason: str, message: str) -> None:
        self._b._record(self.component, obj, type_, reason, message)


class EventBroadcaster:
    """Aggregating event pipeline (reference: event_broadcaster.go:120
    StartRecordingToSink + events_cache.go EventAggregator): events with
    the same (component, object, type, reason) key inside the aggregation
    window bump the existing Event's count/lastTimestamp instead of
    creating a new object."""

    def __init__(self, sink=None, clock: Callable[[], float] = time.time,
                 window: float = AGGREGATION_WINDOW,
                 max_entries: int = MAX_CACHE_ENTRIES):
        from collections import OrderedDict
        self.sink = sink          # ClusterStore-like: add(obj), update(obj)
        self._clock = clock
        self._window = window
        self._max = max_entries
        self._lock = threading.Lock()
        self._cache: "OrderedDict[Tuple, Event]" = OrderedDict()  # kubelint: guarded-by(_lock)
        self._watchers: List[Callable[[Event], None]] = []  # kubelint: guarded-by(_lock)
        self._seq = 0  # kubelint: guarded-by(_lock)

    def new_recorder(self, component: str = "default-scheduler"
                     ) -> EventRecorder:
        return EventRecorder(self, component)

    def start_structured_logging(self, log_fn) -> None:
        """reference: event_broadcaster.go StartStructuredLogging."""
        with self._lock:
            self._watchers.append(
                lambda ev: log_fn(f"{ev.type} {ev.reason} "
                                  f"{ev.involved_namespace}/"
                                  f"{ev.involved_name}: "
                                  f"{ev.message} (x{ev.count})"))

    def watch(self, fn: Callable[[Event], None]) -> None:
        # registration races _record's watcher snapshot without the lock
        with self._lock:
            self._watchers.append(fn)

    def _record(self, component: str, obj, type_: str, reason: str,
                message: str) -> None:
        now = self._clock()
        meta = getattr(obj, "metadata", api.ObjectMeta())
        key = (component, getattr(obj, "kind", ""), meta.namespace,
               meta.name, type_, reason)
        with self._lock:
            ev = self._cache.get(key)
            if ev is not None:
                self._cache.move_to_end(key)
            if ev is not None and now - ev.last_timestamp <= self._window:
                ev.count += 1
                ev.last_timestamp = now
                ev.message = message
                # watchers and the sink get an immutable SNAPSHOT taken
                # under the lock: the cached Event keeps mutating on
                # aggregation, and handing out the live object would let
                # concurrent recorders expose torn count/message reads
                ev = copy.copy(ev)
                if self.sink is not None:
                    try:
                        self.sink.update(ev)
                    except Exception:
                        pass
            else:
                self._seq += 1
                ev = Event(
                    metadata=api.ObjectMeta(
                        name=f"{meta.name}.{self._seq:x}",
                        namespace=meta.namespace or "default"),
                    involved_kind=getattr(obj, "kind", ""),
                    involved_namespace=meta.namespace,
                    involved_name=meta.name,
                    involved_uid=getattr(obj, "uid", meta.uid),
                    type=type_, reason=reason, message=message,
                    count=1, first_timestamp=now, last_timestamp=now)
                self._cache[key] = ev
                # LRU bound (events_cache.go maxLruCacheEntries): evicted
                # keys simply start a fresh Event on their next repeat
                while len(self._cache) > self._max:
                    self._cache.popitem(last=False)
                # same immutable-snapshot rule: the cached instance will
                # mutate on future aggregations
                ev = copy.copy(ev)
                if self.sink is not None:
                    try:
                        self.sink.add(ev)
                    except Exception:
                        pass
            watchers = list(self._watchers)
        for fn in watchers:
            fn(ev)
