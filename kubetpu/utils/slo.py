"""Per-pod latency SLO layer: streaming quantile sketches + exemplars.

The north-star acceptance for ROADMAP item 1 is a PER-POD latency SLO
("100k pods x 10k nodes < 1 s p99"), but the flight recorder, Perfetto
export and decision audit are all CYCLE-centric — nothing measured how
long an individual pod waited from first queue admission to bound.  This
module is that substrate: the scheduler threads the timestamps that
already exist on ``QueuedPodInfo`` (``timestamp``,
``initial_attempt_timestamp``, ``attempts``) through pop -> prepare ->
dispatch -> readback -> commit -> bind, and every bound (or terminally
unresolvable) pod lands here as a per-stage latency vector:

  queue_wait   last queue admission -> popped into a cycle
  backoff      first attempt -> last queue admission (retry/backoff debt;
               0 on first-attempt pods)
  cycle_wait   popped -> device dispatch (snapshot, PreFilter, tensorize,
               host masks; includes pipelined parking)
  dispatch     host share of the dispatch->readback window (program
               enqueue) MINUS the window's host-exempt share — other
               in-flight ring slots' commit loops and readbacks plus
               pipelined parking (``PreparedCycle.host_exempt_s``).
               The subtraction is the depth-k PER-SLOT attribution: at
               pipeline depth k the same wall-clock seconds sit inside
               up to k overlapping dispatch->readback windows, and
               without it every overlapped second would be counted once
               per in-flight cycle, swamping ``stage_shares``
  device       the cycle's packed-readback block (``device_wait_s``;
               every pod of a cycle shares the cycle's value).  NOTE:
               this is READBACK-BLOCK host time, not measured device
               time — under the depth-k pipeline, device execution
               overlaps host work and this stage reads near zero even
               when the device is saturated.  MEASURED per-program
               device time (honest at any depth) lives in
               utils/devstats.py (KUBETPU_DEVSTATS, /debug/devicez).
  commit       readback done -> this pod's placement committed
  bind         PreBind/Bind/PostBind wall time (binder thread)
  e2e          first attempt -> bound (the SLO number)

Bounded-memory contract: one fixed 128-bucket log-spaced histogram per
stage (pure numpy int64 counts — no per-pod retention), plus at most
``KUBETPU_SLO_EXEMPLARS`` (default 8) worst-pod exemplars that link back
to the flight-recorder cycle (``flight_seq``) and the decision-audit
entry (``/debug/explain?pod=``) for that pod.  Quantiles are read from
the bucket counts (p50/p90/p99/p999), exact to within one bucket width
(~15.5% relative — 16 buckets per decade).

Arming mirrors the flight recorder (``KUBETPU_SLO=1`` or
``arm_slo_tracker()``): DISARMED (the default) the serving loop reads
one module attribute per cycle and takes ZERO new locks — proven by the
poison-monkeypatch test (tests/test_slo.py), the same contract
tests/test_flightrecorder.py enforces for the recorder.  Importing this
module never imports jax.
"""

from __future__ import annotations

import math
import os
import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

SLO_ENV = "KUBETPU_SLO"
EXEMPLARS_ENV = "KUBETPU_SLO_EXEMPLARS"
DEFAULT_EXEMPLARS = 8

# the stage keys the scheduler emits, in pipeline order (e2e rides next
# to them but is not a "stage": shares are computed over STAGES only)
STAGES = ("queue_wait", "backoff", "cycle_wait", "dispatch", "device",
          "commit", "bind")

# fixed log-spaced bucket ladder: 16 buckets per decade over
# [100 us, 10^4 s] — 8 decades, 128 edges.  One shared immutable array;
# every sketch is just a [129] int64 count vector against it.
_BUCKETS_PER_DECADE = 16
_EDGE_LO_EXP, _EDGE_HI_EXP = -4, 4
BUCKET_EDGES = np.logspace(
    _EDGE_LO_EXP, _EDGE_HI_EXP,
    num=(_EDGE_HI_EXP - _EDGE_LO_EXP) * _BUCKETS_PER_DECADE + 1)
BUCKET_EDGES.setflags(write=False)
# one bucket's relative width: adjacent edges differ by this ratio
BUCKET_RATIO = float(10 ** (1.0 / _BUCKETS_PER_DECADE))


class QuantileSketch:
    """Bounded-memory streaming quantile estimator over the fixed
    log-spaced ladder: a [len(edges)+1] int64 count vector plus
    sum/min/max.  NOT thread-safe on its own — the owning SloTracker
    serializes access under its lock (like Histogram's per-metric lock,
    but one lock for the whole stage family)."""

    __slots__ = ("counts", "total", "sum_s", "min_s", "max_s")

    def __init__(self):
        self.counts = np.zeros(len(BUCKET_EDGES) + 1, np.int64)
        self.total = 0
        self.sum_s = 0.0
        self.min_s = math.inf
        self.max_s = 0.0

    def observe(self, value: float) -> None:
        v = max(float(value), 0.0)
        # searchsorted('left'): first edge >= v, i.e. the bucket whose
        # UPPER edge bounds v; values past the last edge land in the
        # overflow slot (quantile clamps to max_s)
        self.counts[int(np.searchsorted(BUCKET_EDGES, v))] += 1
        self.total += 1
        self.sum_s += v
        if v < self.min_s:
            self.min_s = v
        if v > self.max_s:
            self.max_s = v

    def quantile(self, q: float) -> float:
        """Upper bucket edge at rank ceil(q * n), clamped to the observed
        [min, max] — within one bucket width of numpy.percentile on the
        same draws (the property test's contract)."""
        if self.total == 0:
            return 0.0
        rank = min(max(int(math.ceil(q * self.total)), 1), self.total)
        cum = 0
        for i, c in enumerate(self.counts.tolist()):
            cum += c
            if cum >= rank:
                edge = (BUCKET_EDGES[i] if i < len(BUCKET_EDGES)
                        else self.max_s)
                return float(min(max(edge, self.min_s), self.max_s))
        return float(self.max_s)

    def to_dict(self, quantiles=(0.5, 0.9, 0.99, 0.999)) -> Dict[str, Any]:
        d: Dict[str, Any] = {"count": int(self.total),
                             "sum_s": round(self.sum_s, 6)}
        if self.total:
            d["min_s"] = round(self.min_s, 6)
            d["max_s"] = round(self.max_s, 6)
            for q in quantiles:
                key = "p" + ("%g" % (q * 100)).replace(".", "")
                d[key + "_s"] = round(self.quantile(q), 6)
        return d


class SloTracker:
    """Per-stage quantile sketches + worst-pod exemplars for bound /
    terminally-unresolvable pods.  Lock-guarded: the serving thread and
    the binder pool both observe (async binds complete on binder
    threads), and /debug/slo reads concurrently."""

    def __init__(self, max_exemplars: Optional[int] = None):
        self.max_exemplars = max_exemplars if max_exemplars is not None \
            else int(os.environ.get(EXEMPLARS_ENV, str(DEFAULT_EXEMPLARS)))
        self._lock = threading.Lock()
        self._sketches: Dict[str, QuantileSketch] = {}  # kubelint: guarded-by(_lock)
        self._exemplars: List[Dict[str, Any]] = []  # kubelint: guarded-by(_lock)
        self._pods = 0          # kubelint: guarded-by(_lock)
        self._unresolvable = 0  # kubelint: guarded-by(_lock)

    # -- recording ----------------------------------------------------------

    def observe_pod(self, stages: Dict[str, float], *, pod: str = "",
                    namespace: str = "", uid: str = "",
                    outcome: str = "bound", attempts: int = 0,
                    cycle: int = 0, flight_seq: int = 0,
                    journal_seq: int = 0) -> None:
        """Fold one terminal pod's per-stage latency vector in.  stages:
        stage name -> seconds (missing stages are simply not observed);
        an ``e2e`` key is the SLO number and drives exemplar ranking."""
        e2e = float(stages.get("e2e", 0.0))
        with self._lock:
            self._pods += 1
            if outcome != "bound":
                self._unresolvable += 1
            for name, v in stages.items():
                sk = self._sketches.get(name)
                if sk is None:
                    sk = self._sketches[name] = QuantileSketch()
                sk.observe(v)
            ex = self._exemplars
            # second clause only reachable with ex at capacity (> 0):
            # KUBETPU_SLO_EXEMPLARS=0 is the quantiles-only config
            if len(ex) < self.max_exemplars or (
                    ex and e2e > ex[-1]["e2e_s"]):
                entry = {
                    "pod": pod, "namespace": namespace, "uid": uid,
                    "outcome": outcome, "attempts": int(attempts),
                    "e2e_s": round(e2e, 6),
                    "stages_s": {k: round(float(v), 6)
                                 for k, v in stages.items() if k != "e2e"},
                    # the cross-links: the flight-recorder cycle record
                    # (/debug/flightz, CycleRecord.seq), the decision
                    # audit entry (/debug/explain?pod=) and — when
                    # KUBETPU_JOURNAL is armed — the durable journal
                    # record id tools/kubereplay can re-execute
                    "cycle": int(cycle),
                    "flight_seq": int(flight_seq),
                    "journal_seq": int(journal_seq),
                    "explain": (f"/debug/explain?pod={pod}"
                                f"&namespace={namespace}" if pod else ""),
                }
                ex.append(entry)
                ex.sort(key=lambda e: -e["e2e_s"])
                del ex[self.max_exemplars:]

    def clear(self) -> None:
        with self._lock:
            self._sketches.clear()
            self._exemplars.clear()
            self._pods = 0
            self._unresolvable = 0

    # -- reads --------------------------------------------------------------

    def stage_quantiles(self,
                        quantiles=(0.5, 0.9, 0.99, 0.999)
                        ) -> Dict[str, Dict[str, Any]]:
        # serialize UNDER the lock: a sketch mid-observe is torn
        # (total bumped, min_s still inf -> json Infinity); the whole
        # read is a ~130-bucket walk per stage, cheap enough to hold a
        # debug-endpoint scrape against the observe path
        with self._lock:
            return {name: sk.to_dict(quantiles)
                    for name, sk in sorted(self._sketches.items())}

    def shares(self) -> Dict[str, float]:
        """Each stage's share of the total per-pod latency SUM (e2e
        excluded) — the attribution vector tools/benchtrend.py diffs to
        name which stage a regression grew in."""
        with self._lock:
            sums = {n: sk.sum_s for n, sk in self._sketches.items()
                    if n != "e2e"}
        total = sum(sums.values())
        if total <= 0:
            return {}
        return {n: round(s / total, 4) for n, s in sorted(sums.items())}

    def exemplars(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(e) for e in self._exemplars]

    def counts_snapshot(self) -> Dict[str, Any]:
        """Cumulative raw bucket counts per stage (copies, safe to keep)
        + pod totals, in ONE locked read — the telemetry ring
        (utils/telemetry.py) subtracts two of these one window apart to
        get exact per-window quantiles over the same ladder."""
        with self._lock:
            return {
                "stages": {name: {"counts": sk.counts.copy(),
                                  "sum_s": sk.sum_s}
                           for name, sk in self._sketches.items()},
                "pods": self._pods,
                "unresolvable": self._unresolvable,
            }

    def to_dict(self, quantiles=(0.5, 0.9, 0.99, 0.999)) -> Dict[str, Any]:
        """The /debug/slo document."""
        with self._lock:
            pods, unres = self._pods, self._unresolvable
        return {"armed": True,
                "pods": pods,
                "unresolvable": unres,
                "stages": self.stage_quantiles(quantiles),
                "shares": self.shares(),
                "exemplars": self.exemplars()}


# module arming state — read WITHOUT a lock on the hot path (rebinding a
# Python reference is atomic; a racing reader sees old or new), exactly
# like utils/trace.py's _flight.  arm/disarm serialize via _slo_lock.
_tracker: Optional[SloTracker] = None
_slo_lock = threading.Lock()


def tracker() -> Optional[SloTracker]:
    """The armed tracker, or None (disarmed, the default)."""
    return _tracker


def arm_slo_tracker(max_exemplars: Optional[int] = None) -> SloTracker:
    """Idempotently arm the SLO tracker (returns the existing one if
    already armed)."""
    global _tracker
    with _slo_lock:
        if _tracker is None:
            _tracker = SloTracker(max_exemplars=max_exemplars)
        return _tracker


def disarm_slo_tracker() -> None:
    global _tracker
    with _slo_lock:
        _tracker = None


def maybe_arm_from_env() -> Optional[SloTracker]:
    """Scheduler-construction hook: arms iff KUBETPU_SLO=1."""
    if os.environ.get(SLO_ENV, "0") not in ("", "0", "false", "False"):
        return arm_slo_tracker()
    return None
