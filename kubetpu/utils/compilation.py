"""Persistent XLA compilation cache.

The scheduling programs are large (the sequential scan and the gang auction
compile in tens of seconds at serving shapes) but their shapes are bucketed
(utils/intern.py pow2_bucket), so a process restart recompiles byte-identical
programs.  Enabling JAX's persistent compilation cache makes warm restarts
skip XLA entirely — the serving analog of the reference reusing a running
process (there is no compile step to amortize in Go; here there is, and this
bounds it).
"""

from __future__ import annotations

import os

DEFAULT_CACHE_DIR = os.path.expanduser("~/.cache/kubetpu/xla")

_enabled: str | None = None  # cache dir once enabled


def enable_persistent_cache(cache_dir: str | None = None) -> str:
    """Idempotently enable the JAX persistent compilation cache.  Returns
    the cache directory in use.  Safe to call before or after jax init."""
    global _enabled
    if _enabled:
        return _enabled
    cache_dir = cache_dir or os.environ.get("KUBETPU_XLA_CACHE_DIR",
                                            DEFAULT_CACHE_DIR)
    import jax
    existing = getattr(jax.config, "jax_compilation_cache_dir", None)
    if existing:
        # the embedding application already configured a cache — respect it
        _enabled = existing
        return existing
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # cache every program: even sub-second kernels add up across restarts
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    _enabled = cache_dir
    return cache_dir
