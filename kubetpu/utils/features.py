"""Feature gates.

reference: staging/src/k8s.io/component-base/featuregate/feature_gate.go:33
(featureGate) and pkg/features/kube_features.go (83 gates; the
scheduler-relevant subset is mirrored here with the same stages).
"""

from __future__ import annotations

import threading
from typing import Dict, NamedTuple

ALPHA, BETA, GA, DEPRECATED = "ALPHA", "BETA", "GA", "DEPRECATED"


class FeatureSpec(NamedTuple):
    default: bool
    pre_release: str
    lock_to_default: bool = False


# scheduler-relevant gates (reference: pkg/features/kube_features.go)
DEFAULT_FEATURES: Dict[str, FeatureSpec] = {
    "EvenPodsSpread": FeatureSpec(True, GA),            # :366
    "BalanceAttachedNodeVolumes": FeatureSpec(False, ALPHA),  # :155
    "PodOverhead": FeatureSpec(True, BETA),             # :432
    "CSIMigration": FeatureSpec(True, BETA),
    "VolumeScheduling": FeatureSpec(True, GA, lock_to_default=True),
    "PodDisruptionBudget": FeatureSpec(True, BETA),
    "ServiceAffinity": FeatureSpec(False, ALPHA),
    "NonPreemptingPriority": FeatureSpec(False, ALPHA),  # :392
    "DefaultPodTopologySpread": FeatureSpec(False, ALPHA),
    "AllAlpha": FeatureSpec(False, ALPHA),
    "AllBeta": FeatureSpec(False, BETA),
}


class FeatureGate:
    """reference: featuregate/feature_gate.go:33."""

    def __init__(self, known: Dict[str, FeatureSpec] = None):
        self._known = dict(known if known is not None else DEFAULT_FEATURES)  # kubelint: guarded-by(_lock)
        self._enabled: Dict[str, bool] = {}  # kubelint: guarded-by(_lock)
        self._lock = threading.Lock()

    def enabled(self, key: str) -> bool:
        with self._lock:
            if key in self._enabled:
                return self._enabled[key]
            spec = self._known.get(key)
            if spec is None:
                raise KeyError(f"unknown feature gate {key}")
            if spec.pre_release == ALPHA and self._enabled.get("AllAlpha"):
                return True
            if spec.pre_release == BETA and self._enabled.get("AllBeta"):
                return True
            return spec.default

    def set(self, key: str, value: bool) -> None:
        with self._lock:
            spec = self._known.get(key)
            if spec is None:
                raise KeyError(f"unknown feature gate {key}")
            if spec.lock_to_default and value != spec.default:
                raise ValueError(
                    f"cannot set feature gate {key} to {value}: locked to "
                    f"{spec.default}")
            self._enabled[key] = value

    def set_from_map(self, m: Dict[str, bool]) -> None:
        for k, v in m.items():
            self.set(k, v)

    def add(self, key: str, spec: FeatureSpec) -> None:
        with self._lock:
            self._known[key] = spec

    def known_features(self):
        with self._lock:
            return {k: v for k, v in self._known.items()}


DEFAULT_FEATURE_GATE = FeatureGate()
