"""Runtime sanitizer harness: the dynamic half of the kubelint contract.

kubelint (tools/kubelint) proves hot-path invariants statically; this
module enforces the ones only a live trace can check, behind one opt-in
switch (``KUBETPU_SANITIZE=1``):

  * ``jax_debug_nans`` — a NaN anywhere in filter/score math means a
    broken kernel (every score is finite by construction); fail loudly at
    the producing primitive instead of binding a garbage placement.
  * ``jax_numpy_rank_promotion="raise"`` — every broadcast in the kernels
    is explicit (``[None, :]``); an implicit rank promotion is almost
    always a transposed operand riding a silent broadcast.
  * donation-mismatch logging — a donated buffer XLA could not reuse
    means the donation annotation and the program disagree; surfaced
    every time instead of Python's warn-once default.
  * a per-program compile-count watchdog — with pow2 bucketing
    (utils/intern.py) every jitted program must compile AT MOST ONCE per
    (program, shape-bucket) key per process; a second compile of the same
    key means the jit cache is being defeated (fresh jit objects,
    unhashable statics, dtype drift).  Tests run a scheduling cycle under
    the sanitizer and fail on any recompilation.

The sanitizer deliberately does NOT flip ``jax_enable_x64`` — the scoring
pipeline is calibrated for f32 (see ops/kernels.py) — and restores every
config flag it touched on ``disable_sanitizer()``/context exit, so test
suites can scope it to single cases.
"""

from __future__ import annotations

import logging
import os
import re
import threading
import warnings
from contextlib import contextmanager
from typing import Dict, List, Optional, Set, Tuple

ENV_FLAG = "KUBETPU_SANITIZE"

# the logger jax routes compilation progress through (jax 0.4.x); records
# look like "Compiling <name> with global shapes and types [ShapedArray(
# f32[8,16])...]. Argument mapping: ..."
_PXLA_LOGGER = "jax._src.interpreters.pxla"
_COMPILE_RE = re.compile(
    r"Compiling (\S+) with global shapes and types (\[.*\])\.\s*"
    r"Argument mapping", re.DOTALL)
_DONATION_RE = re.compile(r"[Dd]onated buffers? .*not usable|"
                          r"buffer donat\w+ .*mismatch")


class CompileWatchdog(logging.Handler):
    """Counts XLA compilations per (program name, shape signature) and
    donation-mismatch complaints, from jax's own compilation log stream.

    The handler listens at DEBUG on the pxla logger (jax emits the compile
    record at DEBUG unless jax_log_compiles is set), so installing it does
    not add stderr noise — ancestor handlers keep their own levels.

    Known coarseness: the compile record does not include jit STATIC
    argument keys, so two compiles of one program at identical shapes but
    different static configs count as a recompile.  That is deliberate
    for the serving contract (a cycle's ProgramConfig is stable; churning
    statics per cycle IS a compile-cache defeat), but scoped test
    contexts should start from fresh counts — ``sanitized()`` resets the
    watchdog when it joins an already-armed sanitizer."""

    def __init__(self):
        super().__init__(level=logging.DEBUG)
        self._lock = threading.Lock()
        self.counts: Dict[Tuple[str, str], int] = {}
        self.donation_mismatches: List[str] = []

    # logging.Handler interface ----------------------------------------
    def emit(self, record: logging.LogRecord) -> None:
        try:
            msg = record.getMessage()
        except Exception:
            return
        m = _COMPILE_RE.search(msg)
        if m:
            key = (m.group(1), m.group(2))
            with self._lock:
                self.counts[key] = self.counts.get(key, 0) + 1
            # feed the flight recorder: a compile landing under a cycle's
            # open span (dispatch, audit, wave) is exactly the event the
            # recorder exists to attribute — no-op when disarmed
            from .trace import note_compile_event
            note_compile_event(m.group(1), m.group(2))
            return
        if _DONATION_RE.search(msg):
            with self._lock:
                self.donation_mismatches.append(msg)
            logging.getLogger("kubetpu.sanitize").warning(
                "donation mismatch: %s", msg)

    # warnings interface (jax emits donation mismatches via warnings.warn,
    # not logging — see enable_sanitizer's showwarning hook) -------------
    def note_warning(self, message: str) -> None:
        if _DONATION_RE.search(message):
            with self._lock:
                self.donation_mismatches.append(message)
            logging.getLogger("kubetpu.sanitize").warning(
                "donation mismatch: %s", message)

    # assertions --------------------------------------------------------
    def compile_count(self) -> int:
        with self._lock:
            return sum(self.counts.values())

    def recompiled(self) -> Dict[Tuple[str, str], int]:
        """(program, shapes) keys that compiled more than once — each one
        is a defeated jit cache."""
        with self._lock:
            return {k: c for k, c in self.counts.items() if c > 1}

    def assert_no_recompilation(self) -> None:
        bad = self.recompiled()
        if bad:
            lines = ["%s compiled %d times for shapes %s" % (name, c, shapes)
                     for (name, shapes), c in sorted(bad.items())]
            raise AssertionError(
                "compile-count watchdog: jit cache defeated —\n  "
                + "\n  ".join(lines))

    def reset(self) -> None:
        with self._lock:
            self.counts.clear()
            self.donation_mismatches.clear()


class _SanitizerState:
    def __init__(self):
        self.active = False
        self.watchdog: Optional[CompileWatchdog] = None
        self.prev_config: Dict[str, object] = {}
        self.prev_warn_filters: Optional[list] = None
        self.prev_showwarning = None


_state = _SanitizerState()
_state_lock = threading.Lock()

# refcounted pxla-logger arming, shared by enable_sanitizer and
# install_compile_watchdog: the ORIGINAL level/propagate are saved on the
# first arm and restored only when the last armed handler detaches, so a
# watchdog uninstalled while the full sanitizer is still active (or vice
# versa) can't blind the survivor or restore a stale snapshot.  Callers
# hold _state_lock.
_logger_armed: Set[int] = set()   # id()s of handlers _arm_pxla_logger attached
_logger_prev: Optional[Tuple[int, bool]] = None


def _arm_pxla_logger(handler: logging.Handler) -> None:
    global _logger_prev
    logger = logging.getLogger(_PXLA_LOGGER)
    if not _logger_armed:
        _logger_prev = (logger.level, logger.propagate)
        if logger.level == logging.NOTSET or logger.level > logging.DEBUG:
            # jax emits the compile record at DEBUG; opening the logger up
            # would spray every record at ancestor HANDLERS (propagation
            # skips ancestor logger levels), so keep them local to the
            # watchdog while armed
            logger.setLevel(logging.DEBUG)
            logger.propagate = False
    _logger_armed.add(id(handler))
    logger.addHandler(handler)


def _disarm_pxla_logger(handler: logging.Handler) -> None:
    global _logger_prev
    logger = logging.getLogger(_PXLA_LOGGER)
    logger.removeHandler(handler)
    # only handlers WE armed count toward the restore — an uninstall of a
    # shared watchdog handed out while the sanitizer was active (never
    # armed here) must not release someone else's arming
    _logger_armed.discard(id(handler))
    if not _logger_armed and _logger_prev is not None:
        logger.setLevel(_logger_prev[0])
        logger.propagate = _logger_prev[1]
        _logger_prev = None


def sanitize_enabled() -> bool:
    return os.environ.get(ENV_FLAG, "0") not in ("", "0", "false", "False")


def current_watchdog() -> Optional[CompileWatchdog]:
    return _state.watchdog if _state.active else None


_SANITIZE_FLAGS = (("jax_debug_nans", True),
                   ("jax_numpy_rank_promotion", "raise"))


def enable_sanitizer() -> CompileWatchdog:
    """Idempotently turn the sanitizer on; returns the watchdog."""
    import jax
    with _state_lock:
        if _state.active:
            return _state.watchdog
        for name, value in _SANITIZE_FLAGS:
            _state.prev_config[name] = getattr(jax.config, name)
            jax.config.update(name, value)
        wd = CompileWatchdog()
        # jax reports donation mismatches via warnings.warn (not logging):
        # hook showwarning so the watchdog sees every one, and make them
        # repeat-warn instead of Python's warn-once.  Both the filter list
        # and the hook are restored on disable.
        _state.prev_warn_filters = list(warnings.filters)
        warnings.filterwarnings(
            "always", message=r".*[Dd]onated buffers?.*")
        _state.prev_showwarning = warnings.showwarning

        def showwarning(message, category, filename, lineno, file=None,
                        line=None, _prev=warnings.showwarning):
            wd.note_warning(str(message))
            return _prev(message, category, filename, lineno, file, line)

        warnings.showwarning = showwarning
        _arm_pxla_logger(wd)
        _state.watchdog = wd
        _state.active = True
        logging.getLogger("kubetpu.sanitize").info(
            "sanitizer on: debug_nans, rank_promotion=raise, donation "
            "logging, compile-count watchdog")
        return wd


def disable_sanitizer() -> None:
    """Restore every flag/handler enable_sanitizer() touched."""
    import jax
    with _state_lock:
        if not _state.active:
            return
        for name, value in _state.prev_config.items():
            jax.config.update(name, value)
        _state.prev_config.clear()
        if _state.watchdog is not None:
            _disarm_pxla_logger(_state.watchdog)
        if _state.prev_warn_filters is not None:
            warnings.filters[:] = _state.prev_warn_filters
        if _state.prev_showwarning is not None:
            warnings.showwarning = _state.prev_showwarning
        _state.prev_warn_filters = None
        _state.prev_showwarning = None
        _state.watchdog = None
        _state.active = False


@contextmanager
def sanitized():
    """Scoped sanitizer for tests: restores config on exit.  If the
    sanitizer was already active (e.g. armed process-wide via
    KUBETPU_SANITIZE=1 at import), the context joins it and leaves it
    running on exit instead of tearing it down.

    ::

        with sanitized() as watchdog:
            run_cycle()
            watchdog.assert_no_recompilation()
    """
    owned = not _state.active
    wd = enable_sanitizer()
    if not owned:
        # joining a process-wide sanitizer: scope the counts so this
        # block's assert_no_recompilation() judges only its own work
        wd.reset()
    try:
        yield wd
    finally:
        if owned:
            disable_sanitizer()


def install_compile_watchdog() -> CompileWatchdog:
    """Attach ONLY the compile-count watchdog (no debug_nans, no
    rank-promotion, no warnings hook): the observer bench.py's
    BENCH_GATE=1 census cross-check needs — compile events must be
    recorded without perturbing the measured numerics.  If the full
    sanitizer is already armed, its watchdog is shared.  Pair with
    uninstall_compile_watchdog()."""
    with _state_lock:
        if _state.active:
            return _state.watchdog
        wd = CompileWatchdog()
        _arm_pxla_logger(wd)
        return wd


def uninstall_compile_watchdog(wd: CompileWatchdog) -> None:
    """Detach a watchdog installed by install_compile_watchdog().  A
    watchdog owned by the full sanitizer is left in place (its lifecycle
    belongs to disable_sanitizer)."""
    with _state_lock:
        if _state.active and wd is _state.watchdog:
            return
        _disarm_pxla_logger(wd)


# --------------------------------------------------------- compile timer
#
# The pxla-log watchdog above COUNTS compiles; it cannot time them, and
# with the persistent cache enabled "a compile happened" conflates two
# very different costs: a true XLA backend compile (seconds to minutes)
# and a disk load of a previously compiled executable (milliseconds).
# jax's own monitoring stream separates them:
#
#   /jax/core/compile/backend_compile_duration   fires on BOTH paths (on
#       a cache hit its duration is the deserialization/load time)
#   /jax/compilation_cache/cache_retrieval_time_sec   fires on hits only
#   /jax/compilation_cache/cache_hits | cache_misses  the counts
#
# so true compile seconds = backend total - retrieval total.  bench.py's
# compile_estimate used first-minus-best wall clock, which goes NEGATIVE
# on cache-warm runs; the timer reports compile_s and cache_load_s
# separately and exactly.

_COMPILE_DURATION_EV = "/jax/core/compile/backend_compile_duration"
_CACHE_RETRIEVAL_EV = "/jax/compilation_cache/cache_retrieval_time_sec"
_CACHE_HIT_EV = "/jax/compilation_cache/cache_hits"
_CACHE_MISS_EV = "/jax/compilation_cache/cache_miss"


class CompileTimer:
    """Cumulative compile/cache-load seconds from jax.monitoring events.
    Thread-safe; read with snapshot() and diff two snapshots with delta()
    to attribute cost to a measured phase (bench attempt 0, a prewarm)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.backend_s = 0.0          # kubelint: guarded-by(_lock)
        self.cache_load_s = 0.0       # kubelint: guarded-by(_lock)
        self.cache_hits = 0           # kubelint: guarded-by(_lock)
        self.cache_misses = 0         # kubelint: guarded-by(_lock)

    def on_duration(self, event: str, duration: float, **kw) -> None:
        with self._lock:
            if event == _COMPILE_DURATION_EV:
                self.backend_s += duration
            elif event == _CACHE_RETRIEVAL_EV:
                self.cache_load_s += duration

    def on_event(self, event: str, **kw) -> None:
        with self._lock:
            if event == _CACHE_HIT_EV:
                self.cache_hits += 1
            elif event.startswith(_CACHE_MISS_EV):   # cache_miss(es)
                self.cache_misses += 1

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return {
                "compile_s": max(self.backend_s - self.cache_load_s, 0.0),
                "cache_load_s": self.cache_load_s,
                "cache_hits": self.cache_hits,
                "cache_misses": self.cache_misses,
            }

    @staticmethod
    def delta(before: Dict[str, float],
              after: Dict[str, float]) -> Dict[str, float]:
        """after - before, per key (seconds rounded to ms)."""
        out = {}
        for k, v in after.items():
            d = v - before.get(k, 0)
            out[k] = round(d, 3) if isinstance(d, float) else d
        return out


_timer: Optional[CompileTimer] = None
_timer_lock = threading.Lock()


def install_compile_timer() -> CompileTimer:
    """Idempotently install the module's CompileTimer.  jax.monitoring
    offers no per-listener detach, so ONE timer is registered for the
    process lifetime and shared by every caller (cumulative totals;
    consumers diff snapshots)."""
    global _timer
    with _timer_lock:
        if _timer is None:
            import jax.monitoring as _mon
            t = CompileTimer()
            _mon.register_event_duration_secs_listener(t.on_duration)
            _mon.register_event_listener(t.on_event)
            _timer = t
        return _timer


def maybe_enable_from_env() -> Optional[CompileWatchdog]:
    """Serving-path hook: enables the sanitizer iff KUBETPU_SANITIZE=1.
    Called from kubetpu/__init__.py so every entry point (scheduler,
    server, bench, harness) gets it without its own wiring.  Importing
    this module never imports jax; enabling does."""
    if sanitize_enabled():
        return enable_sanitizer()
    return None
