"""Per-pod "why (un)scheduled" decision audit.

The reference surfaces scheduling failures as one aggregated event string
("0/100 nodes are available: 88 Insufficient cpu, 12 node(s) didn't match
pod affinity rules" — framework/v1alpha1/interface.go FitError).  The
batched device path already computes per-(pod, node) verdict masks; the
scheduler folds them (models/programs.py:explain_verdicts), together with
host-plugin and extender outcomes, into this bounded log so
``/debug/explain?pod=`` can answer "which plugin, on how many nodes,
rejected pod X" — and "which node would it have landed on" — long after
the cycle's tensors are gone.

Bounded-memory contract: at most ``KUBETPU_DECISIONS`` entries (default
1024) keyed by namespace/name; recording an already-known pod replaces
its entry in place (a pod's LAST attempt is the interesting one), older
pods evict FIFO and count in ``evicted``.  The audit is on by default and
disabled with ``KUBETPU_AUDIT=0`` — disabled, the scheduler never calls
into this module, so the hot path takes no DecisionLog lock.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Any, Dict, List, Optional

AUDIT_ENV = "KUBETPU_AUDIT"
CAPACITY_ENV = "KUBETPU_DECISIONS"
DEFAULT_CAPACITY = 1024


def audit_enabled() -> bool:
    return os.environ.get(AUDIT_ENV, "1") not in ("", "0", "false", "False")


class PodDecision:
    """One pod's most recent scheduling decision."""

    __slots__ = ("name", "namespace", "uid", "outcome", "node",
                 "nominated_node", "message", "n_feasible", "best_node",
                 "best_score", "rejections", "blocking", "host_reasons",
                 "extenders", "cycle", "ts")

    def __init__(self, name: str, namespace: str, uid: str, outcome: str,
                 node: str = "", nominated_node: str = "",
                 message: str = "", n_feasible: int = 0,
                 best_node: str = "", best_score: Optional[float] = None,
                 rejections: Optional[Dict[str, int]] = None,
                 blocking: Optional[List[str]] = None,
                 host_reasons: Optional[Dict[str, int]] = None,
                 extenders: Optional[Dict[str, Any]] = None,
                 cycle: int = 0):
        self.name = name
        self.namespace = namespace
        self.uid = uid
        self.outcome = outcome          # "scheduled" | "unschedulable"
        self.node = node
        self.nominated_node = nominated_node
        self.message = message
        self.n_feasible = n_feasible
        self.best_node = best_node
        self.best_score = best_score
        self.rejections = rejections or {}   # plugin -> failed-node count
        self.blocking = blocking or []       # decisive plugin name(s)
        self.host_reasons = host_reasons or {}  # host reason -> node count
        self.extenders = extenders or {}
        self.cycle = cycle
        self.ts = time.time()

    def why(self) -> str:
        """The human one-liner: 'pod X: 412 nodes failed NodeResourcesFit,
        588 failed InterPodAffinity, best feasible score 0.83 on
        node-17'."""
        key = f"{self.namespace}/{self.name}"
        if self.outcome == "scheduled":
            out = (f"pod {key}: scheduled on {self.node} "
                   f"({self.n_feasible} feasible node(s))")
            return out
        parts = [f"{n} nodes failed {plugin}"
                 for plugin, n in sorted(self.rejections.items(),
                                         key=lambda kv: -kv[1]) if n]
        parts += [f"{n} nodes rejected by host filter: {reason}"
                  for reason, n in sorted(self.host_reasons.items(),
                                          key=lambda kv: -kv[1]) if n]
        for ename, info in self.extenders.items():
            parts.append(f"extender {ename}: {info}")
        out = f"pod {key}: " + (", ".join(parts) if parts
                                else self.message or "unschedulable")
        if self.blocking:
            out += f" (blocking: {', '.join(self.blocking)})"
        if self.best_node and self.best_score is not None:
            out += (f", best feasible score {self.best_score:.2f} "
                    f"on {self.best_node}")
        if self.nominated_node:
            out += f"; preemption nominated {self.nominated_node}"
        return out

    def to_dict(self) -> Dict[str, Any]:
        d = {"pod": self.name, "namespace": self.namespace, "uid": self.uid,
             "outcome": self.outcome, "cycle": self.cycle,
             "ts": round(self.ts, 3), "why": self.why()}
        if self.node:
            d["node"] = self.node
        if self.nominated_node:
            d["nominated_node"] = self.nominated_node
        if self.message:
            d["message"] = self.message
        d["n_feasible"] = self.n_feasible
        if self.best_node:
            d["best_node"] = self.best_node
            d["best_score"] = (round(self.best_score, 4)
                               if self.best_score is not None else None)
        if self.rejections:
            d["rejections"] = dict(self.rejections)
        if self.blocking:
            d["blocking"] = list(self.blocking)
        if self.host_reasons:
            d["host_reasons"] = dict(self.host_reasons)
        if self.extenders:
            d["extenders"] = dict(self.extenders)
        return d


class DecisionLog:
    """Bounded, lock-guarded map of the most recent decision per pod."""

    def __init__(self, capacity: Optional[int] = None,
                 enabled: Optional[bool] = None):
        self.capacity = capacity or int(
            os.environ.get(CAPACITY_ENV, str(DEFAULT_CAPACITY)))
        self.enabled = audit_enabled() if enabled is None else enabled
        self._lock = threading.Lock()
        self._entries: "collections.OrderedDict[str, PodDecision]" = \
            collections.OrderedDict()  # kubelint: guarded-by(_lock)
        self._evicted = 0              # kubelint: guarded-by(_lock)

    @staticmethod
    def _key(name: str, namespace: str) -> str:
        return f"{namespace}/{name}"

    def record(self, decision: PodDecision) -> None:
        key = self._key(decision.name, decision.namespace)
        with self._lock:
            self._entries.pop(key, None)
            self._entries[key] = decision
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)
                self._evicted += 1

    def get(self, name: str,
            namespace: Optional[str] = None) -> Optional[PodDecision]:
        """Lookup by pod name; namespace=None matches any namespace (the
        /debug/explain?pod= convenience — pod names are usually unique
        enough for a debugging endpoint)."""
        with self._lock:
            if namespace is not None:
                return self._entries.get(self._key(name, namespace))
            for d in reversed(self._entries.values()):
                if d.name == name:
                    return d
        return None

    def recent(self, n: int = 50,
               outcome: Optional[str] = None) -> List[PodDecision]:
        if n <= 0:
            return []   # entries[-0:] would be the WHOLE log
        with self._lock:
            entries = list(self._entries.values())
        if outcome:
            entries = [d for d in entries if d.outcome == outcome]
        return entries[-n:][::-1]

    def evicted(self) -> int:
        with self._lock:
            return self._evicted

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def to_dict(self, n: int = 50,
                outcome: Optional[str] = None) -> Dict[str, Any]:
        return {"enabled": self.enabled, "capacity": self.capacity,
                "size": len(self), "evicted": self.evicted(),
                "decisions": [d.to_dict()
                              for d in self.recent(n, outcome=outcome)]}
