"""Device-side observability: measured per-program device time, the HBM
residency ledger, and roofline attribution.

Every device-side number the stack reported before this module was
``device_wait_s`` — host wall-clock blocked on the packed readback —
which the depth-k pipeline deliberately hides: overlap makes the number
a lie (bench.py refused to compute achieved TFLOP/s on pipelined runs
for exactly that reason), and both ROADMAP north-star items terminate in
claims ("<1 s p99 at 100k x 10k", "Mosaic kernel: device time down")
that could not be attributed to the device at all.  Three pillars:

1. MEASURED PER-PROGRAM DEVICE TIME.  A sampled deep-timing mode fences
   individual dispatches: every Nth cycle (``KUBETPU_DEVSTATS_SAMPLE``,
   default 8) the scheduler reads back one SMALL output right after
   each program dispatch (np.asarray — the only completion signal the
   tunneled chip exposes; ``jax.block_until_ready`` does not block
   through the axon tunnel and would measure dispatch only) and records
   the wall seconds as that program's
   ``device_time_s`` (programs: ``run_auction``,
   ``schedule_sequential``, ``apply_cluster_delta``;
   ``explain_verdicts`` is recorded on EVERY armed failure cycle — its
   ``np.asarray`` readback is already a natural sync, so the
   measurement is free).  The fence serializes work the pipeline would
   have overlapped, so sampling bounds the overhead to ~1/N of cycles
   and the cumulative fenced seconds are recorded
   (``fence_wait_s``) so the overhead is never invisible.  Where the
   ``jax.profiler`` capture hook runs (``trace.capture_device_trace``),
   ``ingest_xplane`` additionally parses the XPlane capture into
   per-program records when the profiler tooling is importable, and
   records WHY not when it isn't — never silently.

2. HBM RESIDENCY LEDGER.  Allocation seams register what actually
   lives on device: the DeltaTensorizer's resident cluster (per-table
   bytes per profile), the speculative chain's materialized cluster at
   its pad buckets, prewarm-ladder buffers, and AOT resident executable
   blobs.  ``project()`` scales a registered entry's per-table shapes
   to arbitrary (nodes, pods) — node-axis dims scale linearly, pod-axis
   dims re-bucket through ``pow2_bucket``, kv-vocab dims follow the
   hostname-dominated linear-in-nodes model, everything else is held —
   so "does the 100k x 10k north-star fit per v5e shard" is answerable
   OFFLINE from any ledger snapshot (tools/devplan, /debug/devicez, or
   a bench ``device`` block).  The projection model is validated by the
   capacity-planner sanity gate in tests/test_devstats.py: projected vs
   actually-measured bytes at bench shapes agree within 10%.

3. ROOFLINE JOIN.  Measured device time joins the committed
   ``COMPILE_MANIFEST.json`` cost rows (XLA cost-analysis ``flops`` and
   ``bytes_accessed`` per lowering sha): each program's arithmetic
   intensity classifies it compute- vs memory-bound against the chip's
   peak FLOP/s (utils/flops.peak_flops_per_s) and peak HBM bandwidth
   (``KUBETPU_PEAK_GBPS``, default v5e 819 GB/s), and achieved FLOP/s
   over the measured seconds yields ``roofline_fraction`` — how much of
   the bound the program actually sustains.  Achieved FLOPs come from
   the analytic model where one exists (the gang auction,
   utils/flops.gang_cycle_flops, attributed per fenced cycle) and from
   the manifest cost row scaled by operand bytes otherwise
   (``flops_source`` says which).  Surfaced in ``/debug/devicez``, the
   bench per-case ``device`` block, flight-recorder ``device-fence``
   span args, the pipeline doc's ``device`` block (the traceview
   "device:" digest), and tools/benchtrend.py attribution.

ARMING (the house contract, mirroring utils/slo.py / utils/trace.py):
``KUBETPU_DEVSTATS=1`` or ``arm_devstats()``.  DISARMED (the default)
every seam is ONE module-attribute read and the hot path takes ZERO new
locks — proven by the poison-monkeypatch test — and armed-vs-disarmed
placements are bit-identical (the parity golden): fencing only waits,
it never changes a value.  Importing this module never imports jax.
"""

from __future__ import annotations

import json
import math
import os
import re
import threading
from typing import Any, Dict, List, Optional, Tuple

from .intern import pow2_bucket

DEVSTATS_ENV = "KUBETPU_DEVSTATS"
SAMPLE_ENV = "KUBETPU_DEVSTATS_SAMPLE"
PEAK_GBPS_ENV = "KUBETPU_PEAK_GBPS"
HBM_GIB_ENV = "KUBETPU_HBM_GIB"
DEFAULT_SAMPLE_INTERVAL = 8
# v5e: 819 GB/s HBM bandwidth, 16 GiB HBM per chip
DEFAULT_PEAK_GBPS = 819.0
DEFAULT_HBM_GIB = 16.0

# the serving programs devstats attributes, mapped to their manifest
# program names (tools/kubecensus traces the jitted inner functions)
PROGRAMS = {
    "run_auction": "_schedule_gang",
    "schedule_sequential": "_schedule_sequential",
    "apply_cluster_delta": "_apply_cluster_delta",
    "explain_verdicts": "_explain_verdicts",
}

_AVAL_RE = re.compile(r"^([a-z_0-9]+)\[([0-9,]*)\]$")
_DTYPE_BYTES = {"bool": 1, "int8": 1, "uint8": 1, "int16": 2, "uint16": 2,
                "bfloat16": 2, "float16": 2, "int32": 4, "uint32": 4,
                "float32": 4, "int64": 8, "uint64": 8, "float64": 8}


def peak_membw_bytes_per_s() -> float:
    """Chip peak HBM bandwidth (bytes/s); KUBETPU_PEAK_GBPS overrides
    the v5e default for other parts."""
    return float(os.environ.get(PEAK_GBPS_ENV,
                                str(DEFAULT_PEAK_GBPS))) * 1e9


def hbm_bytes() -> float:
    """Per-chip HBM capacity (bytes); KUBETPU_HBM_GIB overrides."""
    return float(os.environ.get(HBM_GIB_ENV,
                                str(DEFAULT_HBM_GIB))) * 2.0 ** 30


def _aval_bytes(aval: str) -> int:
    """Bytes of one manifest aval string ('float32[64,12]')."""
    m = _AVAL_RE.match(aval.strip())
    if not m:
        return 0
    dt, dims = m.groups()
    n = 1
    for d in filter(None, dims.split(",")):
        n *= int(d)
    return n * _DTYPE_BYTES.get(dt, 4)


def pytree_nbytes(tree) -> int:
    """Total bytes of a pytree of shaped arrays (jax or numpy) — pure
    shape/dtype arithmetic, no transfer, no sync.  Armed-only helper
    (the import of jax.tree is why)."""
    import jax
    total = 0
    for leaf in jax.tree.leaves(tree):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            continue
        n = 1
        for d in shape:
            n *= int(d)
        total += n * _DTYPE_BYTES.get(str(dtype), 4)
    return total


def table_entries(named_tables: Dict[str, Any]) -> Dict[str, List[dict]]:
    """Per-table leaf entries ({name: [{shape, dtype, bytes}, ...]}) of
    a dict of array pytrees — the ledger registration payload, computed
    OUTSIDE any lock (armed-only; imports jax.tree)."""
    import jax
    out: Dict[str, List[dict]] = {}
    for name, tree in named_tables.items():
        rows = []
        for leaf in jax.tree.leaves(tree):
            shape = getattr(leaf, "shape", None)
            dtype = getattr(leaf, "dtype", None)
            if shape is None or dtype is None:
                continue
            n = 1
            for d in shape:
                n *= int(d)
            rows.append({"shape": [int(d) for d in shape],
                         "dtype": str(dtype),
                         "bytes": n * _DTYPE_BYTES.get(str(dtype), 4)})
        out[name] = rows
    return out


# -------------------------------------------------------- manifest costs


_manifest_cache: Optional[Dict[str, dict]] = None
_manifest_lock = threading.Lock()


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def manifest_costs(path: Optional[str] = None) -> Dict[str, dict]:
    """Per-program cost reference from COMPILE_MANIFEST.json: for each
    manifest program the single-device row with the LARGEST flops (the
    biggest census rung — the most representative arithmetic-intensity
    sample), as {program: {flops, bytes_accessed, in_bytes, variant,
    lowering_sha256}}.  Cached after the first read; an unreadable
    manifest yields an empty map (every roofline degrades to
    timing-only, never an error)."""
    global _manifest_cache
    with _manifest_lock:
        if _manifest_cache is not None and path is None:
            return _manifest_cache
    try:
        with open(path or os.path.join(_repo_root(),
                                       "COMPILE_MANIFEST.json")) as f:
            rows = json.load(f).get("rows", [])
    except (OSError, ValueError):
        rows = []
    out: Dict[str, dict] = {}
    for row in rows:
        if row.get("sharding"):
            continue            # mesh twins: per-shard costs mislead
        prog = row.get("program")
        cost = row.get("cost") or {}
        flops = cost.get("flops")
        ba = cost.get("bytes_accessed")
        if not prog or not isinstance(flops, (int, float)) \
                or not isinstance(ba, (int, float)) or ba <= 0:
            continue
        cur = out.get(prog)
        if cur is None or flops > cur["flops"]:
            out[prog] = {
                "flops": float(flops), "bytes_accessed": float(ba),
                "in_bytes": sum(_aval_bytes(a)
                                for a in row.get("in_avals") or []),
                "variant": row.get("variant"),
                "lowering_sha256": (row.get("lowering_sha256") or "")[:16],
            }
            # per-collective DCN bytes (joined into the census row from
            # EXACT_MANIFEST.json): lets the roofline split arithmetic
            # bandwidth from cross-device transfer per program
            xb = cost.get("collective_bytes")
            if isinstance(xb, dict):
                out[prog]["collective_bytes"] = xb
    if path is None:
        with _manifest_lock:
            _manifest_cache = out
    return out


def roofline(program: str, seconds: float,
             flops: Optional[float] = None,
             in_bytes: Optional[float] = None,
             costs: Optional[Dict[str, dict]] = None) -> Optional[dict]:
    """Join one program's measured device seconds against its manifest
    cost row.  ``flops``: analytically-counted FLOPs executed during
    ``seconds`` (utils/flops) — preferred; without it the manifest row's
    flops are SCALED by operand bytes (``in_bytes`` / row in_bytes, the
    linear model that holds for these memory-shaped programs) and
    ``flops_source`` says "scaled-census".  Returns None when the
    program has no manifest cost row; the roofline bound is
    min(peak FLOP/s, AI * peak bytes/s)."""
    costs = costs if costs is not None else manifest_costs()
    row = costs.get(PROGRAMS.get(program, program))
    if row is None or seconds <= 0:
        return None
    from .flops import peak_flops_per_s
    ai = row["flops"] / row["bytes_accessed"]
    peak_f = peak_flops_per_s()
    peak_b = peak_membw_bytes_per_s()
    bound = min(peak_f, ai * peak_b)
    out = {
        "manifest_variant": row["variant"],
        "lowering_sha256": row["lowering_sha256"],
        "arithmetic_intensity": round(ai, 4),
        "regime": ("compute-bound" if ai * peak_b >= peak_f
                   else "memory-bound"),
        "roofline_bound_tflops": round(bound / 1e12, 3),
    }
    if flops is not None and flops > 0:
        out["flops_source"] = "analytic"
        achieved = flops / seconds
    elif in_bytes and row["in_bytes"] > 0:
        out["flops_source"] = "scaled-census"
        achieved = row["flops"] * (in_bytes / row["in_bytes"]) / seconds
    else:
        return out
    out["achieved_tflops"] = round(achieved / 1e12, 6)
    out["roofline_fraction"] = round(achieved / bound, 6)
    return out


# ------------------------------------------------------------- projection


def project(ledger_doc: Dict[str, Any], nodes: int, pods: int,
            shards: int = 1,
            groups: Optional[Tuple[str, ...]] = None) -> Dict[str, Any]:
    """Capacity projection: scale a ledger snapshot's per-table shapes
    to (nodes, pods) and answer whether the result fits per-chip HBM.

    The per-dim model (validated within 10% at bench shapes by the
    sanity gate in tests/test_devstats.py):

      * a dim equal to the entry's recorded node count scales linearly
        to ``nodes`` (the node axis is exact, never bucketed);
      * a dim equal to the recorded pod-axis bucket re-buckets to
        ``pow2_bucket(pods)``;
      * a dim equal to the recorded kv-vocab cap follows the
        hostname-dominated model ``pow2_bucket(kv0 * nodes/nodes0)`` —
        every node contributes a unique hostname (k, v) pair, so the
        label-pair vocab grows linearly with the node count;
      * every other dim (resource channels, label KEYS, zones, ports,
        taints — content-bounded vocabularies) is held.

    ``shards`` models a mesh that shards the POD axis (parallel/mesh.py
    does): per-shard bytes re-project with pods/shards.  Returns per-
    table and per-group projected bytes plus the fit verdict against
    ``hbm_bytes()`` (KUBETPU_HBM_GIB)."""

    def scale_entry(entry: dict, n_pods: int) -> Tuple[int, Dict[str, int]]:
        axes = entry.get("axes") or {}
        n0 = axes.get("nodes")
        p0 = axes.get("pods")
        kv0 = axes.get("kv")
        p1 = pow2_bucket(max(int(n_pods), 1))
        kv1 = (pow2_bucket(int(math.ceil(kv0 * nodes / n0)))
               if kv0 and n0 else None)
        per_table: Dict[str, int] = {}
        total = 0
        for name, leaves in (entry.get("tables") or {}).items():
            tb = 0
            for leaf in leaves:
                b = leaf.get("bytes", 0)
                shape = leaf.get("shape") or []
                # per-dim role tags stamped at registration
                # (register_cluster) are authoritative — they survive
                # the n0 == p0 collision that value matching cannot
                # (e.g. 2048 nodes with a 2048 pod bucket would
                # otherwise scale the pod axis node-linearly and
                # corrupt the north-star projection).  Entries without
                # tags (opaque byte records, foreign documents) fall
                # back to value matching per dim.
                dims = leaf.get("dims")
                factor = 1.0
                for j, d in enumerate(shape):
                    if dims is not None and j < len(dims):
                        tag = dims[j]
                    elif n0 and d == n0:
                        tag = "nodes"
                    elif p0 and d == p0:
                        tag = "pods"
                    elif kv0 and d == kv0:
                        tag = "kv"
                    else:
                        tag = None
                    if tag == "nodes" and n0:
                        factor *= nodes / n0
                    elif tag == "pods" and p0:
                        factor *= p1 / p0
                    elif tag == "kv" and kv0 and kv1:
                        factor *= kv1 / kv0
                tb += int(math.ceil(b * factor))
            per_table[name] = tb
            total += tb
        return total, per_table

    per_group: Dict[str, int] = {}
    tables: Dict[str, int] = {}
    total = 0
    shard_total = 0
    for key, entry in sorted((ledger_doc.get("entries") or {}).items()):
        if groups is not None and entry.get("group") not in groups:
            continue
        t, per_table = scale_entry(entry, pods)
        st, _ = scale_entry(entry, max(pods // max(shards, 1), 1))
        per_group[key] = t
        total += t
        shard_total += st
        for name, b in per_table.items():
            tables[f"{key}/{name}"] = b
    cap = hbm_bytes()
    return {
        "nodes": int(nodes), "pods": int(pods),
        "pod_bucket": pow2_bucket(max(int(pods), 1)),
        "shards": int(shards),
        "per_group_bytes": per_group,
        "per_table_bytes": tables,
        "total_bytes": total,
        "per_shard_bytes": shard_total,
        "hbm_bytes_per_chip": int(cap),
        "fits_single_chip": total <= cap,
        "fits_per_shard": shard_total <= cap,
    }


# ---------------------------------------------------------------- DevStats


class DevStats:
    """Per-program device-time records + the residency ledger.

    Lock-guarded: the serving thread records, /debug/devicez and the
    bench read concurrently.  All derivation (shape walks, byte sums,
    roofline math) happens OUTSIDE the lock — only dict updates run
    under it (concurrency-family contract, like utils/slo.py)."""

    def __init__(self, sample_interval: Optional[int] = None):
        si = sample_interval if sample_interval is not None else int(
            os.environ.get(SAMPLE_ENV, str(DEFAULT_SAMPLE_INTERVAL)))
        self.sample_interval = max(int(si), 1)
        self._lock = threading.Lock()
        self._programs: Dict[str, dict] = {}  # kubelint: guarded-by(_lock)
        self._entries: Dict[str, dict] = {}   # kubelint: guarded-by(_lock)
        self._cycles = 0                      # kubelint: guarded-by(_lock)
        self._deep = False                    # kubelint: guarded-by(_lock)
        self.fenced_cycles = 0                # kubelint: guarded-by(_lock)
        self.fence_wait_s = 0.0               # kubelint: guarded-by(_lock)
        self._xplane: Optional[dict] = None   # kubelint: guarded-by(_lock)

    # ---- sampling --------------------------------------------------------

    def begin_cycle(self) -> bool:
        """Serving-thread cycle tick: every ``sample_interval``-th cycle
        is a deep-timing cycle — its dispatches are micro-fenced.  The
        flag latches until the next tick so the cycle's later seams
        (delta apply, dispatch) agree on the decision.  Phase: the
        FIRST cycle after arming (or a bench-case clear()) is deep, so
        a drain shorter than the interval still yields at least one
        measured sample (compile cost can't pollute it — jit traces and
        compiles synchronously in the dispatch call, before the fence
        timer starts)."""
        with self._lock:
            self._cycles += 1
            self._deep = (self._cycles - 1) % self.sample_interval == 0
            if self._deep:
                self.fenced_cycles += 1
            return self._deep

    def deep_active(self) -> bool:
        with self._lock:
            return self._deep

    # ---- per-program device time ----------------------------------------

    def record_program(self, program: str, seconds: float,
                       source: str = "fence",
                       in_bytes: Optional[int] = None) -> None:
        """Fold one measured device-time sample in.  source: "fence"
        (block_until_ready micro-fence), "sync" (a naturally-blocking
        readback, e.g. explain_verdicts), "xplane" (profiler capture)."""
        s = max(float(seconds), 0.0)
        with self._lock:
            st = self._programs.get(program)
            if st is None:
                st = self._programs[program] = {
                    "count": 0, "sum_s": 0.0, "min_s": math.inf,
                    "max_s": 0.0, "last_s": 0.0, "sources": {},
                    "in_bytes_sum": 0, "flops_sum": 0.0,
                    "flops_time_s": 0.0}
            st["count"] += 1
            st["sum_s"] += s
            st["min_s"] = min(st["min_s"], s)
            st["max_s"] = max(st["max_s"], s)
            st["last_s"] = s
            st["sources"][source] = st["sources"].get(source, 0) + 1
            if in_bytes:
                st["in_bytes_sum"] += int(in_bytes)
            if source == "fence":
                self.fence_wait_s += s

    def attribute_flops(self, program: str, flops: float,
                        seconds: Optional[float] = None) -> None:
        """Pair analytically-counted FLOPs with a recorded sample's
        seconds (the scheduler knows the auction's round count — and so
        its flops — only after the readback, one seam later than the
        fence).  Callers pass the SAMPLE'S OWN fence seconds: under a
        sampling interval smaller than the pipeline depth, newer fence
        samples land before the older cycle's commit runs, so "the last
        sample" would mispair; last_s is only the fallback."""
        with self._lock:
            st = self._programs.get(program)
            if st is None or not st["count"]:
                return
            st["flops_sum"] += float(flops)
            st["flops_time_s"] += (float(seconds) if seconds is not None
                                   else st["last_s"])

    def program_stats(self, program: str) -> Optional[dict]:
        with self._lock:
            st = self._programs.get(program)
            return dict(st) if st is not None else None

    def mean_seconds(self, program: str) -> float:
        """Mean measured device seconds per sampled dispatch of a
        program (0.0 when never sampled) — bench estimates a drain's
        total device time as mean * cycle count."""
        with self._lock:
            st = self._programs.get(program)
            if st is None or not st["count"]:
                return 0.0
            return st["sum_s"] / st["count"]

    # ---- residency ledger ------------------------------------------------

    def record_ledger(self, group: str, profile: str,
                      tables: Dict[str, List[dict]],
                      axes: Optional[Dict[str, int]] = None,
                      meta: Optional[Dict[str, Any]] = None) -> None:
        """(Re-)register one allocation seam's resident tables.  Keyed
        (group, profile): a re-registration REPLACES the previous one —
        the ledger describes what is resident NOW, not history.  tables:
        ``table_entries()`` output, computed by the caller outside this
        lock."""
        total = sum(leaf.get("bytes", 0)
                    for leaves in tables.values() for leaf in leaves)
        entry = {"group": group, "profile": profile,
                 "tables": tables, "axes": dict(axes or {}),
                 "bytes": total, "meta": dict(meta or {})}
        key = f"{group}/{profile}" if profile else group
        with self._lock:
            prev = self._entries.get(key)
            entry["registrations"] = (prev["registrations"] + 1
                                      if prev else 1)
            self._entries[key] = entry

    def record_bytes(self, group: str, profile: str, name: str,
                     nbytes: int) -> None:
        """Register one opaque resident allocation (e.g. a deserialized
        AOT executable blob) by NAME within the (group, profile) entry.
        Re-registering the same name REPLACES the previous bytes —
        a restarted runtime (or a bench attempt's fresh Scheduler)
        re-loading the same artifact describes the SAME residency, and
        an additive ledger would grow without bound while real HBM use
        did not."""
        key = f"{group}/{profile}" if profile else group
        leaf = {"shape": [], "dtype": "bytes", "bytes": int(nbytes)}
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                entry = self._entries[key] = {
                    "group": group, "profile": profile, "tables": {},
                    "axes": {}, "bytes": 0, "meta": {},
                    "registrations": 0}
            prev = entry["tables"].get(name)
            if prev:
                entry["bytes"] -= sum(l.get("bytes", 0) for l in prev)
            entry["tables"][name] = [leaf]
            entry["bytes"] += int(nbytes)
            entry["registrations"] += 1

    def has_group(self, group: str) -> bool:
        with self._lock:
            return any(e["group"] == group
                       for e in self._entries.values())

    def drop_group(self, group: str,
                   profile: Optional[str] = None) -> None:
        """Unregister a group's entries (all profiles, or one) — the
        ledger describes what is resident NOW: a discarded speculative
        chain's cluster is freed device memory and must stop counting
        against the capacity projection."""
        with self._lock:
            for k in [k for k, e in self._entries.items()
                      if e["group"] == group
                      and (profile is None or e["profile"] == profile)]:
                del self._entries[k]

    def ledger(self) -> Dict[str, Any]:
        """The ledger snapshot tools/devplan projects from."""
        with self._lock:
            entries = {k: {**v, "tables": {n: [dict(l) for l in ls]
                                           for n, ls in
                                           v["tables"].items()}}
                       for k, v in self._entries.items()}
        return {"entries": entries,
                "total_bytes": sum(e["bytes"] for e in entries.values())}

    # ---- xplane ----------------------------------------------------------

    def ingest_xplane(self, log_dir: str) -> dict:
        """Best-effort XPlane ingestion from a jax.profiler capture dir
        (trace.capture_device_trace calls this on exit when armed).
        When the profiler analysis tooling is importable, per-program
        device durations fold in as "xplane"-source samples; when it is
        not (the common serving image), the REASON is recorded — the
        capture is never silently dropped."""
        status: Dict[str, Any] = {"dir": log_dir}
        paths: List[str] = []
        for dp, _dirs, fs in os.walk(log_dir):
            paths.extend(os.path.join(dp, f) for f in fs
                         if f.endswith(".xplane.pb"))
        status["captures"] = len(paths)
        records = 0
        if not paths:
            status["available"] = False
            status["reason"] = "no .xplane.pb capture found"
        else:
            try:
                # the TensorBoard profiler plugin's converter is the
                # only public XPlane parser; serving images usually
                # don't ship it
                from tensorflow.python.profiler.internal import _pywrap_profiler  # noqa: F401
                status["available"] = True
            except Exception as e:
                status["available"] = False
                status["reason"] = ("xplane tooling unavailable "
                                    f"({type(e).__name__}); deep-timing "
                                    "fences remain the measured source")
            else:  # pragma: no cover - profiler tooling not in CI image
                for p in paths:
                    for prog, secs in _parse_xplane(p).items():
                        self.record_program(prog, secs, source="xplane")
                        records += 1
        status["records"] = records
        with self._lock:
            self._xplane = status
        return status

    # ---- reads -----------------------------------------------------------

    def clear(self) -> None:
        """Drop program samples and the fence accounting; the ledger
        (what is resident) survives — bench calls this between attempts
        so each case's ``device`` block describes one drain."""
        with self._lock:
            self._programs.clear()
            self.fenced_cycles = 0
            self.fence_wait_s = 0.0
            self._cycles = 0
            self._deep = False

    def to_dict(self) -> Dict[str, Any]:
        """The /debug/devicez document: per-program measured device
        time + roofline join, the residency ledger, and the sampling
        overhead accounting."""
        with self._lock:
            programs = {k: dict(v) for k, v in self._programs.items()}
            cycles = self._cycles
            fenced = self.fenced_cycles
            fence_s = self.fence_wait_s
            xplane = dict(self._xplane) if self._xplane else None
        costs = manifest_costs()
        progs_out: Dict[str, Any] = {}
        for name, st in sorted(programs.items()):
            d = {"count": st["count"],
                 "device_time_s": round(st["sum_s"], 6),
                 "mean_s": round(st["sum_s"] / max(st["count"], 1), 6),
                 "min_s": round(st["min_s"], 6) if st["count"] else 0.0,
                 "max_s": round(st["max_s"], 6),
                 "last_s": round(st["last_s"], 6),
                 "sources": dict(st["sources"])}
            flops = st["flops_sum"] if st["flops_time_s"] > 0 else None
            secs = (st["flops_time_s"] if flops is not None
                    else st["sum_s"])
            mean_in = (st["in_bytes_sum"] / st["count"]
                       if st["in_bytes_sum"] and st["count"] else None)
            rl = roofline(name, secs, flops=flops,
                          in_bytes=(mean_in * st["count"]
                                    if mean_in else None),
                          costs=costs)
            if rl is not None:
                d["roofline"] = rl
            progs_out[name] = d
        doc = {"armed": True,
               "sample_interval": self.sample_interval,
               "cycles_seen": cycles,
               "fenced_cycles": fenced,
               "fence_wait_s": round(fence_s, 6),
               "programs": progs_out,
               "ledger": self.ledger()}
        if xplane is not None:
            doc["xplane"] = xplane
        return doc

    def summary(self) -> Dict[str, Any]:
        """Compact block for the pipeline doc / bench ``device`` JSON:
        per-program {count, device_time_s, mean_s, achieved/fraction}
        plus resident-byte totals per ledger group."""
        doc = self.to_dict()
        progs = {}
        for name, d in doc["programs"].items():
            p = {"count": d["count"],
                 "device_time_s": d["device_time_s"],
                 "mean_s": d["mean_s"]}
            rl = d.get("roofline")
            if rl:
                for k in ("achieved_tflops", "roofline_fraction",
                          "regime", "flops_source"):
                    if k in rl:
                        p[k] = rl[k]
            progs[name] = p
        groups: Dict[str, int] = {}
        for key, e in doc["ledger"]["entries"].items():
            groups[e["group"]] = groups.get(e["group"], 0) + e["bytes"]
        return {"sample_interval": doc["sample_interval"],
                "fenced_cycles": doc["fenced_cycles"],
                "fence_wait_s": doc["fence_wait_s"],
                "programs": progs,
                "ledger_bytes": doc["ledger"]["total_bytes"],
                "ledger_group_bytes": groups}


def _parse_xplane(path: str) -> Dict[str, float]:  # pragma: no cover
    """Placeholder for environments that DO ship the profiler tooling;
    the CI image does not, so ingest_xplane records the reason
    instead."""
    return {}


# ----------------------------------------------------- module arming state
#
# Read WITHOUT a lock on the hot path (rebinding a Python reference is
# atomic; a racing reader sees old or new), exactly like utils/slo.py's
# _tracker.  arm/disarm serialize via _devstats_lock.

_stats: Optional[DevStats] = None
_devstats_lock = threading.Lock()


def devstats() -> Optional[DevStats]:
    """The armed DevStats, or None (disarmed, the default)."""
    return _stats


def arm_devstats(sample_interval: Optional[int] = None) -> DevStats:
    """Idempotently arm device-side observability (returns the existing
    instance if already armed)."""
    global _stats
    with _devstats_lock:
        if _stats is None:
            _stats = DevStats(sample_interval=sample_interval)
        return _stats


def disarm_devstats() -> None:
    global _stats
    with _devstats_lock:
        _stats = None


def maybe_arm_from_env() -> Optional[DevStats]:
    """Scheduler-construction hook: arms iff KUBETPU_DEVSTATS=1."""
    if os.environ.get(DEVSTATS_ENV, "0") not in ("", "0", "false",
                                                 "False"):
        return arm_devstats()
    return None


# --------------------------------------------------- registration helpers

# ClusterTensors tables whose dim 0 is NOT the node axis: the vocab-side
# metadata rows ([T]/[I]) and the flattened term tensors ([E, .]) — a
# coincidental dim-0 == node-count match must not tag them node-scaled
_NODE_AXIS0_EXCLUDE = ("taint_is_hard", "taint_is_prefer", "image_size",
                       "image_spread", "filter_terms", "score_terms")


def _tag_cluster_dims(entries: Dict[str, List[dict]],
                      axes: Dict[str, int]) -> None:
    """Stamp per-dim role tags ("nodes"/"pods"/"kv"/None) onto a
    registered cluster's leaf entries using the ClusterTensors layout:
    dim 0 of a ``pod_*`` table IS the pod axis and dim 0 of any other
    (non-vocab, non-term) table IS the node axis — authoritative even
    when the node count and pod bucket coincide, which pure value
    matching cannot disambiguate (see project())."""
    n, p, kv = axes.get("nodes"), axes.get("pods"), axes.get("kv")
    for name, leaves in entries.items():
        pod_table = name.startswith("pod_")
        node_dim0 = (not pod_table and name not in _NODE_AXIS0_EXCLUDE)
        for leaf in leaves:
            tags: List[Optional[str]] = []
            for i, d in enumerate(leaf["shape"]):
                if i == 0 and pod_table and d == p:
                    tags.append("pods")
                elif i == 0 and node_dim0 and d == n:
                    tags.append("nodes")
                elif i > 0 and d == kv:
                    tags.append("kv")
                elif i > 0 and d == p:
                    tags.append("pods")
                elif i > 0 and d == n:
                    tags.append("nodes")
                else:
                    tags.append(None)
            leaf["dims"] = tags


def register_cluster(group: str, profile: str, cluster,
                     n_nodes: int, meta: Optional[Dict[str, Any]] = None
                     ) -> None:
    """Register a resident ClusterTensors' per-table bytes under
    (group, profile) — the DeltaTensorizer resident, the speculative
    chain, a prewarm-ladder rung.  No-op disarmed (one attribute
    read); the shape walk runs outside the ledger lock."""
    ds = _stats
    if ds is None:
        return
    named = {name: getattr(cluster, name)
             for name in type(cluster)._fields}
    axes = {"nodes": int(n_nodes),
            "pods": int(cluster.pod_valid.shape[0]),
            "kv": int(cluster.kv.shape[1])}
    entries = table_entries(named)
    _tag_cluster_dims(entries, axes)
    ds.record_ledger(group, profile, entries, axes=axes, meta=meta)
