"""Pallas megakernel backend selection (the impure half).

ops/pallas_kernels.py is a kernel module and must stay pure (kubelint
purity family); everything environment- or state-touching about the
backend choice lives here instead:

  * capability probe: is jax.experimental.pallas importable, and should
    kernels run under ``interpret=True`` (any non-TPU backend, or the
    KUBETPU_PALLAS_INTERPRET override — read ONCE at import so the
    decision is process-stable and cannot silently flip between traces)?
  * support surface: ``unsupported_reason`` is the single authority on
    when ``kernel_backend="pallas"`` may engage; the gang dispatcher
    falls back to the lax path (and records why) on any non-None reason.
  * fallback accounting: a lock-guarded counter by reason, surfaced in
    flight-recorder cycle meta and asserted by tests so a configuration
    that silently always falls back cannot masquerade as a Pallas win.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Optional

import jax
import numpy as np

# read ONCE at import: "1" forces interpret mode even on TPU (debugging),
# "0" forces compiled mode even off-TPU (will fail without a TPU backend —
# intended for lowering tests only), unset = probe the backend.
_INTERPRET_ENV = os.environ.get("KUBETPU_PALLAS_INTERPRET")

_lock = threading.Lock()
_fallbacks: Dict[str, int] = {}   # kubelint: guarded-by(_lock)
# runtime demotion (the self-healing ladder's pallas->lax rung): set by
# the scheduler's deadline-guarded dispatch when a pallas-backed cycle
# errors or blows its deadline; unsupported_reason() then refuses the
# backend process-wide until reset, so every later cycle — including
# other profiles' — serves the lax oracle path instead of re-tripping
# the same fault
_demotion: Optional[str] = None   # kubelint: guarded-by(_lock)


def available() -> bool:
    from ..ops import pallas_kernels
    return pallas_kernels.HAVE_PALLAS


def interpret_mode() -> bool:
    """True when pallas_call must run under interpret=True: every non-TPU
    backend (the Mosaic compiler is TPU-only), unless explicitly
    overridden.  Trace-time static: the returned value is baked into the
    lowered program, which is correct — an interpret-mode lowering and a
    Mosaic lowering are different programs with different AOT keys."""
    if _INTERPRET_ENV is not None:
        return _INTERPRET_ENV != "0"
    return jax.default_backend() != "tpu"


def unsupported_reason(cfg, intra_batch_topology: bool,
                       batch=None) -> Optional[str]:
    """None when the Pallas backend can serve this (cfg, routing, batch)
    with bit-identical placements; otherwise a short reason string.

    The intra-batch-topology condition mirrors the scheduler's needs_topo
    gate: a term-free batch (no pod (anti-)affinity, no spread
    constraints, no controller spread selectors) is exactly the batch
    whose per-round score surface the megakernel reproduces.

    The batch check closes the one content-dependent hole: the kernel
    scores PodTopologySpread via the no-soft-constraints constant path,
    so a batch whose pods carry whenUnsatisfiable=ScheduleAnyway spread
    constraints must fall back even under intra_batch_topology=False
    (where the lax path evaluates the REAL soft constraints statically).
    Serving batches are host-side numpy at dispatch time, so the
    inspection is free — no device sync.  A caller passing device-array
    batches (never the serving path) skips the check and carries the
    term-free contract itself."""
    demoted = demotion()
    if demoted is not None:
        return "demoted:%s" % demoted
    if not available():
        return "pallas-unavailable"
    if intra_batch_topology:
        return "intra-batch-topology"
    from ..ops import pallas_kernels
    for name, _ in cfg.scores:
        if name not in pallas_kernels.SUPPORTED_SCORES:
            return "score:%s" % name
    if batch is not None:
        sv = getattr(getattr(batch, "spread_soft", None), "valid", None)
        if isinstance(sv, np.ndarray) and bool(sv.any()):
            return "soft-spread-constraints"
    return None


def demote(reason: str) -> None:
    """Demote the pallas backend process-wide with a recorded reason
    (scheduler dispatch-recovery hook); idempotent, first reason wins."""
    global _demotion
    with _lock:
        if _demotion is None:
            _demotion = reason


def demotion() -> Optional[str]:
    with _lock:
        return _demotion


def reset_demotion() -> None:
    global _demotion
    with _lock:
        _demotion = None


def note_fallback(reason: str) -> None:
    with _lock:
        _fallbacks[reason] = _fallbacks.get(reason, 0) + 1


def fallback_counts() -> Dict[str, int]:
    with _lock:
        return dict(_fallbacks)


def reset_fallbacks() -> None:
    with _lock:
        _fallbacks.clear()


def effective_backend(cfg, intra_batch_topology: bool,
                      requested: Optional[str], batch=None) -> str:
    """The backend schedule_gang will actually trace for this call."""
    if requested != "pallas":
        return "lax"
    return ("pallas"
            if unsupported_reason(cfg, intra_batch_topology, batch) is None
            else "lax")
