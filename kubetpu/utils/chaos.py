"""Chaos harness: a seeded, deterministic fault-injection registry.

The reference scheduler survives etcd hiccups, API-server disconnects and
crashed binders by design (informer resync, backoff queues, idempotent
commits — SURVEY L0-L4).  The TPU-native reproduction grew three state
surfaces the reference never had — device-resident cluster tensors
(state/delta.py), serialized AOT executables (utils/aot.py) and a Pallas
kernel backend (ops/pallas_kernels.py) — each of which can silently
corrupt, hang or diverge.  This module makes those faults first-class:
every failure mode the recovery machinery claims to survive has a NAMED
injection point here, armed deterministically so tests/test_chaos.py can
assert the recovery invariants (serving thread alive, no lost pods, no
double binds, mirror/device bit-consistency) scenario by scenario.

Injection points threaded through the stack:

  ``dispatch``   scheduler._dispatch_group — raise a runtime error the
                 way a dying device does, or inject a stall (the
                 deadline-guarded dispatch's two failure classes)
  ``delta``      state/delta.DeltaTensorizer._apply — drop a ClusterDelta
                 application or corrupt the device residents (what the
                 anti-entropy verifier exists to catch)
  ``aot-load``   utils/aot.AotStore.load — truncate the artifact blob
                 (pickle fails; the seam must degrade to the trace path)
  ``bind``       plugins/intree.DefaultBinder.bind — transient bind
                 transport error (the binder retry ladder's test feed)
  ``extender``   extender.HTTPExtender._send — transient webhook error
  ``rest``       client/rest.RestClusterStore._req — transient API-server
                 transport error
  ``watch``      client/rest.RestClusterStore._watch_loop — watch
                 disconnect (drives the capped-backoff reconnect)
  ``journal``    utils/journal.CycleJournal.append — fail the record
                 write ("error": degrade to a counted drop) or land a
                 damaged frame on disk ("truncate"/"corrupt": the
                 reader-side crc skips it with a per-record reason)

Arming: ``KUBETPU_CHAOS=<spec>`` at import of the consumer (read by
``maybe_arm_from_env``), or programmatically (``arm(registry)``) for
tests.  Spec grammar — comma-separated clauses::

    seed=<int>                        registry seed (default 0)
    <point>:<mode>[:k=v]...           arm one injection point

with per-point keys ``n=<max fires>`` (default unlimited), ``p=<prob>``
(default 1.0, drawn from a per-point PRNG seeded by (seed, point) so
decisions are deterministic and independent of arming order) and
``delay=<seconds>`` (stall length, default 0.05).  Example::

    KUBETPU_CHAOS="seed=7,dispatch:error:n=1,delta:corrupt:p=0.25"

Disarmed (the default) every site helper is ONE module-attribute read —
no lock, no allocation, no branch beyond the None check — mirroring the
flight recorder's arming contract (utils/trace.py); the poison test in
tests/test_chaos.py enforces it the same way trace's does.
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Dict, Optional, Tuple

ENV = "KUBETPU_CHAOS"

# point -> modes it supports (parse-time validation: a typo'd clause must
# fail loudly at arm time, not silently never fire)
POINTS: Dict[str, Tuple[str, ...]] = {
    "dispatch": ("error", "stall"),
    "delta": ("drop", "corrupt"),
    "aot-load": ("corrupt",),
    "bind": ("error",),
    "extender": ("error",),
    "rest": ("error",),
    "watch": ("error",),
    # utils/journal.CycleJournal.append — "error" fails the write (the
    # record degrades to a counted drop), "truncate"/"corrupt" land a
    # damaged frame on disk (the reader-side crc skips it per record)
    "journal": ("error", "truncate", "corrupt"),
}

DEFAULT_STALL_S = 0.05


class ChaosFault(RuntimeError):
    """The injected failure.  Subclasses RuntimeError so sites that catch
    their transport's error family (XlaRuntimeError and urllib errors
    both are RuntimeError/OSError-adjacent; every seam here catches at
    least Exception) treat it like the real thing."""


class _Rule:
    """One armed injection point.  Mutable fire counters are guarded by
    the registry lock; the rule itself is write-once at arm time."""

    __slots__ = ("point", "mode", "n", "prob", "delay", "rng", "fired")

    def __init__(self, point: str, mode: str, n: Optional[int],
                 prob: float, delay: float, seed: int):
        self.point = point
        self.mode = mode
        self.n = n
        self.prob = prob
        self.delay = delay
        # per-point stream seeded by (seed, point): deterministic and
        # independent of arming order / other points' draw counts
        self.rng = random.Random("%d:%s" % (seed, point))
        self.fired = 0


class ChaosRegistry:
    """Seeded rule set + fire accounting.

    ``decide()`` is the single choice point: it draws, counts and
    records the incident (a flight-recorder instant on the open cycle,
    when armed) under the registry lock, and returns ``(mode, delay)``
    for the SITE to act on outside the lock — sleeping or raising under
    the lock would trip kubelint's blocking-under-lock family and stall
    unrelated threads' decisions."""

    def __init__(self, seed: int = 0):
        self.seed = seed
        self._lock = threading.Lock()
        self._rules: Dict[str, _Rule] = {}   # kubelint: guarded-by(_lock)
        self._fired: Dict[str, int] = {}     # kubelint: guarded-by(_lock)

    def arm_point(self, point: str, mode: str, n: Optional[int] = None,
                  prob: float = 1.0,
                  delay: float = DEFAULT_STALL_S) -> "ChaosRegistry":
        modes = POINTS.get(point)
        if modes is None:
            raise ValueError("unknown chaos point %r (known: %s)"
                             % (point, ", ".join(sorted(POINTS))))
        if mode not in modes:
            raise ValueError("chaos point %r has no mode %r (supported: %s)"
                             % (point, mode, ", ".join(modes)))
        with self._lock:
            self._rules[point] = _Rule(point, mode, n, prob, delay,
                                       self.seed)
        return self

    def decide(self, point: str) -> Optional[Tuple[str, float]]:
        """(mode, delay) when the point fires this call, else None."""
        with self._lock:
            rule = self._rules.get(point)
            if rule is None:
                return None
            if rule.n is not None and rule.fired >= rule.n:
                return None
            if rule.prob < 1.0 and rule.rng.random() >= rule.prob:
                return None
            rule.fired += 1
            self._fired[point] = self._fired.get(point, 0) + 1
            mode, delay = rule.mode, rule.delay
        # incident breadcrumb OUTSIDE the lock: the trace helper takes
        # the cycle record's own lock
        from .trace import note_instant
        note_instant("chaos", point=point, mode=mode)
        return mode, delay

    def counts(self) -> Dict[str, int]:
        """Monotonic per-point fire counts (the
        scheduler_faults_injected_total feed)."""
        with self._lock:
            return dict(self._fired)

    def total_fired(self) -> int:
        with self._lock:
            return sum(self._fired.values())


def parse_spec(spec: str) -> ChaosRegistry:
    """Build a registry from the KUBETPU_CHAOS grammar (docstring above).
    Raises ValueError on any malformed clause — a typo must not silently
    disarm the harness."""
    seed = 0
    clauses = []
    for raw in spec.split(","):
        raw = raw.strip()
        if not raw:
            continue
        if raw.startswith("seed="):
            seed = int(raw[len("seed="):])
            continue
        clauses.append(raw)
    reg = ChaosRegistry(seed=seed)
    for raw in clauses:
        parts = raw.split(":")
        if len(parts) < 2:
            raise ValueError("chaos clause %r: want point:mode[:k=v...]"
                             % raw)
        point, mode = parts[0], parts[1]
        kw: Dict[str, float] = {}
        for kv in parts[2:]:
            k, _, v = kv.partition("=")
            if k == "n":
                kw["n"] = int(v)
            elif k == "p":
                kw["prob"] = float(v)
            elif k == "delay":
                kw["delay"] = float(v)
            else:
                raise ValueError("chaos clause %r: unknown key %r"
                                 % (raw, k))
        reg.arm_point(point, mode, **kw)
    return reg


# ---------------------------------------------------------------- arming
#
# Same contract as trace.py's recorder and aot.py's runtime: _active is
# read WITHOUT a lock on the hot path (rebinding a reference is atomic; a
# racing reader sees old or new), arm/disarm serialize through
# _active_lock.

_active: Optional[ChaosRegistry] = None
_active_lock = threading.Lock()


def active() -> Optional[ChaosRegistry]:
    return _active


def arm(registry: ChaosRegistry) -> ChaosRegistry:
    global _active
    with _active_lock:
        _active = registry
    return registry


def disarm() -> None:
    global _active
    with _active_lock:
        _active = None


def maybe_arm_from_env() -> Optional[ChaosRegistry]:
    """Scheduler-construction hook: arms from KUBETPU_CHAOS when set.
    Parse errors RAISE — an operator who armed chaos and typo'd the spec
    must find out now, not after the run proved nothing."""
    spec = os.environ.get(ENV, "")
    if not spec:
        return None
    if _active is not None:
        return _active
    return arm(parse_spec(spec))


# ------------------------------------------------------------ site helpers


def action(point: str) -> Optional[str]:
    """The armed mode for ``point`` if it fires this call, else None.
    For sites that implement the fault themselves (delta drop/corrupt,
    aot blob truncation).  Disarmed: one attribute read."""
    reg = _active
    if reg is None:
        return None
    decision = reg.decide(point)
    return decision[0] if decision is not None else None


def raise_or_stall(point: str) -> None:
    """Raise ChaosFault (mode "error") or sleep (mode "stall") when the
    point fires; no-op otherwise.  Disarmed: one attribute read."""
    reg = _active
    if reg is None:
        return
    decision = reg.decide(point)
    if decision is None:
        return
    mode, delay = decision
    if mode == "stall":
        time.sleep(delay)
        return
    raise ChaosFault("injected %s fault at %r" % (mode, point))
