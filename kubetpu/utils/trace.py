"""Step tracing, the cycle FLIGHT RECORDER, and Perfetto trace export.

reference: vendor/k8s.io/utils/trace (utiltrace.Trace) as used by the
scheduling cycle (core/generic_scheduler.go:147-202 — steps "Basic checks
done", "Snapshotting scheduler cache and node infos done", "Computing
predicates done", "Prioritizing done", logged when the cycle exceeds
100 ms) — SURVEY.md §5 keeps the same span structure and slow-cycle log.

On top of the reference's threshold log, this module is the structured
observability layer: every ``Trace`` carries a span id, parent linkage and
thread tag, and — when the flight recorder is ARMED — the full span tree
of each scheduling cycle (prepare/tensorize steps, dispatch,
packed-readback with device-wait attribution, commit, preemption wave,
per-pod binds, recompile events fed by the sanitize watchdog, and the
queue depths at cycle start) lands in a lock-guarded ring buffer of the
last N cycles (``KUBETPU_FLIGHT_N``, default 64).  The ring serializes to
the Chrome ``traceEvents`` JSON format (one pid per component, one tid
per thread, ``ph: "X"`` spans) loadable in Perfetto/chrome://tracing,
alongside the existing ``jax.profiler`` XPlane capture.

Bounded-memory contract: the recorder holds AT MOST ``capacity`` cycle
records (older ones are dropped and counted — see ``dropped()`` and the
``scheduler_flight_recorder_dropped_total`` metric) and at most
``KUBETPU_FLIGHT_SPANS`` (default 512) spans AND instant events per
cycle (excess is dropped per record and counted in ``span_drops`` /
``event_drops``).  DISARMED (the
default) the recorder is a strict no-op: ``Trace`` takes no lock,
allocates no record, and the serving loop skips the queue-depth read —
the hot path is byte-identical to the pre-recorder behavior.
"""

from __future__ import annotations

import collections
import contextlib
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

LOG = logging.getLogger("kubetpu.trace")

SLOW_CYCLE_THRESHOLD = 0.1  # 100 ms (generic_scheduler.go:148 LogIfLong)

# Monotonic wall clock: perf_counter deltas anchored to the process's
# wall epoch, captured ONCE at import.  Every span/duration stamp in
# this module (and the scheduler's dispatch-deadline / device-wait
# domain) reads wallclock() instead of time.time(): an NTP step moves
# time.time() but not perf_counter, so a step mid-cycle used to corrupt
# device_wait_s and every span length (negative durations, bogus
# deadline trips).  The epoch anchor keeps the values wall-meaningful —
# Perfetto `ts` microseconds still line up with real time — while
# durations-by-subtraction stay strictly monotonic.
_WALL_EPOCH = time.time() - time.perf_counter()


def wallclock() -> float:
    """time.time()-compatible timestamp that can never run backwards
    (see _WALL_EPOCH).  Use for any pair of stamps whose DIFFERENCE is
    a duration."""
    return _WALL_EPOCH + time.perf_counter()

FLIGHT_ENV = "KUBETPU_FLIGHT"
FLIGHT_N_ENV = "KUBETPU_FLIGHT_N"
FLIGHT_SPANS_ENV = "KUBETPU_FLIGHT_SPANS"
DEFAULT_FLIGHT_N = 64
DEFAULT_FLIGHT_SPANS = 512

# SURVEY §5: keep jax.profiler traces alongside the host-side step spans.
# When a capture is active (capture_device_trace below, or
# KUBETPU_PROFILE_DIR at import), every Trace phase also opens a
# jax.profiler.TraceAnnotation so device ops group under the cycle phase
# names in the TensorBoard/XProf timeline.
_PROFILE_ACTIVE = False


@contextlib.contextmanager
def capture_device_trace(log_dir: str):
    """Capture a jax.profiler trace (XPlane/TensorBoard format) for the
    enclosed serving activity — the TPU analog of the reference's pprof
    endpoints (DebuggingConfiguration.EnableProfiling, SURVEY §5).  Host
    Trace phases appear as TraceAnnotations inside the capture."""
    global _PROFILE_ACTIVE
    import jax
    os.makedirs(log_dir, exist_ok=True)
    jax.profiler.start_trace(log_dir)
    _PROFILE_ACTIVE = True
    try:
        yield log_dir
    finally:
        _PROFILE_ACTIVE = False
        jax.profiler.stop_trace()
        # devstats xplane hook: when device-side observability is armed,
        # fold the capture into per-program device-time records (or
        # record WHY the tooling can't — never silently); disarmed this
        # is one attribute read
        from . import devstats as _devstats
        ds = _devstats.devstats()
        if ds is not None:
            ds.ingest_xplane(log_dir)


# --------------------------------------------------------------------- spans


class FlightSpan:
    """One recorded span: a node of a cycle's span tree."""

    __slots__ = ("span_id", "parent_id", "name", "thread", "t0", "t1",
                 "args")

    def __init__(self, span_id: int, parent_id: int, name: str,
                 thread: str, t0: float, t1: Optional[float] = None,
                 args: Optional[Dict[str, Any]] = None):
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.thread = thread
        self.t0 = t0
        self.t1 = t1
        self.args = args if args is not None else {}

    def to_dict(self) -> Dict[str, Any]:
        return {"id": self.span_id, "parent": self.parent_id,
                "name": self.name, "thread": self.thread,
                "t0": round(self.t0, 6),
                "t1": round(self.t1 if self.t1 is not None else self.t0, 6),
                "args": dict(self.args)}


class _NullSpan:
    """Reusable no-op context manager: the disarmed hot path allocates
    nothing and takes no lock."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


# thread-local stack of (CycleRecord, FlightSpan) for the spans currently
# OPEN on this thread: parents nested spans and routes recompile events
# (note_compile_event) to the right cycle.  Thread-local, so no lock.
_tls = threading.local()


def _span_stack() -> list:
    st = getattr(_tls, "spans", None)
    if st is None:
        st = []
        _tls.spans = st
    return st


class CycleRecord:
    """The span tree of ONE scheduling cycle.  Spans may be appended from
    multiple threads (serving loop + binder pool), so the lists are
    lock-guarded; the per-record span cap keeps a 4k-pod commit loop from
    ballooning the record (drops are counted, never silent)."""

    def __init__(self, seq: int, label: str,
                 queue_depths: Optional[Dict[str, int]] = None,
                 fields: Optional[Dict[str, Any]] = None,
                 max_spans: int = DEFAULT_FLIGHT_SPANS):
        self.seq = seq
        self.label = label
        self.t0 = wallclock()
        self.t1: Optional[float] = None
        self.queue_depths = dict(queue_depths or {})
        self.meta: Dict[str, Any] = dict(fields or {})
        self.max_spans = max_spans
        self._lock = threading.Lock()
        self._spans: List[FlightSpan] = []   # kubelint: guarded-by(_lock)
        self._events: List[Dict[str, Any]] = []  # kubelint: guarded-by(_lock)
        self._next_id = 1                    # kubelint: guarded-by(_lock)
        self.span_drops = 0                  # kubelint: guarded-by(_lock)
        self.event_drops = 0                 # kubelint: guarded-by(_lock)

    # -- recording ----------------------------------------------------------

    def begin_span(self, name: str, parent_id: int = 0,
                   t0: Optional[float] = None,
                   **args) -> Optional[FlightSpan]:
        """Open a span; returns None when the per-record cap is hit (the
        drop is counted)."""
        thread = threading.current_thread().name
        with self._lock:
            if len(self._spans) >= self.max_spans:
                self.span_drops += 1
                return None
            span = FlightSpan(self._next_id, parent_id, name, thread,
                              t0 if t0 is not None else wallclock(),
                              args=args or {})
            self._next_id += 1
            self._spans.append(span)
        return span

    @staticmethod
    def end_span(span: Optional[FlightSpan],
                 t1: Optional[float] = None) -> None:
        if span is not None:
            span.t1 = t1 if t1 is not None else wallclock()

    def record_span(self, name: str, t0: float, t1: float,
                    parent_id: int = 0, **args) -> Optional[FlightSpan]:
        """Record an already-finished span (e.g. a Trace.step interval)."""
        span = self.begin_span(name, parent_id=parent_id, t0=t0, **args)
        if span is not None:
            span.t1 = t1
        return span

    def event(self, name: str, parent_id: int = 0, **args) -> None:
        """Record an instant event (ph "i" in the Chrome export) — used
        for recompiles fed by the sanitize watchdog.  Capped like spans
        (a recompile storm must not balloon the record); drops count."""
        ev = {"name": name, "ts": wallclock(), "parent": parent_id,
              "thread": threading.current_thread().name,
              "args": dict(args)}
        with self._lock:
            if len(self._events) >= self.max_spans:
                self.event_drops += 1
                return
            self._events.append(ev)

    @contextlib.contextmanager
    def span(self, name: str, parent_id: Optional[int] = None, **args):
        """Scoped span: pushes itself on the thread's open-span stack so
        nested spans (and recompile events) parent under it.  Yields the
        FlightSpan (or None past the span cap) so callers can attach args
        — e.g. the readback's device_wait_s — before exit."""
        stack = _span_stack()
        if parent_id is None:
            parent_id = (stack[-1][1].span_id
                         if stack and stack[-1][0] is self
                         and stack[-1][1] is not None else 0)
        sp = self.begin_span(name, parent_id=parent_id, **args)
        stack.append((self, sp))
        try:
            yield sp
        finally:
            stack.pop()
            self.end_span(sp)

    # -- introspection ------------------------------------------------------

    def spans(self) -> List[FlightSpan]:
        with self._lock:
            return list(self._spans)

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def to_dict(self) -> Dict[str, Any]:
        with self._lock:
            spans = [s.to_dict() for s in self._spans]
            events = [dict(e) for e in self._events]
            drops = self.span_drops
            ev_drops = self.event_drops
        return {"seq": self.seq, "label": self.label,
                "t0": round(self.t0, 6),
                "t1": round(self.t1 if self.t1 is not None else self.t0, 6),
                "queue_depths": dict(self.queue_depths),
                "meta": dict(self.meta),
                "span_drops": drops, "event_drops": ev_drops,
                "spans": spans, "events": events}


class FlightRecorder:
    """Lock-guarded ring buffer of the last N CycleRecords.

    Bounded-memory contract: at most ``capacity`` records x
    ``max_spans_per_cycle`` spans each are retained; overflow in either
    dimension drops (oldest cycle / newest span) and counts.  Reads
    (``cycles``/``to_dict``/``to_chrome_trace``) snapshot under the lock
    and serialize outside it."""

    def __init__(self, capacity: Optional[int] = None,
                 max_spans_per_cycle: Optional[int] = None):
        self.capacity = capacity or int(
            os.environ.get(FLIGHT_N_ENV, str(DEFAULT_FLIGHT_N)))
        self.max_spans_per_cycle = max_spans_per_cycle or int(
            os.environ.get(FLIGHT_SPANS_ENV, str(DEFAULT_FLIGHT_SPANS)))
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque()  # kubelint: guarded-by(_lock)
        self._dropped = 0    # kubelint: guarded-by(_lock)
        self._seq = 0        # kubelint: guarded-by(_lock)

    def begin_cycle(self, label: str,
                    queue_depths: Optional[Dict[str, int]] = None,
                    fields: Optional[Dict[str, Any]] = None) -> CycleRecord:
        with self._lock:
            self._seq += 1
            seq = self._seq
        return CycleRecord(seq, label, queue_depths=queue_depths,
                           fields=fields,
                           max_spans=self.max_spans_per_cycle)

    def commit_cycle(self, rec: CycleRecord) -> None:
        """Push a finished record into the ring, dropping (and counting)
        the oldest when full."""
        if rec.t1 is None:
            rec.t1 = wallclock()
        with self._lock:
            self._ring.append(rec)
            while len(self._ring) > self.capacity:
                self._ring.popleft()
                self._dropped += 1

    def cycles(self) -> List[CycleRecord]:
        with self._lock:
            return list(self._ring)

    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._dropped = 0

    # -- serialization ------------------------------------------------------

    def to_dict(self) -> Dict[str, Any]:
        """The /debug/flightz document."""
        recs = self.cycles()
        return {"armed": True, "capacity": self.capacity,
                "max_spans_per_cycle": self.max_spans_per_cycle,
                "dropped": self.dropped(),
                "cycles": [r.to_dict() for r in recs]}

    def to_pipeline_doc(self, workload: str = "") -> Dict[str, Any]:
        """The PIPELINE_TRACE.json document: a flat stage/cycle span list
        (the shape tools/traceview.py and the committed artifact consume).
        ``span_total`` equals the number of ``ph: "X"`` events in
        ``to_chrome_trace()`` for the same ring content — the two exports
        describe the same spans.  Still-OPEN spans (e.g. an async bind in
        flight on a committed record) are excluded from BOTH exports —
        they would serialize with a bogus zero duration; the full
        ``to_dict()``/flightz dump still shows them."""
        recs = self.cycles()
        t_base = recs[0].t0 if recs else 0.0
        spans = []
        for rec in recs:
            for s in rec.spans():
                if s.t1 is None:
                    continue
                spans.append({
                    "stage": s.name, "cycle": rec.seq,
                    "thread": s.thread,
                    "span_id": s.span_id, "parent_id": s.parent_id,
                    "start_s": round(s.t0 - t_base, 4),
                    "end_s": round(s.t1 - t_base, 4),
                    **({"args": dict(s.args)} if s.args else {})})
        doc = {"workload": workload,
               "cycles": len(recs),
               "dropped": self.dropped(),
               "span_total": len(spans),
               "device_wait_s": round(sum(
                   s.get("args", {}).get("device_wait_s", 0.0)
                   for s in spans), 3),
               # per-cycle meta (pod_bucket, delta_rows, aot stats):
               # tools/kubeaot --prune reads the bucket-hit set from here
               "cycle_meta": [{"seq": r.seq, "label": r.label,
                               "meta": dict(r.meta)} for r in recs],
               "spans": spans}
        if recs:
            doc["total_s"] = round(max((r.t1 or r.t0) for r in recs)
                                   - t_base, 3)
        # per-pod latency meta (utils/slo.py): when the SLO tracker is
        # armed alongside the recorder, the pipeline doc carries the
        # per-stage quantiles + shares so traceview can print the "SLO:"
        # digest from the committed artifact alone
        from . import slo as _slo
        trk = _slo.tracker()
        if trk is not None:
            doc["slo"] = {"stages": trk.stage_quantiles(),
                          "shares": trk.shares()}
        # durable-journal digest (utils/journal.py): when the journal is
        # armed alongside the recorder, the pipeline doc carries its
        # status — records, bytes, drops, window span and the linkage
        # hit-rate into THIS ring's live cycle seqs — so traceview can
        # print the "journal:" digest from the committed artifact alone
        from . import journal as _journal
        jr = _journal.journal()
        if jr is not None:
            doc["journal"] = jr.status(
                flight_seqs={r.seq for r in recs})
        # device-side observability digest (utils/devstats.py): when
        # armed alongside the recorder, the pipeline doc carries the
        # measured per-program device times + roofline join and the
        # residency-ledger totals so traceview can print the "device:"
        # digest from the committed artifact alone
        from . import devstats as _devstats
        ds = _devstats.devstats()
        if ds is not None:
            doc["device"] = ds.summary()
        # sustained-load digest (utils/telemetry.py): when the windowed
        # telemetry ring is armed alongside the recorder, the pipeline
        # doc carries its digest — window count/cadence, steady-state
        # span + p99, demotions, worst window with flight_seq link — so
        # traceview can print the "load:" digest from the committed
        # artifact alone
        from . import telemetry as _telemetry
        tel = _telemetry.ring()
        if tel is not None:
            doc["load"] = tel.digest()
        return doc

    @staticmethod
    def _component_of(thread: str) -> str:
        if thread.startswith("binder"):
            return "binder"
        if "preempt" in thread:
            return "preemption"
        return "scheduler"

    def to_chrome_trace(self) -> Dict[str, Any]:
        """Chrome trace-event JSON (Perfetto/chrome://tracing loadable):
        one pid per component (scheduler/binder/preemption), one tid per
        thread, ``ph: "X"`` complete spans with microsecond timestamps,
        ``ph: "C"`` queue-depth counters at each cycle start, ``ph: "i"``
        instants for recompile events, and ``ph: "M"`` metadata naming
        processes and threads.  The number of "X" events equals
        ``to_pipeline_doc()["span_total"]``."""
        recs = self.cycles()
        events: List[Dict[str, Any]] = []
        pids: Dict[str, int] = {}
        tids: Dict[Tuple[int, str], int] = {}

        def pid_of(component: str) -> int:
            if component not in pids:
                pid = len(pids) + 1
                pids[component] = pid
                events.append({"ph": "M", "name": "process_name",
                               "pid": pid, "tid": 0,
                               "args": {"name": f"kubetpu-{component}"}})
            return pids[component]

        def tid_of(pid: int, thread: str) -> int:
            key = (pid, thread)
            if key not in tids:
                tid = sum(1 for (p, _t) in tids if p == pid) + 1
                tids[key] = tid
                events.append({"ph": "M", "name": "thread_name",
                               "pid": pid, "tid": tid,
                               "args": {"name": thread}})
            return tids[key]

        def us(t: float) -> int:
            return int(t * 1e6)

        for rec in recs:
            sched_pid = pid_of("scheduler")
            if rec.queue_depths:
                events.append({"ph": "C", "name": "queue_depth",
                               "pid": sched_pid, "tid": 0,
                               "ts": us(rec.t0),
                               "args": {k: int(v) for k, v
                                        in rec.queue_depths.items()}})
            for s in rec.spans():
                if s.t1 is None:
                    continue   # open span: excluded like to_pipeline_doc
                comp = self._component_of(s.thread)
                pid = pid_of(comp)
                tid = tid_of(pid, s.thread)
                args = {"cycle": rec.seq, "span_id": s.span_id,
                        "parent_id": s.parent_id}
                args.update(s.args)
                events.append({"ph": "X", "name": s.name, "cat": comp,
                               "pid": pid, "tid": tid,
                               "ts": us(s.t0),
                               "dur": max(us(s.t1) - us(s.t0), 0),
                               "args": args})
            for ev in rec.events():
                comp = self._component_of(ev["thread"])
                pid = pid_of(comp)
                tid = tid_of(pid, ev["thread"])
                events.append({"ph": "i", "name": ev["name"], "cat": comp,
                               "pid": pid, "tid": tid, "s": "t",
                               "ts": us(ev["ts"]),
                               "args": dict(ev["args"])})
        return {"traceEvents": events, "displayTimeUnit": "ms"}


# module arming state.  The reference is read WITHOUT a lock on the hot
# path (Trace.__init__): rebinding a Python reference is atomic, a racing
# reader sees either the old or the new recorder, and the disarmed fast
# path must not pay a lock acquisition per cycle.  arm/disarm themselves
# serialize through _flight_lock.
_flight: Optional[FlightRecorder] = None
_flight_lock = threading.Lock()


def flight_recorder() -> Optional[FlightRecorder]:
    """The armed recorder, or None (disarmed, the default)."""
    return _flight


def arm_flight_recorder(capacity: Optional[int] = None,
                        max_spans_per_cycle: Optional[int] = None
                        ) -> FlightRecorder:
    """Idempotently arm the flight recorder (returns the existing one if
    already armed)."""
    global _flight
    with _flight_lock:
        if _flight is None:
            _flight = FlightRecorder(
                capacity=capacity,
                max_spans_per_cycle=max_spans_per_cycle)
        return _flight


def disarm_flight_recorder() -> None:
    global _flight
    with _flight_lock:
        _flight = None


def maybe_arm_from_env() -> Optional[FlightRecorder]:
    """kubetpu/__init__ hook: arms the recorder iff KUBETPU_FLIGHT=1.
    Importing this module never imports jax."""
    if os.environ.get(FLIGHT_ENV, "0") not in ("", "0", "false", "False"):
        return arm_flight_recorder()
    return None


@contextlib.contextmanager
def flight_span(name: str, **args):
    """Span attached to the CURRENT thread's innermost open cycle span
    (used by code — e.g. the preemption wave's what-if readback — that
    has no handle on the cycle's Trace).  No-op when nothing is open."""
    stack = _span_stack()
    if not stack:
        yield None
        return
    rec, parent = stack[-1]
    with rec.span(name, parent_id=parent.span_id if parent else 0,
                  **args) as sp:
        yield sp


def note_instant(name: str, **args) -> None:
    """Record an instant event on the cycle currently open on this
    thread — the hook code with no handle on the cycle's Trace uses
    (sanitize watchdog recompiles, chaos-harness fault injections,
    backend demotions).  Disarmed or outside a cycle this is a no-op."""
    if _flight is None:
        return
    stack = _span_stack()
    if not stack:
        return
    rec, parent = stack[-1]
    rec.event(name, parent_id=parent.span_id if parent else 0, **args)


def note_compile_event(program: str, shapes: str) -> None:
    """Sanitize-watchdog hook: record an XLA (re)compile as an instant
    event on the cycle currently open on this thread (compiles triggered
    by a cycle's dispatch happen under its dispatch span).  Disarmed or
    outside a cycle this is a no-op."""
    note_instant("xla-compile", program=program, shapes=shapes[:512])


# --------------------------------------------------------------------- Trace


class Trace:
    """The per-cycle step trace (reference: utiltrace.Trace) — now also
    the flight recorder's cycle handle: when the recorder is armed at
    construction, the Trace owns a CycleRecord, carries a span id, parent
    linkage and thread tag, and every ``step()`` interval becomes a child
    span.  Disarmed, nothing beyond the original step list is touched."""

    def __init__(self, name: str, parent: Optional["Trace"] = None,
                 queue_depths: Optional[Dict[str, int]] = None, **fields):
        self.name = name
        self.fields = fields
        self.start = wallclock()
        self.steps: List[Tuple[float, str]] = []
        self.thread = threading.current_thread().name
        self._ann = None
        self._closed = False
        # flight recorder linkage (no lock taken when disarmed: _flight is
        # read once; None short-circuits everything below)
        fr = _flight
        self._fr = fr
        self.rec: Optional[CycleRecord] = None
        self._root: Optional[FlightSpan] = None
        self.span_id = 0
        self.parent_id = parent.span_id if parent is not None else 0
        if fr is not None:
            self.rec = fr.begin_cycle(name, queue_depths=queue_depths,
                                      fields=dict(fields))
            self._root = self.rec.begin_span(name,
                                             parent_id=self.parent_id)
            if self._root is not None:
                self.span_id = self._root.span_id
        self._last_mark = self.start
        if _PROFILE_ACTIVE:
            self._open_annotation("begin")

    def _close_annotation(self) -> None:
        if self._ann is not None:
            self._ann.__exit__(None, None, None)
            self._ann = None

    def _open_annotation(self, label: str) -> None:
        import jax
        self._close_annotation()
        if _PROFILE_ACTIVE:
            self._ann = jax.profiler.TraceAnnotation(f"{self.name}:{label}")
            self._ann.__enter__()

    def step(self, msg: str) -> None:
        now = wallclock()
        self.steps.append((now, msg))
        if self.rec is not None:
            # the interval since the previous mark becomes a child span
            self.rec.record_span(msg, self._last_mark, now,
                                 parent_id=self.span_id)
        self._last_mark = now
        if self._ann is not None or _PROFILE_ACTIVE:
            self._open_annotation(msg)

    def stage(self, name: str, **args):
        """Scoped child span for a cycle stage (dispatch, commit,
        preemption wave...).  Returns a no-op context when disarmed —
        zero allocation, zero locks."""
        if self.rec is None:
            return _NULL_SPAN
        return self.rec.span(name, parent_id=self.span_id, **args)

    def finish(self, **meta) -> None:
        """Commit this cycle's record to the recorder's ring (idempotent;
        no-op when disarmed).  meta lands on the record (e.g.
        discarded=True for a pipelined cycle whose dispatch was thrown
        away)."""
        rec, fr = self.rec, self._fr
        self.rec = None
        if rec is None or fr is None:
            return
        if meta:
            rec.meta.update(meta)
        CycleRecord.end_span(self._root)
        rec.t1 = wallclock()
        fr.commit_cycle(rec)

    def __del__(self):
        # last-resort close so an early-return cycle can never leak an
        # entered TraceAnnotation into the rest of the capture
        self._close_annotation()
        # ...and a cycle that unwound on an exception still commits its
        # record: the crashing cycle is exactly the one the flight
        # recorder exists to capture (CPython refcounting runs this as
        # the serving loop's except-and-continue drops the cycle state)
        try:
            if self.rec is not None:
                self.finish(aborted=True)
        except Exception:
            pass

    def total(self) -> float:
        return wallclock() - self.start

    def log_if_long(self, threshold: float = SLOW_CYCLE_THRESHOLD) -> Optional[str]:
        self._close_annotation()
        total = self.total()
        if total < threshold:
            return None
        fields = ",".join(f"{k}:{v}" for k, v in self.fields.items())
        lines = [f'Trace "{self.name}" ({fields}) (total {total * 1000:.0f}ms):']
        last = self.start
        for ts, msg in self.steps:
            lines.append(f"  ---\"{msg}\" {(ts - last) * 1000:.0f}ms")
            last = ts
        out = "\n".join(lines)
        LOG.info(out)
        return out

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.log_if_long()
        self.finish()
        return False
