"""Step tracing with threshold logging.

reference: vendor/k8s.io/utils/trace (utiltrace.Trace) as used by the
scheduling cycle (core/generic_scheduler.go:147-202 — steps "Basic checks
done", "Snapshotting scheduler cache and node infos done", "Computing
predicates done", "Prioritizing done", logged when the cycle exceeds
100 ms) — SURVEY.md §5 keeps the same span structure and slow-cycle log.
"""

from __future__ import annotations

import logging
import time
from typing import List, Optional, Tuple

LOG = logging.getLogger("kubetpu.trace")

SLOW_CYCLE_THRESHOLD = 0.1  # 100 ms (generic_scheduler.go:148 LogIfLong)


class Trace:
    def __init__(self, name: str, **fields):
        self.name = name
        self.fields = fields
        self.start = time.time()
        self.steps: List[Tuple[float, str]] = []

    def step(self, msg: str) -> None:
        self.steps.append((time.time(), msg))

    def total(self) -> float:
        return time.time() - self.start

    def log_if_long(self, threshold: float = SLOW_CYCLE_THRESHOLD) -> Optional[str]:
        total = self.total()
        if total < threshold:
            return None
        fields = ",".join(f"{k}:{v}" for k, v in self.fields.items())
        lines = [f'Trace "{self.name}" ({fields}) (total {total * 1000:.0f}ms):']
        last = self.start
        for ts, msg in self.steps:
            lines.append(f"  ---\"{msg}\" {(ts - last) * 1000:.0f}ms")
            last = ts
        out = "\n".join(lines)
        LOG.info(out)
        return out

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.log_if_long()
        return False
