"""Step tracing with threshold logging.

reference: vendor/k8s.io/utils/trace (utiltrace.Trace) as used by the
scheduling cycle (core/generic_scheduler.go:147-202 — steps "Basic checks
done", "Snapshotting scheduler cache and node infos done", "Computing
predicates done", "Prioritizing done", logged when the cycle exceeds
100 ms) — SURVEY.md §5 keeps the same span structure and slow-cycle log.
"""

from __future__ import annotations

import contextlib
import logging
import os
import time
from typing import List, Optional, Tuple

LOG = logging.getLogger("kubetpu.trace")

SLOW_CYCLE_THRESHOLD = 0.1  # 100 ms (generic_scheduler.go:148 LogIfLong)

# SURVEY §5: keep jax.profiler traces alongside the host-side step spans.
# When a capture is active (capture_device_trace below, or
# KUBETPU_PROFILE_DIR at import), every Trace phase also opens a
# jax.profiler.TraceAnnotation so device ops group under the cycle phase
# names in the TensorBoard/XProf timeline.
_PROFILE_ACTIVE = False


@contextlib.contextmanager
def capture_device_trace(log_dir: str):
    """Capture a jax.profiler trace (XPlane/TensorBoard format) for the
    enclosed serving activity — the TPU analog of the reference's pprof
    endpoints (DebuggingConfiguration.EnableProfiling, SURVEY §5).  Host
    Trace phases appear as TraceAnnotations inside the capture."""
    global _PROFILE_ACTIVE
    import jax
    os.makedirs(log_dir, exist_ok=True)
    jax.profiler.start_trace(log_dir)
    _PROFILE_ACTIVE = True
    try:
        yield log_dir
    finally:
        _PROFILE_ACTIVE = False
        jax.profiler.stop_trace()


class Trace:
    def __init__(self, name: str, **fields):
        self.name = name
        self.fields = fields
        self.start = time.time()
        self.steps: List[Tuple[float, str]] = []
        self._ann = None
        self._closed = False
        if _PROFILE_ACTIVE:
            self._open_annotation("begin")

    def _close_annotation(self) -> None:
        if self._ann is not None:
            self._ann.__exit__(None, None, None)
            self._ann = None

    def _open_annotation(self, label: str) -> None:
        import jax
        self._close_annotation()
        if _PROFILE_ACTIVE:
            self._ann = jax.profiler.TraceAnnotation(f"{self.name}:{label}")
            self._ann.__enter__()

    def step(self, msg: str) -> None:
        self.steps.append((time.time(), msg))
        if self._ann is not None or _PROFILE_ACTIVE:
            self._open_annotation(msg)

    def __del__(self):
        # last-resort close so an early-return cycle can never leak an
        # entered TraceAnnotation into the rest of the capture
        self._close_annotation()

    def total(self) -> float:
        return time.time() - self.start

    def log_if_long(self, threshold: float = SLOW_CYCLE_THRESHOLD) -> Optional[str]:
        self._close_annotation()
        total = self.total()
        if total < threshold:
            return None
        fields = ",".join(f"{k}:{v}" for k, v in self.fields.items())
        lines = [f'Trace "{self.name}" ({fields}) (total {total * 1000:.0f}ms):']
        last = self.start
        for ts, msg in self.steps:
            lines.append(f"  ---\"{msg}\" {(ts - last) * 1000:.0f}ms")
            last = ts
        out = "\n".join(lines)
        LOG.info(out)
        return out

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.log_if_long()
        return False
