"""String interning: the bridge from Kubernetes' string-typed world (labels,
taints, ports, images, namespaces) to dense integer ids usable on TPU.

Every membership test the reference does with Go maps/sets (label selector
matching, taint toleration, hostPort conflict, image presence) becomes a
multi-hot vector over one of these vocabularies, and set intersection becomes
a matmul on the MXU.  Vocabularies are grow-only so ids are stable across
snapshots; device buffer capacity is padded to power-of-two buckets to bound
XLA recompilation.
"""

from __future__ import annotations

from typing import Dict, Hashable, List, Tuple


def pow2_bucket(n: int, minimum: int = 8) -> int:
    """Smallest power of two >= max(n, minimum).  Keeping tensor dims in
    pow2 buckets means vocab growth only recompiles the jitted program at
    doublings, not on every new label."""
    cap = minimum
    while cap < n:
        cap *= 2
    return cap


class Vocab:
    """Grow-only intern table: hashable key -> stable dense id."""

    __slots__ = ("name", "_ids", "_keys")

    def __init__(self, name: str):
        self.name = name
        self._ids: Dict[Hashable, int] = {}
        self._keys: List[Hashable] = []

    def intern(self, key: Hashable) -> int:
        i = self._ids.get(key)
        if i is None:
            i = len(self._keys)
            self._ids[key] = i
            self._keys.append(key)
        return i

    def get(self, key: Hashable, default: int = -1) -> int:
        """default (-1) if unknown (unknown => can never match anything
        in-cluster).  The explicit default keeps dict-style call sites —
        e.g. table.rname.get(name, -1) for a victim carrying an
        unregistered extended resource — from raising."""
        return self._ids.get(key, default)

    def key(self, i: int) -> Hashable:
        return self._keys[i]

    def __len__(self) -> int:
        return len(self._keys)

    def __contains__(self, key: Hashable) -> bool:
        return key in self._ids

    @property
    def cap(self) -> int:
        return pow2_bucket(len(self._keys))


class InternTable:
    """All vocabularies for one cluster.

    kv     : (label_key, label_value) pairs -> id       (axis L)
    key    : label keys -> id                           (axis K)
    port   : (protocol, host_ip, host_port) -> id       (axis P)
    taint  : (key, value, effect) -> id                 (axis T)
    image  : normalized image name -> id                (axis I)
    ns     : namespace -> id                            (axis NS)
    rname  : extended/scalar resource name -> id        (scalar channels)
    topokey: topology label keys in active use -> id    (axis TK)

    topokey is a *small* subset of `key`: only keys named by topology spread
    constraints or pod (anti-)affinity terms, plus the well-known
    zone/region/hostname keys — so the per-node (key -> label-value-id)
    matrix stays [N, TK] with TK tiny instead of [N, K].
    """

    def __init__(self):
        self.kv = Vocab("kv")
        self.key = Vocab("key")
        self.port = Vocab("port")
        self.taint = Vocab("taint")
        self.image = Vocab("image")
        self.ns = Vocab("ns")
        self.rname = Vocab("rname")
        self.topokey = Vocab("topokey")
        self.zone = Vocab("zone")    # GetZoneKey strings (region:zone)
        self.avoid = Vocab("avoid")  # (controller kind, uid) pairs from
                                     # preferAvoidPods annotations

    def intern_labels(self, labels: Dict[str, str]) -> Tuple[List[int], List[int]]:
        """Intern a label map; returns (kv ids, key ids)."""
        kv_ids = [self.kv.intern((k, v)) for k, v in labels.items()]
        key_ids = [self.key.intern(k) for k in labels.keys()]
        return kv_ids, key_ids
