"""Durable cycle journal: every committed scheduling cycle, on disk.

The flight recorder (utils/trace.py) and SLO sketches (utils/slo.py) are
in-memory rings that die with the process — a production incident or an
interesting placement decision cannot be re-examined after the fact, let
alone re-EXECUTED.  This module is the persistence substrate under both:
when armed (``KUBETPU_JOURNAL=<dir>``, mirroring the KUBETPU_FLIGHT /
KUBETPU_SLO arming discipline), every committed cycle appends ONE
self-contained record to a bounded, size-capped on-disk journal —

  INPUTS   the cycle's exact device-program inputs: the applied
           ``ClusterDelta`` (or the blessed-resync host-mirror snapshot,
           or the chain-materialize pad buckets), the pod batch with its
           interned vocab slice, the RNG fold counter, the
           ``ProgramConfig`` + profile/config digest, the recorded
           host-plugin mask (``host_ok``) and host score bias, the
           effective ``kernel_backend``, ``pipeline_depth`` and
           ``ring_slot``
  OUTPUTS  the packed placement vector (chosen / n_feasible /
           unresolvable / rounds), per-pod placements by name, and a
           per-plugin verdict summary folded from the decision audit
  LINKAGE  the flight-recorder cycle seq (``/debug/flightz``) and the
           decision-audit cycle (``/debug/explain``) so a journal record
           cross-references the in-memory observability for as long as
           those rings still hold it

— and ``tools/kubereplay`` re-executes any journaled window offline,
bit-matching replayed placements against the recorded ones (the same
oracle discipline as the Pallas and AOT gates: a divergence is a
correctness failure, attributed to the first divergent cycle), or
re-runs the window under a modified profile (``--counterfactual``) to
turn every recorded trace into an eval set — the gating substrate for
ROADMAP item 3's learned-scorer work.

On-disk format: one file per record (``cyc-<seq>.rec``) under the armed
directory — a magic/version header, a crc32 of the payload, the payload
length, then the pickled record dict.  Self-contained files make
size-cap eviction an unlink (oldest first, every eviction counted in
``scheduler_journal_dropped_total`` — never silent) and isolate
corruption: a record truncated by a crash (or the ``journal`` chaos
point) fails its crc and is SKIPPED with a per-record reason at read
time instead of poisoning the window.

Bounded-disk contract: at most ``KUBETPU_JOURNAL_MAX_BYTES`` (default
256 MiB) of records are retained.  A replay window must start at a
resync record (the full-snapshot anchor); evicting one orphans the
delta/chain records behind it, which kubereplay skips with reason
``broken-lineage`` until the next anchor.

Arming contract (the poison test in tests/test_journal.py enforces it
exactly like trace's and slo's): DISARMED (the default) every seam is
one module-attribute read — the serving hot path takes ZERO new locks
and allocates no journal state; armed-vs-disarmed placements are
bit-identical (the journal only observes).  Importing this module never
imports jax.

Write-failure contract: an armed append that fails for ANY reason (disk
full, chaos ``journal:error``, an unpicklable capture) degrades to a
counted drop (``dropped_total`` + the metric) — recording must never
fail a scheduling cycle.
"""

from __future__ import annotations

import binascii
import os
import pickle
import struct
import threading
from typing import Any, Dict, Iterator, List, Optional, Tuple

JOURNAL_ENV = "KUBETPU_JOURNAL"
MAX_BYTES_ENV = "KUBETPU_JOURNAL_MAX_BYTES"
DEFAULT_MAX_BYTES = 256 << 20

# record file framing: magic + u32 crc32(payload) + u64 len(payload)
MAGIC = b"KTPJ1"
_HEADER = struct.Struct(">5sIQ")
RECORD_VERSION = 1

# journal record input kinds (the state/delta capture seam's vocabulary):
#   resync  payload = pickled HostClusterArrays (the blessed full-snapshot
#           anchor: initial build, anti-entropy, vocab growth, pod-axis
#           growth, verify-divergence)
#   delta   payload = pickled (ClusterDelta, terms-or-None) applied to the
#           previous record's cluster by programs.apply_cluster_delta
#   chain   payload = (pad_pods, pad_terms): the cluster is the PREVIOUS
#           record's auction materialized at these pow2 pad buckets
#           (models/gang.materialize_assigned, extend_score_terms=True)
#   noop    zero-dirty delta cycle: the previous record's cluster, as is
INPUT_KINDS = ("resync", "delta", "chain", "noop")


class JournalCorrupt(ValueError):
    """A record file whose framing, crc or pickle does not check out —
    the reader-side skip reason, never an abort."""


def _env_max_bytes() -> int:
    """KUBETPU_JOURNAL_MAX_BYTES, tolerant of junk: a malformed value
    (e.g. "256MiB") falls back to the default with a warning instead of
    crashing Scheduler construction through arm_journal."""
    raw = os.environ.get(MAX_BYTES_ENV, "")
    if not raw:
        return DEFAULT_MAX_BYTES
    try:
        return int(raw)
    except ValueError:
        import logging
        logging.getLogger("kubetpu").warning(
            "%s=%r is not an integer byte count; using the default %d",
            MAX_BYTES_ENV, raw, DEFAULT_MAX_BYTES)
        return DEFAULT_MAX_BYTES


def record_filename(seq: int) -> str:
    return "cyc-%012d.rec" % seq


def encode_record(record: Dict[str, Any]) -> bytes:
    payload = pickle.dumps(record, protocol=4)
    return _HEADER.pack(MAGIC, binascii.crc32(payload) & 0xFFFFFFFF,
                        len(payload)) + payload


def decode_record(blob: bytes) -> Dict[str, Any]:
    """Inverse of encode_record; raises JournalCorrupt on any framing,
    length, crc or unpickling failure."""
    if len(blob) < _HEADER.size:
        raise JournalCorrupt("truncated header "
                             f"({len(blob)} < {_HEADER.size} bytes)")
    magic, crc, n = _HEADER.unpack_from(blob)
    if magic != MAGIC:
        raise JournalCorrupt(f"bad magic {magic!r}")
    payload = blob[_HEADER.size:]
    if len(payload) != n:
        raise JournalCorrupt(f"truncated payload ({len(payload)} of {n} "
                             "bytes)")
    if binascii.crc32(payload) & 0xFFFFFFFF != crc:
        raise JournalCorrupt("crc mismatch")
    try:
        rec = pickle.loads(payload)
    except Exception as e:
        raise JournalCorrupt(f"unpicklable payload: {e!r}")
    if not isinstance(rec, dict) or "seq" not in rec:
        raise JournalCorrupt("payload is not a journal record dict")
    return rec


class CycleJournal:
    """The armed journal: a directory of self-contained record files plus
    the counters the ``scheduler_journal_*`` metrics sync from.

    Threading: ``next_seq``/``append`` run on the serving thread; the
    status/linkage reads run on the HTTP debug thread — the counter and
    file-index state is lock-guarded.  File WRITES happen outside the
    lock (one writer, the serving thread, so index order still matches
    file order; blocking I/O must never stall a concurrent status
    read)."""

    def __init__(self, directory: str, max_bytes: Optional[int] = None):
        self.dir = directory
        self.max_bytes = (max_bytes if max_bytes is not None
                          else _env_max_bytes())
        os.makedirs(self.dir, exist_ok=True)
        self._lock = threading.Lock()
        # seq -> on-disk size, insertion-ordered (dicts preserve order);
        # rebuilt from the directory at arm time so a restarted process
        # appends after the previous run's records
        self._files: Dict[int, int] = {}       # kubelint: guarded-by(_lock)
        self._seq = 0                          # kubelint: guarded-by(_lock)
        # running on-disk total (maintained on insert/evict so neither
        # the per-append cap check nor a /debug/journal scrape walks the
        # whole file index under the lock)
        self._disk_total = 0                   # kubelint: guarded-by(_lock)
        self.records_total = 0                 # kubelint: guarded-by(_lock)
        self.bytes_written = 0                 # kubelint: guarded-by(_lock)
        self.dropped_total = 0                 # kubelint: guarded-by(_lock)
        # (journal seq, flight seq, decision cycle, sched cycle) of recent
        # appends — the traceview linkage digest's feed, bounded
        self._links: List[Tuple[int, int, int, int]] = []  # kubelint: guarded-by(_lock)
        self._max_links = 512
        for name in sorted(os.listdir(self.dir)):
            if not (name.startswith("cyc-") and name.endswith(".rec")):
                continue
            try:
                seq = int(name[4:-4])
                size = os.path.getsize(os.path.join(self.dir, name))
            except (ValueError, OSError):
                continue
            self._files[seq] = size
            self._disk_total += size
            self._seq = max(self._seq, seq)

    # -- write side (serving thread) ---------------------------------------

    def next_seq(self) -> int:
        """Reserve the next record id.  Called at commit start so the SLO
        exemplars of the cycle's pods can carry the id the record will be
        appended under."""
        with self._lock:
            self._seq += 1
            return self._seq

    def note_drop(self, n: int = 1) -> None:
        """Count a record that could not be recorded (build or write
        failure) — the degrade-to-drop half of the write contract."""
        with self._lock:
            self.dropped_total += n

    def append(self, record: Dict[str, Any]) -> bool:
        """Write one record file; True when it landed.  Any failure —
        including an injected ``journal`` chaos fault — degrades to a
        counted drop.  Size-cap eviction (oldest records unlinked) runs
        after a successful write and counts as drops too."""
        from . import chaos
        seq = int(record["seq"])
        path = os.path.join(self.dir, record_filename(seq))
        try:
            blob = encode_record(record)
            act = chaos.action("journal")
            if act == "error":
                raise OSError("injected journal write fault")
            if act == "truncate":
                # a crash mid-write: half the frame reaches the disk
                blob = blob[:max(len(blob) // 2, 1)]
            elif act == "corrupt":
                # a flipped byte INSIDE the payload: framing intact, crc
                # check catches it at read time
                mid = _HEADER.size + max((len(blob) - _HEADER.size) // 2, 0)
                mid = min(mid, len(blob) - 1)
                blob = blob[:mid] + bytes([blob[mid] ^ 0xFF]) + blob[mid + 1:]
            with open(path, "wb") as f:
                f.write(blob)
        except Exception:
            try:
                os.unlink(path)
            except OSError:
                pass
            self.note_drop()
            return False
        evict: List[int] = []
        with self._lock:
            self._files[seq] = len(blob)
            self.records_total += 1
            self.bytes_written += len(blob)
            self._links.append((seq, int(record.get("links", {})
                                         .get("flight_seq", 0) or 0),
                                int(record.get("links", {})
                                    .get("decision_cycle", 0) or 0),
                                int(record.get("cycle", 0) or 0)))
            del self._links[:-self._max_links]
            self._disk_total += len(blob)
            while self._disk_total > self.max_bytes \
                    and len(self._files) > 1:
                old = next(iter(self._files))
                self._disk_total -= self._files.pop(old)
                self.dropped_total += 1
                evict.append(old)
        for old in evict:
            try:
                os.unlink(os.path.join(self.dir, record_filename(old)))
            except OSError:
                pass
        return True

    # -- read side ---------------------------------------------------------

    def counters(self) -> Tuple[int, int]:
        """(records_total, dropped_total) — the scheduler_journal_*
        metric sync's feed (monotonic)."""
        with self._lock:
            return self.records_total, self.dropped_total

    def seqs(self) -> List[int]:
        with self._lock:
            return sorted(self._files)

    def disk_bytes(self) -> int:
        with self._lock:
            return self._disk_total

    def status(self, flight_seqs: Optional[set] = None,
               decision_cycles: Optional[set] = None) -> Dict[str, Any]:
        """The /debug/journal + traceview digest document.  When the
        caller passes the flight recorder's live ring seqs (and/or the
        decision log's live cycle set), linkage hit-rates report what
        fraction of recent journal records still cross-reference a live
        in-memory entry."""
        with self._lock:
            seqs = sorted(self._files)
            links = list(self._links)
            doc: Dict[str, Any] = {
                "armed": True,
                "dir": self.dir,
                "max_bytes": self.max_bytes,
                "records": len(seqs),
                "bytes": self._disk_total,
                "records_total": self.records_total,
                "dropped_total": self.dropped_total,
            }
        if seqs:
            doc["first_seq"] = seqs[0]
            doc["last_seq"] = seqs[-1]
        cycles = [c for (_s, _f, _d, c) in links if c]
        if cycles:
            doc["cycle_span"] = [min(cycles), max(cycles)]
        flagged = [(s, f, d) for (s, f, d, _c) in links]
        with_flight = sum(1 for (_s, f, _d) in flagged if f > 0)
        doc["flight_linked"] = with_flight
        if flagged:
            doc["flight_link_rate"] = round(with_flight / len(flagged), 3)
            if flight_seqs is not None:
                live = sum(1 for (_s, f, _d) in flagged
                           if f in flight_seqs)
                doc["flight_live_rate"] = round(live / len(flagged), 3)
            if decision_cycles is not None:
                live = sum(1 for (_s, _f, d) in flagged
                           if d in decision_cycles)
                doc["decision_live_rate"] = round(live / len(flagged), 3)
        return doc


def read_records(directory: str) -> Iterator[Tuple[int, Optional[Dict],
                                                   Optional[str]]]:
    """Yield ``(seq, record, skip_reason)`` for every record file in seq
    order — exactly one of record/skip_reason is None.  Corrupt or
    truncated files (crash, chaos ``journal`` point) yield a per-record
    reason instead of aborting the window; kubereplay surfaces them in
    its report."""
    try:
        names = sorted(n for n in os.listdir(directory)
                       if n.startswith("cyc-") and n.endswith(".rec"))
    except OSError as e:
        raise FileNotFoundError(f"journal directory unreadable: {e}")
    for name in names:
        try:
            seq = int(name[4:-4])
        except ValueError:
            continue
        path = os.path.join(directory, name)
        try:
            with open(path, "rb") as f:
                blob = f.read()
            rec = decode_record(blob)
        except JournalCorrupt as e:
            yield seq, None, str(e)
            continue
        except OSError as e:
            yield seq, None, f"unreadable: {e}"
            continue
        if int(rec.get("seq", -1)) != seq:
            yield seq, None, (f"seq mismatch (file {seq}, "
                              f"payload {rec.get('seq')})")
            continue
        yield seq, rec, None


def config_digest(mode: str, profile: str, cfg, hard_weight: float,
                  kernel_backend: str) -> str:
    """Stable digest of the profile/program configuration a record was
    produced under.  kubereplay surfaces the distinct digests of a
    window (``config_digests`` in its report): a window spanning more
    than one mixes program configurations (a rollout landed mid-window)
    and should be partitioned before being used as an eval set."""
    import hashlib
    text = repr((RECORD_VERSION, mode, profile, tuple(cfg.filters),
                 tuple(cfg.scores), cfg.hostname_topokey,
                 tuple(cfg.plugin_args), cfg.percentage_of_nodes_to_score,
                 tuple(cfg.active_topo_keys), float(hard_weight),
                 kernel_backend))
    return hashlib.sha256(text.encode()).hexdigest()[:16]


# ---------------------------------------------------------------- arming
#
# Same contract as trace.py's recorder, slo.py's tracker and chaos.py's
# registry: _journal is read WITHOUT a lock on the hot path (rebinding a
# reference is atomic; a racing reader sees old or new), arm/disarm
# serialize through _journal_lock.

_journal: Optional[CycleJournal] = None
_journal_lock = threading.Lock()


def journal() -> Optional[CycleJournal]:
    """The armed journal, or None (disarmed, the default)."""
    return _journal


def arm_journal(directory: str,
                max_bytes: Optional[int] = None) -> CycleJournal:
    """Idempotently arm the journal (an already-armed journal for ANY
    directory wins — one journal per process)."""
    global _journal
    with _journal_lock:
        if _journal is None:
            _journal = CycleJournal(directory, max_bytes=max_bytes)
        return _journal


def disarm_journal() -> None:
    global _journal
    with _journal_lock:
        _journal = None


def maybe_arm_from_env() -> Optional[CycleJournal]:
    """Scheduler-construction hook: arms iff KUBETPU_JOURNAL names a
    directory.  An unwritable directory disarms with a warning rather
    than failing scheduler construction."""
    directory = os.environ.get(JOURNAL_ENV, "")
    if not directory:
        return None
    if _journal is not None:
        return _journal
    try:
        return arm_journal(directory)
    except OSError:
        import logging
        logging.getLogger("kubetpu").warning(
            "KUBETPU_JOURNAL=%r is not a writable directory; journal "
            "disarmed", directory)
        return None
