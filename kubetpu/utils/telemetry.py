"""Sustained-load telemetry plane: a windowed time-series ring.

Every substrate the repo already has — the flight recorder
(utils/trace.py), the SLO sketches (utils/slo.py), devstats
(utils/devstats.py) — aggregates over a WHOLE RUN with no time axis, so
none of them can state the number ROADMAP item 3 is judged on:
*steady-state* ``pod_e2e_p99_s`` under continuous production-rate
churn.  This module is that time axis: on a fixed cadence
(``KUBETPU_TELEMETRY_WINDOW`` seconds, default 5) the serving loop's
tick seam rolls one WINDOW record into a bounded ring (default 720
windows ~= 1 h at the default cadence), and each window carries

  * per-stage latency sketches DELTA-MERGED from the SLO tracker's
    cumulative log-ladder counts — the per-window p50/p99 are exact
    window quantiles over the same bucket ladder, not run-cumulative
    numbers that warmup pollutes forever;
  * queue depths, cycle / delta-cycle / resync counts and the last
    auction round count;
  * recovery-ladder events and demotions that landed IN this window
    (tracked by object identity against ``sched.recovery_log``'s tail,
    so a chaos storm's demotions are attributed to the window that
    fired them);
  * journal record/drop and flight-recorder drop deltas;
  * devstats fenced ``device_time_s`` + fence-wait + HBM-ledger deltas.

The ring is served at ``/debug/loadz`` (kubetpu/server.py), exported as
Prometheus series on ``/metrics`` (utils/metrics.py), and summarized as
the ``load`` block of the pipeline doc (utils/trace.py) for the
traceview "load:" digest.

Steady-state detection (``steady_state_span``) is the open-loop
harness's gate half: the earliest suffix of the windowed e2e-p99 series
whose least-squares slope is flat relative to its mean — warmup windows
(compiles, cache fills) are excluded by the slope test, not by a
hand-picked cut.  ``harness/perf.py``'s SustainedLoadRunner injects at
TARGET rate regardless of scheduler backpressure and records offered
vs. completed — the coordinated-omission defense — and reads its
verdict from this ring.

Arming mirrors every other observability layer (``KUBETPU_TELEMETRY=1``
or ``arm_telemetry()``): DISARMED (the default) the serving loop reads
ONE module attribute per cycle and takes ZERO new locks — proven by the
poison-monkeypatch test (tests/test_telemetry.py) — and armed-vs-
disarmed placements are bit-identical (the parity golden).  Importing
this module never imports jax.
"""

from __future__ import annotations

import math
import os
import threading
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .slo import BUCKET_EDGES
from .trace import wallclock

TELEMETRY_ENV = "KUBETPU_TELEMETRY"
WINDOW_ENV = "KUBETPU_TELEMETRY_WINDOW"
CAPACITY_ENV = "KUBETPU_TELEMETRY_N"
DEFAULT_WINDOW_S = 5.0
DEFAULT_CAPACITY = 720          # ~1 h at the 5 s default cadence

# windows keep the full per-stage delta ladder only for e2e (the gate
# number needs exact cross-window merges); other stages keep scalar
# summaries — a window record stays a few KB, bounding the ring
_QUANTS = (0.5, 0.99)

# at most this many recovery-event dicts ride a window record verbatim
# (counts are always exact; the verbatim entries are the debug sample)
_MAX_RECOVERIES_PER_WINDOW = 8


def quantile_from_counts(counts: np.ndarray, q: float) -> float:
    """Upper-bucket-edge quantile over a raw per-bucket count vector on
    the shared slo.py ladder (``[len(BUCKET_EDGES)+1] int64``; the last
    slot is the overflow bucket, clamped to the last edge).  This is the
    window-delta twin of QuantileSketch.quantile — same rank rule, but
    over SUBTRACTED counts, so two cumulative snapshots one window apart
    yield the exact quantile of that window's observations."""
    total = int(counts.sum())
    if total <= 0:
        return 0.0
    rank = min(max(int(math.ceil(q * total)), 1), total)
    cum = 0
    edges = BUCKET_EDGES
    for i, c in enumerate(counts.tolist()):
        cum += int(c)
        if cum >= rank:
            return float(edges[i] if i < len(edges) else edges[-1])
    return float(edges[-1])


def steady_state_span(p99s: List[float], min_windows: int = 6,
                      slope_frac: float = 0.15
                      ) -> Optional[Tuple[int, int]]:
    """(start index, length) of the EARLIEST suffix of the windowed-p99
    series that is statistically flat — least-squares slope times the
    suffix's span at most ``slope_frac`` of the suffix mean — and at
    least ``min_windows`` long.  None when no suffix qualifies.  This is
    the warmup cut: compiles and cache fills inflate the leading
    windows, and a hand-picked warmup count would either waste steady
    windows or leak warmup into the gate number."""
    n = len(p99s)
    for start in range(0, n - min_windows + 1):
        tail = p99s[start:]
        m = len(tail)
        mean = sum(tail) / m
        if mean <= 0:
            return (start, m)
        xs = range(m)
        xbar = (m - 1) / 2.0
        sxx = sum((x - xbar) ** 2 for x in xs)
        if sxx == 0:
            return (start, m)
        slope = sum((x - xbar) * (y - mean)
                    for x, y in zip(xs, tail)) / sxx
        if abs(slope) * (m - 1) <= slope_frac * mean:
            return (start, m)
    return None


def _stage_block(delta: np.ndarray, sum_s: float) -> Dict[str, Any]:
    """One stage's per-window summary from its DELTA count vector."""
    d = {"count": int(delta.sum()), "sum_s": round(max(sum_s, 0.0), 6)}
    if d["count"]:
        for q in _QUANTS:
            key = "p" + ("%g" % (q * 100)).replace(".", "")
            d[key + "_s"] = round(quantile_from_counts(delta, q), 6)
    return d


def _gather_slo() -> Optional[Dict[str, Any]]:
    """Cumulative SLO snapshot (counts per stage + pods/unresolvable),
    or None when the tracker is disarmed."""
    from . import slo as _slo
    trk = _slo.tracker()
    if trk is None:
        return None
    return trk.counts_snapshot()


def _gather_device() -> Optional[Dict[str, float]]:
    """Cumulative devstats totals, or None when disarmed."""
    from . import devstats as _devstats
    ds = _devstats.devstats()
    if ds is None:
        return None
    summary = ds.summary()
    return {
        "device_time_s": sum(
            p.get("device_time_s", 0.0)
            for p in (summary.get("programs") or {}).values()),
        "fence_wait_s": float(summary.get("fence_wait_s", 0.0)),
        "ledger_bytes": float(summary.get("ledger_bytes", 0)),
    }


def _gather_journal() -> Optional[Dict[str, int]]:
    """Cumulative journal record/drop totals, or None when disarmed."""
    from . import journal as _journal
    jr = _journal.journal()
    if jr is None:
        return None
    st = jr.status()
    return {"records_total": int(st.get("records_total", 0)),
            "dropped_total": int(st.get("dropped_total", 0))}


def _gather_flight() -> Optional[Dict[str, int]]:
    """Cumulative flight-recorder drop count + newest live cycle seq
    (the window's cross-link into /debug/flightz), or None."""
    from . import trace as _trace
    fr = _trace.flight_recorder()
    if fr is None:
        return None
    recs = fr.cycles()
    return {"dropped": int(fr.dropped()),
            "last_seq": int(recs[-1].seq) if recs else 0}


class TelemetryRing:
    """Bounded ring of window records.  Two locks, strictly ordered
    ``_roll_lock`` -> ``_lock``: the roll lock serializes snapshot
    gathering + delta state (ALL cross-layer I/O happens under it and
    it is only ever taken from the tick seam, never from readers); the
    ring lock guards only the deque append and the reader copies, so a
    /debug/loadz scrape can never stall a roll's gather and vice
    versa."""

    def __init__(self, window_s: Optional[float] = None,
                 capacity: Optional[int] = None):
        if window_s is None:
            window_s = float(os.environ.get(WINDOW_ENV,
                                            str(DEFAULT_WINDOW_S)))
        if capacity is None:
            capacity = int(os.environ.get(CAPACITY_ENV,
                                          str(DEFAULT_CAPACITY)))
        self.window_s = max(float(window_s), 1e-3)
        self.capacity = max(int(capacity), 1)
        self._lock = threading.Lock()
        self._roll_lock = threading.Lock()
        self._windows: deque = deque()   # kubelint: guarded-by(_lock)
        self._dropped = 0                # kubelint: guarded-by(_lock)
        self._seq = 0                    # kubelint: guarded-by(_lock)
        # deadline for the next roll: READ LOCK-FREE on the tick fast
        # path (rebinding a float is atomic — a racing reader sees the
        # old or the new deadline, and the roll lock serializes actual
        # rolls), WRITTEN only under _roll_lock
        self._deadline = wallclock() + self.window_s  # kubelint: guarded-by(none)
        # previous cumulative snapshots the next roll subtracts from —
        # only ever touched under _roll_lock
        self._prev_slo: Optional[Dict[str, Any]] = None
        self._prev_sched: Optional[Dict[str, float]] = None
        self._prev_device: Optional[Dict[str, float]] = None
        self._prev_journal: Optional[Dict[str, int]] = None
        self._prev_flight: Optional[Dict[str, int]] = None
        self._last_recovery = None      # identity of the last-seen tail
        self._t_open = wallclock()      # kubelint: guarded-by(_roll_lock)

    # -- recording (the serving-loop seam) ------------------------------

    def maybe_tick(self, sched) -> None:
        """Serving-loop seam: roll a window iff the cadence elapsed.
        The fast path is ONE float compare — no locks taken until a roll
        is actually due (once per window, not per cycle)."""
        if wallclock() < self._deadline:
            return
        with self._roll_lock:
            # re-check under the roll lock: a racing ticker may have
            # rolled this window already
            if wallclock() < self._deadline:
                return
            self._roll(sched)

    def force_roll(self, sched=None) -> Dict[str, Any]:
        """Close the current window NOW regardless of cadence (bench /
        test hook; the open-loop runner uses the cadence path)."""
        with self._roll_lock:
            return self._roll(sched)

    def _roll(self, sched) -> Dict[str, Any]:
        # entered with _roll_lock held.  EVERY gather below runs outside
        # the ring lock; only the final append takes it.
        now = wallclock()
        slo = _gather_slo()
        device = _gather_device()
        journal = _gather_journal()
        flight = _gather_flight()
        sched_tot = self._read_sched(sched)
        depths = None
        if sched is not None:
            # the queue read takes the queue's condition lock — allowed
            # here because telemetry is ARMED (opt-in), mirroring the
            # flight recorder's gated depths read in _prepare_group
            depths = sched.queue.depths()
        rec: Dict[str, Any] = {
            "t0": round(self._t_open, 6),
            "t1": round(now, 6),
            "window_s": round(now - self._t_open, 6),
        }
        rec.update(self._delta_sched(sched_tot))
        rec.update(self._delta_slo(slo))
        rec.update(self._delta_recoveries(sched))
        rec.update(self._delta_io(journal, flight))
        rec.update(self._delta_device(device))
        if depths is not None:
            rec["queue_depths"] = depths
        if flight is not None:
            rec["flight_seq"] = flight["last_seq"]
        self._prev_slo = slo
        self._prev_sched = sched_tot
        self._prev_device = device
        self._prev_journal = journal
        self._prev_flight = flight
        self._t_open = now
        # schedule the NEXT roll relative to now, not the nominal grid:
        # a stalled serving loop then yields one long window (window_s
        # says how long), never a burst of zero-length catch-up windows
        self._deadline = now + self.window_s
        with self._lock:
            self._seq += 1
            rec["seq"] = self._seq
            self._windows.append(rec)
            if len(self._windows) > self.capacity:
                self._windows.popleft()
                self._dropped += 1
        return rec

    def _read_sched(self, sched) -> Optional[Dict[str, float]]:
        """Racy-but-atomic cumulative counter reads off the scheduler
        (the same discipline bench.py uses on the drain path)."""
        if sched is None:
            return None
        return {"cycles": float(sched.cycle_count),
                "delta_cycles": float(sched.delta_cycle_count),
                "resyncs": float(sched.resync_count),
                "device_wait_s": float(sched.device_wait_s),
                "gang_rounds_last": float(sched.last_gang_rounds)}

    def _delta_sched(self, cur) -> Dict[str, Any]:
        if cur is None:
            return {}
        prev = self._prev_sched or {k: 0.0 for k in cur}
        return {"cycles": int(cur["cycles"] - prev.get("cycles", 0.0)),
                "delta_cycles": int(cur["delta_cycles"]
                                    - prev.get("delta_cycles", 0.0)),
                "resyncs": int(cur["resyncs"] - prev.get("resyncs", 0.0)),
                "device_wait_s": round(
                    max(cur["device_wait_s"]
                        - prev.get("device_wait_s", 0.0), 0.0), 6),
                "gang_rounds_last": int(cur["gang_rounds_last"])}

    def _delta_slo(self, cur) -> Dict[str, Any]:
        if cur is None:
            return {}
        prev = self._prev_slo
        stages: Dict[str, Any] = {}
        e2e_delta = None
        for name, blk in cur["stages"].items():
            pblk = (prev or {"stages": {}})["stages"].get(name)
            delta = blk["counts"] - pblk["counts"] if pblk is not None \
                else blk["counts"].copy()
            np.maximum(delta, 0, out=delta)   # clear() mid-window
            dsum = blk["sum_s"] - (pblk["sum_s"] if pblk else 0.0)
            stages[name] = _stage_block(delta, dsum)
            if name == "e2e":
                e2e_delta = delta
        ppods = (prev or {}).get("pods", 0)
        punres = (prev or {}).get("unresolvable", 0)
        out: Dict[str, Any] = {
            "stages": stages,
            "pods": max(int(cur["pods"] - ppods), 0),
            "unresolvable": max(int(cur["unresolvable"] - punres), 0),
        }
        if e2e_delta is not None:
            # the raw e2e delta ladder rides the record (stripped from
            # JSON exports) so steady windows merge to an EXACT
            # steady-state quantile instead of a quantile-of-quantiles
            out["_e2e_counts"] = e2e_delta
        return out

    def _delta_recoveries(self, sched) -> Dict[str, Any]:
        log = getattr(sched, "recovery_log", None)
        if log is None:
            return {}
        entries = list(log)
        start = 0
        if self._last_recovery is not None:
            for i in range(len(entries) - 1, -1, -1):
                if entries[i] is self._last_recovery:
                    start = i + 1
                    break
        new = entries[start:]
        if entries:
            self._last_recovery = entries[-1]
        demoted = sum(len(e.get("demoted") or ()) for e in new)
        out: Dict[str, Any] = {"recoveries": len(new),
                               "demotions": int(demoted)}
        if new:
            out["recovery_events"] = [
                {"kind": e.get("kind", ""), "cycle": int(e.get("cycle", 0)),
                 "demoted": len(e.get("demoted") or ())}
                for e in new[:_MAX_RECOVERIES_PER_WINDOW]]
        return out

    def _delta_io(self, journal, flight) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        if journal is not None:
            pj = self._prev_journal or {}
            out["journal_records"] = max(
                journal["records_total"] - pj.get("records_total", 0), 0)
            out["journal_dropped"] = max(
                journal["dropped_total"] - pj.get("dropped_total", 0), 0)
        if flight is not None:
            pf = self._prev_flight or {}
            out["flight_dropped"] = max(
                flight["dropped"] - pf.get("dropped", 0), 0)
        return out

    def _delta_device(self, cur) -> Dict[str, Any]:
        if cur is None:
            return {}
        prev = self._prev_device or {}
        return {"device_time_s": round(
                    max(cur["device_time_s"]
                        - prev.get("device_time_s", 0.0), 0.0), 6),
                "fence_wait_s": round(
                    max(cur["fence_wait_s"]
                        - prev.get("fence_wait_s", 0.0), 0.0), 6),
                "ledger_bytes": int(cur["ledger_bytes"]),
                "ledger_delta_bytes": int(
                    cur["ledger_bytes"] - prev.get("ledger_bytes", 0.0))}

    # -- reads ----------------------------------------------------------

    def windows(self) -> List[Dict[str, Any]]:
        """Oldest-first window records (the raw internal shape — e2e
        delta ladders included; exports strip them)."""
        with self._lock:
            return list(self._windows)

    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def clear(self) -> None:
        with self._roll_lock:
            self._prev_slo = None
            self._prev_sched = None
            self._prev_device = None
            self._prev_journal = None
            self._prev_flight = None
            self._last_recovery = None
            self._t_open = wallclock()
            self._deadline = self._t_open + self.window_s
            with self._lock:
                self._windows.clear()
                self._dropped = 0

    def e2e_p99_series(self) -> List[float]:
        """Per-window e2e p99 seconds — zeros for windows that saw no
        terminal pods (the steady-state slope test's input)."""
        return [w.get("stages", {}).get("e2e", {}).get("p99_s", 0.0)
                for w in self.windows()]

    def steady_quantile(self, start: int, n: int, q: float = 0.99
                        ) -> float:
        """EXACT quantile over the merged raw e2e ladders of windows
        [start, start+n) — the gate number.  Falls back to the max of
        the per-window quantiles when no window kept a ladder (SLO
        tracker disarmed)."""
        wins = self.windows()[start:start + n]
        merged = None
        for w in wins:
            counts = w.get("_e2e_counts")
            if counts is None:
                continue
            merged = counts.copy() if merged is None else merged + counts
        if merged is not None and int(merged.sum()) > 0:
            return quantile_from_counts(merged, q)
        return max((w.get("stages", {}).get("e2e", {}).get("p99_s", 0.0)
                    for w in wins), default=0.0)

    @staticmethod
    def _public(w: Dict[str, Any]) -> Dict[str, Any]:
        return {k: v for k, v in w.items() if not k.startswith("_")}

    def digest(self) -> Dict[str, Any]:
        """The pipeline-doc ``load`` block: window count + cadence,
        drops, the steady-state span over the e2e-p99 series, the
        steady-state p99 (exact merged), total demotions, and the worst
        window (by e2e p99) with its flight_seq cross-link — everything
        tools/traceview.py needs for the one-line "load:" digest."""
        wins = self.windows()
        d: Dict[str, Any] = {"windows": len(wins),
                             "window_s": self.window_s,
                             "dropped": self.dropped()}
        if not wins:
            return d
        p99s = [w.get("stages", {}).get("e2e", {}).get("p99_s", 0.0)
                for w in wins]
        d["demotions"] = sum(int(w.get("demotions", 0)) for w in wins)
        d["pods"] = sum(int(w.get("pods", 0)) for w in wins)
        worst_i = max(range(len(wins)), key=lambda i: p99s[i])
        d["worst_window"] = {"seq": wins[worst_i].get("seq", 0),
                             "p99_s": round(p99s[worst_i], 6),
                             "flight_seq": wins[worst_i].get(
                                 "flight_seq", 0)}
        span = steady_state_span(p99s)
        if span is not None:
            start, n = span
            d["steady"] = {
                "start": start, "windows": n,
                "p99_s": round(self.steady_quantile(start, n, 0.99), 6),
                "p50_s": round(self.steady_quantile(start, n, 0.5), 6)}
        return d

    def to_dict(self, last: Optional[int] = None) -> Dict[str, Any]:
        """The /debug/loadz document: digest + the (optionally tail-
        limited) window records, raw ladders stripped."""
        wins = [self._public(w) for w in self.windows()]
        if last is not None and last >= 0:
            wins = wins[-last:] if last else []
        return {"armed": True,
                "capacity": self.capacity,
                "digest": self.digest(),
                "windows": wins}


# module arming state — read WITHOUT a lock on the hot path (rebinding a
# Python reference is atomic; a racing reader sees old or new), exactly
# like utils/slo.py's _tracker.  arm/disarm serialize via _tel_lock.
_ring: Optional[TelemetryRing] = None
_tel_lock = threading.Lock()


def ring() -> Optional[TelemetryRing]:
    """The armed telemetry ring, or None (disarmed, the default)."""
    return _ring


def arm_telemetry(window_s: Optional[float] = None,
                  capacity: Optional[int] = None) -> TelemetryRing:
    """Idempotently arm the telemetry ring (returns the existing one if
    already armed — one ring per process)."""
    global _ring
    with _tel_lock:
        if _ring is None:
            _ring = TelemetryRing(window_s=window_s, capacity=capacity)
        return _ring


def disarm_telemetry() -> None:
    global _ring
    with _tel_lock:
        _ring = None


def maybe_arm_from_env() -> Optional[TelemetryRing]:
    """Scheduler-construction hook: arms iff KUBETPU_TELEMETRY=1."""
    if os.environ.get(TELEMETRY_ENV, "0") not in ("", "0", "false",
                                                  "False"):
        return arm_telemetry()
    return None
