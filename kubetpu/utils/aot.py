"""AOT executable artifacts: serialized-XLA warm start for the scheduler.

Cold start is the production blocker, not steady-state speed: a restart
pays XLA for every ladder program (first_run_s is 133-737 s at the
north-star shapes).  The persistent compilation cache (utils/compilation)
bounds that to a disk load, but a cache-warm restart still pays the full
trace + lower for each program before the cache key can even be computed.
This module removes that too: executables compiled at BUILD/DEPLOY time
(tools/kubeaot) are serialized via ``jax.experimental.serialize_executable``
into a versioned artifact directory, and at serving start the dispatch
seams load them directly — no trace, no lower, no XLA.

Three pieces:

* ``AotStore`` — the artifact directory.  One ``.aotx`` file per compiled
  variant, named by the lowering sha256 + an environment key, plus an
  ``index.json`` mapping runtime signature keys to artifacts.  Artifacts
  are pickles (executable payload + in/out tree defs) and are TRUSTED
  BUILD OUTPUTS — load them only from directories you produced.
* ``AotRuntime`` — the dispatch half.  Armed (``arm()`` /
  ``KUBETPU_AOT_DIR``), the serving seams in models/gang.py,
  models/sequential.py, models/programs.py and scheduler.py route each
  call through ``dispatch()``: a signature hit calls the loaded
  executable (statics dropped — they are baked into the program), a miss
  falls back to the jit exactly as before (the persistent-cache/trace
  ladder).  ``capture`` mode is the build side of the same seam: instead
  of calling the jit it runs ``jit.lower(...).compile()``, serializes the
  result, and registers it — so captured call forms are byte-identical
  to the serving call forms by construction.
* Artifact KEYS.  An artifact's identity is its build-time lowering
  sha256 (the census manifest's canonical hash) + (jax/jaxlib version,
  backend, device/topology signature).  The RUNTIME lookup key adds
  nothing that needs a trace: (program, static signature, call treedef,
  flattened avals), plus an index-level environment check that includes a
  digest of the kernel source tree — a kernel edit, jaxlib bump, backend
  or topology change all invalidate every artifact and the seams fall
  back per bucket to the persistent-cache/trace path.

Disarmed (the default) the seams add one module-attribute read per
dispatch — the hot path is otherwise untouched, mirroring the flight
recorder's arming contract (trace.py).
"""

from __future__ import annotations

import hashlib
import inspect
import json
import logging
import os
import pickle
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

LOG = logging.getLogger("kubetpu.aot")

DIR_ENV = "KUBETPU_AOT_DIR"
INDEX_NAME = "index.json"
INDEX_COMMENT = ("AOT executable artifact index (tools/kubeaot). "
                 "Regenerate: make aot. ci_lint.sh fails when the census-"
                 "family rows drift from COMPILE_MANIFEST.json.")

# the kernel source surface an artifact's program is compiled from: any
# edit here must invalidate every artifact (the lowering would change in
# ways the signature key cannot see)
_KERNEL_PATHS = ("models", "ops", "state", "preemption.py", "parallel")


# ------------------------------------------------------------ environment


def _pkg_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


_kernel_digest: Optional[str] = None
_kernel_digest_lock = threading.Lock()


def kernel_digest() -> str:
    """sha256 over the kernel source files (kubetpu/models, ops, state,
    parallel, preemption.py) — the cheap no-trace staleness fence: a
    kernel edit changes the digest, which mismatches every artifact
    index built before it."""
    global _kernel_digest
    with _kernel_digest_lock:
        if _kernel_digest is not None:
            return _kernel_digest
        h = hashlib.sha256()
        root = _pkg_root()
        for rel in _KERNEL_PATHS:
            path = os.path.join(root, rel)
            if os.path.isfile(path):
                files = [path]
            else:
                files = sorted(
                    os.path.join(dp, f)
                    for dp, _dirs, fs in os.walk(path)
                    for f in fs if f.endswith(".py"))
            for f in files:
                h.update(os.path.relpath(f, root).encode())
                with open(f, "rb") as fh:
                    h.update(fh.read())
        _kernel_digest = h.hexdigest()
        return _kernel_digest


def device_signature() -> str:
    """backend:device-kind x count — the topology half of the artifact
    key (a serialized executable is loadable only onto the device set it
    was compiled for)."""
    import jax
    devs = jax.devices()
    kind = getattr(devs[0], "device_kind", devs[0].platform)
    return "%s:%s x%d" % (devs[0].platform, kind, len(devs))


def env_signature() -> Dict[str, str]:
    """The environment an artifact set is valid for; any field drifting
    invalidates the whole index (serve arming refuses it)."""
    import jax
    try:
        import jaxlib
        jl = getattr(getattr(jaxlib, "version", None), "__version__",
                     jax.__version__)
    except Exception:  # pragma: no cover - jaxlib always ships with jax
        jl = jax.__version__
    return {"jax": jax.__version__, "jaxlib": jl,
            "backend": jax.default_backend(),
            "device_sig": device_signature(),
            "kernel_digest": kernel_digest()}


# ------------------------------------------------------------- signatures


def _leaf_sig(x) -> str:
    if hasattr(x, "shape") and hasattr(x, "dtype"):
        sig = "%s[%s]" % (x.dtype.name if hasattr(x.dtype, "name")
                          else str(x.dtype),
                          ",".join(str(d) for d in x.shape))
        # a MULTI-DEVICE array (mesh profile: pmesh shards the cluster,
        # then calls the same seamed Python entries) must never key to an
        # artifact compiled for single-device inputs — the deserialized
        # executable's input-sharding check would reject it.  Tag the
        # mesh placement; single-device arrays and numpy hosts keep the
        # bare signature, so single-chip artifact keys are unchanged.
        sh = getattr(x, "sharding", None)
        if sh is not None:
            try:
                devs = sh.device_set
                if len(devs) > 1:
                    sig += "@%s" % (sh.spec if hasattr(sh, "spec")
                                    else "sharded%d" % len(devs))
            except Exception:  # pragma: no cover - exotic sharding types
                sig += "@sharded"
        return sig
    # python scalars trace as weak rank-0 avals; their VALUE is dynamic
    return "py:%s" % type(x).__name__


def static_sig(statics: Dict[str, Any]) -> str:
    """Stable digest of the static argument values (same convention as
    tools/kubecensus.census._static_sig)."""
    r = repr(sorted((k, repr(v)) for k, v in statics.items()))
    return hashlib.sha256(r.encode()).hexdigest()[:16]


# defaults of each seamed program's keyword parameters, by program name —
# jit resolves an unpassed static kwarg to its function default, so the
# signature must too or `f(x)` and `f(x, mr=None)` would key differently
_defaults_cache: Dict[str, Dict[str, Any]] = {}
_defaults_lock = threading.Lock()


def _kw_defaults(program: str, jitfn) -> Dict[str, Any]:
    with _defaults_lock:
        d = _defaults_cache.get(program)
        if d is None:
            try:
                fn = getattr(jitfn, "__wrapped__", jitfn)
                d = {k: p.default
                     for k, p in inspect.signature(fn).parameters.items()
                     if p.default is not inspect.Parameter.empty}
            except (TypeError, ValueError):  # pragma: no cover - C callables
                d = {}
            _defaults_cache[program] = d
        return d


def call_signature(program: str, jitfn, args: tuple, kwargs: dict,
                   static_argnums: Tuple[int, ...] = (),
                   static_argnames: Tuple[str, ...] = (),
                   ) -> Tuple[str, tuple, dict, dict, str]:
    """(sig_key, dyn_args, dyn_kwargs, norm_kwargs, static_sig) for one
    call.  The key is computable without tracing: program name + static
    digest + the call's pytree structure + per-leaf avals.

    NORMALIZATION — capture and serve must produce byte-identical call
    forms, because a deserialized executable validates its input pytree
    exactly (positional-vs-keyword and a present-but-None kwarg both
    mismatch):

    * static kwargs NOT passed are filled from the function's declared
      defaults (what jit's cache key resolves them to anyway);
    * dynamic kwargs passed as None whose declared default IS None are
      DROPPED from both the signature and the dispatched call — every
      seamed program's optional arrays (host_ok, score_bias, tie_index)
      follow that convention, so `f(x)` and `f(x, host_ok=None)` key and
      call identically.

    dyn_args/dyn_kwargs are the statics-stripped call the compiled
    executable accepts; norm_kwargs is the full normalized keyword dict
    (statics included) the capture side must lower with."""
    import jax

    defaults = _kw_defaults(program, jitfn)
    stat_idx = set(static_argnums)
    statics = {"arg%d" % i: args[i] for i in stat_idx if i < len(args)}
    dyn_args = tuple(a for i, a in enumerate(args) if i not in stat_idx)
    dyn_kwargs = {}
    norm_kwargs = {}
    for k, v in kwargs.items():
        if k in static_argnames:
            statics[k] = v
            norm_kwargs[k] = v
        elif v is None and defaults.get(k, ()) is None:
            continue                       # == omitting it, see docstring
        else:
            dyn_kwargs[k] = v
            norm_kwargs[k] = v
    for k in static_argnames:
        if k not in statics and k in defaults:
            statics[k] = defaults[k]
    ssig = static_sig(statics)
    leaves, treedef = jax.tree_util.tree_flatten((dyn_args, dyn_kwargs))
    doc = json.dumps([program, ssig, str(treedef),
                      [_leaf_sig(l) for l in leaves]])
    key = hashlib.sha256(doc.encode()).hexdigest()[:24]
    return key, dyn_args, dyn_kwargs, norm_kwargs, ssig


def pod_bucket_of(args: tuple) -> Optional[int]:
    """The pod-axis bucket of a seam call (cluster is always the first
    argument of the seamed programs) — the unit the flight recorder's
    bucket-hit pruning works in."""
    try:
        return int(args[0].pod_valid.shape[0])
    except Exception:
        return None


def _note_resident_executable(row: dict) -> None:
    """Residency-ledger seam (utils/devstats.py): a deserialized AOT
    executable is a live device-program allocation — register its
    serialized size (the closest committed proxy for the loaded program
    binary) so the capacity planner counts the resident executable set.
    Disarmed: one attribute read."""
    from . import devstats as _devstats
    ds = _devstats.devstats()
    if ds is None:
        return
    ds.record_bytes("aot-executables", "",
                    str(row.get("row") or row.get("artifact") or "?"),
                    int(row.get("bytes") or 0))


# ------------------------------------------------------------------ store


class AotStore:
    """One artifact directory: ``<root>/<program>-<sha16>-<env8>.aotx``
    files plus ``<root>/index.json``.  Serialization format per artifact:
    pickle of {"meta", "payload", "in_tree", "out_tree"}."""

    def __init__(self, root: str):
        self.root = root
        self.index_path = os.path.join(root, INDEX_NAME)

    def _env_key(self, env: Dict[str, str]) -> str:
        doc = json.dumps([env.get("jaxlib"), env.get("backend"),
                          env.get("device_sig")])
        return hashlib.sha256(doc.encode()).hexdigest()[:8]

    def artifact_name(self, program: str, lowering_sha256: str,
                      env: Dict[str, str]) -> str:
        return "%s-%s-%s.aotx" % (program.strip("_"), lowering_sha256[:16],
                                  self._env_key(env))

    def save(self, name: str, meta: Dict[str, Any], payload: bytes,
             in_tree, out_tree) -> int:
        os.makedirs(self.root, exist_ok=True)
        blob = pickle.dumps({"meta": meta, "payload": payload,
                             "in_tree": in_tree, "out_tree": out_tree})
        path = os.path.join(self.root, name)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, path)
        return len(blob)

    def load(self, name: str) -> Dict[str, Any]:
        with open(os.path.join(self.root, name), "rb") as f:
            data = f.read()
        # chaos seam (utils/chaos.py "aot-load"): a truncated blob is
        # what a torn deploy / partial rsync actually produces — the
        # pickle failure below must flow through every caller's
        # degrade-to-trace-path handling, never crash prewarm
        from . import chaos
        if chaos.action("aot-load") is not None:
            data = data[:max(len(data) // 2, 1)]
        return pickle.loads(data)

    def remove(self, name: str) -> None:
        try:
            os.unlink(os.path.join(self.root, name))
        except OSError:
            pass

    # ---- index ----------------------------------------------------------

    def write_index(self, env: Dict[str, str], rows: List[dict],
                    extra_path: Optional[str] = None) -> str:
        doc = {"_comment": INDEX_COMMENT, "env": env,
               "rows": sorted(rows, key=lambda r: (r.get("row") or "",
                                                   r.get("sig_key") or ""))}
        os.makedirs(self.root, exist_ok=True)
        for path in filter(None, (self.index_path, extra_path)):
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=1, sort_keys=True)
                f.write("\n")
            os.replace(tmp, path)
        return self.index_path

    def read_index(self) -> Optional[dict]:
        try:
            with open(self.index_path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None


# ---------------------------------------------------------------- runtime


class AotRuntime:
    """The serving (or capture) half over one AotStore.

    serve mode: dispatch() resolves the call's signature key against the
    index, deserialize-and-loads the artifact on first use (an
    ``aot-load`` flight span records seconds + hit/miss per bucket), and
    calls the loaded executable with the statics-stripped call.  Any
    miss — unknown signature, unreadable artifact, env drift — falls
    back to the jit (persistent-cache/trace ladder) and is remembered so
    later calls skip the probe.

    capture mode (tools/kubeaot build side): dispatch() compiles the
    exact serving call via ``jit.lower(...).compile()``, serializes it
    into the store, and returns the compiled result so multi-cycle
    prewarm ladders keep chaining."""

    def __init__(self, store: AotStore, mode: str = "serve",
                 env: Optional[Dict[str, str]] = None,
                 family: str = "serving"):
        assert mode in ("serve", "capture")
        self.store = store
        self.mode = mode
        self.family = family
        self.env = env or env_signature()
        self._lock = threading.Lock()
        self._execs: Dict[str, Any] = {}      # kubelint: guarded-by(_lock)
        self._missing: set = set()            # kubelint: guarded-by(_lock)
        self._rows_by_sig: Dict[str, dict] = {}  # kubelint: guarded-by(_lock)
        self._rows: List[dict] = []           # kubelint: guarded-by(_lock)
        self.hits = 0                         # kubelint: guarded-by(_lock)
        self.misses = 0                       # kubelint: guarded-by(_lock)
        self.loads = 0                        # kubelint: guarded-by(_lock)
        self.disabled_reason: Optional[str] = None
        if mode == "serve":
            self._load_index()

    # ---- index / status -------------------------------------------------

    def _load_index(self) -> None:
        doc = self.store.read_index()
        if doc is None:
            self.disabled_reason = "no artifact index at %s" % \
                self.store.index_path
            return
        built = doc.get("env") or {}
        here = self.env
        for field in ("jax", "jaxlib", "backend", "device_sig",
                      "kernel_digest"):
            if built.get(field) != here.get(field):
                self.disabled_reason = (
                    "artifact env mismatch on %s: built %r, serving %r — "
                    "falling back to the persistent-cache/trace path"
                    % (field, built.get(field), here.get(field)))
                LOG.warning(self.disabled_reason)
                return
        with self._lock:
            for row in doc.get("rows", []):
                sig = row.get("sig_key")
                if sig:
                    self._rows_by_sig[sig] = row
                self._rows.append(row)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"mode": self.mode, "hits": self.hits,
                    "misses": self.misses, "loads": self.loads,
                    "indexed": len(self._rows),
                    "disabled": self.disabled_reason}

    def rows(self) -> List[dict]:
        with self._lock:
            return list(self._rows)

    def serving_buckets(self) -> set:
        """Pod-axis buckets the artifact set covers (empty = no pruning
        information; prewarm walks its full ladder)."""
        with self._lock:
            return {r["pod_bucket"] for r in self._rows
                    if r.get("family") == "serving"
                    and r.get("pod_bucket")}

    def allows_bucket(self, bucket: int) -> bool:
        """Ladder pruning: a bucket with no artifact — because the flight
        recorder never saw it and tools/kubeaot --prune dropped it — is
        not worth prewarm's dry-run either."""
        buckets = self.serving_buckets()
        return not buckets or bucket in buckets

    # ---- dispatch -------------------------------------------------------

    def dispatch(self, program: str, jitfn, args: tuple, kwargs: dict,
                 static_argnums: Tuple[int, ...] = (),
                 static_argnames: Tuple[str, ...] = ()):
        if self.disabled_reason is not None:
            return jitfn(*args, **kwargs)
        try:
            key, dyn_args, dyn_kwargs, norm_kwargs, ssig = call_signature(
                program, jitfn, args, kwargs, static_argnums,
                static_argnames)
        except Exception:  # pragma: no cover - malformed seam call
            LOG.warning("aot signature failed for %s", program,
                        exc_info=True)
            return jitfn(*args, **kwargs)
        with self._lock:
            fn = self._execs.get(key)
            missing = key in self._missing
        if fn is None and not missing:
            if self.mode == "capture":
                fn = self._capture(program, key, ssig, jitfn, args,
                                   norm_kwargs)
            else:
                fn = self._load(program, key, args)
        if fn is not None:
            try:
                out = fn(*dyn_args, **dyn_kwargs)
            except Exception:
                # the loaded executable REJECTED the call (input sharding
                # or layout the signature could not see) — the serving
                # contract is "never worse than disarmed": remember the
                # miss and fall back to the jit.  No seamed program
                # donates buffers, so the failed attempt consumed nothing
                # and the retry below is safe.
                LOG.warning("aot executable for %s rejected the call; "
                            "falling back to the trace path", program,
                            exc_info=True)
                with self._lock:
                    self._missing.add(key)
                    self._execs.pop(key, None)
                    self.misses += 1
                return jitfn(*args, **kwargs)
            with self._lock:
                self.hits += 1
            return out
        with self._lock:
            self.misses += 1
        return jitfn(*args, **kwargs)

    # ---- serve side -----------------------------------------------------

    def preload(self, family: Optional[str] = "serving") -> List[dict]:
        """Warm-start fast path (Scheduler.prewarm): deserialize-and-load
        every indexed artifact of ``family`` (None = all) UP FRONT, so
        prewarm's dry-run and the first serving cycle dispatch into
        resident executables — no trace, no lower, no XLA for covered
        call forms.  Returns one report dict per row: {program, variant,
        pod_bucket, seconds, ok}; rows whose artifact is unreadable
        report ok=False and stay on the per-bucket fallback
        (persistent-cache/trace) path."""
        from jax.experimental import serialize_executable as se

        from .trace import flight_span
        report: List[dict] = []
        for row in self.rows():
            if family is not None and row.get("family") != family:
                continue
            key, name = row.get("sig_key"), row.get("artifact")
            if not key or not name:
                continue
            with self._lock:
                if key in self._execs:
                    continue
            t0 = time.time()
            ok = True
            reason = None
            with flight_span("aot-load", program=row.get("program", "?"),
                             bucket=row.get("pod_bucket"), hit=True) as sp:
                try:
                    blob = self.store.load(name)
                    fn = se.deserialize_and_load(
                        blob["payload"], blob["in_tree"], blob["out_tree"])
                except Exception as e:
                    # a corrupt/unreadable artifact (truncated blob, torn
                    # deploy, chaos "aot-load") degrades THIS row to the
                    # per-bucket trace fallback with the reason recorded;
                    # prewarm keeps going — an artifact set is allowed to
                    # be partially rotten without costing availability
                    LOG.warning("aot preload of %s failed; bucket falls "
                                "back to the trace path", name,
                                exc_info=True)
                    ok = False
                    reason = "%s: %s" % (type(e).__name__, e)
                    if sp is not None:
                        sp.args["hit"] = False
                        sp.args["reason"] = reason[:256]
                dt = time.time() - t0
                if sp is not None:
                    sp.args["seconds"] = round(dt, 4)
            if ok:
                with self._lock:
                    self._execs[key] = fn
                    self.loads += 1
                _note_resident_executable(row)
            else:
                with self._lock:
                    self._missing.add(key)
            entry = {"program": row.get("program"),
                     "variant": row.get("variant"),
                     "pod_bucket": row.get("pod_bucket"),
                     "seconds": round(dt, 4), "ok": ok}
            if reason is not None:
                entry["reason"] = reason
            report.append(entry)
        return report

    def _load(self, program: str, key: str, args: tuple):
        from .trace import flight_span
        with self._lock:
            row = self._rows_by_sig.get(key)
        bucket = pod_bucket_of(args)
        if row is None or not row.get("artifact"):
            with flight_span("aot-load", program=program, hit=False,
                             bucket=bucket):
                pass
            with self._lock:
                self._missing.add(key)
            return None
        t0 = time.time()
        with flight_span("aot-load", program=program, hit=True,
                         bucket=bucket) as sp:
            try:
                from jax.experimental import serialize_executable as se
                blob = self.store.load(row["artifact"])
                fn = se.deserialize_and_load(
                    blob["payload"], blob["in_tree"], blob["out_tree"])
            except Exception:
                LOG.warning("aot artifact %s unreadable; falling back",
                            row["artifact"], exc_info=True)
                if sp is not None:
                    sp.args["hit"] = False
                with self._lock:
                    self._missing.add(key)
                return None
            if sp is not None:
                sp.args["seconds"] = round(time.time() - t0, 4)
        with self._lock:
            self._execs[key] = fn
            self.loads += 1
        _note_resident_executable(row)
        return fn

    # ---- capture (build) side ------------------------------------------

    def capture_call(self, program: str, jitfn, args: tuple, kwargs: dict,
                     static_argnums: Tuple[int, ...] = (),
                     static_argnames: Tuple[str, ...] = (),
                     row_name: Optional[str] = None,
                     variant: Optional[str] = None) -> Optional[dict]:
        """Build-side capture WITHOUT execution (tools/kubeaot --build):
        lower + compile + serialize the normalized call form and register
        it, exactly as a capture-mode dispatch would — minus the call.
        ``row_name``/``variant`` override the index row id (the census
        build keys rows by COMPILE_MANIFEST row id so ci_lint.sh can
        compare the two key sets).  Returns the index row, or None when
        the capture failed (the variant stays on the trace path)."""
        try:
            key, _dyn_args, _dyn_kwargs, norm_kwargs, ssig = call_signature(
                program, jitfn, args, kwargs, static_argnums,
                static_argnames)
        except Exception:
            LOG.warning("aot signature failed for %s", program,
                        exc_info=True)
            return None
        with self._lock:
            if key in self._execs:
                return self._rows_by_sig.get(key)
        if self._capture(program, key, ssig, jitfn, args, norm_kwargs,
                         row_name=row_name, variant=variant) is None:
            return None
        with self._lock:
            return self._rows_by_sig.get(key)

    def _capture(self, program: str, key: str, ssig: str, jitfn,
                 args: tuple, norm_kwargs: dict,
                 row_name: Optional[str] = None,
                 variant: Optional[str] = None):
        """norm_kwargs is call_signature's NORMALIZED keyword dict — the
        lower below must see the exact call form serve-side dispatch will
        use, or the executable's input pytree check rejects the call."""
        import hashlib as _h
        try:
            from jax.experimental import serialize_executable as se
            lowered = jitfn.lower(*args, **norm_kwargs)
            sha = _h.sha256(lowered.as_text().encode()).hexdigest()
            compiled = lowered.compile()
            payload, in_tree, out_tree = se.serialize(compiled)
            # build-time round trip: an executable that came back as a
            # PERSISTENT-CACHE HIT serializes to a blob referencing JIT
            # symbols it does not carry (CPU deserialize fails with
            # "Symbols not found"), and a blob that cannot load is a
            # build failure NOW, not a silent trace-path fallback at
            # serve (tools/kubeaot captures under _fresh_compiles for
            # this reason)
            se.deserialize_and_load(payload, in_tree, out_tree)
        except Exception:
            LOG.warning("aot capture failed for %s; serving keeps the "
                        "trace path for this variant", program,
                        exc_info=True)
            with self._lock:
                self._missing.add(key)
            return None
        bucket = pod_bucket_of(args)
        name = self.store.artifact_name(program, sha, self.env)
        row = {"row": row_name or "serving:%s@b%s/%s" % (program,
                                                         bucket or 0, key),
               "family": self.family, "program": program,
               "variant": variant or "b%s" % (bucket or 0),
               "sig_key": key, "static_sig": ssig,
               "lowering_sha256": sha, "artifact": name,
               "pod_bucket": bucket}
        row["bytes"] = self.store.save(name, dict(row), payload, in_tree,
                                       out_tree)
        with self._lock:
            self._rows.append(row)
            self._rows_by_sig[key] = row
            self._execs[key] = compiled
            self.loads += 1
        return compiled

    def flush_index(self, extra_path: Optional[str] = None,
                    replace_family: Optional[str] = None) -> str:
        """Write (capture mode) or rewrite the store index, merging with
        any rows already on disk from a previous build.  The merge keys
        on ROW ID (unique per variant; serving rows embed their sig in
        the id), so a re-captured variant REPLACES its previous row — a
        call-form change must not leave the stale signature mapping
        behind, where it would cost a wasted deserialize + rejected call
        at serve.  ``replace_family``: drop ALL existing rows of that
        family first — build_census enumerates the census family
        exhaustively, so rows it did not re-capture are dead variants,
        not partial-build survivors."""
        merged: Dict[str, dict] = {}
        existing = self.store.read_index()
        if existing and (existing.get("env") or {}) == self.env:
            for r in existing.get("rows", []):
                if replace_family and r.get("family") == replace_family:
                    continue
                merged[r.get("row") or r.get("sig_key")] = r
        for r in self.rows():
            merged[r.get("row") or r.get("sig_key")] = r
        return self.store.write_index(self.env, list(merged.values()),
                                      extra_path=extra_path)


# ---------------------------------------------------------------- arming
#
# Same contract as trace.py's recorder arming: _active is read WITHOUT a
# lock on the hot path (rebinding a reference is atomic; a racing reader
# sees old or new), arm/disarm serialize through _active_lock.

_active: Optional[AotRuntime] = None
_active_lock = threading.Lock()
# why the runtime was last disarmed mid-run (the scheduler's
# dispatch-recovery AOT->trace demotion records its reason here so
# /debug and tests can see the ladder rung that fired); None = never
_demotion_reason: Optional[str] = None   # kubelint: guarded-by(_active_lock)


def active_runtime() -> Optional[AotRuntime]:
    return _active


def arm(runtime: AotRuntime) -> AotRuntime:
    global _active
    with _active_lock:
        _active = runtime
    return runtime


def disarm(reason: Optional[str] = None) -> None:
    """Disarm the runtime; a non-None reason marks this as a DEMOTION
    (AOT->trace, the self-healing ladder) rather than a clean teardown."""
    global _active, _demotion_reason
    with _active_lock:
        _active = None
        if reason is not None:
            _demotion_reason = reason


def demotion_reason() -> Optional[str]:
    with _active_lock:
        return _demotion_reason


def reset_demotion() -> None:
    """Clear the demotion latch (operator/test hook) so
    maybe_arm_from_env may arm again."""
    global _demotion_reason
    with _active_lock:
        _demotion_reason = None


def serve_runtime(root: str) -> AotRuntime:
    return AotRuntime(AotStore(root), mode="serve")


def capture_runtime(root: str) -> AotRuntime:
    return AotRuntime(AotStore(root), mode="capture")


def maybe_arm_from_env() -> Optional[AotRuntime]:
    """Scheduler-construction hook: arms the serve runtime iff
    KUBETPU_AOT_DIR names a directory with a readable, env-matching
    index.  Never raises — a bad artifact set must not block serving
    (the trace path still works); it logs and stays disarmed."""
    root = os.environ.get(DIR_ENV, "")
    if not root:
        return None
    if _active is not None:
        return _active
    if demotion_reason() is not None:
        # the self-healing ladder demoted AOT->trace in this process: a
        # later Scheduler construction must not silently re-arm the
        # artifact set that just faulted (explicit arm() still can,
        # reset_demotion() clears the latch)
        LOG.warning("AOT artifacts stay demoted (%s); serving the trace "
                    "path", demotion_reason())
        return None
    try:
        rt = serve_runtime(root)
    except Exception:  # pragma: no cover - index IO is already guarded
        LOG.warning("KUBETPU_AOT_DIR=%s unusable; serving without AOT "
                    "artifacts", root, exc_info=True)
        return None
    if rt.disabled_reason is not None:
        LOG.warning("AOT artifacts disabled: %s", rt.disabled_reason)
        return None
    return arm(rt)


def dispatch(program: str, jitfn, args: tuple, kwargs: dict,
             static_argnums: Tuple[int, ...] = (),
             static_argnames: Tuple[str, ...] = ()):
    """The seam entry: AOT-armed calls resolve against the artifact set,
    disarmed calls go straight to the jit (one attribute read of cost)."""
    rt = _active
    if rt is None:
        return jitfn(*args, **kwargs)
    return rt.dispatch(program, jitfn, args, kwargs,
                       static_argnums=static_argnums,
                       static_argnames=static_argnames)
