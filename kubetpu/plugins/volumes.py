"""Host-side volume plugin family.

These stay host plugins (not kernels) because they read/write API objects
(PVCs/PVs) and their per-pod work is small and gated on the pod actually
using volumes — mirroring where the reference put its complexity:
  VolumeBinding      reference: volumebinding/volume_binding.go +
                     pkg/controller/volume/scheduling (SchedulerVolumeBinder)
  VolumeRestrictions reference: volumerestrictions/volume_restrictions.go
  VolumeZone         reference: volumezone/volume_zone.go
  NodeVolumeLimits   reference: nodevolumelimits/{csi,non_csi}.go

The framework runner calls .relevant(pod) first and skips the whole plugin
for volume-less pods, so the TPU fast path is untouched.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Set, Tuple

from ..api import types as api
from ..framework import interface as fw
from ..framework.interface import CycleState, Status

ERR_REASON_BIND_CONFLICT = "node(s) didn't find available persistent volumes to bind"
ERR_REASON_NODE_CONFLICT = "node(s) had volume node affinity conflict"
ERR_REASON_DISK_CONFLICT = "node(s) had no available disk"
ERR_REASON_ZONE_CONFLICT = "node(s) had no available volume zone"
ERR_REASON_MAX_VOLUME_COUNT = "node(s) exceed max volume count"

# zone/region label keys checked by VolumeZone (reference: volume_zone.go:41)
_ZONE_KEYS = (api.LABEL_ZONE, api.LABEL_REGION, api.LABEL_ZONE_LEGACY,
              api.LABEL_REGION_LEGACY)


def _quantity_or_none(q) -> Optional[float]:
    """Parse a quantity, treating a malformed string as absent: one bad
    object in the store must degrade to an unconstrained match, not
    raise out of the per-cycle overlay build / commit-time re-check."""
    from ..api.resource import parse_quantity
    try:
        return float(parse_quantity(q))
    except ValueError:
        return None


def claim_storage_request(pvc: api.PersistentVolumeClaim) -> float:
    """Requested storage bytes (0 = unconstrained)."""
    q = pvc.resources.requests.get("storage")
    if not q:
        return 0.0
    return _quantity_or_none(q) or 0.0


def pv_satisfies_claim(pv: api.PersistentVolume,
                       pvc: api.PersistentVolumeClaim) -> bool:
    """Node-independent half of findMatchingVolume (reference:
    pkg/controller/volume/persistentvolume/pv_controller checkVolumeSatisfy
    ClaimSpec): same StorageClass, capacity >= the claim's storage
    request, and access modes a SUPERSET of the claim's.  A PV without a
    declared capacity is treated as unbounded and a claim without access
    modes as unconstrained (back-compat with minimal objects).  Shared by
    the host plugin's _find_matching_pv and the device overlay's
    matchable-PV pre-filter (state/volumes.py) so commit-time re-checks
    can never disagree with the device mask on this dimension."""
    if pv.storage_class_name != pvc.storage_class_name:
        return False
    want = claim_storage_request(pvc)
    if want > 0:
        cap = pv.capacity.get("storage")
        got = _quantity_or_none(cap) if cap is not None else None
        if got is not None and got < want:
            return False
    if pvc.access_modes and not set(pvc.access_modes) <= set(pv.access_modes):
        return False
    return True

class _VolumePlugin(fw.Plugin):
    def __init__(self, store=None):
        self.store = store

    def relevant(self, pod: api.Pod) -> bool:
        return bool(pod.spec.volumes)

    def _pvc(self, pod: api.Pod, claim: str) -> Optional[api.PersistentVolumeClaim]:
        if self.store is None:
            return None
        return self.store.get_pvc(pod.namespace, claim)

    def _pv(self, name: str) -> Optional[api.PersistentVolume]:
        if self.store is None or not name:
            return None
        return self.store.get_pv(name)


class VolumeBinding(_VolumePlugin, fw.PreFilterPlugin, fw.FilterPlugin,
                    fw.ReservePlugin, fw.UnreservePlugin, fw.PreBindPlugin,
                    fw.PostBindPlugin):
    """Delayed PVC binding (reference: volumebinding/volume_binding.go:223;
    FindPodVolumes/AssumePodVolumes/BindPodVolumes from
    pkg/controller/volume/scheduling/scheduler_binder.go)."""
    NAME = "VolumeBinding"
    STATE_KEY = "PreFilterVolumeBinding"

    def pre_filter(self, state: CycleState, pod: api.Pod) -> Status:
        # PVC existence is a basic check (reference:
        # generic_scheduler.go:1084 podPassesBasicChecks)
        for v in pod.spec.volumes:
            if v.persistent_volume_claim:
                pvc = self._pvc(pod, v.persistent_volume_claim)
                if pvc is None:
                    return Status.unresolvable(
                        f'persistentvolumeclaim "{v.persistent_volume_claim}" '
                        "not found")
                if pvc.metadata.deletion_timestamp is not None:
                    return Status.unresolvable(
                        f'persistentvolumeclaim "{v.persistent_volume_claim}" '
                        "is being deleted")
        return Status.success()

    def filter(self, state: CycleState, pod: api.Pod, node_info) -> Status:
        """FindPodVolumes (reference: scheduler_binder.go:220): bound PVCs
        must have node-compatible PVs; unbound PVCs must be matchable or
        provisionable on this node."""
        node = node_info.node
        for v in pod.spec.volumes:
            if not v.persistent_volume_claim:
                continue
            pvc = self._pvc(pod, v.persistent_volume_claim)
            if pvc is None:
                return Status.unresolvable("pvc not found")
            if pvc.volume_name:
                pv = self._pv(pvc.volume_name)
                if pv is None or not _pv_matches_node(pv, node):
                    return Status.unschedulable(ERR_REASON_NODE_CONFLICT)
            else:
                if not self._find_matching_pv(pvc, node) \
                        and not self._provisionable(pvc):
                    return Status.unschedulable(ERR_REASON_BIND_CONFLICT)
        return Status.success()

    def _find_matching_pv(self, pvc, node) -> Optional[api.PersistentVolume]:
        if self.store is None:
            return None
        for pv in self.store.list_pvs():
            if (pv_satisfies_claim(pv, pvc)
                    and _pv_matches_node(pv, node)
                    and not self.store.pv_is_bound(pv.metadata.name)):
                return pv
        return None

    def _provisionable(self, pvc) -> bool:
        if self.store is None:
            return False
        sc = self.store.get_storage_class(pvc.storage_class_name)
        return sc is not None and sc.volume_binding_mode == "WaitForFirstConsumer"

    def reserve(self, state: CycleState, pod: api.Pod, node_name: str) -> Status:
        """AssumePodVolumes: pick PVs for unbound claims and cache the
        decision for pre_bind (reference: volume_binding.go Reserve)."""
        decisions: List[Tuple[str, str]] = []  # (pvc name, pv name|"" provision)
        if self.store is not None:
            node = self.store.get_node(node_name)
            if node is None:
                # node deleted between snapshot and commit
                return Status.error(f"node {node_name} no longer exists")
            for v in pod.spec.volumes:
                if not v.persistent_volume_claim:
                    continue
                pvc = self._pvc(pod, v.persistent_volume_claim)
                if pvc is None:
                    return Status.error("pvc disappeared during reserve")
                if pvc.volume_name:
                    continue
                pv = self._find_matching_pv(pvc, node)
                if pv is not None:
                    self.store.assume_pv_binding(pv.metadata.name,
                                                 pvc.metadata.name)
                    decisions.append((pvc.metadata.name, pv.metadata.name))
                elif self._provisionable(pvc):
                    # delayed provisioning: record the claim so pre_bind can
                    # stamp the selected node (reference: scheduler_binder
                    # AssumePodVolumes provisioning decisions)
                    decisions.append((pvc.metadata.name, ""))
                else:
                    # the PV another batch pod just claimed is gone and the
                    # class can't provision: fail reserve -> requeue
                    # (reference: AssumePodVolumes error path)
                    for _, assumed_pv in decisions:
                        if assumed_pv:
                            self.store.forget_pv_binding(assumed_pv)
                    return Status.error(
                        f"no persistent volume available for claim "
                        f"{pvc.metadata.name} on {node_name}")
        state.write(self.STATE_KEY, decisions)
        return Status.success()

    def unreserve(self, state: CycleState, pod: api.Pod, node_name: str) -> None:
        try:
            decisions = state.read(self.STATE_KEY)
        except KeyError:
            return
        if self.store is not None:
            for _, pv_name in decisions:
                if pv_name:
                    self.store.forget_pv_binding(pv_name)
        state.delete(self.STATE_KEY)

    def pre_bind(self, state: CycleState, pod: api.Pod, node_name: str) -> Status:
        """BindPodVolumes: write the assumed bindings through the API
        (reference: volume_binding.go PreBind)."""
        try:
            decisions = state.read(self.STATE_KEY)
        except KeyError:
            return Status.success()
        if self.store is not None:
            for pvc_name, pv_name in decisions:
                try:
                    self.store.bind_pvc(pod.namespace, pvc_name, pv_name,
                                        node_name)
                except Exception as e:
                    return Status.error(f"binding volumes: {e}")
        return Status.success()

    def post_bind(self, state: CycleState, pod: api.Pod, node_name: str) -> None:
        state.delete(self.STATE_KEY)


class VolumeRestrictions(_VolumePlugin, fw.FilterPlugin):
    """Read-write conflict rules for GCE-PD / EBS / ISCSI / RBD
    (reference: volumerestrictions/volume_restrictions.go:134)."""
    NAME = "VolumeRestrictions"

    def relevant(self, pod: api.Pod) -> bool:
        return any(v.gce_persistent_disk or v.aws_elastic_block_store
                   or v.iscsi or v.rbd for v in pod.spec.volumes)

    def filter(self, state: CycleState, pod: api.Pod, node_info) -> Status:
        for v in pod.spec.volumes:
            for existing in node_info.pods:
                for ev in existing.pod.spec.volumes:
                    if _volume_conflict(v, ev):
                        return Status.unschedulable(ERR_REASON_DISK_CONFLICT)
        return Status.success()


def _volume_conflict(v: api.Volume, ev: api.Volume) -> bool:
    """reference: volume_restrictions.go:48 isVolumeConflict."""
    if v.gce_persistent_disk and ev.gce_persistent_disk:
        if (v.gce_persistent_disk == ev.gce_persistent_disk
                and not (v.read_only and ev.read_only)):
            return True
    if v.aws_elastic_block_store and ev.aws_elastic_block_store:
        if v.aws_elastic_block_store == ev.aws_elastic_block_store:
            return True
    if v.iscsi and ev.iscsi:
        if v.iscsi == ev.iscsi and not (v.read_only and ev.read_only):
            return True
    if v.rbd and ev.rbd:
        if v.rbd == ev.rbd and not (v.read_only and ev.read_only):
            return True
    return False


class VolumeZone(_VolumePlugin, fw.FilterPlugin):
    """Bound PV zone/region labels must match the node
    (reference: volumezone/volume_zone.go:185)."""
    NAME = "VolumeZone"

    def filter(self, state: CycleState, pod: api.Pod, node_info) -> Status:
        """reference: volume_zone.go:80 Filter — a node with NO zone labels
        always fits (fast path); an unbound claim is skipped only under a
        WaitForFirstConsumer class; zone/region mismatch is
        UnschedulableAndUnresolvable (no preemption can move a node's
        zone)."""
        if not pod.spec.volumes:
            return Status.success()
        node = node_info.node
        node_constraints = {k: v for k, v in node.metadata.labels.items()
                            if k in _ZONE_KEYS}
        if not node_constraints:
            return Status.success()
        for v in pod.spec.volumes:
            if not v.persistent_volume_claim:
                continue
            pvc = self._pvc(pod, v.persistent_volume_claim)
            if pvc is None:
                return Status.error("PersistentVolumeClaim was not found: "
                                    f"{v.persistent_volume_claim!r}")
            if not pvc.volume_name:
                sc = (self.store.get_storage_class(pvc.storage_class_name)
                      if self.store and pvc.storage_class_name else None)
                if sc is not None and \
                        sc.volume_binding_mode == "WaitForFirstConsumer":
                    continue   # unbound, delayed binding: skip
                return Status.error(
                    "PersistentVolumeClaim had no pv name and no "
                    "WaitForFirstConsumer storageClass")
            pv = self._pv(pvc.volume_name)
            if pv is None:
                return Status.error("PersistentVolume was not found: "
                                    f"{pvc.volume_name!r}")
            for key, want in pv.metadata.labels.items():
                if key not in _ZONE_KEYS:
                    continue
                # PV zone labels may hold a __ separated set
                allowed = set(want.split("__"))
                if node_constraints.get(key) not in allowed:
                    return Status.unresolvable(ERR_REASON_ZONE_CONFLICT)
        return Status.success()


class NodeVolumeLimits(_VolumePlugin, fw.FilterPlugin):
    """CSI attachable-volume count limits (reference: nodevolumelimits/
    csi.go:62 — CSIName == "NodeVolumeLimits").  Counts CSI-sourced
    volumes (PVC -> PV -> spec.csi) per driver against the node's CSINode
    allocatable; a driver with no CSINode entry has no limit (csi.go:263).
    In-tree sources are the per-driver plugins' job (EBSLimits etc.);
    CSI-migration double-counting translation is not implemented."""
    NAME = "NodeVolumeLimits"

    def relevant(self, pod: api.Pod) -> bool:
        return any(v.persistent_volume_claim for v in pod.spec.volumes)

    def filter(self, state: CycleState, pod: api.Pod, node_info) -> Status:
        new: Dict[str, Set[str]] = {}
        self._count_csi(pod, new)
        if not new:
            return Status.success()
        limits = self._node_limits(node_info)
        if not limits:
            return Status.success()
        counts: Dict[str, Set[str]] = {}
        for pi in node_info.pods:
            self._count_csi(pi.pod, counts)
        for driver, vols in new.items():
            limit = limits.get(driver)
            if limit is None:
                continue
            total = counts.get(driver, set()) | vols
            if len(total) > limit:
                return Status.unschedulable(ERR_REASON_MAX_VOLUME_COUNT)
        return Status.success()

    def _count_csi(self, pod: api.Pod, out: Dict[str, Set[str]]) -> None:
        """PVC -> PV -> csi source (reference: csi.go:180
        filterAttachableVolumes)."""
        for v in pod.spec.volumes:
            if not v.persistent_volume_claim:
                continue
            pvc = self._pvc(pod, v.persistent_volume_claim)
            pv = self._pv(pvc.volume_name) if pvc else None
            if pv is not None and pv.csi_driver:
                out.setdefault(pv.csi_driver, set()).add(
                    pv.csi_volume_handle or pv.metadata.name)

    def _node_limits(self, node_info) -> Dict[str, int]:
        if self.store is not None and node_info.node is not None:
            csinode = self.store.get_csinode(node_info.node.name)
            if csinode is not None:
                return dict(csinode.driver_allocatable)
        return {}


class _NonCSILimits(_VolumePlugin, fw.FilterPlugin):
    """One in-tree volume type's attachable count limit (reference:
    nodevolumelimits/non_csi.go:126 nonCSILimits + the four filter types).
    Limit resolution order (non_csi.go:310 getMaxVolLimit):
    node.status.allocatable[<attachable-volumes-key>] ->
    $KUBE_MAX_PD_VOLS -> the per-type default.  A PVC that cannot be
    resolved counts against the limit (non_csi.go:230 — unbound claims are
    assumed to need this type)."""
    NAME = ""
    LIMIT_KEY = ""       # volumeutil.*VolumeLimitKey
    DEFAULT_LIMIT = 0
    PROVISIONER = ""     # in-tree provisioner this filter owns

    def _source(self, v) -> Optional[str]:
        raise NotImplementedError

    def relevant(self, pod: api.Pod) -> bool:
        return any(self._source(v) or v.persistent_volume_claim
                   for v in pod.spec.volumes)

    def _match_provisioner(self, pvc: api.PersistentVolumeClaim) -> bool:
        """Does this PVC's StorageClass belong to the running filter?
        (reference: non_csi.go:328 matchProvisioner — nil StorageClassName
        or a missing class both mean NO)."""
        if not pvc.storage_class_name or self.store is None:
            return False
        sc = self.store.get_storage_class(pvc.storage_class_name)
        return sc is not None and sc.provisioner == self.PROVISIONER

    def _count(self, pod: api.Pod, out: Set[str]) -> None:
        """reference: non_csi.go:272 filterVolumes — an unresolvable PVC is
        counted ONLY when its StorageClass provisioner matches this filter's
        type; a PVC that cannot be looked up at all is never counted."""
        for v in pod.spec.volumes:
            src = self._source(v)
            if src:
                out.add(src)
                continue
            if not v.persistent_volume_claim:
                continue
            pvc = (self.store.get_pvc(pod.namespace,
                                      v.persistent_volume_claim)
                   if self.store else None)
            if pvc is None:
                # no guarantee the claim belongs to this predicate
                # (non_csi.go:287-291)
                continue
            pv_id = f"{pod.namespace}/{v.persistent_volume_claim}"
            if not pvc.volume_name:
                # unbound claim: counted iff its class provisions this type
                # (non_csi.go:294-303)
                if self._match_provisioner(pvc):
                    out.add(pv_id)
                continue
            pv = self._pv(pvc.volume_name)
            if pv is None:
                # bound to a deleted PV: same provisioner rule
                # (non_csi.go:306-314)
                if self._match_provisioner(pvc):
                    out.add(pv_id)
                continue
            src = self._source(pv)
            if src:
                out.add(src)

    def filter(self, state: CycleState, pod: api.Pod, node_info) -> Status:
        new: Set[str] = set()
        self._count(pod, new)
        if not new:
            return Status.success()
        used: Set[str] = set()
        for pi in node_info.pods:
            self._count(pi.pod, used)
        if len(used | new) > self._max_volumes(node_info):
            return Status.unschedulable(ERR_REASON_MAX_VOLUME_COUNT)
        return Status.success()

    def _max_volumes(self, node_info) -> int:
        import os
        node = node_info.node
        if node is not None and self.LIMIT_KEY in node.status.allocatable:
            try:
                return int(node.status.allocatable[self.LIMIT_KEY])
            except (TypeError, ValueError):
                pass
        env = os.environ.get("KUBE_MAX_PD_VOLS")
        if env:
            try:
                return int(env)
            except ValueError:
                pass
        return self._default_limit(node)

    def _default_limit(self, node) -> int:
        return self.DEFAULT_LIMIT


# reference: pkg/volume/util/attach_limit.go:30-37.  Go's
# regexp.MatchString is an unanchored SEARCH (only the first alternative
# carries an explicit ^) — compiled once, used with .search()
EBS_NITRO_LIMIT_REGEX = re.compile(r"^[cmr]5.*|t3|z1d")
DEFAULT_MAX_EBS_NITRO_VOLUME_LIMIT = 25
LABEL_INSTANCE_TYPE = "beta.kubernetes.io/instance-type"
LABEL_INSTANCE_TYPE_STABLE = "node.kubernetes.io/instance-type"


class EBSLimits(_NonCSILimits):
    """reference: non_csi.go:86 EBSName; default 39 (non_csi.go:41), 25 on
    Nitro instance types (non_csi.go:509 getMaxEBSVolume)."""
    NAME = "EBSLimits"
    LIMIT_KEY = "attachable-volumes-aws-ebs"
    DEFAULT_LIMIT = 39
    PROVISIONER = "kubernetes.io/aws-ebs"

    def _source(self, v):
        return v.aws_elastic_block_store

    def _default_limit(self, node) -> int:
        itype = ""
        if node is not None:
            labels = node.metadata.labels
            itype = (labels.get(LABEL_INSTANCE_TYPE)
                     or labels.get(LABEL_INSTANCE_TYPE_STABLE) or "")
        if itype and EBS_NITRO_LIMIT_REGEX.search(itype):
            return DEFAULT_MAX_EBS_NITRO_VOLUME_LIMIT
        return self.DEFAULT_LIMIT


class GCEPDLimits(_NonCSILimits):
    """reference: non_csi.go:95 GCEPDName; default 16 (non_csi.go:45)."""
    NAME = "GCEPDLimits"
    LIMIT_KEY = "attachable-volumes-gce-pd"
    DEFAULT_LIMIT = 16
    PROVISIONER = "kubernetes.io/gce-pd"

    def _source(self, v):
        return v.gce_persistent_disk


class AzureDiskLimits(_NonCSILimits):
    """reference: non_csi.go:68 AzureDiskName; default 16 (non_csi.go:49)."""
    NAME = "AzureDiskLimits"
    LIMIT_KEY = "attachable-volumes-azure-disk"
    DEFAULT_LIMIT = 16
    PROVISIONER = "kubernetes.io/azure-disk"

    def _source(self, v):
        return v.azure_disk


class CinderLimits(_NonCSILimits):
    """reference: non_csi.go:77 CinderName; default 256
    (volume_stats.go DefaultMaxCinderVolumes)."""
    NAME = "CinderLimits"
    LIMIT_KEY = "attachable-volumes-cinder"
    DEFAULT_LIMIT = 256
    PROVISIONER = "kubernetes.io/cinder"

    def _source(self, v):
        return v.cinder


def _pv_matches_node(pv: api.PersistentVolume, node: api.Node) -> bool:
    """PV .spec.nodeAffinity check (reference:
    pkg/volume/util.CheckNodeAffinity)."""
    if pv.node_affinity is None:
        return True
    labels = node.metadata.labels
    for term in pv.node_affinity.node_selector_terms:
        ok = True
        for req in term.match_expressions:
            val = labels.get(req.key)
            if req.operator == "In":
                ok = ok and val in req.values
            elif req.operator == "NotIn":
                # a node missing the key matches NotIn (reference:
                # apimachinery labels/selector.go Requirement.Matches rule 4)
                ok = ok and (val is None or val not in req.values)
            elif req.operator == "Exists":
                ok = ok and val is not None
            elif req.operator == "DoesNotExist":
                ok = ok and val is None
            else:
                ok = False
        if ok:
            return True
    return False
