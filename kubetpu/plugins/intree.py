"""In-tree plugin declarations + registry.

reference: pkg/scheduler/framework/plugins/registry.go:47-74 (NewInTreeRegistry)
and the per-plugin packages under pkg/scheduler/framework/plugins/.

Most plugins are *tensorized*: their Filter/Score algorithm lives in the
device kernels (kubetpu/ops/kernels.py) and the class here only declares
which kernels implement it, so the framework runner can route them into the
jitted program's ProgramConfig.  Genuinely host-side plugins (volume
binding's API writes, the binder) implement the Python methods.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

from ..api import types as api
from ..framework import interface as fw
from ..framework.interface import Status, TensorPlugin


class PrioritySort(fw.QueueSortPlugin):
    """reference: queuesort/priority_sort.go:40-45."""
    NAME = "PrioritySort"

    def less(self, a, b) -> bool:
        pa, pb = a.pod.priority(), b.pod.priority()
        if pa != pb:
            return pa > pb
        return a.timestamp < b.timestamp

    def sort_key(self, qp) -> tuple:
        return (-qp.pod.priority(), qp.timestamp)


class NodeResourcesFit(TensorPlugin, fw.PreFilterPlugin, fw.FilterPlugin):
    """reference: noderesources/fit.go."""
    NAME = "NodeResourcesFit"
    FILTER_KERNEL = "NodeResourcesFit"


class NodeResourcesLeastAllocated(TensorPlugin, fw.ScorePlugin):
    """reference: noderesources/least_allocated.go."""
    NAME = "NodeResourcesLeastAllocated"
    SCORE_KERNEL = "NodeResourcesLeastAllocated"


class NodeResourcesMostAllocated(TensorPlugin, fw.ScorePlugin):
    """reference: noderesources/most_allocated.go."""
    NAME = "NodeResourcesMostAllocated"
    SCORE_KERNEL = "NodeResourcesMostAllocated"


class NodeResourcesBalancedAllocation(TensorPlugin, fw.ScorePlugin):
    """reference: noderesources/balanced_allocation.go."""
    NAME = "NodeResourcesBalancedAllocation"
    SCORE_KERNEL = "NodeResourcesBalancedAllocation"


class NodeName(TensorPlugin, fw.FilterPlugin):
    """reference: nodename/node_name.go."""
    NAME = "NodeName"
    FILTER_KERNEL = "NodeName"


class NodePorts(TensorPlugin, fw.PreFilterPlugin, fw.FilterPlugin):
    """reference: nodeports/node_ports.go."""
    NAME = "NodePorts"
    FILTER_KERNEL = "NodePorts"


class NodeAffinity(TensorPlugin, fw.FilterPlugin, fw.ScorePlugin):
    """reference: nodeaffinity/node_affinity.go."""
    NAME = "NodeAffinity"
    FILTER_KERNEL = "NodeAffinity"
    SCORE_KERNEL = "NodeAffinity"


class NodeUnschedulable(TensorPlugin, fw.FilterPlugin):
    """reference: nodeunschedulable/node_unschedulable.go."""
    NAME = "NodeUnschedulable"
    FILTER_KERNEL = "NodeUnschedulable"


class NodePreferAvoidPods(TensorPlugin, fw.ScorePlugin):
    """reference: nodepreferavoidpods/node_prefer_avoid_pods.go."""
    NAME = "NodePreferAvoidPods"
    SCORE_KERNEL = "NodePreferAvoidPods"


class TaintToleration(TensorPlugin, fw.FilterPlugin, fw.PreScorePlugin,
                      fw.ScorePlugin):
    """reference: tainttoleration/taint_toleration.go."""
    NAME = "TaintToleration"
    FILTER_KERNEL = "TaintToleration"
    SCORE_KERNEL = "TaintToleration"


class InterPodAffinity(TensorPlugin, fw.PreFilterPlugin, fw.FilterPlugin,
                       fw.PreScorePlugin, fw.ScorePlugin):
    """reference: interpodaffinity/{plugin,filtering,scoring}.go."""
    NAME = "InterPodAffinity"
    FILTER_KERNEL = "InterPodAffinity"
    SCORE_KERNEL = "InterPodAffinity"

    def __init__(self, hard_pod_affinity_weight: int = 1):
        self.hard_pod_affinity_weight = hard_pod_affinity_weight


class PodTopologySpread(TensorPlugin, fw.PreFilterPlugin, fw.FilterPlugin,
                        fw.PreScorePlugin, fw.ScorePlugin):
    """reference: podtopologyspread/{plugin,filtering,scoring}.go."""
    NAME = "PodTopologySpread"
    FILTER_KERNEL = "PodTopologySpread"
    SCORE_KERNEL = "PodTopologySpread"


class DefaultPodTopologySpread(TensorPlugin, fw.PreScorePlugin, fw.ScorePlugin):
    """reference: defaultpodtopologyspread/default_pod_topology_spread.go."""
    NAME = "DefaultPodTopologySpread"
    SCORE_KERNEL = "DefaultPodTopologySpread"


class ImageLocality(TensorPlugin, fw.ScorePlugin):
    """reference: imagelocality/image_locality.go."""
    NAME = "ImageLocality"
    SCORE_KERNEL = "ImageLocality"


# ---------------------------------------------------------------------------
# host-side plugins (volume family is fleshed out in kubetpu/plugins/volumes.py)


class DefaultBinder(fw.BindPlugin):
    """POST pods/<name>/binding via the client (reference:
    defaultbinder/default_binder.go:50-61)."""
    NAME = "DefaultBinder"

    def __init__(self, client=None):
        self.client = client

    def bind(self, state, pod: api.Pod, node_name: str) -> Status:
        if self.client is None:
            return Status.error("DefaultBinder: no client configured")
        try:
            self.client.bind(pod, node_name)
        except Exception as e:  # bind failures feed the Forget/requeue path
            return Status.error(f"binding rejected: {e}")
        return Status.success()


# ---------------------------------------------------------------------------
# registry


Registry = Dict[str, Callable[..., fw.Plugin]]


def new_in_tree_registry() -> Registry:
    """reference: plugins/registry.go:47-74."""
    from . import volumes
    return {
        PrioritySort.NAME: lambda args=None, handle=None: PrioritySort(),
        NodeResourcesFit.NAME: lambda args=None, handle=None: NodeResourcesFit(),
        NodeResourcesLeastAllocated.NAME:
            lambda args=None, handle=None: NodeResourcesLeastAllocated(),
        NodeResourcesMostAllocated.NAME:
            lambda args=None, handle=None: NodeResourcesMostAllocated(),
        NodeResourcesBalancedAllocation.NAME:
            lambda args=None, handle=None: NodeResourcesBalancedAllocation(),
        NodeName.NAME: lambda args=None, handle=None: NodeName(),
        NodePorts.NAME: lambda args=None, handle=None: NodePorts(),
        NodeAffinity.NAME: lambda args=None, handle=None: NodeAffinity(),
        NodeUnschedulable.NAME: lambda args=None, handle=None: NodeUnschedulable(),
        NodePreferAvoidPods.NAME: lambda args=None, handle=None: NodePreferAvoidPods(),
        TaintToleration.NAME: lambda args=None, handle=None: TaintToleration(),
        InterPodAffinity.NAME: lambda args=None, handle=None: InterPodAffinity(
            hard_pod_affinity_weight=(args or {}).get("hardPodAffinityWeight", 1)),
        PodTopologySpread.NAME: lambda args=None, handle=None: PodTopologySpread(),
        DefaultPodTopologySpread.NAME:
            lambda args=None, handle=None: DefaultPodTopologySpread(),
        ImageLocality.NAME: lambda args=None, handle=None: ImageLocality(),
        DefaultBinder.NAME: lambda args=None, handle=None: DefaultBinder(
            client=handle.client if handle else None),
        volumes.VolumeBinding.NAME:
            lambda args=None, handle=None: volumes.VolumeBinding(
                store=handle.client if handle else None),
        volumes.VolumeRestrictions.NAME:
            lambda args=None, handle=None: volumes.VolumeRestrictions(
                store=handle.client if handle else None),
        volumes.VolumeZone.NAME:
            lambda args=None, handle=None: volumes.VolumeZone(
                store=handle.client if handle else None),
        volumes.NodeVolumeLimits.NAME:
            lambda args=None, handle=None: volumes.NodeVolumeLimits(
                store=handle.client if handle else None),
    }
