"""In-tree plugin declarations + registry.

reference: pkg/scheduler/framework/plugins/registry.go:47-74 (NewInTreeRegistry)
and the per-plugin packages under pkg/scheduler/framework/plugins/.

Most plugins are *tensorized*: their Filter/Score algorithm lives in the
device kernels (kubetpu/ops/kernels.py) and the class here only declares
which kernels implement it, so the framework runner can route them into the
jitted program's ProgramConfig.  Genuinely host-side plugins (volume
binding's API writes, the binder) implement the Python methods.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

from ..api import types as api
from ..framework import interface as fw
from ..framework.interface import Status, TensorPlugin
from ..ops import kernels as K
from ..utils import chaos


class PrioritySort(fw.QueueSortPlugin):
    """reference: queuesort/priority_sort.go:40-45."""
    NAME = "PrioritySort"

    def less(self, a, b) -> bool:
        pa, pb = a.pod.priority(), b.pod.priority()
        if pa != pb:
            return pa > pb
        return a.timestamp < b.timestamp

    def sort_key(self, qp) -> tuple:
        return (-qp.pod.priority(), qp.timestamp)


class NodeResourcesFit(TensorPlugin, fw.PreFilterPlugin, fw.FilterPlugin):
    """reference: noderesources/fit.go."""
    NAME = "NodeResourcesFit"
    FILTER_KERNEL = "NodeResourcesFit"


class NodeResourcesLeastAllocated(TensorPlugin, fw.ScorePlugin):
    """reference: noderesources/least_allocated.go."""
    NAME = "NodeResourcesLeastAllocated"
    SCORE_KERNEL = "NodeResourcesLeastAllocated"


class NodeResourcesMostAllocated(TensorPlugin, fw.ScorePlugin):
    """reference: noderesources/most_allocated.go."""
    NAME = "NodeResourcesMostAllocated"
    SCORE_KERNEL = "NodeResourcesMostAllocated"


class NodeResourcesBalancedAllocation(TensorPlugin, fw.ScorePlugin):
    """reference: noderesources/balanced_allocation.go."""
    NAME = "NodeResourcesBalancedAllocation"
    SCORE_KERNEL = "NodeResourcesBalancedAllocation"


class NodeName(TensorPlugin, fw.FilterPlugin):
    """reference: nodename/node_name.go."""
    NAME = "NodeName"
    FILTER_KERNEL = "NodeName"


class NodePorts(TensorPlugin, fw.PreFilterPlugin, fw.FilterPlugin):
    """reference: nodeports/node_ports.go."""
    NAME = "NodePorts"
    FILTER_KERNEL = "NodePorts"


class NodeAffinity(TensorPlugin, fw.FilterPlugin, fw.ScorePlugin):
    """reference: nodeaffinity/node_affinity.go."""
    NAME = "NodeAffinity"
    FILTER_KERNEL = "NodeAffinity"
    SCORE_KERNEL = "NodeAffinity"


class NodeUnschedulable(TensorPlugin, fw.FilterPlugin):
    """reference: nodeunschedulable/node_unschedulable.go."""
    NAME = "NodeUnschedulable"
    FILTER_KERNEL = "NodeUnschedulable"


class NodePreferAvoidPods(TensorPlugin, fw.ScorePlugin):
    """reference: nodepreferavoidpods/node_prefer_avoid_pods.go."""
    NAME = "NodePreferAvoidPods"
    SCORE_KERNEL = "NodePreferAvoidPods"


class TaintToleration(TensorPlugin, fw.FilterPlugin, fw.PreScorePlugin,
                      fw.ScorePlugin):
    """reference: tainttoleration/taint_toleration.go."""
    NAME = "TaintToleration"
    FILTER_KERNEL = "TaintToleration"
    SCORE_KERNEL = "TaintToleration"


class InterPodAffinity(TensorPlugin, fw.PreFilterPlugin, fw.FilterPlugin,
                       fw.PreScorePlugin, fw.ScorePlugin):
    """reference: interpodaffinity/{plugin,filtering,scoring}.go."""
    NAME = "InterPodAffinity"
    FILTER_KERNEL = "InterPodAffinity"
    SCORE_KERNEL = "InterPodAffinity"

    def __init__(self, hard_pod_affinity_weight: int = 1):
        self.hard_pod_affinity_weight = hard_pod_affinity_weight


class PodTopologySpread(TensorPlugin, fw.PreFilterPlugin, fw.FilterPlugin,
                        fw.PreScorePlugin, fw.ScorePlugin):
    """reference: podtopologyspread/{plugin,filtering,scoring}.go."""
    NAME = "PodTopologySpread"
    FILTER_KERNEL = "PodTopologySpread"
    SCORE_KERNEL = "PodTopologySpread"


class DefaultPodTopologySpread(TensorPlugin, fw.PreScorePlugin, fw.ScorePlugin):
    """reference: defaultpodtopologyspread/default_pod_topology_spread.go."""
    NAME = "DefaultPodTopologySpread"
    SCORE_KERNEL = "DefaultPodTopologySpread"


class ImageLocality(TensorPlugin, fw.ScorePlugin):
    """reference: imagelocality/image_locality.go."""
    NAME = "ImageLocality"
    SCORE_KERNEL = "ImageLocality"


class RequestedToCapacityRatio(TensorPlugin, fw.ScorePlugin):
    """User-shaped bin-packing scorer
    (reference: noderesources/requested_to_capacity_ratio.go)."""
    NAME = "RequestedToCapacityRatio"
    SCORE_KERNEL = "RequestedToCapacityRatio"

    def __init__(self, args=None):
        args = args or {}
        shape = args.get("shape") or [{"utilization": 0, "score": 0},
                                      {"utilization": 100, "score": 10}]
        # config scores live on the 0..MaxCustomPriorityScore(=10) scale;
        # the plugin rescales them to MaxNodeScore at construction
        # (reference: requested_to_capacity_ratio.go:60-66)
        scale = int(K.MAX_NODE_SCORE) // 10
        self.shape = tuple((int(p["utilization"]), int(p["score"]) * scale)
                           for p in shape)
        # weight 0 means "apply the default weight 1"
        # (requested_to_capacity_ratio.go:71-75)
        self.resources = [(r["name"], int(r.get("weight", 1)) or 1)
                          for r in args.get("resources")
                          or [{"name": "cpu", "weight": 1},
                              {"name": "memory", "weight": 1}]]

    def kernel_args(self, table) -> tuple:
        from ..state.tensors import N_FIXED_CHANNELS
        resolved = []
        for name, weight in self.resources:
            if name == "cpu":
                resolved.append((0, 0, weight))
            elif name == "memory":
                resolved.append((1, 0, weight))
            else:
                ch = table.rname.get(name)
                resolved.append((2, N_FIXED_CHANNELS + max(ch, 0), weight))
        return (self.shape, tuple(resolved))


class NodeResourceLimits(TensorPlugin, fw.PreScorePlugin, fw.ScorePlugin):
    """reference: noderesources/resource_limits.go."""
    NAME = "NodeResourceLimits"
    SCORE_KERNEL = "NodeResourceLimits"


class NodeLabel(TensorPlugin, fw.FilterPlugin, fw.ScorePlugin):
    """Configured label presence/absence (legacy)
    (reference: nodelabel/node_label.go)."""
    NAME = "NodeLabel"
    FILTER_KERNEL = "NodeLabel"
    SCORE_KERNEL = "NodeLabel"

    def __init__(self, args=None):
        args = args or {}
        self.present = list(args.get("presentLabels", []))
        self.absent = list(args.get("absentLabels", []))
        self.present_pref = list(args.get("presentLabelsPreference", []))
        self.absent_pref = list(args.get("absentLabelsPreference", []))

    def kernel_args(self, table) -> tuple:
        prefs = tuple([(table.key.get(l), True) for l in self.present_pref]
                      + [(table.key.get(l), False) for l in self.absent_pref])
        return (tuple(table.key.get(l) for l in self.present),
                tuple(table.key.get(l) for l in self.absent),
                prefs)


class ServiceAffinity(fw.PreFilterPlugin, fw.FilterPlugin, fw.ScorePlugin):
    """Legacy host plugin: co-locate a service's pods on nodes with equal
    values for the configured labels (reference:
    serviceaffinity/service_affinity.go:428).  Host-side because it is
    legacy, rarely enabled, and service-membership-driven."""
    NAME = "ServiceAffinity"
    STATE_KEY = "PreFilterServiceAffinity"

    def __init__(self, store=None, args=None):
        self.store = store
        args = args or {}
        self.affinity_labels = list(args.get("affinityLabels", []))
        self.antiaffinity_labels = list(
            args.get("antiAffinityLabelsPreference", []))

    def relevant(self, pod) -> bool:
        return bool(self.affinity_labels or self.antiaffinity_labels)

    def _matching_pods(self, pod):
        """Pods of the same service(s), cluster-wide, deduplicated across
        services (reference: service_affinity.go:169 createPreFilterState)."""
        if self.store is None:
            return []
        seen = set()
        out = []
        for svc in self.store.list("Service"):
            if svc.metadata.namespace != pod.namespace or not svc.selector:
                continue
            if all(pod.metadata.labels.get(k) == v
                   for k, v in svc.selector.items()):
                for other in self.store.list("Pod"):
                    if (other.uid not in seen
                            and other.namespace == pod.namespace
                            and other.spec.node_name
                            and all(other.metadata.labels.get(k) == v
                                    for k, v in svc.selector.items())):
                        seen.add(other.uid)
                        out.append(other)
        return out

    def pre_filter(self, state, pod) -> Status:
        state.write(self.STATE_KEY, self._matching_pods(pod))
        return Status.success()

    def filter(self, state, pod, node_info) -> Status:
        # reference: service_affinity.go:214 Filter — the node must carry the
        # same values for the affinity labels as the service's other pods'
        # nodes (derived from any one matching pod's node)
        if not self.affinity_labels:
            return Status.success()
        try:
            matching = state.read(self.STATE_KEY)
        except KeyError:
            matching = self._matching_pods(pod)
        node = node_info.node
        wanted = {}
        for other in matching:
            other_node = (self.store.get_node(other.spec.node_name)
                          if self.store else None)
            if other_node is None:
                continue
            for lab in self.affinity_labels:
                if lab in other_node.metadata.labels:
                    wanted[lab] = other_node.metadata.labels[lab]
        for lab, val in wanted.items():
            if node.metadata.labels.get(lab) != val:
                return Status.unschedulable(
                    "node(s) didn't match service affinity")
        return Status.success()

    SCORE_STATE_KEY = "ScoreServiceAffinity"

    def score(self, state, pod, node_name):
        """reference: service_affinity.go:269 Score — count of
        same-namespace, NON-TERMINATING pods on the node matching the
        FIRST matching service's selector (empty selector or no service
        scores 0).  The per-node counts are computed ONCE per pod and
        cached in CycleState: one store scan per scheduling attempt, O(1)
        per node after that."""
        try:
            counts = state.read(self.SCORE_STATE_KEY)
        except KeyError:
            counts = {}
            selector = None
            if self.store is not None:
                for svc in self.store.list("Service"):
                    if (svc.metadata.namespace == pod.namespace
                            and svc.selector
                            and all(pod.metadata.labels.get(k) == v
                                    for k, v in svc.selector.items())):
                        selector = dict(svc.selector)
                        break
            if selector:
                for other in self.store.list("Pod"):
                    if (other.namespace == pod.namespace
                            and other.spec.node_name
                            and other.metadata.deletion_timestamp is None
                            and all(other.metadata.labels.get(k) == v
                                    for k, v in selector.items())):
                        counts[other.spec.node_name] = \
                            counts.get(other.spec.node_name, 0) + 1
            state.write(self.SCORE_STATE_KEY, counts)
        return counts.get(node_name, 0), Status.success()

    def score_extensions(self):
        return self

    def normalize_score(self, state, pod, scores):
        """reference: service_affinity.go:305 NormalizeScore + :331
        updateNodeScoresForLabel — per anti-affinity label, a node's final
        score is MaxNodeScore x (fraction of service pods NOT sharing its
        label value), averaged over the configured labels; nodes missing a
        label contribute nothing for it (VERDICT r3 weak #7)."""
        reduced = {n: 0.0 for n, _ in scores}
        num_service_pods = sum(s for _, s in scores)
        for label in self.antiaffinity_labels:
            counts: Dict[str, float] = {}
            label_of: Dict[str, str] = {}
            for n, s in scores:
                node = self.store.get_node(n) if self.store else None
                if node is None or label not in node.metadata.labels:
                    continue
                v = node.metadata.labels[label]
                label_of[n] = v
                counts[v] = counts.get(v, 0.0) + s
            for n, _ in scores:
                v = label_of.get(n)
                if v is None:
                    continue
                f = float(fw.MAX_NODE_SCORE)
                if num_service_pods > 0:
                    f = (fw.MAX_NODE_SCORE
                         * (num_service_pods - counts[v]) / num_service_pods)
                reduced[n] += f / len(self.antiaffinity_labels)
        return ([(n, int(reduced[n])) for n, _ in scores],
                Status.success())


# ---------------------------------------------------------------------------
# host-side plugins (volume family is fleshed out in kubetpu/plugins/volumes.py)


class DefaultBinder(fw.BindPlugin):
    """POST pods/<name>/binding via the client (reference:
    defaultbinder/default_binder.go:50-61)."""
    NAME = "DefaultBinder"

    def __init__(self, client=None):
        self.client = client

    def bind(self, state, pod: api.Pod, node_name: str) -> Status:
        if self.client is None:
            return Status.error("DefaultBinder: no client configured")
        try:
            # chaos seam (utils/chaos.py "bind"): a transient binding
            # transport error, caught below like any real one — the
            # scheduler's bind retry ladder is what recovers it
            chaos.raise_or_stall("bind")
            self.client.bind(pod, node_name)
        except Exception as e:  # bind failures feed the Forget/requeue path
            return Status.error(f"binding rejected: {e}")
        return Status.success()


class DefaultPreemption(fw.PostFilterPlugin):
    """Preemption as the PostFilter extension point (the reference's TODO
    realized in later releases: defaultpreemption.DefaultPreemption; for
    this vintage the behavior lives in generic_scheduler.go:252 Preempt,
    invoked from scheduler.go:391).  The Preemptor instance is late-bound
    by the Scheduler after construction; the cycle's shared tensors arrive
    through CycleState under CYCLE_CONTEXT_KEY."""
    NAME = "DefaultPreemption"
    CYCLE_CONTEXT_KEY = "kubetpu.io/cycle-context"

    def __init__(self, handle=None):
        self.handle = handle
        self.preemptor = None   # set by Scheduler.__init__

    def name(self) -> str:
        return self.NAME

    def post_filter(self, state, pod, filtered_node_status):
        if self.preemptor is None:
            return None, Status.unschedulable("preemption disabled")
        try:
            cycle = state.read(self.CYCLE_CONTEXT_KEY)
        except KeyError:
            cycle = None
        nominated = self.preemptor.preempt(self.handle, state, pod,
                                           cycle=cycle)
        if nominated:
            return fw.PostFilterResult(nominated), Status.success()
        return None, Status.unschedulable(
            "preemption: 0/%d nodes are available" %
            len(filtered_node_status or {}))


# ---------------------------------------------------------------------------
# registry


Registry = Dict[str, Callable[..., fw.Plugin]]


def new_in_tree_registry() -> Registry:
    """reference: plugins/registry.go:47-74."""
    from . import volumes
    return {
        PrioritySort.NAME: lambda args=None, handle=None: PrioritySort(),
        DefaultPreemption.NAME:
            lambda args=None, handle=None: DefaultPreemption(handle=handle),
        NodeResourcesFit.NAME: lambda args=None, handle=None: NodeResourcesFit(),
        NodeResourcesLeastAllocated.NAME:
            lambda args=None, handle=None: NodeResourcesLeastAllocated(),
        NodeResourcesMostAllocated.NAME:
            lambda args=None, handle=None: NodeResourcesMostAllocated(),
        NodeResourcesBalancedAllocation.NAME:
            lambda args=None, handle=None: NodeResourcesBalancedAllocation(),
        NodeName.NAME: lambda args=None, handle=None: NodeName(),
        NodePorts.NAME: lambda args=None, handle=None: NodePorts(),
        NodeAffinity.NAME: lambda args=None, handle=None: NodeAffinity(),
        NodeUnschedulable.NAME: lambda args=None, handle=None: NodeUnschedulable(),
        NodePreferAvoidPods.NAME: lambda args=None, handle=None: NodePreferAvoidPods(),
        TaintToleration.NAME: lambda args=None, handle=None: TaintToleration(),
        InterPodAffinity.NAME: lambda args=None, handle=None: InterPodAffinity(
            hard_pod_affinity_weight=(args or {}).get("hardPodAffinityWeight", 1)),
        PodTopologySpread.NAME: lambda args=None, handle=None: PodTopologySpread(),
        DefaultPodTopologySpread.NAME:
            lambda args=None, handle=None: DefaultPodTopologySpread(),
        ImageLocality.NAME: lambda args=None, handle=None: ImageLocality(),
        RequestedToCapacityRatio.NAME:
            lambda args=None, handle=None: RequestedToCapacityRatio(args),
        NodeResourceLimits.NAME:
            lambda args=None, handle=None: NodeResourceLimits(),
        NodeLabel.NAME: lambda args=None, handle=None: NodeLabel(args),
        ServiceAffinity.NAME: lambda args=None, handle=None: ServiceAffinity(
            store=handle.client if handle else None, args=args),
        DefaultBinder.NAME: lambda args=None, handle=None: DefaultBinder(
            client=handle.client if handle else None),
        volumes.VolumeBinding.NAME:
            lambda args=None, handle=None: volumes.VolumeBinding(
                store=handle.client if handle else None),
        volumes.VolumeRestrictions.NAME:
            lambda args=None, handle=None: volumes.VolumeRestrictions(
                store=handle.client if handle else None),
        volumes.VolumeZone.NAME:
            lambda args=None, handle=None: volumes.VolumeZone(
                store=handle.client if handle else None),
        volumes.NodeVolumeLimits.NAME:
            lambda args=None, handle=None: volumes.NodeVolumeLimits(
                store=handle.client if handle else None),
        volumes.EBSLimits.NAME:
            lambda args=None, handle=None: volumes.EBSLimits(
                store=handle.client if handle else None),
        volumes.GCEPDLimits.NAME:
            lambda args=None, handle=None: volumes.GCEPDLimits(
                store=handle.client if handle else None),
        volumes.AzureDiskLimits.NAME:
            lambda args=None, handle=None: volumes.AzureDiskLimits(
                store=handle.client if handle else None),
        volumes.CinderLimits.NAME:
            lambda args=None, handle=None: volumes.CinderLimits(
                store=handle.client if handle else None),
    }
