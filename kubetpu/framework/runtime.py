"""Framework runner: the concrete plugin pipeline.

reference: pkg/scheduler/framework/v1alpha1/framework.go (NewFramework :205,
RunPreFilterPlugins :369, RunFilterPlugins :477, RunPreScorePlugins :543,
RunScorePlugins :579, RunReservePlugins, RunPermitPlugins :818,
RunBindPlugins :708, WaitOnPermit).

The TPU twist: enabled plugins are partitioned into *tensorized* plugins
(device kernels, collected into a ProgramConfig and executed for the whole
pod batch in one XLA program) and *host* plugins (Python methods, run only
when `relevant(pod)` — volumes, out-of-tree extensions).  The extension
points below therefore run ONLY host plugins; the tensor side's results
arrive as dense masks/scores from kubetpu/models/programs.py.  That keeps
the device fast path pure while preserving the reference's plugin contract
for everything else.
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..api import types as api
from ..apis.config import KubeSchedulerProfile, Plugins
from . import interface as fw
from .interface import Code, CycleState, Status, TensorPlugin, WaitingPod, WaitingPodsMap
from .provider import default_plugins

MAX_PERMIT_TIMEOUT = 600.0  # reference: interface.go maxTimeout 15min; we cap lower


def _status_label(result) -> str:
    """Status label for the extension-point histogram (reference:
    framework.go frameworkMetric status values)."""
    st = result[1] if isinstance(result, tuple) else result
    if st is None or st.is_success():
        return "Success"
    if st.code == Code.WAIT:
        return "Wait"
    return "Unschedulable" if st.is_unschedulable() else "Error"


def _timed_point(point: str):
    """Observe scheduler_framework_extension_point_duration_seconds for
    one host extension point (reference: framework.go:369,660,678,708,
    818 each wrap their run in metrics.ObserveExtensionPoint).  Only the
    per-pod-per-cycle points are instrumented — the per-(pod, node)
    Filter loop is deliberately unsampled (see utils/metrics.py note).
    Without a metrics registry the wrapper is one attribute read."""
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(self, *args, **kwargs):
            m = self.metrics
            if m is None:
                return fn(self, *args, **kwargs)
            t0 = time.time()
            result = fn(self, *args, **kwargs)
            m.framework_extension_point_duration.observe(
                time.time() - t0, point, _status_label(result))
            return result
        return wrapper
    return deco


class Framework:
    """One framework per profile (reference: framework.go:96 framework)."""

    def __init__(self, registry, profile: Optional[KubeSchedulerProfile] = None,
                 base_plugins: Optional[Plugins] = None, client=None,
                 nominator=None, metrics=None):
        self.client = client
        self.nominator = nominator
        self.metrics = metrics
        self.profile_name = profile.scheduler_name if profile else "default-scheduler"
        plugins = (base_plugins or default_plugins()).apply(
            profile.plugins if profile else None)
        self.plugins_config = plugins
        args = dict(profile.plugin_config) if profile else {}

        self._instances: Dict[str, fw.Plugin] = {}

        def instantiate(name: str) -> fw.Plugin:
            if name not in self._instances:
                factory = registry.get(name)
                if factory is None:
                    raise ValueError(f"plugin {name} not in registry")
                self._instances[name] = factory(args.get(name), self)
            return self._instances[name]

        def point(ps, iface) -> List[fw.Plugin]:
            out = []
            for p in ps.enabled:
                inst = instantiate(p.name)
                if not isinstance(inst, iface):
                    raise ValueError(
                        f"plugin {p.name} does not implement {iface.__name__}")
                out.append(inst)
            return out

        self.queue_sort_plugins = point(plugins.queue_sort, fw.QueueSortPlugin)
        self.pre_filter_plugins = point(plugins.pre_filter, fw.PreFilterPlugin)
        self.filter_plugins = point(plugins.filter, fw.FilterPlugin)
        self.post_filter_plugins = point(plugins.post_filter,
                                         fw.PostFilterPlugin)
        self.pre_score_plugins = point(plugins.pre_score, fw.PreScorePlugin)
        self.score_plugins = point(plugins.score, fw.ScorePlugin)
        self.score_weights = {p.name: p.weight or 1
                              for p in plugins.score.enabled}
        self.reserve_plugins = point(plugins.reserve, fw.ReservePlugin)
        self.permit_plugins = point(plugins.permit, fw.PermitPlugin)
        self.pre_bind_plugins = point(plugins.pre_bind, fw.PreBindPlugin)
        self.bind_plugins = point(plugins.bind, fw.BindPlugin)
        self.post_bind_plugins = point(plugins.post_bind, fw.PostBindPlugin)
        self.unreserve_plugins = point(plugins.unreserve, fw.UnreservePlugin)
        self.waiting_pods = WaitingPodsMap()

        # -- tensor/host partition ------------------------------------------
        self.tensor_filters: Tuple[str, ...] = tuple(
            p.FILTER_KERNEL for p in self.filter_plugins
            if isinstance(p, TensorPlugin) and p.FILTER_KERNEL)
        self.tensor_scores: Tuple[Tuple[str, int], ...] = tuple(
            (p.SCORE_KERNEL, self.score_weights[p.name()])
            for p in self.score_plugins
            if isinstance(p, TensorPlugin) and p.SCORE_KERNEL)
        self.host_filter_plugins = [
            p for p in self.filter_plugins
            if not (isinstance(p, TensorPlugin) and p.FILTER_KERNEL)]
        self.host_score_plugins = [
            p for p in self.score_plugins
            if not (isinstance(p, TensorPlugin) and p.SCORE_KERNEL)]
        self.host_pre_filter_plugins = [
            p for p in self.pre_filter_plugins
            if not isinstance(p, TensorPlugin)]
        self.host_pre_score_plugins = [
            p for p in self.pre_score_plugins
            if not isinstance(p, TensorPlugin)]
        ipa = self._instances.get("InterPodAffinity")
        self.hard_pod_affinity_weight = getattr(
            ipa, "hard_pod_affinity_weight", 1)

    def tensor_plugin_args(self, table) -> Tuple[Tuple[str, Tuple], ...]:
        """Resolve per-plugin static kernel args against the intern table
        (e.g. NodeLabel key ids, RequestedToCapacityRatio shape)."""
        out = []
        for name, inst in self._instances.items():
            ka = getattr(inst, "kernel_args", None)
            if ka is not None and isinstance(inst, TensorPlugin):
                out.append((name, ka(table)))
        return tuple(out)

    def queue_sort_less(self, a, b) -> bool:
        # reference: framework.go:358 QueueSortFunc (exactly one plugin)
        return self.queue_sort_plugins[0].less(a, b)

    def queue_sort_key(self, qp) -> tuple:
        return self.queue_sort_plugins[0].sort_key(qp)

    @staticmethod
    def _relevant(plugin, pod) -> bool:
        rel = getattr(plugin, "relevant", None)
        return rel(pod) if rel is not None else True

    # -- extension points (host plugins only; see module docstring) ---------

    @_timed_point("PreFilter")
    def run_pre_filter_plugins(self, state: CycleState, pod: api.Pod) -> Status:
        # reference: framework.go:369
        for p in self.host_pre_filter_plugins:
            if not self._relevant(p, pod):
                continue
            st = p.pre_filter(state, pod)
            if not st.is_success():
                if st.is_unschedulable():
                    return st
                return Status.error(
                    f'error while running "{p.name()}" prefilter plugin for '
                    f'pod "{pod.metadata.name}": {st.message()}')
        return Status.success()

    def run_filter_plugins(self, state: CycleState, pod: api.Pod,
                           node_info) -> Status:
        """Host filters for one node (reference: framework.go:477); the
        tensor filters already produced the dense feasibility mask."""
        for p in self.host_filter_plugins:
            if not self._relevant(p, pod):
                continue
            st = p.filter(state, pod, node_info)
            if not st.is_success():
                if not st.is_unschedulable():
                    return Status.error(st.message() or p.name())
                if not st.reasons:
                    st.reasons = [f"filter plugin {p.name()} failed"]
                return st
        return Status.success()

    def has_relevant_host_filters(self, pod: api.Pod,
                                  exclude=frozenset()) -> bool:
        """exclude: plugin names whose verdicts something else already
        covers (the scheduler's device-side volume mask passes the covered
        set so fully-covered pods skip the per-node Python filter loop)."""
        return any(self._relevant(p, pod) for p in self.host_filter_plugins
                   if p.name() not in exclude)

    def run_pre_score_plugins(self, state: CycleState, pod: api.Pod,
                              nodes: List[api.Node]) -> Status:
        for p in self.host_pre_score_plugins:
            if not self._relevant(p, pod):
                continue
            st = p.pre_score(state, pod, nodes)
            if not st.is_success():
                return Status.error(
                    f'error while running "{p.name()}" prescore plugin: '
                    f'{st.message()}')
        return Status.success()

    def run_host_score_plugins(self, state: CycleState, pod: api.Pod,
                               node_names: List[str]) -> Dict[str, List[int]]:
        """Host scores per node (reference: framework.go:579 RunScorePlugins
        with NormalizeScore :613 and weights :633).  Returns weighted
        per-plugin score lists aligned with node_names."""
        out: Dict[str, List[int]] = {}
        for p in self.host_score_plugins:
            if not self._relevant(p, pod):
                continue
            scores = []
            for name in node_names:
                s, st = p.score(state, pod, name)
                if not st.is_success():
                    raise RuntimeError(
                        f"score plugin {p.name()}: {st.message()}")
                scores.append((name, s))
            ext = p.score_extensions()
            if ext is not None:
                scores, st = ext.normalize_score(state, pod, scores)
                if not st.is_success():
                    raise RuntimeError(
                        f"normalize {p.name()}: {st.message()}")
            w = self.score_weights.get(p.name(), 1)
            out[p.name()] = [s * w for _, s in scores]
        return out

    @_timed_point("Reserve")
    def run_reserve_plugins(self, state: CycleState, pod: api.Pod,
                            node_name: str) -> Status:
        # reference: framework.go:660
        for p in self.reserve_plugins:
            if not self._relevant(p, pod):
                continue
            st = p.reserve(state, pod, node_name)
            if not st.is_success():
                return Status.error(
                    f'error while running "{p.name()}" reserve plugin: '
                    f'{st.message()}')
        return Status.success()

    def run_unreserve_plugins(self, state: CycleState, pod: api.Pod,
                              node_name: str) -> None:
        for p in self.unreserve_plugins:
            if self._relevant(p, pod):
                p.unreserve(state, pod, node_name)

    @_timed_point("Permit")
    def run_permit_plugins(self, state: CycleState, pod: api.Pod,
                           node_name: str) -> Status:
        """reference: framework.go:818 — collects Wait verdicts into a
        WaitingPod with per-plugin timeouts."""
        plugin_timeouts: Dict[str, float] = {}
        status_code = Code.SUCCESS
        for p in self.permit_plugins:
            if not self._relevant(p, pod):
                continue
            st, timeout = p.permit(state, pod, node_name)
            if st.is_success():
                continue
            if st.is_unschedulable():
                return st
            if st.code == Code.WAIT:
                plugin_timeouts[p.name()] = min(timeout, MAX_PERMIT_TIMEOUT)
                status_code = Code.WAIT
            else:
                return Status.error(
                    f'error while running "{p.name()}" permit plugin: '
                    f'{st.message()}')
        if status_code == Code.WAIT:
            wp = WaitingPod(pod, plugin_timeouts)
            self.waiting_pods.add(wp)
            return Status(Code.WAIT)
        return Status.success()

    def wait_on_permit(self, pod: api.Pod) -> Status:
        # reference: framework.go:775 WaitOnPermit — the permit-wait
        # histogram is observed only for pods that actually entered a
        # Wait (result: allowed/rejected, matching the reference labels)
        wp = self.waiting_pods.get(pod.uid)
        if wp is None:
            return Status.success()
        t0 = time.time()
        try:
            st = wp.wait()
        finally:
            self.waiting_pods.remove(pod.uid)
        if self.metrics is not None:
            self.metrics.permit_wait_duration.observe(
                time.time() - t0,
                "allowed" if st.is_success() else "rejected")
        return st

    @_timed_point("PreBind")
    def run_pre_bind_plugins(self, state: CycleState, pod: api.Pod,
                             node_name: str) -> Status:
        # reference: framework.go:678
        for p in self.pre_bind_plugins:
            if not self._relevant(p, pod):
                continue
            st = p.pre_bind(state, pod, node_name)
            if not st.is_success():
                return Status.error(
                    f'error while running "{p.name()}" prebind plugin: '
                    f'{st.message()}')
        return Status.success()

    @_timed_point("PostFilter")
    def run_post_filter_plugins(self, state: CycleState, pod: api.Pod,
                                filtered_node_status=None):
        """reference: framework.go:514 RunPostFilterPlugins — run until the
        first SUCCESS or error; UNSCHEDULABLE statuses accumulate.  Returns
        (PostFilterResult or None, Status)."""
        reasons: List[str] = []
        for p in self.post_filter_plugins:
            r, st = p.post_filter(state, pod, filtered_node_status or {})
            if st.is_success():
                return r, st
            if not st.is_unschedulable():
                return None, Status.error(
                    f'error while running "{p.name()}" postfilter plugin: '
                    f'{st.message()}')
            reasons.extend(st.reasons)
        return None, Status(Code.UNSCHEDULABLE, reasons)

    @_timed_point("Bind")
    def run_bind_plugins(self, state: CycleState, pod: api.Pod,
                         node_name: str) -> Status:
        # reference: framework.go:708 — SKIP falls through to the next binder
        if not self.bind_plugins:
            return Status.error("no bind plugin configured")
        for p in self.bind_plugins:
            st = p.bind(state, pod, node_name)
            if st.code == Code.SKIP:
                continue
            return st
        return Status(Code.SKIP, [
            f"all bind plugins skipped binding pod "
            f"{pod.namespace}/{pod.metadata.name}"])

    @_timed_point("PostBind")
    def run_post_bind_plugins(self, state: CycleState, pod: api.Pod,
                              node_name: str) -> None:
        for p in self.post_bind_plugins:
            if self._relevant(p, pod):
                p.post_bind(state, pod, node_name)

    # -- FrameworkHandle surface (reference: interface.go:493) --------------

    def get_waiting_pod(self, uid: str):
        return self.waiting_pods.get(uid)

    def reject_waiting_pod(self, uid: str) -> None:
        wp = self.waiting_pods.get(uid)
        if wp is not None:
            wp.reject("removed")

    def iterate_over_waiting_pods(self, fn) -> None:
        self.waiting_pods.iterate(fn)
