"""Algorithm providers: the default enabled-plugin matrix.

reference: pkg/scheduler/algorithmprovider/registry.go — getDefaultConfig
:77-160 (plugin sets + weights), NewRegistry :60 (DefaultProvider and
ClusterAutoscalerProvider, which swaps LeastAllocated for MostAllocated).
"""

from __future__ import annotations

from ..apis.config import Plugin, PluginSet, Plugins

DEFAULT_PROVIDER = "DefaultProvider"
CLUSTER_AUTOSCALER_PROVIDER = "ClusterAutoscalerProvider"


def default_plugins() -> Plugins:
    """reference: algorithmprovider/registry.go:77-160."""
    return Plugins(
        queue_sort=PluginSet(enabled=[Plugin("PrioritySort")]),
        pre_filter=PluginSet(enabled=[
            Plugin("NodeResourcesFit"),
            Plugin("NodePorts"),
            Plugin("PodTopologySpread"),
            Plugin("InterPodAffinity"),
            Plugin("VolumeBinding"),
        ]),
        filter=PluginSet(enabled=[
            Plugin("NodeUnschedulable"),
            Plugin("NodeResourcesFit"),
            Plugin("NodeName"),
            Plugin("NodePorts"),
            Plugin("NodeAffinity"),
            Plugin("VolumeRestrictions"),
            Plugin("TaintToleration"),
            Plugin("EBSLimits"),
            Plugin("GCEPDLimits"),
            Plugin("NodeVolumeLimits"),
            Plugin("AzureDiskLimits"),
            Plugin("VolumeBinding"),
            Plugin("VolumeZone"),
            Plugin("PodTopologySpread"),
            Plugin("InterPodAffinity"),
        ]),
        post_filter=PluginSet(enabled=[
            Plugin("DefaultPreemption"),
        ]),
        pre_score=PluginSet(enabled=[
            Plugin("InterPodAffinity"),
            Plugin("DefaultPodTopologySpread"),
            Plugin("PodTopologySpread"),
            Plugin("TaintToleration"),
        ]),
        score=PluginSet(enabled=[
            Plugin("NodeResourcesBalancedAllocation", weight=1),
            Plugin("ImageLocality", weight=1),
            Plugin("InterPodAffinity", weight=1),
            Plugin("NodeResourcesLeastAllocated", weight=1),
            Plugin("NodeAffinity", weight=1),
            Plugin("NodePreferAvoidPods", weight=10000),
            Plugin("PodTopologySpread", weight=2),
            Plugin("DefaultPodTopologySpread", weight=1),
            Plugin("TaintToleration", weight=1),
        ]),
        reserve=PluginSet(enabled=[Plugin("VolumeBinding")]),
        unreserve=PluginSet(enabled=[Plugin("VolumeBinding")]),
        pre_bind=PluginSet(enabled=[Plugin("VolumeBinding")]),
        post_bind=PluginSet(enabled=[Plugin("VolumeBinding")]),
        bind=PluginSet(enabled=[Plugin("DefaultBinder")]),
    )


def cluster_autoscaler_plugins() -> Plugins:
    """reference: algorithmprovider/registry.go:49 (applyFeatureGates /
    ClusterAutoscalerProvider): MostAllocated replaces LeastAllocated."""
    p = default_plugins()
    p.score.enabled = [
        Plugin("NodeResourcesMostAllocated", weight=1)
        if pl.name == "NodeResourcesLeastAllocated" else pl
        for pl in p.score.enabled]
    return p


PROVIDERS = {
    DEFAULT_PROVIDER: default_plugins,
    CLUSTER_AUTOSCALER_PROVIDER: cluster_autoscaler_plugins,
}
