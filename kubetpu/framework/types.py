"""Scheduler data model: NodeInfo, PodInfo, QueuedPodInfo.

reference: pkg/scheduler/framework/v1alpha1/types.go (NodeInfo :171,
Resource :262, PodInfo :70, QueuedPodInfo :43, AffinityTerm :79).

NodeInfo is the host-side aggregated per-node state, updated incrementally
by the scheduler cache with a monotonically increasing Generation used for
incremental snapshotting (reference: types.go:208).  The tensor snapshot
(kubetpu/state/tensors.py) is built *from* NodeInfos, row-per-node.
"""

from __future__ import annotations

import itertools
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ..api import types as api
from ..utils.trace import wallclock
from ..api.resource import (DEFAULT_MEMORY_REQUEST, DEFAULT_MILLI_CPU_REQUEST,
                            Resource)

_generation = itertools.count(1)


def next_generation() -> int:
    # reference: types.go:160 (nextGeneration)
    return next(_generation)


# ---------------------------------------------------------------------------
# pod resource requests


def compute_pod_resource_request(pod: api.Pod) -> Resource:
    """requests = max(sum(app containers), max(init containers)) + overhead.

    reference: pkg/scheduler/framework/plugins/noderesources/fit.go:112-129
    (computePodResourceRequest) and types.go:432 (calculateResource).
    """
    r = Resource()
    for c in pod.spec.containers:
        r.add_resource_list(c.resources.requests)
    for ic in pod.spec.init_containers:
        r.set_max(ic.resources.requests)
    if pod.spec.overhead:
        r.add_resource_list(pod.spec.overhead)
    return r


def compute_pod_resource_limits(pod: api.Pod) -> Resource:
    """Same shape as requests but over .limits
    (reference: noderesources/resource_limits.go getResourceLimits)."""
    r = Resource()
    for c in pod.spec.containers:
        r.add_resource_list(c.resources.limits)
    for ic in pod.spec.init_containers:
        r.set_max(ic.resources.limits)
    return r


def non_zero_request(pod: api.Pod) -> Tuple[int, int]:
    """(milli_cpu, memory) where each *container* with an UNSET request is
    defaulted to 100m / 200MB — "override if un-set, but not if explicitly
    set to zero" — aggregated with the same max(sum(containers), init) +
    overhead rule.

    reference: pkg/scheduler/util/non_zero.go:50-63
    (GetNonzeroRequestForResource, applied per container in
    types.go:432 calculateResource and
    noderesources/resource_allocation.go:118 calculatePodResourceRequest).
    """
    from ..api.resource import to_int, to_milli

    def one(requests):
        c = (to_milli(requests["cpu"]) if "cpu" in requests
             else DEFAULT_MILLI_CPU_REQUEST)
        m = (to_int(requests["memory"]) if "memory" in requests
             else DEFAULT_MEMORY_REQUEST)
        return c, m

    cpu = mem = 0
    for c in pod.spec.containers:
        ccpu, cmem = one(c.resources.requests)
        cpu += ccpu
        mem += cmem
    for ic in pod.spec.init_containers:
        ccpu, cmem = one(ic.resources.requests)
        cpu = max(cpu, ccpu)
        mem = max(mem, cmem)
    if pod.spec.overhead:
        cpu += to_milli(pod.spec.overhead.get("cpu", 0))
        mem += to_int(pod.spec.overhead.get("memory", 0))
    return cpu, mem


# ---------------------------------------------------------------------------
# pre-parsed pod info


@dataclass
class AffinityTerm:
    """A pre-processed pod affinity term.
    reference: types.go:79 (AffinityTerm)."""
    selector: api.LabelSelector
    namespaces: Set[str]
    topology_key: str

    def matches(self, pod: api.Pod) -> bool:
        return (pod.namespace in self.namespaces
                and self.selector.matches(pod.metadata.labels))


@dataclass
class WeightedAffinityTerm:
    term: AffinityTerm
    weight: int


def _get_affinity_terms(pod: api.Pod,
                        terms: List[api.PodAffinityTerm]) -> List[AffinityTerm]:
    # reference: types.go:96 (getAffinityTerms / newAffinityTerm)
    out = []
    for t in terms:
        ns = set(t.namespaces) if t.namespaces else {pod.namespace}
        sel = t.label_selector or api.LabelSelector()
        out.append(AffinityTerm(selector=sel, namespaces=ns, topology_key=t.topology_key))
    return out


def _get_weighted_terms(pod: api.Pod,
                        terms: List[api.WeightedPodAffinityTerm]) -> List[WeightedAffinityTerm]:
    out = []
    for wt in terms:
        at = _get_affinity_terms(pod, [wt.pod_affinity_term])[0]
        out.append(WeightedAffinityTerm(term=at, weight=wt.weight))
    return out


class PodInfo:
    """Pod wrapper with pre-computed affinity terms and resource vectors.
    reference: types.go:70 (PodInfo)."""

    __slots__ = ("pod", "required_affinity_terms", "required_anti_affinity_terms",
                 "preferred_affinity_terms", "preferred_anti_affinity_terms",
                 "resource", "non_zero_cpu", "non_zero_mem")

    def __init__(self, pod: api.Pod):
        self.pod = pod
        aff = pod.spec.affinity
        self.required_affinity_terms: List[AffinityTerm] = []
        self.required_anti_affinity_terms: List[AffinityTerm] = []
        self.preferred_affinity_terms: List[WeightedAffinityTerm] = []
        self.preferred_anti_affinity_terms: List[WeightedAffinityTerm] = []
        if aff is not None:
            if aff.pod_affinity is not None:
                self.required_affinity_terms = _get_affinity_terms(
                    pod, aff.pod_affinity.required_during_scheduling_ignored_during_execution)
                self.preferred_affinity_terms = _get_weighted_terms(
                    pod, aff.pod_affinity.preferred_during_scheduling_ignored_during_execution)
            if aff.pod_anti_affinity is not None:
                self.required_anti_affinity_terms = _get_affinity_terms(
                    pod, aff.pod_anti_affinity.required_during_scheduling_ignored_during_execution)
                self.preferred_anti_affinity_terms = _get_weighted_terms(
                    pod, aff.pod_anti_affinity.preferred_during_scheduling_ignored_during_execution)
        self.resource = compute_pod_resource_request(pod)
        self.non_zero_cpu, self.non_zero_mem = non_zero_request(pod)

    def with_pod(self, pod: api.Pod) -> "PodInfo":
        """Rewrap a pod object that shares this one's parsed spec content
        (e.g. the scheduler's assumed shallow-copy with node_name set) —
        shares the parsed terms/resources instead of re-parsing.  Term and
        resource parsing dominates PodInfo cost (quantity parsing is
        string work), and the commit path would otherwise re-do it for
        every scheduled pod."""
        pi = PodInfo.__new__(PodInfo)
        pi.pod = pod
        pi.required_affinity_terms = self.required_affinity_terms
        pi.required_anti_affinity_terms = self.required_anti_affinity_terms
        pi.preferred_affinity_terms = self.preferred_affinity_terms
        pi.preferred_anti_affinity_terms = self.preferred_anti_affinity_terms
        pi.resource = self.resource
        pi.non_zero_cpu = self.non_zero_cpu
        pi.non_zero_mem = self.non_zero_mem
        return pi


@dataclass
class QueuedPodInfo:
    """Queue bookkeeping for a pending pod.
    reference: types.go:43 (QueuedPodInfo)."""
    pod: api.Pod
    # wallclock (utils/trace.py), not time.time: these stamps anchor the
    # SLO layer's queue_wait/backoff/e2e durations against scheduler-side
    # wallclock stamps — the whole domain must share the monotonic clock
    timestamp: float = field(default_factory=wallclock)
    attempts: int = 0
    initial_attempt_timestamp: float = field(default_factory=wallclock)
    # queue.scheduling_cycle captured when this pod was popped (reference:
    # scheduler.go:515 podSchedulingCycle := SchedulingQueue.SchedulingCycle()
    # is read at pop time, not at failure time)
    scheduling_cycle: int = 0
    # when the pod was popped into its current cycle — stamped by the
    # queue ONLY while the SLO tracker (utils/slo.py) is armed; 0.0 means
    # "never stamped" and the SLO layer skips the pod
    pop_timestamp: float = 0.0
    # the SLO layer already recorded an "unresolvable" vector for this
    # pod: requeued pods retry, and re-recording every failing cycle
    # would multi-count the pod in the sketches (a later successful bind
    # still records its own "bound" vector)
    slo_unres_observed: bool = False

    def deep_copy(self) -> "QueuedPodInfo":
        return QueuedPodInfo(pod=self.pod, timestamp=self.timestamp,
                             attempts=self.attempts,
                             initial_attempt_timestamp=self.initial_attempt_timestamp,
                             scheduling_cycle=self.scheduling_cycle,
                             pop_timestamp=self.pop_timestamp,
                             slo_unres_observed=self.slo_unres_observed)


# ---------------------------------------------------------------------------
# NodeInfo


def pod_with_affinity(pod: api.Pod) -> bool:
    # reference: types.go:492 (podWithAffinity)
    a = pod.spec.affinity
    return a is not None and (a.pod_affinity is not None or a.pod_anti_affinity is not None)


def pod_with_required_anti_affinity(pod: api.Pod) -> bool:
    a = pod.spec.affinity
    return (a is not None and a.pod_anti_affinity is not None
            and bool(a.pod_anti_affinity.required_during_scheduling_ignored_during_execution))


class NodeInfo:
    """Aggregated per-node scheduling state.
    reference: types.go:171 (NodeInfo)."""

    __slots__ = ("node", "pods", "pods_with_affinity", "pods_with_required_anti_affinity",
                 "used_ports", "requested", "non_zero_requested", "allocatable",
                 "image_states", "generation")

    def __init__(self, node: Optional[api.Node] = None):
        self.node: Optional[api.Node] = None
        self.pods: List[PodInfo] = []
        self.pods_with_affinity: List[PodInfo] = []
        self.pods_with_required_anti_affinity: List[PodInfo] = []
        # (protocol, host_ip, host_port) triples, mirroring HostPortInfo
        # (reference: types.go:660 HostPortInfo.Add).
        self.used_ports: Set[Tuple[str, str, int]] = set()
        self.requested = Resource()
        self.non_zero_requested = Resource()
        self.allocatable = Resource()
        self.image_states: Dict[str, int] = {}  # image name -> size bytes
        self.generation = next_generation()
        if node is not None:
            self.set_node(node)

    @property
    def node_name(self) -> str:
        return self.node.name if self.node else ""

    def set_node(self, node: api.Node) -> None:
        # reference: types.go:553 (SetNode)
        self.node = node
        self.allocatable = Resource.from_resource_list(node.status.allocatable)
        self.image_states = {}
        for img in node.status.images:
            for name in img.names:
                self.image_states[name] = img.size_bytes
        self.generation = next_generation()

    def add_pod(self, pod: api.Pod, pinfo: Optional[PodInfo] = None) -> None:
        # reference: types.go:456 (AddPod).  pinfo: optional pre-parsed
        # PodInfo wrapping THIS pod object (callers on the hot path pass it
        # to skip re-parsing terms/resources).
        pi = pinfo if pinfo is not None and pinfo.pod is pod else PodInfo(pod)
        self.pods.append(pi)
        if pod_with_affinity(pod):
            self.pods_with_affinity.append(pi)
        if pod_with_required_anti_affinity(pod):
            self.pods_with_required_anti_affinity.append(pi)
        self.requested.add(pi.resource)
        self.non_zero_requested.milli_cpu += pi.non_zero_cpu
        self.non_zero_requested.memory += pi.non_zero_mem
        self._update_used_ports(pod, add=True)
        self.generation = next_generation()

    def remove_pod(self, pod: api.Pod) -> bool:
        # reference: types.go:483 (RemovePod); returns False if absent
        for i, pi in enumerate(self.pods):
            if pi.pod.uid == pod.uid:
                del self.pods[i]
                self.pods_with_affinity = [p for p in self.pods_with_affinity
                                           if p.pod.uid != pod.uid]
                self.pods_with_required_anti_affinity = [
                    p for p in self.pods_with_required_anti_affinity if p.pod.uid != pod.uid]
                self.requested.sub(pi.resource)
                self.non_zero_requested.milli_cpu -= pi.non_zero_cpu
                self.non_zero_requested.memory -= pi.non_zero_mem
                self._update_used_ports(pod, add=False)
                self.generation = next_generation()
                return True
        return False

    def _update_used_ports(self, pod: api.Pod, add: bool) -> None:
        for c in pod.spec.containers:
            for p in c.ports:
                if p.host_port <= 0:
                    continue
                triple = (p.protocol or "TCP", p.host_ip or "0.0.0.0", p.host_port)
                if add:
                    self.used_ports.add(triple)
                else:
                    self.used_ports.discard(triple)

    def clone(self) -> "NodeInfo":
        # reference: types.go:380 (Clone) — used by preemption simulation
        ni = NodeInfo()
        ni.node = self.node
        ni.pods = list(self.pods)
        ni.pods_with_affinity = list(self.pods_with_affinity)
        ni.pods_with_required_anti_affinity = list(self.pods_with_required_anti_affinity)
        ni.used_ports = set(self.used_ports)
        ni.requested = self.requested.clone()
        ni.non_zero_requested = self.non_zero_requested.clone()
        ni.allocatable = self.allocatable.clone()
        ni.image_states = dict(self.image_states)
        ni.generation = self.generation
        return ni
