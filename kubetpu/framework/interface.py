"""Scheduler Framework plugin contract.

reference: pkg/scheduler/framework/v1alpha1/interface.go — Status codes :77,
MaxNodeScore :85, the 11 extension points (QueueSort, PreFilter(+extensions),
Filter, PreScore, Score(+NormalizeScore), Reserve, Permit, PreBind, Bind,
PostBind, Unreserve) and the Framework/FrameworkHandle contracts :398/:493.

Host plugins implement these Python interfaces 1:1.  Tensorized plugins
additionally declare kernel names consumed by the device program
(kubetpu/models/programs.py) — the framework runner routes them to XLA and
runs only genuinely host-side logic (API writes, volume binding, webhooks)
through these methods.
"""

from __future__ import annotations

import threading
import time
from enum import IntEnum
from typing import Callable, Dict, List, Optional, Tuple

from ..api import types as api

MAX_NODE_SCORE = 100  # reference: interface.go:85
MIN_NODE_SCORE = 0

MAX_TOTAL_PRIORITY = 2 ** 31 - 1


class Code(IntEnum):
    """reference: interface.go:77-103."""
    SUCCESS = 0
    ERROR = 1
    UNSCHEDULABLE = 2
    UNSCHEDULABLE_AND_UNRESOLVABLE = 3
    WAIT = 4
    SKIP = 5


class Status:
    """reference: interface.go:106 Status."""

    __slots__ = ("code", "reasons")

    def __init__(self, code: Code = Code.SUCCESS,
                 reasons: Optional[List[str]] = None):
        self.code = code
        self.reasons = reasons or []

    @classmethod
    def success(cls) -> "Status":
        return cls(Code.SUCCESS)

    @classmethod
    def error(cls, msg: str) -> "Status":
        return cls(Code.ERROR, [msg])

    @classmethod
    def unschedulable(cls, *reasons: str) -> "Status":
        return cls(Code.UNSCHEDULABLE, list(reasons))

    @classmethod
    def unresolvable(cls, *reasons: str) -> "Status":
        return cls(Code.UNSCHEDULABLE_AND_UNRESOLVABLE, list(reasons))

    def is_success(self) -> bool:
        return self.code == Code.SUCCESS

    def is_unschedulable(self) -> bool:
        return self.code in (Code.UNSCHEDULABLE,
                             Code.UNSCHEDULABLE_AND_UNRESOLVABLE)

    def message(self) -> str:
        return ", ".join(self.reasons)

    def __repr__(self) -> str:
        return f"Status({self.code.name}, {self.reasons})"


class FitError(Exception):
    """Scheduling failure carrying per-node reasons
    (reference: core/generic_scheduler.go:68 FitError)."""

    def __init__(self, pod: api.Pod, num_all_nodes: int,
                 filtered_nodes_statuses: Dict[str, Status]):
        self.pod = pod
        self.num_all_nodes = num_all_nodes
        self.filtered_nodes_statuses = filtered_nodes_statuses
        super().__init__(self.error_message())

    def error_message(self) -> str:
        # reference: generic_scheduler.go:82 (ErrorMessageFormat)
        counts: Dict[str, int] = {}
        for st in self.filtered_nodes_statuses.values():
            for r in st.reasons:
                counts[r] = counts.get(r, 0) + 1
        reasons = ", ".join(f"{n} {r}" for r, n in sorted(counts.items()))
        return (f"0/{self.num_all_nodes} nodes are available: {reasons}."
                if reasons else f"0/{self.num_all_nodes} nodes are available.")


class CycleState:
    """Per-scheduling-cycle shared KV store
    (reference: framework/v1alpha1/cycle_state.go:40)."""

    def __init__(self):
        self._data: Dict[str, object] = {}  # kubelint: guarded-by(_lock)
        self._lock = threading.RLock()
        self.record_plugin_metrics = False

    def read(self, key: str):
        with self._lock:
            if key not in self._data:
                raise KeyError(key)
            return self._data[key]

    def write(self, key: str, value: object) -> None:
        with self._lock:
            self._data[key] = value

    def delete(self, key: str) -> None:
        with self._lock:
            self._data.pop(key, None)

    def clone(self) -> "CycleState":
        c = CycleState()
        with self._lock:
            for k, v in self._data.items():
                c._data[k] = v.clone() if hasattr(v, "clone") else v
        c.record_plugin_metrics = self.record_plugin_metrics
        return c


# ---------------------------------------------------------------------------
# plugin interfaces (reference: interface.go:228-396)


class Plugin:
    NAME = "Plugin"

    def name(self) -> str:
        return self.NAME


class QueueSortPlugin(Plugin):
    def less(self, a, b) -> bool:
        raise NotImplementedError

    def sort_key(self, qp) -> tuple:
        """Total-order key equivalent of less(), snapshotted at enqueue time
        (the heap freezes it — see schedqueue/heap.py).  Plugins should
        implement this; the default derives nothing and must be overridden
        when less() is."""
        raise NotImplementedError


class PreFilterPlugin(Plugin):
    def pre_filter(self, state: CycleState, pod: api.Pod) -> Status:
        raise NotImplementedError

    def pre_filter_extensions(self):
        """Returns self if AddPod/RemovePod are implemented, else None
        (reference: interface.go:252 PreFilterExtensions)."""
        return None

    def add_pod(self, state: CycleState, pod_to_schedule: api.Pod,
                pod_to_add: api.Pod, node_info) -> Status:
        return Status.success()

    def remove_pod(self, state: CycleState, pod_to_schedule: api.Pod,
                   pod_to_remove: api.Pod, node_info) -> Status:
        return Status.success()


class FilterPlugin(Plugin):
    def filter(self, state: CycleState, pod: api.Pod, node_info) -> Status:
        raise NotImplementedError


class PostFilterResult:
    """reference: framework/v1alpha1/interface.go:522."""
    __slots__ = ("nominated_node_name",)

    def __init__(self, nominated_node_name: str = ""):
        self.nominated_node_name = nominated_node_name


class PostFilterPlugin(Plugin):
    """Called when no node passed filtering; may make the pod schedulable
    (e.g. by preempting).  Statuses: SUCCESS (made schedulable, result may
    nominate a node), UNSCHEDULABLE (ran fine, couldn't help), anything
    else is an error (reference: framework/v1alpha1/interface.go:278,
    framework.go:516)."""

    def post_filter(self, state: CycleState, pod: api.Pod,
                    filtered_node_status: Dict[str, Status]
                    ) -> Tuple[Optional[PostFilterResult], Status]:
        raise NotImplementedError


class PreScorePlugin(Plugin):
    def pre_score(self, state: CycleState, pod: api.Pod,
                  nodes: List[api.Node]) -> Status:
        raise NotImplementedError


class ScorePlugin(Plugin):
    def score(self, state: CycleState, pod: api.Pod,
              node_name: str) -> Tuple[int, Status]:
        raise NotImplementedError

    def score_extensions(self):
        """Returns self if normalize_score is implemented, else None."""
        return None

    def normalize_score(self, state: CycleState, pod: api.Pod,
                        scores: List[Tuple[str, int]]) -> Tuple[List[Tuple[str, int]], Status]:
        return scores, Status.success()


class ReservePlugin(Plugin):
    def reserve(self, state: CycleState, pod: api.Pod, node_name: str) -> Status:
        raise NotImplementedError


class UnreservePlugin(Plugin):
    def unreserve(self, state: CycleState, pod: api.Pod, node_name: str) -> None:
        raise NotImplementedError


class PermitPlugin(Plugin):
    def permit(self, state: CycleState, pod: api.Pod,
               node_name: str) -> Tuple[Status, float]:
        """Returns (status, timeout_seconds); Wait status parks the pod
        (reference: interface.go:330)."""
        raise NotImplementedError


class PreBindPlugin(Plugin):
    def pre_bind(self, state: CycleState, pod: api.Pod, node_name: str) -> Status:
        raise NotImplementedError


class BindPlugin(Plugin):
    def bind(self, state: CycleState, pod: api.Pod, node_name: str) -> Status:
        """SKIP status passes to the next bind plugin
        (reference: interface.go:376)."""
        raise NotImplementedError


class PostBindPlugin(Plugin):
    def post_bind(self, state: CycleState, pod: api.Pod, node_name: str) -> None:
        raise NotImplementedError


class TensorPlugin(Plugin):
    """A plugin whose Filter/Score semantics are implemented as device
    kernels.  The framework runner collects these into the jitted program's
    ProgramConfig instead of calling per-node Python methods — this is how
    the TPU backend stays 'gated behind the Scheduler Framework plugin
    interface' (BASELINE.json north star)."""
    FILTER_KERNEL: Optional[str] = None   # name in programs.run_filters
    SCORE_KERNEL: Optional[str] = None    # name in programs.run_scores


# ---------------------------------------------------------------------------
# waiting pods (Permit -> Wait)


class WaitingPod:
    """reference: framework/v1alpha1/waiting_pods_map.go:52 waitingPod."""

    def __init__(self, pod: api.Pod, plugin_timeouts: Dict[str, float]):
        self.pod = pod
        self._pending = dict(plugin_timeouts)  # kubelint: guarded-by(_cond)
        self._cond = threading.Condition()
        self._status: Optional[Status] = None
        self._deadline = time.time() + (max(plugin_timeouts.values())
                                        if plugin_timeouts else 0.0)

    def get_pending_plugins(self) -> List[str]:
        with self._cond:
            return list(self._pending)

    def allow(self, plugin_name: str) -> None:
        # reference: waiting_pods_map.go:106
        with self._cond:
            self._pending.pop(plugin_name, None)
            if not self._pending and self._status is None:
                self._status = Status.success()
                self._cond.notify_all()

    def reject(self, msg: str) -> None:
        with self._cond:
            if self._status is None:
                self._status = Status.unschedulable(
                    f"pod {self.pod.metadata.name} rejected while waiting on "
                    f"permit: {msg}")
                self._cond.notify_all()

    def wait(self, timeout: Optional[float] = None) -> Status:
        deadline = self._deadline if timeout is None else time.time() + timeout
        with self._cond:
            while self._status is None:
                remaining = deadline - time.time()
                if remaining <= 0:
                    self._status = Status.unschedulable(
                        "pod rejected due to timeout after waiting on permit")
                    break
                self._cond.wait(timeout=remaining)
            return self._status


class WaitingPodsMap:
    """reference: waiting_pods_map.go:29."""

    def __init__(self):
        self._pods: Dict[str, WaitingPod] = {}  # kubelint: guarded-by(_lock)
        self._lock = threading.RLock()

    def add(self, wp: WaitingPod) -> None:
        with self._lock:
            self._pods[wp.pod.uid] = wp

    def remove(self, uid: str) -> None:
        with self._lock:
            self._pods.pop(uid, None)

    def get(self, uid: str) -> Optional[WaitingPod]:
        with self._lock:
            return self._pods.get(uid)

    def iterate(self, fn: Callable[[WaitingPod], None]) -> None:
        with self._lock:
            for wp in list(self._pods.values()):
                fn(wp)
