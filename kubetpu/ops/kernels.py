"""Batched Filter/Score kernels: the TPU-native re-implementation of every
default-enabled scheduler plugin's algorithm (reference:
pkg/scheduler/framework/plugins/*, default matrix in
pkg/scheduler/algorithmprovider/registry.go:77-160).

Shape conventions: B pending pods x N nodes x P existing pods.  All kernels
are pure jnp functions over (ClusterTensors, PodBatch) pytrees, composed and
jitted by kubetpu/models/programs.py.  Where the reference runs int64
arithmetic, we use f32 with explicit floor() at every integer-division /
truncation site so scores agree exactly for in-range values (see
state/tensors.py for the unit-scaling argument).
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ..state.tensors import CH_CPU, CH_EPH, CH_MEM, CH_PODS, N_FIXED_CHANNELS
from .selectors import match_selectors

MAX_NODE_SCORE = 100.0  # reference: framework/v1alpha1/interface.go:85


def _f(x):
    return x.astype(jnp.float32)


def _idiv(a, b):
    """Go int64 division (truncation toward zero) for non-negative operands;
    b == 0 guarded by callers.

    floor(a / b) alone is WRONG under XLA on TPU: fast-math lowers x/b to
    x * (1/b), and e.g. 200 * (1/100) = 1.9999999 floors to 1.  Both
    operands here are exact integers in f32 range, so one remainder
    correction recovers the exact quotient."""
    q = jnp.floor(a / b)  # kubelint: ignore[numeric/floor-div] this IS the corrected division — the remainder fixup below recovers the exact quotient
    r = a - q * b
    return q + jnp.where(r >= b, 1.0, 0.0) - jnp.where(r < 0, 1.0, 0.0)


# ---------------------------------------------------------------------------
# blessed exact cross-axis reductions
#
# The only sanctioned ways to reduce across shard_map mesh axes or Pallas
# grid tiles (kubelint exact/raw-collective-reduce + exact/raw-tie-argmax
# route every call site here; tools/kubeexact proves the discipline on the
# traced jaxprs).  The contract:
#
#   * float max/min are exactly associative — any tile order, same bits;
#   * float sums must be integer-valued with |value| < 2**24 (callers are
#     responsible; kubeexact checks the bound at north-star shapes);
#   * tie-broken argmax must decompose through the per-pod gumbel plane
#     (argmax over where(tie, gumbel, neg) == jax.random.categorical over
#     the tie set) and cross-axis selection must fold (best, gumbel,
#     lowest-index) by STRICT improvement so the winner equals the
#     replicated jnp.argmax bit-for-bit.
#
# Sentinels (neg) ride in from the caller: the lax twin uses
# jnp.float32(-2**62) while the Pallas kernel uses the python float — the
# weak-type difference is part of each program's committed lowering.


def exact_psum(x, axis_name):
    """Cross-shard sum under the integer-exactness contract (int dtypes,
    or integer-valued f32 with range < 2**24 — see tools/kubeexact)."""
    return jax.lax.psum(x, axis_name)


def exact_pmax(x, axis_name):
    """Cross-shard float/int max: exactly associative, always bit-stable."""
    return jax.lax.pmax(x, axis_name)


def exact_pmin(x, axis_name):
    """Cross-shard float/int min: exactly associative, always bit-stable."""
    return jax.lax.pmin(x, axis_name)


def gumbel_tiebreak_argmax(total, f, gumbel, col_offset, neg):
    """Per-tile propose half of the selectHost decomposition.

    Masks infeasible columns to ``neg``, takes the tile max, then breaks
    exact score ties by gumbel (argmax over where(tie, gumbel, neg) is
    jax.random.categorical restricted to the tie set — selectHost's
    reservoir draw).  Returns (tile_best, tile_h, tile_arg) with
    tile_arg offset into global column space by ``col_offset``;
    jnp.argmax keeps the lowest index on exact gumbel ties, which is the
    first-index contract the cross-axis fold preserves."""
    masked = jnp.where(f, total, neg)
    tile_best = jnp.max(masked, axis=1)
    h = jnp.where((masked == tile_best[:, None]) & f, gumbel, neg)
    tile_h = jnp.max(h, axis=1)
    tile_arg = jnp.argmax(h, axis=1).astype(jnp.int32) + col_offset
    return tile_best, tile_h, tile_arg


def crossaxis_first_index_argmax(tile_best, tile_h, tile_arg, axis_name,
                                 neg):
    """Cross-shard resolve half: max score, then max gumbel among score
    ties, then MIN global index among exact (score, gumbel) ties — all
    via exactly-associative pmax/pmin, so the winner is the index the
    replicated jnp.argmax would have chosen (gather-free)."""
    best = jax.lax.pmax(tile_best, axis_name)
    gh = jax.lax.pmax(jnp.where(tile_best == best, tile_h, neg),
                      axis_name)
    cand = jnp.where((tile_best == best) & (tile_h == gh), tile_arg,
                     jnp.int32(2 ** 30))
    return best, jax.lax.pmin(cand, axis_name)


# ---------------------------------------------------------------------------
# shared aggregation helpers


def per_node_counts(match_sp: jnp.ndarray, pod_node: jnp.ndarray, n_nodes: int) -> jnp.ndarray:
    """[S, P] per-existing-pod values -> [S, N] per-node sums.

    One-hot MATMUL, not a scatter: TPU scatters serialize, while a
    [S, P] x [P, N] contraction rides the MXU.  bf16 inputs are exact for
    the bool/small-int values every caller passes (products are exact and
    the MXU accumulates in f32), so counts are bit-exact up to 2^24."""
    oh = (pod_node[:, None] == jnp.arange(n_nodes)[None, :])  # [P, N]
    return jnp.einsum("sp,pn->sn", match_sp.astype(jnp.bfloat16),
                      oh.astype(jnp.bfloat16),
                      preferred_element_type=jnp.float32)


def _samepair_pods_to_nodes(cluster, values_sp: jnp.ndarray,
                            keys_s: jnp.ndarray, pod_node: jnp.ndarray,
                            pod_valid: jnp.ndarray,
                            active_keys=None) -> jnp.ndarray:
    """out[s, n] = sum of values[s, p] over existing pods p placed on a node
    sharing node n's (keys_s[s], value) topology pair.

    This is the MXU form of scatter-to-pair-space + gather-back-to-nodes
    (pair_scatter/pair_gather): one [S, P] x [P, N] matmul per topology key
    (TK static, unrolled), with the same-pair membership matrix built
    elementwise.  Rows whose key id is out of [0, TK) yield zeros; nodes
    without the key receive 0; pods on nodes without the key contribute
    nothing.  values must be bf16-exact per element (bools or small ints —
    accumulation is f32 on the MXU, so sums are exact).

    active_keys: optional static iterable of the topology-key ids that can
    appear in keys_s — the matmul runs ONLY for those keys (typical
    workloads touch 2 of the TK=8 seeded keys, a 4x FLOP cut).  MUST be a
    superset of every key in keys_s or those rows silently read 0; None
    means all keys."""
    tp = cluster.topo_pair                      # [N, TK]
    TK = tp.shape[1]
    pod_tp = jnp.take(tp, jnp.clip(pod_node, 0, None), axis=0)  # [P, TK]
    placed = (pod_node >= 0) & pod_valid
    vals = values_sp.astype(jnp.bfloat16)
    out = jnp.zeros((values_sp.shape[0], tp.shape[0]), jnp.float32)
    keys = range(TK) if active_keys is None else \
        [k for k in active_keys if 0 <= k < TK]
    for k in keys:
        pk = jnp.where(placed, pod_tp[:, k], -1)            # [P]
        sp = (pk[:, None] == tp[None, :, k]) & (pk >= 0)[:, None]
        red = jnp.einsum("sp,pn->sn", vals, sp.astype(jnp.bfloat16),
                         preferred_element_type=jnp.float32)
        out = jnp.where((keys_s == k)[:, None], red, out)
    return out


def _samepair_nodes(cluster, values_sn: jnp.ndarray,
                    keys_s: jnp.ndarray, active_keys=None) -> jnp.ndarray:
    """out[s, n] = sum of values[s, n'] over nodes n' sharing node n's
    (keys_s[s], value) pair — the node-valued sibling of
    _samepair_pods_to_nodes ([S, N] x [N, N] matmul per key; same
    active_keys contract)."""
    tp = cluster.topo_pair
    TK = tp.shape[1]
    vals = values_sn.astype(jnp.bfloat16)
    out = jnp.zeros(values_sn.shape, jnp.float32)
    keys = range(TK) if active_keys is None else \
        [k for k in active_keys if 0 <= k < TK]
    for k in keys:
        col = tp[:, k]
        sp = (col[:, None] == col[None, :]) & (col >= 0)[:, None]
        red = jnp.einsum("sn,nm->sm", vals, sp.astype(jnp.bfloat16),
                         preferred_element_type=jnp.float32)
        out = jnp.where((keys_s == k)[:, None], red, out)
    return out


def pair_scatter(values_sn: jnp.ndarray, pair_sn: jnp.ndarray, L: int) -> jnp.ndarray:
    """Aggregate per-(s, item) values by topology-pair id -> [S, L].
    pair id -1 entries are dropped."""
    ids = jnp.where(pair_sn >= 0, pair_sn, L)
    out = jax.vmap(lambda v, i: jax.ops.segment_sum(v, i, num_segments=L + 1))(
        _f(values_sn), ids)
    return out[:, :L]


def pair_gather(pair_counts_sl: jnp.ndarray, pair_sn: jnp.ndarray) -> jnp.ndarray:
    """[S, L] pair values gathered back to items via [S, N] pair ids; -1 -> 0."""
    got = jnp.take_along_axis(pair_counts_sl, jnp.clip(pair_sn, 0, None), axis=1)
    return jnp.where(pair_sn >= 0, got, 0.0)


def node_topo_pairs(cluster, topo_key_sb: jnp.ndarray) -> jnp.ndarray:
    """For selector rows with topology-key ids [S] (or [S, ...] flattened),
    return each node's pair id [S, N] (-1 if the node lacks the key)."""
    return jnp.take(cluster.topo_pair.T, topo_key_sb, axis=0)  # [S, N]


def pod_topo_pairs(cluster, topo_key_s: jnp.ndarray) -> jnp.ndarray:
    """Pair ids of each *existing pod's node* for given keys -> [S, P]."""
    pod_topo = jnp.take(cluster.topo_pair, jnp.clip(cluster.pod_node, 0, None),
                        axis=0)  # [P, TK]
    pairs = jnp.take(pod_topo.T, topo_key_s, axis=0)  # [S, P]
    return jnp.where((cluster.pod_node >= 0) & cluster.pod_valid, pairs, -1)


# ---------------------------------------------------------------------------
# filters — each returns ok [B, N] bool (over valid nodes; caller masks padding)


def fit_rows(req: jnp.ndarray, avail: jnp.ndarray) -> jnp.ndarray:
    """Row-wise NodeResourcesFit verdict: request rows [X, R] against
    available rows [X, R] (fit.go:194-267 semantics: pod count always
    checked; cpu/mem/ephemeral checked when the pod requests anything;
    scalar channels only when requested)."""
    free_ok = avail >= req
    R = req.shape[-1]
    # channel masks broadcast EXPLICITLY against the [..., R] operands:
    # bare [R] | [X, R] is an implicit rank promotion the sanitizer
    # (KUBETPU_SANITIZE rank_promotion="raise") rejects
    shape1 = (1,) * (req.ndim - 1) + (R,)
    ch = jnp.arange(R).reshape(shape1)
    is_fixed = (ch < N_FIXED_CHANNELS) & (ch != CH_PODS)
    is_pods = ch == CH_PODS
    check = jnp.where(is_fixed, True, req > 0)
    res_ok = jnp.all(free_ok | ~check | is_pods, axis=-1)
    pods_ok = free_ok[..., CH_PODS]
    nonpods = jnp.where(is_pods, 0.0, req)
    zero_req = jnp.all(nonpods == 0, axis=-1)
    return pods_ok & (zero_req | res_ok)


def fit_filter(cluster, batch, ignored_channels: jnp.ndarray | None = None) -> jnp.ndarray:
    """NodeResourcesFit (reference: noderesources/fit.go:194-267 fitsRequest).
    ignored_channels: optional [R] f32 mask, 1.0 = check the channel."""
    alloc, used, req = cluster.allocatable, cluster.requested, batch.req
    free_ok = alloc[None, :, :] >= req[:, None, :] + used[None, :, :]  # [B, N, R]
    R = alloc.shape[1]
    ch = jnp.arange(R)[None, None, :]  # explicit [1, 1, R] broadcast
    # pod count is always checked; cpu/mem/ephemeral checked whenever the pod
    # requests anything at all; scalar channels only when requested.
    is_fixed = (ch < N_FIXED_CHANNELS) & (ch != CH_PODS)
    is_pods = ch == CH_PODS
    scalar_req = req[:, None, :] > 0
    check = jnp.where(is_fixed, True, scalar_req)
    if ignored_channels is not None:
        check = jnp.logical_and(check, (ignored_channels > 0)[None, None, :])
    res_ok = jnp.all(free_ok | ~check | is_pods, axis=-1)
    pods_ok = free_ok[:, :, CH_PODS]
    nonpods = jnp.where(is_pods[0], 0.0, req)
    zero_req = jnp.all(nonpods == 0, axis=-1)  # [B]
    return pods_ok & (zero_req[:, None] | res_ok)


def node_name_filter(cluster, batch) -> jnp.ndarray:
    """NodeName (reference: nodename/node_name.go:51)."""
    has = jnp.take(cluster.kv.T, jnp.clip(batch.node_name_kvid, 0, None), axis=0)
    named_ok = has & (batch.node_name_kvid >= 0)[:, None]
    return jnp.where(batch.has_node_name[:, None], named_ok, True)


def node_unschedulable_filter(cluster, batch) -> jnp.ndarray:
    """NodeUnschedulable (reference: nodeunschedulable/node_unschedulable.go:51)."""
    return ~(cluster.unschedulable[None, :]
             & ~batch.tolerates_unschedulable[:, None])


def node_ports_filter(cluster, batch) -> jnp.ndarray:
    """NodePorts (reference: nodeports/node_ports.go:108; wildcard semantics
    encoded at intern time, see state/tensors.py port_ids)."""
    conflicts = jnp.einsum("bp,np->bn", batch.ports_hot, _f(cluster.ports),
                           preferred_element_type=jnp.float32)
    return conflicts < 0.5


def taint_filter(cluster, batch) -> jnp.ndarray:
    """TaintToleration: untolerated NoSchedule/NoExecute taint fails
    (reference: tainttoleration/taint_toleration.go:54-72)."""
    untol_hard = _f(~batch.tolerated) * _f(cluster.taint_is_hard)[None, :]
    hits = jnp.einsum("bt,nt->bn", untol_hard, _f(cluster.taints),
                      preferred_element_type=jnp.float32)
    return hits < 0.5


def node_affinity_filter(cluster, batch) -> jnp.ndarray:
    """NodeAffinity + spec.nodeSelector (reference:
    nodeaffinity/node_affinity.go:54, plugins/helper/node_affinity.go
    PodMatchesNodeSelectorAndAffinityTerms).  Also reused by the topology
    spread kernels as the node-eligibility mask."""
    B = batch.req.shape[0]
    sel_ok = match_selectors(batch.node_selector, cluster.kv, cluster.keymask,
                             cluster.num)  # [B, N]
    term_m = match_selectors(batch.rna_sel, cluster.kv, cluster.keymask,
                             cluster.num)  # [B*Tn, N]
    Tn = batch.rna_valid.shape[1]
    term_m = term_m.reshape(B, Tn, -1)
    any_term = jnp.any(term_m & batch.rna_valid[:, :, None], axis=1)
    rna_ok = jnp.where(batch.has_rna[:, None], any_term, True)
    return sel_ok & rna_ok


# ---------------------------------------------------------------------------
# PodTopologySpread


class SpreadState(NamedTuple):
    node_counts: jnp.ndarray   # [B, C, N] matching-pod counts per node
    pair_counts: jnp.ndarray   # [B*C, L] counts per registered pair
    registered: jnp.ndarray    # [B*C, L] bool pair registered from eligible nodes
    node_pair: jnp.ndarray     # [B*C, N] node's pair id per constraint
    has_key: jnp.ndarray       # [B, C, N] node has the topology key
    eligible: jnp.ndarray      # [B, N] affinity-ok nodes with all constraint keys
    any_eligible: jnp.ndarray  # [B]


def spread_match_ns(cluster, batch, constraints) -> jnp.ndarray:
    """[B, C, P] constraint-selector x namespace match against the pod axis
    — the assignment-independent part of _spread_state, precomputable once
    for gang mode's per-round re-evaluation."""
    B, C = constraints.topo_key.shape
    m = match_selectors(constraints.sel, cluster.pod_kv, cluster.pod_key)
    ns_ok = jnp.einsum("bn,pn->bp", batch.ns_hot, cluster.pod_ns_hot,
                       preferred_element_type=jnp.float32) > 0.5
    return m.reshape(B, C, -1) & ns_ok[:, None, :]


def _spread_state(cluster, batch, constraints, affinity_ok, count_mask_nodes,
                  match_ns=None) -> SpreadState:
    """Shared machinery of hard-filter and soft-score spreading.

    constraints: batch.spread or batch.spread_soft.
    count_mask_nodes: [B, N] bool — nodes whose pods are counted into pair
    sums (PreFilter counts every node's pods into registered pairs; PreScore
    counts only affinity-matching nodes with all keys).
    match_ns: optional precomputed spread_match_ns output."""
    B, C = constraints.topo_key.shape
    N = cluster.allocatable.shape[0]
    L = cluster.kv.shape[1]

    # matching existing pods: same namespace, selector, non-terminating
    # (reference: podtopologyspread/common.go:87 countPodsMatchSelector)
    if match_ns is None:
        match_ns = spread_match_ns(cluster, batch, constraints)
    countable = cluster.pod_valid & ~cluster.pod_terminating
    m = match_ns & countable[None, None, :]
    node_counts = per_node_counts(m.reshape(B * C, -1), cluster.pod_node,
                                  N).reshape(B, C, N)

    node_pair = node_topo_pairs(cluster, constraints.topo_key.reshape(-1))  # [B*C, N]
    has_key = ((node_pair >= 0).reshape(B, C, N)
               & constraints.topo_known.reshape(B, C)[:, :, None])
    node_pair = jnp.where(has_key.reshape(B * C, N), node_pair, -1)
    valid_c = constraints.valid  # [B, C]
    all_keys = jnp.all(has_key | ~valid_c[:, :, None], axis=1)  # [B, N]
    eligible = affinity_ok & cluster.node_valid[None, :] & all_keys
    any_eligible = jnp.any(eligible, axis=1)

    elig_bc = jnp.broadcast_to(eligible[:, None, :], (B, C, N)).reshape(B * C, N)
    registered = pair_scatter(elig_bc, node_pair, L) > 0.5
    counted = jnp.broadcast_to(count_mask_nodes[:, None, :], (B, C, N)).reshape(B * C, N)
    pair_counts = pair_scatter(node_counts.reshape(B * C, N) * _f(counted),
                               node_pair, L)
    pair_counts = jnp.where(registered, pair_counts, 0.0)
    return SpreadState(node_counts=node_counts, pair_counts=pair_counts,
                       registered=registered, node_pair=node_pair,
                       has_key=has_key, eligible=eligible,
                       any_eligible=any_eligible)


def spread_filter(cluster, batch, affinity_ok, match_ns=None,
                  active_keys=None) -> jnp.ndarray:
    """PodTopologySpread hard constraints
    (reference: podtopologyspread/filtering.go:200-283 calPreFilterState/Filter).

    Node-space formulation: pair aggregates are constant across a pair's
    member nodes, so "min over registered pairs" == "min over nodes of
    registered pairs" and no explicit pair axis is needed — everything is
    same-pair matmuls on the MXU (see _samepair_pods_to_nodes)."""
    cons = batch.spread
    B, C = cons.topo_key.shape
    N = cluster.allocatable.shape[0]
    if match_ns is None:
        match_ns = spread_match_ns(cluster, batch, cons)
    countable = cluster.pod_valid & ~cluster.pod_terminating
    m = (match_ns & countable[None, None, :]).reshape(B * C, -1)
    keys = jnp.where(cons.topo_known, cons.topo_key, -1).reshape(-1)
    # matching-pod count of each node's pair, per constraint  [B*C, N]
    cnt = _samepair_pods_to_nodes(cluster, m, keys, cluster.pod_node,
                                  cluster.pod_valid,
                                  active_keys=active_keys)
    node_pair = node_topo_pairs(cluster, cons.topo_key.reshape(-1))
    has_key = ((node_pair >= 0).reshape(B, C, N)
               & cons.topo_known.reshape(B, C)[:, :, None])
    all_keys = jnp.all(has_key | ~cons.valid[:, :, None], axis=1)  # [B, N]
    eligible = affinity_ok & cluster.node_valid[None, :] & all_keys
    any_eligible = jnp.any(eligible, axis=1)
    # a pair is registered iff some eligible node carries it
    elig_bc = jnp.broadcast_to(eligible[:, None, :], (B, C, N)).reshape(B * C, N)
    registered = _samepair_nodes(cluster, elig_bc, keys,
                                 active_keys=active_keys) > 0.5  # [B*C, N]
    big = jnp.float32(2**31)
    min_match = jnp.min(jnp.where(registered, cnt, big),
                        axis=1).reshape(B, C)
    # unregistered pair => matchNum 0 (reference Filter: nil *tpCount)
    match_num = jnp.where(registered, cnt, 0.0).reshape(B, C, N)
    self_m = _f(cons.self_match)[:, :, None]
    skew = match_num + self_m - min_match[:, :, None]
    c_ok = has_key & (skew <= cons.max_skew[:, :, None])
    ok = jnp.all(c_ok | ~cons.valid[:, :, None], axis=1)
    has_any = jnp.any(cons.valid, axis=1)
    # empty preFilterState (no eligible nodes anywhere) tolerates every pod
    return jnp.where(has_any[:, None] & any_eligible[:, None], ok, True)


def spread_soft_score(cluster, batch, feasible, affinity_ok,
                      hostname_topokey: int, match_ns=None,
                      active_keys=None) -> jnp.ndarray:
    """PodTopologySpread soft constraints scoring, already normalized
    (reference: podtopologyspread/scoring.go PreScore/Score/NormalizeScore)."""
    cons = batch.spread_soft
    B, C = cons.topo_key.shape
    N = cluster.allocatable.shape[0]
    count_nodes = affinity_ok & cluster.node_valid[None, :]
    if match_ns is None:
        match_ns = spread_match_ns(cluster, batch, cons)
    countable = cluster.pod_valid & ~cluster.pod_terminating
    m = match_ns & countable[None, None, :]          # [B, C, P]
    keys = jnp.where(cons.topo_known, cons.topo_key, -1).reshape(-1)
    node_pair = node_topo_pairs(cluster, cons.topo_key.reshape(-1))
    has_key = ((node_pair >= 0).reshape(B, C, N)
               & cons.topo_known.reshape(B, C)[:, :, None])
    is_host = (cons.topo_key == hostname_topokey) & cons.topo_known
    valid = cons.valid

    # per-node match counts (hostname constraints read these directly)
    node_counts = per_node_counts(m.reshape(B * C, -1), cluster.pod_node,
                                  N).reshape(B, C, N)
    # pair sums count only pods on PreScore-eligible nodes
    # (reference: scoring.go:139-165 counts over filtered+affinity nodes)
    cm_pods = jnp.take_along_axis(
        count_nodes, jnp.clip(cluster.pod_node, 0, None)[None, :], axis=1)
    cm_pods = cm_pods & (cluster.pod_node >= 0)[None, :]     # [B, P]
    m_counted = (m & cm_pods[:, None, :]).reshape(B * C, -1)
    cnt_pair = _samepair_pods_to_nodes(cluster, m_counted, keys,
                                       cluster.pod_node, cluster.pod_valid,
                                       active_keys=active_keys)

    # eligibility / registration from *filtered* nodes only
    all_keys = jnp.all(has_key | ~valid[:, :, None], axis=1)  # [B, N]
    ignored = feasible & ~all_keys
    scored = feasible & all_keys
    eligible = feasible & cluster.node_valid[None, :] & all_keys
    elig_bc = jnp.broadcast_to(eligible[:, None, :], (B, C, N)).reshape(B * C, N)
    members = _samepair_nodes(cluster, elig_bc, keys,
                              active_keys=active_keys)      # [B*C, N]
    registered = members > 0.5

    # distinct registered-pair count: each pair contributes
    # sum-over-its-eligible-members of 1/members == exactly 1
    inv = jnp.where(registered & elig_bc, 1.0 / jnp.maximum(members, 1.0),
                    0.0)
    topo_size = jnp.round(jnp.sum(inv, axis=1)).reshape(B, C)
    n_scored = jnp.sum(_f(scored), axis=1)  # [B]
    size = jnp.where(is_host, n_scored[:, None], topo_size)
    weight = jnp.log(size + 2.0)  # reference: scoring.go:286

    pair_cnt = jnp.where(registered, cnt_pair, 0.0).reshape(B, C, N)
    cnt = jnp.where(is_host[:, :, None], node_counts, pair_cnt)
    # adjustForMaxSkew (scoring.go:294)
    ms = cons.max_skew[:, :, None]
    cnt = jnp.where(cnt < ms, ms - 1.0, cnt)
    contrib = jnp.where((valid & cons.topo_known)[:, :, None] & has_key,
                        cnt * weight[:, :, None], 0.0)
    raw = jnp.floor(jnp.sum(contrib, axis=1))  # int64(score)
    raw = jnp.where(ignored, 0.0, raw)

    # NormalizeScore (scoring.go:210-257): min/max over non-ignored filtered
    sel = scored
    big = jnp.float32(2**62)
    min_s = jnp.min(jnp.where(sel, raw, big), axis=1, keepdims=True)
    max_s = jnp.max(jnp.where(sel, raw, -big), axis=1, keepdims=True)
    max_s = jnp.maximum(max_s, 0.0)
    norm = jnp.where(max_s > 0,
                     _idiv(MAX_NODE_SCORE * (max_s + jnp.minimum(min_s, big)
                                             - raw), jnp.maximum(max_s, 1.0)),
                     MAX_NODE_SCORE)
    out = jnp.where(ignored, 0.0, norm)
    # no soft constraints => every filtered node scores MaxNodeScore (the
    # reference's maxScore==0 branch)
    has_any = jnp.any(valid, axis=1, keepdims=True)
    out = jnp.where(has_any, out, MAX_NODE_SCORE)
    return jnp.where(feasible, out, 0.0)


# ---------------------------------------------------------------------------
# InterPodAffinity


def _pod_term_matches_static(cluster, terms, B: int) -> jnp.ndarray:
    """Selector x namespace match of pod-side terms against the pod axis —
    the assignment-independent part of _pod_term_matches -> [B, T, P]."""
    m = match_selectors(terms.sel, cluster.pod_kv, cluster.pod_key)  # [B*T, P]
    T = terms.valid.shape[1]
    m = m.reshape(B, T, -1)
    ns_ok = jnp.einsum("btn,pn->btp", terms.ns_hot, cluster.pod_ns_hot,
                       preferred_element_type=jnp.float32) > 0.5
    return m & ns_ok


def _pod_term_matches(cluster, terms, B: int, pre=None) -> jnp.ndarray:
    """Match pod-side affinity terms against existing pods -> [B, T, P]."""
    if pre is None:
        pre = _pod_term_matches_static(cluster, terms, B)
    return pre & cluster.pod_valid[None, None, :]


def existing_terms_match(terms, batch) -> jnp.ndarray:
    """[Et, B] existing-pod term-selector x namespace x validity match
    against the batch — assignment-independent."""
    em = match_selectors(terms.sel, batch.kv_hot, batch.key_hot)
    ens = jnp.einsum("en,bn->eb", terms.ns_hot, batch.ns_hot,
                     preferred_element_type=jnp.float32) > 0.5
    return em & ens & terms.valid[:, None]


class InterpodPre(NamedTuple):
    """Assignment-independent matches for interpod_filter, precomputable
    once for gang mode's per-round re-evaluation."""
    m_ra: jnp.ndarray   # [B, Tr, P]
    m_raa: jnp.ndarray  # [B, Ta, P]
    em: jnp.ndarray     # [Et, B]


def interpod_filter_pre(cluster, batch) -> InterpodPre:
    B = batch.req.shape[0]
    return InterpodPre(
        m_ra=_pod_term_matches_static(cluster, batch.ra, B),
        m_raa=_pod_term_matches_static(cluster, batch.raa, B),
        em=existing_terms_match(cluster.filter_terms, batch))


def interpod_filter(cluster, batch,
                    pre: InterpodPre | None = None,
                    return_no_matches: bool = False,
                    active_keys=None):
    """InterPodAffinity filter.  Returns (ok, affinity_unresolvable) where
    affinity_unresolvable marks required-affinity failures
    (UnschedulableAndUnresolvable, reference: filtering.go:371-396).
    With return_no_matches, also returns the [B] bool marking pods whose
    required-affinity terms currently match nothing — i.e. the self-match
    bootstrap branch (filtering.go:356) is what admits them."""
    B = batch.req.shape[0]
    N = cluster.allocatable.shape[0]
    if pre is None:
        pre = interpod_filter_pre(cluster, batch)

    # --- incoming required affinity (filtering.go:342 satisfyPodAffinity)
    ra = batch.ra
    Tr = ra.valid.shape[1]
    m = _pod_term_matches(cluster, ra, B, pre=pre.m_ra)  # [B, T, P]
    match_all = jnp.all(m | ~ra.valid[:, :, None], axis=1)  # [B, P]
    has_ra = jnp.any(ra.valid, axis=1)  # [B]
    keys_r = jnp.where(ra.topo_known, ra.topo_key, -1).reshape(-1)
    contrib = jnp.broadcast_to(match_all[:, None, :], m.shape).reshape(B * Tr, -1)
    cnt = _samepair_pods_to_nodes(cluster, contrib, keys_r,
                                  cluster.pod_node, cluster.pod_valid,
                                  active_keys=active_keys)
    node_pair = node_topo_pairs(cluster, ra.topo_key.reshape(-1))  # [B*T, N]
    node_has_key = (node_pair >= 0).reshape(B, Tr, N) & ra.topo_known[:, :, None]
    cnt = cnt.reshape(B, Tr, N)
    term_ok = node_has_key & (cnt > 0.5)
    aff_ok = jnp.all(term_ok | ~ra.valid[:, :, None], axis=1)
    # bootstrap: no matches anywhere + pod matches its own terms
    # (filtering.go:356-366); node must still carry every topology key.
    # "matches anywhere" counts matching pods on key-carrying nodes over
    # VALID terms only (the reference's topologyToMatchedAffinityTerms map
    # has entries only for (term, key-bearing-node) pods).
    pod_tp = jnp.take(cluster.topo_pair, jnp.clip(cluster.pod_node, 0, None),
                      axis=0)  # [P, TK]
    pod_keyed = (jnp.take(pod_tp.T, jnp.clip(keys_r, 0, None), axis=0) >= 0) \
        & (keys_r >= 0)[:, None] \
        & (cluster.pod_node >= 0)[None, :] & cluster.pod_valid[None, :]
    # bool -> f32 cast, not where(mask, 1.0, 0.0): two Python-float
    # branches COMMIT to the default float dtype, so the count silently
    # becomes f64 wherever x64 is enabled (census/f64-promotion)
    tot = jnp.sum((pod_keyed & contrib
                   & ra.valid.reshape(-1)[:, None]).astype(jnp.float32),
                  axis=1)  # [B*Tr]
    no_matches = jnp.sum(tot.reshape(B, Tr), axis=1) < 0.5
    self_all = jnp.all(ra.self_match | ~ra.valid, axis=1) & has_ra
    all_keys = jnp.all(node_has_key | ~ra.valid[:, :, None], axis=1)
    aff_ok = aff_ok | ((no_matches & self_all)[:, None] & all_keys)
    aff_ok = jnp.where(has_ra[:, None], aff_ok, True)

    # --- incoming required anti-affinity (filtering.go:329 satisfyPodAntiAffinity)
    raa = batch.raa
    Ta = raa.valid.shape[1]
    ma = _pod_term_matches(cluster, raa, B, pre=pre.m_raa).reshape(B * Ta, -1)
    keys_a = jnp.where(raa.topo_known, raa.topo_key, -1).reshape(-1)
    cnt_a = _samepair_pods_to_nodes(cluster, ma, keys_a,
                                    cluster.pod_node, cluster.pod_valid,
                                    active_keys=active_keys)
    np_a = node_topo_pairs(cluster, raa.topo_key.reshape(-1))
    has_key_a = (np_a >= 0).reshape(B, Ta, N) & raa.topo_known[:, :, None]
    cnt_a = cnt_a.reshape(B, Ta, N)
    anti_fail = jnp.any(has_key_a & (cnt_a > 0.5) & raa.valid[:, :, None], axis=1)

    # --- existing pods' required anti-affinity
    # (filtering.go:314 satisfyExistingPodsAntiAffinity): each term's owner
    # pins one (key, value) pair; a node fails iff it shares that pair and
    # the incoming pod matches the term — an [Et, B] x [Et, N] contraction
    ft = cluster.filter_terms
    em = pre.em  # [Et, B]
    e_pair = jnp.take_along_axis(pod_tp[jnp.clip(ft.pod_idx, 0, None)],
                                 ft.topo_key[:, None], axis=1)[:, 0]  # [Et]
    owner_ok = (jnp.take(cluster.pod_valid, jnp.clip(ft.pod_idx, 0, None))
                & (jnp.take(cluster.pod_node,
                            jnp.clip(ft.pod_idx, 0, None)) >= 0))
    e_pair = jnp.where(ft.valid & owner_ok, e_pair, -1)
    node_pairs_e = jnp.take(cluster.topo_pair.T, ft.topo_key, axis=0)  # [Et, N]
    sp_rows = (node_pairs_e == e_pair[:, None]) & (e_pair >= 0)[:, None]
    exist_fail = jnp.einsum("eb,en->bn", em.astype(jnp.bfloat16),
                            sp_rows.astype(jnp.bfloat16),
                            preferred_element_type=jnp.float32) > 0.5

    ok = aff_ok & ~anti_fail & ~exist_fail
    if return_no_matches:
        return ok, ~aff_ok, no_matches
    return ok, ~aff_ok


class InterpodScorePre(NamedTuple):
    m_pref: jnp.ndarray  # [B, Tp, P]
    em: jnp.ndarray      # [Es, B]


def interpod_score_pre(cluster, batch) -> InterpodScorePre:
    B = batch.req.shape[0]
    return InterpodScorePre(
        m_pref=_pod_term_matches_static(cluster, batch.pref, B),
        em=existing_terms_match(cluster.score_terms, batch))


def interpod_score_raw(cluster, batch,
                       pre: InterpodScorePre | None = None,
                       active_keys=None):
    """The assignment-dependent RAW half of interpod_score -> (raw [B, N],
    any_counts [B, 1]).  Split out so gang mode's Pallas backend can
    precompute it once per auction (under intra_batch_topology=False the
    pod axis is frozen, so raw is round-invariant) and fuse only the
    feasibility-dependent normalization into the megakernel."""
    B = batch.req.shape[0]
    N = cluster.allocatable.shape[0]
    if pre is None:
        pre = interpod_score_pre(cluster, batch)

    # incoming pod's preferred terms vs existing pods
    pt = batch.pref
    T = pt.valid.shape[1]
    m = _pod_term_matches(cluster, pt, B, pre=pre.m_pref)  # [B, T, P]
    data = (_f(m) * pt.weight[:, :, None] * _f(pt.valid)[:, :, None])
    keys_p = jnp.where(pt.topo_known, pt.topo_key, -1).reshape(-1)
    raw1 = _samepair_pods_to_nodes(cluster, data.reshape(B * T, -1), keys_p,
                                   cluster.pod_node, cluster.pod_valid,
                                   active_keys=active_keys)
    raw1 = jnp.sum(raw1.reshape(B, T, N), axis=1)  # [B, N]

    # existing pods' terms vs incoming pod: each term pins its owner-node's
    # (key, value) pair; nodes sharing it receive the term weight
    st = cluster.score_terms
    owner_ok = (jnp.take(cluster.pod_valid, jnp.clip(st.pod_idx, 0, None))
                & (jnp.take(cluster.pod_node,
                            jnp.clip(st.pod_idx, 0, None)) >= 0))
    em = _f(pre.em & owner_ok[:, None]) * st.weight[:, None]  # [Es, B]
    pod_topo = jnp.take(cluster.topo_pair, jnp.clip(cluster.pod_node, 0, None), axis=0)
    e_pair = jnp.take_along_axis(pod_topo[jnp.clip(st.pod_idx, 0, None)],
                                 st.topo_key[:, None], axis=1)[:, 0]
    e_pair = jnp.where(st.valid & owner_ok, e_pair, -1)
    node_pairs_e = jnp.take(cluster.topo_pair.T, st.topo_key, axis=0)  # [Es, N]
    sp_rows = (node_pairs_e == e_pair[:, None]) & (e_pair >= 0)[:, None]
    raw2 = jnp.einsum("eb,en->bn", em.astype(jnp.bfloat16),
                      sp_rows.astype(jnp.bfloat16),
                      preferred_element_type=jnp.float32)

    raw = raw1 + raw2

    # NormalizeScore skips entirely when the topologyScore map is empty.
    # Every counted pair lives on at least its owner's node, so "map
    # empty" == "raw zero at every node".
    any_counts = jnp.any(raw != 0, axis=1, keepdims=True)
    return raw, any_counts


def interpod_score(cluster, batch, feasible,
                   pre: InterpodScorePre | None = None,
                   active_keys=None) -> jnp.ndarray:
    """InterPodAffinity scoring, already normalized (reference: scoring.go).

    Node-space formulation: the (topologyKey, value) -> weight map becomes
    per-node weighted same-pair sums — MXU matmuls with bf16-exact inputs
    (weights are ints |w| <= 100; accumulation is f32)."""
    raw, any_counts = interpod_score_raw(cluster, batch, pre=pre,
                                         active_keys=active_keys)
    # NormalizeScore (scoring.go:237-271): min/max start at 0
    big = jnp.float32(2**62)
    max_c = jnp.maximum(jnp.max(jnp.where(feasible, raw, -big), axis=1,
                                keepdims=True), 0.0)
    min_c = jnp.minimum(jnp.min(jnp.where(feasible, raw, big), axis=1,
                                keepdims=True), 0.0)
    diff = max_c - min_c
    norm = jnp.where(diff > 0,
                     _idiv(MAX_NODE_SCORE * (raw - min_c),
                           jnp.maximum(diff, 1.0)),
                     0.0)
    out = jnp.where(any_counts, norm, raw)
    return jnp.where(feasible, out, 0.0)


# ---------------------------------------------------------------------------
# resource scorers


def _safe_den(cap):
    """Division guard that preserves sub-unit capacities: the old
    maximum(cap, 1.0) clamp silently zeroed fractions for capacities under
    one unit (e.g. byte-scale memory in the reference's test tables, which
    land below 1 MiB after channel conversion).  Only true zero is
    redirected (the caller masks that case)."""
    return jnp.where(cap > 0, cap, 1.0)


def _alloc_req(cluster, batch):
    """(requested-with-pod, allocatable) for cpu/mem using NonZeroRequested
    (reference: noderesources/resource_allocation.go:108-117)."""
    req_cpu = cluster.nonzero_requested[None, :, 0] + batch.nonzero_req[:, 0][:, None]
    req_mem = cluster.nonzero_requested[None, :, 1] + batch.nonzero_req[:, 1][:, None]
    alloc_cpu = cluster.allocatable[None, :, CH_CPU]
    alloc_mem = cluster.allocatable[None, :, CH_MEM]
    return req_cpu, req_mem, alloc_cpu, alloc_mem


def balanced_formula(req_cpu, req_mem, alloc_cpu, alloc_mem) -> jnp.ndarray:
    """(1 - |cpuFraction - memFraction|) * MaxNodeScore — the formula shared
    by the batch kernel and the sequential scan
    (reference: noderesources/balanced_allocation.go:83-113)."""
    cpu_frac = jnp.where(alloc_cpu > 0, req_cpu / _safe_den(alloc_cpu), 1.0)
    mem_frac = jnp.where(alloc_mem > 0, req_mem / _safe_den(alloc_mem), 1.0)
    diff = jnp.abs(cpu_frac - mem_frac)
    # the reference truncates a float64 product (balanced_allocation.go:103);
    # two f32 divisions can land an ulp under the true value (e.g.
    # 74.999997 for a true 75), so compensate before the floor.  The
    # epsilon must stay at ulp scale (~7.6e-6 at score 75): anything
    # larger would round UP true products legitimately within epsilon
    # below an integer, diverging from the reference's floor
    score = jnp.floor((1.0 - diff) * MAX_NODE_SCORE + 1e-5)
    return jnp.where((cpu_frac >= 1.0) | (mem_frac >= 1.0), 0.0, score)


def least_formula(req, cap) -> jnp.ndarray:
    """(capacity - requested) * MaxNodeScore / capacity
    (reference: least_allocated.go:95-117)."""
    s = _idiv((cap - req) * MAX_NODE_SCORE, _safe_den(cap))
    return jnp.where((cap <= 0) | (req > cap), 0.0, s)


def most_formula(req, cap) -> jnp.ndarray:
    """requested * MaxNodeScore / capacity (reference: most_allocated.go:101)."""
    s = _idiv(req * MAX_NODE_SCORE, _safe_den(cap))
    return jnp.where((cap <= 0) | (req > cap), 0.0, s)


def balanced_allocation_score(cluster, batch) -> jnp.ndarray:
    return balanced_formula(*_alloc_req(cluster, batch))


def _weighted_resource_score(cluster, batch, per_resource, cpu_weight=1.0,
                             mem_weight=1.0) -> jnp.ndarray:
    req_cpu, req_mem, alloc_cpu, alloc_mem = _alloc_req(cluster, batch)
    s_cpu = per_resource(req_cpu, alloc_cpu)
    s_mem = per_resource(req_mem, alloc_mem)
    total = s_cpu * cpu_weight + s_mem * mem_weight
    return _idiv(total, cpu_weight + mem_weight)


def least_allocated_score(cluster, batch) -> jnp.ndarray:
    return _weighted_resource_score(cluster, batch, least_formula)


def most_allocated_score(cluster, batch) -> jnp.ndarray:
    return _weighted_resource_score(cluster, batch, most_formula)


# ---------------------------------------------------------------------------
# remaining scorers


def node_affinity_score(cluster, batch) -> jnp.ndarray:
    """Sum of matched preferred node-affinity term weights (raw; normalized
    by default_normalize) (reference: nodeaffinity/node_affinity.go:65-103)."""
    B = batch.req.shape[0]
    Tp = batch.pna_valid.shape[1]
    m = match_selectors(batch.pna_sel, cluster.kv, cluster.keymask, cluster.num)
    m = m.reshape(B, Tp, -1)
    w = batch.pna_weight * _f(batch.pna_valid)
    return jnp.einsum("bt,btn->bn", w, _f(m), preferred_element_type=jnp.float32)


def taint_toleration_score(cluster, batch) -> jnp.ndarray:
    """Count of untolerated PreferNoSchedule taints (raw; reverse-normalized)
    (reference: tainttoleration/taint_toleration.go:123-141)."""
    untol_prefer = _f(~batch.tolerated) * _f(cluster.taint_is_prefer)[None, :]
    return jnp.einsum("bt,nt->bn", untol_prefer, _f(cluster.taints),
                      preferred_element_type=jnp.float32)


_MB = 1024.0 * 1024.0
IMAGE_MIN_THRESHOLD = 23.0 * _MB       # reference: image_locality.go:44
IMAGE_MAX_CONTAINER_THRESHOLD = 1000.0 * _MB


def image_locality_score(cluster, batch) -> jnp.ndarray:
    """Scaled sum of present image sizes (reference: image_locality.go:82-110)."""
    scaled = _f(cluster.images) * jnp.floor(cluster.image_size
                                            * cluster.image_spread)[None, :]
    s = jnp.einsum("bi,ni->bn", batch.images_hot, scaled,
                   preferred_element_type=jnp.float32)
    max_thr = IMAGE_MAX_CONTAINER_THRESHOLD * jnp.maximum(batch.n_containers, 1.0)
    s = jnp.clip(s, IMAGE_MIN_THRESHOLD, max_thr[:, None])
    return _idiv(MAX_NODE_SCORE * (s - IMAGE_MIN_THRESHOLD),
                 max_thr[:, None] - IMAGE_MIN_THRESHOLD)


def prefer_avoid_pods_score(cluster, batch) -> jnp.ndarray:
    """MaxNodeScore unless the node's preferAvoidPods annotation names the
    pod's RC/RS controller (reference: node_prefer_avoid_pods.go:46-81)."""
    hit = jnp.take(cluster.avoid_hot.T, jnp.clip(batch.avoid_id, 0, None), axis=0)
    avoided = hit & (batch.avoid_id >= 0)[:, None]
    return jnp.where(avoided, 0.0, MAX_NODE_SCORE)


def default_spread_match_ns(cluster, batch) -> jnp.ndarray:
    """[B, P] DefaultPodTopologySpread selector x namespace match —
    assignment-independent."""
    m = match_selectors(batch.spread_selector, cluster.pod_kv, cluster.pod_key)
    ns_ok = jnp.einsum("bn,pn->bp", batch.ns_hot, cluster.pod_ns_hot,
                       preferred_element_type=jnp.float32) > 0.5
    return m & ns_ok


def default_spread_score(cluster, batch, match_ns=None) -> jnp.ndarray:
    """DefaultPodTopologySpread raw score: count of same-namespace,
    non-terminating pods on the node matched by the combined controller
    selector (reference: default_pod_topology_spread.go:74-97, 200-215)."""
    N = cluster.allocatable.shape[0]
    if match_ns is None:
        match_ns = default_spread_match_ns(cluster, batch)
    countable = cluster.pod_valid & ~cluster.pod_terminating
    m = match_ns & countable[None, :]
    counts = per_node_counts(m, cluster.pod_node, N)
    return jnp.where(batch.spread_skip[:, None], 0.0, counts)


ZONE_WEIGHTING = 2.0 / 3.0  # reference: default_pod_topology_spread.go:44


def default_spread_normalize(cluster, batch, raw, feasible) -> jnp.ndarray:
    """Zone-aware normalization (reference: default_pod_topology_spread.go:104-166).

    Zone aggregation rides cluster.zone_hot [N, Z] with Z = the zone-vocab
    bucket (typically 8-16), so both the per-zone sum and the
    gather-back-to-nodes are tiny [., Z] matmuls.  The earlier formulation
    used an [N, N] zone one-hot: at 8k nodes its HIGHEST-precision
    [B, N] x [N, N] contraction plus an [B, N] gather was ~800 ms/round —
    the single largest op in the gang auction."""
    big = jnp.float32(2**62)
    raw_f = jnp.where(feasible, raw, 0.0)
    max_node = jnp.max(jnp.where(feasible, raw, -big), axis=1, keepdims=True)
    max_node = jnp.maximum(max_node, 0.0)

    zh = cluster.zone_hot  # [N, Z]; zero rows for zoneless/invalid nodes
    has_zone = jnp.any(zh > 0, axis=1)  # [N]
    counts_by_zone = jnp.einsum("bn,nz->bz", raw_f, zh,
                                precision=jax.lax.Precision.HIGHEST,
                                preferred_element_type=jnp.float32)  # [B, Z]
    have_zones = jnp.any(feasible & has_zone[None, :], axis=1, keepdims=True)
    max_zone = jnp.maximum(jnp.max(counts_by_zone, axis=1, keepdims=True), 0.0)

    f_score = jnp.where(max_node > 0,
                        MAX_NODE_SCORE * (max_node - raw) / jnp.maximum(max_node, 1.0),  # kubelint: ignore[numeric/score-div] reference computes fScore in float64 (default_pod_topology_spread.go:126); floor lands after the zone combine
                        MAX_NODE_SCORE)
    # one nonzero term per output (one-hot) => exact regardless of precision
    node_zone_count = jnp.einsum("bz,nz->bn", counts_by_zone, zh,
                                 precision=jax.lax.Precision.HIGHEST,
                                 preferred_element_type=jnp.float32)
    zone_score = jnp.where(max_zone > 0,
                           MAX_NODE_SCORE * (max_zone - node_zone_count)  # kubelint: ignore[numeric/score-div] reference computes zoneScore in float64 (default_pod_topology_spread.go:142); floor lands after the combine
                           / jnp.maximum(max_zone, 1.0),
                           MAX_NODE_SCORE)
    with_zone = (f_score * (1.0 - ZONE_WEIGHTING)) + ZONE_WEIGHTING * zone_score
    out = jnp.where(have_zones & has_zone[None, :], with_zone, f_score)
    out = jnp.floor(out)
    out = jnp.where(batch.spread_skip[:, None], 0.0, out)
    return jnp.where(feasible, out, 0.0)


# ---------------------------------------------------------------------------
# normalization helpers


def default_normalize(raw, feasible, reverse: bool) -> jnp.ndarray:
    """reference: plugins/helper/normalize_score.go:26 (DefaultNormalizeScore)."""
    big = jnp.float32(2**62)
    max_c = jnp.maximum(jnp.max(jnp.where(feasible, raw, -big), axis=1,
                                keepdims=True), 0.0)
    scaled = _idiv(MAX_NODE_SCORE * raw, jnp.maximum(max_c, 1.0))
    if reverse:
        scaled = MAX_NODE_SCORE - scaled
    zero_case = MAX_NODE_SCORE if reverse else 0.0
    out = jnp.where(max_c > 0, scaled, zero_case)
    return jnp.where(feasible, out, 0.0)


# ---------------------------------------------------------------------------
# configurable scorers (plugin-args driven)


def _itrunc(a, b):
    """Go int64 division truncates toward ZERO (not floor); b > 0."""
    q = _idiv(jnp.abs(a), b)
    return jnp.where(a < 0, -q, q)


def broken_linear(p, shape):
    """Piecewise-linear shape function with Go integer-division semantics
    (reference: noderesources/requested_to_capacity_ratio.go:158
    buildBrokenLinearFunction).  shape: static tuple of (utilization, score).
    Decreasing segments produce negative deltas, so the division must
    truncate toward zero like Go's, not floor."""
    out = jnp.full_like(p, float(shape[-1][1]))  # kubelint: ignore[host-sync/cast] trace-time constant: shape is the static plugin-args tuple
    for i in range(len(shape) - 1, -1, -1):
        u_i, s_i = float(shape[i][0]), float(shape[i][1])  # kubelint: ignore[host-sync/cast] trace-time constant: shape is the static plugin-args tuple
        if i == 0:
            seg = jnp.full_like(p, s_i)
        else:
            u_p, s_p = float(shape[i - 1][0]), float(shape[i - 1][1])  # kubelint: ignore[host-sync/cast] trace-time constant: shape is the static plugin-args tuple
            seg = s_p + _itrunc((s_i - s_p) * (p - u_p), u_i - u_p)
        out = jnp.where(p <= u_i, seg, out)
    return out


def rtcr_combine(parts, shape):
    """Weighted RequestedToCapacityRatio combine shared by the batch kernel
    and the sequential scan (reference: requested_to_capacity_ratio.go:
    124-147).  parts: iterable of (req, cap, weight) arrays; zero/exceeded
    capacity falls back to rawScoringFunction(maxUtilization); the final
    divide is math.Round (half away from zero) in exact integer form."""
    total = None
    weight_sum = None
    for req, cap, weight in parts:
        # _safe_den, not maximum(cap, 1): sub-unit capacities (byte-scale
        # memory after MiB conversion) must still divide by their true
        # value — the cap<=0 case is redirected to the fallback below
        util = 100.0 - _idiv((cap - req) * 100.0, _safe_den(cap))
        s = broken_linear(util, shape)
        s = jnp.where((cap <= 0) | (req > cap),
                      broken_linear(jnp.full_like(util, 100.0), shape), s)
        contrib = jnp.where(s > 0, s * weight, 0.0)
        w = jnp.where(s > 0, float(weight), 0.0)  # kubelint: ignore[host-sync/cast] trace-time constant: weight comes from the static resources tuple
        total = contrib if total is None else total + contrib
        weight_sum = w if weight_sum is None else weight_sum + w
    return jnp.where(weight_sum > 0,
                     _idiv(2.0 * total + weight_sum,
                           jnp.maximum(2.0 * weight_sum, 1.0)),
                     0.0)


def requested_to_capacity_ratio_score(cluster, batch, shape, resources) -> jnp.ndarray:
    """RequestedToCapacityRatio (reference: requested_to_capacity_ratio.go:
    124-147).  shape: ((utilization, score)...); resources: ((kind, ch,
    weight)...) with kind 0=cpu (NonZero), 1=memory (NonZero), 2=scalar
    channel ch.  Scores use math.Round (half away from zero)."""
    req_cpu, req_mem, alloc_cpu, alloc_mem = _alloc_req(cluster, batch)
    parts = []
    for kind, ch, weight in resources:
        if kind == 0:
            req, cap = req_cpu, alloc_cpu
        elif kind == 1:
            req, cap = req_mem, alloc_mem
        elif ch < 0:
            # resource name unknown to the cluster: capacity 0 everywhere
            # (Go falls to rawScoringFunction(maxUtilization))
            req = jnp.zeros_like(req_cpu)
            cap = jnp.zeros_like(alloc_cpu)
        else:
            cap = cluster.allocatable[None, :, ch]
            req = cluster.requested[None, :, ch] + batch.req[:, ch][:, None]
        parts.append((req, cap, weight))
    return rtcr_combine(parts, shape)


def resource_limits_score(cluster, batch) -> jnp.ndarray:
    """NodeResourceLimits: 1 if the node satisfies the pod's cpu or memory
    *limit* (reference: noderesources/resource_limits.go:104-123,155)."""
    lim_cpu = batch.limits[:, None, CH_CPU]
    lim_mem = batch.limits[:, None, CH_MEM]
    alloc_cpu = cluster.allocatable[None, :, CH_CPU]
    alloc_mem = cluster.allocatable[None, :, CH_MEM]
    cpu_ok = (lim_cpu > 0) & (alloc_cpu > 0) & (lim_cpu <= alloc_cpu)
    mem_ok = (lim_mem > 0) & (alloc_mem > 0) & (lim_mem <= alloc_mem)
    return jnp.where(cpu_ok | mem_ok, 1.0, 0.0)


def node_label_filter(cluster, batch, present_ids, absent_ids) -> jnp.ndarray:
    """NodeLabel filter: all configured present labels present, absent ones
    absent (reference: nodelabel/node_label.go:48-68).  ids are key-vocab
    ids; -1 means the label exists nowhere in the cluster."""
    B = batch.req.shape[0]
    N = cluster.keymask.shape[0]
    ok = jnp.ones((N,), bool)
    for kid in present_ids:
        ok = ok & (cluster.keymask[:, kid] if kid >= 0
                   else jnp.zeros((N,), bool))
    for kid in absent_ids:
        ok = ok & (~cluster.keymask[:, kid] if kid >= 0
                   else jnp.ones((N,), bool))
    return jnp.broadcast_to(ok[None, :], (B, N))


def node_label_score(cluster, batch, prefs) -> jnp.ndarray:
    """NodeLabel score: average of MaxNodeScore per satisfied preference
    (reference: nodelabel/node_label.go:70-93).  prefs: ((key_id,
    want_present)...)."""
    B = batch.req.shape[0]
    N = cluster.keymask.shape[0]
    if not prefs:
        return jnp.zeros((B, N), jnp.float32)
    score = jnp.zeros((N,), jnp.float32)
    for kid, want_present in prefs:
        has = (cluster.keymask[:, kid] if kid >= 0
               else jnp.zeros((N,), bool))
        hit = has if want_present else ~has
        score = score + jnp.where(hit, MAX_NODE_SCORE, 0.0)
    score = _idiv(score, float(len(prefs)))
    return jnp.broadcast_to(score[None, :], (B, N))
