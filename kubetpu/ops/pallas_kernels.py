"""Fused filter -> score-combine -> auction-propose Pallas megakernel.

The gang auction's round loop (models/gang.py round_step) is a chain of
XLA-fused-but-separate stages: NodeResourcesFit + NodePorts feasibility
materialize a [B, N] mask in HBM, run_scores materializes the [B, N]
weighted score matrix, and the propose step re-reads both to pick each
pod's argmax node.  Every auction round pays that HBM round trip
(auction_rounds_max is 4-13 at BENCH/MULTICHIP shapes), and the serial
round dependency — not FLOPs — bounds cycle latency.

This module is the Pallas beachhead for ROADMAP item 3: ONE kernel, tiled
over the node axis, that per [TB, TN] tile

  (a) computes the feasibility mask (static filter mask AND'd with the
      fit verdict against the round's committed usage and the hostPort
      conflict against the round's registered ports),
  (b) combines the weighted plugin scores (resource scorers from the
      evolving requested/nonzero carries; normalization-family scorers
      from per-pod statistics accumulated in a first grid phase), and
  (c) runs the propose step of the bidding round (masked score max +
      selectHost gumbel tie-break argmax),

with the per-tile [B, N_tile] score block living entirely in VMEM: per
round, HBM traffic is the carry reads plus three [B]-sized outputs — the
[B, N] mask/score intermediates never exist off-chip.  Admission stays on
the existing segmented-reduce logic in models/gang.py (it is O(B), not
O(B*N)), as does round 0 (whose [B, N] feasibility IS a GangResult
diagnostic output).  What remains for a later PR is full auction-LOOP
residency: the while_loop still lives at lax level, so score tiles are
re-streamed per round rather than pinned across rounds.

Bit-match oracle contract
-------------------------
The lax path is the oracle: for any supported (cfg, batch) this kernel's
(prop, active, best) are BIT-IDENTICAL to round_step's propose half.
Three properties make that tractable:

  * selectHost tie-breaks decompose: jax.random.categorical(key, logits)
    == argmax(gumbel(key, shape) + logits), and with the auction's
    0 / -2**62 logits the sum is exactly ``where(tie, gumbel, -2**62)``
    in f32 — so the gumbel matrix is precomputed ONCE from the same
    fold_in keys and the kernel only needs a cross-tile argmax whose
    first-index tie-break matches jnp.argmax.
  * every cross-node reduction the supported score family needs is
    either a float max/min (exactly associative) or a sum of
    integer-valued f32 (exact in any order below 2**24): per-pod
    normalization stats accumulate tile-by-tile without rounding drift.
  * everything else is elementwise, reusing the SAME jnp formula
    helpers as the lax kernels (balanced_formula/least_formula/...), so
    each element sees an identical f32 op sequence.

Supported surface (see kubetpu/utils/pallas_backend.unsupported_reason):
intra_batch_topology=False rounds (the host already routes term-free
batches there), score plugins whose feasibility dependence is per-pod
stats — the full default family.  PodTopologySpread soft scoring is
supported via its no-soft-constraints constant path (MaxNodeScore on
every feasible node), which is exactly what a term-free batch evaluates
to; batches carrying soft constraints fall back in the dispatcher (the
scheduler's needs_topo gate routes them away anyway, and the
schedule_gang wrapper's host-side batch inspection catches direct
callers — reason "soft-spread-constraints").
"""

from __future__ import annotations

from typing import Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from . import kernels as K
from ..state.tensors import CH_CPU, CH_MEM, CH_PODS, N_FIXED_CHANNELS
from ..utils.intern import pow2_bucket

try:  # capability probe: pallas is absent on some jaxlib builds
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    HAVE_PALLAS = True
except Exception:  # pragma: no cover - environment-dependent
    pl = None
    pltpu = None
    HAVE_PALLAS = False

_NEG = float(-2**62)
_BIG = float(2**62)
MAX_NODE_SCORE = K.MAX_NODE_SCORE

# score plugins whose raw matrix is round-invariant under
# intra_batch_topology=False and enters the kernel as a plane
_PLANE_OF = {
    "ImageLocality": "raw:ImageLocality",
    "NodeAffinity": "raw:NodeAffinity",
    "NodePreferAvoidPods": "raw:NodePreferAvoidPods",
    "TaintToleration": "raw:TaintToleration",
    "InterPodAffinity": "ipa_raw",
    "DefaultPodTopologySpread": "dps_raw",
}

# the full supported score family; anything else falls back to lax
SUPPORTED_SCORES = frozenset(_PLANE_OF) | frozenset({
    "NodeResourcesBalancedAllocation",
    "NodeResourcesLeastAllocated",
    "NodeResourcesMostAllocated",
    "PodTopologySpread",  # no-soft-constraints constant path (see above)
})

_LANE = 128  # TPU lane width: the natural node-tile quantum


def plane_order(cfg, has_bias: bool) -> Tuple[str, ...]:
    """Static plane layout of the stacked [S, B, N] input: score raws in
    cfg.scores order, then the optional host score bias, then the
    selectHost gumbel matrix (always last)."""
    names = []
    for name, _ in cfg.scores:
        key = _PLANE_OF.get(name)
        if key is not None and key not in names:
            names.append(key)
    if has_bias:
        names.append("bias")
    names.append("gumbel")
    return tuple(names)


def build_bundle(cluster, batch, cfg, static_ok, ports_ok0, score_pre,
                 score_bias, gumbel) -> Dict[str, jnp.ndarray]:
    """Precompute the megakernel's round-invariant inputs, once per
    auction (traced inside _schedule_gang).  All [B, N] planes here are
    assignment-independent under intra_batch_topology=False: the pod axis
    is frozen during the loop, so interpod/default-spread raws are
    round-invariant even though their lax twins recompute per round."""
    B = batch.req.shape[0]
    planes: Dict[str, jnp.ndarray] = {}
    ipa_any = jnp.zeros((B,), bool)
    for name, _ in cfg.scores:
        if name == "InterPodAffinity" and "ipa_raw" not in planes:
            raw, any_counts = K.interpod_score_raw(
                cluster, batch, pre=score_pre.get("interpod_score"),
                active_keys=cfg.active_keys)
            planes["ipa_raw"] = raw
            ipa_any = any_counts[:, 0]
        elif name == "DefaultPodTopologySpread" and "dps_raw" not in planes:
            planes["dps_raw"] = K.default_spread_score(
                cluster, batch, match_ns=score_pre.get("default_spread"))
        elif name in _PLANE_OF and _PLANE_OF[name] not in planes:
            planes[_PLANE_OF[name]] = score_pre["raw:" + name]
    if score_bias is not None:
        planes["bias"] = score_bias
    planes["gumbel"] = gumbel
    order = plane_order(cfg, score_bias is not None)
    stack = jnp.stack([planes[k].astype(jnp.float32) for k in order])
    zone = cluster.zone_hot
    if zone.shape[1] == 0:
        zone = jnp.zeros((zone.shape[0], 1), jnp.float32)
    return dict(
        planes=stack,                         # [S, B, N] f32
        mask=static_ok & ports_ok0,           # [B, N] bool
        ipa_any=ipa_any,                      # [B] bool
        skip=batch.spread_skip,               # [B] bool
        breq=batch.req,                       # [B, R] f32
        bnz=batch.nonzero_req,                # [B, 2] f32
        bports=batch.ports_hot,               # [B, P] f32
        alloc=cluster.allocatable,            # [N, R] f32 (node side)
        zone=zone,                            # [N, Z] f32 (node side)
    )


_POD_SIDE = ("planes", "mask", "ipa_any", "skip", "breq", "bnz", "bports")


def gather_bundle(bundle: Dict[str, jnp.ndarray], rows: jnp.ndarray,
                  B: int) -> Dict[str, jnp.ndarray]:
    """Row-gather the pod-side bundle tensors for a windowed sub-round.
    Sentinel rows (>= B) clip to row B-1; the caller's `live` vector is
    False there, so the kernel proposes the no-op segment for them."""
    rsafe = jnp.clip(rows, 0, B - 1)
    out = dict(bundle)
    for k in _POD_SIDE:
        axis = 1 if k == "planes" else 0
        out[k] = jnp.take(bundle[k], rsafe, axis=axis)
    return out


class _Layout(NamedTuple):
    """Static kernel layout, derived once per trace."""
    scores: Tuple[Tuple[str, float], ...]
    planes: Tuple[str, ...]
    use_fit: bool
    use_ports: bool
    stat_cols: Tuple[Tuple[str, int], ...]
    n_stats: int
    W: int
    N: int
    R: int
    P: int
    Z: int
    TB: int
    TN: int
    NT: int


def _layout(cfg, has_bias: bool, W: int, N: int, R: int, P: int,
            Z: int) -> _Layout:
    filters = set(cfg.filters)
    cols = []
    for name, _ in cfg.scores:
        if name == "NodeAffinity":
            cols.append("max_na")
        elif name == "TaintToleration":
            cols.append("max_tt")
        elif name == "InterPodAffinity":
            cols += ["max_ip", "min_ip"]
        elif name == "DefaultPodTopologySpread":
            cols += ["max_dps", "havez"]
    cols += ["act", "best", "hh"]
    stat_cols = tuple((c, i) for i, c in enumerate(dict.fromkeys(cols)))
    TB = min(_LANE, pow2_bucket(max(W, 1), 1))
    TN = min(_LANE, pow2_bucket(max(N, 1), 1))
    return _Layout(
        scores=tuple((n, float(w)) for n, w in cfg.scores),
        planes=plane_order(cfg, has_bias),
        use_fit="NodeResourcesFit" in filters,
        use_ports="NodePorts" in filters,
        stat_cols=stat_cols, n_stats=len(stat_cols),
        W=W, N=N, R=R, P=P, Z=Z, TB=TB, TN=TN,
        NT=-(-N // TN))


class Buf(NamedTuple):
    """One kernel buffer: a BlockSpec'd input/output or a VMEM scratch.
    ``shape`` is the BLOCK shape for in/out (full shape for scratch);
    ``index`` gives the grid->block index map per dim ("b" = pod-block
    axis, "n" = node-tile axis, "z" = pinned 0)."""
    name: str
    kind: str                  # "in" | "out" | "scratch"
    shape: Tuple[int, ...]
    dtype: str
    index: Tuple[str, ...] = ()


def kernel_buffers(L: _Layout, WB: int) -> Tuple[Buf, ...]:
    """The kernel's full buffer table, in pallas_call operand order.
    Single source of truth: propose() builds its BlockSpecs/out_shape/
    scratch_shapes from this, and tools/kubeexact computes the static
    VMEM budget from the same rows — the gate can never drift from the
    traced program."""
    Wpad = WB * L.TB
    return (
        Buf("planes", "in", (len(L.planes), L.TB, L.TN), "float32",
            ("z", "b", "n")),
        Buf("mask", "in", (L.TB, L.TN), "bool", ("b", "n")),
        Buf("alloc", "in", (L.TN, L.R), "float32", ("n", "z")),
        Buf("zone", "in", (L.TN, L.Z), "float32", ("n", "z")),
        Buf("req", "in", (L.TN, L.R), "float32", ("n", "z")),
        Buf("nz", "in", (L.TN, 2), "float32", ("n", "z")),
        Buf("ports_used", "in", (L.TN, L.P), "float32", ("n", "z")),
        Buf("breq", "in", (L.TB, L.R), "float32", ("b", "z")),
        Buf("bnz", "in", (L.TB, 2), "float32", ("b", "z")),
        Buf("bports", "in", (L.TB, L.P), "float32", ("b", "z")),
        Buf("live", "in", (L.TB,), "bool", ("b",)),
        Buf("skip", "in", (L.TB,), "bool", ("b",)),
        Buf("ipa_any", "in", (L.TB,), "bool", ("b",)),
        Buf("prop", "out", (L.TB,), "int32", ("b",)),
        Buf("best", "out", (L.TB,), "float32", ("b",)),
        Buf("act", "out", (L.TB,), "bool", ("b",)),
        Buf("stats", "scratch", (Wpad, L.n_stats), "float32"),
        Buf("czone", "scratch", (Wpad, L.Z), "float32"),
        Buf("idxs", "scratch", (Wpad,), "int32"),
    )


def _make_kernel(L: _Layout):
    """Build the kernel body for one static layout.  Phase 0 sweeps the
    node tiles accumulating the per-pod normalization statistics; phase 1
    re-derives feasibility (VPU recompute is cheaper than an HBM round
    trip), combines the weighted scores and folds the propose argmax."""
    col = {name: i for name, i in L.stat_cols}
    plane = {name: i for i, name in enumerate(L.planes)}

    def kernel(planes_ref, mask_ref, alloc_ref, zone_ref, req_ref, nz_ref,
               pu_ref, breq_ref, bnz_ref, bports_ref, live_ref, skip_ref,
               ipaany_ref, prop_ref, best_ref, act_ref, stats, czone, idxs):
        p = pl.program_id(0)
        b = pl.program_id(1)
        n = pl.program_id(2)
        sl = pl.ds(b * L.TB, L.TB)
        col_ok = (n * L.TN + jax.lax.broadcasted_iota(
            jnp.int32, (L.TB, L.TN), 1)) < L.N

        def feas_tile():
            f = mask_ref[...] & live_ref[...][:, None] & col_ok
            breq = breq_ref[...]
            if L.use_fit:
                alloc = alloc_ref[...]
                used = req_ref[...]
                pods_ok = (alloc[:, CH_PODS][None, :]
                           >= breq[:, CH_PODS][:, None]
                           + used[:, CH_PODS][None, :])
                res_ok = jnp.ones((L.TB, L.TN), bool)
                zero_req = jnp.ones((L.TB,), bool)
                for r in range(L.R):
                    if r == CH_PODS:
                        continue
                    free_ok = (alloc[:, r][None, :]
                               >= breq[:, r][:, None] + used[:, r][None, :])
                    if r < N_FIXED_CHANNELS:
                        res_ok = res_ok & free_ok
                    else:
                        res_ok = res_ok & (free_ok
                                           | (breq[:, r] <= 0)[:, None])
                    zero_req = zero_req & (breq[:, r] == 0)
                f = f & pods_ok & (zero_req[:, None] | res_ok)
            if L.use_ports:
                conflict = jnp.dot(bports_ref[...], pu_ref[...].T,
                                   preferred_element_type=jnp.float32) > 0.5
                f = f & ~conflict
            return f

        def resource_fracs():
            bnz = bnz_ref[...]
            nzc = nz_ref[...]
            alloc = alloc_ref[...]
            req_cpu = nzc[:, 0][None, :] + bnz[:, 0][:, None]
            req_mem = nzc[:, 1][None, :] + bnz[:, 1][:, None]
            alloc_cpu = jnp.broadcast_to(alloc[:, CH_CPU][None, :],
                                         (L.TB, L.TN))
            alloc_mem = jnp.broadcast_to(alloc[:, CH_MEM][None, :],
                                         (L.TB, L.TN))
            return req_cpu, req_mem, alloc_cpu, alloc_mem

        def zone_tile():
            ztile = zone_ref[...]
            cok = (n * L.TN + jax.lax.broadcasted_iota(
                jnp.int32, (L.TN, 1), 0).reshape(L.TN)) < L.N
            return jnp.where(cok[:, None], ztile, 0.0)

        # ---- phase 0: per-pod normalization statistics -----------------
        @pl.when(p == 0)
        def _():
            f = feas_tile()

            def acc(name, tile_val, comb):
                c = col[name]

                @pl.when(n == 0)
                def _():
                    stats[sl, c] = tile_val

                @pl.when(n > 0)
                def _():
                    stats[sl, c] = comb(stats[sl, c], tile_val)

            # bool -> f32 cast, not where(f, 1.0, 0.0): two python-float
            # branches commit the default float dtype, which is f64
            # wherever x64 is enabled (census/f64-promotion)
            acc("act", jnp.max(f.astype(jnp.float32), axis=1),
                jnp.maximum)
            if "max_na" in col:
                raw = planes_ref[plane["raw:NodeAffinity"]]
                acc("max_na", jnp.max(jnp.where(f, raw, _NEG), axis=1),
                    jnp.maximum)
            if "max_tt" in col:
                raw = planes_ref[plane["raw:TaintToleration"]]
                acc("max_tt", jnp.max(jnp.where(f, raw, _NEG), axis=1),
                    jnp.maximum)
            if "max_ip" in col:
                raw = planes_ref[plane["ipa_raw"]]
                acc("max_ip", jnp.max(jnp.where(f, raw, _NEG), axis=1),
                    jnp.maximum)
                acc("min_ip", jnp.min(jnp.where(f, raw, _BIG), axis=1),
                    jnp.minimum)
            if "max_dps" in col:
                raw = planes_ref[plane["dps_raw"]]
                zt = zone_tile()
                acc("max_dps", jnp.max(jnp.where(f, raw, _NEG), axis=1),
                    jnp.maximum)
                has_zone = jnp.any(zt > 0, axis=1)
                acc("havez",
                    jnp.max((f & has_zone[None, :]).astype(jnp.float32),
                            axis=1), jnp.maximum)
                cz = jnp.dot(jnp.where(f, raw, 0.0), zt,
                             preferred_element_type=jnp.float32)

                @pl.when(n == 0)
                def _():
                    czone[sl, :] = cz

                @pl.when(n > 0)
                def _():
                    czone[sl, :] = czone[sl, :] + cz

        # ---- phase 1: score combine + propose --------------------------
        @pl.when(p == 1)
        def _():
            f = feas_tile()
            total = jnp.zeros((L.TB, L.TN), jnp.float32)
            for name, weight in L.scores:
                if name == "NodeResourcesBalancedAllocation":
                    s = K.balanced_formula(*resource_fracs())
                elif name == "NodeResourcesLeastAllocated":
                    rc, rm, ac, am = resource_fracs()
                    s = K._idiv(K.least_formula(rc, ac) * 1.0
                                + K.least_formula(rm, am) * 1.0, 2.0)
                elif name == "NodeResourcesMostAllocated":
                    rc, rm, ac, am = resource_fracs()
                    s = K._idiv(K.most_formula(rc, ac) * 1.0
                                + K.most_formula(rm, am) * 1.0, 2.0)
                elif name == "ImageLocality":
                    s = planes_ref[plane["raw:ImageLocality"]]
                elif name == "NodePreferAvoidPods":
                    s = planes_ref[plane["raw:NodePreferAvoidPods"]]
                elif name == "NodeAffinity":
                    raw = planes_ref[plane["raw:NodeAffinity"]]
                    max_c = jnp.maximum(stats[sl, col["max_na"]], 0.0)
                    scaled = K._idiv(MAX_NODE_SCORE * raw,
                                     jnp.maximum(max_c, 1.0)[:, None])
                    s = jnp.where((max_c > 0)[:, None], scaled, 0.0)
                elif name == "TaintToleration":
                    raw = planes_ref[plane["raw:TaintToleration"]]
                    max_c = jnp.maximum(stats[sl, col["max_tt"]], 0.0)
                    scaled = MAX_NODE_SCORE - K._idiv(
                        MAX_NODE_SCORE * raw,
                        jnp.maximum(max_c, 1.0)[:, None])
                    s = jnp.where((max_c > 0)[:, None], scaled,
                                  MAX_NODE_SCORE)
                elif name == "InterPodAffinity":
                    raw = planes_ref[plane["ipa_raw"]]
                    max_c = jnp.maximum(stats[sl, col["max_ip"]], 0.0)
                    min_c = jnp.minimum(stats[sl, col["min_ip"]], 0.0)
                    diff = max_c - min_c
                    norm = jnp.where(
                        (diff > 0)[:, None],
                        K._idiv(MAX_NODE_SCORE * (raw - min_c[:, None]),
                                jnp.maximum(diff, 1.0)[:, None]), 0.0)
                    s = jnp.where(ipaany_ref[...][:, None], norm, raw)
                elif name == "PodTopologySpread":
                    # no-soft-constraints constant path (scoring.go
                    # maxScore==0): MaxNodeScore on every feasible node
                    s = jnp.where(f, MAX_NODE_SCORE, 0.0)
                elif name == "DefaultPodTopologySpread":
                    raw = planes_ref[plane["dps_raw"]]
                    zt = zone_tile()
                    max_node = jnp.maximum(stats[sl, col["max_dps"]], 0.0)
                    f_score = jnp.where(
                        (max_node > 0)[:, None],
                        MAX_NODE_SCORE * (max_node[:, None] - raw)
                        / jnp.maximum(max_node, 1.0)[:, None],
                        MAX_NODE_SCORE)
                    cz = czone[sl, :]
                    max_zone = jnp.maximum(jnp.max(cz, axis=1), 0.0)
                    nzc = jnp.dot(cz, zt.T,
                                  preferred_element_type=jnp.float32)
                    zone_score = jnp.where(
                        (max_zone > 0)[:, None],
                        MAX_NODE_SCORE * (max_zone[:, None] - nzc)
                        / jnp.maximum(max_zone, 1.0)[:, None],
                        MAX_NODE_SCORE)
                    with_zone = (f_score * (1.0 - K.ZONE_WEIGHTING)
                                 + K.ZONE_WEIGHTING * zone_score)
                    havez = stats[sl, col["havez"]] > 0
                    has_zone = jnp.any(zt > 0, axis=1)
                    out = jnp.where(havez[:, None] & has_zone[None, :],
                                    with_zone, f_score)
                    out = jnp.floor(out)
                    s = jnp.where(skip_ref[...][:, None], 0.0, out)
                else:  # pragma: no cover - unsupported_reason() gates this
                    raise ValueError("pallas backend: unsupported score "
                                     "kernel %s" % name)
                total = total + jnp.where(f, s, 0.0) * weight
            if "bias" in plane:
                total = total + planes_ref[plane["bias"]]
            gum = planes_ref[plane["gumbel"]]
            # blessed gumbel decomposition (ops/kernels.py): same tuple
            # the shard_map tiled surface folds across the node axis
            tile_best, tile_h, tile_arg = K.gumbel_tiebreak_argmax(
                total, f, gum, n * L.TN, _NEG)

            @pl.when(n == 0)
            def _():
                stats[sl, col["best"]] = tile_best
                stats[sl, col["hh"]] = tile_h
                idxs[sl] = tile_arg

            @pl.when(n > 0)
            def _():
                rb = stats[sl, col["best"]]
                rh = stats[sl, col["hh"]]
                ri = idxs[sl]
                # first-index tie-break: update only on STRICT improvement
                # (earlier tiles, and jnp.argmax within a tile, keep the
                # lowest index on exact equality — matching the oracle)
                upd = tile_best > rb
                updh = (tile_best == rb) & (tile_h > rh)
                stats[sl, col["best"]] = jnp.where(upd, tile_best, rb)
                stats[sl, col["hh"]] = jnp.where(
                    upd, tile_h, jnp.where(updh, tile_h, rh))
                idxs[sl] = jnp.where(upd, tile_arg,
                                     jnp.where(updh, tile_arg, ri))

            @pl.when(n == L.NT - 1)
            def _():
                act = stats[sl, col["act"]] > 0
                best_ref[...] = stats[sl, col["best"]]
                prop_ref[...] = jnp.where(act, idxs[sl], L.N).astype(
                    jnp.int32)
                act_ref[...] = act

    return kernel


def propose(bundle: Dict[str, jnp.ndarray], cfg, live: jnp.ndarray,
            req: jnp.ndarray, nz: jnp.ndarray, ports_used: jnp.ndarray,
            n_nodes: int, interpret: bool):
    """One fused propose step -> (prop [W] i32 in [0, N] with N = no-op,
    active [W] bool, best [W] f32) — bit-identical to the lax round's
    propose half for supported configurations."""
    W = int(live.shape[0])
    N = int(n_nodes)
    R = int(bundle["alloc"].shape[1])
    P = int(bundle["bports"].shape[1])
    Z = int(bundle["zone"].shape[1])
    has_bias = bundle["planes"].shape[0] == len(plane_order(cfg, True))
    L = _layout(cfg, has_bias, W, N, R, P, Z)
    WB = -(-W // L.TB)
    Wpad = WB * L.TB

    def padw(x, fill=0):
        if Wpad == x.shape[0]:
            return x
        pad = [(0, Wpad - x.shape[0])] + [(0, 0)] * (x.ndim - 1)
        return jnp.pad(x, pad, constant_values=fill)

    def padw1(x, fill=0):  # planes: pad axis 1
        if Wpad == x.shape[1]:
            return x
        return jnp.pad(x, [(0, 0), (0, Wpad - x.shape[1]), (0, 0)],
                       constant_values=fill)

    kernel = _make_kernel(L)
    grid = (2, WB, L.NT)
    bufs = kernel_buffers(L, WB)

    def spec(bf: Buf) -> "pl.BlockSpec":
        dims = bf.index
        return pl.BlockSpec(
            bf.shape,
            lambda p, b, n, dims=dims: tuple(
                b if t == "b" else n if t == "n" else 0 for t in dims))

    # an out's full shape tiles its block over the grid axes it indexes
    def full(bf: Buf) -> Tuple[int, ...]:
        mult = {"b": WB, "n": L.NT, "z": 1}
        return tuple(d * mult[t] for d, t in zip(bf.shape, bf.index))

    prop, best, act = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[spec(bf) for bf in bufs if bf.kind == "in"],
        out_specs=tuple(spec(bf) for bf in bufs if bf.kind == "out"),
        out_shape=tuple(
            jax.ShapeDtypeStruct(full(bf), jnp.dtype(bf.dtype))
            for bf in bufs if bf.kind == "out"),
        scratch_shapes=[
            pltpu.VMEM(bf.shape, jnp.dtype(bf.dtype))
            for bf in bufs if bf.kind == "scratch"],
        interpret=interpret,
    )(
        padw1(bundle["planes"]), padw(bundle["mask"]),
        bundle["alloc"], bundle["zone"], req, nz, ports_used,
        padw(bundle["breq"]), padw(bundle["bnz"]), padw(bundle["bports"]),
        padw(live), padw(bundle["skip"]), padw(bundle["ipa_any"]),
    )
    return prop[:W], act[:W], best[:W]
