"""Batched label-selector matching as dense tensor ops.

This is the TPU-native replacement for the reference's per-object
``labels.Selector.Matches`` calls scattered across every plugin
(reference: staging/src/k8s.io/apimachinery/pkg/labels/selector.go,
pkg/scheduler/framework/plugins/*/): a *selector* is compiled host-side
into multi-hot vectors over the interned (key,value) / key vocabularies,
and matching S selectors against M targets (nodes or pods) becomes two
batched matmuls on the MXU plus elementwise logic — no per-object string
work on the hot path.

Semantics per requirement (AND across requirements of one selector):
  In(key, vals)      -> target has any interned (key,v) for v in vals
  NotIn(key, vals)   -> negation of In  (key absent also matches)
  Exists(key)        -> target has the key
  DoesNotExist(key)  -> negation of Exists
  Gt/Lt(key, val)    -> numeric parse of the target's label value compared
                        to val; unparsable/missing never matches
matching apimachinery's Requirement.Matches (selector.go:214-260).
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional, Sequence, Union

import jax.numpy as jnp
import numpy as np

from ..api import types as api
from ..utils.intern import InternTable, pow2_bucket


class SelectorSet(NamedTuple):
    """S selector *slots* backed by U <= S unique compiled selectors.

    Pods stamped out by one controller share identical selectors, so the
    compiler dedups: the dense requirement tensors are stored once per
    unique selector and each slot carries only an index.  This is the
    difference between O(B x L) and O(U x L) memory/FLOPs for a B-pod batch
    (hollow 100k-pod batches have U in the tens), and it is invisible to
    callers — match_selectors still returns [S, M].

    vals_hot : [U, Q, L] bool multi-hot over (key,value) vocab (In/NotIn)
    key_hot  : [U, Q, K] bool multi-hot over key vocab (Exists/DoesNotExist)
    negate   : [U, Q] bool    requirement result is inverted
    use_key  : [U, Q] bool    requirement tests key presence, not values
    req_valid: [U, Q] bool    padding mask for requirements
    num_key  : [U, Q] i32     key index for Gt/Lt (0 if unused)
    num_op   : [U, Q] i32     0 = none, 1 = Gt, 2 = Lt
    num_val  : [U, Q] f32     comparison constant for Gt/Lt
    sel_valid: [U] bool       nil/padding selectors (match nothing)
    index    : [S] i32        slot -> unique row
    """
    vals_hot: jnp.ndarray
    key_hot: jnp.ndarray
    negate: jnp.ndarray
    use_key: jnp.ndarray
    req_valid: jnp.ndarray
    num_key: jnp.ndarray
    num_op: jnp.ndarray
    num_val: jnp.ndarray
    sel_valid: jnp.ndarray
    index: jnp.ndarray

    @property
    def n_selectors(self) -> int:
        return self.index.shape[0]


def match_selectors(sel: SelectorSet,
                    kv: jnp.ndarray,      # [M, L] bool/float — target has (key,value)
                    key: jnp.ndarray,     # [M, K] bool/float — target has key
                    num: Optional[jnp.ndarray] = None,  # [M, K] f32 numeric label values (+inf = non-numeric)
                    ) -> jnp.ndarray:
    """Match S selector slots against M targets -> [S, M] bool.

    The two einsums are batched matmuls over the U unique selectors;
    per-slot results are a gather on the slot index.
    """
    return jnp.take(match_selectors_unique(sel, kv, key, num), sel.index,
                    axis=0)


def match_selectors_unique(sel: SelectorSet,
                           kv: jnp.ndarray,
                           key: jnp.ndarray,
                           num: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """The [U, M] unique-selector match matrix behind match_selectors;
    slot s maps to row sel.index[s].  Consumers that aggregate per unique
    selector (e.g. gang's intra-round deferral) use this directly to stay
    O(U x M) instead of O(S x M)."""
    kv_f = kv.astype(jnp.float32)
    key_f = key.astype(jnp.float32)
    cnt_v = jnp.einsum("uql,ml->uqm", sel.vals_hot.astype(jnp.float32), kv_f,
                       preferred_element_type=jnp.float32)
    cnt_k = jnp.einsum("uqk,mk->uqm", sel.key_hot.astype(jnp.float32), key_f,
                       preferred_element_type=jnp.float32)
    present = jnp.where(sel.use_key[..., None], cnt_k > 0.5, cnt_v > 0.5)
    ok = present ^ sel.negate[..., None]

    if num is not None:
        # Gt/Lt: gather each requirement's numeric label column.
        nval = jnp.take(num.T, jnp.clip(sel.num_key, 0, num.shape[1] - 1),
                        axis=0)  # [U, Q, M]
        is_gt = sel.num_op[..., None] == 1
        cmp = jnp.where(is_gt, nval > sel.num_val[..., None],
                        nval < sel.num_val[..., None])
        # absent/non-numeric labels are +inf (NaN-free cluster contract,
        # state/tensors.py): isfinite fails them for both Gt and Lt
        cmp = jnp.logical_and(cmp, jnp.isfinite(nval))
        ok = jnp.where(sel.num_op[..., None] > 0, cmp, ok)

    ok = jnp.logical_or(ok, jnp.logical_not(sel.req_valid[..., None]))
    return jnp.logical_and(jnp.all(ok, axis=1), sel.sel_valid[:, None])


def pad_selector_slots(s: SelectorSet, to: int) -> SelectorSet:
    """Pad the SLOT axis to `to` entries (index 0, callers mask via their
    own validity arrays — every consumer ANDs a valid mask over slots)."""
    idx = jnp.asarray(s.index)
    n = to - idx.shape[0]
    if n <= 0:
        return s
    return s._replace(index=jnp.concatenate(
        [idx, jnp.zeros((n,), idx.dtype)]))


def concat_selector_sets(a: SelectorSet, b: SelectorSet) -> SelectorSet:
    """Concatenate two SelectorSets compiled against the SAME vocab (same
    InternTable): unique rows are stacked (b's slot indices shifted), and the
    requirement axis is padded to the larger Q.  Works on traced arrays, so
    gang mode can splice batch-pod terms into the snapshot's ExistingTerms
    inside jit."""
    qa, qb = a.req_valid.shape[1], b.req_valid.shape[1]
    q = max(qa, qb)

    def padq(x, have):
        if have == q:
            return x
        pad = [(0, 0)] * x.ndim
        pad[1] = (0, q - have)
        return jnp.pad(x, pad)

    ua = a.sel_valid.shape[0]
    return SelectorSet(
        vals_hot=jnp.concatenate([padq(a.vals_hot, qa), padq(b.vals_hot, qb)]),
        key_hot=jnp.concatenate([padq(a.key_hot, qa), padq(b.key_hot, qb)]),
        negate=jnp.concatenate([padq(a.negate, qa), padq(b.negate, qb)]),
        use_key=jnp.concatenate([padq(a.use_key, qa), padq(b.use_key, qb)]),
        req_valid=jnp.concatenate([padq(a.req_valid, qa),
                                   padq(b.req_valid, qb)]),
        num_key=jnp.concatenate([padq(a.num_key, qa), padq(b.num_key, qb)]),
        num_op=jnp.concatenate([padq(a.num_op, qa), padq(b.num_op, qb)]),
        num_val=jnp.concatenate([padq(a.num_val, qa), padq(b.num_val, qb)]),
        sel_valid=jnp.concatenate([a.sel_valid, b.sel_valid]),
        index=jnp.concatenate([jnp.asarray(a.index),
                               jnp.asarray(b.index) + ua]),
    )


# ---------------------------------------------------------------------------
# host-side compiler


SelectorLike = Union[api.LabelSelector, api.NodeSelectorTerm, dict, None]

# Synthetic label-key prefix for NodeSelectorTerm.match_fields (the only
# supported field is metadata.name, reference:
# pkg/apis/core/v1/helper/helpers.go GetNodeFieldSelectorMap).
FIELD_PREFIX = "__field__"


class _Req(NamedTuple):
    op: str
    key: str
    values: Sequence[str]


def _reqs_of(sel: SelectorLike) -> Optional[List[_Req]]:
    """Normalize any selector-ish object to a requirement list; None => the
    selector matches nothing (nil selector)."""
    if sel is None:
        return None
    if isinstance(sel, dict):  # plain match-labels map (e.g. spec.nodeSelector)
        return [_Req("In", k, [v]) for k, v in sorted(sel.items())]
    if isinstance(sel, api.LabelSelector):
        return [_Req(r.operator, r.key, list(r.values)) for r in sel.requirements()]
    if isinstance(sel, api.NodeSelectorTerm):
        reqs = [_Req(r.operator, r.key, list(r.values)) for r in sel.match_expressions]
        reqs += [_Req(r.operator, FIELD_PREFIX + r.key, list(r.values))
                 for r in sel.match_fields]
        # A term with no expressions and no fields matches nothing
        # (reference: pkg/apis/core/v1/helper/helpers.go:180 MatchNodeSelectorTerms).
        if not reqs:
            return None
        return reqs
    raise TypeError(f"unsupported selector type {type(sel)}")


class SelectorCompiler:
    """Compiles host selector objects into a SelectorSet of numpy arrays."""

    def __init__(self, table: InternTable):
        self.table = table

    def compile(self, selectors: Sequence[SelectorLike],
                pad_s: Optional[int] = None,
                intern_new: bool = True) -> SelectorSet:
        """intern_new: selectors may introduce vocab entries (normally the
        snapshot builder has already interned all cluster labels; pod
        selectors referencing unknown values simply never match, so lookups
        use get() when intern_new=False).

        Identical requirement lists compile to ONE unique row shared via the
        slot index — both the numpy build work and the device tensors scale
        with the number of distinct selectors, not the batch size."""
        all_req_lists = [_reqs_of(s) for s in selectors]
        S = pad_s if pad_s is not None else pow2_bucket(len(selectors), 1)
        if S < len(selectors):
            raise ValueError("pad_s smaller than selector count")

        uniq: dict = {}
        index = np.zeros((S,), np.int32)
        req_lists: List[Optional[List[_Req]]] = []
        for i in range(S):
            reqs = all_req_lists[i] if i < len(all_req_lists) else None
            k = None if reqs is None else tuple(
                (r.op, r.key, tuple(r.values)) for r in reqs)
            u = uniq.get(k)
            if u is None:
                u = len(req_lists)
                uniq[k] = u
                req_lists.append(reqs)
            index[i] = u

        max_q = max((len(r) for r in req_lists if r), default=1)
        Q = pow2_bucket(max_q, 2)
        U = pow2_bucket(len(req_lists), 1)
        L, K = self.table.kv.cap, self.table.key.cap

        vals_hot = np.zeros((U, Q, L), bool)
        key_hot = np.zeros((U, Q, K), bool)
        negate = np.zeros((U, Q), bool)
        use_key = np.zeros((U, Q), bool)
        req_valid = np.zeros((U, Q), bool)
        num_key = np.zeros((U, Q), np.int32)
        num_op = np.zeros((U, Q), np.int32)
        num_val = np.zeros((U, Q), np.float32)
        sel_valid = np.zeros((U,), bool)

        kv_id = (self.table.kv.intern if intern_new else self.table.kv.get)
        key_id = (self.table.key.intern if intern_new else self.table.key.get)

        for i, reqs in enumerate(req_lists):
            if reqs is None:
                continue  # matches nothing
            sel_valid[i] = True
            for q, r in enumerate(reqs):
                req_valid[i, q] = True
                if r.op in ("In", "NotIn"):
                    for v in r.values:
                        j = kv_id((r.key, v))
                        if j >= 0:
                            vals_hot[i, q, j] = 1.0
                    negate[i, q] = (r.op == "NotIn")
                elif r.op in ("Exists", "DoesNotExist"):
                    j = key_id(r.key)
                    if j >= 0:
                        key_hot[i, q, j] = 1.0
                    use_key[i, q] = True
                    negate[i, q] = (r.op == "DoesNotExist")
                elif r.op in ("Gt", "Lt"):
                    j = key_id(r.key)
                    num_key[i, q] = max(j, 0)
                    num_op[i, q] = 1 if r.op == "Gt" else 2
                    try:
                        num_val[i, q] = float(int(r.values[0]))
                    except (ValueError, IndexError):
                        # unparsable constant never matches: impossible compare
                        num_op[i, q] = 1
                        num_val[i, q] = np.inf
                    if j < 0:
                        # unknown key can never be numeric-matched
                        num_val[i, q] = np.inf if r.op == "Gt" else -np.inf
                else:
                    raise ValueError(f"unknown selector op {r.op}")

        return SelectorSet(vals_hot=vals_hot, key_hot=key_hot, negate=negate,
                           use_key=use_key, req_valid=req_valid, num_key=num_key,
                           num_op=num_op, num_val=num_val, sel_valid=sel_valid,
                           index=index)
