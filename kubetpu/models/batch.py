"""Pending-pod batch tensorization.

The reference schedules strictly one pod per cycle (reference:
pkg/scheduler/scheduler.go:509 scheduleOne); the TPU framework lifts a whole
batch of B pending pods into dense arrays and runs Filter+Score for all of
them in one XLA program.  Everything string-typed is resolved against the
cluster InternTable at batch-build time (lookups only — a pod referencing a
label value that exists nowhere in the cluster simply never matches).
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional, Sequence

import numpy as np

from ..api import types as api
from ..framework.types import PodInfo, compute_pod_resource_limits
from ..ops.selectors import FIELD_PREFIX, SelectorCompiler, SelectorSet
from ..state.tensors import (MIB, N_FIXED_CHANNELS, CH_PODS, port_ids_pod,
                             resource_to_channels, _norm_image)
from ..utils.intern import InternTable, pow2_bucket


class PodTerms(NamedTuple):
    """Flattened pod-side (anti-)affinity terms, matched against existing
    pods (reference: framework/v1alpha1/types.go:79 AffinityTerm).
    Selector set is flat [B*T]; companion arrays are [B, T]."""
    sel: SelectorSet
    ns_hot: np.ndarray    # [B, T, NS]
    topo_key: np.ndarray  # [B, T] i32 (index into topokey axis)
    topo_known: np.ndarray  # [B, T] bool — topology key exists in cluster vocab
    weight: np.ndarray    # [B, T] f32 (signed for preferred anti)
    valid: np.ndarray     # [B, T] bool
    self_match: np.ndarray  # [B, T] bool — incoming pod matches its own term
                            # (the bootstrap rule, interpodaffinity/filtering.go:353)


class SpreadConstraints(NamedTuple):
    """Topology spread constraints per pod
    (reference: podtopologyspread/common.go:70 topologySpreadConstraint)."""
    sel: SelectorSet      # [B*C] over existing pods
    topo_key: np.ndarray  # [B, C] i32
    topo_known: np.ndarray  # [B, C] bool
    max_skew: np.ndarray  # [B, C] f32
    valid: np.ndarray     # [B, C] bool
    self_match: np.ndarray  # [B, C] bool — pod's own labels match the
                            # constraint selector (counts itself when placed)


class PodBatch(NamedTuple):
    """B pending pods as a struct-of-arrays (a JAX pytree once jnp-ified).

    Pod label sets travel to the device as compact id lists (kv_ids/key_ids)
    and are densified to [B, L]/[B, K] one-hots on device by densify() at
    program entry — a pod has O(10) labels, so shipping [B, L] dense floats
    would waste transfer bandwidth by ~L/10x.  kv_hot/key_hot are None until
    densify() fills them."""
    req: np.ndarray            # [B, R] resource request channels
    nonzero_req: np.ndarray    # [B, 2] (cpu milli, mem MiB) with defaults
    limits: np.ndarray         # [B, R] resource limit channels
    kv_ids: np.ndarray         # [B, ML] i32 label (key,value) vocab ids, -1 pad
    key_ids: np.ndarray        # [B, ML] i32 label key vocab ids, -1 pad
    kv_hot: Optional[np.ndarray]   # [B, L] bool — filled on device
    key_hot: Optional[np.ndarray]  # [B, K] bool — filled on device
    ns_hot: np.ndarray         # [B, NS] f32 one-hot namespace
    node_name_kvid: np.ndarray  # [B] i32 kv id of (__field__metadata.name, spec.nodeName); -1 unset
    has_node_name: np.ndarray  # [B] bool
    ports_hot: np.ndarray      # [B, P] f32 — ids the pod *probes* for conflicts
    ports_asnode_hot: np.ndarray  # [B, P] f32 — ids the pod *registers* once
                               # placed (for intra-batch conflicts in the scan)
    tolerated: np.ndarray      # [B, T] bool over taint vocab
    priority: np.ndarray       # [B] i32
    images_hot: np.ndarray     # [B, I] f32 — container images (non-init)
    n_containers: np.ndarray   # [B] f32 — len(spec.containers) for ImageLocality
    avoid_id: np.ndarray       # [B] i32 — (controllerRef kind, uid) vocab id, -1 if
                               #   not controlled by an RC/RS (NodePreferAvoidPods)
    tolerates_unschedulable: np.ndarray  # [B] bool — tolerates the
                               #   node.kubernetes.io/unschedulable:NoSchedule taint
    node_selector: SelectorSet  # [B] spec.nodeSelector as a selector
    rna_sel: SelectorSet       # [B*Tn] required node affinity terms (ORed)
    rna_valid: np.ndarray      # [B, Tn]
    has_rna: np.ndarray        # [B] bool
    pna_sel: SelectorSet       # [B*Tp] preferred node affinity terms
    pna_weight: np.ndarray     # [B, Tp] f32
    pna_valid: np.ndarray      # [B, Tp]
    ra: PodTerms               # required pod affinity
    raa: PodTerms              # required pod anti-affinity
    pref: PodTerms             # preferred affinity and anti (signed weights)
    spread: SpreadConstraints  # hard (DoNotSchedule) constraints
    spread_soft: SpreadConstraints  # soft (ScheduleAnyway) constraints
    spread_selector: SelectorSet  # [B] DefaultPodTopologySpread selector (the
                               # combined service/RC/RS/SS selector; nil => score 0)
    spread_skip: np.ndarray    # [B] bool — pod has explicit spread constraints, so
                               # DefaultPodTopologySpread is skipped entirely
    valid: np.ndarray          # [B] bool padding mask

    @property
    def batch_cap(self) -> int:
        return self.req.shape[0]


class NominatedPods(NamedTuple):
    """Pods nominated to nodes by preemption, overlaid onto node usage when
    filtering lower/equal-priority pods (reference: addNominatedPods,
    core/generic_scheduler.go:530 — equal-or-greater priority nominated pods
    are treated as running on their nominated node).  The tensor overlay
    covers the resource/pod-count dimension of AddPod; topology-term
    contributions of nominated pods are not overlaid."""
    req: np.ndarray    # [M, R] request channels (CH_PODS = 1)
    node: np.ndarray   # [M] i32 node row
    prio: np.ndarray   # [M] i32 pod priority
    valid: np.ndarray  # [M] bool
    self_row: np.ndarray  # [M] i32 — the nominated pod's own row in the
                       # CURRENT batch (-1 if not in it); a pod never
                       # overlays itself (addNominatedPods skips the pod
                       # being scheduled)


def build_nominated(entries: Sequence, table: InternTable,
                    pad_m: Optional[int] = None) -> NominatedPods:
    """entries: (PodInfo, node_row) or (PodInfo, node_row, self_row) tuples
    for pods nominated to snapshot rows.  Returns the device overlay arrays
    (pow2-padded)."""
    R = N_FIXED_CHANNELS + table.rname.cap
    M = pad_m if pad_m is not None else pow2_bucket(len(entries), 1)
    req = np.zeros((M, R), np.float32)
    node = np.full((M,), -1, np.int32)
    prio = np.zeros((M,), np.int32)
    valid = np.zeros((M,), bool)
    self_row = np.full((M,), -1, np.int32)
    for i, entry in enumerate(entries):
        pi, row = entry[0], entry[1]
        req[i] = resource_to_channels(pi.resource, table, R, intern_new=False)
        req[i, CH_PODS] = 1.0
        node[i] = row
        prio[i] = pi.pod.priority()
        valid[i] = True
        if len(entry) > 2:
            self_row[i] = entry[2]
    return NominatedPods(req=req, node=node, prio=prio, valid=valid,
                         self_row=self_row)


def densify_for(cluster, batch: "PodBatch") -> "PodBatch":
    """Materialize the [B, L]/[B, K] pod-label one-hots from the id lists,
    sized to the cluster tensors' vocab capacities.  Called once at
    jitted-program entry (idempotent).  Ids at or beyond the cluster
    capacity (interned after the snapshot arrays were sized) are dropped —
    such labels exist nowhere in the cluster, so they can never match."""
    import jax.numpy as jnp
    if batch.kv_hot is not None:
        return batch
    L, K = cluster.kv.shape[1], cluster.keymask.shape[1]
    B = batch.kv_ids.shape[0]
    rows = jnp.arange(B)[:, None]
    kv_hot = jnp.zeros((B, L), bool).at[
        rows, jnp.clip(batch.kv_ids, 0, L - 1)].max(
        (batch.kv_ids >= 0) & (batch.kv_ids < L))
    key_hot = jnp.zeros((B, K), bool).at[
        rows, jnp.clip(batch.key_ids, 0, K - 1)].max(
        (batch.key_ids >= 0) & (batch.key_ids < K))
    return batch._replace(kv_hot=kv_hot, key_hot=key_hot)


def gather_batch_rows(batch: "PodBatch", rows: np.ndarray) -> "PodBatch":
    """Select pod rows (numpy; -1 entries are padding -> valid False).
    The residual-auction host loop uses this to re-run only the CONTENDED
    pods of a batch.  Selector sets gather by slot index — the unique
    compiled tensors are shared, so this is O(rows), not O(vocab)."""
    B = batch.valid.shape[0]
    U = rows.shape[0]
    safe = np.clip(rows, 0, B - 1)
    live = rows >= 0

    def arr(x):
        if x is None:
            return None
        x = np.asarray(x)
        if x.ndim >= 1 and x.shape[0] == B:          # [B, ...]
            return x[safe]
        if x.ndim >= 1 and x.shape[0] % B == 0:      # flat [B*T, ...]
            t = x.shape[0] // B
            return x.reshape((B, t) + x.shape[1:])[safe].reshape(
                (U * t,) + x.shape[1:])
        return x

    def sel(s: SelectorSet) -> SelectorSet:
        return s._replace(index=arr(np.asarray(s.index)))

    def walk(v):
        if isinstance(v, SelectorSet):
            return sel(v)
        if isinstance(v, (PodTerms, SpreadConstraints)):
            return type(v)(*[walk(f) for f in v])
        return arr(v)

    out = PodBatch(*[walk(f) for f in batch])
    return out._replace(valid=np.asarray(out.valid) & live,
                        kv_hot=None, key_hot=None)


class PodBatchBuilder:
    def __init__(self, table: InternTable):
        self.table = table
        self.compiler = SelectorCompiler(table)

    def build(self, pods: Sequence[PodInfo], pad_b: Optional[int] = None,
              spread_selectors: Optional[Sequence] = None) -> PodBatch:
        """spread_selectors: per-pod combined service/RC/RS/SS selector for
        DefaultPodTopologySpread (reference: plugins/helper/spread.go
        DefaultSelector), or None per pod when nothing selects it."""
        t = self.table
        B = pad_b if pad_b is not None else pow2_bucket(len(pods), 8)
        if B < len(pods):
            raise ValueError("pad_b smaller than batch")
        R = N_FIXED_CHANNELS + t.rname.cap
        L, K, NS, P = t.kv.cap, t.key.cap, t.ns.cap, t.port.cap
        T, I = t.taint.cap, t.image.cap

        req = np.zeros((B, R), np.float32)
        nonzero = np.zeros((B, 2), np.float32)
        limits = np.zeros((B, R), np.float32)
        ML = pow2_bucket(max((len(pi.pod.metadata.labels) for pi in pods),
                             default=0), 4)
        kv_ids = np.full((B, ML), -1, np.int32)
        key_ids = np.full((B, ML), -1, np.int32)
        ns_hot = np.zeros((B, NS), np.float32)
        node_name_kvid = np.full((B,), -1, np.int32)
        has_node_name = np.zeros((B,), bool)
        ports_hot = np.zeros((B, P), np.float32)
        ports_asnode_hot = np.zeros((B, P), np.float32)
        tolerated = np.zeros((B, T), bool)
        priority = np.zeros((B,), np.int32)
        images_hot = np.zeros((B, I), np.float32)
        n_containers = np.zeros((B,), np.float32)
        avoid_id = np.full((B,), -1, np.int32)
        tolerates_unschedulable = np.zeros((B,), bool)
        valid = np.zeros((B,), bool)

        node_selectors: List = []
        rna_terms: List[List[api.NodeSelectorTerm]] = []
        pna_terms: List[List[api.PreferredSchedulingTerm]] = []

        for i, pi in enumerate(pods):
            p = pi.pod
            valid[i] = True
            req[i] = resource_to_channels(pi.resource, t, R, intern_new=False)
            req[i, CH_PODS] = 1.0
            nonzero[i, 0] = pi.non_zero_cpu
            nonzero[i, 1] = pi.non_zero_mem / MIB
            limits[i] = resource_to_channels(compute_pod_resource_limits(p), t, R,
                                             intern_new=False)
            for li, (k, v) in enumerate(p.metadata.labels.items()):
                kv_ids[i, li] = t.kv.get((k, v))
                key_ids[i, li] = t.key.get(k)
            jn = t.ns.get(p.namespace)
            if jn >= 0:
                ns_hot[i, jn] = 1.0
            if p.spec.node_name:
                has_node_name[i] = True
                node_name_kvid[i] = t.kv.get(
                    (FIELD_PREFIX + "metadata.name", p.spec.node_name))
            for c in p.spec.containers:
                for port in c.ports:
                    if port.host_port <= 0:
                        continue
                    triple = (port.protocol or "TCP", port.host_ip or "0.0.0.0",
                              port.host_port)
                    for pid in port_ids_pod(triple):
                        j = t.port.get(pid)
                        if j >= 0:
                            ports_hot[i, j] = 1.0
                    from ..state.tensors import _port_ids_node
                    for pid in _port_ids_node(triple):
                        j = t.port.get(pid)
                        if j >= 0:
                            ports_asnode_hot[i, j] = 1.0
                if c.image:
                    j = t.image.get(_norm_image(c.image))
                    if j >= 0:
                        images_hot[i, j] = 1.0
            for ti in range(len(t.taint)):
                k, v, effect = t.taint.key(ti)
                taint = api.Taint(key=k, value=v, effect=effect)
                tolerated[i, ti] = api.tolerations_tolerate_taint(
                    p.spec.tolerations, taint)
            priority[i] = p.priority()
            n_containers[i] = len(p.spec.containers)
            # reference: nodepreferavoidpods/node_prefer_avoid_pods.go:57 —
            # only RC/RS controllers participate; others score MaxNodeScore.
            for ref in p.metadata.owner_references:
                if ref.controller and ref.kind in ("ReplicationController", "ReplicaSet"):
                    avoid_id[i] = t.avoid.get((ref.kind, ref.uid))
                    break
            # reference: nodeunschedulable/node_unschedulable.go:56
            tolerates_unschedulable[i] = api.tolerations_tolerate_taint(
                p.spec.tolerations,
                api.Taint(key="node.kubernetes.io/unschedulable",
                          effect=api.TAINT_EFFECT_NO_SCHEDULE))

            node_selectors.append(dict(p.spec.node_selector)
                                  if p.spec.node_selector else {})
            aff = p.spec.affinity
            na = aff.node_affinity if aff else None
            # nil-vs-empty matters: a PRESENT required NodeSelector with an
            # empty (or nil) terms list matches NO node (reference:
            # helpers.go:180 MatchNodeSelectorTerms over zero terms), while
            # an absent selector matches every node
            rna = (na.required_during_scheduling_ignored_during_execution
                   if na else None)
            rna_terms.append(list(rna.node_selector_terms)
                             if rna is not None else None)
            pna_terms.append(list(
                na.preferred_during_scheduling_ignored_during_execution)
                if na else [])

        node_selector = self.compiler.compile(
            node_selectors + [None] * (B - len(pods)), pad_s=B, intern_new=False)

        Tn = pow2_bucket(max((len(x) for x in rna_terms if x is not None),
                             default=0), 1)
        rna_flat: List = []
        rna_valid = np.zeros((B, Tn), bool)
        has_rna = np.zeros((B,), bool)
        for i in range(B):
            terms = rna_terms[i] if i < len(pods) else None
            has_rna[i] = terms is not None   # present selector, even empty
            terms = terms or []
            for j in range(Tn):
                if j < len(terms):
                    rna_flat.append(terms[j])
                    rna_valid[i, j] = True
                else:
                    rna_flat.append(None)
        rna_sel = self.compiler.compile(rna_flat, pad_s=B * Tn, intern_new=False)

        Tp = pow2_bucket(max((len(x) for x in pna_terms), default=0), 1)
        pna_flat: List = []
        pna_weight = np.zeros((B, Tp), np.float32)
        pna_valid = np.zeros((B, Tp), bool)
        for i in range(B):
            terms = pna_terms[i] if i < len(pods) else []
            for j in range(Tp):
                if j < len(terms) and terms[j].weight != 0:
                    # Preferred terms use only matchExpressions, and an empty
                    # preference matches every node (reference:
                    # nodeaffinity/node_affinity.go:81-99).
                    exprs = terms[j].preference.match_expressions
                    if exprs:
                        pna_flat.append(api.NodeSelectorTerm(match_expressions=exprs))
                    else:
                        pna_flat.append(api.LabelSelector())
                    pna_weight[i, j] = terms[j].weight
                    pna_valid[i, j] = True
                else:
                    pna_flat.append(None)
        pna_sel = self.compiler.compile(pna_flat, pad_s=B * Tp, intern_new=False)

        if spread_selectors is None:
            spread_selectors = [None] * len(pods)
        spread_sel_list = list(spread_selectors) + [None] * (B - len(pods))
        spread_selector = self.compiler.compile(spread_sel_list, pad_s=B,
                                                intern_new=False)
        spread_skip = np.zeros((B,), bool)
        for i, pi in enumerate(pods):
            spread_skip[i] = bool(pi.pod.spec.topology_spread_constraints)

        ra = self._build_pod_terms(pods, B, "required_affinity")
        raa = self._build_pod_terms(pods, B, "required_anti")
        pref = self._build_pod_terms(pods, B, "preferred")
        spread_hard = self._build_spread(pods, B, hard=True)
        spread_soft = self._build_spread(pods, B, hard=False)

        return PodBatch(req=req, nonzero_req=nonzero, limits=limits,
                        kv_ids=kv_ids, key_ids=key_ids,
                        kv_hot=None, key_hot=None,
                        ns_hot=ns_hot, node_name_kvid=node_name_kvid,
                        has_node_name=has_node_name, ports_hot=ports_hot,
                        ports_asnode_hot=ports_asnode_hot,
                        tolerated=tolerated, priority=priority, images_hot=images_hot,
                        n_containers=n_containers, avoid_id=avoid_id,
                        tolerates_unschedulable=tolerates_unschedulable,
                        node_selector=node_selector,
                        rna_sel=rna_sel, rna_valid=rna_valid, has_rna=has_rna,
                        pna_sel=pna_sel, pna_weight=pna_weight, pna_valid=pna_valid,
                        ra=ra, raa=raa, pref=pref, spread=spread_hard,
                        spread_soft=spread_soft, spread_selector=spread_selector,
                        spread_skip=spread_skip, valid=valid)

    def _term_lists(self, pi: PodInfo, kind: str):
        if kind == "required_affinity":
            return [(term, 1.0) for term in pi.required_affinity_terms]
        if kind == "required_anti":
            return [(term, 1.0) for term in pi.required_anti_affinity_terms]
        out = [(w.term, float(w.weight)) for w in pi.preferred_affinity_terms]
        out += [(w.term, -float(w.weight)) for w in pi.preferred_anti_affinity_terms]
        return out

    def _build_pod_terms(self, pods: Sequence[PodInfo], B: int, kind: str) -> PodTerms:
        t = self.table
        NS = t.ns.cap
        lists = [self._term_lists(pi, kind) for pi in pods]
        T = pow2_bucket(max((len(x) for x in lists), default=0), 1)
        sels: List = []
        ns_hot = np.zeros((B, T, NS), np.float32)
        topo_key = np.zeros((B, T), np.int32)
        topo_known = np.zeros((B, T), bool)
        weight = np.zeros((B, T), np.float32)
        tvalid = np.zeros((B, T), bool)
        self_match = np.zeros((B, T), bool)
        for i in range(B):
            terms = lists[i] if i < len(pods) else []
            for j in range(T):
                if j < len(terms):
                    term, w = terms[j]
                    sels.append(term.selector)
                    for ns in term.namespaces:
                        k = t.ns.get(ns)
                        if k >= 0:
                            ns_hot[i, j, k] = 1.0
                    tk = t.topokey.get(term.topology_key)
                    topo_key[i, j] = max(tk, 0)
                    topo_known[i, j] = tk >= 0
                    weight[i, j] = w
                    tvalid[i, j] = True
                    self_match[i, j] = term.matches(pods[i].pod)
                else:
                    sels.append(None)
        sel = self.compiler.compile(sels, pad_s=B * T, intern_new=False)
        return PodTerms(sel=sel, ns_hot=ns_hot, topo_key=topo_key,
                        topo_known=topo_known, weight=weight, valid=tvalid,
                        self_match=self_match)

    def _build_spread(self, pods: Sequence[PodInfo], B: int, hard: bool) -> SpreadConstraints:
        t = self.table
        want = "DoNotSchedule" if hard else "ScheduleAnyway"
        lists = []
        for pi in pods:
            cs = [c for c in pi.pod.spec.topology_spread_constraints
                  if c.when_unsatisfiable == want]
            lists.append(cs)
        C = pow2_bucket(max((len(x) for x in lists), default=0), 1)
        sels: List = []
        topo_key = np.zeros((B, C), np.int32)
        topo_known = np.zeros((B, C), bool)
        max_skew = np.zeros((B, C), np.float32)
        valid = np.zeros((B, C), bool)
        self_match = np.zeros((B, C), bool)
        for i in range(B):
            cs = lists[i] if i < len(pods) else []
            for j in range(C):
                if j < len(cs):
                    c = cs[j]
                    sels.append(c.label_selector)
                    tk = t.topokey.get(c.topology_key)
                    topo_key[i, j] = max(tk, 0)
                    topo_known[i, j] = tk >= 0
                    max_skew[i, j] = c.max_skew
                    valid[i, j] = True
                    if c.label_selector is not None:
                        self_match[i, j] = c.label_selector.matches(
                            pods[i].pod.metadata.labels)
                else:
                    sels.append(None)
        sel = self.compiler.compile(sels, pad_s=B * C, intern_new=False)
        return SpreadConstraints(sel=sel, topo_key=topo_key, topo_known=topo_known,
                                 max_skew=max_skew, valid=valid, self_match=self_match)
