"""Conflict-free batched (gang) assignment: propose-and-admit auction.

The reference schedules one pod per cycle, so intra-batch capacity conflicts
cannot happen (reference: pkg/scheduler/scheduler.go:509 scheduleOne).  The
naive batched program (programs.schedule_batch) scores every pod against the
same snapshot, so two pods can both claim the last slot of a node.  The
sequential scan (models/sequential.py) is exact but pays O(B) serial steps.

This module is the third mode: a parallel auction in the family of Bertsekas'
assignment auctions, specialised to the scheduler's one-sided capacity
constraints.  Each round, entirely on device:

1. every unassigned pod *proposes* to its argmax feasible node, using the
   same per-pod tie-break RNG as the sequential replay
   (jax.random.fold_in(rng, pod_index) — selectHost semantics,
   generic_scheduler.go:217);
2. pods proposing the same node are *admitted* in pod order (the batch is
   popped from the queue in priority order, so pod index = the reference's
   serial order) up to the node's remaining multi-resource capacity and
   hostPort set.  Admission is a sort by proposed node + a segmented
   prefix-sum over request channels — no [B, N, R] intermediate, so it
   scales to 100k x 10k;
3. admitted placements commit: node requested/ports update, and the next
   round recomputes feasibility *and scores* against the updated usage
   (pods placed in later rounds see earlier rounds' placements, the batched
   analog of the serial loop's assume; capacity semantics exactly match
   noderesources/fit.go:194-267 + NodePorts).

Invariants:
- zero capacity violations: an admitted pod's request fits within
  free-capacity-minus-earlier-proposers (a superset of earlier admitted),
  and a pod whose probed hostPorts collide with any earlier proposer's
  registered ports is deferred to the next round;
- progress: the first proposer of every proposed-to node always fits (the
  node was feasible for it this round), so each round either admits >=1 pod
  or proves the remaining pods unschedulable — the loop terminates;
- uncontended agreement: when every pod's argmax is distinct and capacity
  suffices, round 1 admits every pod at exactly the node the sequential
  replay picks under the same rng.

Scope note: topology filters/scores (PodTopologySpread, InterPodAffinity)
are evaluated against the snapshot plus the batch's committed *resource*
usage, not against intra-batch topology-pair counts — gang mode trades the
scan's serial topology carries for O(rounds) parallel passes.  Workloads
where intra-batch topology interaction must be exact use the sequential
replay mode.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from ..ops import kernels as K
from .programs import ProgramConfig, run_filters, run_scores

_f = K._f
_NEG = jnp.float32(-2**62)


class GangResult(NamedTuple):
    chosen: jnp.ndarray     # [B] i32 node row, -1 unschedulable this pass
    score: jnp.ndarray      # [B] f32 score of the winning node at admission
    rounds: jnp.ndarray     # i32 number of propose/admit rounds executed
    requested: jnp.ndarray  # [N, R] final requested incl. batch placements
    feasible0: jnp.ndarray  # [B, N] bool first-round feasibility (diagnostics)
    unresolvable: jnp.ndarray  # [B, N] bool from the static filter pass
    n_feasible: jnp.ndarray    # [B] i32 first-round feasible-node count
    all_unresolvable: jnp.ndarray  # [B] bool — every failed node failed
                            # UnschedulableAndUnresolvable (preemption gate,
                            # scheduler.go:391; matches SeqResult's field)


def _segment_base(values: jnp.ndarray, is_start: jnp.ndarray) -> jnp.ndarray:
    """For row-sorted segments: propagate each segment-start row's value
    forward.  values must be non-decreasing along axis 0 (cumsum outputs),
    so a cummax over (start ? value : -1) yields, at every row, the value at
    its segment's first row."""
    marked = jnp.where(is_start[:, None] if values.ndim == 2 else is_start,
                       values, -1.0)
    return jax.lax.cummax(marked, axis=0)


def _fit_rows(req: jnp.ndarray, avail: jnp.ndarray) -> jnp.ndarray:
    """Per-row NodeResourcesFit verdict for request rows [B, R] against
    available rows [B, R] (fit.go:194-267 semantics: pod count always
    checked; cpu/mem/ephemeral checked when the pod requests anything;
    scalar channels only when requested)."""
    free_ok = avail >= req
    R = req.shape[1]
    ch = jnp.arange(R)
    is_fixed = (ch < K.N_FIXED_CHANNELS) & (ch != K.CH_PODS)
    check = jnp.where(is_fixed[None, :], True, req > 0)
    res_ok = jnp.all(free_ok | ~check | (ch == K.CH_PODS)[None, :], axis=-1)
    pods_ok = free_ok[:, K.CH_PODS]
    nonpods = jnp.where((ch == K.CH_PODS)[None, :], 0.0, req)
    zero_req = jnp.all(nonpods == 0, axis=-1)
    return pods_ok & (zero_req | res_ok)


@functools.partial(jax.jit, static_argnames=("cfg", "max_rounds"))
def schedule_gang(cluster, batch, cfg: ProgramConfig, rng,
                  host_ok: Optional[jnp.ndarray] = None,
                  max_rounds: Optional[int] = None) -> GangResult:
    from .batch import densify_for
    batch = densify_for(cluster, batch)
    B = batch.req.shape[0]
    N = cluster.allocatable.shape[0]
    if max_rounds is None:
        max_rounds = B
    filters = set(cfg.filters)
    use_fit = "NodeResourcesFit" in filters
    use_ports = "NodePorts" in filters

    # Static filters once (everything but the capacity filters the rounds
    # re-evaluate); unresolvable mask matches run_filters' full pass because
    # neither Fit nor Ports is an UnschedulableAndUnresolvable filter.
    static_ok, unresolvable, affinity_ok = run_filters(
        cluster, batch, cfg, host_ok,
        skip=("NodeResourcesFit", "NodePorts"))
    ports_ok0 = (K.node_ports_filter(cluster, batch) if use_ports
                 else jnp.ones((B, N), bool))

    pod_idx = jnp.arange(B, dtype=jnp.int32)
    tie_keys = jax.vmap(lambda i: jax.random.fold_in(rng, i))(pod_idx)

    P = batch.ports_hot.shape[1]
    carry0 = dict(
        req=cluster.requested,
        nz=cluster.nonzero_requested,
        ports_used=jnp.zeros((N, P), jnp.float32),
        assigned=jnp.full((B,), -1, jnp.int32),
        win_score=jnp.zeros((B,), jnp.float32),
        feas0=jnp.zeros((B, N), bool),
        rounds=jnp.int32(0),
        progress=jnp.bool_(True),
    )

    def feasibility(c):
        feas = static_ok
        if use_fit:
            cl = cluster._replace(requested=c["req"])
            feas = feas & K.fit_filter(cl, batch)
        if use_ports:
            batch_conf = jnp.einsum(
                "bp,np->bn", batch.ports_hot, c["ports_used"],
                preferred_element_type=jnp.float32) > 0.5
            feas = feas & ports_ok0 & ~batch_conf
        return feas

    def cond(c):
        return c["progress"] & (c["rounds"] < max_rounds)

    def body(c):
        unassigned = (c["assigned"] < 0) & batch.valid
        feas = feasibility(c) & unassigned[:, None]

        # scores against committed usage so later rounds see earlier rounds'
        # placements (the batched analog of assume-before-next-pod)
        cl = cluster._replace(requested=c["req"], nonzero_requested=c["nz"])
        scores, _ = run_scores(cl, batch, cfg, feas, affinity_ok)

        masked = jnp.where(feas, scores, _NEG)
        best = jnp.max(masked, axis=1)
        ties = (masked == best[:, None]) & feas
        logits = jnp.where(ties, 0.0, _NEG)
        choice = jax.vmap(jax.random.categorical)(tie_keys, logits)
        active = jnp.any(feas, axis=1)
        prop = jnp.where(active, choice.astype(jnp.int32), N)  # N = no-op seg

        # ---- admission: sort by proposed node (stable keeps pod order) ----
        order = jnp.argsort(prop, stable=True)
        snode = prop[order]
        sactive = active[order]
        is_start = jnp.concatenate(
            [jnp.ones((1,), bool), snode[1:] != snode[:-1]])

        sreq = batch.req[order] * _f(sactive)[:, None]          # [B, R]
        csum = jnp.cumsum(sreq, axis=0)
        excl = csum - sreq
        prefix_excl = excl - _segment_base(excl, is_start)      # earlier
        node_safe = jnp.clip(snode, 0, N - 1)                   # proposers'
        free = (cluster.allocatable[node_safe]                  # usage
                - c["req"][node_safe])
        cap_ok = _fit_rows(batch.req[order], free - prefix_excl)

        if use_ports:
            sreg = batch.ports_asnode_hot[order] * _f(sactive)[:, None]
            pcs = jnp.cumsum(sreg, axis=0)
            pexcl = pcs - sreg
            earlier_ports = pexcl - _segment_base(pexcl, is_start)
            conflict = jnp.sum(batch.ports_hot[order] * earlier_ports,
                               axis=1) > 0.5
            cap_ok = cap_ok & ~conflict

        admit_sorted = cap_ok & sactive & (snode < N)
        admit = jnp.zeros((B,), bool).at[order].set(admit_sorted)

        # ---- commit ----
        seg = jnp.where(admit, prop, N)
        add_req = jax.ops.segment_sum(
            batch.req * _f(admit)[:, None], seg, num_segments=N + 1)[:N]
        add_nz = jax.ops.segment_sum(
            batch.nonzero_req * _f(admit)[:, None], seg,
            num_segments=N + 1)[:N]
        new = dict(c)
        new["req"] = c["req"] + add_req
        new["nz"] = c["nz"] + add_nz
        if use_ports:
            add_ports = jax.ops.segment_max(
                batch.ports_asnode_hot * _f(admit)[:, None], seg,
                num_segments=N + 1)[:N]
            new["ports_used"] = jnp.maximum(c["ports_used"], add_ports)
        new["assigned"] = jnp.where(admit, prop, c["assigned"])
        new["win_score"] = jnp.where(admit, best, c["win_score"])
        new["feas0"] = jnp.where(c["rounds"] == 0, feas, c["feas0"])
        new["rounds"] = c["rounds"] + 1
        new["progress"] = jnp.any(admit)
        return new

    out = jax.lax.while_loop(cond, body, carry0)
    base = cluster.node_valid[None, :] & batch.valid[:, None]
    if host_ok is not None:
        base = base & host_ok
    all_unres = jnp.all(unresolvable | out["feas0"] | ~base, axis=1)
    return GangResult(chosen=out["assigned"], score=out["win_score"],
                      rounds=out["rounds"], requested=out["req"],
                      feasible0=out["feas0"], unresolvable=unresolvable,
                      n_feasible=jnp.sum(out["feas0"].astype(jnp.int32),
                                         axis=1),
                      all_unresolvable=all_unres)
