"""Conflict-free batched (gang) assignment: propose-and-admit auction.

The reference schedules one pod per cycle, so intra-batch capacity conflicts
cannot happen (reference: pkg/scheduler/scheduler.go:509 scheduleOne).  The
naive batched program (programs.schedule_batch) scores every pod against the
same snapshot, so two pods can both claim the last slot of a node.  The
sequential scan (models/sequential.py) is exact but pays O(B) serial steps.

This module is the third mode: a parallel auction in the family of Bertsekas'
assignment auctions, specialised to the scheduler's one-sided capacity
constraints.  Each round, entirely on device:

1. every unassigned pod *proposes* to its argmax feasible node, using the
   same per-pod tie-break RNG as the sequential replay
   (jax.random.fold_in(rng, pod_index) — selectHost semantics,
   generic_scheduler.go:217);
2. pods proposing the same node are *admitted* in pod order (the batch is
   popped from the queue in priority order, so pod index = the reference's
   serial order) up to the node's remaining multi-resource capacity and
   hostPort set.  Admission is a sort by proposed node + a segmented
   prefix-sum over request channels — no [B, N, R] intermediate, so it
   scales to 100k x 10k;
3. admitted placements commit: node requested/ports update, and the next
   round recomputes feasibility *and scores* against the updated usage
   (pods placed in later rounds see earlier rounds' placements, the batched
   analog of the serial loop's assume; capacity semantics exactly match
   noderesources/fit.go:194-267 + NodePorts).

Invariants:
- zero capacity violations: an admitted pod's request fits within
  free-capacity-minus-earlier-proposers (a superset of earlier admitted),
  and a pod whose probed hostPorts collide with any earlier proposer's
  registered ports is deferred to the next round;
- progress: the first proposer of every proposed-to node always fits (the
  node was feasible for it this round), so each round either admits >=1 pod
  or proves the remaining pods unschedulable — the loop terminates;
- uncontended agreement: when every pod's argmax is distinct and capacity
  suffices, round 1 admits every pod at exactly the node the sequential
  replay picks under the same rng.

Topology correctness (intra-batch): the batch's pods are appended to the
snapshot's existing-pod axis once, and each round updates their
pod_node/pod_valid from the carry, so PodTopologySpread and InterPodAffinity
filters (and the topology scores) are re-evaluated against committed
placements exactly — a pod admitted in round r sees every pod admitted in
rounds < r the way the reference's serial loop sees previously bound pods
(interpodaffinity/filtering.go:314, podtopologyspread/filtering.go:200).
Admitted pods' own required anti-affinity terms are spliced into
filter_terms so they repel later-round pods (the existing-pods direction).
Within a round, a conservative same-topology-pair deferral keeps admission
order safe: a pod with required topology terms is deferred to the next
round if any earlier-index pod was admitted this round into a topo pair one
of its term keys maps its proposal to (and any pod is deferred from a pair
an earlier-admitted anti-affinity-active pod landed in); the next round then
re-checks it against exact committed counts.  Deferral never blocks the
first admitted pod, so progress is preserved.  Score staleness within a
single round (not across rounds) is the remaining gap vs the sequential
replay mode.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from ..ops import kernels as K
from ..ops.selectors import concat_selector_sets, match_selectors_unique
from ..state.tensors import ExistingTerms
from .programs import ProgramConfig, run_filters, run_scores

_f = K._f
_NEG = jnp.float32(-2**62)


class GangResult(NamedTuple):
    chosen: jnp.ndarray     # [B] i32 node row, -1 unschedulable this pass
    score: jnp.ndarray      # [B] f32 score of the winning node at admission
    rounds: jnp.ndarray     # i32 number of propose/admit rounds executed
    requested: jnp.ndarray  # [N, R] final requested incl. batch placements
    nz: jnp.ndarray         # [N, 2] final non-zero requested
    ports_used: jnp.ndarray  # [N, P] f32 ports registered by batch placements
    feasible0: jnp.ndarray  # [B, N] bool first-round feasibility (diagnostics)
    unresolvable: jnp.ndarray  # [B, N] bool — static filters plus the
                            # InterPodAffinity required-affinity bits
                            # re-captured at round 0 when intra-batch
                            # topology moves that filter into the loop
    n_feasible: jnp.ndarray    # [B] i32 first-round feasible-node count
    all_unresolvable: jnp.ndarray  # [B] bool — every failed node failed
                            # UnschedulableAndUnresolvable (preemption gate,
                            # scheduler.go:391; matches SeqResult's field)
    packed: jnp.ndarray     # [3*B + 1] i32 = concat(chosen, n_feasible,
                            # all_unresolvable, [rounds]) — the host's
                            # per-cycle view in ONE device->host readback
                            # (the tunnel pays ~100 ms latency PER transfer,
                            # so the serving loop must pull exactly one
                            # small array)


def _segment_base(values: jnp.ndarray, is_start: jnp.ndarray) -> jnp.ndarray:
    """For row-sorted segments: propagate each segment-start row's value
    forward.  values must be non-decreasing along axis 0 (cumsum outputs),
    so a cummax over (start ? value : -1) yields, at every row, the value at
    its segment's first row."""
    marked = jnp.where(is_start[:, None] if values.ndim == 2 else is_start,
                       values, -1.0)
    return jax.lax.cummax(marked, axis=0)


def _extend_cluster(cluster, batch):
    """Append the batch's pods to the existing-pod axis (pod_node/pod_valid
    are patched per round from the carry) and splice the batch pods' required
    anti-affinity terms into filter_terms with owner rows P+j, so admitted
    batch pods repel later pods exactly like bound existing pods
    (interpodaffinity/filtering.go:166 getExistingAntiAffinityCounts)."""
    B = batch.req.shape[0]
    P = cluster.pod_valid.shape[0]
    raa = batch.raa
    Ta = raa.valid.shape[1]
    TK = cluster.topo_pair.shape[1]
    ft = cluster.filter_terms
    topo_key = raa.topo_key.reshape(-1)
    # a term whose topology key exists nowhere in the cluster can never
    # produce a pair, so it never fails anything — drop it
    valid = (raa.valid & raa.topo_known
             & (raa.topo_key < TK)).reshape(-1)
    ext_terms = ExistingTerms(
        sel=concat_selector_sets(ft.sel, raa.sel),
        ns_hot=jnp.concatenate([ft.ns_hot, raa.ns_hot.reshape(B * Ta, -1)]),
        topo_key=jnp.concatenate([ft.topo_key, topo_key]),
        pod_idx=jnp.concatenate(
            [ft.pod_idx, P + jnp.repeat(jnp.arange(B, dtype=jnp.int32), Ta)]),
        weight=jnp.concatenate([ft.weight, jnp.ones((B * Ta,), jnp.float32)]),
        valid=jnp.concatenate([ft.valid, valid]),
    )
    return cluster._replace(
        pod_kv=jnp.concatenate([cluster.pod_kv, batch.kv_hot]),
        pod_key=jnp.concatenate([cluster.pod_key, batch.key_hot]),
        pod_ns_hot=jnp.concatenate([cluster.pod_ns_hot, batch.ns_hot]),
        pod_node=jnp.concatenate(
            [cluster.pod_node, jnp.full((B,), -1, jnp.int32)]),
        pod_valid=jnp.concatenate(
            [cluster.pod_valid, jnp.zeros((B,), bool)]),
        pod_terminating=jnp.concatenate(
            [cluster.pod_terminating, jnp.zeros((B,), bool)]),
        filter_terms=ext_terms,
    )


def _seg_prefix(e_sorted: jnp.ndarray, is_start: jnp.ndarray) -> jnp.ndarray:
    """Exclusive per-segment prefix sums of [B, U] rows sorted by segment."""
    cs = jnp.cumsum(e_sorted, axis=0)
    excl = cs - e_sorted
    return excl - _segment_base(excl, is_start)


def admission_mask(prop, active, req_b, ports_hot_b, ports_asnode_b,
                   allocatable, req_carry, use_ports: bool,
                   n_nodes: int) -> jnp.ndarray:
    """The segmented-reduce admission verdict over one round's proposals:
    sort by proposed node (stable keeps pod order — the batch is popped in
    priority order, so row index IS the reference's serial order), then
    admit each proposer iff its request fits the node's free capacity
    minus EARLIER proposers' requests (a superset of earlier admitted)
    and its probed hostPorts miss every earlier proposer's registered
    set.  Shared verbatim by the lax round (_round_tail) and the
    shard_map tiled round (parallel/shardmap.py) — ONE source of truth
    keeps the two paths bit-identical by construction.  prop must use
    n_nodes as the no-op segment for inactive pods."""
    order = jnp.argsort(prop, stable=True)
    snode = prop[order]
    sactive = active[order]
    is_start = jnp.concatenate(
        [jnp.ones((1,), bool), snode[1:] != snode[:-1]])
    sreq = req_b[order] * _f(sactive)[:, None]
    prefix_excl = _seg_prefix(sreq, is_start)
    node_safe = jnp.clip(snode, 0, n_nodes - 1)
    free = allocatable[node_safe] - req_carry[node_safe]
    cap_ok = K.fit_rows(req_b[order], free - prefix_excl)
    if use_ports:
        sreg = ports_asnode_b[order] * _f(sactive)[:, None]
        earlier_ports = _seg_prefix(sreg, is_start)
        conflict = jnp.sum(ports_hot_b[order] * earlier_ports,
                           axis=1) > 0.5
        cap_ok = cap_ok & ~conflict
    admit_sorted = cap_ok & sactive & (snode < n_nodes)
    return jnp.zeros(prop.shape, bool).at[order].set(admit_sorted)


def admission_sums(admit, prop, req_b, nonzero_b, ports_asnode_b,
                   use_ports: bool, n_nodes: int):
    """Commit-side segment sums of one round's admitted placements:
    (add_req [N, R], add_nz [N, 2], add_ports [N, P] | None).  Shared by
    _round_tail and the shard_map tiled round."""
    seg = jnp.where(admit, prop, n_nodes)
    add_req = jax.ops.segment_sum(
        req_b * _f(admit)[:, None], seg, num_segments=n_nodes + 1)[:n_nodes]
    add_nz = jax.ops.segment_sum(
        nonzero_b * _f(admit)[:, None], seg,
        num_segments=n_nodes + 1)[:n_nodes]
    add_ports = None
    if use_ports:
        add_ports = jax.ops.segment_max(
            ports_asnode_b * _f(admit)[:, None], seg,
            num_segments=n_nodes + 1)[:n_nodes]
    return add_req, add_nz, add_ports


def _key_terms_mask(terms, k: int) -> jnp.ndarray:
    """[B, T] bool — valid required terms on topology key k."""
    return (terms.topo_key == k) & terms.valid & terms.topo_known


def materialize_assigned(cluster, batch, chosen, requested, nz, ports_used,
                         pad_pods_to: int = 0, pad_terms_to: int = 0,
                         extend_score_terms: bool = False,
                         hard_pod_affinity_weight: float = 1.0):
    """Python entry for the jitted materialize — AOT seam (utils/aot.py):
    armed, a signature hit runs the deserialized build-time executable;
    disarmed this is the plain jit call.  See _materialize_assigned."""
    from ..utils import aot
    return aot.dispatch(
        "_materialize_assigned", _materialize_assigned,
        (cluster, batch, chosen, requested, nz, ports_used),
        dict(pad_pods_to=pad_pods_to, pad_terms_to=pad_terms_to,
             extend_score_terms=extend_score_terms,
             hard_pod_affinity_weight=hard_pod_affinity_weight),
        static_argnames=("pad_pods_to", "pad_terms_to",
                         "extend_score_terms"))


@functools.partial(jax.jit, static_argnames=("pad_pods_to", "pad_terms_to",
                                             "extend_score_terms"))
def _materialize_assigned(cluster, batch, chosen, requested, nz, ports_used,
                          pad_pods_to: int = 0, pad_terms_to: int = 0,
                          extend_score_terms: bool = False,
                          hard_pod_affinity_weight: float = 1.0):
    """Fold a (partial) auction's placements into the cluster: assigned
    batch pods join the existing-pod axis at their nodes, their committed
    usage replaces requested/nonzero, and their registered hostPorts join
    cluster.ports.  Two consumers: the RESIDUAL auction over the pods
    that lost round one, and CYCLE CHAINING — the serving loop reuses this
    as the next cycle's cluster instead of re-tensorizing the world
    (SURVEY §7 delta-updates; pad_pods_to/pad_terms_to pow2-pad the grown
    axes so successive cycles hit the same compiled programs)."""
    from .batch import densify_for
    from ..ops.selectors import pad_selector_slots
    batch = densify_for(cluster, batch)
    ext = _extend_cluster(cluster, batch)
    assigned = (chosen >= 0) & batch.valid
    ext = ext._replace(
        pod_node=jnp.concatenate([cluster.pod_node, chosen]),
        pod_valid=jnp.concatenate([cluster.pod_valid, assigned]),
        requested=requested,
        nonzero_requested=nz,
        ports=cluster.ports | (ports_used > 0.5),
    )
    if extend_score_terms:
        # a FRESH rebuild would put the newly-bound pods' preferred terms
        # (signed weights) and required-affinity terms (hardPodAffinityWeight)
        # into score_terms (state/tensors.py:334); chained clusters must
        # match or scoring silently diverges from a rebuild
        P0 = cluster.pod_valid.shape[0]
        TK = cluster.topo_pair.shape[1]
        st = cluster.score_terms

        def term_rows(t, w):
            bb, tt = t.valid.shape
            return (t.sel, t.ns_hot.reshape(bb * tt, -1),
                    t.topo_key.reshape(-1),
                    P0 + jnp.repeat(jnp.arange(bb, dtype=jnp.int32), tt),
                    w.reshape(-1),
                    (t.valid & t.topo_known & (t.topo_key < TK)).reshape(-1))

        pr = term_rows(batch.pref, batch.pref.weight * _f(batch.pref.valid))
        ra = term_rows(batch.ra,
                       jnp.full_like(batch.ra.weight,
                                     hard_pod_affinity_weight)
                       * _f(batch.ra.valid))
        ext = ext._replace(score_terms=ExistingTerms(
            sel=concat_selector_sets(concat_selector_sets(st.sel, pr[0]),
                                     ra[0]),
            ns_hot=jnp.concatenate([st.ns_hot, pr[1], ra[1]]),
            topo_key=jnp.concatenate([st.topo_key, pr[2], ra[2]]),
            pod_idx=jnp.concatenate([st.pod_idx, pr[3], ra[3]]),
            weight=jnp.concatenate([st.weight, pr[4], ra[4]]),
            valid=jnp.concatenate([st.valid, pr[5], ra[5]])))
    P = ext.pod_valid.shape[0]
    if pad_pods_to > P:
        n = pad_pods_to - P

        def padp(x, fill=0):
            pad = [(0, n)] + [(0, 0)] * (x.ndim - 1)
            return jnp.pad(x, pad, constant_values=fill)
        ext = ext._replace(
            pod_kv=padp(ext.pod_kv), pod_key=padp(ext.pod_key),
            pod_ns_hot=padp(ext.pod_ns_hot),
            pod_node=padp(ext.pod_node, -1),
            pod_valid=padp(ext.pod_valid),
            pod_terminating=padp(ext.pod_terminating))
    ft = ext.filter_terms
    E = ft.valid.shape[0]
    if pad_terms_to > E:
        n = pad_terms_to - E

        def padt(x, fill=0):
            pad = [(0, n)] + [(0, 0)] * (x.ndim - 1)
            return jnp.pad(x, pad, constant_values=fill)
        ext = ext._replace(filter_terms=ft._replace(
            sel=pad_selector_slots(ft.sel, pad_terms_to),
            ns_hot=padt(ft.ns_hot), topo_key=padt(ft.topo_key),
            pod_idx=padt(ft.pod_idx), weight=padt(ft.weight),
            valid=padt(ft.valid)))
    return ext


def run_auction(cluster, batch, cfg: ProgramConfig, rng,
                host_ok=None, intra_batch_topology: bool = True,
                score_bias=None,
                kernel_backend: Optional[str] = None) -> GangResult:
    """The serving-loop gang entry: ONE device dispatch, ONE small readback.

    Round 3 ran a two-phase host-orchestrated residual auction here (full
    round, pull losers to host, re-auction a gathered pow2 bucket).  That
    traded device FLOPs for host round trips — the right trade when a
    full-batch round cost ~1.2 s of scatter-bound device time.  The
    same-pair MATMUL kernels dropped a 4096x1000 full-matrix round to
    ~10 ms, while every device->host transfer costs ~100 ms of tunnel
    latency; the two-phase wrapper's 3+ intermediate syncs now cost an
    order of magnitude more than the full-batch rounds they avoid.  The
    monolithic while_loop (all rounds on device, zero intermediate syncs)
    is strictly faster at every measured shape, so it IS the auction."""
    return schedule_gang(cluster, batch, cfg, rng, host_ok=host_ok,
                         intra_batch_topology=intra_batch_topology,
                         score_bias=score_bias,
                         kernel_backend=kernel_backend)


def schedule_gang(cluster, batch, cfg: ProgramConfig, rng,
                  host_ok: Optional[jnp.ndarray] = None,
                  max_rounds: Optional[int] = None,
                  intra_batch_topology: bool = True,
                  tie_index: Optional[jnp.ndarray] = None,
                  residual_window: int = 512,
                  score_bias: Optional[jnp.ndarray] = None,
                  kernel_backend: Optional[str] = None) -> GangResult:
    """Python entry for the jitted auction.  The indirection is a REQUIRED
    workaround for this runtime's jit dispatch: calling the jit object
    directly from multiple call sites with different static-arg
    combinations intermittently fails with 'Execution supplied N buffers
    but compiled program expected N+1' (argument-pruning bookkeeping
    crossing cache entries); routing every call through one Python frame
    avoids the C++ fastpath state that triggers it."""
    # the auction never samples nodes (it needs the global view), so
    # percentage_of_nodes_to_score must not split the program cache —
    # normalize it out of the static key
    if cfg.percentage_of_nodes_to_score != 100:
        cfg = cfg._replace(percentage_of_nodes_to_score=100)
    # kernel backend selection: "pallas" engages the fused
    # filter->score->propose megakernel (ops/pallas_kernels.py) for the
    # supported surface; any unsupported (cfg, routing) combination falls
    # back to the lax path and records why (utils/pallas_backend) — the
    # lax path doubles as the bit-match oracle either way
    backend = kernel_backend or "lax"
    if backend == "pallas":
        from ..utils import pallas_backend as PB
        # batch passed too: a host-side (numpy) batch carrying soft
        # spread constraints falls back here — the kernel's constant
        # PodTopologySpread path only matches term-free batches
        reason = PB.unsupported_reason(cfg, intra_batch_topology, batch)
        if reason is not None:
            PB.note_fallback(reason)
            backend = "lax"
    # AOT seam (utils/aot.py): armed, a signature hit runs the
    # deserialized build-time executable instead of tracing/compiling;
    # disarmed this is the plain jit call through the same Python frame
    from ..utils import aot
    return aot.dispatch(
        "_schedule_gang", _schedule_gang,
        (cluster, batch, cfg, rng),
        dict(host_ok=host_ok, max_rounds=max_rounds,
             intra_batch_topology=intra_batch_topology,
             tie_index=tie_index, residual_window=residual_window,
             score_bias=score_bias, kernel_backend=backend),
        static_argnums=(2,),
        static_argnames=("max_rounds", "intra_batch_topology",
                         "residual_window", "kernel_backend"))


@functools.partial(jax.jit,
                   static_argnames=("cfg", "max_rounds",
                                    "intra_batch_topology",
                                    "residual_window", "kernel_backend"))
def _schedule_gang(cluster, batch, cfg: ProgramConfig, rng,
                   host_ok: Optional[jnp.ndarray] = None,
                   max_rounds: Optional[int] = None,
                   intra_batch_topology: bool = True,
                   tie_index: Optional[jnp.ndarray] = None,
                   residual_window: int = 512,
                   score_bias: Optional[jnp.ndarray] = None,
                   kernel_backend: str = "lax") -> GangResult:
    return _gang_program(cluster, batch, cfg, rng, host_ok=host_ok,
                         max_rounds=max_rounds,
                         intra_batch_topology=intra_batch_topology,
                         tie_index=tie_index,
                         residual_window=residual_window,
                         score_bias=score_bias,
                         kernel_backend=kernel_backend)


def _gang_program(cluster, batch, cfg: ProgramConfig, rng,
                  host_ok: Optional[jnp.ndarray] = None,
                  max_rounds: Optional[int] = None,
                  intra_batch_topology: bool = True,
                  tie_index: Optional[jnp.ndarray] = None,
                  residual_window: int = 512,
                  score_bias: Optional[jnp.ndarray] = None,
                  kernel_backend: str = "lax") -> GangResult:
    """The auction program body, jit-free: `_schedule_gang` above is its
    single-device jit root, and the shard_map mesh path
    (parallel/shardmap.py) traces the SAME body per device for its
    replicated topology surface — bit-identity across paths by
    construction, not by parallel maintenance."""
    from .batch import densify_for
    batch = densify_for(cluster, batch)
    B = batch.req.shape[0]
    N = cluster.allocatable.shape[0]
    if max_rounds is None:
        max_rounds = B
    filters = set(cfg.filters)
    use_fit = "NodeResourcesFit" in filters
    use_ports = "NodePorts" in filters
    # Topology filters move into the round body (evaluated against committed
    # placements) when intra-batch topology is on; the host may pass
    # intra_batch_topology=False for batches it knows carry no pod-topology
    # terms, restoring the cheaper static evaluation.
    use_sph = "PodTopologySpread" in filters and intra_batch_topology
    use_ipa = "InterPodAffinity" in filters and intra_batch_topology
    intra = use_sph or use_ipa

    skip = ["NodeResourcesFit", "NodePorts"]
    if use_sph:
        skip.append("PodTopologySpread")
    if use_ipa:
        skip.append("InterPodAffinity")
    # Static filters once (everything the rounds don't re-evaluate);
    # Fit/Ports are not UnschedulableAndUnresolvable filters and
    # InterPodAffinity's unresolvable part is re-captured at round 0, so the
    # final unresolvable mask matches run_filters' full pass.
    static_ok, static_unres, affinity_ok = run_filters(
        cluster, batch, cfg, host_ok, skip=tuple(skip))
    base = cluster.node_valid[None, :] & batch.valid[:, None]
    if host_ok is not None:
        base = base & host_ok
    ports_ok0 = (K.node_ports_filter(cluster, batch) if use_ports
                 else jnp.ones((B, N), bool))

    ext = _extend_cluster(cluster, batch) if intra else cluster
    score_names = set(n for n, _ in cfg.scores)
    # assignment-independent raw scores: computed ONCE; only their
    # normalization (a [B, N] reduce over the evolving feasible mask)
    # stays in the round loop.  node_affinity_score alone re-ran a full
    # [B*Tp, L] x [N, L] selector match per round before this.
    from .programs import static_raw_scores
    score_pre = dict(static_raw_scores(ext, batch, cfg))
    # hoist every assignment-independent match matrix out of the round
    # loop: only the segment/gather work that depends on the carry's
    # assignments runs per round.  The score pres are needed regardless of
    # intra_batch_topology: windowed sub-rounds row-gather ONLY these
    # matrices (the SelectorSets stay full-size), so a score kernel falling
    # back to selector matching against a width-W batch would crash.
    if "InterPodAffinity" in score_names:
        score_pre["interpod_score"] = K.interpod_score_pre(ext, batch)
    if "PodTopologySpread" in score_names:
        score_pre["spread_soft"] = K.spread_match_ns(ext, batch,
                                                     batch.spread_soft)
    if "DefaultPodTopologySpread" in score_names:
        score_pre["default_spread"] = K.default_spread_match_ns(ext, batch)
    if intra:
        sph_match = (K.spread_match_ns(ext, batch, batch.spread)
                     if use_sph else None)
        ipa_pre = K.interpod_filter_pre(ext, batch) if use_ipa else None
    if use_ipa:
        has_ra = jnp.any(batch.ra.valid, axis=1)
        ra_boot = (jnp.all(batch.ra.self_match | ~batch.ra.valid, axis=1)
                   & has_ra)
        mu_raa = match_selectors_unique(batch.raa.sel, batch.kv_hot,
                                        batch.key_hot)  # [Ur, B]
        raa_uidx = jnp.asarray(batch.raa.sel.index).reshape(
            B, batch.raa.valid.shape[1])
    if use_sph:
        mu_sph = match_selectors_unique(batch.spread.sel, batch.kv_hot,
                                        batch.key_hot)  # [Us, B]
        sph_uidx = jnp.asarray(batch.spread.sel.index).reshape(
            B, batch.spread.valid.shape[1])

    # tie_index: each pod's selectHost RNG stream id (fold_in index).  The
    # residual auction passes the pods' ORIGINAL batch rows here so its
    # draws replay the monolithic loop's exactly.
    pod_idx = (jnp.arange(B, dtype=jnp.int32) if tie_index is None
               else jnp.asarray(tie_index, jnp.int32))
    tie_keys = jax.vmap(lambda i: jax.random.fold_in(rng, i))(pod_idx)

    # ---- Pallas megakernel backend (ops/pallas_kernels.py) ----
    # selectHost's categorical(key, logits) decomposes into
    # argmax(where(tie, gumbel(key), -2**62)) EXACTLY in f32, so the
    # per-pod gumbel rows are drawn once from the same fold_in keys and
    # the kernel's cross-tile argmax replays the lax tie-break bit-for-bit
    use_pallas = kernel_backend == "pallas"
    pallas_interpret = False
    bundle = None
    if use_pallas:
        if intra:
            raise ValueError(
                "kernel_backend='pallas' requires intra_batch_topology="
                "False (schedule_gang's wrapper routes this; see "
                "utils/pallas_backend.unsupported_reason)")
        from ..ops import pallas_kernels as PK
        from ..utils.pallas_backend import interpret_mode
        pallas_interpret = interpret_mode()
        gumbel = jax.vmap(
            lambda k: jax.random.gumbel(k, (N,), jnp.float32))(tie_keys)
        bundle = PK.build_bundle(cluster, batch, cfg, static_ok, ports_ok0,
                                 score_pre, score_bias, gumbel)

    P = batch.ports_hot.shape[1]
    carry0 = dict(
        req=cluster.requested,
        nz=cluster.nonzero_requested,
        ports_used=jnp.zeros((N, P), jnp.float32),
        assigned=jnp.full((B,), -1, jnp.int32),
        win_score=jnp.zeros((B,), jnp.float32),
        feas0=jnp.zeros((B, N), bool),
        unres=static_unres,
        rounds=jnp.int32(0),
        # rounds that ADMITTED >= 1 pod: the windowed loop's budget.
        # Retire-only rounds must not consume it — with many permanently-
        # infeasible low-index pods the admit/retire alternation can take
        # far more than B total rounds while making real progress, and
        # charging those rounds against max_rounds starved still-feasible
        # pods into spurious preemption_may_help failures (ADVICE r5).
        # Admission rounds are intrinsically <= B (each assigns >= 1 pod),
        # so the budget keeps its original meaning.
        admits=jnp.int32(0),
        progress=jnp.bool_(True),
        # windowed-residual bookkeeping: pods proven infeasible in a round
        # with no admission leave the selection pool until an admission
        # re-opens feasibility (see _round below)
        retired=jnp.zeros((B,), bool),
    )

    # ---- width-W views of every per-pod tensor the round math reads ----
    TERM_ROW_FIELDS = ("ns_hot", "topo_key", "topo_known", "weight",
                       "valid", "self_match", "max_skew")

    def _gather_terms(t, rsafe):
        """Row-gather the dense [B, ...] companion arrays of a
        PodTerms/SpreadConstraints set.  The SelectorSet stays full-size:
        every in-round kernel consumes the precomputed match matrices
        (sph_match / ipa_pre / score_pre), never the selectors."""
        return t._replace(**{f: jnp.take(getattr(t, f), rsafe, axis=0)
                             for f in TERM_ROW_FIELDS if f in t._fields})

    def full_sub():
        sb = dict(rows=jnp.arange(B, dtype=jnp.int32), valid=batch.valid,
                  batch=batch, static_ok=static_ok, ports_ok0=ports_ok0,
                  affinity_ok=affinity_ok, tie_keys=tie_keys,
                  score_pre=score_pre, score_bias=score_bias)
        if intra:
            sb["sph_match"] = sph_match
            sb["ipa_pre"] = ipa_pre
        if use_ipa:
            sb["ra_boot"] = ra_boot
            sb["mu_raa"] = mu_raa
            sb["raa_uidx"] = raa_uidx
        if use_sph:
            sb["mu_sph"] = mu_sph
            sb["sph_uidx"] = sph_uidx
        return sb

    def gather_sub(rows):
        rsafe = jnp.clip(rows, 0, B - 1)
        wvalid = rows < B

        def g(x):
            return jnp.take(x, rsafe, axis=0)

        def g_pre(v):
            if isinstance(v, K.InterpodPre):
                return K.InterpodPre(m_ra=g(v.m_ra), m_raa=g(v.m_raa),
                                     em=v.em[:, rsafe])
            if isinstance(v, K.InterpodScorePre):
                return K.InterpodScorePre(m_pref=g(v.m_pref),
                                          em=v.em[:, rsafe])
            return g(v)

        sub_batch = batch._replace(
            req=g(batch.req), nonzero_req=g(batch.nonzero_req),
            ports_hot=g(batch.ports_hot),
            ports_asnode_hot=g(batch.ports_asnode_hot),
            spread_skip=g(batch.spread_skip),
            valid=g(batch.valid) & wvalid,
            ra=_gather_terms(batch.ra, rsafe),
            raa=_gather_terms(batch.raa, rsafe),
            pref=_gather_terms(batch.pref, rsafe),
            spread=_gather_terms(batch.spread, rsafe),
            spread_soft=_gather_terms(batch.spread_soft, rsafe))
        sb = dict(rows=rows, valid=sub_batch.valid, batch=sub_batch,
                  static_ok=g(static_ok), ports_ok0=g(ports_ok0),
                  affinity_ok=g(affinity_ok), tie_keys=g(tie_keys),
                  score_pre={k: g_pre(v) for k, v in score_pre.items()},
                  score_bias=None if score_bias is None
                  else g(score_bias))
        if intra:
            sb["sph_match"] = g(sph_match) if use_sph else None
            sb["ipa_pre"] = g_pre(ipa_pre) if use_ipa else None
        if use_ipa:
            sb["ra_boot"] = g(ra_boot)
            sb["mu_raa"] = mu_raa[:, rsafe]
            sb["raa_uidx"] = g(raa_uidx)
        if use_sph:
            sb["mu_sph"] = mu_sph[:, rsafe]
            sb["sph_uidx"] = g(sph_uidx)
        return sb

    def cluster_at(c):
        """The cluster as this round sees it: committed resource usage, and
        (under intra) the batch's admitted pods live on the existing-pod
        axis at their assigned nodes."""
        cl = ext._replace(requested=c["req"], nonzero_requested=c["nz"])
        if intra:
            pod_node = jnp.concatenate([cluster.pod_node, c["assigned"]])
            pod_valid = jnp.concatenate(
                [cluster.pod_valid, (c["assigned"] >= 0) & batch.valid])
            cl = cl._replace(pod_node=pod_node, pod_valid=pod_valid)
        return cl

    def feasibility(c, cl, sb):
        feas = sb["static_ok"]
        sbatch = sb["batch"]
        aff_unres = None
        boot_live = None
        if use_sph:
            feas = feas & K.spread_filter(cl, sbatch, sb["affinity_ok"],
                                          match_ns=sb["sph_match"],
                                          active_keys=cfg.active_keys)
        if use_ipa:
            ok, aff_unres, boot_live = K.interpod_filter(
                cl, sbatch, pre=sb["ipa_pre"], return_no_matches=True,
                active_keys=cfg.active_keys)
            feas = feas & ok
        if use_fit:
            feas = feas & K.fit_filter(cl, sbatch)
        if use_ports:
            batch_conf = jnp.einsum(
                "bp,np->bn", sbatch.ports_hot, c["ports_used"],
                preferred_element_type=jnp.float32) > 0.5
            feas = feas & sb["ports_ok0"] & ~batch_conf
        return feas, aff_unres, boot_live

    def _rules_for(terms, mu, uidx, k, pair_ok, order, is_start, admit_cap,
                   anti: bool):
        """Selector-precise same-pair deferral for one term set x one key.
        rule A: pod j defers iff an earlier-admitted pod in its landing pair
        matches one of j's key-k term selectors.  rule B (anti only): pod j
        defers iff it matches a key-k anti term of an earlier-admitted pod
        in the same pair."""
        W = admit_cap.shape[0]
        key_terms = _key_terms_mask(terms, k)  # [W, T]
        adm = _f(admit_cap & pair_ok)[:, None]
        # events A: admitted pods as selector members
        e_a = mu.T * adm                               # [W, U]
        pref_a = jnp.zeros_like(e_a).at[order].set(
            _seg_prefix(e_a[order], is_start))
        hits = jnp.take_along_axis(pref_a, uidx, axis=1) > 0  # [W, T]
        defer = jnp.any(hits & key_terms, axis=1) & pair_ok
        if anti:
            # events B: admitted pods registering their key-k selectors
            reg = jnp.zeros_like(e_a).at[
                jnp.arange(W)[:, None], uidx].max(_f(key_terms))
            e_b = reg * adm
            pref_b = jnp.zeros_like(e_b).at[order].set(
                _seg_prefix(e_b[order], is_start))
            defer = defer | (jnp.any((pref_b > 0) & mu.T, axis=1) & pair_ok)
        return defer

    def topology_deferral(sb, admit_cap, prop, boot_live):
        """Selector-precise intra-round serialization: see module
        docstring.  One stable sort by landing pair per topology key; the
        per-pair exclusive prefix sums run in unique-selector space
        (O(W x U) per key), so deferral only triggers on genuinely
        interacting pods — not on mere pair co-occupancy."""
        W = prop.shape[0]
        prop_safe = jnp.clip(prop, 0, N - 1)
        is_prop = prop < N
        defer = jnp.zeros((W,), bool)
        TK = cluster.topo_pair.shape[1]
        deferral_keys = (range(TK) if not cfg.active_topo_keys else
                         [k for k in cfg.active_topo_keys if 0 <= k < TK])
        for k in deferral_keys:
            pair_k = jnp.where(is_prop, cluster.topo_pair[prop_safe, k], -1)
            pair_ok = pair_k >= 0
            skey = jnp.where(pair_ok, pair_k, jnp.int32(2**30))
            order = jnp.argsort(skey, stable=True)
            spair = skey[order]
            is_start = jnp.concatenate(
                [jnp.ones((1,), bool), spair[1:] != spair[:-1]])
            if use_ipa:
                defer = defer | _rules_for(sb["batch"].raa, sb["mu_raa"],
                                           sb["raa_uidx"], k,
                                           pair_ok, order, is_start,
                                           admit_cap, anti=True)
            if use_sph:
                defer = defer | _rules_for(sb["batch"].spread, sb["mu_sph"],
                                           sb["sph_uidx"], k,
                                           pair_ok, order, is_start,
                                           admit_cap, anti=False)
        if use_ipa:
            # bootstrap rule: a pod whose required-affinity terms match
            # nothing THIS round is admitted only via the self-match
            # bootstrap (filtering.go:356); any same-round admission could
            # create a match and invalidate "no matches", so it defers
            # behind any earlier admission.  Once matches exist the normal
            # count path applies and co-admission is monotone-safe
            # (placements only add matches), so no deferral.
            earlier_any = jnp.cumsum(_f(admit_cap)) - _f(admit_cap)
            live = (sb["ra_boot"] if boot_live is None
                    else (sb["ra_boot"] & boot_live))
            defer = defer | (live & (earlier_any > 0))
        return defer

    def round_step(c, sb, capture_first: bool, windowed: bool = False):
        """One propose/admit round over sb's rows (width W <= B; the full
        round passes identity rows).  Updates the full-width carry through
        mode='drop' scatters, so sentinel rows (>= B) are no-ops."""
        rows = sb["rows"]
        rsafe = jnp.clip(rows, 0, B - 1)
        sbatch = sb["batch"]
        unassigned = (jnp.take(c["assigned"], rsafe) < 0) & sb["valid"]
        cl = cluster_at(c)
        feas, aff_unres, boot_live = feasibility(c, cl, sb)
        feas = feas & unassigned[:, None]

        # scores against committed usage + placements so later rounds see
        # earlier rounds' pods (the batched analog of assume-before-next-pod)
        scores, _ = run_scores(cl, sbatch, cfg, feas, sb["affinity_ok"],
                               pre=sb["score_pre"])
        if sb.get("score_bias") is not None:
            # weighted host Score/NormalizeScore plugin totals, computed by
            # the framework runner pre-dispatch (framework.go:579-656)
            scores = scores + sb["score_bias"]

        masked = jnp.where(feas, scores, _NEG)
        best = jnp.max(masked, axis=1)
        ties = (masked == best[:, None]) & feas
        logits = jnp.where(ties, 0.0, _NEG)
        choice = jax.vmap(jax.random.categorical)(sb["tie_keys"], logits)
        active = jnp.any(feas, axis=1)
        prop = jnp.where(active, choice.astype(jnp.int32), N)  # N = no-op seg
        return _round_tail(c, sb, prop, active, best, unassigned,
                           windowed=windowed, capture_first=capture_first,
                           feas=feas, aff_unres=aff_unres,
                           boot_live=boot_live)

    def pallas_round(c, sb, windowed: bool = False):
        """round_step with the propose half fused into the Pallas
        megakernel: feasibility, score combine and the tie-broken argmax
        stay in VMEM per node tile; only the [W]-sized (prop, active,
        best) come back to HBM.  Bit-identical to round_step by the
        oracle contract (ops/pallas_kernels.py).  Round 0 stays on
        round_step because its [B, N] feasibility IS a GangResult
        diagnostic output (feas0/unres capture)."""
        from ..ops import pallas_kernels as PK
        rows = sb["rows"]
        rsafe = jnp.clip(rows, 0, B - 1)
        unassigned = (jnp.take(c["assigned"], rsafe) < 0) & sb["valid"]
        prop, active, best = PK.propose(
            sb["bundle"], cfg, unassigned, c["req"], c["nz"],
            c["ports_used"], n_nodes=N, interpret=pallas_interpret)
        return _round_tail(c, sb, prop, active, best, unassigned,
                           windowed=windowed)

    def _round_tail(c, sb, prop, active, best, unassigned,
                    windowed: bool, capture_first: bool = False,
                    feas=None, aff_unres=None, boot_live=None):
        """The shared admit/commit half of a round: segmented-reduce
        admission over the proposed nodes + carry update.  O(W) / O(W, R)
        work — kept at lax level for both backends."""
        rows = sb["rows"]
        rsafe = jnp.clip(rows, 0, B - 1)
        sbatch = sb["batch"]

        # ---- admission: sort by proposed node (stable keeps pod order;
        # rows are ascending original indices, so sub-round order == the
        # full round's order restricted to these pods) ----
        admit = admission_mask(prop, active, sbatch.req, sbatch.ports_hot,
                               sbatch.ports_asnode_hot, cluster.allocatable,
                               c["req"], use_ports, N)
        if intra:
            # intra-round topology serialization (conservative; deferred
            # pods re-check against exact committed counts next round)
            admit = admit & ~topology_deferral(sb, admit, prop, boot_live)

        # ---- commit ----
        add_req, add_nz, add_ports = admission_sums(
            admit, prop, sbatch.req, sbatch.nonzero_req,
            sbatch.ports_asnode_hot, use_ports, N)
        new = dict(c)
        new["req"] = c["req"] + add_req
        new["nz"] = c["nz"] + add_nz
        if use_ports:
            new["ports_used"] = jnp.maximum(c["ports_used"], add_ports)
        new["assigned"] = c["assigned"].at[rows].set(
            jnp.where(admit, prop, jnp.take(c["assigned"], rsafe)),
            mode="drop")
        new["win_score"] = c["win_score"].at[rows].set(
            jnp.where(admit, best, jnp.take(c["win_score"], rsafe)),
            mode="drop")
        if capture_first:
            new["feas0"] = jnp.where(c["rounds"] == 0, feas, c["feas0"])
            if aff_unres is not None:
                new["unres"] = jnp.where(c["rounds"] == 0,
                                         c["unres"] | (aff_unres & base),
                                         c["unres"])
        admitted_any = jnp.any(admit)
        new["rounds"] = c["rounds"] + 1
        new["admits"] = c["admits"] + admitted_any.astype(jnp.int32)
        if windowed:
            # retirement: a pod with NO feasible node in a no-admission
            # round leaves the window-selection pool; any admission
            # re-opens everyone's feasibility (affinity matches only
            # accumulate), so the pool resets.  This keeps windowed rounds
            # live: unschedulable pods at the head of the pool cannot pin
            # the window forever.  Only FIRST-TIME retirements count as
            # progress, or an all-unschedulable tail would re-retire
            # forever and burn max_rounds.
            new_retire = ((~active) & unassigned
                          & ~jnp.take(c["retired"], rsafe))
            new["retired"] = jnp.where(
                admitted_any, jnp.zeros_like(c["retired"]),
                c["retired"].at[rows].max(new_retire, mode="drop"))
            new["progress"] = admitted_any | jnp.any(new_retire)
        else:
            new["progress"] = admitted_any
        return new

    fsb = full_sub()
    if use_pallas:
        fsb["bundle"] = bundle
    use_window = bool(residual_window) and residual_window < B  # kubelint: ignore[host-sync/cast] trace-time constant: residual_window is a static int (jit static_argnames on _schedule_gang)

    if not use_window:
        def cond(c):
            return c["progress"] & (c["rounds"] < max_rounds)

        if use_pallas:
            # hybrid: round 0 on the lax path (it must materialize the
            # [B, N] feasibility anyway for the feas0/unres diagnostics),
            # every later round fused in the megakernel.  Identical round
            # sequencing: the peeled round runs iff cond(carry0) held.
            if max_rounds < 1:
                out = carry0
            else:
                out = round_step(carry0, fsb, capture_first=True)

                def bodyp(c):
                    return pallas_round(c, fsb)

                out = jax.lax.while_loop(cond, bodyp, out)
        else:
            def body(c):
                return round_step(c, fsb, capture_first=True)

            out = jax.lax.while_loop(cond, body, carry0)
    elif max_rounds < 1:
        out = carry0
    else:
        # phase A: one full-width round admits the uncontended bulk and
        # captures feas0/unres; phase B loops over a residual WINDOW of the
        # first residual_window still-unassigned pods — the same round math
        # at ~W/B the FLOPs, since every in-round tensor row-gathers to W.
        out = round_step(carry0, fsb, capture_first=True, windowed=True)

        def condw(c):
            # budget on ADMISSION rounds, not total rounds: retire-only
            # rounds are free (progress still gates them — a round that
            # neither admits nor newly retires ends the loop), so feasible
            # pods behind a long infeasible tail cannot be starved by the
            # admit/retire alternation burning the shared budget
            pool = (c["assigned"] < 0) & batch.valid & ~c["retired"]
            return (c["progress"] & jnp.any(pool)
                    & (c["admits"] < max_rounds))

        def bodyw(c):
            pool = (c["assigned"] < 0) & batch.valid & ~c["retired"]
            rows = jnp.nonzero(pool, size=residual_window,
                               fill_value=B)[0].astype(jnp.int32)
            if use_pallas:
                from ..ops import pallas_kernels as PK
                sb = gather_sub(rows)
                sb["bundle"] = PK.gather_bundle(bundle, rows, B)
                return pallas_round(c, sb, windowed=True)
            return round_step(c, gather_sub(rows), capture_first=False,
                              windowed=True)

        out = jax.lax.while_loop(condw, bodyw, out)
    unresolvable = out["unres"]
    # the preemption gate must see HOST-filter failures as resolvable
    # (nodesWherePreemptionMightHelp counts them;
    # preemption.Preemptor._wave_candidates re-checks them), so
    # host_ok is deliberately NOT part of this node-exclusion mask
    base_nodes = cluster.node_valid[None, :] & batch.valid[:, None]
    all_unres = jnp.all(unresolvable | out["feas0"] | ~base_nodes, axis=1)
    n_feas = jnp.sum(out["feas0"].astype(jnp.int32), axis=1)
    packed = jnp.concatenate([out["assigned"], n_feas,
                              all_unres.astype(jnp.int32),
                              out["rounds"].reshape(1)])
    return GangResult(chosen=out["assigned"], score=out["win_score"],
                      rounds=out["rounds"], requested=out["req"],
                      nz=out["nz"], ports_used=out["ports_used"],
                      feasible0=out["feas0"], unresolvable=unresolvable,
                      n_feasible=n_feas,
                      all_unresolvable=all_unres, packed=packed)
