"""Composed scheduling programs: the device-side replacement for the
reference's per-pod Filter -> Score -> NormalizeScore -> weight -> selectHost
pipeline (reference: pkg/scheduler/core/generic_scheduler.go:146 Schedule,
prioritizeNodes :622, selectHost :217; weight application
framework/v1alpha1/framework.go:579-656).

A ScheduleProgram is configured with a static plugin set + weights (one per
scheduler profile) and jit-compiles one XLA program that filters and scores a
whole batch of B pods against N nodes at once.
"""

from __future__ import annotations

import functools
from typing import Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ..ops import kernels as K

# Sharded and single-device dispatch must pick IDENTICAL placements: the
# selectHost tie-break samples random bits, and under the legacy
# (non-partitionable) threefry lowering those bits change when the logits
# are sharded over a mesh — silently breaking the serial-replay oracle for
# multi-chip runs.  Partitionable threefry makes the bits a pure function
# of key + position at every sharding (newer jax defaults to exactly this;
# pinning it here keeps placements stable across jax versions too).
jax.config.update("jax_threefry_partitionable", True)

# Default plugin weights (reference: algorithmprovider/registry.go:119-134).
DEFAULT_SCORE_PLUGINS: Tuple[Tuple[str, int], ...] = (
    ("NodeResourcesBalancedAllocation", 1),
    ("ImageLocality", 1),
    ("InterPodAffinity", 1),
    ("NodeResourcesLeastAllocated", 1),
    ("NodeAffinity", 1),
    ("NodePreferAvoidPods", 10000),
    ("PodTopologySpread", 2),
    ("DefaultPodTopologySpread", 1),
    ("TaintToleration", 1),
)

DEFAULT_FILTER_PLUGINS: Tuple[str, ...] = (
    "NodeUnschedulable",
    "NodeResourcesFit",
    "NodeName",
    "NodePorts",
    "NodeAffinity",
    "TaintToleration",
    "PodTopologySpread",
    "InterPodAffinity",
)

# Filters whose failure is UnschedulableAndUnresolvable — preemption cannot
# help on such nodes (reference: status codes per plugin; consumed by
# nodesWherePreemptionMightHelp, core/generic_scheduler.go:1041).
UNRESOLVABLE_FILTERS = frozenset({
    "NodeUnschedulable", "NodeName", "NodeAffinity", "TaintToleration",
    "NodeLabel",  # nodelabel/node_label.go:106 ErrReasonPresenceViolated
})


class ProgramConfig(NamedTuple):
    """Static (hashable) program configuration — one per profile."""
    filters: Tuple[str, ...] = DEFAULT_FILTER_PLUGINS
    scores: Tuple[Tuple[str, int], ...] = DEFAULT_SCORE_PLUGINS
    hostname_topokey: int = 0  # topokey vocab id of kubernetes.io/hostname
    # per-plugin static kernel args, e.g. RequestedToCapacityRatio's shape
    # or NodeLabel's resolved key ids: ((plugin, args-tuple), ...)
    plugin_args: Tuple[Tuple[str, Tuple], ...] = ()
    # adaptive node-sampling percentage for the sequential replay
    # (reference: percentageOfNodesToScore, generic_scheduler.go:54-59,
    # 379-399).  100 = search every node (the unit-test/kernel default);
    # 0 = the reference's adaptive default 50 - n/125, floor 5%; the
    # sampled search only ever binds on clusters >= 100 nodes.
    percentage_of_nodes_to_score: int = 100
    # topology-key ids that can appear in the BATCH's term sets (affinity,
    # anti-affinity, preferred, spread constraints).  The same-pair matmul
    # kernels run one [S, .] x [., N] contraction per key; restricting to
    # the keys actually present (typically 2 of the TK=8 seeded keys) cuts
    # that work proportionally.  () = unknown -> all keys (always safe);
    # a non-empty tuple MUST be a superset of the batch's term keys.
    active_topo_keys: Tuple[int, ...] = ()

    @property
    def active_keys(self):
        return self.active_topo_keys or None

    def arg(self, name: str, default=()):
        for n, a in self.plugin_args:
            if n == name:
                return a
        return default


class FilterScoreResult(NamedTuple):
    feasible: jnp.ndarray       # [B, N] bool
    unresolvable: jnp.ndarray   # [B, N] bool (failed beyond preemption help)
    scores: jnp.ndarray         # [B, N] f32 weighted total (0 where infeasible)
    plugin_scores: Dict[str, jnp.ndarray]  # per-plugin weighted [B, N]


def _filter_mask(name: str, cluster, batch, cfg: ProgramConfig, affinity_ok):
    """One filter plugin's pass mask [B, N]; returns (ok, extra_unresolvable
    or None)."""
    if name == "NodeUnschedulable":
        return K.node_unschedulable_filter(cluster, batch), None
    if name == "NodeResourcesFit":
        return K.fit_filter(cluster, batch), None
    if name == "NodeName":
        return K.node_name_filter(cluster, batch), None
    if name == "NodePorts":
        return K.node_ports_filter(cluster, batch), None
    if name == "NodeAffinity":
        return affinity_ok, None
    if name == "TaintToleration":
        return K.taint_filter(cluster, batch), None
    if name == "PodTopologySpread":
        return K.spread_filter(cluster, batch, affinity_ok,
                               active_keys=cfg.active_keys), None
    if name == "InterPodAffinity":
        ok, aff_unres = K.interpod_filter(cluster, batch,
                                          active_keys=cfg.active_keys)
        return ok, aff_unres
    if name == "NodeLabel":
        present, absent, _ = cfg.arg("NodeLabel", ((), (), ()))
        return K.node_label_filter(cluster, batch, present, absent), None
    raise ValueError(f"unknown filter kernel {name}")


def run_filters(cluster, batch, cfg: ProgramConfig, host_ok=None,
                skip: Tuple[str, ...] = ()):
    """Returns (feasible, unresolvable, node_affinity_ok).  host_ok [B, N]
    carries the verdicts of host-side (non-tensorized) filter plugins —
    volumes, out-of-tree — computed by the framework runner and ANDed in
    here so device and host plugins share one feasibility mask.  skip names
    filters the caller evaluates itself (e.g. gang mode re-evaluates
    NodeResourcesFit/NodePorts against in-flight batch placements)."""
    base = cluster.node_valid[None, :] & batch.valid[:, None]
    if host_ok is not None:
        base = base & host_ok
    feasible = base
    unresolvable = jnp.zeros_like(base)
    affinity_ok = K.node_affinity_filter(cluster, batch)

    for name in cfg.filters:
        if name in skip:
            continue
        ok, extra_unres = _filter_mask(name, cluster, batch, cfg, affinity_ok)
        if extra_unres is not None:
            unresolvable = unresolvable | (extra_unres & base)
        if name in UNRESOLVABLE_FILTERS:
            unresolvable = unresolvable | (~ok & base)
        feasible = feasible & ok
    return feasible, unresolvable, affinity_ok


@functools.partial(jax.jit, static_argnames=("cfg",))
def explain_filters(cluster, batch, cfg: ProgramConfig, host_ok=None):
    """Per-filter unschedulability attribution for diagnostics/benchmarks
    (the tensor analog of the reference's per-node FailedPredicates map,
    core/generic_scheduler.go:565 podPassesFiltersOnNode status collection).

    For every pod with no feasible node, a filter is *blocking* when every
    node that passes all OTHER filters fails it.  Returns (no_feasible [B]
    bool, blocking [F, B] bool) with F = len(cfg.filters), evaluated against
    this snapshot."""
    from .batch import densify_for
    batch = densify_for(cluster, batch)
    base = cluster.node_valid[None, :] & batch.valid[:, None]
    if host_ok is not None:
        base = base & host_ok
    affinity_ok = K.node_affinity_filter(cluster, batch)
    masks = [
        _filter_mask(name, cluster, batch, cfg, affinity_ok)[0] & base
        for name in cfg.filters]
    all_ok = base
    for m in masks:
        all_ok = all_ok & m
    no_feasible = ~jnp.any(all_ok, axis=1) & batch.valid
    blocking = []
    for i in range(len(masks)):
        others = base
        for j, m in enumerate(masks):
            if j != i:
                others = others & m
        blocked = jnp.any(others, axis=1) & ~jnp.any(others & masks[i], axis=1)
        blocking.append(blocked & no_feasible)
    return no_feasible, jnp.stack(blocking)


# best_score is shipped in integer MILLI-units so the whole audit packs
# into ONE i32 array (one tunnel transfer); the host divides back.
# Milli, not micro: default-profile totals reach ~1e6 per node
# (NodePreferAvoidPods weight 10000 x MAX_NODE_SCORE 100), which already
# overflows i32 at micro scale — and the cast clips as a second fence.
SCORE_SCALE = 1_000
_SCORE_I32_MAX = float(2**31 - 128)


def explain_verdicts(cluster, batch, cfg: ProgramConfig, host_ok=None):
    """Python entry for the jitted audit program — AOT seam (utils/aot.py):
    armed, a signature hit runs the deserialized build-time executable;
    disarmed this is the plain jit call.  See _explain_verdicts for the
    program itself."""
    from ..utils import aot
    return aot.dispatch(
        "_explain_verdicts", _explain_verdicts,
        (cluster, batch, cfg), dict(host_ok=host_ok),
        static_argnums=(2,))


@functools.partial(jax.jit, static_argnames=("cfg",))
def _explain_verdicts(cluster, batch, cfg: ProgramConfig, host_ok=None):
    """The per-pod decision audit program (DecisionLog feed): everything
    the host needs to say WHY a pod was (un)schedulable this cycle, in
    ONE packed [2F + 3, B] i32 readback (F = len(cfg.filters)):

      rows 0..F-1      per-filter FAILED-NODE counts ("412 nodes failed
                       NodeResourcesFit") over valid nodes passing host_ok
      rows F..2F-1     0/1 blocking flags (explain_filters semantics: every
                       node passing all OTHER filters fails this one)
      row 2F           0/1 no-feasible-node flag
      row 2F + 1       best feasible node row (-1 when none) — argmax of
                       the weighted score over the feasible mask
      row 2F + 2       best feasible score in milli-units (SCORE_SCALE,
                       clipped to the i32 range)

    Evaluated against the cycle-start snapshot (same state the dispatch
    filtered), so a gang pod that lost purely to intra-batch contention
    reports its round-0 feasible count and best score."""
    from .batch import densify_for
    batch = densify_for(cluster, batch)
    base = cluster.node_valid[None, :] & batch.valid[:, None]
    if host_ok is not None:
        base = base & host_ok
    affinity_ok = K.node_affinity_filter(cluster, batch)
    masks = [
        _filter_mask(name, cluster, batch, cfg, affinity_ok)[0] & base
        for name in cfg.filters]
    all_ok = base
    for m in masks:
        all_ok = all_ok & m
    no_feasible = ~jnp.any(all_ok, axis=1) & batch.valid
    fail_counts = [jnp.sum((base & ~m).astype(jnp.int32), axis=1)
                   for m in masks]
    blocking = []
    for i in range(len(masks)):
        others = base
        for j, m in enumerate(masks):
            if j != i:
                others = others & m
        blocked = jnp.any(others, axis=1) & ~jnp.any(others & masks[i], axis=1)
        blocking.append((blocked & no_feasible).astype(jnp.int32))
    scores, _ = run_scores(cluster, batch, cfg, all_ok, affinity_ok)
    neg = jnp.float32(-2**30)
    masked = jnp.where(all_ok, scores, neg)
    any_ok = jnp.any(all_ok, axis=1)
    best_node = jnp.where(any_ok, jnp.argmax(masked, axis=1), -1)
    best_score = jnp.where(any_ok, jnp.max(masked, axis=1), 0.0)
    return jnp.stack(fail_counts + blocking + [
        no_feasible.astype(jnp.int32),
        best_node.astype(jnp.int32),
        jnp.clip(jnp.round(best_score * SCORE_SCALE),
                 -_SCORE_I32_MAX, _SCORE_I32_MAX).astype(jnp.int32)])


STATIC_RAW_SCORES = {
    # score plugins whose RAW scores are independent of the auction carry
    # (requested usage and intra-batch placements): gang mode computes them
    # once and re-normalizes per round against the evolving feasible mask
    "ImageLocality": K.image_locality_score,
    "NodeAffinity": K.node_affinity_score,
    "NodePreferAvoidPods": K.prefer_avoid_pods_score,
    "TaintToleration": K.taint_toleration_score,
}


def static_raw_scores(cluster, batch, cfg: ProgramConfig):
    """Precompute the assignment-independent raw scores for run_scores'
    pre dict (keyed "raw:<plugin>")."""
    return {f"raw:{name}": fn(cluster, batch)
            for name, fn in STATIC_RAW_SCORES.items()
            if any(n == name for n, _ in cfg.scores)}


def run_scores(cluster, batch, cfg: ProgramConfig, feasible, affinity_ok,
               pre=None):
    """Per-plugin normalized scores x weight, summed
    (reference: framework.go:579-656 RunScorePlugins).  pre: optional dict
    of precomputed assignment-independent match tensors (gang mode hoists
    them out of its round loop): keys "interpod_score", "spread_soft",
    "default_spread", and "raw:<plugin>" raw-score arrays from
    static_raw_scores."""
    pre = pre or {}
    total = jnp.zeros(feasible.shape, jnp.float32)
    per_plugin: Dict[str, jnp.ndarray] = {}
    for name, weight in cfg.scores:
        if name == "NodeResourcesBalancedAllocation":
            s = K.balanced_allocation_score(cluster, batch)
        elif name == "ImageLocality":
            s = pre.get("raw:ImageLocality")
            if s is None:
                s = K.image_locality_score(cluster, batch)
        elif name == "InterPodAffinity":
            s = K.interpod_score(cluster, batch, feasible,
                                 pre=pre.get("interpod_score"),
                                 active_keys=cfg.active_keys)
        elif name == "NodeResourcesLeastAllocated":
            s = K.least_allocated_score(cluster, batch)
        elif name == "NodeResourcesMostAllocated":
            s = K.most_allocated_score(cluster, batch)
        elif name == "NodeAffinity":
            raw = pre.get("raw:NodeAffinity")
            if raw is None:
                raw = K.node_affinity_score(cluster, batch)
            s = K.default_normalize(raw, feasible, reverse=False)
        elif name == "NodePreferAvoidPods":
            s = pre.get("raw:NodePreferAvoidPods")
            if s is None:
                s = K.prefer_avoid_pods_score(cluster, batch)
        elif name == "PodTopologySpread":
            s = K.spread_soft_score(cluster, batch, feasible, affinity_ok,
                                    cfg.hostname_topokey,
                                    match_ns=pre.get("spread_soft"),
                                    active_keys=cfg.active_keys)
        elif name == "DefaultPodTopologySpread":
            raw = K.default_spread_score(cluster, batch,
                                         match_ns=pre.get("default_spread"))
            s = K.default_spread_normalize(cluster, batch, raw, feasible)
        elif name == "TaintToleration":
            raw = pre.get("raw:TaintToleration")
            if raw is None:
                raw = K.taint_toleration_score(cluster, batch)
            s = K.default_normalize(raw, feasible, reverse=True)
        elif name == "RequestedToCapacityRatio":
            # default shape already on the MaxNodeScore scale (the plugin
            # rescales config scores x10 at construction, see intree.py)
            shape, resources = cfg.arg(
                "RequestedToCapacityRatio",
                (((0, 0), (100, 100)), ((0, 0, 1), (1, 0, 1))))
            s = K.requested_to_capacity_ratio_score(cluster, batch, shape,
                                                    resources)
        elif name == "NodeResourceLimits":
            s = K.resource_limits_score(cluster, batch)
        elif name == "NodeLabel":
            _, _, prefs = cfg.arg("NodeLabel", ((), (), ()))
            s = K.node_label_score(cluster, batch, prefs)
        else:
            raise ValueError(f"unknown score kernel {name}")
        s = jnp.where(feasible, s, 0.0) * float(weight)  # kubelint: ignore[host-sync/cast] trace-time constant: weight is a static int from cfg.scores (jit static arg)
        per_plugin[name] = s
        total = total + s
    return total, per_plugin


@functools.partial(jax.jit, static_argnames=("cfg",))
def filter_verdicts(cluster, batch, cfg: ProgramConfig, host_ok=None):
    """Filters only — (feasible, unresolvable).  Preemption's shared
    verdict refresh uses this; computing scores there would be pure
    waste."""
    from .batch import densify_for
    batch = densify_for(cluster, batch)
    feasible, unresolvable, _ = run_filters(cluster, batch, cfg, host_ok)
    return feasible, unresolvable


@functools.partial(jax.jit, static_argnames=("cfg",))
def whatif_static_ok(cluster, batch, cfg: ProgramConfig):
    """Per-(pod, node) verdict of every filter EXCEPT NodeResourcesFit —
    the victim-removal-invariant half of the preemption what-if.  Removing
    victims only perturbs the resource channels (requested/pod-count; the
    serial what-if never restores ports either) and, for term-carrying
    pods, the topology one-hots; callers route term-carrying pods to the
    per-pod reprieve instead (see preemption.py), so for wave pods this
    verdict is constant across the whole reprieve scan and one [B, N]
    pass covers every reprieve step of every candidate.  cfg must already
    have the droppable topology filters removed."""
    from .batch import densify_for
    batch = densify_for(cluster, batch)
    feasible, _, _ = run_filters(cluster, batch, cfg,
                                 skip=("NodeResourcesFit",))
    return feasible


@jax.jit
def whatif_wave(cluster, static_ok, wave_req, cand_rows, cand_valid,
                nom_add, tab_req, tab_valid, cand_idx):
    """Wave-batched selectVictimsOnNode (generic_scheduler.go:949) for a
    whole cycle's failed pods at once — the [B, C, K] axis of the
    preemption wave (preemption.py preempt_wave).  All shape axes are
    pow2-bucketed by the caller (pow2_bucket) so repeated waves of similar
    size hit one compiled program.

    Victim tensors arrive as a compact per-(priority, node) TABLE plus
    per-(pod, candidate) indices into it — same-priority preemptors share
    victim rows, so the host->device transfer is O(S * K) instead of
    O(B * C * K) (the [B, C, K, R] expansion happens on device, in HBM).

    static_ok [B, N]      all non-fit filter verdicts (whatif_static_ok)
    wave_req  [B, R]      preemptor resource request channels
    cand_rows [B, C]      candidate node rows per pod (-1 pad)
    cand_valid [B, C]     real (pod, candidate) pairs
    nom_add   [B, C, R]   nominated-pod requests reserved on each candidate
                          (equal/higher priority, self excluded — the
                          addNominatedPods overlay, :594)
    tab_req   [S, K, R]   victim resources per table row, reprieve order
    tab_valid [S, K]      real victim slots per table row
    cand_idx  [B, C]      table row per (pod, candidate) (0 pad, masked by
                          cand_valid)

    Returns packed [B, C, K+1] bool: [..., 0] = pod fits with every victim
    removed (fits0); [..., 1 + k] = victim k was reprieved (stays)."""
    import jax.numpy as jnp

    rows = jnp.clip(cand_rows, 0)
    sok = jnp.take_along_axis(static_ok, rows, axis=1) & cand_valid  # [B, C]
    vic_req = jnp.take(tab_req, cand_idx, axis=0)           # [B, C, K, R]
    vic_valid = (jnp.take(tab_valid, cand_idx, axis=0)
                 & cand_valid[:, :, None])                  # [B, C, K]
    rm_req = jnp.sum(vic_req * vic_valid[..., None].astype(vic_req.dtype),
                     axis=2)                                # [B, C, R]
    free_base = jnp.take(cluster.allocatable - cluster.requested, rows,
                         axis=0)                            # [B, C, R]
    breq = jnp.broadcast_to(wave_req[:, None, :], free_base.shape)
    free = free_base - nom_add + rm_req
    fits0 = K.fit_rows(breq, free) & sok

    def step(carry, xs):
        free, ok = carry
        vreq, vvalid = xs                                   # [B,C,R],[B,C]
        exists = vvalid & ok
        try_free = free - vreq * exists[..., None].astype(free.dtype)
        fit = K.fit_rows(breq, try_free) & sok & exists
        free = jnp.where(fit[..., None], try_free, free)
        return (free, ok), fit

    (_, _), reprieved = jax.lax.scan(
        step, (free, fits0),
        (jnp.moveaxis(vic_req, 2, 0), jnp.moveaxis(vic_valid, 2, 0)))
    return jnp.concatenate(
        [fits0[:, :, None], jnp.moveaxis(reprieved, 0, -1)], axis=2)


def _apply_cluster_delta(cluster, delta):
    """Scatter one cycle's ClusterDelta tables (state/tensors.py) into the
    device-resident ClusterTensors.  Row vectors are padded with
    one-past-capacity indices, so ``mode="drop"`` discards the pads (a -1
    pad would WRAP to the last row); duplicate REAL rows never occur (the
    host dedups dirty rows before gathering).  The compact label-id lists
    densify on device exactly like HostClusterArrays.to_device, so a
    delta-applied cluster stays byte-identical to a rebuild."""
    from ..state.tensors import _densify_ids
    from ..utils.intern import pow2_bucket

    nr, pr = delta.node_rows, delta.pod_rows
    # kv width is always an InternTable .cap (pow2_bucket of the vocab, so a
    # power of two >= 8) — re-bucketing is identity at runtime and proves to
    # the closure engine that the static L of _densify_ids stays on the
    # pow2 ladder.
    L = pow2_bucket(cluster.kv.shape[1])

    def scat(x, rows, vals):
        return x.at[rows].set(vals, mode="drop")

    return cluster._replace(
        allocatable=scat(cluster.allocatable, nr, delta.allocatable),
        requested=scat(cluster.requested, nr, delta.requested),
        nonzero_requested=scat(cluster.nonzero_requested, nr,
                               delta.nonzero_requested),
        node_valid=scat(cluster.node_valid, nr, delta.node_valid),
        unschedulable=scat(cluster.unschedulable, nr, delta.unschedulable),
        kv=scat(cluster.kv, nr, _densify_ids(delta.kv_ids, L=L)),
        keymask=scat(cluster.keymask, nr, delta.keymask),
        num=scat(cluster.num, nr, delta.num),
        topo_pair=scat(cluster.topo_pair, nr, delta.topo_pair),
        taints=scat(cluster.taints, nr, delta.taints),
        ports=scat(cluster.ports, nr, delta.ports),
        images=scat(cluster.images, nr, delta.images),
        avoid_hot=scat(cluster.avoid_hot, nr, delta.avoid_hot),
        zone_hot=scat(cluster.zone_hot, nr, delta.zone_hot),
        image_size=jnp.asarray(delta.image_size),
        image_spread=jnp.asarray(delta.image_spread),
        taint_is_hard=jnp.asarray(delta.taint_is_hard),
        taint_is_prefer=jnp.asarray(delta.taint_is_prefer),
        pod_kv=scat(cluster.pod_kv, pr, _densify_ids(delta.pod_kv_ids, L=L)),
        pod_key=scat(cluster.pod_key, pr, delta.pod_key),
        pod_ns_hot=scat(cluster.pod_ns_hot, pr, delta.pod_ns_hot),
        pod_node=scat(cluster.pod_node, pr, delta.pod_node),
        pod_valid=scat(cluster.pod_valid, pr, delta.pod_valid),
        pod_terminating=scat(cluster.pod_terminating, pr,
                             delta.pod_terminating))


# the donated variant updates the resident buffers in place (the cluster
# lives on device across cycles and nobody else may hold it); the
# no-donate twin serves the pipelined drain's rare case where a
# dispatched-but-uncommitted cycle still reads the same buffers
_apply_cluster_delta_donated = jax.jit(_apply_cluster_delta,
                                       donate_argnums=(0,))
_apply_cluster_delta_shared = jax.jit(_apply_cluster_delta)


def apply_cluster_delta(cluster, delta, donate: bool = True):
    """Apply a ClusterDelta on device.  delta leaves must already be
    pow2-bucketed (state/tensors.gather_delta) so repeated same-bucket
    deltas hit one compiled program.  donate=False keeps the input
    buffers alive (in-flight pipelined reader)."""
    delta = jax.tree.map(jnp.asarray, delta)
    fn = (_apply_cluster_delta_donated if donate
          else _apply_cluster_delta_shared)
    return fn(cluster, delta)


@functools.partial(jax.jit, static_argnames=("cfg",))
def filter_and_score(cluster, batch, cfg: ProgramConfig,
                     host_ok=None) -> FilterScoreResult:
    from .batch import densify_for
    batch = densify_for(cluster, batch)
    feasible, unresolvable, affinity_ok = run_filters(cluster, batch, cfg,
                                                      host_ok)
    scores, per_plugin = run_scores(cluster, batch, cfg, feasible, affinity_ok)
    return FilterScoreResult(feasible=feasible, unresolvable=unresolvable,
                             scores=scores, plugin_scores=per_plugin)


@jax.jit
def nominated_fit_mask(cluster, batch, nom):
    """The nominated-pods overlay pass (reference: addNominatedPods +
    two-pass filtering, core/generic_scheduler.go:530,594-612): for each
    pod, nominated pods of EQUAL-OR-GREATER priority — excluding the pod
    ITSELF when it is the nominator — are treated as already running on
    their nominated nodes, and the pod must fit with that usage added.  The
    second (overlay-free) pass of the reference is the main filter program,
    so ANDing this mask in reproduces the two-pass rule for the resource
    dimension (topology-term contributions of nominated pods are not
    overlaid — a documented deviation, see models/batch.py NominatedPods).

    The mask differs from all-True only at the <=M nominated node rows, so
    the work is [B, M, R] (M = nominated pods, tiny) — never [B, N, R].
    Returns [B, N] bool."""
    from .batch import densify_for
    from ..ops import kernels as K
    batch = densify_for(cluster, batch)
    B = batch.priority.shape[0]
    N = cluster.allocatable.shape[0]
    M = nom.node.shape[0]
    ok_entry = nom.valid & (nom.node >= 0)
    # w[b, j]: entry j reserves capacity against pod b
    w = (nom.prio[None, :] >= batch.priority[:, None]) & ok_entry[None, :] \
        & (nom.self_row[None, :] != jnp.arange(B)[:, None])
    # same_node[m, j]: entry j lands on slot m's node (duplicates collapse:
    # every slot on a node carries that node's FULL applicable sum)
    same_node = (nom.node[None, :] == nom.node[:, None]) & ok_entry[None, :]
    overlay = jnp.einsum("bj,mj,jr->bmr", w.astype(jnp.float32),
                         same_node.astype(jnp.float32), nom.req,
                         preferred_element_type=jnp.float32)  # [B, M, R]
    rows = jnp.clip(nom.node, 0, N - 1)
    free = cluster.allocatable[rows] - cluster.requested[rows]  # [M, R]
    ok = K.fit_rows(jnp.broadcast_to(batch.req[:, None, :], overlay.shape),
                    free[None, :, :] - overlay)                 # [B, M]
    mask = jnp.ones((B, N), bool).at[:, rows].min(
        jnp.where(ok_entry[None, :], ok, True))
    return mask


@functools.partial(jax.jit, static_argnames=("cfg",))
def nominated_topology_mask(cluster, nom_batch, nom_rows, nom_prio, batch,
                            cfg: ProgramConfig):
    """Topology dimension of addNominatedPods (generic_scheduler.go:530):
    nominated pods become EXISTING pods placed on their nominated nodes —
    labels, namespaces and required anti-affinity terms included — and the
    batch re-runs its InterPodAffinity + PodTopologySpread filters against
    that extended cluster.  ANDed with the overlay-free main pass this
    reproduces the reference's two-pass rule for the topology dimension:
    a nominated pod can REPEL or SKEW lower/equal-priority pods but never
    satisfy their affinity (the without-pass still gates).

    Per-row applicability (only nominated pods of >= priority are visible,
    :536) is gated at row granularity: rows where NO nominated pod
    qualifies pass untouched.  Rows where only a SUBSET qualifies see the
    full overlay — a conservative (over-blocking) deviation, exact in the
    common case where nominated pods outrank the whole batch.

    Returns [B, N] bool."""
    from .batch import densify_for
    from .gang import _extend_cluster  # lazy: gang imports this module

    batch = densify_for(cluster, batch)
    nom_batch = densify_for(cluster, nom_batch)
    ext = _extend_cluster(cluster, nom_batch)
    M = nom_batch.valid.shape[0]
    placed = nom_batch.valid & (nom_rows >= 0)
    ext = ext._replace(
        pod_node=jnp.concatenate([cluster.pod_node,
                                  jnp.asarray(nom_rows, jnp.int32)]),
        pod_valid=jnp.concatenate([cluster.pod_valid, placed]))
    affinity_ok = K.node_affinity_filter(ext, batch)
    ok = jnp.ones((batch.valid.shape[0], cluster.allocatable.shape[0]), bool)
    if "PodTopologySpread" in cfg.filters:
        ok = ok & K.spread_filter(ext, batch, affinity_ok,
                                  active_keys=cfg.active_keys)
    if "InterPodAffinity" in cfg.filters:
        ipa_ok, _ = K.interpod_filter(ext, batch,
                                      active_keys=cfg.active_keys)
        ok = ok & ipa_ok
    affected = jnp.any(placed[None, :]
                       & (nom_prio[None, :] >= batch.priority[:, None]),
                       axis=1)
    return jnp.where(affected[:, None], ok, True)


def select_host(scores: jnp.ndarray, feasible: jnp.ndarray,
                rng: jnp.ndarray) -> jnp.ndarray:
    """Masked argmax with uniform tie-break among max-score nodes
    (reference: generic_scheduler.go:217 selectHost — reservoir sampling;
    here a seeded categorical over the tie set, equivalent in distribution).
    Returns [B] node index, -1 when no feasible node."""
    B = scores.shape[0]
    neg = jnp.float32(-2**62)
    masked = jnp.where(feasible, scores, neg)
    best = jnp.max(masked, axis=1, keepdims=True)
    ties = (masked == best) & feasible
    logits = jnp.where(ties, 0.0, neg)
    keys = jax.random.split(rng, B)
    choice = jax.vmap(lambda k, lg: jax.random.categorical(k, lg))(keys, logits)
    has = jnp.any(feasible, axis=1)
    return jnp.where(has, choice.astype(jnp.int32), -1)


@functools.partial(jax.jit, static_argnames=("cfg",))
def schedule_batch(cluster, batch, cfg: ProgramConfig, rng, host_ok=None):
    """One-shot independent scheduling of a batch: every pod scored against
    the same snapshot (no intra-batch interactions).  Used for gang/auction
    modes and as the building block of the sequential scan program."""
    res = filter_and_score(cluster, batch, cfg, host_ok)
    chosen = select_host(res.scores, res.feasible, rng)
    return res, chosen
