"""Sequential-replay scheduling as one on-device lax.scan.

The reference schedules pods strictly serially because pod i's binding
changes pod i+1's filter/score inputs (reference: pkg/scheduler/scheduler.go
:509 scheduleOne; cache.AssumePod :435).  The TPU-native redesign keeps those
exact semantics but runs the whole batch in ONE compiled program: all
O(B x P x N) matching work is precomputed as batched matmuls, and a lax.scan
over the pod axis carries the small mutable state a placement creates:

  - node resource vectors (requested / non-zero requested / pod count)
  - topology-pair match counts for PodTopologySpread (hard + soft)
  - pair counts for InterPodAffinity (incoming required terms, existing
    anti-affinity, scoring contributions)
  - per-node matching-pod counts (hostname spread, DefaultPodTopologySpread)
  - hostPort conflicts between batch pods

so each scan step does only O(N + T*L) elementwise work plus two [L]x[N,L]
matvecs — no per-pod host round-trip, no re-snapshotting.  Step i sees
exactly the cluster state the reference's serial loop would see after
placements 0..i-1 (assumed pods included).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..ops import kernels as K
from ..ops.selectors import match_selectors
from .programs import ProgramConfig, UNRESOLVABLE_FILTERS

_f = K._f


class SeqResult(NamedTuple):
    chosen: jnp.ndarray        # [B] i32 node row, -1 unschedulable
    score: jnp.ndarray         # [B] f32 winning score
    n_feasible: jnp.ndarray    # [B] i32 feasible-node count at the pod's turn
                               # (of the SAMPLED search when sampling binds)
    all_unresolvable: jnp.ndarray  # [B] bool — every failed node failed
                               # UnschedulableAndUnresolvable (preemption
                               # cannot help; scheduler.go:391 preempt gate)
    requested: jnp.ndarray     # [N, R] final requested (for host cache sync checks)
    next_start: jnp.ndarray    # i32 — rotated start index after the batch
                               # (reference: nextStartNodeIndex,
                               # generic_scheduler.go:451,487)
    packed: jnp.ndarray        # [3*B+1] i32 = concat(chosen, n_feasible,
                               # all_unresolvable, [next_start]) — the
                               # host's whole per-cycle view in ONE
                               # device->host readback (tunnel transfers
                               # pay ~100 ms latency each)


def _num_feasible_nodes_to_find(n_valid, pct: int):
    """reference: generic_scheduler.go:54-59,379-399 numFeasibleNodesToFind.
    n_valid is traced (i32); pct is static."""
    if pct >= 100:
        return n_valid
    adaptive = pct if pct > 0 else jnp.maximum(50 - n_valid // 125, 5)
    num = jnp.maximum(n_valid * adaptive // 100, 100)
    return jnp.where(n_valid < 100, n_valid, num)


def _term_state(cluster, terms, B):
    """Base pair counts and node-pair maps for a PodTerms set."""
    T = terms.valid.shape[1]
    N = cluster.allocatable.shape[0]
    L = cluster.kv.shape[1]
    m = K._pod_term_matches(cluster, terms, B)  # [B, T, P]
    ep_pair = K.pod_topo_pairs(cluster, terms.topo_key.reshape(-1))
    node_pair = K.node_topo_pairs(cluster, terms.topo_key.reshape(-1))
    has_key = (node_pair >= 0).reshape(B, T, N) & terms.topo_known[:, :, None]
    return m, ep_pair, node_pair, has_key


def _batch_term_matches(terms, batch, B):
    """Match pod-side terms against the *batch's own* pods -> [B*T, B]."""
    m = match_selectors(terms.sel, batch.kv_hot, batch.key_hot)  # [B*T, B]
    T = terms.valid.shape[1]
    ns_ok = jnp.einsum("btn,in->bti", terms.ns_hot, batch.ns_hot,
                       preferred_element_type=jnp.float32) > 0.5
    m = m.reshape(B, T, B) & ns_ok & terms.valid[:, :, None] & batch.valid[None, None, :]
    return m.reshape(B * T, B)


def schedule_sequential(cluster, batch, cfg: ProgramConfig, rng,
                        hard_pod_affinity_weight: float = 1.0,
                        host_ok=None, start_index=0,
                        score_bias=None) -> SeqResult:
    """Python entry for the jitted scan — same required dispatch-bug
    workaround as gang.schedule_gang (one Python frame between callers and
    the jit object; see that docstring).  score_bias: optional [B, N] f32
    of weighted host-plugin scores (framework runner's Score/NormalizeScore
    extension point) added to the device total before selectHost.

    AOT seam (utils/aot.py): armed, a signature hit runs the deserialized
    build-time executable; disarmed this is the plain jit call."""
    from ..utils import aot
    return aot.dispatch(
        "_schedule_sequential", _schedule_sequential,
        (cluster, batch, cfg, rng),
        dict(hard_pod_affinity_weight=hard_pod_affinity_weight,
             host_ok=host_ok, start_index=start_index,
             score_bias=score_bias),
        static_argnums=(2,))


@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnums=())
def _schedule_sequential(cluster, batch, cfg: ProgramConfig, rng,
                         hard_pod_affinity_weight: float = 1.0,
                         host_ok=None, start_index=0,
                         score_bias=None) -> SeqResult:
    return _sequential_program(
        cluster, batch, cfg, rng,
        hard_pod_affinity_weight=hard_pod_affinity_weight,
        host_ok=host_ok, start_index=start_index, score_bias=score_bias)


def _sequential_program(cluster, batch, cfg: ProgramConfig, rng,
                        hard_pod_affinity_weight: float = 1.0,
                        host_ok=None, start_index=0,
                        score_bias=None) -> SeqResult:
    """The scan program body, jit-free: `_schedule_sequential` above is
    its single-device jit root, and the shard_map mesh path
    (parallel/shardmap.py) traces the SAME body per device — the pod-axis
    mesh correctness fix replicates this serial scan explicitly instead
    of letting the legacy SPMD partitioner mis-lower its cross-shard
    index selection."""
    from .batch import densify_for
    batch = densify_for(cluster, batch)
    B = batch.req.shape[0]
    N = cluster.allocatable.shape[0]
    L = cluster.kv.shape[1]
    filters = set(cfg.filters)
    score_w = dict(cfg.scores)
    # adaptive sampling: each pod searches only the first `limit` feasible
    # nodes in rotated processing order, then advances the start index by
    # the number of nodes examined (generic_scheduler.go:379-399,451,487)
    sample = cfg.percentage_of_nodes_to_score < 100
    # dtype pinned: integer jnp.sum promotes to the DEFAULT int, which is
    # i64 wherever x64 is enabled — and n_valid feeds the i32 'start'
    # scan carry (census/f64-promotion)
    n_valid = jnp.sum(cluster.node_valid, dtype=jnp.int32)
    sample_limit = _num_feasible_nodes_to_find(
        n_valid, cfg.percentage_of_nodes_to_score)

    # ---------------- static precompute (batched, MXU-heavy) ----------------
    base = cluster.node_valid[None, :] & batch.valid[:, None]
    if host_ok is not None:
        base = base & host_ok
    affinity_ok = K.node_affinity_filter(cluster, batch)
    static_ok = base
    static_unres = jnp.zeros_like(base)

    def apply_static(name, ok):
        nonlocal static_ok, static_unres
        if name in filters:
            if name in UNRESOLVABLE_FILTERS:
                static_unres = static_unres | (~ok & base)
            static_ok = static_ok & ok

    apply_static("NodeUnschedulable", K.node_unschedulable_filter(cluster, batch))
    apply_static("NodeName", K.node_name_filter(cluster, batch))
    apply_static("NodeAffinity", affinity_ok)
    apply_static("TaintToleration", K.taint_filter(cluster, batch))
    if "NodeLabel" in filters:
        nl_present, nl_absent, _ = cfg.arg("NodeLabel", ((), (), ()))
        apply_static("NodeLabel",
                     K.node_label_filter(cluster, batch, nl_present, nl_absent))

    ports_ok0 = K.node_ports_filter(cluster, batch) if "NodePorts" in filters else None

    ns_eq = jnp.einsum("bn,in->bi", batch.ns_hot, batch.ns_hot,
                       preferred_element_type=jnp.float32) > 0.5  # [B, B]
    not_term = batch.valid  # new pods are never terminating

    # --- spread hard
    use_sph = "PodTopologySpread" in filters
    if use_sph:
        cons = batch.spread
        C = cons.topo_key.shape[1]
        st = K._spread_state(cluster, batch, cons, affinity_ok,
                             cluster.node_valid[None, :] & jnp.ones((B, N), bool))
        sph_m_bb = match_selectors(cons.sel, batch.kv_hot, batch.key_hot)  # [BC, B]
        sph_m_bb = (_f(sph_m_bb.reshape(B, C, B)
                       & ns_eq[:, None, :] & not_term[None, None, :])
                    .reshape(B * C, B))
        sph = dict(st=st, cons=cons, C=C, m_bb=sph_m_bb,
                   has_cons=jnp.any(cons.valid, axis=1))

    # --- spread soft (score)
    use_sps = "PodTopologySpread" in score_w
    if use_sps:
        scons = batch.spread_soft
        Cs = scons.topo_key.shape[1]
        count_mask = affinity_ok & cluster.node_valid[None, :]
        sst = K._spread_state(cluster, batch, scons, jnp.zeros_like(affinity_ok),
                              count_mask)
        # registration is per-step (depends on the pod's feasible set); the
        # precomputed registered mask is unused — counts and node_counts are.
        all_keys_s = jnp.all(sst.has_key | ~scons.valid[:, :, None], axis=1)
        cm_soft = count_mask & all_keys_s  # nodes whose pods are counted
        sps_m_bb = match_selectors(scons.sel, batch.kv_hot, batch.key_hot)
        sps_m_bb = (_f(sps_m_bb.reshape(B, Cs, B)
                       & ns_eq[:, None, :] & not_term[None, None, :])
                    .reshape(B * Cs, B))
        is_host = (scons.topo_key == cfg.hostname_topokey) & scons.topo_known
        sps = dict(st=sst, cons=scons, Cs=Cs, m_bb=sps_m_bb, is_host=is_host,
                   cm_soft=cm_soft, all_keys=all_keys_s)

    # --- interpod filter
    use_ipf = "InterPodAffinity" in filters
    if use_ipf:
        ra, raa = batch.ra, batch.raa
        Tr, Ta = ra.valid.shape[1], raa.valid.shape[1]
        m_ra, ep_ra, np_ra, hk_ra = _term_state(cluster, ra, B)
        match_all = jnp.all(m_ra | ~ra.valid[:, :, None], axis=1)  # [B, P]
        ra_pair0 = K.pair_scatter(
            jnp.broadcast_to(match_all[:, None, :], m_ra.shape).reshape(B * Tr, -1),
            ep_ra, L)
        m_raa, ep_raa, np_raa, hk_raa = _term_state(cluster, raa, B)
        raa_pair0 = K.pair_scatter(m_raa.reshape(B * Ta, -1), ep_raa, L)

        ra_ind_bb = _batch_term_matches(ra, batch, B)  # [BTr, B]
        ra_all_bb = jnp.all((ra_ind_bb.reshape(B, Tr, B) > 0)
                            | ~ra.valid[:, :, None], axis=1)  # [B, B]
        has_ra = jnp.any(ra.valid, axis=1)
        ra_all_bb = _f(ra_all_bb & has_ra[:, None] & batch.valid[None, :])
        raa_ind_bb = _batch_term_matches(raa, batch, B)  # [BTa, B]

        # existing pods' required anti-affinity -> [B, L] base counts
        ft = cluster.filter_terms
        em = match_selectors(ft.sel, batch.kv_hot, batch.key_hot)
        ens = jnp.einsum("en,bn->eb", ft.ns_hot, batch.ns_hot,
                         preferred_element_type=jnp.float32) > 0.5
        em = em & ens & ft.valid[:, None]
        pod_topo = jnp.take(cluster.topo_pair, jnp.clip(cluster.pod_node, 0, None),
                            axis=0)
        e_pair = jnp.take_along_axis(pod_topo[jnp.clip(ft.pod_idx, 0, None)],
                                     ft.topo_key[:, None], axis=1)[:, 0]
        owner_ok = jnp.take(cluster.pod_valid, jnp.clip(ft.pod_idx, 0, None))
        e_pair = jnp.where(ft.valid & owner_ok, e_pair, -1)
        ids = jnp.where(e_pair >= 0, e_pair, L)
        ea_cnt0 = jax.ops.segment_sum(_f(em), ids, num_segments=L + 1)[:L].T  # [B, L]

        self_all = jnp.all(ra.self_match | ~ra.valid, axis=1) & has_ra
        ipf = dict(Tr=Tr, Ta=Ta, ra=ra, raa=raa, np_ra=np_ra, hk_ra=hk_ra,
                   np_raa=np_raa, hk_raa=hk_raa, ra_pair0=ra_pair0,
                   raa_pair0=raa_pair0, ra_all_bb=ra_all_bb, ra_ind_bb=ra_ind_bb,
                   raa_ind_bb=raa_ind_bb, ea_cnt0=ea_cnt0, self_all=self_all,
                   has_ra=has_ra)

    # --- interpod score
    use_ips = "InterPodAffinity" in score_w
    if use_ips:
        pt = batch.pref
        Tp = pt.valid.shape[1]
        m_p, ep_p, np_p, hk_p = _term_state(cluster, pt, B)
        data = _f(m_p) * pt.weight[:, :, None] * _f(pt.valid)[:, :, None]
        pref_pair0 = K.pair_scatter(data.reshape(B * Tp, -1), ep_p, L)  # [BTp, L]

        st_terms = cluster.score_terms
        em = match_selectors(st_terms.sel, batch.kv_hot, batch.key_hot)
        ens = jnp.einsum("en,bn->eb", st_terms.ns_hot, batch.ns_hot,
                         preferred_element_type=jnp.float32) > 0.5
        owner_ok = jnp.take(cluster.pod_valid, jnp.clip(st_terms.pod_idx, 0, None))
        em = (_f(em & ens & st_terms.valid[:, None] & owner_ok[:, None])
              * st_terms.weight[:, None])
        pod_topo = jnp.take(cluster.topo_pair, jnp.clip(cluster.pod_node, 0, None),
                            axis=0)
        e_pair = jnp.take_along_axis(pod_topo[jnp.clip(st_terms.pod_idx, 0, None)],
                                     st_terms.topo_key[:, None], axis=1)[:, 0]
        e_pair = jnp.where(st_terms.valid & owner_ok, e_pair, -1)
        ids = jnp.where(e_pair >= 0, e_pair, L)
        sc_cnt0 = jax.ops.segment_sum(em, ids, num_segments=L + 1)[:L].T  # [B, L]

        pref_w_bb = _f(_batch_term_matches(pt, batch, B)) \
            * (pt.weight * _f(pt.valid)).reshape(B * Tp, 1)  # [BTp, B]
        # hard (required) affinity terms of a placed pod scored at hardWeight
        ra_s = batch.ra
        Trs = ra_s.valid.shape[1]
        hard_bb = _f(_batch_term_matches(ra_s, batch, B)) \
            * hard_pod_affinity_weight  # [BTr, B]
        _, _, np_ra_s, _ = _term_state(cluster, ra_s, B)
        ips = dict(Tp=Tp, pt=pt, np_p=np_p, pref_pair0=pref_pair0,
                   sc_cnt0=sc_cnt0, pref_w_bb=pref_w_bb, hard_bb=hard_bb,
                   np_ra_s=np_ra_s, Trs=Trs, ra_s=ra_s)

    # --- default spread (score)
    use_ds = "DefaultPodTopologySpread" in score_w
    if use_ds:
        ds_raw0 = K.default_spread_score(cluster, batch)  # [B, N]
        ds_m = match_selectors(batch.spread_selector, batch.kv_hot, batch.key_hot)
        ds_bb = _f(ds_m & ns_eq & not_term[None, :]
                   & ~batch.spread_skip[:, None])  # [B, B]

    # --- static score rows
    image_score = (K.image_locality_score(cluster, batch)
                   if "ImageLocality" in score_w else None)
    avoid_score = (K.prefer_avoid_pods_score(cluster, batch)
                   if "NodePreferAvoidPods" in score_w else None)
    node_aff_raw = (K.node_affinity_score(cluster, batch)
                    if "NodeAffinity" in score_w else None)
    taint_raw = (K.taint_toleration_score(cluster, batch)
                 if "TaintToleration" in score_w else None)
    limits_score = (K.resource_limits_score(cluster, batch)
                    if "NodeResourceLimits" in score_w else None)
    nodelabel_score = (K.node_label_score(cluster, batch,
                                          cfg.arg("NodeLabel", ((), (), ()))[2])
                       if "NodeLabel" in score_w else None)
    rtcr_args = (cfg.arg("RequestedToCapacityRatio",
                         (((0, 0), (100, 100)), ((0, 0, 1), (1, 0, 1))))
                 if "RequestedToCapacityRatio" in score_w else None)

    # ---------------- scan ----------------
    neg = jnp.float32(-2**62)
    big = jnp.float32(2**62)

    def row_normalize(raw_row, feas_row, reverse):
        max_c = jnp.maximum(jnp.max(jnp.where(feas_row, raw_row, neg)), 0.0)
        scaled = K._idiv(K.MAX_NODE_SCORE * raw_row, jnp.maximum(max_c, 1.0))
        if reverse:
            scaled = K.MAX_NODE_SCORE - scaled
        zero_case = K.MAX_NODE_SCORE if reverse else 0.0
        out = jnp.where(max_c > 0, scaled, zero_case)
        return jnp.where(feas_row, out, 0.0)

    carry0 = {
        "req": cluster.requested,
        "nz": cluster.nonzero_requested,
    }
    if sample:
        carry0["start"] = jnp.asarray(start_index, jnp.int32)
    if ports_ok0 is not None:
        # ports the scan's own placements have registered per node; existing
        # pods' ports are already inside ports_ok0 via cluster.ports
        carry0["ports_used"] = jnp.zeros((N, batch.ports_hot.shape[1]),
                                         jnp.float32)
    if use_sph:
        carry0["sph_cnt"] = sph["st"].pair_counts
    if use_sps:
        carry0["sps_cnt"] = sps["st"].pair_counts
        carry0["sps_node"] = sps["st"].node_counts.reshape(B * sps["Cs"], N)
    if use_ipf:
        carry0["ra_cnt"] = ipf["ra_pair0"]
        carry0["raa_cnt"] = ipf["raa_pair0"]
        carry0["ea_cnt"] = ipf["ea_cnt0"]
    if use_ips:
        carry0["pref_cnt"] = ips["pref_pair0"]
        carry0["sc_own"] = ips["sc_cnt0"]
    if use_ds:
        carry0["ds_cnt"] = ds_raw0

    kv_f = _f(cluster.kv)

    def step(carry, i):
        feas = static_ok[i]
        unres = static_unres[i]

        # ---- dynamic filters
        if "NodeResourcesFit" in filters:
            alloc = cluster.allocatable
            req_i = batch.req[i]
            free_ok = alloc >= req_i[None, :] + carry["req"]
            R = alloc.shape[1]
            ch = jnp.arange(R)
            is_fixed = (ch < K.N_FIXED_CHANNELS) & (ch != K.CH_PODS)
            check = jnp.where(is_fixed, True, req_i[None, :] > 0)
            res_ok = jnp.all(free_ok | ~check | (ch == K.CH_PODS)[None, :], axis=-1)
            pods_ok = free_ok[:, K.CH_PODS]
            zero_req = jnp.all(jnp.where(ch == K.CH_PODS, 0.0, req_i) == 0)
            feas = feas & pods_ok & (zero_req | res_ok)

        if ports_ok0 is not None:
            conflict = carry["ports_used"] @ batch.ports_hot[i] > 0.5  # [N]
            feas = feas & ports_ok0[i] & ~conflict

        if use_sph:
            C = sph["C"]
            st = sph["st"]
            cnt = jax.lax.dynamic_slice_in_dim(carry["sph_cnt"], i * C, C)  # [C, L]
            reg = jax.lax.dynamic_slice_in_dim(st.registered, i * C, C)
            npair = jax.lax.dynamic_slice_in_dim(st.node_pair, i * C, C)  # [C, N]
            min_match = jnp.min(jnp.where(reg, cnt, big), axis=1)  # [C]
            mn = K.pair_gather(jnp.where(reg, cnt, 0.0), npair)  # [C, N]
            skew = mn + _f(sph["cons"].self_match[i])[:, None] - min_match[:, None]
            c_ok = st.has_key[i] & (skew <= sph["cons"].max_skew[i][:, None])
            ok = jnp.all(c_ok | ~sph["cons"].valid[i][:, None], axis=0)
            ok = jnp.where(sph["has_cons"][i] & st.any_eligible[i], ok, True)
            feas = feas & ok

        if use_ipf:
            Tr, Ta = ipf["Tr"], ipf["Ta"]
            ra, raa = ipf["ra"], ipf["raa"]
            cnt_r = jax.lax.dynamic_slice_in_dim(carry["ra_cnt"], i * Tr, Tr)
            np_r = jax.lax.dynamic_slice_in_dim(ipf["np_ra"], i * Tr, Tr)
            c_at = K.pair_gather(cnt_r, np_r)  # [Tr, N]
            term_ok = ipf["hk_ra"][i] & (c_at > 0.5)
            aff_ok = jnp.all(term_ok | ~ra.valid[i][:, None], axis=0)
            no_matches = jnp.sum(cnt_r) < 0.5
            all_keys = jnp.all(ipf["hk_ra"][i] | ~ra.valid[i][:, None], axis=0)
            aff_ok = aff_ok | (no_matches & ipf["self_all"][i] & all_keys)
            aff_ok = jnp.where(ipf["has_ra"][i], aff_ok, True)

            cnt_a = jax.lax.dynamic_slice_in_dim(carry["raa_cnt"], i * Ta, Ta)
            np_a = jax.lax.dynamic_slice_in_dim(ipf["np_raa"], i * Ta, Ta)
            ca = K.pair_gather(cnt_a, np_a)
            anti_fail = jnp.any(ipf["hk_raa"][i] & (ca > 0.5)
                                & raa.valid[i][:, None], axis=0)
            exist_fail = (carry["ea_cnt"][i] @ kv_f.T) > 0.5
            unres = unres | (~aff_ok & static_ok[i])
            feas = feas & aff_ok & ~anti_fail & ~exist_fail

        # ---- adaptive sampling: keep only the first `sample_limit`
        # feasible nodes in rotated processing order (reference:
        # findNodesThatFit's stop-at-numFeasibleNodesToFind + the
        # nextStartNodeIndex rotation, generic_scheduler.go:451-487)
        if sample:
            start = carry["start"]
            k = jnp.arange(N)
            in_range = k < n_valid
            nv = jnp.maximum(n_valid, 1)
            perm = jnp.where(in_range, (start + k) % nv, 0)
            feas_perm = jnp.where(in_range, feas[perm], False)
            cum = jnp.cumsum(feas_perm.astype(jnp.int32))
            allowed_perm = feas_perm & (cum <= sample_limit)
            total_feas = cum[-1]
            reached = cum >= sample_limit
            # argmax returns the DEFAULT int dtype, which widens to i64
            # wherever x64 is enabled and breaks the i32 'start' carry
            # (census/f64-promotion); pin the index dtype
            kth_pos = jnp.argmax(reached).astype(jnp.int32)
            n_processed = jnp.where(total_feas >= sample_limit,
                                    kth_pos + 1, n_valid)
            feas = jnp.zeros((N,), bool).at[perm].max(allowed_perm)
            new_start = (start + n_processed) % nv

        # ---- scores
        total = jnp.zeros((N,), jnp.float32)
        nz_req = carry["nz"]
        alloc_cpu = cluster.allocatable[:, K.CH_CPU]
        alloc_mem = cluster.allocatable[:, K.CH_MEM]
        req_cpu = nz_req[:, 0] + batch.nonzero_req[i, 0]
        req_mem = nz_req[:, 1] + batch.nonzero_req[i, 1]

        if "NodeResourcesBalancedAllocation" in score_w:
            s = K.balanced_formula(req_cpu, req_mem, alloc_cpu, alloc_mem)
            total += jnp.where(feas, s, 0.0) * score_w["NodeResourcesBalancedAllocation"]

        if "NodeResourcesLeastAllocated" in score_w:
            s = K._idiv(K.least_formula(req_cpu, alloc_cpu)
                        + K.least_formula(req_mem, alloc_mem), 2.0)
            total += jnp.where(feas, s, 0.0) * score_w["NodeResourcesLeastAllocated"]

        if "NodeResourcesMostAllocated" in score_w:
            s = K._idiv(K.most_formula(req_cpu, alloc_cpu)
                        + K.most_formula(req_mem, alloc_mem), 2.0)
            total += jnp.where(feas, s, 0.0) * score_w["NodeResourcesMostAllocated"]

        if image_score is not None:
            total += jnp.where(feas, image_score[i], 0.0) * score_w["ImageLocality"]
        if avoid_score is not None:
            total += jnp.where(feas, avoid_score[i], 0.0) * score_w["NodePreferAvoidPods"]
        if limits_score is not None:
            total += jnp.where(feas, limits_score[i], 0.0) * score_w["NodeResourceLimits"]
        if nodelabel_score is not None:
            total += jnp.where(feas, nodelabel_score[i], 0.0) * score_w["NodeLabel"]
        if rtcr_args is not None:
            shape, resources = rtcr_args
            parts = []
            for kind, ch, weight in resources:
                if kind == 0:
                    req, cap = req_cpu, alloc_cpu
                elif kind == 1:
                    req, cap = req_mem, alloc_mem
                elif ch < 0:
                    req = jnp.zeros_like(req_cpu)
                    cap = jnp.zeros_like(alloc_cpu)
                else:
                    cap = cluster.allocatable[:, ch]
                    req = carry["req"][:, ch] + batch.req[i, ch]
                parts.append((req, cap, weight))
            rtcr = K.rtcr_combine(parts, shape)
            total += jnp.where(feas, rtcr, 0.0) * score_w["RequestedToCapacityRatio"]
        if node_aff_raw is not None:
            total += row_normalize(node_aff_raw[i], feas, False) * score_w["NodeAffinity"]
        if taint_raw is not None:
            total += row_normalize(taint_raw[i], feas, True) * score_w["TaintToleration"]

        if use_ips:
            Tp = ips["Tp"]
            pc = jax.lax.dynamic_slice_in_dim(carry["pref_cnt"], i * Tp, Tp)
            counts = jnp.sum(pc, axis=0) + carry["sc_own"][i]  # [L]
            raw = counts @ kv_f.T  # [N]
            any_counts = jnp.any(counts != 0)
            max_c = jnp.maximum(jnp.max(jnp.where(feas, raw, neg)), 0.0)
            min_c = jnp.minimum(jnp.min(jnp.where(feas, raw, big)), 0.0)
            diff = max_c - min_c
            norm = jnp.where(diff > 0,
                             K._idiv(K.MAX_NODE_SCORE * (raw - min_c),
                                     jnp.maximum(diff, 1.0)), 0.0)
            s = jnp.where(any_counts, norm, raw)
            total += jnp.where(feas, s, 0.0) * score_w["InterPodAffinity"]

        if use_sps:
            Cs = sps["Cs"]
            sst = sps["st"]
            scons = sps["cons"]
            cnt = jax.lax.dynamic_slice_in_dim(carry["sps_cnt"], i * Cs, Cs)  # [Cs, L]
            ncnt = jax.lax.dynamic_slice_in_dim(carry["sps_node"], i * Cs, Cs)  # [Cs, N]
            npair = jax.lax.dynamic_slice_in_dim(sst.node_pair, i * Cs, Cs)
            valid = scons.valid[i]
            is_host = sps["is_host"][i]
            all_keys = sps["all_keys"][i]
            ignored = feas & ~all_keys
            scored = feas & all_keys
            # per-step registration from this pod's feasible set
            elig = scored[None, :] & (npair >= 0)
            reg = K.pair_scatter(elig, npair, L) > 0.5  # [Cs, L]
            reg = reg & ~is_host[:, None]
            topo_size = jnp.sum(_f(reg), axis=1)
            n_scored = jnp.sum(_f(scored))
            size = jnp.where(is_host, n_scored, topo_size)
            weight = jnp.log(size + 2.0)
            pair_c = K.pair_gather(jnp.where(reg, cnt, 0.0), npair)  # [Cs, N]
            cval = jnp.where(is_host[:, None], ncnt, pair_c)
            ms = scons.max_skew[i][:, None]
            cval = jnp.where(cval < ms, ms - 1.0, cval)
            contrib = jnp.where((valid & scons.topo_known[i])[:, None]
                                & sst.has_key[i], cval * weight[:, None], 0.0)
            raw = jnp.floor(jnp.sum(contrib, axis=0))
            raw = jnp.where(ignored, 0.0, raw)
            min_s = jnp.min(jnp.where(scored, raw, big))
            max_s = jnp.maximum(jnp.max(jnp.where(scored, raw, neg)), 0.0)
            norm = jnp.where(max_s > 0,
                             K._idiv(K.MAX_NODE_SCORE * (max_s + jnp.minimum(min_s, big)
                                                         - raw),
                                     jnp.maximum(max_s, 1.0)),
                             K.MAX_NODE_SCORE)
            s = jnp.where(ignored, 0.0, norm)
            s = jnp.where(jnp.any(valid), s, K.MAX_NODE_SCORE)
            total += jnp.where(feas, s, 0.0) * score_w["PodTopologySpread"]

        if use_ds:
            raw = carry["ds_cnt"][i]
            max_node = jnp.maximum(jnp.max(jnp.where(feas, raw, neg)), 0.0)
            zh = cluster.zone_hot          # [N, Z], zero rows when zoneless
            has_zone = jnp.any(zh > 0, axis=1)
            zcounts = jnp.einsum("n,nz->z", jnp.where(feas, raw, 0.0), zh,
                                 precision=jax.lax.Precision.HIGHEST,
                                 preferred_element_type=jnp.float32)
            have_zones = jnp.any(feas & has_zone)
            max_zone = jnp.maximum(jnp.max(zcounts), 0.0)
            f_score = jnp.where(max_node > 0,
                                K.MAX_NODE_SCORE * (max_node - raw)  # kubelint: ignore[numeric/score-div] reference computes fScore in float64 (default_pod_topology_spread.go:126); floor lands after the zone combine
                                / jnp.maximum(max_node, 1.0), K.MAX_NODE_SCORE)
            nzc = jnp.einsum("z,nz->n", zcounts, zh,
                             precision=jax.lax.Precision.HIGHEST,
                             preferred_element_type=jnp.float32)
            z_score = jnp.where(max_zone > 0,
                                K.MAX_NODE_SCORE * (max_zone - nzc)  # kubelint: ignore[numeric/score-div] reference computes zoneScore in float64 (default_pod_topology_spread.go:142); floor lands after the combine
                                / jnp.maximum(max_zone, 1.0), K.MAX_NODE_SCORE)
            wz = (f_score * (1.0 - K.ZONE_WEIGHTING)) + K.ZONE_WEIGHTING * z_score
            s = jnp.floor(jnp.where(have_zones & has_zone, wz, f_score))
            s = jnp.where(batch.spread_skip[i], 0.0, s)
            total += jnp.where(feas, s, 0.0) * score_w["DefaultPodTopologySpread"]

        # ---- select
        if score_bias is not None:
            total = total + score_bias[i]
        masked = jnp.where(feas, total, neg)
        best = jnp.max(masked)
        ties = (masked == best) & feas
        logits = jnp.where(ties, 0.0, neg)
        choice = jax.random.categorical(jax.random.fold_in(rng, i), logits)
        has = jnp.any(feas)
        chosen = jnp.where(has, choice.astype(jnp.int32), -1)
        n_feas = jnp.sum(feas.astype(jnp.int32))
        # host-filter failures stay RESOLVABLE for the preemption gate
        # (host_ok is folded into base but not into this exclusion mask)
        base_nodes_i = cluster.node_valid & batch.valid[i]
        all_unres = jnp.all(unres | feas | ~base_nodes_i)
        win_score = jnp.where(has, best, 0.0)

        # ---- apply placement to carries (no-op when unschedulable)
        ok = has & batch.valid[i]
        node = jnp.clip(chosen, 0, N - 1)
        w = jnp.where(ok, 1.0, 0.0)

        new = dict(carry)
        new["req"] = carry["req"].at[node].add(batch.req[i] * w)
        new["nz"] = carry["nz"].at[node].add(batch.nonzero_req[i] * w)
        if sample:
            # padded (invalid) pods must not advance the rotation
            new["start"] = jnp.where(batch.valid[i], new_start, carry["start"])
        if ports_ok0 is not None:
            new["ports_used"] = carry["ports_used"].at[node].max(
                batch.ports_asnode_hot[i] * w)
        if use_sph:
            ids = sph["st"].node_pair[:, node]  # [BC]
            vals = sph["m_bb"][:, i] * w * _f(ids >= 0)
            new["sph_cnt"] = carry["sph_cnt"].at[
                jnp.arange(ids.shape[0]), jnp.clip(ids, 0, None)].add(vals)
        if use_sps:
            ids = sps["st"].node_pair[:, node]
            in_mask = jnp.repeat(sps["cm_soft"][:, node], sps["Cs"])
            vals = sps["m_bb"][:, i] * w * _f(ids >= 0) * _f(in_mask)
            new["sps_cnt"] = carry["sps_cnt"].at[
                jnp.arange(ids.shape[0]), jnp.clip(ids, 0, None)].add(vals)
            new["sps_node"] = carry["sps_node"].at[:, node].add(
                sps["m_bb"][:, i] * w * _f(in_mask))
        if use_ipf:
            Tr, Ta = ipf["Tr"], ipf["Ta"]
            ids = ipf["np_ra"][:, node]
            vals = jnp.repeat(ipf["ra_all_bb"][:, i], Tr) * w * _f(ids >= 0)
            new["ra_cnt"] = carry["ra_cnt"].at[
                jnp.arange(ids.shape[0]), jnp.clip(ids, 0, None)].add(vals)
            ids = ipf["np_raa"][:, node]
            vals = ipf["raa_ind_bb"][:, i] * w * _f(ids >= 0)
            new["raa_cnt"] = carry["raa_cnt"].at[
                jnp.arange(ids.shape[0]), jnp.clip(ids, 0, None)].add(vals)
            # pod i's own anti terms now repel matching future pods
            own_ids = jax.lax.dynamic_slice_in_dim(ipf["np_raa"], i * Ta, Ta)[:, node]
            own_m = jax.lax.dynamic_slice_in_dim(ipf["raa_ind_bb"], i * Ta, Ta)  # [Ta, B]
            vals = own_m.T * w * _f(own_ids >= 0)[None, :]  # [B, Ta]
            new["ea_cnt"] = carry["ea_cnt"].at[
                :, jnp.clip(own_ids, 0, None)].add(vals)
        if use_ips:
            Tp, Trs = ips["Tp"], ips["Trs"]
            ids = ips["np_p"][:, node]
            vals = ips["pref_w_bb"][:, i] * w * _f(ids >= 0)
            new["pref_cnt"] = carry["pref_cnt"].at[
                jnp.arange(ids.shape[0]), jnp.clip(ids, 0, None)].add(vals)
            own_ids = jax.lax.dynamic_slice_in_dim(ips["np_p"], i * Tp, Tp)[:, node]
            own_m = jax.lax.dynamic_slice_in_dim(ips["pref_w_bb"], i * Tp, Tp)
            vals = own_m.T * w * _f(own_ids >= 0)[None, :]
            new["sc_own"] = carry["sc_own"].at[:, jnp.clip(own_ids, 0, None)].add(vals)
            own_ids = jax.lax.dynamic_slice_in_dim(ips["np_ra_s"], i * Trs, Trs)[:, node]
            own_m = jax.lax.dynamic_slice_in_dim(ips["hard_bb"], i * Trs, Trs)
            vals = own_m.T * w * _f(own_ids >= 0)[None, :]
            new["sc_own"] = new["sc_own"].at[:, jnp.clip(own_ids, 0, None)].add(vals)
        if use_ds:
            new["ds_cnt"] = carry["ds_cnt"].at[:, node].add(ds_bb[:, i] * w)

        out = (chosen, win_score, n_feas, all_unres)
        return new, out

    carry, (chosen, score, n_feas, all_unres) = jax.lax.scan(
        step, carry0, jnp.arange(B))
    next_start = carry["start"] if sample else jnp.asarray(start_index,
                                                           jnp.int32)
    packed = jnp.concatenate([chosen, n_feas, all_unres.astype(jnp.int32),
                              next_start[None]])
    return SeqResult(chosen=chosen, score=score, n_feasible=n_feas,
                     all_unresolvable=all_unres, requested=carry["req"],
                     next_start=next_start, packed=packed)
