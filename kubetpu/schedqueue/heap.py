"""Keyed min-heap with push-time sort keys.

reference: pkg/scheduler/internal/heap/heap.go (Heap :127, data :36 — a
keyed heap over interface{} items with Add/Update/Delete/Peek/Pop/Get).

Unlike the Go heap (which re-heapifies via interface methods), this port
snapshots each item's sort key AT PUSH TIME.  Queue code mutates
QueuedPodInfo in place (timestamps, pod updates), which would corrupt a
comparison-at-pop-time heap; freezing the key on push keeps the heapq
invariant regardless of later mutation, and updates simply push a fresh
entry (lazy deletion drops the stale one by sequence number).
"""

from __future__ import annotations

import heapq
import itertools
from typing import Any, Callable, Dict, List, Optional, Tuple


class Heap:
    def __init__(self, key_func: Callable[[Any], str],
                 sort_key: Callable[[Any], Tuple],
                 metric_recorder=None):
        self._key = key_func
        self._sort_key = sort_key
        self._items: Dict[str, Any] = {}
        self._live_seq: Dict[str, int] = {}
        self._heap: List[Tuple[Tuple, int, str]] = []
        self._seq = itertools.count()
        self._recorder = metric_recorder

    def add(self, item: Any) -> None:
        """Insert or overwrite (reference: heap.go:173 Add — Update is Add)."""
        k = self._key(item)
        if k not in self._items and self._recorder:
            self._recorder.inc()
        seq = next(self._seq)
        self._items[k] = item
        self._live_seq[k] = seq
        heapq.heappush(self._heap, (self._sort_key(item), seq, k))

    update = add

    def delete(self, item: Any) -> bool:
        k = self._key(item)
        if k in self._items:
            del self._items[k]
            del self._live_seq[k]
            if self._recorder:
                self._recorder.dec()
            return True
        return False

    def get(self, item: Any) -> Optional[Any]:
        return self.get_by_key(self._key(item))

    def get_by_key(self, key: str) -> Optional[Any]:
        return self._items.get(key)

    def peek(self) -> Optional[Any]:
        self._drop_stale()
        if not self._heap:
            return None
        return self._items[self._heap[0][2]]

    def pop(self) -> Optional[Any]:
        self._drop_stale()
        if not self._heap:
            return None
        _, _, k = heapq.heappop(self._heap)
        item = self._items.pop(k)
        del self._live_seq[k]
        if self._recorder:
            self._recorder.dec()
        return item

    def _drop_stale(self) -> None:
        while self._heap:
            _, seq, k = self._heap[0]
            if self._live_seq.get(k) != seq:
                heapq.heappop(self._heap)
            else:
                return

    def list(self) -> List[Any]:
        return list(self._items.values())

    def __len__(self) -> int:
        return len(self._items)

    def __contains__(self, item: Any) -> bool:
        return self._key(item) in self._items
